"""Headline benchmark: downsample + group-by aggregation throughput.

Measures the BASELINE.json primary metric — datapoints aggregated per second
per chip — for the fused kernel replacing the reference's per-datapoint
iterator stack (/root/reference/src/core/AggregationIterator.java:514,
Downsampler.java:292, TsdbQuery.GroupByAndAggregateCB :981): avg downsample
1h + group-by over 100 tag groups on 67M device-resident datapoints.

Methodology: data is generated on device inside the jitted program (the
host<->device tunnel would otherwise dominate), and the aggregation body runs
K times in a `lax.fori_loop` with the window origin varying per iteration (so
XLA cannot hoist it).  Per-iteration time comes from the slope between a
K_LO-iteration and a K_HI-iteration execution, cancelling data generation and
dispatch overhead.

Baseline: BASELINE.json's north star — "1B datapoints in <2s on v5e-8" —
i.e. 62.5M datapoints/sec/chip.  vs_baseline > 1.0 beats the target.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import json
import time

S = 1024          # series
N = 65_536        # points per series  (S*N = 67.1M datapoints)
GROUPS = 100
START = 1_356_998_400_000
INTERVAL_MS = 3_600_000   # 1h avg downsample
STEP_MEAN_MS = 15_500     # ~15.5s cadence -> ~11.8 days of data
K_LO, K_HI = 2, 12


def build_bench(mesh, iters: int):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P
    from opentsdb_tpu.ops.downsample import pad_pow2
    from opentsdb_tpu.parallel.mesh import AXIS_SERIES, AXIS_TIME

    n_s = mesh.shape[AXIS_SERIES]
    n_t = mesh.shape[AXIS_TIME]
    s_loc, n_loc = S // n_s, N // n_t
    span_ms = int(N * STEP_MEAN_MS)
    w = pad_pow2(span_ms // INTERVAL_MS + 2)

    def body(seed):
        i_s = lax.axis_index(AXIS_SERIES)
        i_t = lax.axis_index(AXIS_TIME)
        # Closed-form synthetic series (no PRNG/cumsum — cheap to generate,
        # irregular enough to defeat constant folding): per-point jitter from
        # a Knuth-multiplicative hash keeps timestamps strictly increasing
        # (step 15.5s +/- <5s jitter).
        rows = i_s.astype(jnp.int64) * s_loc + jnp.arange(s_loc,
                                                          dtype=jnp.int64)
        cols = (i_t.astype(jnp.int64) * n_loc
                + jnp.arange(n_loc, dtype=jnp.int64))
        h = (rows[:, None] * 2_654_435_761 + cols[None, :] * 40_503
             + seed.astype(jnp.int64)) & 0x7FFFFFFF
        jitter = h % 5_000
        ts = START + cols[None, :] * STEP_MEAN_MS + jitter
        val = 100.0 + (h % 1_000).astype(jnp.float64) * 0.05
        gid = rows % GROUPS

        onehot = (gid[None, :] == jnp.arange(GROUPS, dtype=jnp.int64)
                  [:, None]).astype(jnp.float64)  # [G, s_loc]

        def one(i, acc):
            # Sorted-timestamp fast path: window sums via exclusive prefix
            # sums + binary-searched window edges (no scatter — TPU scatters
            # serialize); group combine as a one-hot matmul on the MXU.
            first = jnp.asarray(START, jnp.int64) - i * 1_000
            edges = first + jnp.arange(w + 1, dtype=jnp.int64) * INTERVAL_MS
            idx = jax.vmap(
                lambda row: jnp.searchsorted(row, edges, side="left"))(ts)
            csum = jnp.concatenate(
                [jnp.zeros((s_loc, 1), jnp.float64),
                 jnp.cumsum(val, axis=1)], axis=1)
            at = jnp.take_along_axis(csum, idx, axis=1)
            wsum = at[:, 1:] - at[:, :-1]                      # [s_loc, w]
            wcnt = (idx[:, 1:] - idx[:, :-1]).astype(jnp.float64)
            gsum = lax.psum(onehot @ wsum, (AXIS_SERIES, AXIS_TIME))
            gcnt = lax.psum(onehot @ wcnt, (AXIS_SERIES, AXIS_TIME))
            avg = gsum / jnp.maximum(gcnt, 1.0)
            return acc + jnp.sum(jnp.where(gcnt > 0, avg, 0.0))

        return lax.fori_loop(0, iters, one, jnp.asarray(0.0, jnp.float64))

    from jax import shard_map
    mapped = shard_map(body, mesh=mesh, in_specs=(P(),), out_specs=P(),
                       check_vma=False)
    return jax.jit(mapped)


def time_best(fn, seed, reps=3):
    import jax
    best = float("inf")
    for r in range(reps):
        t0 = time.perf_counter()
        jax.device_get(fn(seed + r))
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> None:
    import jax
    from opentsdb_tpu.parallel import make_mesh

    n_dev = len(jax.devices())
    mesh = make_mesh(n_dev)

    lo = build_bench(mesh, K_LO)
    hi = build_bench(mesh, K_HI)
    jax.device_get(lo(0))   # compile
    jax.device_get(hi(0))

    t_lo = time_best(lo, 1)
    t_hi = time_best(hi, 1)
    per_iter = max((t_hi - t_lo) / (K_HI - K_LO), 1e-9)

    dp_per_sec_per_chip = S * N / per_iter / n_dev
    baseline = 1e9 / 2.0 / 8.0  # north star: 1B pts < 2s on 8 chips
    print(json.dumps({
        "metric": "datapoints aggregated/sec/chip (avg 1h downsample + "
                  "groupby 100 groups, 67M pts device-resident)",
        "value": round(dp_per_sec_per_chip, 1),
        "unit": "datapoints/sec/chip",
        "vs_baseline": round(dp_per_sec_per_chip / baseline, 4),
    }))


if __name__ == "__main__":
    main()
