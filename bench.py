"""Headline benchmark: PRODUCTION query pipeline throughput.

Measures the BASELINE.json primary metric — datapoints aggregated per second
per chip — through the exact jitted function `/api/query` dispatches
(`ops.pipeline.run_group_pipeline`: prefix-sum windowed downsample + grouped
cross-series reduce), replacing the reference's per-datapoint iterator stack
(/root/reference/src/core/AggregationIterator.java:514, Downsampler.java:292,
TsdbQuery.GroupByAndAggregateCB :981).  Round 1 benched a bespoke inline
kernel; round 2's planner runs the same prefix-sum windowing in production,
so the bench now measures the served path.

Shape: BASELINE config 3 scaled up — 1024 series in 100 tag groups, 65536
points each (67.1M datapoints), avg 1h downsample + sum group aggregation.

Methodology: the batch is generated on device once (host<->device transfer
excluded — the storage layer hands the planner device-resident batches in
steady state) by a closed-form hash (no PRNG state, irregular enough to
defeat constant folding).  The production function is dispatched K times
back-to-back with a varying window origin (a traced operand, so no
recompile and no hoisting), blocking once at the end; per-iteration time is
the slope between a K_LO and K_HI run, cancelling dispatch ramp-up.

Baseline: BASELINE.json north star — 1B datapoints < 2s on v5e-8, i.e.
62.5M datapoints/sec/chip.  vs_baseline > 1.0 beats the target.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import json
import sys
import time


def _note(msg: str) -> None:
    """Progress to stderr (stdout carries exactly the one JSON line)."""
    print("[bench] " + msg, file=sys.stderr, flush=True)

S = 1024          # series
N = 65_536        # points per series  (S*N = 67.1M datapoints)
GROUPS = 100
START = 1_356_998_400_000
INTERVAL_MS = 3_600_000   # 1h avg downsample
STEP_MEAN_MS = 15_500     # ~15.5s cadence -> ~11.8 days of data
K_LO, K_HI = 2, 10


def make_batch():
    """Device-resident [S, N] batch via a jitted closed-form generator."""
    import opentsdb_tpu.ops  # noqa: F401  (enables jax x64 mode)
    import jax
    import jax.numpy as jnp

    def gen():
        rows = jnp.arange(S, dtype=jnp.int64)
        cols = jnp.arange(N, dtype=jnp.int64)
        h = (rows[:, None] * 2_654_435_761 + cols[None, :] * 40_503) \
            & 0x7FFFFFFF
        ts = START + cols[None, :] * STEP_MEAN_MS + h % 5_000
        val = 100.0 + (h % 1_000).astype(jnp.float64) * 0.05
        mask = jnp.ones((S, N), dtype=bool)
        gid = rows % GROUPS
        return ts, val, mask, gid

    out = jax.jit(gen)()
    jax.block_until_ready(out)
    return out


def build_spec():
    from opentsdb_tpu.ops.downsample import FixedWindows, pad_pow2
    from opentsdb_tpu.ops.pipeline import PipelineSpec, DownsampleStep

    end = START + N * STEP_MEAN_MS + 5_000
    fixed = FixedWindows.for_range(START, end, INTERVAL_MS)
    window_spec, wargs = fixed.split()
    spec = PipelineSpec(
        aggregator="sum",
        downsample=DownsampleStep("avg", window_spec, "none", 0.0))
    return spec, wargs, pad_pow2(GROUPS)


def run_iters(spec, g_pad, batch, wargs, iters: int) -> float:
    """Wall time for `iters` production dispatches (origin varies each)."""
    import jax
    import jax.numpy as jnp
    from opentsdb_tpu.ops.pipeline import run_group_pipeline

    ts, val, mask, gid = batch
    t0 = time.perf_counter()
    out = None
    for i in range(iters):
        w = dict(wargs)
        w["first"] = wargs["first"] - jnp.asarray(i * 1_000, jnp.int64)
        out = run_group_pipeline(spec, ts, val, mask, gid, g_pad, w)
    jax.block_until_ready(out)
    return time.perf_counter() - t0


def time_best(spec, g_pad, batch, wargs, iters, reps=3) -> float:
    return min(run_iters(spec, g_pad, batch, wargs, iters)
               for _ in range(reps))


def main() -> None:
    import jax

    n_dev = len(jax.devices())
    _note("devices: %d (%s)" % (n_dev, jax.devices()[0].platform))
    batch = make_batch()
    _note("batch resident")
    spec, wargs, g_pad = build_spec()

    run_iters(spec, g_pad, batch, wargs, 1)  # compile
    _note("compiled")
    t_lo = time_best(spec, g_pad, batch, wargs, K_LO)
    t_hi = time_best(spec, g_pad, batch, wargs, K_HI)
    _note("timed: lo=%.3fs hi=%.3fs" % (t_lo, t_hi))
    per_iter = max((t_hi - t_lo) / (K_HI - K_LO), 1e-9)

    dp_per_sec_per_chip = S * N / per_iter / n_dev
    baseline = 1e9 / 2.0 / 8.0  # north star: 1B pts < 2s on 8 chips
    print(json.dumps({
        "metric": "datapoints aggregated/sec/chip through the production "
                  "/api/query pipeline (avg 1h downsample + groupby "
                  "100 groups, 67M pts device-resident)",
        "value": round(dp_per_sec_per_chip, 1),
        "unit": "datapoints/sec/chip",
        "vs_baseline": round(dp_per_sec_per_chip / baseline, 4),
    }))


if __name__ == "__main__":
    main()
