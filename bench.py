"""Headline benchmark: PRODUCTION query pipeline throughput — honest edition.

Measures the BASELINE.json primary metric — datapoints aggregated per second
per chip — through the exact jitted function `/api/query` dispatches
(`ops.pipeline.run_group_pipeline`: prefix-sum windowed downsample + grouped
cross-series reduce), replacing the reference's per-datapoint iterator stack
(/root/reference/src/core/AggregationIterator.java:514, Downsampler.java:292,
TsdbQuery.GroupByAndAggregateCB :981).

Shape: BASELINE config 3 scaled up — 1024 series in 100 tag groups, 65536
points each (67.1M datapoints), avg 1h downsample + sum group aggregation.

Methodology — designed so the bench CANNOT report a dispatch artifact.
Round 2 shipped a 12551x number; root cause (established by direct probe,
round 3): `jax.block_until_ready` does NOT wait for execution on the axon
tunnel platform — back-to-back "blocked" dispatches return in ~0.1ms while
a forced drain shows each really takes ~0.6s.  (The executions themselves
are never skipped: k enqueued dispatches drain in k * 0.6s, identical
operands or not.)  Therefore:

  1. SYNC IS A HOST FETCH: every timed sample ends by fetching one scalar
     from each output leaf (`np.asarray`), which provably drains the
     execution queue (see k-scaling probe in the r3 commit message).  The
     measured tunnel round-trip (~70ms) is subtracted per sample.
  2. Every dispatch carries a NEVER-REPEATED operand: a per-process random
     base + a monotonic counter folded into the window origin (a traced
     int64 operand), so no two dispatches — within a run or across runs —
     replay an identical execution, guarding against any future
     result-memoization layer as well.
  3. The headline number is a PER-DISPATCH-DRAINED median, and the total
     measured wall time must exceed 1s (more samples are taken until it
     does), so clock noise cannot dominate.
  4. Plausibility guard: the implied HBM traffic (>=17 bytes/datapoint
     touched at least once) must not exceed any real TPU's memory bandwidth
     (cap 3.5 TB/s, above v5p's 2.77 TB/s).  A number above the cap is
     physically impossible and the bench refuses to emit it.
  5. Cross-check: a pipelined run (k dispatches, one drain at the end) must
     agree with the drained median within 2x; a loud warning is emitted
     otherwise.

Baseline: BASELINE.json north star — 1B datapoints < 2s on v5e-8, i.e.
62.5M datapoints/sec/chip.  vs_baseline > 1.0 beats the target.

Prints exactly one JSON line on stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

def _apply_mode_defaults() -> None:
    """Chip-validated hot-path modes, applied INSIDE main() only.

    At module level this would leak into every importer (bench_configs /
    bench_prefix import this module for its measurement helpers and must
    control their own modes — an import-time setdefault put compare_all
    under config 4's streamed grid and OOM'd it).  Preference order:
    explicit env > BENCH_WINNERS.json (written by
    tools/run_chip_measurements.py from the fastest COMPLETE measured
    config of its bench_prefix A/B race on the real chip) > the r4a
    hand-recorded winners (BENCH_CONFIGS_r04a.json: compare_all beat the
    binary search 0.512 vs 0.578 s/dispatch, matmul group-reduce beat
    the segment scatter 0.489 vs 0.606).  Shape guards demote dense
    forms off losing shapes either way.  Must run before the first
    opentsdb_tpu.ops import (the modes are read at import time).
    """
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_WINNERS.json")) as fh:
            for k, v in json.load(fh).get("env", {}).items():
                os.environ.setdefault(k, v)
    except (OSError, ValueError):
        pass
    os.environ.setdefault("TSDB_SEARCH_MODE", "compare_all")
    os.environ.setdefault("TSDB_GROUP_REDUCE_MODE", "matmul")


def _note(msg: str) -> None:
    """Progress to stderr (stdout carries exactly the one JSON line)."""
    print("[bench] " + msg, file=sys.stderr, flush=True)


_emit_lock = threading.Lock()
_emitted = False

# Per-stage progress stamps (r03-r05 blackout diagnosis aid): every
# completed stage appends "<name>@+<seconds>"; the skip artifact carries
# the list, so a 1500s deadline verdict now says WHERE the run wedged —
# an empty list (or no probe_ok) is "tunnel wedged before the first
# dispatch", probe_ok without compiled is "compile stuck after probe
# OK", etc.  BENCH_r0{3,4,5}.json could not distinguish these.
_STAGES: list = []
_T0 = time.monotonic()


def _stamp(name: str) -> None:
    _STAGES.append("%s@+%.1fs" % (name, time.monotonic() - _T0))
    _note("stage: " + _STAGES[-1])

METRIC = ("datapoints aggregated/sec/chip through the production "
          "/api/query pipeline (avg 1h downsample + groupby "
          "100 groups, 67M pts device-resident, per-dispatch-"
          "drained median, unique operands every dispatch)")


def _emit(obj: dict) -> None:
    """Print the ONE stdout JSON line, exactly once across threads."""
    global _emitted
    with _emit_lock:
        if _emitted:
            return
        _emitted = True
        print(json.dumps(obj), flush=True)


def _skip(reason: str) -> None:
    """Structured no-measurement artifact (VERDICT r3: a backend failure
    must never cost the round's provenance by dying with a traceback).
    Carries the per-stage progress stamps so the skip says where the
    run died, not just that it died."""
    _note("SKIPPED: " + reason)
    _emit({"metric": METRIC, "value": 0.0, "unit": "datapoints/sec/chip",
           "vs_baseline": 0.0, "skipped": True, "reason": reason,
           "stages": list(_STAGES)})


def _arm_watchdog(deadline_s: float) -> None:
    """A wedged axon tunnel HANGS (jax.devices() blocks forever) rather
    than raising; emit the skip artifact before any outer timeout would
    kill us JSON-less."""
    def fire():
        time.sleep(deadline_s)
        _skip("deadline %.0fs exceeded — backend unresponsive (tunnel "
              "wedged or compile stuck)" % deadline_s)
        sys.stdout.flush()
        os._exit(0)
    threading.Thread(target=fire, daemon=True).start()


def arm_init_watchdog(timeout_s: float = 240.0) -> threading.Event:
    """Short guard for BACKEND INIT in the session tools: a live tunnel
    dials in seconds, a dead one hangs jax.devices() ~25 min before
    raising (observed Aug 2) — burning most of a recovery window on a
    stage that cannot measure.  Call, touch the backend, then set() the
    returned event; on timeout a JSON error row keeps the artifact
    parseable and exit 1 lets the session runner fail fast.  bench.py's
    own driver runs keep the 1500s _arm_watchdog skip contract instead."""
    ev = threading.Event()

    def fire():
        if not ev.wait(timeout_s):
            print(json.dumps({
                "metric": "backend_init",
                "error": "backend init unresponsive %.0fs (tunnel dead)"
                         % timeout_s}), flush=True)
            sys.stdout.flush()
            os._exit(1)
    threading.Thread(target=fire, daemon=True).start()
    return ev


def guard_backend_init(timeout_s: float = 240.0) -> None:
    """Arm the init watchdog, touch the backend, release — the one-call
    form so call sites can't forget the release half of the contract."""
    ev = arm_init_watchdog(timeout_s)
    import jax
    jax.devices()
    ev.set()


def preflight_probe(deadline_s: float = 240.0) -> None:
    """Device preflight with its OWN short deadline, run before any
    expensive batch build or headline compile.

    The r03-r05 bench blackout produced three 1500s "backend
    unresponsive" verdicts that could not say whether the tunnel was
    wedged before the FIRST dispatch or a compile hung later; this
    probe splits that verdict.  It dials the backend, dispatches one
    trivial kernel, and drains it with the host-fetch sync; a hang
    emits the skip artifact (with the stage stamps showing how far it
    got) after ``deadline_s`` — a fraction of the 1500s outer deadline
    — instead of burning the whole measurement window.
    """
    done = threading.Event()

    def fire():
        if not done.wait(deadline_s):
            _skip("preflight: device probe did not complete in %.0fs — "
                  "tunnel wedged before the first dispatch (stages "
                  "show the last completed step)" % deadline_s)
            sys.stdout.flush()
            os._exit(0)
    threading.Thread(target=fire, daemon=True).start()

    import jax
    devs = jax.devices()
    _stamp("probe_devices_%d_%s" % (len(devs), devs[0].platform))
    import jax.numpy as jnp
    out = (jnp.zeros(8) + 1.0,)
    drain(out)
    _stamp("probe_ok")
    done.set()


S = 1024          # series
N = 65_536        # points per series  (S*N = 67.1M datapoints)
GROUPS = 100
START = 1_356_998_400_000
INTERVAL_MS = 3_600_000   # 1h avg downsample
STEP_MEAN_MS = 15_500     # ~15.5s cadence -> ~11.8 days of data

MIN_WALL_S = 1.0          # guard 3: total measured time must exceed this
MIN_SAMPLES = 5
MAX_SAMPLES = 64
BYTES_PER_DP = 13         # ts int32 + val f64 + mask byte, touched >= once
#                           (cache-hit layout: int32 offset timestamps)
HBM_CAP_BYTES_S = 3.5e12  # guard 4: no TPU chip streams faster than this
PIPELINE_K = 8            # cross-check dispatch count


class _OriginSequence:
    """Never-repeating window-origin offsets (guard 1).

    A per-process random base plus a monotonic counter, mapped into
    [0, INTERVAL_MS) so the shifted origin stays representative of the
    production window layout.  7919 is prime to INTERVAL_MS, so the walk
    visits 3.6M distinct offsets before cycling — far beyond any run.
    """

    def __init__(self):
        self._base = int.from_bytes(os.urandom(4), "big")
        self._i = 0

    def next(self) -> int:
        self._i += 1
        return (self._base + self._i * 7919) % INTERVAL_MS


def make_batch(precompacted: bool = True):
    """Device-resident [S, N] batch via a jitted closed-form generator.

    Default layout: timestamps as int32 offsets from the first window's
    start — what the device cache's gather delivers for eligible fixed
    grids (storage/device_cache.py `ts_base`), so the measured dispatch
    is the production cache-hit dispatch: no per-point compaction pass.
    `precompacted=False` keeps absolute int64 timestamps (the host-build
    path's layout) — bench_prefix uses it to race the per-dispatch
    compaction against the pre-compacted layout honestly.
    """
    import opentsdb_tpu.ops  # noqa: F401  (enables jax x64 mode)
    import jax
    import jax.numpy as jnp

    first = START - (START % INTERVAL_MS)

    def gen():
        rows = jnp.arange(S, dtype=jnp.int64)
        cols = jnp.arange(N, dtype=jnp.int64)
        h = (rows[:, None] * 2_654_435_761 + cols[None, :] * 40_503) \
            & 0x7FFFFFFF
        ts = START + cols[None, :] * STEP_MEAN_MS + h % 5_000
        val = 100.0 + (h % 1_000).astype(jnp.float64) * 0.05
        mask = jnp.ones((S, N), dtype=bool)
        # contiguous group runs — the layout the planner actually emits
        # (planner.py:403 concatenates per-group member lists), so the
        # benched dispatch matches production row order and the sorted
        # reduce modes can skip their permute (spec.rows_sorted)
        gid = rows * GROUPS // S
        if precompacted:
            return (ts - first).astype(jnp.int32), val, mask, gid
        return ts, val, mask, gid

    out = jax.jit(gen)()
    jax.block_until_ready(out)
    return out


def build_spec(precompacted: bool = True):
    import jax.numpy as jnp
    from opentsdb_tpu.ops.downsample import FixedWindows, pad_pow2
    from opentsdb_tpu.ops.pipeline import PipelineSpec, DownsampleStep

    end = START + N * STEP_MEAN_MS + 5_000
    fixed = FixedWindows.for_range(START, end, INTERVAL_MS)
    window_spec, wargs = fixed.split()
    if precompacted:
        # the batch carries int32 offsets from the first window
        # (make_batch); ts_base tells the pipeline so only the [W+1]
        # edges re-base
        wargs["ts_base"] = jnp.asarray(fixed.first_window_ms, jnp.int64)
    spec = PipelineSpec(
        aggregator="sum",
        downsample=DownsampleStep("avg", window_spec, "none", 0.0),
        rows_sorted=True)
    return spec, wargs, pad_pow2(GROUPS)


def dispatch(spec, g_pad, batch, wargs, origin_offset: int):
    """One production dispatch with a unique traced window origin."""
    import jax.numpy as jnp
    from opentsdb_tpu.ops.pipeline import run_group_pipeline

    ts, val, mask, gid = batch
    w = dict(wargs)
    w["first"] = wargs["first"] - jnp.asarray(origin_offset, jnp.int64)
    return run_group_pipeline(spec, ts, val, mask, gid, g_pad, w)


def drain(out) -> None:
    """Force the execution queue: fetch one scalar from every output leaf.

    `jax.block_until_ready` returns without waiting on the axon tunnel;
    a host fetch is the only sync that provably drains (k dispatches then
    one fetch takes k * t_exec — measured, see module docstring)."""
    import jax
    import numpy as np

    for leaf in jax.tree_util.tree_leaves(out):
        np.asarray(leaf.ravel()[0])


def measure_rtt(template=None) -> float:
    """Median cost of draining an ALREADY-COMPUTED output, subtracted from
    each timed sample.

    The drain fetches one scalar per output leaf, and each fetch is a
    serial tunnel round-trip (~70ms on axon) — so the sync cost scales
    with the output's LEAF COUNT, not with chip work.  Measuring it
    against a tiny one-leaf array undercounts a 3-leaf pipeline output by
    two whole round-trips (~0.15s billed as execution at k=1; the r04b
    session recorded exactly this: drained-k=1 0.250s vs pipelined
    0.142s vs race-row-at-k=4 0.154s).  Pass the warmed-up output pytree
    as `template` to measure the true per-sample sync cost; with no
    template the old tiny-array probe is kept (single-leaf drains)."""
    import jax.numpy as jnp

    probe = (jnp.zeros(8),) if template is None else template
    drain(probe)
    samples = []
    for _ in range(5):
        t0 = time.perf_counter()
        drain(probe)
        samples.append(time.perf_counter() - t0)
    return _median(samples)


def measure_drained(spec, g_pad, batch, wargs, origins, rtt
                    ) -> tuple[list[float], int, float]:
    """Per-sample-drained times until MIN_WALL_S total (guards 1-3).

    A sample is k back-to-back unique dispatches ending in one drain; k
    adapts upward when dispatches are fast (amortizing the tunnel RTT so
    legitimately fast hardware accumulates wall time instead of hitting
    the sample cap).  Returns (per-DISPATCH times, final k, total wall)."""
    k = 1
    times: list[float] = []
    wall = 0.0
    while (wall < MIN_WALL_S or len(times) < MIN_SAMPLES) \
            and len(times) < MAX_SAMPLES:
        t0 = time.perf_counter()
        out = None
        for _ in range(k):
            out = dispatch(spec, g_pad, batch, wargs, origins.next())
        drain(out)
        t = time.perf_counter() - t0
        wall += t
        times.append(max(t - rtt, 1e-9) / k)
        if t < max(4.0 * rtt, 0.2):
            # too fast to resolve above the RTT: drain more dispatches per
            # sample next round
            k = min(k * 4, 4096)
    return times, k, wall


def measure_pipelined(spec, g_pad, batch, wargs, origins, rtt) -> float:
    """k dispatches, one drain at the end (guard 5 cross-check)."""
    t0 = time.perf_counter()
    out = None
    for _ in range(PIPELINE_K):
        out = dispatch(spec, g_pad, batch, wargs, origins.next())
    drain(out)
    return (time.perf_counter() - t0 - rtt) / PIPELINE_K


from statistics import median as _median


def run() -> None:
    import jax

    preflight_probe(float(os.environ.get("BENCH_PROBE_DEADLINE_S",
                                         "240")))
    n_dev = len(jax.devices())
    platform = jax.devices()[0].platform
    _note("devices: %d (%s); pipeline dispatches single-device"
          % (n_dev, platform))
    batch = make_batch()
    _stamp("batch_resident")
    spec, wargs, g_pad = build_spec()
    origins = _OriginSequence()

    # compile + warm (unique origins too — even warmup never replays)
    warm = dispatch(spec, g_pad, batch, wargs, origins.next())
    drain(warm)
    _stamp("compiled")
    # Sync cost measured against the REAL output structure: the drain is
    # one serial tunnel round-trip per leaf, so a tiny one-leaf probe
    # undercounts it by (leaves-1) RTTs and bills the difference as chip
    # time (docstring of measure_rtt).
    rtt = measure_rtt(template=warm)
    _note("tunnel rtt: %.4fs for the %d-leaf output drain "
          "(subtracted per sample)"
          % (rtt, len(jax.tree_util.tree_leaves(warm))))

    _stamp("rtt_measured")
    samples, k_final, total_wall = measure_drained(spec, g_pad, batch,
                                                   wargs, origins, rtt)
    _stamp("measured")
    per_iter = _median(samples)
    _note("drained: %d samples (final k=%d dispatches/sample), "
          "median=%.4fs/dispatch, total wall=%.2fs (min=%.4fs max=%.4fs)"
          % (len(samples), k_final, per_iter, total_wall,
             min(samples), max(samples)))
    if total_wall < MIN_WALL_S:
        _skip("could not accumulate %.1fs of measured wall time"
              % MIN_WALL_S)
        return

    dp_per_sec = S * N / per_iter
    implied_bw = dp_per_sec * BYTES_PER_DP
    _note("implied HBM traffic: %.1f GB/s (>= %d B/dp)"
          % (implied_bw / 1e9, BYTES_PER_DP))
    if implied_bw > HBM_CAP_BYTES_S:
        _skip("implied bandwidth %.2e B/s exceeds the %.2e B/s "
              "plausibility cap — measurement artifact, refusing to emit"
              % (implied_bw, HBM_CAP_BYTES_S))
        return

    per_iter_pipe = measure_pipelined(spec, g_pad, batch, wargs, origins, rtt)
    ratio = per_iter / max(per_iter_pipe, 1e-9)
    _note("pipelined cross-check: %.4fs/dispatch (drained/pipelined = %.2fx)"
          % (per_iter_pipe, ratio))
    if ratio > 2.0 or ratio < 0.5:
        # The two timing methods disagree — one of them is an artifact.
        # Report the SLOWER (conservative) per-dispatch time; a bench may
        # understate but must never overstate.
        _note("WARNING: pipelined and drained timings disagree by >2x — "
              "reporting the slower of the two")
        per_iter = max(per_iter, per_iter_pipe)
        dp_per_sec = S * N / per_iter

    baseline = 1e9 / 2.0 / 8.0  # north star: 1B pts < 2s on 8 chips
    _emit({
        "metric": METRIC,
        "value": round(dp_per_sec, 1),
        "unit": "datapoints/sec/chip",
        "vs_baseline": round(dp_per_sec / baseline, 4),
    })


def main() -> None:
    _apply_mode_defaults()
    _arm_watchdog(float(os.environ.get("BENCH_DEADLINE_S", "1500")))
    try:
        run()
    except SystemExit:
        raise
    except BaseException as e:   # noqa: BLE001 — provenance over purity:
        # any backend/init/compile failure becomes a parseable artifact
        _skip("%s: %s" % (type(e).__name__, e))


if __name__ == "__main__":
    main()
