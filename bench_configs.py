"""BASELINE.md measurement configs 1-7 as runnable benchmarks.

`python bench_configs.py [--config N] [--scale F]` prints one JSON line per
config (bench.py stays the single-line headline bench the driver runs).

Configs (BASELINE.md / BASELINE.json):
  1. 1M pts, single series, avg 1h downsample          - correctness baseline
  2. 100M pts, sum/min/max/count multi-agg 10s         - multi-kernel fusion
  3. 10k-series group-by + avg downsample              - segment-reduce fan-out
  4. rate + p99 over 500M pts                          - non-associative kernels
  5. 1B pts -> 1m rollups, time-chunked                - offline batch pass
  6. bulk ingest points/sec (host write path)          - TSDB.add_points_bulk
  7. p50 end-to-end /api/query latency, 1B pts in-store - full served path

Timing methodology (same rules as bench.py — see its module docstring for
why `jax.block_until_ready` CANNOT be used on this platform):
  * every timed run ends in a host scalar fetch (drain) that provably
    empties the execution queue; the measured tunnel RTT is subtracted;
  * no dispatch is ever repeated with identical operands: repetitions
    shift the traced window origin / chunk base through a per-process
    random walk, so neither the runtime nor any future memoization layer
    can short-circuit a rep;
  * each config accumulates >= 1s of measured wall time where the scale
    allows, and reports a median over passes.

Configs 2/4/5 exceed device memory as one batch, so they run through the
streaming machinery (ops.streaming): chunks are generated on device by a
closed-form hash (the storage layer's role; generation is timed separately
with its own drains and subtracted).  Config 5 chunks by TIME (rollup
output rows are emitted per chunk — the write-side shape of
TSDB.addAggregatePoint); the others by point index.

Use --scale 0.01 for a quick CPU smoke run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from bench import drain, measure_rtt, _median

START = 1_356_998_400_000
STEP_MS = 10_000  # 10s cadence

MIN_WALL_S = 1.0
MIN_PASSES = 3
MAX_PASSES = 32


def _note(msg: str) -> None:
    print("[bench_configs] " + msg, file=sys.stderr, flush=True)


def _emit(config: int, label: str, points: int, seconds: float,
          n_dev: int, unit: str = "datapoints/sec/chip",
          baseline: float | None = None) -> None:
    rate = points / max(seconds, 1e-9) / n_dev
    if baseline is None:
        baseline = 1e9 / 2.0 / 8.0  # north star: 62.5M dp/s/chip
    print(json.dumps({
        "metric": "config %d: %s" % (config, label),
        "value": round(rate, 1),
        "unit": unit,
        "vs_baseline": round(rate / baseline, 4),
    }), flush=True)


class _Uniquifier:
    """Never-repeating int offsets (per-process random base + counter) —
    folded into window origins and chunk bases so no two dispatches are
    operand-identical, within or across runs."""

    def __init__(self):
        self._base = int.from_bytes(os.urandom(4), "big")
        self._i = 0

    def next(self, mod: int = 3_600_000) -> int:
        self._i += 1
        return (self._base + self._i * 7919) % mod


_UNIQ = _Uniquifier()
_RTT = 0.0

# Drain cost by leaf count: the drain is one serial tunnel round-trip
# PER LEAF of the drained structure (see bench.measure_rtt), so each
# distinct structure's sync cost is measured against the real thing once
# and cached.  Subtracting only the one-leaf _RTT would bill (leaves-1)
# round-trips per drain as execution time — and in the generation
# calibrations (which drain a 3-leaf batch per chunk) the error flips
# direction: inflated gen_time gets SUBTRACTED, overstating throughput.
_SYNC_BY_LEAVES: dict = {}


def _sync_cost(template) -> float:
    """Measured drain cost of this (already-computed) structure, floored
    at one round-trip; cached per leaf count."""
    import jax
    n = len(jax.tree_util.tree_leaves(template))
    if n not in _SYNC_BY_LEAVES:
        _SYNC_BY_LEAVES[n] = max(measure_rtt(template=template), _RTT)
    return _SYNC_BY_LEAVES[n]


def _timed_passes(run_pass, sync: float | None = None,
                  points: int | None = None):
    """Median per-pass seconds over unique-operand passes, >= MIN_WALL_S
    total measured wall; each pass must end with its own drain inside.
    `sync` is the measured drain cost of the pass's output structure
    (defaults to the one-leaf _RTT).

    Plausibility guard (bench.py guard 4): when a pass is so fast the
    sync subtraction cannot resolve it (dt - sync near zero, implying
    physically impossible throughput for `points`), report the RAW
    median instead — a small-scale smoke must understate, never emit a
    floored-to-1ns artifact (a 0.01-scale CPU run once printed 208T
    dp/s for config 3 exactly this way)."""
    sub = _RTT if sync is None else sync
    times = []
    raw = []
    wall = 0.0
    while (wall < MIN_WALL_S or len(times) < MIN_PASSES) \
            and len(times) < MAX_PASSES:
        t0 = time.perf_counter()
        run_pass()
        dt = time.perf_counter() - t0
        wall += dt
        raw.append(dt)
        times.append(max(dt - sub, 1e-9))
    per = _median(times)
    if points is not None:
        implied_bw = points / per * 13          # >= 13 bytes/datapoint
        if implied_bw > 3.5e12:                 # no chip streams faster
            _note("sync-unresolvable pass (%.2e B/s implied): "
                  "reporting the raw unsubtracted median" % implied_bw)
            per = _median(raw)
    return per, len(times)


def _chunk_gen(s, n, base_col):
    """Closed-form [s, n] chunk (ts sorted per row, deterministic values)."""
    import jax.numpy as jnp
    rows = jnp.arange(s, dtype=jnp.int64)
    cols = base_col + jnp.arange(n, dtype=jnp.int64)
    h = (rows[:, None] * 2_654_435_761 + cols[None, :] * 40_503) & 0x7FFFFFFF
    ts = START + cols[None, :] * STEP_MS + h % 4_000
    val = 100.0 + (h % 1_000).astype(jnp.float64) * 0.05
    mask = jnp.ones((s, n), dtype=bool)
    return ts, val, mask


_GEN = None


def _gen_fn():
    """Module-level jitted chunk generator — one compile cache for every
    pass (a per-pass jax.jit wrapper would land its recompile inside the
    gen calibration that gets SUBTRACTED from measured time, inflating
    the reported throughput)."""
    global _GEN
    if _GEN is None:
        import jax
        _GEN = jax.jit(_chunk_gen, static_argnums=(0, 1))
    return _GEN


# ------------------------------------------------------------------ #

def _grouped_config(config: int, label: str, s: int, n: int, gid, g: int,
                    spec, fixed, n_dev: int, reps_points: int) -> None:
    """Shared shape of configs 1 and 3: one grouped dispatch per pass,
    window origin shifted uniquely each pass."""
    import jax.numpy as jnp
    from opentsdb_tpu.ops.pipeline import run_group_pipeline

    gen = _gen_fn()
    batch = gen(s, n, 0)
    drain(batch)
    wspec, wargs = fixed.split()
    ts, val, mask = batch

    def one_pass():
        w = dict(wargs)
        w["first"] = wargs["first"] - jnp.asarray(_UNIQ.next(), jnp.int64)
        drain(run_group_pipeline(spec, ts, val, mask, gid, g, w))

    w0 = dict(wargs)
    w0["first"] = wargs["first"] - jnp.asarray(_UNIQ.next(), jnp.int64)
    warm = run_group_pipeline(spec, ts, val, mask, gid, g, w0)  # compile
    drain(warm)
    per_pass, n_passes = _timed_passes(one_pass, sync=_sync_cost(warm),
                                       points=s * n)
    _note("config %d: %d passes, median %.4fs" % (config, n_passes,
                                                  per_pass))
    _emit(config, label, reps_points, per_pass, n_dev)


def config1(scale: float, n_dev: int) -> None:
    """1M pts, one series, avg 1h — END TO END through the planner.

    r3 measured the bare device kernel and still lost 11x to the Java
    iterator (dispatch floor).  r4's fix is routing, so this config must
    measure what a client sees: TSQuery -> planner -> (host fast lane
    below tsd.query.host_lane.max_points | accelerator above) -> JSON
    dps.  Both lanes are reported; the default lane (host) is the
    headline config-1 number.
    """
    import numpy as np
    from opentsdb_tpu.core import TSDB
    from opentsdb_tpu.models import TSQuery, parse_m_subquery
    from opentsdb_tpu.utils.config import Config

    n = max(int(1_000_000 * scale), 1024)

    def mk(host_lane_pts):
        t = TSDB(Config({
            "tsd.core.auto_create_metrics": True,
            "tsd.query.device_cache.enable": "false",
            "tsd.query.mesh.enable": False,
            "tsd.query.host_lane.max_points": str(host_lane_pts),
        }))
        key = t._series_key("bench.c1", {"h": "a"}, create=True)
        ts_ms = START + np.arange(n, dtype=np.int64) * STEP_MS
        vals = 100.0 + (np.arange(n) % 1_000) * 0.05
        t.store.add_batch(key, ts_ms, vals, np.zeros(n, bool))
        return t

    for label, host_pts in (("host-lane", 10_000_000), ("device-lane", 0)):
        t = mk(host_pts)

        def one_pass():
            # unique start SECOND per pass (within the hour before the
            # data, so every point stays in range and the epoch-aligned
            # window grid genuinely varies): no cache layer can
            # short-circuit a repeat (review r4 — a sub-second offset
            # was quantized away by the //1000)
            off_s = _UNIQ.next(3600)
            q = TSQuery(start=str(START // 1000 - 3600 + off_s),
                        end=str((START + n * STEP_MS) // 1000),
                        queries=[parse_m_subquery("sum:1h-avg:bench.c1")])
            q.validate()
            res = t.new_query_runner().run(q)
            assert res and res[0].dps   # host values: inherently drained

        one_pass()  # compile
        per_pass, n_passes = _timed_passes(one_pass, points=n)
        _note("config 1 (%s): %d passes, median %.4fs"
              % (label, n_passes, per_pass))
        _emit(1, "1M pts single-series avg-1h end-to-end (%s)" % label,
              n, per_pass, 1)


def config3(scale: float, n_dev: int) -> None:
    """Group-by over 10k tag-series + avg downsample — one dispatch."""
    import jax.numpy as jnp
    from opentsdb_tpu.ops.downsample import FixedWindows, pad_pow2
    from opentsdb_tpu.ops.pipeline import PipelineSpec, DownsampleStep

    s = max(int(10_240 * scale), 64)
    n = 2048
    fixed = FixedWindows.for_range(START, START + n * STEP_MS, 3_600_000)
    wspec, _ = fixed.split()
    spec = PipelineSpec("avg", DownsampleStep("avg", wspec, "none", 0.0))
    _grouped_config(3, "10k-series group-by avg downsample", s, n,
                    jnp.arange(s, dtype=jnp.int64), pad_pow2(s), spec,
                    fixed, n_dev, s * n)


def _stream_pass(s, n_chunk, chunks, wspec, wargs, finishes, base0: int,
                 sketch: bool = False):
    """Generate+accumulate `chunks` chunks starting at column base0;
    returns (elapsed_minus_gen, finish outputs).  Every chunk base is
    unique (caller advances base0 per pass); generation is calibrated with
    its own drains over a disjoint base range."""
    from opentsdb_tpu.ops.streaming import StreamAccumulator, lanes_for

    gen = _gen_fn()

    # Calibrate generation cost alone (disjoint bases; drained per chunk).
    cal0 = base0 + chunks * n_chunk
    batch = None
    t0 = time.perf_counter()
    for k in range(chunks):
        batch = gen(s, n_chunk, cal0 + k * n_chunk)
        drain(batch)
    gen_wall = time.perf_counter() - t0
    gen_time = max(gen_wall - _sync_cost(batch) * chunks, 0.0)

    # Window-sliced folds: each chunk's window range is host-known, so
    # the accumulator merges an O(S*wc) slice instead of the full [S, W]
    # grid (the r04b chip session's 4.7s/chunk on config 2 was full-grid
    # fold traffic).
    first_ms = int(wargs["first"])
    interval = wspec.interval_ms
    wslice = (n_chunk * STEP_MS + 4_000) // interval + 2
    acc = StreamAccumulator.create(s, wspec, wargs, sketch=sketch,
                                   lanes=lanes_for(finishes),
                                   window_slice=wslice)
    t0 = time.perf_counter()
    for k in range(chunks):
        w0 = (START + (base0 + k * n_chunk) * STEP_MS - first_ms) \
            // interval
        acc.update(*gen(s, n_chunk, base0 + k * n_chunk), w0=w0)
    outs = [acc.finish(f) for f in finishes]
    drain(outs)
    elapsed = time.perf_counter() - t0 - _sync_cost(outs)
    assert acc.oob_count() == 0, "streaming slice dropped points"
    return max(elapsed - gen_time, 1e-9), outs


def config2(scale: float, n_dev: int) -> None:
    """100M pts, multi-agg (sum/min/max/count) 10s downsample, streamed."""
    from opentsdb_tpu.ops.downsample import FixedWindows

    total = int(100_000_000 * scale)
    s = 128
    n_chunk = 65_536
    chunks = max(total // (s * n_chunk), 1)
    span = n_chunk * chunks * STEP_MS
    points = s * n_chunk * chunks

    def one_pass():
        # unique chunk base AND matching window origin per pass
        base0 = _UNIQ.next(1 << 26)
        pass_start = START + base0 * STEP_MS
        fixed = FixedWindows.for_range(pass_start, pass_start + span,
                                       10_000)
        wspec, wargs = fixed.split()
        secs, _ = _stream_pass(s, n_chunk, chunks, wspec, wargs,
                               ["sum", "min", "max", "count"], base0)
        return secs

    one_pass()  # compile (wspec is shape-stable across passes)
    times = []
    wall = 0.0
    while (wall < MIN_WALL_S or len(times) < MIN_PASSES) \
            and len(times) < 8:
        secs = one_pass()
        times.append(secs)
        wall += secs
    _note("config 2: %d passes, median %.3fs" % (len(times),
                                                 _median(times)))
    _emit(2, "100M pts multi-agg 10s downsample (streamed)",
          points, _median(times), n_dev)


def config4(scale: float, n_dev: int) -> None:
    """rate + p99 over 500M pts: stream to grid, rate+percentile tail."""
    import jax.numpy as jnp
    from opentsdb_tpu.ops.downsample import FixedWindows
    from opentsdb_tpu.ops.pipeline import (
        PipelineSpec, DownsampleStep, run_grid_tail)
    from opentsdb_tpu.ops.rate import RateOptions

    total = int(500_000_000 * scale)
    s = 512
    n_chunk = 65_536
    chunks = max(total // (s * n_chunk), 1)
    span = n_chunk * chunks * STEP_MS
    fixed0 = FixedWindows.for_range(START, START + span, 60_000)
    wspec0, _ = fixed0.split()
    spec = PipelineSpec("p99", DownsampleStep("avg", wspec0, "none", 0.0),
                        rate=RateOptions())
    gid = jnp.zeros(s, jnp.int64)
    points = s * n_chunk * chunks

    def one_pass():
        base0 = _UNIQ.next(1 << 26) * 6  # keep origin 60s-aligned
        pass_start = START + base0 * STEP_MS
        fixed = FixedWindows.for_range(pass_start, pass_start + span,
                                       60_000)
        wspec, wargs = fixed.split()
        secs, outs = _stream_pass(s, n_chunk, chunks, wspec, wargs,
                                  ["avg"], base0)
        t0 = time.perf_counter()
        wts, v, m = outs[0]
        tail = run_grid_tail(spec, wts, v, m, gid, 1)
        drain(tail)
        return secs + max(time.perf_counter() - t0 - _sync_cost(tail), 0.0)

    one_pass()  # compile
    times = [one_pass() for _ in range(MIN_PASSES)]
    _note("config 4: %d passes, median %.3fs" % (len(times),
                                                 _median(times)))
    _emit(4, "rate+p99 over 500M pts (streamed grid + percentile tail)",
          points, _median(times), n_dev)


def config5(scale: float, n_dev: int) -> None:
    """1B pts -> 1m rollup lanes, time-chunked (write-side batch pass)."""
    from opentsdb_tpu.ops.downsample import FixedWindows
    from opentsdb_tpu.ops.streaming import StreamAccumulator, lanes_for

    total = int(1_000_000_000 * scale)
    s = 1024
    n_chunk = 65_536
    chunks = max(total // (s * n_chunk), 1)
    gen = _gen_fn()
    span = n_chunk * STEP_MS
    points = s * n_chunk * chunks

    def gen_calibration(base0):
        batch = None
        t0 = time.perf_counter()
        for k in range(chunks):
            batch = gen(s, n_chunk, base0 + k * n_chunk)
            drain(batch)
        wall = time.perf_counter() - t0
        return max(wall - _sync_cost(batch) * chunks, 0.0)

    # Each time chunk's 1m windows are disjoint from the next chunk's, so
    # rollup rows (sum/count/min/max lanes) emit per chunk — the write-side
    # shape of TSDB.addAggregatePoint (:1359-1457) batched per window.
    def one_chunk(k: int, base0: int) -> None:
        chunk_start = START + (base0 + k * n_chunk) * STEP_MS
        fixed = FixedWindows.for_range(chunk_start, chunk_start + span,
                                       60_000)
        wspec, wargs = fixed.split()
        acc = StreamAccumulator.create(
            s, wspec, wargs,
            lanes=lanes_for(("sum", "count", "min", "max")))
        acc.update(*gen(s, n_chunk, base0 + k * n_chunk))
        outs = [acc.finish(f) for f in ("sum", "count", "min", "max")]
        drain(outs)
        return outs

    # compile (same shapes every chunk); keep the output structure for
    # the per-chunk sync-cost subtraction below
    tmpl = one_chunk(0, _UNIQ.next(1 << 28))
    chunk_sync = _sync_cost(tmpl)

    def one_pass():
        base0 = _UNIQ.next(1 << 28)
        gen_time = gen_calibration(base0 + chunks * n_chunk)
        t0 = time.perf_counter()
        for k in range(chunks):
            one_chunk(k, base0)
        return max(time.perf_counter() - t0 - gen_time
                   - chunk_sync * chunks, 1e-9)

    times = [one_pass() for _ in range(MIN_PASSES)]
    _note("config 5: %d passes, median %.3fs" % (len(times),
                                                 _median(times)))
    _emit(5, "1B pts -> 1m rollup lanes (time-chunked)", points,
          _median(times), n_dev)


def config6(scale: float, n_dev: int) -> None:
    """Host ingest: bulk /api/put path vs per-point, points/sec.

    Pure host-side (no device dispatch): honest wall clock.  The emitted
    vs_baseline is the speedup of the bulk path over the per-point path
    (the reference's only write-scale claim is qualitative, README:12-15).
    """
    from opentsdb_tpu.core import TSDB
    from opentsdb_tpu.utils.config import Config

    n = max(int(400_000 * scale), 10_000)
    hosts = 64
    dps = [{"metric": "ingest.bench", "timestamp": 1_356_998_400 + i,
            "value": float(i % 97) + 0.5, "tags": {"host": "h%d"
                                                   % (i % hosts)}}
           for i in range(n)]

    t_bulk = TSDB(Config({"tsd.core.auto_create_metrics": True}))
    t0 = time.perf_counter()
    success, errors = t_bulk.add_points_bulk(dps)
    bulk_secs = time.perf_counter() - t0
    assert success == n and not errors

    # native C++ body parser (the path a real POST /api/put takes): raw
    # JSON bytes in, columnar batches out — includes the JSON parse the
    # pre-parsed python timing above gets for free
    body = json.dumps(dps).encode()
    t_native = TSDB(Config({"tsd.core.auto_create_metrics": True}))
    t0 = time.perf_counter()
    native = t_native.add_points_bulk_native(body)
    native_secs = time.perf_counter() - t0
    have_native = native is not None
    if have_native:
        assert native[0] == n and not native[1]

    t_single = TSDB(Config({"tsd.core.auto_create_metrics": True}))
    t0 = time.perf_counter()
    for dp in dps:
        t_single.add_point(dp["metric"], dp["timestamp"], dp["value"],
                           dp["tags"])
    single_secs = time.perf_counter() - t0

    _note("config 6: native %s, bulk %.3fs, per-point %.3fs for %d pts"
          % ("%.3fs" % native_secs if have_native else "unavailable",
             bulk_secs, single_secs, n))
    best_secs = native_secs if have_native else bulk_secs
    _emit(6, "bulk ingest points/sec via %s (vs_baseline = speedup over "
             "per-point add_point)"
          % ("the native C++ /api/put body parser" if have_native
             else "the python bulk path"),
          n, best_secs, 1, unit="points/sec ingested",
          baseline=n / max(single_secs, 1e-9))


def config7(scale: float, n_dev: int) -> None:
    """p50 end-to-end /api/query latency with 1B points IN THE STORE.

    The full served path: planner -> window_count budgeting -> streamed
    chunked reads straight out of the columnar store -> device accumulator
    -> grid tail -> JSON-able result.  Unlike configs 1-5 (device-resident
    batches), this includes host packing and host->device transfer — on
    the dev tunnel that transfer is the bottleneck and is called out in
    the metric text.  The planner's result fetch (np.asarray) is a real
    sync, so wall clock here is honest by construction.

    vs_baseline: north star is 1B pts < 2s on EIGHT chips — a 16
    chip-second budget, so vs_baseline = 16 / (p50_seconds * n_dev).
    """
    from opentsdb_tpu.core import TSDB
    from opentsdb_tpu.models import TSQuery, parse_m_subquery
    from opentsdb_tpu.utils.config import Config
    import numpy as np

    total = int(1_000_000_000 * scale)
    s = 1024
    per = max(total // s, 1024)
    tsdb = TSDB(Config({"tsd.core.auto_create_metrics": True}))
    t0 = time.perf_counter()
    rng = np.random.default_rng(0)
    for i in range(s):
        ts = (START + np.arange(per, dtype=np.int64) * STEP_MS
              + int(rng.integers(0, 4000)))
        sk = tsdb._series_key("lat.m", {"host": "h%04d" % i,
                                        "dc": "d%d" % (i % 16)},
                              create=True)
        tsdb.store.add_batch(sk, ts, rng.normal(100, 25, per), False)
    _note("config 7: ingested %d pts in %.1fs"
          % (s * per, time.perf_counter() - t0))

    end_s = (START + per * STEP_MS) // 1000 + 10

    def run_query():
        q = TSQuery(start=str(START // 1000), end=str(end_s),
                    queries=[parse_m_subquery("sum:1m-avg:lat.m{dc=*}")])
        q.validate()
        return tsdb.new_query_runner().run(q)

    # Production daemons run the maintenance thread, whose device-cache
    # refresh pins the metric's columns in HBM after the first (streamed)
    # query — the steady state a dashboard sees.  Metrics beyond the
    # cache's build budget keep streaming every pass (the honest
    # beyond-memory number).
    tsdb.start_maintenance()
    try:
        run_query()  # compile + queue the cache build
        deadline = time.time() + 60
        while (tsdb.device_cache is not None and len(tsdb.device_cache) == 0
               and s * per <= tsdb.device_cache.build_max_points
               and time.time() < deadline):
            time.sleep(0.5)
        cached = (tsdb.device_cache is not None
                  and len(tsdb.device_cache) > 0)
        if cached:
            run_query()     # compile the cached-batch shape untimed
        lats = []
        for _ in range(MIN_PASSES):
            t0 = time.perf_counter()
            run_query()
            lats.append(time.perf_counter() - t0)
    finally:
        if tsdb.maintenance is not None:
            tsdb.maintenance.stop(final_flush=False)
            tsdb.maintenance = None
    p50 = _median(lats)
    _note("config 7: latencies %s (device cache %s)"
          % ([round(x, 3) for x in lats],
             "warm" if cached else "not used"))
    print(json.dumps({
        "metric": "config 7: p50 /api/query latency, %d pts in-store, "
                  "%s; single-chip-equivalent target 16s"
                  % (s * per,
                     "served from the device-resident series cache "
                     "(production steady state: maintenance thread "
                     "pinned the metric in HBM after the first streamed "
                     "pass)" if cached else
                     "streamed via chunked store reads (beyond the "
                     "device cache budget; includes host packing + "
                     "host->device transfer)"),
        "value": round(p50, 3),
        "unit": "seconds p50 latency",
        "vs_baseline": round(16.0 / max(p50, 1e-9) / n_dev, 4),
    }), flush=True)


CONFIGS = {1: config1, 2: config2, 3: config3, 4: config4, 5: config5,
           6: config6, 7: config7}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, default=0,
                    help="run one config (default: all)")
    ap.add_argument("--scale", type=float, default=1.0,
                    help="shrink factor for smoke runs (e.g. 0.01)")
    ap.add_argument("--platform", default="",
                    help="force a jax platform (e.g. cpu) — the env var "
                         "alone is overridden by the ambient sitecustomize, "
                         "so CPU smoke runs need the in-process update")
    args = ap.parse_args()

    import opentsdb_tpu.ops  # noqa: F401  (jax x64)
    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    global _RTT
    n_dev = len(jax.devices())
    _note("devices: %d (%s)" % (n_dev, jax.devices()[0].platform))
    _RTT = measure_rtt()
    _note("tunnel rtt: %.4fs" % _RTT)

    targets = [args.config] if args.config else sorted(CONFIGS)
    for c in targets:
        _note("running config %d" % c)
        CONFIGS[c](args.scale, n_dev)


if __name__ == "__main__":
    main()
