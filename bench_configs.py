"""BASELINE.md measurement configs 1-5 as runnable benchmarks.

`python bench_configs.py [--config N] [--scale F]` prints one JSON line per
config (bench.py stays the single-line headline bench the driver runs).

Configs (BASELINE.md / BASELINE.json):
  1. 1M pts, single series, avg 1h downsample          - correctness baseline
  2. 100M pts, sum/min/max/count multi-agg 10s         - multi-kernel fusion
  3. 10k-series group-by + avg downsample              - segment-reduce fan-out
  4. rate + p99 over 500M pts                          - non-associative kernels
  5. 1B pts -> 1m rollups, time-chunked                - offline batch pass

Configs 2/4/5 exceed device memory as one batch, so they run through the
streaming machinery (ops.streaming): chunks are generated on device by a
closed-form hash (the storage layer's role; generation is timed separately
and subtracted via a generation-only calibration pass).  Config 5 chunks by
TIME (rollup output rows are emitted per chunk — the write-side shape of
TSDB.addAggregatePoint), the others by point index.

Use --scale 0.01 for a quick CPU smoke run.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

START = 1_356_998_400_000
STEP_MS = 10_000  # 10s cadence


def _note(msg: str) -> None:
    print("[bench_configs] " + msg, file=sys.stderr, flush=True)


def _emit(config: int, label: str, points: int, seconds: float,
          n_dev: int) -> None:
    dp_s_chip = points / max(seconds, 1e-9) / n_dev
    baseline = 1e9 / 2.0 / 8.0  # north star: 62.5M dp/s/chip
    print(json.dumps({
        "metric": "config %d: %s" % (config, label),
        "value": round(dp_s_chip, 1),
        "unit": "datapoints/sec/chip",
        "vs_baseline": round(dp_s_chip / baseline, 4),
    }), flush=True)


def _chunk_gen(s, n, base_col):
    """Closed-form [s, n] chunk (ts sorted per row, deterministic values)."""
    import jax.numpy as jnp
    rows = jnp.arange(s, dtype=jnp.int64)
    cols = base_col + jnp.arange(n, dtype=jnp.int64)
    h = (rows[:, None] * 2_654_435_761 + cols[None, :] * 40_503) & 0x7FFFFFFF
    ts = START + cols[None, :] * STEP_MS + h % 4_000
    val = 100.0 + (h % 1_000).astype(jnp.float64) * 0.05
    mask = jnp.ones((s, n), dtype=bool)
    return ts, val, mask


# ------------------------------------------------------------------ #

def config1(scale: float, n_dev: int) -> None:
    """1M pts, one series, avg 1h — through the production grouped path."""
    import jax
    import jax.numpy as jnp
    from opentsdb_tpu.ops.downsample import FixedWindows, pad_pow2
    from opentsdb_tpu.ops.pipeline import (
        PipelineSpec, DownsampleStep, run_group_pipeline)

    n = max(int(1_000_000 * scale), 1024)
    ts, val, mask = jax.jit(lambda: _chunk_gen(1, n, 0))()
    gid = jnp.zeros(1, jnp.int64)
    fixed = FixedWindows.for_range(START, START + n * STEP_MS, 3_600_000)
    wspec, wargs = fixed.split()
    spec = PipelineSpec("sum", DownsampleStep("avg", wspec, "none", 0.0))
    run_group_pipeline(spec, ts, val, mask, gid, 1, wargs)  # compile
    t0 = time.perf_counter()
    reps = 5
    out = None
    for _ in range(reps):
        out = run_group_pipeline(spec, ts, val, mask, gid, 1, wargs)
    jax.block_until_ready(out)
    _emit(1, "1M pts single-series avg-1h", n * reps,
          time.perf_counter() - t0, n_dev)


def _stream_pass(s, n_chunk, chunks, wspec, wargs, finishes):
    """Generate+accumulate `chunks` chunks; return elapsed minus gen-only."""
    import jax
    from opentsdb_tpu.ops.streaming import StreamAccumulator

    gen = jax.jit(_chunk_gen, static_argnums=(0, 1))

    # Calibrate generation cost alone.
    t0 = time.perf_counter()
    for k in range(chunks):
        jax.block_until_ready(gen(s, n_chunk, k * n_chunk))
    gen_time = time.perf_counter() - t0

    acc = StreamAccumulator.create(s, wspec, wargs)
    acc.update(*gen(s, n_chunk, 0))  # compile
    acc = StreamAccumulator.create(s, wspec, wargs)
    t0 = time.perf_counter()
    for k in range(chunks):
        acc.update(*gen(s, n_chunk, k * n_chunk))
    outs = [acc.finish(f) for f in finishes]
    jax.block_until_ready(outs)
    elapsed = time.perf_counter() - t0
    return max(elapsed - gen_time, 1e-9), outs


def config2(scale: float, n_dev: int) -> None:
    """100M pts, multi-agg (sum/min/max/count) 10s downsample, streamed."""
    from opentsdb_tpu.ops.downsample import FixedWindows

    total = int(100_000_000 * scale)
    s = 128
    n_chunk = 65_536
    chunks = max(total // (s * n_chunk), 1)
    span = n_chunk * chunks * STEP_MS
    fixed = FixedWindows.for_range(START, START + span, 10_000)
    wspec, wargs = fixed.split()
    secs, _ = _stream_pass(s, n_chunk, chunks, wspec, wargs,
                           ["sum", "min", "max", "count"])
    _emit(2, "100M pts multi-agg 10s downsample (streamed)",
          s * n_chunk * chunks, secs, n_dev)


def config3(scale: float, n_dev: int) -> None:
    """Group-by over 10k tag-series + avg downsample — one dispatch."""
    import jax
    import jax.numpy as jnp
    from opentsdb_tpu.ops.downsample import FixedWindows, pad_pow2
    from opentsdb_tpu.ops.pipeline import (
        PipelineSpec, DownsampleStep, run_group_pipeline)

    s = max(int(10_240 * scale), 64)
    n = 2048
    ts, val, mask = jax.jit(lambda: _chunk_gen(s, n, 0))()
    gid = jnp.arange(s, dtype=jnp.int64)  # every series its own group
    fixed = FixedWindows.for_range(START, START + n * STEP_MS, 3_600_000)
    wspec, wargs = fixed.split()
    spec = PipelineSpec("avg", DownsampleStep("avg", wspec, "none", 0.0))
    g = pad_pow2(s)
    run_group_pipeline(spec, ts, val, mask, gid, g, wargs)  # compile
    t0 = time.perf_counter()
    reps = 3
    out = None
    for _ in range(reps):
        out = run_group_pipeline(spec, ts, val, mask, gid, g, wargs)
    jax.block_until_ready(out)
    _emit(3, "10k-series group-by avg downsample", s * n * reps,
          time.perf_counter() - t0, n_dev)


def config4(scale: float, n_dev: int) -> None:
    """rate + p99 over 500M pts: stream to grid, rate+percentile tail."""
    import jax
    import jax.numpy as jnp
    from opentsdb_tpu.ops.downsample import FixedWindows
    from opentsdb_tpu.ops.pipeline import (
        PipelineSpec, DownsampleStep, run_grid_tail)
    from opentsdb_tpu.ops.rate import RateOptions

    total = int(500_000_000 * scale)
    s = 512
    n_chunk = 65_536
    chunks = max(total // (s * n_chunk), 1)
    span = n_chunk * chunks * STEP_MS
    fixed = FixedWindows.for_range(START, START + span, 60_000)
    wspec, wargs = fixed.split()
    t0 = time.perf_counter()
    secs, outs = _stream_pass(s, n_chunk, chunks, wspec, wargs, ["avg"])
    wts, v, m = outs[0]
    spec = PipelineSpec("p99", DownsampleStep("avg", wspec, "none", 0.0),
                        rate=RateOptions())
    gid = jnp.zeros(s, jnp.int64)
    tail = run_grid_tail(spec, wts, v, m, gid, 1)
    jax.block_until_ready(tail)
    tail_secs = time.perf_counter() - t0 - secs
    _emit(4, "rate+p99 over 500M pts (streamed grid + percentile tail)",
          s * n_chunk * chunks, secs + max(tail_secs, 0), n_dev)


def config5(scale: float, n_dev: int) -> None:
    """1B pts -> 1m rollup lanes, time-chunked (write-side batch pass)."""
    import jax
    from opentsdb_tpu.ops.downsample import FixedWindows
    from opentsdb_tpu.ops.streaming import StreamAccumulator

    total = int(1_000_000_000 * scale)
    s = 1024
    n_chunk = 65_536
    chunks = max(total // (s * n_chunk), 1)
    gen = jax.jit(_chunk_gen, static_argnums=(0, 1))

    t0 = time.perf_counter()
    for k in range(chunks):
        jax.block_until_ready(gen(s, n_chunk, k * n_chunk))
    gen_time = time.perf_counter() - t0

    # Each time chunk's 1m windows are disjoint from the next chunk's, so
    # rollup rows (sum/count/min/max lanes) emit per chunk — the write-side
    # shape of TSDB.addAggregatePoint (:1359-1457) batched per window.
    span = n_chunk * STEP_MS

    def one_chunk(k: int) -> None:
        chunk_start = START + k * span
        fixed = FixedWindows.for_range(chunk_start, chunk_start + span,
                                       60_000)
        wspec, wargs = fixed.split()
        acc = StreamAccumulator.create(s, wspec, wargs)
        acc.update(*gen(s, n_chunk, k * n_chunk))
        lanes = [acc.finish(f) for f in ("sum", "count", "min", "max")]
        jax.block_until_ready(lanes)

    one_chunk(0)  # compile (same [s, n_chunk] shape for every chunk)
    t0 = time.perf_counter()
    for k in range(chunks):
        one_chunk(k)
    elapsed = max(time.perf_counter() - t0 - gen_time, 1e-9)
    points = s * n_chunk * chunks
    _emit(5, "1B pts -> 1m rollup lanes (time-chunked)", points, elapsed,
          n_dev)


CONFIGS = {1: config1, 2: config2, 3: config3, 4: config4, 5: config5}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, default=0,
                    help="run one config (default: all)")
    ap.add_argument("--scale", type=float, default=1.0,
                    help="shrink factor for smoke runs (e.g. 0.01)")
    args = ap.parse_args()

    import opentsdb_tpu.ops  # noqa: F401  (jax x64)
    import jax
    n_dev = len(jax.devices())
    _note("devices: %d (%s)" % (n_dev, jax.devices()[0].platform))

    targets = [args.config] if args.config else sorted(CONFIGS)
    for c in targets:
        _note("running config %d" % c)
        CONFIGS[c](args.scale, n_dev)


if __name__ == "__main__":
    main()
