"""BASELINE.md measurement configs 1-7 as runnable benchmarks.

`python bench_configs.py [--config N] [--scale F]` prints one JSON line per
config (bench.py stays the single-line headline bench the driver runs).

Configs (BASELINE.md / BASELINE.json):
  1. 1M pts, single series, avg 1h downsample          - correctness baseline
  2. 100M pts, sum/min/max/count multi-agg 10s         - multi-kernel fusion
  3. 10k-series group-by + avg downsample              - segment-reduce fan-out
  4. rate + p99 over 500M pts                          - non-associative kernels
  5. 1B pts -> 1m rollups, time-chunked                - offline batch pass
  6. bulk ingest points/sec (host write path)          - TSDB.add_points_bulk
  7. p50 end-to-end /api/query latency, 1B pts in-store - full served path

Timing methodology (same rules as bench.py — see its module docstring for
why `jax.block_until_ready` CANNOT be used on this platform):
  * every timed run ends in a host scalar fetch (drain) that provably
    empties the execution queue; the measured tunnel RTT is subtracted;
  * no dispatch is ever repeated with identical operands: repetitions
    shift the traced window origin / chunk base through a per-process
    random walk, so neither the runtime nor any future memoization layer
    can short-circuit a rep;
  * each config accumulates >= 1s of measured wall time where the scale
    allows, and reports a median over passes.

Configs 2/4/5 exceed device memory as one batch, so they run through the
streaming machinery (ops.streaming): chunks are generated on device by a
closed-form hash (the storage layer's role; generation is timed separately
with its own drains and subtracted).  Config 5 chunks by TIME (rollup
output rows are emitted per chunk — the write-side shape of
TSDB.addAggregatePoint); the others by point index.

Use --scale 0.01 for a quick CPU smoke run.

Deadline discipline (--deadline S): every loop that can run long — timed
passes, streamed chunk folds, config 7's ingest — checks a COOPERATIVE
per-config deadline between units of work and finalizes early with a
partial-but-honest row (the points actually processed over the seconds
actually measured) instead of being SIGKILLed mid-dispatch by an outer
subprocess timeout.  A JAX process killed mid-dispatch wedges the axon
tunnel (it ended both r4 chip sessions and cost configs 5-7 twice);
the outer kill is now a last resort that fires only after this
in-process deadline has already had a grace window to finish draining.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import threading
import time

from bench import drain, measure_rtt, _median

START = 1_356_998_400_000
STEP_MS = 10_000  # 10s cadence

MIN_WALL_S = 1.0
MIN_PASSES = 3
MAX_PASSES = 32

# Cooperative per-config deadline (monotonic seconds; None = unlimited).
_DEADLINE: float | None = None
_CURRENT_CONFIG = 0


def _deadline_left() -> float:
    return math.inf if _DEADLINE is None else _DEADLINE - time.monotonic()


def _fits(estimated_s: float) -> bool:
    """Can another unit of work (estimated from the last one) finish
    before the deadline?  1.5x headroom: overrunning by one unit is the
    failure mode this exists to prevent."""
    return _deadline_left() > 1.5 * estimated_s


def _note(msg: str) -> None:
    print("[bench_configs] " + msg, file=sys.stderr, flush=True)


# Audit trail for the sync-unresolvable plausibility guard: when it
# fires, BOTH medians land in the emitted record so the classification
# can be re-checked offline (ADVICE r4: a hardcoded ceiling could
# silently flip a future faster chip between subtracted and raw).
_GUARD_INFO: dict | None = None


def _emit(config: int, label: str, points: int, seconds: float,
          n_dev: int, unit: str = "datapoints/sec/chip",
          baseline: float | None = None) -> None:
    global _GUARD_INFO
    rate = points / max(seconds, 1e-9) / n_dev
    if baseline is None:
        baseline = 1e9 / 2.0 / 8.0  # north star: 62.5M dp/s/chip
    rec = {
        "metric": "config %d: %s" % (config, label),
        "value": round(rate, 1),
        "unit": unit,
        "vs_baseline": round(rate / baseline, 4),
    }
    if _GUARD_INFO:
        rec.update(_GUARD_INFO)
        _GUARD_INFO = None
    print(json.dumps(rec), flush=True)


class _Uniquifier:
    """Never-repeating int offsets (per-process random base + counter) —
    folded into window origins and chunk bases so no two dispatches are
    operand-identical, within or across runs."""

    def __init__(self):
        self._base = int.from_bytes(os.urandom(4), "big")
        self._i = 0

    def next(self, mod: int = 3_600_000) -> int:
        self._i += 1
        return (self._base + self._i * 7919) % mod


_UNIQ = _Uniquifier()
_RTT = 0.0

# Drain cost by output structure: the drain is one serial tunnel
# round-trip PER LEAF of the drained structure (see bench.measure_rtt),
# so each distinct structure's sync cost is measured against the real
# thing once and cached.  Subtracting only the one-leaf _RTT would bill
# (leaves-1) round-trips per drain as execution time — and in the
# generation calibrations (which drain a 3-leaf batch per chunk) the
# error flips direction: inflated gen_time gets SUBTRACTED, overstating
# throughput.  Keyed on the full structure identity (treedef + leaf
# shapes/dtypes), not leaf count alone: two same-leaf-count outputs
# (a replicated tail vs a sharded grid) must not share one cached value.
_SYNC_BY_STRUCT: dict = {}


def _sync_cost(template) -> float:
    """Measured drain cost of this (already-computed) structure, floored
    at one round-trip; cached per structure identity."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(template)
    key = (str(treedef),
           tuple((tuple(l.shape), str(l.dtype)) for l in leaves))
    if key not in _SYNC_BY_STRUCT:
        _SYNC_BY_STRUCT[key] = max(measure_rtt(template=template), _RTT)
    return _SYNC_BY_STRUCT[key]


def _timed_passes(run_pass, sync: float | None = None,
                  points: int | None = None):
    """Median per-pass seconds over unique-operand passes, >= MIN_WALL_S
    total measured wall; each pass must end with its own drain inside.
    `sync` is the measured drain cost of the pass's output structure
    (defaults to the one-leaf _RTT).

    Plausibility guard (bench.py guard 4): when a pass is so fast the
    sync subtraction cannot resolve it (dt - sync near zero, implying
    physically impossible throughput for `points`), report the RAW
    median instead — a small-scale smoke must understate, never emit a
    floored-to-1ns artifact (a 0.01-scale CPU run once printed 208T
    dp/s for config 3 exactly this way)."""
    global _GUARD_INFO
    sub = _RTT if sync is None else sync
    times = []
    raw = []
    wall = 0.0
    while (wall < MIN_WALL_S or len(times) < MIN_PASSES) \
            and len(times) < MAX_PASSES:
        if times and not _fits(raw[-1]):
            _note("deadline: stopping after %d passes (%.0fs left)"
                  % (len(times), _deadline_left()))
            break
        t0 = time.perf_counter()
        run_pass()
        dt = time.perf_counter() - t0
        wall += dt
        raw.append(dt)
        times.append(max(dt - sub, 1e-9))
    per = _median(times)
    if points is not None:
        implied_bw = points / per * 13          # >= 13 bytes/datapoint
        if implied_bw > 3.5e12:                 # no chip streams faster
            _note("sync-unresolvable pass (%.2e B/s implied): "
                  "reporting the raw unsubtracted median" % implied_bw)
            # both medians ride the emitted record for offline audit
            _GUARD_INFO = {"sync_unresolvable": True,
                           "raw_median_s": round(_median(raw), 6),
                           "subtracted_median_s": round(per, 6)}
            per = _median(raw)
    return per, len(times)


def _chunk_gen(s, n, base_col):
    """Closed-form [s, n] chunk (ts sorted per row, deterministic values)."""
    import jax.numpy as jnp
    rows = jnp.arange(s, dtype=jnp.int64)
    cols = base_col + jnp.arange(n, dtype=jnp.int64)
    h = (rows[:, None] * 2_654_435_761 + cols[None, :] * 40_503) & 0x7FFFFFFF
    ts = START + cols[None, :] * STEP_MS + h % 4_000
    val = 100.0 + (h % 1_000).astype(jnp.float64) * 0.05
    mask = jnp.ones((s, n), dtype=bool)
    return ts, val, mask


def _queue_sync(acc) -> None:
    """Force the execution queue with ONE scalar fetch (~one tunnel
    round-trip) — jax.block_until_ready is a no-op on axon (bench.py
    module docstring), so a host fetch is the only real sync."""
    import jax
    import numpy as np
    leaf = jax.tree_util.tree_leaves(acc.state)[0]
    np.asarray(leaf.ravel()[0])


_GEN = None


def _gen_fn():
    """Module-level jitted chunk generator — one compile cache for every
    pass (a per-pass jax.jit wrapper would land its recompile inside the
    gen calibration that gets SUBTRACTED from measured time, inflating
    the reported throughput)."""
    global _GEN
    if _GEN is None:
        import jax
        _GEN = jax.jit(_chunk_gen, static_argnums=(0, 1))
    return _GEN


# ------------------------------------------------------------------ #

def _grouped_config(config: int, label: str, s: int, n: int, gid, g: int,
                    spec, fixed, n_dev: int, reps_points: int) -> None:
    """Shared shape of configs 1 and 3: one grouped dispatch per pass,
    window origin shifted uniquely each pass."""
    import jax.numpy as jnp
    from opentsdb_tpu.ops.pipeline import run_group_pipeline

    gen = _gen_fn()
    batch = gen(s, n, 0)
    drain(batch)
    wspec, wargs = fixed.split()
    ts, val, mask = batch

    def one_pass():
        w = dict(wargs)
        w["first"] = wargs["first"] - jnp.asarray(_UNIQ.next(), jnp.int64)
        drain(run_group_pipeline(spec, ts, val, mask, gid, g, w))

    w0 = dict(wargs)
    w0["first"] = wargs["first"] - jnp.asarray(_UNIQ.next(), jnp.int64)
    warm = run_group_pipeline(spec, ts, val, mask, gid, g, w0)  # compile
    drain(warm)
    per_pass, n_passes = _timed_passes(one_pass, sync=_sync_cost(warm),
                                       points=s * n)
    _note("config %d: %d passes, median %.4fs" % (config, n_passes,
                                                  per_pass))
    _emit(config, label, reps_points, per_pass, n_dev)


def config1(scale: float, n_dev: int) -> None:
    """1M pts, one series, avg 1h — END TO END through the planner.

    r3 measured the bare device kernel and still lost 11x to the Java
    iterator (dispatch floor).  r4's fix is routing, so this config must
    measure what a client sees: TSQuery -> planner -> (host fast lane
    below tsd.query.host_lane.max_points | accelerator above) -> JSON
    dps.  Both lanes are reported; the default lane (host) is the
    headline config-1 number.
    """
    import numpy as np
    from opentsdb_tpu.core import TSDB
    from opentsdb_tpu.models import TSQuery, parse_m_subquery
    from opentsdb_tpu.utils.config import Config

    n = max(int(1_000_000 * scale), 1024)

    def mk(host_lane_pts):
        t = TSDB(Config({
            "tsd.core.auto_create_metrics": True,
            "tsd.query.device_cache.enable": "false",
            "tsd.query.mesh.enable": False,
            "tsd.query.host_lane.max_points": str(host_lane_pts),
        }))
        key = t._series_key("bench.c1", {"h": "a"}, create=True)
        ts_ms = START + np.arange(n, dtype=np.int64) * STEP_MS
        vals = 100.0 + (np.arange(n) % 1_000) * 0.05
        t.store.add_batch(key, ts_ms, vals, np.zeros(n, bool))
        return t

    for label, host_pts in (("host-lane", 10_000_000), ("device-lane", 0)):
        t = mk(host_pts)

        def one_pass():
            # unique start SECOND per pass (within the hour before the
            # data, so every point stays in range and the epoch-aligned
            # window grid genuinely varies): no cache layer can
            # short-circuit a repeat (review r4 — a sub-second offset
            # was quantized away by the //1000)
            off_s = _UNIQ.next(3600)
            q = TSQuery(start=str(START // 1000 - 3600 + off_s),
                        end=str((START + n * STEP_MS) // 1000),
                        queries=[parse_m_subquery("sum:1h-avg:bench.c1")])
            q.validate()
            res = t.new_query_runner().run(q)
            assert res and res[0].dps   # host values: inherently drained

        one_pass()  # compile
        per_pass, n_passes = _timed_passes(one_pass, points=n)
        _note("config 1 (%s): %d passes, median %.4fs"
              % (label, n_passes, per_pass))
        _emit(1, "1M pts single-series avg-1h end-to-end (%s)" % label,
              n, per_pass, 1)


def config3(scale: float, n_dev: int) -> None:
    """Group-by over 10k tag-series + avg downsample — one dispatch."""
    import jax.numpy as jnp
    from opentsdb_tpu.ops.downsample import FixedWindows, pad_pow2
    from opentsdb_tpu.ops.pipeline import PipelineSpec, DownsampleStep

    s = max(int(10_240 * scale), 64)
    n = 2048
    fixed = FixedWindows.for_range(START, START + n * STEP_MS, 3_600_000)
    wspec, _ = fixed.split()
    spec = PipelineSpec("avg", DownsampleStep("avg", wspec, "none", 0.0))
    _grouped_config(3, "10k-series group-by avg downsample", s, n,
                    jnp.arange(s, dtype=jnp.int64), pad_pow2(s), spec,
                    fixed, n_dev, s * n)


def _stream_pass(s, n_chunk, chunks, wspec, wargs, finishes, base0: int,
                 sketch: bool = False):
    """Generate+accumulate up to `chunks` chunks starting at column
    base0; returns (elapsed_minus_gen, finish outputs, chunks_done).
    Every chunk base is unique (caller advances base0 per pass);
    generation is calibrated with its own drains over a disjoint base
    range.  Both loops check the cooperative deadline BETWEEN chunks:
    a slow chip folds fewer chunks and the caller reports the partial
    point count honestly — it is never SIGKILLed mid-dispatch."""
    from opentsdb_tpu.ops.streaming import StreamAccumulator, lanes_for

    gen = _gen_fn()

    # Calibrate generation cost alone (disjoint bases; drained per chunk).
    # The per-chunk gen cost also feeds the fold loop's deadline estimate.
    cal0 = base0 + chunks * n_chunk
    batch = None
    t0 = time.perf_counter()
    cal_done = 0
    for k in range(chunks):
        if cal_done and not _fits((time.perf_counter() - t0) / cal_done):
            break
        batch = gen(s, n_chunk, cal0 + k * n_chunk)
        drain(batch)
        cal_done += 1
    gen_wall = time.perf_counter() - t0
    gen_per_chunk = max(gen_wall / cal_done - _sync_cost(batch), 0.0)

    # Window-sliced folds: each chunk's window range is host-known, so
    # the accumulator merges an O(S*wc) slice instead of the full [S, W]
    # grid (the r04b chip session's 4.7s/chunk on config 2 was full-grid
    # fold traffic).
    first_ms = int(wargs["first"])
    interval = wspec.interval_ms
    wslice = (n_chunk * STEP_MS + 4_000) // interval + 2
    acc = StreamAccumulator.create(s, wspec, wargs, sketch=sketch,
                                   lanes=lanes_for(finishes),
                                   window_slice=wslice)
    # update() is async (returns at enqueue): without a sync the
    # between-chunk clock reads enqueue time and a slow chip is only
    # discovered inside the final — uninterruptible — drain.  With a
    # deadline armed, one scalar fetch per chunk forces the queue so
    # elapsed/done is true execution time; each fetch costs ~one RTT,
    # measured and subtracted below.
    pace = _DEADLINE is not None
    t0 = time.perf_counter()
    done = 0
    for k in range(chunks):
        if done and not _fits((time.perf_counter() - t0) / done):
            _note("deadline: folding stopped at chunk %d/%d (%.0fs left)"
                  % (done, chunks, _deadline_left()))
            break
        w0 = (START + (base0 + k * n_chunk) * STEP_MS - first_ms) \
            // interval
        acc.update(*gen(s, n_chunk, base0 + k * n_chunk), w0=w0)
        done += 1
        if pace:
            _queue_sync(acc)
        if done % 4 == 0:
            _note("stream: %d/%d chunks (%.2fs/chunk)"
                  % (done, chunks, (time.perf_counter() - t0) / done))
    outs = [acc.finish(f) for f in finishes]
    drain(outs)
    elapsed = time.perf_counter() - t0 - _sync_cost(outs)
    if pace:
        elapsed -= _RTT * done
    assert acc.oob_count() == 0, "streaming slice dropped points"
    return max(elapsed - gen_per_chunk * done, 1e-9), outs, done


def config2(scale: float, n_dev: int) -> None:
    """100M pts, multi-agg (sum/min/max/count) 10s downsample, streamed."""
    from opentsdb_tpu.ops.downsample import FixedWindows

    total = int(100_000_000 * scale)
    s = 128
    n_chunk = 65_536
    chunks = max(total // (s * n_chunk), 1)
    span = n_chunk * chunks * STEP_MS
    points = s * n_chunk * chunks

    def one_pass():
        # unique chunk base AND matching window origin per pass
        base0 = _UNIQ.next(1 << 26)
        pass_start = START + base0 * STEP_MS
        fixed = FixedWindows.for_range(pass_start, pass_start + span,
                                       10_000)
        wspec, wargs = fixed.split()
        secs, _, done = _stream_pass(s, n_chunk, chunks, wspec, wargs,
                                     ["sum", "min", "max", "count"], base0)
        return secs, s * n_chunk * done

    one_pass()  # compile (wspec is shape-stable across passes)
    passes = []     # (secs, points actually folded) — may be partial
    wall = 0.0
    t_loop = time.perf_counter()
    while (wall < MIN_WALL_S or len(passes) < MIN_PASSES) \
            and len(passes) < 8:
        if passes and not _fits((time.perf_counter() - t_loop)
                                / len(passes)):
            break
        secs, pts = one_pass()
        passes.append((secs, pts))
        wall += secs
    ranked = sorted(passes, key=lambda p: p[0] / p[1])
    secs_med, pts_med = ranked[len(ranked) // 2]   # median per-point time
    partial = pts_med < points
    _note("config 2: %d passes, median %.3fs over %d pts%s"
          % (len(passes), secs_med, pts_med,
             " (deadline-partial)" if partial else ""))
    _emit(2, "100M pts multi-agg 10s downsample (streamed)%s"
          % (" [partial: %d of %d pts before the deadline]"
             % (pts_med, points) if partial else ""),
          pts_med, secs_med, n_dev)


def config4(scale: float, n_dev: int) -> None:
    """rate + p99 over 500M pts: stream to grid, rate+percentile tail."""
    import jax.numpy as jnp
    from opentsdb_tpu.ops.downsample import FixedWindows
    from opentsdb_tpu.ops.pipeline import (
        PipelineSpec, DownsampleStep, run_grid_tail)
    from opentsdb_tpu.ops.rate import RateOptions

    total = int(500_000_000 * scale)
    s = 512
    n_chunk = 65_536
    chunks = max(total // (s * n_chunk), 1)
    span = n_chunk * chunks * STEP_MS
    fixed0 = FixedWindows.for_range(START, START + span, 60_000)
    wspec0, _ = fixed0.split()
    spec = PipelineSpec("p99", DownsampleStep("avg", wspec0, "none", 0.0),
                        rate=RateOptions())
    gid = jnp.zeros(s, jnp.int64)
    points = s * n_chunk * chunks

    def one_pass():
        base0 = _UNIQ.next(1 << 26) * 6  # keep origin 60s-aligned
        pass_start = START + base0 * STEP_MS
        fixed = FixedWindows.for_range(pass_start, pass_start + span,
                                       60_000)
        wspec, wargs = fixed.split()
        secs, outs, done = _stream_pass(s, n_chunk, chunks, wspec, wargs,
                                        ["avg"], base0)
        t0 = time.perf_counter()
        wts, v, m = outs[0]
        tail = run_grid_tail(spec, wts, v, m, gid, 1)
        drain(tail)
        tail_s = max(time.perf_counter() - t0 - _sync_cost(tail), 0.0)
        return secs + tail_s, s * n_chunk * done

    one_pass()  # compile
    t1 = time.perf_counter()
    passes = [one_pass()]
    last_wall = time.perf_counter() - t1
    for _ in range(MIN_PASSES - 1):
        if not _fits(last_wall):
            break
        t1 = time.perf_counter()
        passes.append(one_pass())
        last_wall = time.perf_counter() - t1
    ranked = sorted(passes, key=lambda p: p[0] / p[1])
    secs_med, pts_med = ranked[len(ranked) // 2]
    partial = pts_med < points
    _note("config 4: %d passes, median %.3fs over %d pts%s"
          % (len(passes), secs_med, pts_med,
             " (deadline-partial)" if partial else ""))
    _emit(4, "rate+p99 over 500M pts (streamed grid + percentile tail)%s"
          % (" [partial: %d of %d pts before the deadline]"
             % (pts_med, points) if partial else ""),
          pts_med, secs_med, n_dev)


def config5(scale: float, n_dev: int) -> None:
    """1B pts -> 1m rollup lanes, time-chunked (write-side batch pass)."""
    from opentsdb_tpu.ops.downsample import FixedWindows
    from opentsdb_tpu.ops.streaming import StreamAccumulator, lanes_for

    total = int(1_000_000_000 * scale)
    s = 1024
    n_chunk = 65_536
    chunks = max(total // (s * n_chunk), 1)
    gen = _gen_fn()
    span = n_chunk * STEP_MS
    points = s * n_chunk * chunks

    def gen_calibration(base0):
        batch = None
        t0 = time.perf_counter()
        done = 0
        for k in range(chunks):
            if done and not _fits((time.perf_counter() - t0) / done):
                break
            batch = gen(s, n_chunk, base0 + k * n_chunk)
            drain(batch)
            done += 1
        wall = time.perf_counter() - t0
        return max(wall / done - _sync_cost(batch), 0.0)   # per chunk

    # Each time chunk's 1m windows are disjoint from the next chunk's, so
    # rollup rows (sum/count/min/max lanes) emit per chunk — the write-side
    # shape of TSDB.addAggregatePoint (:1359-1457) batched per window.
    def one_chunk(k: int, base0: int) -> None:
        chunk_start = START + (base0 + k * n_chunk) * STEP_MS
        fixed = FixedWindows.for_range(chunk_start, chunk_start + span,
                                       60_000)
        wspec, wargs = fixed.split()
        acc = StreamAccumulator.create(
            s, wspec, wargs,
            lanes=lanes_for(("sum", "count", "min", "max")))
        acc.update(*gen(s, n_chunk, base0 + k * n_chunk))
        outs = [acc.finish(f) for f in ("sum", "count", "min", "max")]
        drain(outs)
        return outs

    # compile (same shapes every chunk); keep the output structure for
    # the per-chunk sync-cost subtraction below.  Progress notes bracket
    # every potentially-slow phase: the r5 session's config-5 watchdog
    # fired with ZERO notes in stderr, leaving the hang unattributable.
    _note("config 5: compiling rollup chunk (%d chunks/pass)" % chunks)
    tmpl = one_chunk(0, _UNIQ.next(1 << 28))
    chunk_sync = _sync_cost(tmpl)
    _note("config 5: compile done")

    def one_pass():
        base0 = _UNIQ.next(1 << 28)
        gen_per_chunk = gen_calibration(base0 + chunks * n_chunk)
        _note("config 5: gen calibrated (%.3fs/chunk)" % gen_per_chunk)
        t0 = time.perf_counter()
        done = 0
        for k in range(chunks):
            # one_chunk drains per chunk, so elapsed/done is real
            # execution time and the deadline check is meaningful
            if done and not _fits((time.perf_counter() - t0) / done):
                _note("deadline: rollup stopped at chunk %d/%d"
                      % (done, chunks))
                break
            one_chunk(k, base0)
            done += 1
            if done % 4 == 0:
                _note("config 5: %d/%d chunks (%.2fs/chunk)"
                      % (done, chunks, (time.perf_counter() - t0) / done))
        secs = max(time.perf_counter() - t0
                   - (gen_per_chunk + chunk_sync) * done, 1e-9)
        return secs, s * n_chunk * done

    t1 = time.perf_counter()
    passes = [one_pass()]
    last_wall = time.perf_counter() - t1
    for _ in range(MIN_PASSES - 1):
        if not _fits(last_wall):
            break
        t1 = time.perf_counter()
        passes.append(one_pass())
        last_wall = time.perf_counter() - t1
    ranked = sorted(passes, key=lambda p: p[0] / p[1])
    secs_med, pts_med = ranked[len(ranked) // 2]
    partial = pts_med < points
    _note("config 5: %d passes, median %.3fs over %d pts%s"
          % (len(passes), secs_med, pts_med,
             " (deadline-partial)" if partial else ""))
    _emit(5, "1B pts -> 1m rollup lanes (time-chunked)%s"
          % (" [partial: %d of %d pts before the deadline]"
             % (pts_med, points) if partial else ""),
          pts_med, secs_med, n_dev)


def config6(scale: float, n_dev: int) -> None:
    """Host ingest: bulk /api/put path vs per-point, points/sec.

    Pure host-side (no device dispatch): honest wall clock.  The emitted
    vs_baseline is the speedup of the bulk path over the per-point path
    (the reference's only write-scale claim is qualitative, README:12-15).
    """
    from opentsdb_tpu.core import TSDB
    from opentsdb_tpu.utils.config import Config

    n = max(int(400_000 * scale), 10_000)
    hosts = 64
    dps = [{"metric": "ingest.bench", "timestamp": 1_356_998_400 + i,
            "value": float(i % 97) + 0.5, "tags": {"host": "h%d"
                                                   % (i % hosts)}}
           for i in range(n)]

    t_bulk = TSDB(Config({"tsd.core.auto_create_metrics": True}))
    t0 = time.perf_counter()
    success, errors = t_bulk.add_points_bulk(dps)
    bulk_secs = time.perf_counter() - t0
    assert success == n and not errors

    # native C++ body parser (the path a real POST /api/put takes): raw
    # JSON bytes in, columnar batches out — includes the JSON parse the
    # pre-parsed python timing above gets for free
    body = json.dumps(dps).encode()
    t_native = TSDB(Config({"tsd.core.auto_create_metrics": True}))
    t0 = time.perf_counter()
    native = t_native.add_points_bulk_native(body)
    native_secs = time.perf_counter() - t0
    have_native = native is not None
    if have_native:
        assert native[0] == n and not native[1]

    t_single = TSDB(Config({"tsd.core.auto_create_metrics": True}))
    t0 = time.perf_counter()
    for dp in dps:
        t_single.add_point(dp["metric"], dp["timestamp"], dp["value"],
                           dp["tags"])
    single_secs = time.perf_counter() - t0

    _note("config 6: native %s, bulk %.3fs, per-point %.3fs for %d pts"
          % ("%.3fs" % native_secs if have_native else "unavailable",
             bulk_secs, single_secs, n))
    best_secs = native_secs if have_native else bulk_secs
    _emit(6, "bulk ingest points/sec via %s (vs_baseline = speedup over "
             "per-point add_point)"
          % ("the native C++ /api/put body parser" if have_native
             else "the python bulk path"),
          n, best_secs, 1, unit="points/sec ingested",
          baseline=n / max(single_secs, 1e-9))


def config7(scale: float, n_dev: int) -> None:
    """p50 end-to-end /api/query latency with 1B points IN THE STORE.

    The full served path: planner -> window_count budgeting -> streamed
    chunked reads straight out of the columnar store -> device accumulator
    -> grid tail -> JSON-able result.  Unlike configs 1-5 (device-resident
    batches), this includes host packing and host->device transfer — on
    the dev tunnel that transfer is the bottleneck and is called out in
    the metric text.  The planner's result fetch (np.asarray) is a real
    sync, so wall clock here is honest by construction.

    vs_baseline: north star is 1B pts < 2s on EIGHT chips — a 16
    chip-second budget, so vs_baseline = 16 / (p50_seconds * n_dev).
    """
    from opentsdb_tpu.core import TSDB
    from opentsdb_tpu.models import TSQuery, parse_m_subquery
    from opentsdb_tpu.utils.config import Config
    import numpy as np

    total = int(1_000_000_000 * scale)
    s = 1024
    per = max(total // s, 1024)
    tsdb = TSDB(Config({"tsd.core.auto_create_metrics": True}))
    t0 = time.perf_counter()
    rng = np.random.default_rng(0)
    n_series = 0
    for i in range(s):
        # host-side ingest can dominate a slow box: a deadline cut here
        # still yields an honest row — the label carries the real
        # in-store point count and vs_baseline scales with it
        if i and not _fits((time.perf_counter() - t0) / i):
            _note("deadline: ingest stopped at series %d/%d" % (i, s))
            break
        ts = (START + np.arange(per, dtype=np.int64) * STEP_MS
              + int(rng.integers(0, 4000)))
        sk = tsdb._series_key("lat.m", {"host": "h%04d" % i,
                                        "dc": "d%d" % (i % 16)},
                              create=True)
        tsdb.store.add_batch(sk, ts, rng.normal(100, 25, per), False)
        n_series += 1
    in_store = n_series * per
    _note("config 7: ingested %d pts in %.1fs"
          % (in_store, time.perf_counter() - t0))

    end_s = (START + per * STEP_MS) // 1000 + 10

    def run_query():
        q = TSQuery(start=str(START // 1000), end=str(end_s),
                    queries=[parse_m_subquery("sum:1m-avg:lat.m{dc=*}")])
        q.validate()
        return tsdb.new_query_runner().run(q)

    # Production daemons run the maintenance thread, whose device-cache
    # refresh pins the metric's columns in HBM after the first (streamed)
    # query — the steady state a dashboard sees.  Metrics beyond the
    # cache's build budget keep streaming every pass (the honest
    # beyond-memory number).
    tsdb.start_maintenance()
    try:
        t1 = time.perf_counter()
        run_query()  # compile + queue the cache build
        first_query_s = time.perf_counter() - t1
        deadline = time.time() + min(60.0, max(_deadline_left() / 2, 5.0))
        while (tsdb.device_cache is not None and len(tsdb.device_cache) == 0
               and in_store <= tsdb.device_cache.build_max_points
               and time.time() < deadline):
            time.sleep(0.5)
        cached = (tsdb.device_cache is not None
                  and len(tsdb.device_cache) > 0)
        if cached and _fits(first_query_s):
            run_query()     # compile the cached-batch shape untimed
        lats = []
        last = first_query_s
        for _ in range(MIN_PASSES):
            if lats and not _fits(last):
                _note("deadline: stopping after %d latency passes"
                      % len(lats))
                break
            t0 = time.perf_counter()
            run_query()
            last = time.perf_counter() - t0
            lats.append(last)
    finally:
        if tsdb.maintenance is not None:
            tsdb.maintenance.stop(final_flush=False)
            tsdb.maintenance = None
    p50 = _median(lats)
    _note("config 7: latencies %s (device cache %s)"
          % ([round(x, 3) for x in lats],
             "warm" if cached else "not used"))
    # north star: 1B pts < 2s on 8 chips = a 16 chip-second budget PER
    # BILLION points; scale the budget to what is actually in the store
    # so smoke runs and deadline-partial ingests stay honest
    budget_s = 16.0 * in_store / 1e9
    print(json.dumps({
        "metric": "config 7: p50 /api/query latency, %d pts in-store, "
                  "%s; single-chip-equivalent budget %.2fs"
                  % (in_store,
                     "served from the device-resident series cache "
                     "(production steady state: maintenance thread "
                     "pinned the metric in HBM after the first streamed "
                     "pass)" if cached else
                     "streamed via chunked store reads (beyond the "
                     "device cache budget; includes host packing + "
                     "host->device transfer)", budget_s),
        "value": round(p50, 3),
        "unit": "seconds p50 latency",
        "vs_baseline": round(budget_s / max(p50, 1e-9) / n_dev, 4),
    }), flush=True)


CONFIGS = {1: config1, 2: config2, 3: config3, 4: config4, 5: config5,
           6: config6, 7: config7}


def _arm_watchdog(grace_after_deadline_s: float) -> None:
    """Last resort behind the cooperative checks: if a single dispatch
    hangs past the deadline + grace (a truly wedged tunnel — the
    cooperative checks can't interrupt an in-flight drain), emit an
    error row for the current config and exit 0 so the session's
    artifact stays parseable.  The outer subprocess SIGKILL sits behind
    BOTH layers and should never fire on a merely-slow config."""
    if _DEADLINE is None:
        return

    def fire():
        while True:
            left = _deadline_left() + grace_after_deadline_s
            if left <= 0:
                break
            time.sleep(min(left, 10.0))
        print(json.dumps({
            "metric": "config %d" % _CURRENT_CONFIG,
            "error": "in-process watchdog: dispatch unresponsive %.0fs "
                     "past the cooperative deadline (tunnel wedged?)"
                     % grace_after_deadline_s,
        }), flush=True)
        sys.stdout.flush()
        os._exit(0)
    threading.Thread(target=fire, daemon=True).start()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, default=0,
                    help="run one config (default: all)")
    ap.add_argument("--scale", type=float, default=1.0,
                    help="shrink factor for smoke runs (e.g. 0.01)")
    ap.add_argument("--deadline", type=float, default=0.0,
                    help="cooperative per-config budget in seconds: each "
                         "config finalizes a partial-but-honest row "
                         "instead of overrunning (0 = unlimited)")
    ap.add_argument("--platform", default="",
                    help="force a jax platform (e.g. cpu) — the env var "
                         "alone is overridden by the ambient sitecustomize, "
                         "so CPU smoke runs need the in-process update")
    args = ap.parse_args()

    global _RTT, _DEADLINE, _CURRENT_CONFIG
    if args.deadline > 0:
        # covers backend init too: jax.devices() on a wedged tunnel
        # hangs forever and would otherwise die JSON-less to the outer
        # timeout
        _DEADLINE = time.monotonic() + args.deadline
        _arm_watchdog(300.0)

    import opentsdb_tpu.ops  # noqa: F401  (jax x64)
    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    # Backend init fails fast on a dead tunnel (see guard_backend_init):
    # only under a session deadline — unattended runs must not burn a
    # recovery window inside a hung dial.
    try:
        if args.deadline > 0:
            from bench import guard_backend_init
            guard_backend_init()
        n_dev = len(jax.devices())
    except Exception as e:
        print(json.dumps({
            "metric": "backend_init",
            "error": "backend init failed: %s" % e}), flush=True)
        sys.exit(1)
    _note("devices: %d (%s)" % (n_dev, jax.devices()[0].platform))
    _RTT = measure_rtt()
    _note("tunnel rtt: %.4fs" % _RTT)

    targets = [args.config] if args.config else sorted(CONFIGS)
    for c in targets:
        _note("running config %d" % c)
        _CURRENT_CONFIG = c
        if args.deadline > 0:
            _DEADLINE = time.monotonic() + args.deadline
        CONFIGS[c](args.scale, n_dev)


if __name__ == "__main__":
    main()
