"""A/B harness for the downsample hot path (VERDICT r2 next-step #2).

Measures the production `/api/query` pipeline (same shape as bench.py)
under each combination of:
  * scan mode: flat one-pass cumsum  vs  blocked two-level scan
  * timestamp compaction: int64 ms  vs  int32 ms-offsets
  * value accumulation: float64 (default, Java-double parity)  vs  the
    float32 fast mode (set_value_precision('single'))

using the honest drain-based timing from bench.py (unique operands per
dispatch, host-fetch sync, RTT-subtracted per-dispatch medians — see
bench.py's module docstring for why `block_until_ready` cannot be used).

The toggle setters clear every dependent jit cache themselves (the
toggles are read at trace time, so a stale cache would silently measure
the previous config).

The fitted calibration table is the PRIOR (ROADMAP item 1 leftover):
every race row carries the layered costmodel's predicted per-dispatch
seconds for its mode combo (`predicted_s`, priced through
DEFAULT_COSTS -> BENCH_CALIBRATION.json -> any live layer; the
`calibration` field names the winning layer), so a measurement session
can see at a glance where the fitted constants disagree with reality —
and `--prune N` races only the N best-predicted candidates per kernel
axis (each dropped candidate is announced, never silently skipped),
which is how a local CPU run prices candidates with live-fitted
constants instead of racing everything.

Prints one JSON line per config on stdout (stderr carries progress), e.g.
  {"config": "blocked+int32", "s_per_dispatch": 0.61, "dp_per_sec": 1.1e8}
"""

from __future__ import annotations

import json
import sys

import bench
from bench import (_OriginSequence, build_spec, dispatch, drain, make_batch,
                   measure_drained, measure_rtt, _median, S, N, GROUPS)


def main() -> None:
    from opentsdb_tpu.ops import costmodel as cm
    from opentsdb_tpu.ops import downsample as ds
    from opentsdb_tpu.ops import group_agg as ga
    from opentsdb_tpu.ops.hostlane import execution_platform
    from opentsdb_tpu.ops.pipeline import PipelineSpec, DownsampleStep

    prune = None
    if "--prune" in sys.argv:
        prune = max(int(sys.argv[sys.argv.index("--prune") + 1]), 1)

    # This harness races EXPLICIT kernel modes: the platform guard (which
    # demotes dense search forms on CPU execution) would silently time
    # the scan kernel under a dense row's label on a CPU dev box.  A
    # no-op on the chip, where the race is meant to run.
    ds.set_platform_mode_guard(False)

    # Fail fast if the tunnel died since the previous stage (a hung
    # dial burns the whole recovery window otherwise).
    bench.guard_backend_init()

    batch = make_batch()                       # int32 ts_base layout
    batch64 = make_batch(precompacted=False)   # absolute int64 layout
    bench._note("batches resident")
    spec, wargs, g_pad = build_spec()
    _spec64, wargs64, _g = build_spec(precompacted=False)
    spec_min = PipelineSpec(
        aggregator="sum",
        downsample=DownsampleStep("min", spec.downsample.window_spec,
                                  "none", 0.0))
    origins = _OriginSequence()
    # Sync-cost probe against a REAL warmed pipeline output: the drain is
    # one tunnel round-trip per leaf, so a one-leaf probe would bill
    # (leaves-1) RTTs as chip time on every non-escalated sample, and a
    # hand-built template would go stale if the pipeline's output pytree
    # ever changes shape (see bench.measure_rtt docstring).  Every race
    # row dispatches this same structure.
    warm = dispatch(spec, g_pad, batch, wargs, origins.next())
    drain(warm)
    rtt = measure_rtt(template=warm)
    bench._note("rtt %.4fs (real-output drain)" % rtt)

    def restore_defaults() -> None:
        ga.set_group_reduce_mode("segment")
        ds.set_extreme_mode("scan")
        ds.set_search_mode("scan")
        ds.set_scan_mode("flat")
        ds.set_ts_compaction(True)
        ds.set_value_precision("double")

    # the fitted-table prior: predicted per-dispatch seconds for one
    # explicit mode combo at the bench shape, priced through the
    # layered cost table (file/live calibration when present)
    platform = execution_platform()
    w_count = spec.downsample.window_spec.count
    edges = w_count + 1

    def predict_combo(scan=None, search=None, extreme=None,
                      group=None) -> float:
        parts = [cm.predict_search(search or "scan", S, N, edges,
                                   platform)]
        if extreme is not None:
            parts.append(cm.predict_extreme(extreme, S, N, edges,
                                            platform))
        else:
            parts.append(cm.predict_scan(scan or "flat", S, N, edges,
                                         platform))
        parts.append(cm.predict_group(group or "segment", S, w_count,
                                      GROUPS, platform))
        return sum(parts)

    def keep_best(axis: str, cands: list, key) -> list:
        """--prune: race only the prune best-predicted candidates of
        one kernel axis; announce every drop (no silent caps)."""
        if prune is None or len(cands) <= prune:
            return cands
        ordered = sorted(cands, key=lambda c: predict_combo(**key(c)))
        for dropped in ordered[prune:]:
            print(json.dumps({
                "config": "%s (pruned)" % dropped[0] if
                isinstance(dropped, tuple) else "%s (pruned)" % dropped,
                "axis": axis, "pruned_by_prior": True,
                "predicted_s": round(predict_combo(**key(dropped)), 4),
                "calibration": cm.calibration_source(platform),
            }), flush=True)
            bench._note("%s: pruned by the fitted prior" % (dropped,))
        return ordered[:prune]

    def race(name: str, setup, pipeline_spec, use_batch=None,
             use_wargs=None, modes: dict | None = None) -> None:
        """One isolated race row: a candidate that fails to compile or
        dispatch prints an error row and the race continues — an
        unattended session must never lose the remaining rows to one
        bad candidate (the setters below always run from the restored
        default state)."""
        restore_defaults()
        b = batch if use_batch is None else use_batch
        w = wargs if use_wargs is None else use_wargs
        prior = {}
        if modes is not None:
            prior = {"predicted_s": round(predict_combo(**modes), 4),
                     "calibration": cm.calibration_source(platform)}
        try:
            setup()
            drain(dispatch(pipeline_spec, g_pad, b, w,
                           origins.next()))           # compile + warm
            samples, _, _ = measure_drained(pipeline_spec, g_pad, b,
                                            w, origins, rtt)
            per = _median(samples)
        except Exception as e:   # noqa: BLE001 — provenance over purity
            print(json.dumps({"config": name,
                              "error": "%s: %s" % (type(e).__name__, e),
                              **prior}),
                  flush=True)
            bench._note("%s FAILED: %s" % (name, e))
            return
        print(json.dumps({
            "config": name,
            "s_per_dispatch": round(per, 4),
            "dp_per_sec": round(S * N / per, 1),
            **prior,
        }), flush=True)
        bench._note("%s: %.4fs/dispatch" % (name, per))

    # Batch-layout evidence rows on the ABSOLUTE-int64 batch (the
    # host-build layout): raw int64 end-to-end vs per-dispatch int32
    # compaction (the r3 production path).  These quantify what the
    # pre-compacted ts_base layout saves; the default rows below all
    # ride the pre-compacted int32 batch (the cache-hit layout bench.py
    # measures) where per-dispatch compaction is already gone.
    for name, compact in [("flat+int64raw", False),
                          ("flat+int64+dispatchcompact", True)]:
        def setup(c=compact):
            ds.set_ts_compaction(c)
        race(name, setup, spec, use_batch=batch64, use_wargs=wargs64,
             modes={"scan": "flat"})

    # scan mode x accumulation precision on the pre-compacted batch.
    # "subblock" is the r4 chip-attribution lever: no full-length f64
    # scan at all — sub-block f64 reduces + tiny cumsum + 32-wide
    # remainder dots.  The f32 row is evidence-only (breaks the
    # Java-double parity contract).
    scan_rows = keep_best(
        "scan",
        [("flat+int32", "flat", "double"),
         ("blocked+int32", "blocked", "double"),
         ("subblock+int32", "subblock", "double"),
         ("subblock2+int32", "subblock2", "double"),
         ("blocked+int32+f32", "blocked", "single")],
        key=lambda c: {"scan": c[1]})
    for name, mode, precision in scan_rows:
        def setup(m=mode, p=precision):
            ds.set_scan_mode(m)
            ds.set_value_precision(p)
        race(name, setup, spec, modes={"scan": mode})

    # edge-search strategy at the flat+int32 config: binary search
    # (log2(N) gather rounds) vs compare_all (fused compare+reduce) vs
    # hier (sub-block firsts + 32-wide remainder — 1/32 the compares).
    for smode in keep_best("search", ["scan", "compare_all", "hier"],
                           key=lambda m: {"search": m}):
        race("flat+int32+search_" + smode,
             lambda m=smode: ds.set_search_mode(m), spec,
             modes={"search": smode})

    # min/max strategy: full-length reset-scan vs segment scatter vs the
    # r4 sub-block decomposition.
    for emode in keep_best("extreme", ["scan", "segment", "subblock"],
                           key=lambda m: {"extreme": m}):
        race("min+extreme_" + emode,
             lambda m=emode: ds.set_extreme_mode(m), spec_min,
             modes={"extreme": emode})

    # group-reduce strategy: segment scatter vs one-hot matmul (MXU) vs
    # sorted contiguous-run reset-scans (r4) vs the r5 blocked
    # level-masked fold with int32 counts ("sorted2").
    for gmode in keep_best("group",
                           ["segment", "matmul", "sorted", "sorted2"],
                           key=lambda m: {"group": m}):
        race("flat+int32+group_" + gmode,
             lambda m=gmode: ga.set_group_reduce_mode(m), spec,
             modes={"group": gmode})

    # r4 compositions: the attribution-driven levers together and in
    # pairs — fusion can interact, and pick_winners only ever feeds
    # forward MEASURED rows, so the pairs are the fallbacks if the full
    # combo regresses on one member.
    def combo(scan=None, search=None, group=None):
        def setup():
            if scan:
                ds.set_scan_mode(scan)
            if search:
                ds.set_search_mode(search)
            if group:
                ga.set_group_reduce_mode(group)
        return setup

    race("subblock+int32+hier", combo("subblock", "hier"), spec,
         modes={"scan": "subblock", "search": "hier"})
    race("subblock+int32+sorted", combo("subblock", group="sorted"), spec,
         modes={"scan": "subblock", "group": "sorted"})
    race("flat+int32+hier+sorted", combo(search="hier", group="sorted"),
         spec, modes={"search": "hier", "group": "sorted"})
    race("subblock+int32+hier+sorted",
         combo("subblock", "hier", "sorted"), spec,
         modes={"scan": "subblock", "search": "hier", "group": "sorted"})
    race("subblock2+int32+hier+sorted",
         combo("subblock2", "hier", "sorted"), spec,
         modes={"scan": "subblock2", "search": "hier",
                "group": "sorted"})
    race("subblock+int32+hier+sorted2",
         combo("subblock", "hier", "sorted2"), spec,
         modes={"scan": "subblock", "search": "hier",
                "group": "sorted2"})
    race("subblock2+int32+hier+sorted2",
         combo("subblock2", "hier", "sorted2"), spec,
         modes={"scan": "subblock2", "search": "hier",
                "group": "sorted2"})

    # the shape-driven cost model's own pick (ops/costmodel.py "auto"):
    # racing it against the explicit rows shows on-chip whether the
    # chooser lands on the winner without being crowned
    race("auto+int32", combo("auto", "auto", "auto"), spec)

    restore_defaults()


if __name__ == "__main__":
    main()
