"""A/B harness for the downsample hot path (VERDICT r2 next-step #2).

Measures the production `/api/query` pipeline (same shape as bench.py)
under each combination of:
  * scan mode: flat one-pass cumsum  vs  blocked two-level scan
  * timestamp compaction: int64 ms  vs  int32 ms-offsets
  * value accumulation: float64 (default, Java-double parity)  vs  the
    float32 fast mode (set_value_precision('single'))

using the honest drain-based timing from bench.py (unique operands per
dispatch, host-fetch sync, RTT-subtracted per-dispatch medians — see
bench.py's module docstring for why `block_until_ready` cannot be used).

The toggle setters clear every dependent jit cache themselves (the
toggles are read at trace time, so a stale cache would silently measure
the previous config).

Prints one JSON line per config on stdout (stderr carries progress), e.g.
  {"config": "blocked+int32", "s_per_dispatch": 0.61, "dp_per_sec": 1.1e8}
"""

from __future__ import annotations

import json
import sys

import bench
from bench import (_OriginSequence, build_spec, dispatch, drain, make_batch,
                   measure_drained, measure_rtt, _median, S, N)


def main() -> None:
    from opentsdb_tpu.ops import downsample as ds
    from opentsdb_tpu.ops import group_agg as ga
    from opentsdb_tpu.ops.pipeline import PipelineSpec, DownsampleStep

    # This harness races EXPLICIT kernel modes: the platform guard (which
    # demotes dense search forms on CPU execution) would silently time
    # the scan kernel under a dense row's label on a CPU dev box.  A
    # no-op on the chip, where the race is meant to run.
    ds.set_platform_mode_guard(False)

    # Fail fast if the tunnel died since the previous stage (a hung
    # dial burns the whole recovery window otherwise).
    bench.guard_backend_init()

    batch = make_batch()                       # int32 ts_base layout
    batch64 = make_batch(precompacted=False)   # absolute int64 layout
    bench._note("batches resident")
    spec, wargs, g_pad = build_spec()
    _spec64, wargs64, _g = build_spec(precompacted=False)
    spec_min = PipelineSpec(
        aggregator="sum",
        downsample=DownsampleStep("min", spec.downsample.window_spec,
                                  "none", 0.0))
    origins = _OriginSequence()
    # Sync-cost probe against a REAL warmed pipeline output: the drain is
    # one tunnel round-trip per leaf, so a one-leaf probe would bill
    # (leaves-1) RTTs as chip time on every non-escalated sample, and a
    # hand-built template would go stale if the pipeline's output pytree
    # ever changes shape (see bench.measure_rtt docstring).  Every race
    # row dispatches this same structure.
    warm = dispatch(spec, g_pad, batch, wargs, origins.next())
    drain(warm)
    rtt = measure_rtt(template=warm)
    bench._note("rtt %.4fs (real-output drain)" % rtt)

    def restore_defaults() -> None:
        ga.set_group_reduce_mode("segment")
        ds.set_extreme_mode("scan")
        ds.set_search_mode("scan")
        ds.set_scan_mode("flat")
        ds.set_ts_compaction(True)
        ds.set_value_precision("double")

    def race(name: str, setup, pipeline_spec, use_batch=None,
             use_wargs=None) -> None:
        """One isolated race row: a candidate that fails to compile or
        dispatch prints an error row and the race continues — an
        unattended session must never lose the remaining rows to one
        bad candidate (the setters below always run from the restored
        default state)."""
        restore_defaults()
        b = batch if use_batch is None else use_batch
        w = wargs if use_wargs is None else use_wargs
        try:
            setup()
            drain(dispatch(pipeline_spec, g_pad, b, w,
                           origins.next()))           # compile + warm
            samples, _, _ = measure_drained(pipeline_spec, g_pad, b,
                                            w, origins, rtt)
            per = _median(samples)
        except Exception as e:   # noqa: BLE001 — provenance over purity
            print(json.dumps({"config": name,
                              "error": "%s: %s" % (type(e).__name__, e)}),
                  flush=True)
            bench._note("%s FAILED: %s" % (name, e))
            return
        print(json.dumps({
            "config": name,
            "s_per_dispatch": round(per, 4),
            "dp_per_sec": round(S * N / per, 1),
        }), flush=True)
        bench._note("%s: %.4fs/dispatch" % (name, per))

    # Batch-layout evidence rows on the ABSOLUTE-int64 batch (the
    # host-build layout): raw int64 end-to-end vs per-dispatch int32
    # compaction (the r3 production path).  These quantify what the
    # pre-compacted ts_base layout saves; the default rows below all
    # ride the pre-compacted int32 batch (the cache-hit layout bench.py
    # measures) where per-dispatch compaction is already gone.
    for name, compact in [("flat+int64raw", False),
                          ("flat+int64+dispatchcompact", True)]:
        def setup(c=compact):
            ds.set_ts_compaction(c)
        race(name, setup, spec, use_batch=batch64, use_wargs=wargs64)

    # scan mode x accumulation precision on the pre-compacted batch.
    # "subblock" is the r4 chip-attribution lever: no full-length f64
    # scan at all — sub-block f64 reduces + tiny cumsum + 32-wide
    # remainder dots.  The f32 row is evidence-only (breaks the
    # Java-double parity contract).
    for name, mode, precision in [
            ("flat+int32", "flat", "double"),
            ("blocked+int32", "blocked", "double"),
            ("subblock+int32", "subblock", "double"),
            ("subblock2+int32", "subblock2", "double"),
            ("blocked+int32+f32", "blocked", "single")]:
        def setup(m=mode, p=precision):
            ds.set_scan_mode(m)
            ds.set_value_precision(p)
        race(name, setup, spec)

    # edge-search strategy at the flat+int32 config: binary search
    # (log2(N) gather rounds) vs compare_all (fused compare+reduce) vs
    # hier (sub-block firsts + 32-wide remainder — 1/32 the compares).
    for smode in ("scan", "compare_all", "hier"):
        race("flat+int32+search_" + smode,
             lambda m=smode: ds.set_search_mode(m), spec)

    # min/max strategy: full-length reset-scan vs segment scatter vs the
    # r4 sub-block decomposition.
    for emode in ("scan", "segment", "subblock"):
        race("min+extreme_" + emode,
             lambda m=emode: ds.set_extreme_mode(m), spec_min)

    # group-reduce strategy: segment scatter vs one-hot matmul (MXU) vs
    # sorted contiguous-run reset-scans (r4) vs the r5 blocked
    # level-masked fold with int32 counts ("sorted2").
    for gmode in ("segment", "matmul", "sorted", "sorted2"):
        race("flat+int32+group_" + gmode,
             lambda m=gmode: ga.set_group_reduce_mode(m), spec)

    # r4 compositions: the attribution-driven levers together and in
    # pairs — fusion can interact, and pick_winners only ever feeds
    # forward MEASURED rows, so the pairs are the fallbacks if the full
    # combo regresses on one member.
    def combo(scan=None, search=None, group=None):
        def setup():
            if scan:
                ds.set_scan_mode(scan)
            if search:
                ds.set_search_mode(search)
            if group:
                ga.set_group_reduce_mode(group)
        return setup

    race("subblock+int32+hier", combo("subblock", "hier"), spec)
    race("subblock+int32+sorted", combo("subblock", group="sorted"), spec)
    race("flat+int32+hier+sorted", combo(search="hier", group="sorted"),
         spec)
    race("subblock+int32+hier+sorted",
         combo("subblock", "hier", "sorted"), spec)
    race("subblock2+int32+hier+sorted",
         combo("subblock2", "hier", "sorted"), spec)
    race("subblock+int32+hier+sorted2",
         combo("subblock", "hier", "sorted2"), spec)
    race("subblock2+int32+hier+sorted2",
         combo("subblock2", "hier", "sorted2"), spec)

    # the shape-driven cost model's own pick (ops/costmodel.py "auto"):
    # racing it against the explicit rows shows on-chip whether the
    # chooser lands on the winner without being crowned
    race("auto+int32", combo("auto", "auto", "auto"), spec)

    restore_defaults()


if __name__ == "__main__":
    main()
