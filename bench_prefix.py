"""A/B harness for the downsample hot path (VERDICT r2 next-step #2).

Measures the production `/api/query` pipeline (same shape as bench.py)
under each combination of:
  * scan mode: flat one-pass cumsum  vs  blocked two-level scan
  * timestamp compaction: int64 ms  vs  int32 ms-offsets
  * value accumulation: float64 (default, Java-double parity)  vs  the
    float32 fast mode (set_value_precision('single'))

using the honest drain-based timing from bench.py (unique operands per
dispatch, host-fetch sync, RTT-subtracted per-dispatch medians — see
bench.py's module docstring for why `block_until_ready` cannot be used).

The toggle setters clear every dependent jit cache themselves (the
toggles are read at trace time, so a stale cache would silently measure
the previous config).

Prints one JSON line per config on stdout (stderr carries progress), e.g.
  {"config": "blocked+int32", "s_per_dispatch": 0.61, "dp_per_sec": 1.1e8}
"""

from __future__ import annotations

import json
import sys

import bench
from bench import (_OriginSequence, build_spec, dispatch, drain, make_batch,
                   measure_drained, measure_rtt, _median, S, N)


def main() -> None:
    from opentsdb_tpu.ops import downsample as ds

    batch = make_batch()
    bench._note("batch resident")
    spec, wargs, g_pad = build_spec()
    origins = _OriginSequence()
    rtt = measure_rtt()
    bench._note("rtt %.4fs" % rtt)

    configs = [
        ("flat+int64", "flat", False, "double"),
        ("flat+int32", "flat", True, "double"),
        ("blocked+int64", "blocked", False, "double"),
        ("blocked+int32", "blocked", True, "double"),
        # r4 chip-attribution lever: no full-length f64 scan at all —
        # sub-block f64 reduces + tiny cumsum + 32-wide remainder dots
        ("subblock+int32", "subblock", True, "double"),
        # fast mode: float32 accumulation (native ALUs; NOT the default —
        # breaks the 1e-9 Java-double parity contract, documented)
        ("blocked+int32+f32", "blocked", True, "single"),
    ]
    for name, mode, compact, precision in configs:
        ds.set_scan_mode(mode)        # setters clear the jit caches
        ds.set_ts_compaction(compact)
        ds.set_value_precision(precision)
        drain(dispatch(spec, g_pad, batch, wargs, origins.next()))  # compile
        samples, _, _ = measure_drained(spec, g_pad, batch, wargs, origins,
                                        rtt)
        per = _median(samples)
        print(json.dumps({
            "config": name,
            "s_per_dispatch": round(per, 4),
            "dp_per_sec": round(S * N / per, 1),
        }), flush=True)
        bench._note("%s: %.4fs/dispatch" % (name, per))
    # edge-search strategy A/B at the winning scan config: binary search
    # (log2(N) gather rounds) vs compare_all (fused compare+reduce) vs
    # hier (sub-block firsts + 32-wide remainder — 1/32 the compares).
    ds.set_scan_mode("flat")
    ds.set_ts_compaction(True)
    ds.set_value_precision("double")
    for smode in ("scan", "compare_all", "hier"):
        ds.set_search_mode(smode)
        drain(dispatch(spec, g_pad, batch, wargs, origins.next()))
        samples, _, _ = measure_drained(spec, g_pad, batch, wargs, origins,
                                        rtt)
        per = _median(samples)
        print(json.dumps({
            "config": "flat+int32+search_" + smode,
            "s_per_dispatch": round(per, 4),
            "dp_per_sec": round(S * N / per, 1),
        }), flush=True)
        bench._note("search_%s: %.4fs/dispatch" % (smode, per))
    ds.set_search_mode("scan")

    # min/max strategy A/B (NOTES r3: segments won on CPU, the chip
    # decides the default): same shape, "min" downsample instead of avg.
    from opentsdb_tpu.ops.pipeline import PipelineSpec, DownsampleStep
    ds.set_scan_mode("flat")
    ds.set_ts_compaction(True)
    ds.set_value_precision("double")
    spec_min = PipelineSpec(
        aggregator="sum",
        downsample=DownsampleStep("min", spec.downsample.window_spec,
                                  "none", 0.0))
    for mode in ("scan", "segment", "subblock"):
        ds.set_extreme_mode(mode)
        drain(dispatch(spec_min, g_pad, batch, wargs, origins.next()))
        samples, _, _ = measure_drained(spec_min, g_pad, batch, wargs,
                                        origins, rtt)
        per = _median(samples)
        print(json.dumps({
            "config": "min+extreme_" + mode,
            "s_per_dispatch": round(per, 4),
            "dp_per_sec": round(S * N / per, 1),
        }), flush=True)
        bench._note("min+extreme_%s: %.4fs/dispatch" % (mode, per))

    # group-reduce strategy A/B (r4): segment scatter vs one-hot matmul
    # for the cross-series moment combine — scatters serialize on TPU,
    # the matmul streams on the MXU (same f64 contract, reassociated).
    from opentsdb_tpu.ops import group_agg as ga
    ds.set_extreme_mode("scan")
    ds.set_scan_mode("flat")
    ds.set_ts_compaction(True)
    ds.set_value_precision("double")
    for gmode in ("segment", "matmul", "sorted"):
        ga.set_group_reduce_mode(gmode)
        drain(dispatch(spec, g_pad, batch, wargs, origins.next()))
        samples, _, _ = measure_drained(spec, g_pad, batch, wargs,
                                        origins, rtt)
        per = _median(samples)
        print(json.dumps({
            "config": "flat+int32+group_" + gmode,
            "s_per_dispatch": round(per, 4),
            "dp_per_sec": round(S * N / per, 1),
        }), flush=True)
        bench._note("group_%s: %.4fs/dispatch" % (gmode, per))

    # the r4 composition: every attribution-driven lever at once —
    # validates the per-axis winners actually compose (fusion could
    # interact) before run_chip_measurements feeds them forward
    ds.set_scan_mode("subblock")
    ds.set_search_mode("hier")
    ga.set_group_reduce_mode("sorted")
    drain(dispatch(spec, g_pad, batch, wargs, origins.next()))
    samples, _, _ = measure_drained(spec, g_pad, batch, wargs, origins, rtt)
    per = _median(samples)
    print(json.dumps({
        "config": "subblock+int32+hier+sorted",
        "s_per_dispatch": round(per, 4),
        "dp_per_sec": round(S * N / per, 1),
    }), flush=True)
    bench._note("combo subblock+hier+sorted: %.4fs/dispatch" % per)

    # restore defaults
    ga.set_group_reduce_mode("segment")
    ds.set_extreme_mode("scan")
    ds.set_search_mode("scan")
    ds.set_scan_mode("flat")
    ds.set_ts_compaction(True)
    ds.set_value_precision("double")


if __name__ == "__main__":
    main()
