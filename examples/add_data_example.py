"""Library-embedding sample: write datapoints through the TSDB facade.

Counterpart of /root/reference/src/examples/AddDataExample.java — construct
a TSDB from config, validate/write points for one metric with tags, flush,
and shut down cleanly.

Run:  python examples/add_data_example.py
"""

import random
import time

from opentsdb_tpu.core import TSDB
from opentsdb_tpu.utils.config import Config


def main() -> None:
    # Auto-create metrics so the example works on an empty store; a
    # production embedder would pre-assign UIDs via `tsdb uid assign`.
    tsdb = TSDB(Config({
        "tsd.core.auto_create_metrics": True,
        # Uncomment for durability (WAL + snapshots under this directory):
        # "tsd.storage.directory": "/tmp/tsdb-example",
    }))
    # Background compaction/WAL upkeep, as the daemon runs it:
    tsdb.start_maintenance()

    metric = "my.tsdb.test.metric"
    tags = {"script": "example", "host": "web01"}

    now = int(time.time())
    for i in range(100):
        value = random.randint(0, 200)
        tsdb.add_point(metric, now - (100 - i) * 30, value, tags)
    print("wrote 100 points to", metric)

    stats = tsdb.collect_stats()
    print("datapoints added:", stats["tsd.datapoints.added"])
    print("series:", stats["tsd.storage.series"])

    tsdb.shutdown()


if __name__ == "__main__":
    main()
