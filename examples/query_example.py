"""Library-embedding sample: run a query through the planner.

Counterpart of /root/reference/src/examples/QueryExample.java — build a
TSQuery (the /api/query JSON model), execute it against the TSDB, and walk
the aggregated results.

Run:  python examples/query_example.py
"""

import random
import time

from opentsdb_tpu.core import TSDB
from opentsdb_tpu.models import TSQuery, TSSubQuery, parse_m_subquery
from opentsdb_tpu.utils.config import Config


def main() -> None:
    tsdb = TSDB(Config({"tsd.core.auto_create_metrics": True}))

    # Seed some data (see add_data_example.py).
    metric = "my.tsdb.test.metric"
    now = int(time.time())
    for host in ("web01", "web02"):
        for i in range(120):
            tsdb.add_point(metric, now - 3600 + i * 30,
                           random.randint(0, 200), {"host": host})

    # Query form 1: the m-expression grammar used by the URI endpoint.
    query = TSQuery(
        start=str(now - 3600), end=str(now),
        queries=[parse_m_subquery("sum:5m-avg:%s{host=*}" % metric)])
    query.validate()

    # Query form 2 (equivalent): explicit TSSubQuery fields, the JSON body
    # shape of POST /api/query.
    from opentsdb_tpu.query.filters import build_filter
    explicit = TSSubQuery(aggregator="sum", metric=metric,
                          downsample="5m-avg",
                          filters=[build_filter("host", "wildcard", "*",
                                                group_by=True)])
    assert explicit.to_json()["metric"] == metric

    for result in tsdb.new_query_runner().run(query):
        print(result.metric, result.tags, "aggregated:",
              result.aggregate_tags)
        for ts_ms, value in result.dps[:5]:
            print("  %d -> %s" % (ts_ms // 1000, value))
        print("  ... %d datapoints total" % len(result.dps))

    tsdb.shutdown()


if __name__ == "__main__":
    main()
