// Native columnar storage engine for opentsdb_tpu.
//
// Plays the role the HBase storage layer + asynchbase client played for the
// reference (SURVEY.md §2.6 storage schema; compaction's space rationale,
// /root/reference/src/core/CompactionQueue.java:40-56: amortize per-cell
// overhead by packing cells — here, whole chunks compress together).
//
// Design:
//   * per-series storage = sealed compressed chunks + an uncompressed
//     append tail (the CompactionQueue analog: the tail seals into a
//     compressed chunk once it reaches CHUNK_POINTS).
//   * chunk codec: delta-of-delta zig-zag varint timestamps (time-series
//     deltas are near-constant) + XOR'd IEEE754 value bits varint-packed
//     (Gorilla-style), plus an is-int bitmap so Java-long exactness
//     survives: integer points carry their int64 bits instead of a double.
//   * reads decompress + merge + sort + last-write-wins dedup, mirroring
//     MemStore.Series.normalize semantics.
//   * save/load: length-prefixed dump of keys + chunks (snapshot file).
//
// C ABI only (driven from Python via ctypes).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <unordered_map>
#include <string>
#include <vector>

#define EXPORT extern "C" __attribute__((visibility("default")))

namespace {

constexpr size_t CHUNK_POINTS = 512;

// ---------------------------------------------------------------- varint

inline void put_varint(std::vector<uint8_t>& out, uint64_t v) {
    while (v >= 0x80) {
        out.push_back(static_cast<uint8_t>(v) | 0x80);
        v >>= 7;
    }
    out.push_back(static_cast<uint8_t>(v));
}

inline uint64_t get_varint(const uint8_t* data, size_t& pos) {
    uint64_t v = 0;
    int shift = 0;
    for (;;) {
        uint8_t b = data[pos++];
        v |= static_cast<uint64_t>(b & 0x7F) << shift;
        if (!(b & 0x80)) return v;
        shift += 7;
    }
}

inline uint64_t zigzag(int64_t v) {
    return (static_cast<uint64_t>(v) << 1) ^ static_cast<uint64_t>(v >> 63);
}

inline int64_t unzigzag(uint64_t v) {
    return static_cast<int64_t>(v >> 1) ^ -static_cast<int64_t>(v & 1);
}

// ---------------------------------------------------------------- point

struct Point {
    int64_t ts;
    double fval;
    int64_t ival;
    uint8_t is_int;
};

// ---------------------------------------------------------------- chunk

struct Chunk {
    std::vector<uint8_t> data;  // compressed
    size_t n = 0;
    int64_t first_ts = 0;
    int64_t last_ts = 0;

    static Chunk compress(const Point* pts, size_t n) {
        Chunk c;
        c.n = n;
        if (n == 0) return c;
        c.first_ts = pts[0].ts;
        c.last_ts = pts[n - 1].ts;
        std::vector<uint8_t>& out = c.data;
        out.reserve(n * 4);
        // timestamps: first raw, then delta-of-delta zig-zag varints
        put_varint(out, zigzag(pts[0].ts));
        int64_t prev_ts = pts[0].ts;
        int64_t prev_delta = 0;
        for (size_t i = 1; i < n; i++) {
            int64_t delta = pts[i].ts - prev_ts;
            put_varint(out, zigzag(delta - prev_delta));
            prev_delta = delta;
            prev_ts = pts[i].ts;
        }
        // is-int bitmap
        for (size_t i = 0; i < n; i += 8) {
            uint8_t b = 0;
            for (size_t j = 0; j < 8 && i + j < n; j++)
                if (pts[i + j].is_int) b |= (1u << j);
            out.push_back(b);
        }
        // values: ints as zig-zag delta varints, floats as XOR'd bit
        // patterns (Gorilla-style, varint-packed)
        int64_t prev_int = 0;
        uint64_t prev_bits = 0;
        for (size_t i = 0; i < n; i++) {
            if (pts[i].is_int) {
                put_varint(out, zigzag(pts[i].ival - prev_int));
                prev_int = pts[i].ival;
            } else {
                uint64_t bits;
                std::memcpy(&bits, &pts[i].fval, 8);
                put_varint(out, bits ^ prev_bits);
                prev_bits = bits;
            }
        }
        return c;
    }

    void decompress(std::vector<Point>& out) const {
        if (n == 0) return;
        size_t pos = 0;
        const uint8_t* d = data.data();
        size_t base = out.size();
        out.resize(base + n);
        // timestamps
        int64_t ts = unzigzag(get_varint(d, pos));
        out[base].ts = ts;
        int64_t prev_delta = 0;
        for (size_t i = 1; i < n; i++) {
            prev_delta += unzigzag(get_varint(d, pos));
            ts += prev_delta;
            out[base + i].ts = ts;
        }
        // is-int bitmap
        size_t bitmap_pos = pos;
        pos += (n + 7) / 8;
        for (size_t i = 0; i < n; i++) {
            out[base + i].is_int =
                (d[bitmap_pos + i / 8] >> (i % 8)) & 1;
        }
        // values
        int64_t prev_int = 0;
        uint64_t prev_bits = 0;
        for (size_t i = 0; i < n; i++) {
            if (out[base + i].is_int) {
                prev_int += unzigzag(get_varint(d, pos));
                out[base + i].ival = prev_int;
                out[base + i].fval = static_cast<double>(prev_int);
            } else {
                prev_bits ^= get_varint(d, pos);
                double f;
                std::memcpy(&f, &prev_bits, 8);
                out[base + i].fval = f;
                out[base + i].ival = 0;
            }
        }
    }
};

// ---------------------------------------------------------------- series

struct Series {
    std::string key;            // opaque identity bytes from Python
    std::vector<Chunk> chunks;
    std::vector<Point> tail;    // uncompressed append buffer
    bool sorted = true;
    int64_t max_ts = INT64_MIN;
    std::mutex mu;

    size_t size() const {
        size_t total = tail.size();
        for (const auto& c : chunks) total += c.n;
        return total;
    }

    size_t bytes() const {
        size_t total = tail.capacity() * sizeof(Point);
        for (const auto& c : chunks) total += c.data.capacity();
        return total;
    }

    void append(int64_t ts, double fval, int64_t ival, uint8_t is_int) {
        std::lock_guard<std::mutex> lock(mu);
        if (ts <= max_ts) sorted = false;
        max_ts = std::max(max_ts, ts);
        tail.push_back(Point{ts, fval, ival, is_int});
        if (sorted && tail.size() >= CHUNK_POINTS) seal_locked();
    }

    void seal_locked() {
        if (tail.empty()) return;
        chunks.push_back(Chunk::compress(tail.data(), tail.size()));
        tail.clear();
        tail.shrink_to_fit();
    }

    // full materialization: decompress + sort + dedup (last wins).
    // dedup=false keeps duplicate timestamps (stable order, so the last
    // write for a timestamp stays last) — used by snapshot restore so a
    // dirty series round-trips as dirty instead of being silently healed.
    void materialize(std::vector<Point>& out, bool dedup = true) {
        out.clear();
        for (const auto& c : chunks) c.decompress(out);
        out.insert(out.end(), tail.begin(), tail.end());
        if (!sorted || chunks.size() > 1) {
            std::stable_sort(out.begin(), out.end(),
                             [](const Point& a, const Point& b) {
                                 return a.ts < b.ts;
                             });
        }
        // last-write-wins dedup
        if (dedup && !out.empty()) {
            size_t w = 0;
            for (size_t r = 1; r < out.size(); r++) {
                if (out[r].ts == out[w].ts) {
                    out[w] = out[r];
                } else {
                    out[++w] = out[r];
                }
            }
            out.resize(w + 1);
        }
    }

    // normalize: materialize then re-seal as sorted chunks
    void normalize() {
        std::lock_guard<std::mutex> lock(mu);
        if (sorted && chunks.size() <= 1) return;
        std::vector<Point> pts;
        materialize(pts);
        chunks.clear();
        for (size_t i = 0; i < pts.size(); i += CHUNK_POINTS) {
            size_t n = std::min(CHUNK_POINTS, pts.size() - i);
            chunks.push_back(Chunk::compress(pts.data() + i, n));
        }
        tail.clear();
        sorted = true;
    }
};

// ---------------------------------------------------------------- engine

struct Engine {
    std::vector<Series*> series;
    std::map<std::string, int64_t> by_key;
    std::mutex mu;

    ~Engine() {
        for (auto* s : series) delete s;
    }
};

thread_local std::vector<Point> g_scratch;

}  // namespace

EXPORT void* eng_create() { return new Engine(); }

EXPORT void eng_destroy(void* h) { delete static_cast<Engine*>(h); }

EXPORT int64_t eng_series(void* h, const uint8_t* key, int32_t key_len) {
    Engine* eng = static_cast<Engine*>(h);
    std::string k(reinterpret_cast<const char*>(key), key_len);
    std::lock_guard<std::mutex> lock(eng->mu);
    auto it = eng->by_key.find(k);
    if (it != eng->by_key.end()) return it->second;
    int64_t sid = static_cast<int64_t>(eng->series.size());
    Series* s = new Series();
    s->key = std::move(k);
    eng->series.push_back(s);
    eng->by_key.emplace(eng->series.back()->key, sid);
    return sid;
}

EXPORT int32_t eng_num_series(void* h) {
    Engine* eng = static_cast<Engine*>(h);
    std::lock_guard<std::mutex> lock(eng->mu);
    return static_cast<int32_t>(eng->series.size());
}

EXPORT int32_t eng_series_key(void* h, int64_t sid, uint8_t* out,
                              int32_t max_len) {
    Engine* eng = static_cast<Engine*>(h);
    Series* s = eng->series[sid];
    int32_t n = std::min<int32_t>(max_len,
                                  static_cast<int32_t>(s->key.size()));
    std::memcpy(out, s->key.data(), n);
    return static_cast<int32_t>(s->key.size());
}

EXPORT void eng_append(void* h, int64_t sid, int64_t ts, double fval,
                       int64_t ival, int32_t is_int) {
    Engine* eng = static_cast<Engine*>(h);
    eng->series[sid]->append(ts, fval, ival,
                             static_cast<uint8_t>(is_int));
}

EXPORT void eng_append_batch(void* h, int64_t sid, const int64_t* ts,
                             const double* fval, const int64_t* ival,
                             const uint8_t* is_int, int64_t n) {
    Engine* eng = static_cast<Engine*>(h);
    Series* s = eng->series[sid];
    std::lock_guard<std::mutex> lock(s->mu);
    for (int64_t i = 0; i < n; i++) {
        int64_t t = ts[i];
        if (t <= s->max_ts) s->sorted = false;
        s->max_ts = std::max(s->max_ts, t);
        s->tail.push_back(Point{t, fval[i], ival[i], is_int[i]});
    }
    if (s->sorted && s->tail.size() >= CHUNK_POINTS) s->seal_locked();
}

EXPORT int64_t eng_series_len(void* h, int64_t sid) {
    Engine* eng = static_cast<Engine*>(h);
    Series* s = eng->series[sid];
    std::lock_guard<std::mutex> lock(s->mu);
    return static_cast<int64_t>(s->size());
}

EXPORT int64_t eng_series_bytes(void* h, int64_t sid) {
    Engine* eng = static_cast<Engine*>(h);
    Series* s = eng->series[sid];
    std::lock_guard<std::mutex> lock(s->mu);
    return static_cast<int64_t>(s->bytes());
}

// Materialize [start, end] into caller buffers sized via eng_series_len.
// Returns the number of points written.
EXPORT int64_t eng_window(void* h, int64_t sid, int64_t start, int64_t end,
                          int64_t* out_ts, double* out_val,
                          int64_t* out_ival, uint8_t* out_isint,
                          int64_t max_n) {
    Engine* eng = static_cast<Engine*>(h);
    Series* s = eng->series[sid];
    std::lock_guard<std::mutex> lock(s->mu);
    s->materialize(g_scratch);
    auto lo = std::lower_bound(
        g_scratch.begin(), g_scratch.end(), start,
        [](const Point& p, int64_t v) { return p.ts < v; });
    auto hi = std::upper_bound(
        g_scratch.begin(), g_scratch.end(), end,
        [](int64_t v, const Point& p) { return v < p.ts; });
    int64_t n = 0;
    for (auto it = lo; it != hi && n < max_n; ++it, ++n) {
        out_ts[n] = it->ts;
        out_val[n] = it->fval;
        out_ival[n] = it->ival;
        out_isint[n] = it->is_int;
    }
    return n;
}

// Like eng_window over the full range, but duplicates survive (snapshot
// restore fidelity: a series persisted dirty must restore dirty).
EXPORT int64_t eng_window_raw(void* h, int64_t sid, int64_t* out_ts,
                              double* out_val, int64_t* out_ival,
                              uint8_t* out_isint, int64_t max_n) {
    Engine* eng = static_cast<Engine*>(h);
    Series* s = eng->series[sid];
    std::lock_guard<std::mutex> lock(s->mu);
    s->materialize(g_scratch, /*dedup=*/false);
    int64_t n = 0;
    for (auto it = g_scratch.begin(); it != g_scratch.end() && n < max_n;
         ++it, ++n) {
        out_ts[n] = it->ts;
        out_val[n] = it->fval;
        out_ival[n] = it->ival;
        out_isint[n] = it->is_int;
    }
    return n;
}

EXPORT int64_t eng_delete_range(void* h, int64_t sid, int64_t start,
                                int64_t end) {
    Engine* eng = static_cast<Engine*>(h);
    Series* s = eng->series[sid];
    std::lock_guard<std::mutex> lock(s->mu);
    s->materialize(g_scratch);
    std::vector<Point> kept;
    kept.reserve(g_scratch.size());
    int64_t removed = 0;
    for (const auto& p : g_scratch) {
        if (p.ts >= start && p.ts <= end) {
            removed++;
        } else {
            kept.push_back(p);
        }
    }
    s->chunks.clear();
    for (size_t i = 0; i < kept.size(); i += CHUNK_POINTS) {
        size_t n = std::min(CHUNK_POINTS, kept.size() - i);
        s->chunks.push_back(Chunk::compress(kept.data() + i, n));
    }
    s->tail.clear();
    s->sorted = true;
    s->max_ts = kept.empty() ? INT64_MIN : kept.back().ts;
    return removed;
}

EXPORT void eng_normalize(void* h, int64_t sid) {
    Engine* eng = static_cast<Engine*>(h);
    eng->series[sid]->normalize();
}

EXPORT int64_t eng_total_bytes(void* h) {
    Engine* eng = static_cast<Engine*>(h);
    std::lock_guard<std::mutex> lock(eng->mu);
    int64_t total = 0;
    for (auto* s : eng->series) total += s->bytes();
    return total;
}

// ---------------------------------------------------------------- save/load

EXPORT int32_t eng_save(void* h, const char* path) {
    Engine* eng = static_cast<Engine*>(h);
    FILE* f = std::fopen(path, "wb");
    if (!f) return -1;
    std::lock_guard<std::mutex> lock(eng->mu);
    uint64_t magic = 0x545044424E474E45ull;  // "ENGNBDPT"-ish tag
    std::fwrite(&magic, 8, 1, f);
    uint64_t n_series = eng->series.size();
    std::fwrite(&n_series, 8, 1, f);
    for (auto* s : eng->series) {
        std::lock_guard<std::mutex> slock(s->mu);
        s->seal_locked();
        uint64_t klen = s->key.size();
        std::fwrite(&klen, 8, 1, f);
        std::fwrite(s->key.data(), 1, klen, f);
        uint64_t n_chunks = s->chunks.size();
        std::fwrite(&n_chunks, 8, 1, f);
        uint8_t flags = s->sorted ? 1 : 0;
        std::fwrite(&flags, 1, 1, f);
        std::fwrite(&s->max_ts, 8, 1, f);
        for (const auto& c : s->chunks) {
            uint64_t n = c.n;
            uint64_t len = c.data.size();
            std::fwrite(&n, 8, 1, f);
            std::fwrite(&c.first_ts, 8, 1, f);
            std::fwrite(&c.last_ts, 8, 1, f);
            std::fwrite(&len, 8, 1, f);
            std::fwrite(c.data.data(), 1, len, f);
        }
    }
    std::fclose(f);
    return 0;
}

EXPORT void* eng_load(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return nullptr;
    uint64_t magic = 0;
    if (std::fread(&magic, 8, 1, f) != 1 ||
        magic != 0x545044424E474E45ull) {
        std::fclose(f);
        return nullptr;
    }
    Engine* eng = new Engine();
    uint64_t n_series = 0;
    std::fread(&n_series, 8, 1, f);
    for (uint64_t i = 0; i < n_series; i++) {
        Series* s = new Series();
        uint64_t klen = 0;
        std::fread(&klen, 8, 1, f);
        s->key.resize(klen);
        std::fread(s->key.data(), 1, klen, f);
        uint64_t n_chunks = 0;
        std::fread(&n_chunks, 8, 1, f);
        uint8_t flags = 1;
        std::fread(&flags, 1, 1, f);
        s->sorted = flags & 1;
        std::fread(&s->max_ts, 8, 1, f);
        for (uint64_t j = 0; j < n_chunks; j++) {
            Chunk c;
            uint64_t n = 0, len = 0;
            std::fread(&n, 8, 1, f);
            std::fread(&c.first_ts, 8, 1, f);
            std::fread(&c.last_ts, 8, 1, f);
            std::fread(&len, 8, 1, f);
            c.n = n;
            c.data.resize(len);
            std::fread(c.data.data(), 1, len, f);
            s->chunks.push_back(std::move(c));
        }
        int64_t sid = static_cast<int64_t>(eng->series.size());
        eng->series.push_back(s);
        eng->by_key.emplace(s->key, sid);
    }
    std::fclose(f);
    return eng;
}

// ================================================================ bulk put
//
// Native fast path for POST /api/put bodies (the reference's ingest
// scale claim, README:12-15, flows through PutDataPointRpc:272 ->
// TSDB.addPoint per point).  The Python bulk path (TSDB.add_points_bulk)
// already amortizes locks and column appends; profiling shows the
// remaining ~75% is the per-point Python loop: JSON object walk,
// validation, value classification, tag canonicalization.  This parser
// does all of that in one pass over the raw body bytes and hands Python
// back columnar arrays plus a distinct-series key table, so Python cost
// becomes O(distinct series), not O(points).
//
// Semantics mirror tsdb.py EXACTLY (error strings included) — any
// construct whose Python behavior is exotic (non-string metric/tags,
// arbitrary-precision timestamps, bool timestamps) returns FALLBACK so
// the caller reruns the Python path; behavior can never silently drift
// for inputs the native path accepts.  Tag canonicalization: tags sort
// bytewise on UTF-8 keys == Python's sorted() on code points.

namespace putparse {

struct PutBatch {
    std::vector<int64_t> ts;        // normalized ms
    std::vector<double> fval;
    std::vector<int64_t> ival;
    std::vector<uint8_t> isint;
    std::vector<int32_t> group;     // -1 on error
    std::vector<int64_t> span;      // 2*i: start, 2*i+1: end byte offsets
    // errors are SPARSE (parallel arrays, point index ascending) — a
    // per-point string pair would dominate allocation on clean bodies
    std::vector<int64_t> err_idx;
    std::vector<std::string> err_msg;
    std::vector<std::string> err_kind;  // "ValueError" | "TypeError"
    // group table: canonical (sorted-tag) identity keys plus the FIRST-
    // OCCURRENCE original-order form.  Python resolves series keys from
    // the original order so UID ASSIGNMENT order matches the per-point
    // path exactly (tagk/tagv ids are user-visible via /api/uid).
    std::vector<std::string> gkeys;       // canonical, identity
    std::vector<std::string> gorig;       // original tag order, exposed
    std::unordered_map<std::string, int32_t> gindex;
    // reused scratch (steady-state zero allocation per point)
    std::string ckey_scratch;
    std::string orig_scratch;
};

struct Parser {
    const char* p;
    const char* end;
    bool fallback = false;

    explicit Parser(const char* data, size_t len)
        : p(data), end(data + len) {}

    void ws() {
        while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' ||
                           *p == '\r'))
            p++;
    }
    bool lit(const char* s) {
        size_t n = std::strlen(s);
        if (static_cast<size_t>(end - p) < n || std::memcmp(p, s, n) != 0)
            return false;
        p += n;
        return true;
    }
    // JSON string -> UTF-8 std::string; false on malformed
    bool str(std::string& out) {
        out.clear();
        if (p >= end || *p != '"') return false;
        p++;
        while (p < end) {
            unsigned char c = static_cast<unsigned char>(*p);
            if (c == '"') { p++; return true; }
            if (c == '\\') {
                if (++p >= end) return false;
                char e = *p++;
                switch (e) {
                    case '"': out.push_back('"'); break;
                    case '\\': out.push_back('\\'); break;
                    case '/': out.push_back('/'); break;
                    case 'b': out.push_back('\b'); break;
                    case 'f': out.push_back('\f'); break;
                    case 'n': out.push_back('\n'); break;
                    case 'r': out.push_back('\r'); break;
                    case 't': out.push_back('\t'); break;
                    case 'u': {
                        if (end - p < 4) return false;
                        unsigned cp = 0;
                        for (int i = 0; i < 4; i++) {
                            char h = *p++;
                            cp <<= 4;
                            if (h >= '0' && h <= '9') cp |= h - '0';
                            else if (h >= 'a' && h <= 'f') cp |= h - 'a' + 10;
                            else if (h >= 'A' && h <= 'F') cp |= h - 'A' + 10;
                            else return false;
                        }
                        bool paired = false;
                        if (cp >= 0xD800 && cp <= 0xDBFF && end - p >= 6 &&
                            p[0] == '\\' && p[1] == 'u') {
                            unsigned lo = 0;
                            const char* q = p + 2;
                            bool ok = true;
                            for (int i = 0; i < 4; i++) {
                                char h = q[i];
                                lo <<= 4;
                                if (h >= '0' && h <= '9') lo |= h - '0';
                                else if (h >= 'a' && h <= 'f')
                                    lo |= h - 'a' + 10;
                                else if (h >= 'A' && h <= 'F')
                                    lo |= h - 'A' + 10;
                                else { ok = false; break; }
                            }
                            if (ok && lo >= 0xDC00 && lo <= 0xDFFF) {
                                cp = 0x10000 + ((cp - 0xD800) << 10)
                                     + (lo - 0xDC00);
                                p += 6;
                                paired = true;
                            }
                        }
                        // Lone surrogates are valid JSON (json.loads
                        // keeps them as Python surrogate code points)
                        // but have no UTF-8 encoding — the Python path
                        // owns that exotic case.
                        if (cp >= 0xD800 && cp <= 0xDFFF && !paired) {
                            fallback = true;
                            cp = 0xFFFD;
                        }
                        // encode UTF-8
                        if (cp < 0x80) out.push_back(static_cast<char>(cp));
                        else if (cp < 0x800) {
                            out.push_back(static_cast<char>(0xC0 | (cp >> 6)));
                            out.push_back(static_cast<char>(
                                0x80 | (cp & 0x3F)));
                        } else if (cp < 0x10000) {
                            out.push_back(static_cast<char>(0xE0 | (cp >> 12)));
                            out.push_back(static_cast<char>(
                                0x80 | ((cp >> 6) & 0x3F)));
                            out.push_back(static_cast<char>(
                                0x80 | (cp & 0x3F)));
                        } else {
                            out.push_back(static_cast<char>(0xF0 | (cp >> 18)));
                            out.push_back(static_cast<char>(
                                0x80 | ((cp >> 12) & 0x3F)));
                            out.push_back(static_cast<char>(
                                0x80 | ((cp >> 6) & 0x3F)));
                            out.push_back(static_cast<char>(
                                0x80 | (cp & 0x3F)));
                        }
                        break;
                    }
                    default: return false;
                }
            } else {
                out.push_back(static_cast<char>(c));
                p++;
            }
        }
        return false;  // unterminated
    }
    // skip any JSON value (for unknown keys); false on malformed
    bool skip() {
        ws();
        if (p >= end) return false;
        char c = *p;
        if (c == '"') { std::string s_; return str(s_); }
        if (c == '{' || c == '[') {
            char open = c, close = (c == '{') ? '}' : ']';
            int depth = 0;
            bool in_str = false;
            while (p < end) {
                char d = *p;
                if (in_str) {
                    if (d == '\\') { p++; if (p >= end) return false; }
                    else if (d == '"') in_str = false;
                } else {
                    if (d == '"') in_str = true;
                    else if (d == open) depth++;
                    else if (d == close) {
                        if (--depth == 0) { p++; return true; }
                    }
                }
                p++;
            }
            return false;
        }
        // number / literal
        const char* q = p;
        while (q < end && *q != ',' && *q != '}' && *q != ']' &&
               *q != ' ' && *q != '\t' && *q != '\n' && *q != '\r')
            q++;
        if (q == p) return false;
        p = q;
        return true;
    }
};

// Python-int grammar: optional sign, digits with single underscores
// BETWEEN digits (int("1_0") == 10).  Returns false if not an integer
// literal by Python rules.
inline bool py_int(const std::string& t, bool& overflow, int64_t& out) {
    size_t i = 0;
    bool neg = false;
    overflow = false;
    out = 0;
    if (i < t.size() && (t[i] == '+' || t[i] == '-')) {
        neg = t[i] == '-';
        i++;
    }
    if (i >= t.size()) return false;
    bool prev_digit = false;
    bool acc_overflow = false;
    uint64_t acc = 0;
    for (; i < t.size(); i++) {
        char c = t[i];
        if (c == '_') {
            // Python int(): single underscores BETWEEN digits only
            if (!prev_digit || i + 1 >= t.size()) return false;
            prev_digit = false;
            continue;
        }
        if (c < '0' || c > '9') return false;
        prev_digit = true;
        uint64_t d = static_cast<uint64_t>(c - '0');
        if (acc > (UINT64_MAX - d) / 10) acc_overflow = true;
        else acc = acc * 10 + d;
    }
    if (!prev_digit) return false;
    // Java-long range check (Python ints are unbounded; the CALLER
    // rejects out-of-range with "out of long range")
    uint64_t lim = neg ? (1ULL << 63) : (1ULL << 63) - 1;
    if (acc_overflow || acc > lim) {
        overflow = true;
        return true;
    }
    out = neg ? (acc == (1ULL << 63) ? INT64_MIN
                                     : -static_cast<int64_t>(acc))
              : static_cast<int64_t>(acc);
    return true;
}

// Python-float grammar is strtod plus underscores-between-digits and
// without hex floats.  Returns false if not parseable as Python float.
inline bool py_float(const std::string& t, double& out) {
    if (t.empty()) return false;
    std::string clean;
    clean.reserve(t.size());
    bool prev_digit = false;
    for (size_t i = 0; i < t.size(); i++) {
        char c = t[i];
        if (c == '_') {
            bool next_digit = i + 1 < t.size() && t[i + 1] >= '0' &&
                              t[i + 1] <= '9';
            if (!prev_digit || !next_digit) return false;
            continue;
        }
        if (c == 'x' || c == 'X') return false;  // no hex floats
        prev_digit = c >= '0' && c <= '9';
        clean.push_back(c);
    }
    const char* s = clean.c_str();
    char* endp = nullptr;
    out = std::strtod(s, &endp);
    return endp == s + clean.size() && endp != s;
}

// simplified Python repr() of a decoded string (enough for error
// messages on realistic inputs; exotic escapes fall back)
inline bool py_repr(const std::string& s, std::string& out) {
    bool has_sq = s.find('\'') != std::string::npos;
    bool has_dq = s.find('"') != std::string::npos;
    char quote = (has_sq && !has_dq) ? '"' : '\'';
    out.clear();
    out.push_back(quote);
    for (unsigned char c : s) {
        if (c < 0x20 || c == 0x7F) return false;   // control chars: punt
        if (c == static_cast<unsigned char>(quote)) {
            out.push_back('\\');
        } else if (c == '\\') {
            out.push_back('\\');
        }
        out.push_back(static_cast<char>(c));
    }
    out.push_back(quote);
    return true;
}

// repr of a double the way Python renders it in error messages
inline std::string py_float_str(double v) {
    char buf[64];
    double r = v;
    std::snprintf(buf, sizeof buf, "%.17g", r);
    // Python uses repr shortest round-trip; try %.15g, %.16g first
    for (int prec = 15; prec <= 17; prec++) {
        std::snprintf(buf, sizeof buf, "%.*g", prec, r);
        if (std::strtod(buf, nullptr) == r) break;
    }
    std::string s(buf);
    if (s.find('.') == std::string::npos &&
        s.find('e') == std::string::npos &&
        s.find('n') == std::string::npos &&
        s.find('i') == std::string::npos)
        s += ".0";
    return s;
}

constexpr int64_t SECOND_MASK_LO = 0x100000000LL;  // ts >= 2^32 -> already ms

struct PointScratch {
    std::string metric;
    size_t ntags = 0;         // live prefix of `tags` (slots are reused)
    bool metric_seen = false, metric_is_str = false;
    std::string ts_str;       // lexeme or decoded string
    bool ts_seen = false, ts_is_str = false, ts_is_num = false;
    double ts_num = 0;
    bool ts_num_is_int = false;
    int64_t ts_int = 0;
    std::string val_str;
    bool val_seen = false, val_is_str = false, val_is_num = false,
         val_is_bool = false, val_bool = false, val_is_null = false;
    double val_num = 0;
    bool val_num_is_int = false;
    int64_t val_int = 0;
    bool val_int_overflow = false;
    std::vector<std::pair<std::string, std::string>> tags;
    bool tags_seen = false, tags_empty = false;
};

}  // namespace putparse

using putparse::PutBatch;
using putparse::Parser;
using putparse::PointScratch;

namespace putparse {

// parse one number token with STRICT JSON grammar
// ('-'? (0|[1-9][0-9]*) ('.'[0-9]+)? ([eE][+-]?[0-9]+)?); sets is_int if
// the lexeme has no . e E.  Leniency here would make the API accept
// bodies (+5, 007, .5) that json.loads rejects, so accept/reject
// behavior would depend on whether the native library is present.
inline bool number(Parser& P, double& out, bool& is_int, int64_t& ival,
                   bool& overflow, std::string& lexeme) {
    const char* q = P.p;
    if (q < P.end && *q == '-') q++;
    if (q >= P.end || *q < '0' || *q > '9') return false;
    if (*q == '0') q++;                       // no leading zeros
    else while (q < P.end && *q >= '0' && *q <= '9') q++;
    bool frac = false;
    if (q < P.end && *q == '.') {
        frac = true;
        q++;
        if (q >= P.end || *q < '0' || *q > '9') return false;
        while (q < P.end && *q >= '0' && *q <= '9') q++;
    }
    if (q < P.end && (*q == 'e' || *q == 'E')) {
        frac = true;
        q++;
        if (q < P.end && (*q == '+' || *q == '-')) q++;
        if (q >= P.end || *q < '0' || *q > '9') return false;
        while (q < P.end && *q >= '0' && *q <= '9') q++;
    }
    lexeme.assign(P.p, q - P.p);
    is_int = !frac;
    if (is_int) {
        if (!py_int(lexeme, overflow, ival)) return false;
        out = static_cast<double>(ival);
        if (overflow) out = 0;
    } else {
        char* endp = nullptr;
        out = std::strtod(lexeme.c_str(), &endp);
        if (endp != lexeme.c_str() + lexeme.size()) return false;
    }
    P.p = q;
    return true;
}

}  // namespace putparse


namespace putparse {

enum FieldKind : uint8_t {
    K_ABSENT = 0, K_NULL, K_STRING, K_NUMBER, K_BOOL, K_OBJECT, K_ARRAY,
    K_EMPTY_OBJECT
};

struct RawPoint {
    PointScratch s;
    uint8_t metric_kind = K_ABSENT;
    uint8_t ts_kind = K_ABSENT;
    uint8_t val_kind = K_ABSENT;
    uint8_t tags_kind = K_ABSENT;
    int64_t span_start = 0, span_end = 0;
    std::string ts_lexeme;    // original number lexeme for %s rendering
    std::string val_lexeme;

    // Reset for reuse between points: strings keep their capacity, so a
    // long body parses with near-zero steady-state allocation (storing
    // one RawPoint per point cost ~10 allocs x N and dominated the
    // parse at 400k points).
    void reset() {
        metric_kind = ts_kind = val_kind = tags_kind = K_ABSENT;
        span_start = span_end = 0;
        ts_lexeme.clear();
        val_lexeme.clear();
        s.metric.clear();
        s.ts_str.clear();
        s.val_str.clear();
        s.ntags = 0;          // slots stay allocated for reuse
        s.metric_seen = s.metric_is_str = false;
        s.ts_seen = s.ts_is_str = s.ts_is_num = false;
        s.ts_num = 0;
        s.ts_num_is_int = false;
        s.ts_int = 0;
        s.val_seen = s.val_is_str = s.val_is_num = false;
        s.val_is_bool = s.val_bool = s.val_is_null = false;
        s.val_num = 0;
        s.val_num_is_int = false;
        s.val_int = 0;
        s.val_int_overflow = false;
        s.tags_seen = s.tags_empty = false;
    }
};

// Parse one datapoint object into RawPoint; returns false -> malformed
// JSON (whole-body fallback).  Sets P.fallback for exotic-but-valid
// constructs whose Python behavior we refuse to mirror natively.
inline bool parse_point(Parser& P, RawPoint& rp, const char* base) {
    P.ws();
    if (P.p >= P.end || *P.p != '{') return false;
    rp.span_start = P.p - base;
    P.p++;
    bool first = true;
    std::string key;              // reused across fields
    for (;;) {
        P.ws();
        if (P.p < P.end && *P.p == '}') {
            P.p++;
            break;
        }
        if (!first) {
            if (P.p >= P.end || *P.p != ',') return false;
            P.p++;
            P.ws();
        }
        first = false;
        if (!P.str(key)) return false;
        P.ws();
        if (P.p >= P.end || *P.p != ':') return false;
        P.p++;
        P.ws();
        if (key == "metric") {
            if (P.p < P.end && *P.p == '"') {
                if (!P.str(rp.s.metric)) return false;
                rp.metric_kind = K_STRING;
            } else if (P.lit("null")) {
                rp.metric_kind = K_NULL;
            } else {
                rp.metric_kind = K_NUMBER;  // any non-string: fallback later
                P.fallback = true;
                if (!P.skip()) return false;
            }
        } else if (key == "timestamp") {
            if (P.p < P.end && *P.p == '"') {
                if (!P.str(rp.s.ts_str)) return false;
                rp.ts_kind = K_STRING;
            } else if (P.lit("null")) {
                rp.ts_kind = K_NULL;
            } else if (P.lit("true") || P.lit("false")) {
                rp.ts_kind = K_BOOL;
                P.fallback = true;
            } else if (P.p < P.end && (*P.p == '{' || *P.p == '[')) {
                const char* before = P.p;
                char open = *P.p;
                if (!P.skip()) return false;
                // Python: {} == {} -> missing field; others TypeError
                std::string body(before, P.p - before);
                bool empty = true;
                for (char c : body)
                    if (c != '{' && c != '}' && c != '[' && c != ']' &&
                        c != ' ' && c != '\t' && c != '\n' && c != '\r')
                        empty = false;
                rp.ts_kind = (empty && open == '{') ? K_EMPTY_OBJECT
                                                    : K_OBJECT;
                if (rp.ts_kind == K_OBJECT) P.fallback = true;
            } else {
                bool is_int = false, of = false;
                int64_t iv = 0;
                if (!number(P, rp.s.ts_num, is_int, iv, of,
                            rp.ts_lexeme)) return false;
                if (of) { P.fallback = true; }   // arbitrary-precision ts
                rp.ts_kind = K_NUMBER;
                rp.s.ts_is_num = true;
                rp.s.ts_num_is_int = is_int;
                rp.s.ts_int = iv;
            }
        } else if (key == "value") {
            if (P.p < P.end && *P.p == '"') {
                if (!P.str(rp.s.val_str)) return false;
                rp.val_kind = K_STRING;
            } else if (P.lit("null")) {
                rp.val_kind = K_NULL;
            } else if (P.lit("true")) {
                rp.val_kind = K_BOOL;
                rp.s.val_bool = true;
            } else if (P.lit("false")) {
                rp.val_kind = K_BOOL;
                rp.s.val_bool = false;
            } else if (P.p < P.end && (*P.p == '{' || *P.p == '[')) {
                const char* before = P.p;
                char open = *P.p;
                if (!P.skip()) return false;
                std::string body(before, P.p - before);
                bool empty = true;
                for (char c : body)
                    if (c != '{' && c != '}' && c != '[' && c != ']' &&
                        c != ' ' && c != '\t' && c != '\n' && c != '\r')
                        empty = false;
                rp.val_kind = (empty && open == '{') ? K_EMPTY_OBJECT
                                                     : K_OBJECT;
                if (rp.val_kind == K_OBJECT) P.fallback = true;
            } else {
                bool is_int = false, of = false;
                int64_t iv = 0;
                if (!number(P, rp.s.val_num, is_int, iv, of,
                            rp.val_lexeme)) return false;
                rp.val_kind = K_NUMBER;
                rp.s.val_is_num = true;
                rp.s.val_num_is_int = is_int;
                rp.s.val_int = iv;
                rp.s.val_int_overflow = of;
            }
        } else if (key == "tags") {
            if (P.p < P.end && *P.p == '{') {
                P.p++;
                rp.s.ntags = 0;
                bool tfirst = true;
                for (;;) {
                    P.ws();
                    if (P.p < P.end && *P.p == '}') { P.p++; break; }
                    if (!tfirst) {
                        if (P.p >= P.end || *P.p != ',') return false;
                        P.p++;
                        P.ws();
                    }
                    tfirst = false;
                    // The last-wins dedupe below is O(ntags) per tag —
                    // fine to the 8-tag limit (+ slack), quadratic for
                    // adversarial bodies; beyond the cap the Python
                    // path's O(n) dict handles it (the point errors
                    // with "Too many tags" either way).
                    if (rp.s.ntags >= 64) {
                        P.fallback = true;
                        rp.s.ntags = 63;
                    }
                    // parse straight into a reused slot (string
                    // capacities persist across points)
                    if (rp.s.ntags == rp.s.tags.size())
                        rp.s.tags.emplace_back();
                    auto& slot = rp.s.tags[rp.s.ntags];
                    if (!P.str(slot.first)) return false;
                    P.ws();
                    if (P.p >= P.end || *P.p != ':') return false;
                    P.p++;
                    P.ws();
                    if (P.p < P.end && *P.p == '"') {
                        if (!P.str(slot.second)) return false;
                    } else {
                        P.fallback = true;     // non-string tag value
                        if (!P.skip()) return false;
                        slot.second.clear();
                    }
                    // canonical-key separators must stay unambiguous; NUL
                    // would truncate the c_char_p group-key return (ADVICE r3)
                    if (slot.first.find_first_of("\x1E\x1F", 0) != std::string::npos ||
                        slot.first.find('\0', 0) != std::string::npos ||
                        slot.second.find_first_of("\x1E\x1F", 0) != std::string::npos ||
                        slot.second.find('\0', 0) != std::string::npos)
                        P.fallback = true;
                    bool replaced = false;     // JSON duplicate key: last wins
                    for (size_t ti = 0; ti < rp.s.ntags; ti++)
                        if (rp.s.tags[ti].first == slot.first) {
                            rp.s.tags[ti].second = slot.second;
                            replaced = true;
                        }
                    if (!replaced) rp.s.ntags++;
                }
                rp.tags_kind = rp.s.ntags == 0 ? K_EMPTY_OBJECT : K_OBJECT;
            } else if (P.lit("null")) {
                rp.tags_kind = K_NULL;
            } else {
                rp.tags_kind = K_ARRAY;
                P.fallback = true;
                if (!P.skip()) return false;
            }
        } else {
            if (!P.skip()) return false;   // unknown fields are ignored
        }
    }
    rp.span_end = P.p - base;
    return true;
}


// canonical series-key + group-table insert shared by the JSON and
// telnet paths (step 5 of finish_point): identity = metric + bytewise-
// SORTED tags; the stored gorig form keeps ORIGINAL tag order so Python
// key resolution assigns UIDs in per-point-path order.
inline int32_t assign_group(const std::string& metric,
                            const PointScratch& s, PutBatch& out) {
    uint32_t tag_order[8];
    for (uint32_t i = 0; i < s.ntags; i++) tag_order[i] = i;
    std::sort(tag_order, tag_order + s.ntags,
              [&s](uint32_t a, uint32_t b) {
                  return s.tags[a] < s.tags[b];
              });
    std::string& ckey = out.ckey_scratch;
    ckey.clear();
    ckey.append(metric);
    for (uint32_t i = 0; i < s.ntags; i++) {
        const auto& kv = s.tags[tag_order[i]];
        ckey.push_back('\x1F');
        ckey.append(kv.first);
        ckey.push_back('\x1E');
        ckey.append(kv.second);
    }
    auto it = out.gindex.find(ckey);
    if (it != out.gindex.end()) return it->second;
    int32_t gid = static_cast<int32_t>(out.gkeys.size());
    out.gkeys.push_back(ckey);
    std::string& orig = out.orig_scratch;
    orig.clear();
    orig.append(metric);
    for (uint32_t i = 0; i < s.ntags; i++) {
        const auto& kv = s.tags[i];
        orig.push_back('\x1F');
        orig.append(kv.first);
        orig.push_back('\x1E');
        orig.append(kv.second);
    }
    out.gorig.push_back(orig);
    out.gindex.emplace(ckey, gid);
    return gid;
}

// render the Python %s of the timestamp as received
inline std::string ts_as_str(const RawPoint& rp) {
    if (rp.ts_kind == K_STRING) return rp.s.ts_str;
    if (rp.s.ts_num_is_int) return rp.ts_lexeme;
    return py_float_str(rp.s.ts_num);
}

// Validate + normalize one raw point into the batch (mirrors
// add_points_bulk's per-point try block, same error order and strings).
// Returns false -> needs Python fallback for THIS construct.
inline bool finish_point(const RawPoint& rp, PutBatch& out) {
    std::string err, kind;
    int64_t ts_ms = 0;
    double fv = 0;
    int64_t iv = 0;
    bool is_int = false;

    auto fail = [&](const char* k, const std::string& m) {
        out.err_idx.push_back(static_cast<int64_t>(out.ts.size()));
        out.err_msg.push_back(m);
        out.err_kind.push_back(k);
        out.ts.push_back(0);
        out.fval.push_back(0);
        out.ival.push_back(0);
        out.isint.push_back(0);
        out.group.push_back(-1);
        out.span.push_back(rp.span_start);
        out.span.push_back(rp.span_end);
    };

    // 1. missing required fields, in field order
    const char* missing = nullptr;
    if (rp.metric_kind == K_ABSENT || rp.metric_kind == K_NULL ||
        (rp.metric_kind == K_STRING && rp.s.metric.empty()))
        missing = "metric";
    else if (rp.ts_kind == K_ABSENT || rp.ts_kind == K_NULL ||
             rp.ts_kind == K_EMPTY_OBJECT ||
             (rp.ts_kind == K_STRING && rp.s.ts_str.empty()))
        missing = "timestamp";
    else if (rp.val_kind == K_ABSENT || rp.val_kind == K_NULL ||
             rp.val_kind == K_EMPTY_OBJECT ||
             (rp.val_kind == K_STRING && rp.s.val_str.empty()))
        missing = "value";
    else if (rp.tags_kind == K_ABSENT || rp.tags_kind == K_NULL ||
             rp.tags_kind == K_EMPTY_OBJECT)
        missing = "tags";
    if (missing) {
        fail("ValueError", std::string("Missing required field: ") + missing);
        return true;
    }

    // 2. parse_value
    std::string vrepr;
    if (rp.val_kind == K_BOOL) {
        fail("ValueError", std::string("Invalid value: ")
             + (rp.s.val_bool ? "True" : "False"));
        return true;
    } else if (rp.val_kind == K_NUMBER) {
        is_int = rp.s.val_num_is_int;
        if (is_int) {
            iv = rp.s.val_int;
            fv = static_cast<double>(iv);
            vrepr = rp.val_lexeme;
            // normalize "+5" repr to 5 like Python's repr(int)
            if (!vrepr.empty() && vrepr[0] == '+') vrepr = vrepr.substr(1);
            if (rp.s.val_int_overflow) {
                fail("ValueError",
                     "Invalid value, out of long range: " + vrepr);
                return true;
            }
        } else {
            fv = rp.s.val_num;
            // json.loads parses 1e999 to float inf; the Python path
            // rejects it (parse_value: isinf/isnan -> Invalid value)
            if (std::isinf(fv) || std::isnan(fv)) {
                fail("ValueError", "Invalid value: " + py_float_str(fv));
                return true;
            }
        }
    } else {  // string
        std::string text = rp.s.val_str;
        for (char c : text)
            if (static_cast<unsigned char>(c) >= 0x80)
                return false;   // unicode strip semantics: Python path
        size_t a = text.find_first_not_of(" \t\n\r\f\v");
        size_t b = text.find_last_not_of(" \t\n\r\f\v");
        text = (a == std::string::npos) ? "" : text.substr(a, b - a + 1);
        if (!py_repr(rp.s.val_str, vrepr)) return false;
        if (text.empty()) {
            fail("ValueError", "Empty value");
            return true;
        }
        bool of = false;
        if (py_int(text, of, iv)) {
            is_int = true;
            fv = static_cast<double>(iv);
            if (of) {
                fail("ValueError",
                     "Invalid value, out of long range: " + vrepr);
                return true;
            }
        } else {
            double d = 0;
            if (!py_float(text, d)) {
                fail("ValueError", "Invalid value: " + vrepr);
                return true;
            }
            if (std::isnan(d) || std::isinf(d)) {
                fail("ValueError", "Invalid value: " + vrepr);
                return true;
            }
            fv = d;
        }
    }

    // 3. check_timestamp_and_tags: tags presence/count, int(ts) >= 0
    if (rp.s.ntags == 0) {
        fail("ValueError", "Need at least one tag (metric=" + rp.s.metric
             + ", ts=" + ts_as_str(rp) + ")");
        return true;
    }
    if (rp.s.ntags > 8) {
        char buf[80];
        std::snprintf(buf, sizeof buf,
                      "Too many tags: %zu maximum allowed: 8",
                      rp.s.ntags);
        fail("ValueError", buf);
        return true;
    }
    int64_t ts_int = 0;
    if (rp.ts_kind == K_STRING) {
        std::string t = rp.s.ts_str;
        for (char c : t)
            if (static_cast<unsigned char>(c) >= 0x80) return false;
        size_t a = t.find_first_not_of(" \t\n\r\f\v");
        size_t b = t.find_last_not_of(" \t\n\r\f\v");
        std::string stripped =
            (a == std::string::npos) ? "" : t.substr(a, b - a + 1);
        bool of = false;
        if (!py_int(stripped, of, ts_int) || of) {
            if (of) return false;   // arbitrary-precision: Python path
            std::string r;
            if (!py_repr(t, r)) return false;
            fail("ValueError",
                 "invalid literal for int() with base 10: " + r);
            return true;
        }
    } else if (rp.s.ts_num_is_int) {
        ts_int = rp.s.ts_int;
    } else {
        // Beyond int64 the cast is UB and Python's behavior diverges
        // per value (arbitrary-precision ints, OverflowError on inf):
        // the Python path owns those
        if (!(rp.s.ts_num > -9.2e18 && rp.s.ts_num < 9.2e18)) return false;
        ts_int = static_cast<int64_t>(rp.s.ts_num);  // trunc toward zero
    }
    if (ts_int < 0) {
        fail("ValueError", "Invalid timestamp: " + ts_as_str(rp));
        return true;
    }

    // 4. normalize_timestamp_ms
    ts_ms = (ts_int >= SECOND_MASK_LO) ? ts_int : ts_int * 1000;

    // 5. canonical series key: metric + bytewise-sorted tags (index
    //    sort + scratch key buffer: no string copies on the hot path)
    if (rp.s.metric.find_first_of("\x1E\x1F", 0) != std::string::npos ||
        rp.s.metric.find('\0', 0) != std::string::npos)
        return false;
    int32_t gid = assign_group(rp.s.metric, rp.s, out);

    out.ts.push_back(ts_ms);
    out.fval.push_back(fv);
    out.ival.push_back(is_int ? iv : 0);
    out.isint.push_back(is_int ? 1 : 0);
    out.group.push_back(gid);
    out.span.push_back(rp.span_start);
    out.span.push_back(rp.span_end);
    return true;
}

}  // namespace putparse

// -------------------------------------------------------------- C ABI

EXPORT void* eng_put_parse(const char* data, int64_t len) {
    using namespace putparse;
    Parser P(data, static_cast<size_t>(len));
    P.ws();
    if (P.p >= P.end) return nullptr;
    auto* out = new PutBatch();
    out->ts.reserve(static_cast<size_t>(len / 80 + 1));
    RawPoint rp;                 // ONE scratch, reset per point: string
    //                              capacities persist, so a long body
    //                              parses with ~zero per-point allocation
    auto one = [&]() -> bool {
        rp.reset();
        if (!parse_point(P, rp, data)) return false;
        if (P.fallback) return false;
        return finish_point(rp, *out);
    };
    if (*P.p == '[') {
        P.p++;
        bool first = true;
        for (;;) {
            P.ws();
            if (P.p < P.end && *P.p == ']') { P.p++; break; }
            if (!first) {
                if (P.p >= P.end || *P.p != ',') { delete out; return nullptr; }
                P.p++;
            }
            first = false;
            if (!one()) { delete out; return nullptr; }
        }
    } else if (*P.p == '{') {
        if (!one()) { delete out; return nullptr; }
    } else {
        delete out;
        return nullptr;
    }
    P.ws();
    if (P.p != P.end) { delete out; return nullptr; }  // trailing garbage
    return out;
}

EXPORT void eng_put_free(void* h) {
    delete static_cast<putparse::PutBatch*>(h);
}

EXPORT int64_t eng_put_npoints(void* h) {
    return static_cast<int64_t>(
        static_cast<putparse::PutBatch*>(h)->ts.size());
}

EXPORT int64_t eng_put_ngroups(void* h) {
    return static_cast<int64_t>(
        static_cast<putparse::PutBatch*>(h)->gkeys.size());
}

EXPORT const int64_t* eng_put_ts(void* h) {
    return static_cast<putparse::PutBatch*>(h)->ts.data();
}

EXPORT const double* eng_put_fval(void* h) {
    return static_cast<putparse::PutBatch*>(h)->fval.data();
}

EXPORT const int64_t* eng_put_ival(void* h) {
    return static_cast<putparse::PutBatch*>(h)->ival.data();
}

EXPORT const uint8_t* eng_put_isint(void* h) {
    return static_cast<putparse::PutBatch*>(h)->isint.data();
}

EXPORT const int32_t* eng_put_group(void* h) {
    return static_cast<putparse::PutBatch*>(h)->group.data();
}

EXPORT const int64_t* eng_put_spans(void* h) {
    return static_cast<putparse::PutBatch*>(h)->span.data();
}

EXPORT const char* eng_put_group_key(void* h, int64_t g) {
    auto* b = static_cast<putparse::PutBatch*>(h);
    if (g < 0 || static_cast<size_t>(g) >= b->gorig.size()) return nullptr;
    return b->gorig[static_cast<size_t>(g)].c_str();
}

EXPORT int64_t eng_put_nerrors(void* h) {
    return static_cast<int64_t>(
        static_cast<putparse::PutBatch*>(h)->err_idx.size());
}

// j-th error (ascending point index): returns message, sets *point_index
// and *kind
EXPORT const char* eng_put_error(void* h, int64_t j, int64_t* point_index,
                                 const char** kind) {
    auto* b = static_cast<putparse::PutBatch*>(h);
    if (j < 0 || static_cast<size_t>(j) >= b->err_idx.size()) return nullptr;
    *point_index = b->err_idx[static_cast<size_t>(j)];
    *kind = b->err_kind[static_cast<size_t>(j)].c_str();
    return b->err_msg[static_cast<size_t>(j)].c_str();
}

// ============================================================ telnet put
//
// Batch parser for the telnet line protocol's `put` command — the
// reference's primary high-volume ingest path (PutDataPointRpc telnet
// arm, :129).  Input is a block of N complete lines (the server batches
// consecutive put-lines); output reuses PutBatch plus a per-line status
// so exotic lines (non-ASCII, duplicate tags with different values,
// arbitrary-precision numbers) fall back to the per-line Python handler
// INDIVIDUALLY — a weird line costs itself, not the batch.
//
// Line grammar + error strings mirror tsd/rpcs.py exactly:
//   put <metric> <ts> <value> <tag=v>+
//   errors: "not enough arguments (need least 4, got %d)",
//           "invalid timestamp: %s" / int() literal errors, parse_value
//           strings, "invalid tag: %s", "Too many tags: %d ..."

namespace putparse {

enum LineStatus : int8_t {
    LINE_OK = 0,        // columns appended, group assigned
    LINE_ERROR = 1,     // error recorded (telnet-formatted message)
    LINE_FALLBACK = 2,  // python must process this line individually
    LINE_SKIP = 3,      // blank line: no output at all
};

struct TelnetBatch {
    PutBatch batch;                  // columns/groups/errors as for JSON
    std::vector<int8_t> line_status;
    std::vector<int64_t> line_span;  // 2*i: start, 2*i+1: end offsets
    std::vector<int32_t> line_point; // line -> point index or -1
};

// ASCII whitespace only; any byte >= 0x80 in a line forces fallback
// (Python str.split() also splits on unicode whitespace).
inline bool is_ws(char c) {
    return c == ' ' || c == '\t' || c == '\r' || c == '\n' ||
           c == '\f' || c == '\v';
}

// Parse ONE put line [p, q).  Appends to tb.batch on success/error.
inline LineStatus telnet_line(const char* p, const char* q,
                              int64_t span_start, TelnetBatch& tb,
                              RawPoint& rp) {
    PutBatch& out = tb.batch;
    for (const char* c = p; c < q; c++)
        if (static_cast<unsigned char>(*c) >= 0x80) return LINE_FALLBACK;

    // tokenize (Python str.split(): runs of whitespace)
    const char* words[4];        // put, metric, ts, value
    size_t wlen[4];
    size_t nw = 0;
    const char* c = p;
    const char* tag_start = nullptr;
    int extra_words = 0;         // words beyond the first 4 (tags)
    while (c < q) {
        while (c < q && is_ws(*c)) c++;
        if (c >= q) break;
        const char* w0 = c;
        while (c < q && !is_ws(*c)) c++;
        if (nw < 4) {
            words[nw] = w0;
            wlen[nw] = static_cast<size_t>(c - w0);
            nw++;
        } else {
            if (tag_start == nullptr) tag_start = w0;
            extra_words++;
        }
    }
    if (nw == 0) return LINE_SKIP;
    if (wlen[0] != 3 || std::memcmp(words[0], "put", 3) != 0)
        return LINE_FALLBACK;    // not a put line: python handles it

    rp.reset();
    rp.span_start = span_start;
    rp.span_end = span_start + (q - p);

    auto fail = [&](const std::string& m) {
        out.err_idx.push_back(static_cast<int64_t>(out.ts.size()));
        out.err_msg.push_back(m);
        out.err_kind.push_back("ValueError");
        out.ts.push_back(0);
        out.fval.push_back(0);
        out.ival.push_back(0);
        out.isint.push_back(0);
        out.group.push_back(-1);
        out.span.push_back(rp.span_start);
        out.span.push_back(rp.span_end);
        return LINE_ERROR;
    };

    int total_args = static_cast<int>(nw) - 1 + extra_words;
    if (total_args < 4) {
        char buf[72];
        std::snprintf(buf, sizeof buf,
                      "not enough arguments (need least 4, got %d)",
                      total_args);
        return fail(buf);
    }

    // timestamp (parse_telnet_timestamp: float when '.', else int; > 0)
    std::string ts_text(words[2], wlen[2]);
    bool ts_is_float = ts_text.find('.') != std::string::npos;
    double ts_f = 0;
    int64_t ts_i = 0;
    if (ts_is_float) {
        if (!py_float(ts_text, ts_f)) {
            std::string r;
            if (!py_repr(ts_text, r)) return LINE_FALLBACK;
            return fail("could not convert string to float: " + r);
        }
        if (!(ts_f > -9.2e18 && ts_f < 9.2e18)) return LINE_FALLBACK;
        if (ts_f <= 0) return fail("invalid timestamp: " + ts_text);
        ts_i = static_cast<int64_t>(ts_f);
    } else {
        bool of = false;
        if (!py_int(ts_text, of, ts_i)) {
            std::string r;
            if (!py_repr(ts_text, r)) return LINE_FALLBACK;
            return fail("invalid literal for int() with base 10: " + r);
        }
        if (of) return LINE_FALLBACK;   // python arbitrary precision
        if (ts_i <= 0) return fail("invalid timestamp: " + ts_text);
    }

    // tags: re-walk the tail words
    rp.s.ntags = 0;
    c = tag_start;
    while (c != nullptr && c < q) {
        while (c < q && is_ws(*c)) c++;
        if (c >= q) break;
        const char* w0 = c;
        while (c < q && !is_ws(*c)) c++;
        std::string w(w0, c - w0);
        size_t eq = w.find('=');
        if (eq == std::string::npos || eq == 0 || eq + 1 == w.size())
            return fail("invalid tag: " + w);
        if (w.find_first_of("\x1E\x1F", 0) != std::string::npos ||
            w.find('\0', 0) != std::string::npos)
            return LINE_FALLBACK;
        if (rp.s.ntags >= 64) return LINE_FALLBACK;  // bounded dedupe
        if (rp.s.ntags == rp.s.tags.size()) rp.s.tags.emplace_back();
        auto& slot = rp.s.tags[rp.s.ntags];
        slot.first.assign(w, 0, eq);
        slot.second.assign(w, eq + 1, std::string::npos);
        bool dup = false;
        for (size_t ti = 0; ti < rp.s.ntags; ti++) {
            if (rp.s.tags[ti].first == slot.first) {
                if (rp.s.tags[ti].second != slot.second)
                    return LINE_FALLBACK;  // "duplicate tag" repr message
                dup = true;
            }
        }
        if (!dup) rp.s.ntags++;
    }

    // value AFTER tag grammar (python precedence: import_telnet_point
    // runs parse_tags before add_point's parse_value) but BEFORE the
    // tag-count check (which lives in check_timestamp_and_tags, called
    // after parse_value inside _apply_point)
    std::string val_text(words[3], wlen[3]);
    std::string vrepr;
    if (!py_repr(val_text, vrepr)) return LINE_FALLBACK;
    bool is_int = false, vof = false;
    int64_t iv = 0;
    double fv = 0;
    if (py_int(val_text, vof, iv)) {
        is_int = true;
        if (vof) return LINE_FALLBACK;  // store-side OverflowError path
        fv = static_cast<double>(iv);
    } else {
        if (!py_float(val_text, fv))
            return fail("Invalid value: " + vrepr);
        if (std::isnan(fv) || std::isinf(fv))
            return fail("Invalid value: " + vrepr);
    }

    if (rp.s.ntags > 8) {
        char buf[80];
        std::snprintf(buf, sizeof buf,
                      "Too many tags: %zu maximum allowed: 8", rp.s.ntags);
        return fail(buf);
    }

    // canonical key + columns (same as the JSON path's step 5)
    std::string metric(words[1], wlen[1]);
    if (metric.find_first_of("\x1E\x1F", 0) != std::string::npos ||
        metric.find('\0', 0) != std::string::npos)
        return LINE_FALLBACK;
    int32_t gid = assign_group(metric, rp.s, out);
    int64_t ts_ms = (ts_i >= SECOND_MASK_LO) ? ts_i : ts_i * 1000;
    out.ts.push_back(ts_ms);
    out.fval.push_back(fv);
    out.ival.push_back(is_int ? iv : 0);
    out.isint.push_back(is_int ? 1 : 0);
    out.group.push_back(gid);
    out.span.push_back(rp.span_start);
    out.span.push_back(rp.span_end);
    return LINE_OK;
}

}  // namespace putparse

EXPORT void* eng_telnet_parse(const char* data, int64_t len) {
    using namespace putparse;
    auto* tb = new TelnetBatch();
    tb->batch.ts.reserve(static_cast<size_t>(len / 40 + 1));
    RawPoint rp;
    const char* p = data;
    const char* end = data + len;
    while (p < end) {
        const char* nl = static_cast<const char*>(
            std::memchr(p, '\n', static_cast<size_t>(end - p)));
        const char* q = nl ? nl : end;
        int64_t start = p - data;
        size_t pt_before = tb->batch.ts.size();
        LineStatus st = telnet_line(p, q, start, *tb, rp);
        if (st != LINE_SKIP) {
            tb->line_status.push_back(st);
            tb->line_span.push_back(start);
            tb->line_span.push_back(q - data);
            tb->line_point.push_back(
                st == LINE_FALLBACK
                    ? -1 : static_cast<int32_t>(pt_before));
        }
        p = nl ? nl + 1 : end;
    }
    return tb;
}

EXPORT void eng_telnet_free(void* h) {
    delete static_cast<putparse::TelnetBatch*>(h);
}

EXPORT void* eng_telnet_batch(void* h) {   // the embedded PutBatch view
    return &static_cast<putparse::TelnetBatch*>(h)->batch;
}

EXPORT int64_t eng_telnet_nlines(void* h) {
    return static_cast<int64_t>(
        static_cast<putparse::TelnetBatch*>(h)->line_status.size());
}

EXPORT const int8_t* eng_telnet_status(void* h) {
    return static_cast<putparse::TelnetBatch*>(h)->line_status.data();
}

EXPORT const int64_t* eng_telnet_spans(void* h) {
    return static_cast<putparse::TelnetBatch*>(h)->line_span.data();
}

EXPORT const int32_t* eng_telnet_point(void* h) {
    return static_cast<putparse::TelnetBatch*>(h)->line_point.data();
}
