// Native columnar storage engine for opentsdb_tpu.
//
// Plays the role the HBase storage layer + asynchbase client played for the
// reference (SURVEY.md §2.6 storage schema; compaction's space rationale,
// /root/reference/src/core/CompactionQueue.java:40-56: amortize per-cell
// overhead by packing cells — here, whole chunks compress together).
//
// Design:
//   * per-series storage = sealed compressed chunks + an uncompressed
//     append tail (the CompactionQueue analog: the tail seals into a
//     compressed chunk once it reaches CHUNK_POINTS).
//   * chunk codec: delta-of-delta zig-zag varint timestamps (time-series
//     deltas are near-constant) + XOR'd IEEE754 value bits varint-packed
//     (Gorilla-style), plus an is-int bitmap so Java-long exactness
//     survives: integer points carry their int64 bits instead of a double.
//   * reads decompress + merge + sort + last-write-wins dedup, mirroring
//     MemStore.Series.normalize semantics.
//   * save/load: length-prefixed dump of keys + chunks (snapshot file).
//
// C ABI only (driven from Python via ctypes).

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#define EXPORT extern "C" __attribute__((visibility("default")))

namespace {

constexpr size_t CHUNK_POINTS = 512;

// ---------------------------------------------------------------- varint

inline void put_varint(std::vector<uint8_t>& out, uint64_t v) {
    while (v >= 0x80) {
        out.push_back(static_cast<uint8_t>(v) | 0x80);
        v >>= 7;
    }
    out.push_back(static_cast<uint8_t>(v));
}

inline uint64_t get_varint(const uint8_t* data, size_t& pos) {
    uint64_t v = 0;
    int shift = 0;
    for (;;) {
        uint8_t b = data[pos++];
        v |= static_cast<uint64_t>(b & 0x7F) << shift;
        if (!(b & 0x80)) return v;
        shift += 7;
    }
}

inline uint64_t zigzag(int64_t v) {
    return (static_cast<uint64_t>(v) << 1) ^ static_cast<uint64_t>(v >> 63);
}

inline int64_t unzigzag(uint64_t v) {
    return static_cast<int64_t>(v >> 1) ^ -static_cast<int64_t>(v & 1);
}

// ---------------------------------------------------------------- point

struct Point {
    int64_t ts;
    double fval;
    int64_t ival;
    uint8_t is_int;
};

// ---------------------------------------------------------------- chunk

struct Chunk {
    std::vector<uint8_t> data;  // compressed
    size_t n = 0;
    int64_t first_ts = 0;
    int64_t last_ts = 0;

    static Chunk compress(const Point* pts, size_t n) {
        Chunk c;
        c.n = n;
        if (n == 0) return c;
        c.first_ts = pts[0].ts;
        c.last_ts = pts[n - 1].ts;
        std::vector<uint8_t>& out = c.data;
        out.reserve(n * 4);
        // timestamps: first raw, then delta-of-delta zig-zag varints
        put_varint(out, zigzag(pts[0].ts));
        int64_t prev_ts = pts[0].ts;
        int64_t prev_delta = 0;
        for (size_t i = 1; i < n; i++) {
            int64_t delta = pts[i].ts - prev_ts;
            put_varint(out, zigzag(delta - prev_delta));
            prev_delta = delta;
            prev_ts = pts[i].ts;
        }
        // is-int bitmap
        for (size_t i = 0; i < n; i += 8) {
            uint8_t b = 0;
            for (size_t j = 0; j < 8 && i + j < n; j++)
                if (pts[i + j].is_int) b |= (1u << j);
            out.push_back(b);
        }
        // values: ints as zig-zag delta varints, floats as XOR'd bit
        // patterns (Gorilla-style, varint-packed)
        int64_t prev_int = 0;
        uint64_t prev_bits = 0;
        for (size_t i = 0; i < n; i++) {
            if (pts[i].is_int) {
                put_varint(out, zigzag(pts[i].ival - prev_int));
                prev_int = pts[i].ival;
            } else {
                uint64_t bits;
                std::memcpy(&bits, &pts[i].fval, 8);
                put_varint(out, bits ^ prev_bits);
                prev_bits = bits;
            }
        }
        return c;
    }

    void decompress(std::vector<Point>& out) const {
        if (n == 0) return;
        size_t pos = 0;
        const uint8_t* d = data.data();
        size_t base = out.size();
        out.resize(base + n);
        // timestamps
        int64_t ts = unzigzag(get_varint(d, pos));
        out[base].ts = ts;
        int64_t prev_delta = 0;
        for (size_t i = 1; i < n; i++) {
            prev_delta += unzigzag(get_varint(d, pos));
            ts += prev_delta;
            out[base + i].ts = ts;
        }
        // is-int bitmap
        size_t bitmap_pos = pos;
        pos += (n + 7) / 8;
        for (size_t i = 0; i < n; i++) {
            out[base + i].is_int =
                (d[bitmap_pos + i / 8] >> (i % 8)) & 1;
        }
        // values
        int64_t prev_int = 0;
        uint64_t prev_bits = 0;
        for (size_t i = 0; i < n; i++) {
            if (out[base + i].is_int) {
                prev_int += unzigzag(get_varint(d, pos));
                out[base + i].ival = prev_int;
                out[base + i].fval = static_cast<double>(prev_int);
            } else {
                prev_bits ^= get_varint(d, pos);
                double f;
                std::memcpy(&f, &prev_bits, 8);
                out[base + i].fval = f;
                out[base + i].ival = 0;
            }
        }
    }
};

// ---------------------------------------------------------------- series

struct Series {
    std::string key;            // opaque identity bytes from Python
    std::vector<Chunk> chunks;
    std::vector<Point> tail;    // uncompressed append buffer
    bool sorted = true;
    int64_t max_ts = INT64_MIN;
    std::mutex mu;

    size_t size() const {
        size_t total = tail.size();
        for (const auto& c : chunks) total += c.n;
        return total;
    }

    size_t bytes() const {
        size_t total = tail.capacity() * sizeof(Point);
        for (const auto& c : chunks) total += c.data.capacity();
        return total;
    }

    void append(int64_t ts, double fval, int64_t ival, uint8_t is_int) {
        std::lock_guard<std::mutex> lock(mu);
        if (ts <= max_ts) sorted = false;
        max_ts = std::max(max_ts, ts);
        tail.push_back(Point{ts, fval, ival, is_int});
        if (sorted && tail.size() >= CHUNK_POINTS) seal_locked();
    }

    void seal_locked() {
        if (tail.empty()) return;
        chunks.push_back(Chunk::compress(tail.data(), tail.size()));
        tail.clear();
        tail.shrink_to_fit();
    }

    // full materialization: decompress + sort + dedup (last wins).
    // dedup=false keeps duplicate timestamps (stable order, so the last
    // write for a timestamp stays last) — used by snapshot restore so a
    // dirty series round-trips as dirty instead of being silently healed.
    void materialize(std::vector<Point>& out, bool dedup = true) {
        out.clear();
        for (const auto& c : chunks) c.decompress(out);
        out.insert(out.end(), tail.begin(), tail.end());
        if (!sorted || chunks.size() > 1) {
            std::stable_sort(out.begin(), out.end(),
                             [](const Point& a, const Point& b) {
                                 return a.ts < b.ts;
                             });
        }
        // last-write-wins dedup
        if (dedup && !out.empty()) {
            size_t w = 0;
            for (size_t r = 1; r < out.size(); r++) {
                if (out[r].ts == out[w].ts) {
                    out[w] = out[r];
                } else {
                    out[++w] = out[r];
                }
            }
            out.resize(w + 1);
        }
    }

    // normalize: materialize then re-seal as sorted chunks
    void normalize() {
        std::lock_guard<std::mutex> lock(mu);
        if (sorted && chunks.size() <= 1) return;
        std::vector<Point> pts;
        materialize(pts);
        chunks.clear();
        for (size_t i = 0; i < pts.size(); i += CHUNK_POINTS) {
            size_t n = std::min(CHUNK_POINTS, pts.size() - i);
            chunks.push_back(Chunk::compress(pts.data() + i, n));
        }
        tail.clear();
        sorted = true;
    }
};

// ---------------------------------------------------------------- engine

struct Engine {
    std::vector<Series*> series;
    std::map<std::string, int64_t> by_key;
    std::mutex mu;

    ~Engine() {
        for (auto* s : series) delete s;
    }
};

thread_local std::vector<Point> g_scratch;

}  // namespace

EXPORT void* eng_create() { return new Engine(); }

EXPORT void eng_destroy(void* h) { delete static_cast<Engine*>(h); }

EXPORT int64_t eng_series(void* h, const uint8_t* key, int32_t key_len) {
    Engine* eng = static_cast<Engine*>(h);
    std::string k(reinterpret_cast<const char*>(key), key_len);
    std::lock_guard<std::mutex> lock(eng->mu);
    auto it = eng->by_key.find(k);
    if (it != eng->by_key.end()) return it->second;
    int64_t sid = static_cast<int64_t>(eng->series.size());
    Series* s = new Series();
    s->key = std::move(k);
    eng->series.push_back(s);
    eng->by_key.emplace(eng->series.back()->key, sid);
    return sid;
}

EXPORT int32_t eng_num_series(void* h) {
    Engine* eng = static_cast<Engine*>(h);
    std::lock_guard<std::mutex> lock(eng->mu);
    return static_cast<int32_t>(eng->series.size());
}

EXPORT int32_t eng_series_key(void* h, int64_t sid, uint8_t* out,
                              int32_t max_len) {
    Engine* eng = static_cast<Engine*>(h);
    Series* s = eng->series[sid];
    int32_t n = std::min<int32_t>(max_len,
                                  static_cast<int32_t>(s->key.size()));
    std::memcpy(out, s->key.data(), n);
    return static_cast<int32_t>(s->key.size());
}

EXPORT void eng_append(void* h, int64_t sid, int64_t ts, double fval,
                       int64_t ival, int32_t is_int) {
    Engine* eng = static_cast<Engine*>(h);
    eng->series[sid]->append(ts, fval, ival,
                             static_cast<uint8_t>(is_int));
}

EXPORT void eng_append_batch(void* h, int64_t sid, const int64_t* ts,
                             const double* fval, const int64_t* ival,
                             const uint8_t* is_int, int64_t n) {
    Engine* eng = static_cast<Engine*>(h);
    Series* s = eng->series[sid];
    std::lock_guard<std::mutex> lock(s->mu);
    for (int64_t i = 0; i < n; i++) {
        int64_t t = ts[i];
        if (t <= s->max_ts) s->sorted = false;
        s->max_ts = std::max(s->max_ts, t);
        s->tail.push_back(Point{t, fval[i], ival[i], is_int[i]});
    }
    if (s->sorted && s->tail.size() >= CHUNK_POINTS) s->seal_locked();
}

EXPORT int64_t eng_series_len(void* h, int64_t sid) {
    Engine* eng = static_cast<Engine*>(h);
    Series* s = eng->series[sid];
    std::lock_guard<std::mutex> lock(s->mu);
    return static_cast<int64_t>(s->size());
}

EXPORT int64_t eng_series_bytes(void* h, int64_t sid) {
    Engine* eng = static_cast<Engine*>(h);
    Series* s = eng->series[sid];
    std::lock_guard<std::mutex> lock(s->mu);
    return static_cast<int64_t>(s->bytes());
}

// Materialize [start, end] into caller buffers sized via eng_series_len.
// Returns the number of points written.
EXPORT int64_t eng_window(void* h, int64_t sid, int64_t start, int64_t end,
                          int64_t* out_ts, double* out_val,
                          int64_t* out_ival, uint8_t* out_isint,
                          int64_t max_n) {
    Engine* eng = static_cast<Engine*>(h);
    Series* s = eng->series[sid];
    std::lock_guard<std::mutex> lock(s->mu);
    s->materialize(g_scratch);
    auto lo = std::lower_bound(
        g_scratch.begin(), g_scratch.end(), start,
        [](const Point& p, int64_t v) { return p.ts < v; });
    auto hi = std::upper_bound(
        g_scratch.begin(), g_scratch.end(), end,
        [](int64_t v, const Point& p) { return v < p.ts; });
    int64_t n = 0;
    for (auto it = lo; it != hi && n < max_n; ++it, ++n) {
        out_ts[n] = it->ts;
        out_val[n] = it->fval;
        out_ival[n] = it->ival;
        out_isint[n] = it->is_int;
    }
    return n;
}

// Like eng_window over the full range, but duplicates survive (snapshot
// restore fidelity: a series persisted dirty must restore dirty).
EXPORT int64_t eng_window_raw(void* h, int64_t sid, int64_t* out_ts,
                              double* out_val, int64_t* out_ival,
                              uint8_t* out_isint, int64_t max_n) {
    Engine* eng = static_cast<Engine*>(h);
    Series* s = eng->series[sid];
    std::lock_guard<std::mutex> lock(s->mu);
    s->materialize(g_scratch, /*dedup=*/false);
    int64_t n = 0;
    for (auto it = g_scratch.begin(); it != g_scratch.end() && n < max_n;
         ++it, ++n) {
        out_ts[n] = it->ts;
        out_val[n] = it->fval;
        out_ival[n] = it->ival;
        out_isint[n] = it->is_int;
    }
    return n;
}

EXPORT int64_t eng_delete_range(void* h, int64_t sid, int64_t start,
                                int64_t end) {
    Engine* eng = static_cast<Engine*>(h);
    Series* s = eng->series[sid];
    std::lock_guard<std::mutex> lock(s->mu);
    s->materialize(g_scratch);
    std::vector<Point> kept;
    kept.reserve(g_scratch.size());
    int64_t removed = 0;
    for (const auto& p : g_scratch) {
        if (p.ts >= start && p.ts <= end) {
            removed++;
        } else {
            kept.push_back(p);
        }
    }
    s->chunks.clear();
    for (size_t i = 0; i < kept.size(); i += CHUNK_POINTS) {
        size_t n = std::min(CHUNK_POINTS, kept.size() - i);
        s->chunks.push_back(Chunk::compress(kept.data() + i, n));
    }
    s->tail.clear();
    s->sorted = true;
    s->max_ts = kept.empty() ? INT64_MIN : kept.back().ts;
    return removed;
}

EXPORT void eng_normalize(void* h, int64_t sid) {
    Engine* eng = static_cast<Engine*>(h);
    eng->series[sid]->normalize();
}

EXPORT int64_t eng_total_bytes(void* h) {
    Engine* eng = static_cast<Engine*>(h);
    std::lock_guard<std::mutex> lock(eng->mu);
    int64_t total = 0;
    for (auto* s : eng->series) total += s->bytes();
    return total;
}

// ---------------------------------------------------------------- save/load

EXPORT int32_t eng_save(void* h, const char* path) {
    Engine* eng = static_cast<Engine*>(h);
    FILE* f = std::fopen(path, "wb");
    if (!f) return -1;
    std::lock_guard<std::mutex> lock(eng->mu);
    uint64_t magic = 0x545044424E474E45ull;  // "ENGNBDPT"-ish tag
    std::fwrite(&magic, 8, 1, f);
    uint64_t n_series = eng->series.size();
    std::fwrite(&n_series, 8, 1, f);
    for (auto* s : eng->series) {
        std::lock_guard<std::mutex> slock(s->mu);
        s->seal_locked();
        uint64_t klen = s->key.size();
        std::fwrite(&klen, 8, 1, f);
        std::fwrite(s->key.data(), 1, klen, f);
        uint64_t n_chunks = s->chunks.size();
        std::fwrite(&n_chunks, 8, 1, f);
        uint8_t flags = s->sorted ? 1 : 0;
        std::fwrite(&flags, 1, 1, f);
        std::fwrite(&s->max_ts, 8, 1, f);
        for (const auto& c : s->chunks) {
            uint64_t n = c.n;
            uint64_t len = c.data.size();
            std::fwrite(&n, 8, 1, f);
            std::fwrite(&c.first_ts, 8, 1, f);
            std::fwrite(&c.last_ts, 8, 1, f);
            std::fwrite(&len, 8, 1, f);
            std::fwrite(c.data.data(), 1, len, f);
        }
    }
    std::fclose(f);
    return 0;
}

EXPORT void* eng_load(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return nullptr;
    uint64_t magic = 0;
    if (std::fread(&magic, 8, 1, f) != 1 ||
        magic != 0x545044424E474E45ull) {
        std::fclose(f);
        return nullptr;
    }
    Engine* eng = new Engine();
    uint64_t n_series = 0;
    std::fread(&n_series, 8, 1, f);
    for (uint64_t i = 0; i < n_series; i++) {
        Series* s = new Series();
        uint64_t klen = 0;
        std::fread(&klen, 8, 1, f);
        s->key.resize(klen);
        std::fread(s->key.data(), 1, klen, f);
        uint64_t n_chunks = 0;
        std::fread(&n_chunks, 8, 1, f);
        uint8_t flags = 1;
        std::fread(&flags, 1, 1, f);
        s->sorted = flags & 1;
        std::fread(&s->max_ts, 8, 1, f);
        for (uint64_t j = 0; j < n_chunks; j++) {
            Chunk c;
            uint64_t n = 0, len = 0;
            std::fread(&n, 8, 1, f);
            std::fread(&c.first_ts, 8, 1, f);
            std::fread(&c.last_ts, 8, 1, f);
            std::fread(&len, 8, 1, f);
            c.n = n;
            c.data.resize(len);
            std::fread(c.data.data(), 1, len, f);
            s->chunks.push_back(std::move(c));
        }
        int64_t sid = static_cast<int64_t>(eng->series.size());
        eng->series.push_back(s);
        eng->by_key.emplace(s->key, sid);
    }
    std::fclose(f);
    return eng;
}
