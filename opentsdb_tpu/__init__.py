"""opentsdb_tpu — a TPU-native time-series aggregation framework.

A from-scratch rebuild of OpenTSDB 2.4.1's capability surface (reference:
/root/reference, pure Java) with the query-time numeric pipeline executed as
batched JAX/XLA segment-reduction kernels instead of per-datapoint iterator
stacks (reference: src/core/AggregationIterator.java, src/core/Downsampler.java).

Layer map (mirrors SURVEY.md §1, re-designed TPU-first):

  utils/     Config (tsd.* keys), DateTime grammar        (ref: src/utils/)
  uid/       name<->UID dictionaries                      (ref: src/uid/UniqueId.java)
  storage/   columnar chunked series store                (ref: HBase schema, src/core/RowSeq.java)
  ops/       JAX kernels: downsample/aggregate/rate/lerp  (ref: src/core/Aggregators.java etc.)
  core/      TSDB facade, datapoint model                 (ref: src/core/TSDB.java)
  models/    query object model (TSQuery/TSSubQuery/pojo) (ref: src/core/TSQuery.java)
  query/     tag filters, planner, expressions            (ref: src/query/, src/core/TsdbQuery.java)
  parallel/  device mesh, shard_map pipelines             (ref: src/core/SaltScanner.java fan-out)
  tsd/       HTTP + telnet API surface                    (ref: src/tsd/)
  rollup/    rollup config/ingest/read (write-side API)   (ref: src/rollup/)
             storage/rollup.py holds the internal half:
             maintenance-built rollup LANES (docs/rollup.md)
  meta/      annotations, TSMeta/UIDMeta                  (ref: src/meta/)
  search/    lookup + search plugin                       (ref: src/search/)
  tree/      hierarchical namespace                       (ref: src/tree/)
  auth/      authentication/authorization SPIs            (ref: src/auth/)
  stats/     StatsCollector / QueryStats                  (ref: src/stats/)
  tools/     CLI: fsck/import/scan/uid/query              (ref: src/tools/)
"""

__version__ = "3.0.0-tpu"

SHORT_VERSION = "3.0.0"
