"""Authentication / authorization subsystem.

Reference behavior: /root/reference/src/auth/ — Authentication.java (:36
SPI: authenticateTelnet/authenticateHTTP/authorization), Authorization.java,
AuthState.java (:31 SUCCESS/UNAUTHORIZED/FORBIDDEN/REDIRECTED/ERROR),
Permissions.java (:25), Roles.java, AllowAllAuthenticatingAuthorizer.java
(:36 the bundled allow-everything impl), AuthenticationChannelHandler.java
(:50 first-message auth on new connections, telnet `auth` command,
AUTH_SUCCESS/AUTH_FAIL replies).
"""

from opentsdb_tpu.auth.core import (
    AuthState, AuthStatus, Authentication, Authorization, Permissions,
    Roles, AllowAllAuthenticatingAuthorizer)

__all__ = ["AuthState", "AuthStatus", "Authentication", "Authorization",
           "Permissions", "Roles", "AllowAllAuthenticatingAuthorizer"]
