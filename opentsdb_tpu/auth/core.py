"""Auth SPIs + the bundled allow-all implementation."""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class AuthStatus(enum.Enum):
    """AuthState.AuthStatus (:31)."""
    SUCCESS = "SUCCESS"
    UNAUTHORIZED = "UNAUTHORIZED"
    FORBIDDEN = "FORBIDDEN"
    REDIRECTED = "REDIRECTED"
    ERROR = "ERROR"


class Permissions(enum.Enum):
    """Permissions.java:25."""
    TELNET_PUT = "TELNET_PUT"
    HTTP_PUT = "HTTP_PUT"
    HTTP_QUERY = "HTTP_QUERY"
    CREATE_TAGK = "CREATE_TAGK"
    CREATE_TAGV = "CREATE_TAGV"
    CREATE_METRIC = "CREATE_METRIC"


class Roles:
    """A named permission grant set (Roles.java)."""

    def __init__(self, permissions: set[Permissions] | None = None):
        self.permissions: set[Permissions] = set(permissions or ())

    def grant(self, *permissions: Permissions) -> None:
        self.permissions.update(permissions)

    def revoke(self, *permissions: Permissions) -> None:
        self.permissions.difference_update(permissions)

    def has_permission(self, permission: Permissions) -> bool:
        return permission in self.permissions


@dataclass
class AuthState:
    """AuthState.java: the outcome of an authentication attempt."""
    user: str = ""
    status: AuthStatus = AuthStatus.ERROR
    message: str = ""
    token: bytes | None = None
    roles: Roles = field(default_factory=Roles)


class Authentication:
    """SPI (Authentication.java:36)."""

    def initialize(self, tsdb) -> None:
        pass

    def shutdown(self) -> None:
        pass

    def version(self) -> str:
        return "3.0.0"

    def collect_stats(self, collector) -> None:
        pass

    def authenticate_telnet(self, conn, command: list[str]) -> AuthState:
        raise NotImplementedError

    def authenticate_http(self, conn, request) -> AuthState:
        raise NotImplementedError

    def authorization(self) -> "Authorization | None":
        return None

    def is_ready(self, tsdb, conn) -> bool:
        """Whether the channel has already authenticated
        (Authentication.isReady :127)."""
        state = getattr(conn, "auth_state", None)
        if state is None:
            return False
        return state.status == AuthStatus.SUCCESS


class Authorization:
    """SPI (Authorization.java)."""

    def allow_query(self, state: AuthState, query) -> AuthState:
        raise NotImplementedError

    def has_role(self, state: AuthState, role: str) -> AuthState:
        raise NotImplementedError

    def has_permission(self, state: AuthState,
                       permission: Permissions) -> AuthState:
        raise NotImplementedError


class AllowAllAuthenticatingAuthorizer(Authentication, Authorization):
    """Grants everything (AllowAllAuthenticatingAuthorizer.java:36)."""

    GUEST_MESSAGE = "Guest User allowed by AllowAllAuthenticatingAuthorizer"

    def __init__(self):
        self.telnet_allowed = 0
        self.http_allowed = 0
        self.queries_allowed = 0

    def _guest(self) -> AuthState:
        roles = Roles(set(Permissions))
        return AuthState(user="guest", status=AuthStatus.SUCCESS,
                         message=self.GUEST_MESSAGE, roles=roles)

    def authenticate_telnet(self, conn, command: list[str]) -> AuthState:
        self.telnet_allowed += 1
        return self._guest()

    def authenticate_http(self, conn, request) -> AuthState:
        self.http_allowed += 1
        return self._guest()

    def authorization(self) -> Authorization:
        return self

    def allow_query(self, state: AuthState, query) -> AuthState:
        self.queries_allowed += 1
        return state

    def has_role(self, state: AuthState, role: str) -> AuthState:
        return state

    def has_permission(self, state: AuthState,
                       permission: Permissions) -> AuthState:
        return state

    def collect_stats(self, collector) -> None:
        collector.record("authentication.telnet.allowed",
                         self.telnet_allowed)
        collector.record("authentication.http.allowed", self.http_allowed)
        collector.record("authorization.queries.allowed",
                         self.queries_allowed)
