"""Build metadata (the BuildData equivalent the reference generates at
compile time and serves from /version and /api/version)."""

from __future__ import annotations

import socket

from opentsdb_tpu import __version__

VERSION = __version__
SHORT_REVISION = "unknown"
FULL_REVISION = "unknown"
TIMESTAMP = 0
REPO_STATUS = "MODIFIED"
USER = "tsdb"
HOST = socket.gethostname()
REPO = "opentsdb_tpu"
BRANCH = "main"


def _load_git():
    """Best-effort git metadata; falls back to the static defaults."""
    global SHORT_REVISION, FULL_REVISION
    import os
    import subprocess
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        rev = subprocess.run(
            ["git", "-C", root, "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=2)
        if rev.returncode == 0:
            FULL_REVISION = rev.stdout.strip()
            SHORT_REVISION = FULL_REVISION[:7]
    except Exception:
        # best-effort build metadata: no git / not a checkout is a
        # normal deployment shape, the placeholders above serve
        pass  # tsdblint: disable=except-swallow


_load_git()


def version_map() -> dict[str, str]:
    """The /api/version payload (RpcManager.java:660-669)."""
    return {
        "version": VERSION,
        "short_revision": SHORT_REVISION,
        "full_revision": FULL_REVISION,
        "timestamp": str(TIMESTAMP),
        "repo_status": REPO_STATUS,
        "user": USER,
        "host": HOST,
        "repo": REPO,
        "branch": BRANCH,
    }


def revision_string() -> str:
    return "opentsdb_tpu %s built from revision %s (%s)" % (
        VERSION, SHORT_REVISION, REPO_STATUS)


def build_string() -> str:
    return "Built on %s by %s@%s" % (TIMESTAMP, USER, HOST)
