from opentsdb_tpu.core.tsdb import TSDB

__all__ = ["TSDB"]
