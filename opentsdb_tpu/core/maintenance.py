"""Background maintenance: compaction flush, WAL fsync, snapshot cadence.

Reference behavior: CompactionQueue.java:95-165 — a daemon thread started
with the queue ("Start its own thread" :95) flushes dirty rows every
``tsd.storage.compaction.flush_interval`` seconds, at most
``max_concurrent_flushes`` per pass, speeding up by ``flush_speed``× when
the backlog exceeds ``min_flush_threshold`` (the throttle-on-backlog rule).
Errors land in an operator-visible counter, not on the next reader.

TPU-native extensions (ADVICE round-1 lows): the JSONL WAL gets a real
fsync cadence (``tsd.storage.wal_sync_interval``; line buffering alone
survives process crashes but not OS crashes), and full snapshots run off
the request path on ``tsd.storage.snapshot_interval``.
"""

from __future__ import annotations

import logging
import threading
import time

LOG = logging.getLogger(__name__)


class MaintenanceThread(threading.Thread):
    """One daemon thread driving all periodic storage upkeep."""

    TICK_SECONDS = 0.5

    def __init__(self, tsdb):
        super().__init__(name="TSDB-maintenance", daemon=True)
        self.tsdb = tsdb
        cfg = tsdb.config
        self.flush_interval = cfg.get_int(
            "tsd.storage.compaction.flush_interval")
        self.min_flush_threshold = cfg.get_int(
            "tsd.storage.compaction.min_flush_threshold")
        self.max_concurrent_flushes = cfg.get_int(
            "tsd.storage.compaction.max_concurrent_flushes")
        self.flush_speed = max(cfg.get_int(
            "tsd.storage.compaction.flush_speed"), 1)
        self.wal_sync_interval = cfg.get_int(
            "tsd.storage.wal_sync_interval")
        self.snapshot_interval = cfg.get_int(
            "tsd.storage.snapshot_interval")
        self.stats_interval = cfg.get_int("tsd.stats.interval")
        self.rollup_interval = cfg.get_int("tsd.rollup.interval")
        self._stop_event = threading.Event()
        self._next_flush = time.monotonic() + self.flush_interval
        self._next_sync = time.monotonic() + max(self.wal_sync_interval, 1)
        self._next_snapshot = time.monotonic() + max(
            self.snapshot_interval, 1)
        self._next_self_report = time.monotonic() + max(
            self.stats_interval, 1)
        self._next_rollup = time.monotonic() + max(
            self.rollup_interval, 1)
        self.flush_passes = 0
        self.rollup_passes = 0
        self.rollup_blocks_built = 0
        self.wal_syncs = 0
        self.snapshots = 0
        self.snapshot_errors = 0
        self.device_cache_refreshes = 0
        self.self_reports = 0
        self.self_report_errors = 0
        self.self_report_points = 0
        self.autotune_passes = 0
        self.health_passes = 0

    # ------------------------------------------------------------------ #

    def run(self) -> None:
        while not self._stop_event.wait(self.TICK_SECONDS):
            now = time.monotonic()
            try:
                self._maybe_flush(now)
                self._maybe_sync_wal(now)
                self._maybe_snapshot(now)
                self._maybe_refresh_device_cache()
                self._maybe_self_report(now)
                self._maybe_autotune(now)
                self._maybe_rollup(now)
                self._maybe_health(now)
            except Exception:
                LOG.exception("maintenance pass failed")

    def stop(self, final_flush: bool = True) -> None:
        self._stop_event.set()
        if self.is_alive():
            self.join(timeout=5.0)
        if final_flush:
            self.tsdb.store.compaction_queue.flush()

    # ------------------------------------------------------------------ #

    def _maybe_flush(self, now: float) -> None:
        queue = self.tsdb.store.compaction_queue
        backlog = len(queue)
        if now >= self._next_flush:
            self._next_flush = now + self.flush_interval
        elif backlog < self.min_flush_threshold:
            return
        if backlog == 0:
            return
        # Throttle-on-backlog (CompactionQueue.java:133-141): a backlog past
        # the threshold flushes a flush_speed-times bigger slice per pass.
        max_flushes = self.max_concurrent_flushes
        if backlog > self.min_flush_threshold:
            max_flushes *= self.flush_speed
        queue.flush(max_flushes)
        self.flush_passes += 1

    def _maybe_sync_wal(self, now: float) -> None:
        if self.wal_sync_interval <= 0 or now < self._next_sync:
            return
        self._next_sync = now + self.wal_sync_interval
        persistence = self.tsdb.persistence
        if persistence is not None:
            persistence.sync_wal()
            self.wal_syncs += 1

    def _maybe_refresh_device_cache(self) -> None:
        """Rebuild device-cache entries invalidated by ingest.

        Off the query path by design: queries on a stale metric fall back
        to the host build (fast miss) and queue it here; this thread pays
        the re-upload so ingest-heavy metrics regain device-cache hits
        without ever blocking a request."""
        cache = self.tsdb.device_cache
        if cache is not None:
            self.device_cache_refreshes += cache.refresh(self.tsdb.store)
        agg = self.tsdb.agg_cache
        if agg is not None:
            # hot aggregate blocks earn their device/HBM mirrors here,
            # off the query path (storage/agg_cache.py promote_pending)
            agg.promote_pending()

    def _maybe_self_report(self, now: float) -> None:
        """tsd.stats.interval cadence of the self-report loop
        (obs/selfreport.py): the daemon ingests its own tsd.* metrics
        so it is queryable about itself through its own pipeline."""
        if self.stats_interval <= 0 or now < self._next_self_report:
            return
        self._next_self_report = now + self.stats_interval
        from opentsdb_tpu.obs.selfreport import self_report
        try:
            self.self_report_points += self_report(self.tsdb)
            self.self_reports += 1
        except Exception:
            self.self_report_errors += 1
            LOG.exception("self-report pass failed")

    def _maybe_autotune(self, now: float) -> None:
        """tsd.costmodel.autotune.* cadence: one OnlineCalibrator tick
        (fit from the segment ring, install live constants, maybe
        explore — ops/calibrate.py).  The calibrator rate-limits
        itself; this just forwards the heartbeat."""
        calibrator = getattr(self.tsdb, "autotuner", None)
        if calibrator is not None and calibrator.tick(now):
            self.autotune_passes += 1

    def _maybe_rollup(self, now: float) -> None:
        """tsd.rollup.interval cadence: one rollup-lane maintenance
        pass (storage/rollup.py refresh — Storyboard selection under
        the byte budget, then block builds over the demanded ranges,
        with the spill pool bounding over-wall builds)."""
        lanes = getattr(self.tsdb, "rollup_lanes", None)
        if lanes is None or self.rollup_interval <= 0 \
                or now < self._next_rollup:
            return
        self._next_rollup = now + self.rollup_interval
        built = lanes.refresh(self.tsdb.store)
        self.rollup_passes += 1
        self.rollup_blocks_built += built

    def _maybe_health(self, now: float) -> None:
        """tsd.health.interval cadence: one health-engine pass
        (obs/health.py) judging the window since the previous pass.
        The engine rate-limits itself; this forwards the heartbeat."""
        engine = getattr(self.tsdb, "health", None)
        if engine is not None and engine.tick(now):
            self.health_passes += 1

    def _maybe_snapshot(self, now: float) -> None:
        if self.snapshot_interval <= 0 or now < self._next_snapshot:
            return
        self._next_snapshot = now + self.snapshot_interval
        if self.tsdb.persistence is None:
            return
        try:
            self.tsdb.snapshot()
            self.snapshots += 1
        except Exception:
            self.snapshot_errors += 1
            LOG.exception("periodic snapshot failed")

    # ------------------------------------------------------------------ #

    def collect_stats(self) -> dict[str, float]:
        return {
            "tsd.maintenance.flush_passes": self.flush_passes,
            "tsd.maintenance.wal_syncs": self.wal_syncs,
            "tsd.maintenance.snapshots": self.snapshots,
            "tsd.maintenance.snapshot_errors": self.snapshot_errors,
            "tsd.maintenance.device_cache_refreshes":
                self.device_cache_refreshes,
            "tsd.maintenance.self_reports": self.self_reports,
            "tsd.maintenance.self_report_errors": self.self_report_errors,
            "tsd.maintenance.self_report_points": self.self_report_points,
            "tsd.maintenance.autotune_passes": self.autotune_passes,
            "tsd.maintenance.health_passes": self.health_passes,
            "tsd.maintenance.rollup_passes": self.rollup_passes,
            "tsd.maintenance.rollup_blocks_built":
                self.rollup_blocks_built,
        }
