"""TSDB facade: write path, UID administration, query entry.

Reference behavior: /root/reference/src/core/TSDB.java (:87) — the god object
owning the storage client, the three UID dictionaries (:297-302), plugins and
the write path `addPoint` (:1051-1136) with timestamp/tag validation (:1313).
The HBase client + row-key codec are replaced by the columnar MemStore; the
3-byte UID scheme, validation rules, and second/millisecond timestamp
heuristic (Const.SECOND_MASK: ts >= 2^32 means milliseconds) are kept.
"""

from __future__ import annotations

import threading
import time

from opentsdb_tpu import __version__, SHORT_VERSION
from opentsdb_tpu.storage import MemStore
from opentsdb_tpu.storage.memstore import Annotation, SeriesKey, MAX_NUM_TAGS
from opentsdb_tpu.uid import (UniqueId, UniqueIdType, NoSuchUniqueName)
from opentsdb_tpu.utils.config import Config

SECOND_MASK = 0xFFFFFFFF00000000  # Const.java:19 — set bits mean milliseconds

_UNSET = object()  # lazily-built query_mesh sentinel


def normalize_timestamp_ms(timestamp: int | float) -> int:
    """Seconds-or-milliseconds heuristic (TSDB.addPointInternal).

    Values below 2^32 are treated as Unix seconds, larger as milliseconds.
    """
    ts = int(timestamp)
    if ts < 0:
        raise ValueError(
            "The timestamp must be positive and within the extent of a "
            "64-bit integer: %s" % timestamp)
    if ts & SECOND_MASK:
        return ts
    return ts * 1000


class TSDB:
    """The top-level handle: storage + UID dictionaries + write/query APIs."""

    def __init__(self, config: Config | None = None):
        self.config = config or Config()
        self._query_mesh = _UNSET
        self._query_limits = None
        self.maintenance = None
        # extra stats sources keyed by owner (RpcManager registers the
        # ingest/error/server counters); walked by /api/stats AND the
        # self-report loop through obs.selfreport.collect_all.
        # Initialized BEFORE initialize_plugins so a plugin may
        # register its own hook during startup.
        self.stats_hooks: dict = {}
        self._apply_precision_config()
        self._apply_kernel_modes()
        # chaos/failure-testing hooks (tsd.faults.config; no-op unless
        # armed) — installed before any storage or network touchpoint so
        # WAL-replay faults inject from the very first restore
        from opentsdb_tpu.utils import faults
        faults.install_from_config(self.config)
        self.metrics = UniqueId(
            UniqueIdType.METRIC,
            width=self.config.get_int("tsd.storage.uid.width.metric"),
            random_ids=self.config.get_bool("tsd.core.uid.random_metrics"))
        self.tag_names = UniqueId(
            UniqueIdType.TAGK,
            width=self.config.get_int("tsd.storage.uid.width.tagk"))
        self.tag_values = UniqueId(
            UniqueIdType.TAGV,
            width=self.config.get_int("tsd.storage.uid.width.tagv"))
        self.store = MemStore(
            salt_buckets=self.config.salt_buckets,
            fix_duplicates=self.config.fix_duplicates)
        from opentsdb_tpu.storage.device_cache import DeviceSeriesCache
        self.device_cache = (
            DeviceSeriesCache(
                self.config.get_int("tsd.query.device_cache.mb") * 2**20,
                self.config.get_int(
                    "tsd.query.device_cache.build_max_points"),
                fix_duplicates=self.config.fix_duplicates,
                batch_max_bytes=self.config.get_int(
                    "tsd.query.device_cache.batch_mb") * 2**20)
            if self.config.get_bool("tsd.query.device_cache.enable")
            else None)
        # partial-aggregate block cache (ROADMAP item 2): overlapping
        # sliding-window queries reuse per-(series, window) downsample
        # factors; the memstore write path marks the affected
        # (metric, sub-window) keys dirty as each write lands
        # (write-then-mark — see storage/memstore.py)
        from opentsdb_tpu.storage.agg_cache import AggregateCache
        self.agg_cache = (AggregateCache(self.config)
                          if self.config.get_bool("tsd.query.cache.enable")
                          else None)
        if self.agg_cache is not None:
            cache = self.agg_cache
            store = self.store
            self.store.add_mutation_listener(
                lambda metric, lo, hi: cache.note_mutation(
                    metric, lo, hi, store=store))
        # bounded partial-aggregate spill pool (ROADMAP item 4): backs
        # the out-of-core tiled executor (ops/tiling.py) so group-by
        # plans past the tsd.query.streaming.state_mb wall answer
        # instead of refusing; closed (files unlinked) at shutdown
        from opentsdb_tpu.storage.spill import SpillPool
        self.spill_pool = (
            SpillPool(
                self.config.get_int("tsd.query.spill.host_mb") * 2**20,
                self.config.get_int("tsd.query.spill.disk_mb") * 2**20,
                directory=self.config.get_string("tsd.query.spill.dir")
                or None)
            if self.config.get_bool("tsd.query.spill.enable") else None)
        # rollup lanes (ROADMAP item 2): maintenance-built coarse-
        # interval aggregate lanes (mergeable sum/count/min/max
        # partials) serve any fixed-interval query whose interval is a
        # multiple of a lane EXACTLY, in front of the agg-cache/tiled/
        # streamed exact paths; ingest-side invalidation rides the same
        # write-then-mark listener contract as the agg cache
        from opentsdb_tpu.storage.rollup import RollupLanes
        self.rollup_lanes = (RollupLanes(self.config)
                             if self.config.get_bool("tsd.rollup.enable")
                             else None)
        if self.rollup_lanes is not None:
            lanes = self.rollup_lanes
            self.store.add_mutation_listener(
                lambda metric, lo, hi: lanes.note_mutation(
                    metric, lo, hi))
        # flight recorder (obs/flightrec.py): the always-on diagnostics
        # ring every query-path subsystem feeds — admission verdicts,
        # cache/rollup consults, spills, autotune flips, breaker
        # transitions, deadline expiries, recompiles — served at
        # /api/diag and dumped at shutdown so a wedged session leaves
        # a black box
        from opentsdb_tpu.obs.flightrec import FlightRecorder
        self.flightrec = (FlightRecorder(self.config)
                          if self.config.get_bool("tsd.diag.enable")
                          else None)
        if self.flightrec is not None:
            # the compile-event feed (flightrec.start) is armed by the
            # SERVER, not here: subscribing flips jax_log_compiles
            # process-wide, which a bare library TSDB must not do —
            # same split as jaxprof.start_compile_counting
            self.stats_hooks["diag"] = self.flightrec.stats_hook
            if self.agg_cache is not None:
                self.agg_cache.recorder = self.flightrec
            if self.rollup_lanes is not None:
                self.rollup_lanes.recorder = self.flightrec
            if self.spill_pool is not None:
                self.spill_pool.recorder = self.flightrec
        # always-on latency attribution (obs/latattr.py): per-phase
        # stamps the RPC layer attaches to EVERY request fold into
        # bounded profiles keyed by (route, plan fingerprint, tenant),
        # served at /api/diag/latency — where the milliseconds went,
        # with tracing off
        from opentsdb_tpu.obs.latattr import LatencyAttribution
        self.latattr = (LatencyAttribution(self.config)
                        if self.config.get_bool("tsd.latattr.enable")
                        else None)
        if self.latattr is not None:
            self.stats_hooks["latattr"] = self.latattr.stats_hook
        # fused multi-query dispatch (query/batcher.py, ROADMAP item
        # 1): concurrent dispatch-bound plans (plan_decision path
        # "batched") coalesce into one stacked [Q, S, N] kernel with
        # host-side unpack; uncontended queries fall through as solo
        # dispatches with zero hold
        from opentsdb_tpu.query.batcher import DispatchBatcher
        self.dispatch_batcher = (
            DispatchBatcher(self.config, tsdb=self)
            if self.config.get_bool("tsd.query.batch.enable") else None)
        from opentsdb_tpu.rollup import RollupConfig, RollupStore
        self.rollup_config = RollupConfig.from_config(self.config)
        self.rollup_store = (
            RollupStore(self.rollup_config, self.config.salt_buckets)
            if self.rollup_config is not None else None)
        self.agg_tag_key = self.config.get_string("tsd.rollups.agg_tag_key")
        self.raw_agg_tag_value = self.config.get_string(
            "tsd.rollups.raw_agg_tag_value")
        self.tag_raw_data = self.config.get_bool("tsd.rollups.tag_raw")
        self.rollups_block_derived = self.config.get_bool(
            "tsd.rollups.block_derived")
        from opentsdb_tpu.histogram import (HistogramCodecManager,
                                            HistogramStore)
        self.histogram_manager = HistogramCodecManager.from_config(
            self.config)
        self.histogram_store = (HistogramStore()
                                if self.histogram_manager else None)
        from opentsdb_tpu.meta import MetaStore
        from opentsdb_tpu.tree import TreeStore
        self.meta_store = MetaStore()
        self.tree_store = TreeStore()
        self.tree_processing = self.config.get_bool(
            "tsd.core.tree.enable_processing")
        self.rt_publisher = None    # RTPublisher plugin
        self.storage_exception_handler = None
        self.search_plugin = None   # wired by plugins.initialize_plugins
        self.enable_tsuid_tracking = (
            self.config.get_bool("tsd.core.meta.enable_tsuid_tracking")
            or self.config.get_bool(
                "tsd.core.meta.enable_tsuid_incrementing"))
        self.enable_realtime_ts = self.config.get_bool(
            "tsd.core.meta.enable_realtime_ts")
        self.enable_realtime_uid = self.config.get_bool(
            "tsd.core.meta.enable_realtime_uid")
        if self.enable_realtime_uid:
            for kind, table in (("metric", self.metrics),
                                ("tagk", self.tag_names),
                                ("tagv", self.tag_values)):
                table.on_create = self._make_uid_meta_hook(kind, table)
        self.write_filter = None    # WriteableDataPointFilterPlugin
        self.authentication = None
        self.startup_plugin = None
        self.mode = self.config.get_string("tsd.mode")  # rw / ro / wo
        # online costmodel calibration (ops/calibrate.py): fits the
        # kernel-strategy constants from the live segment ring on the
        # maintenance cadence; ticked by MaintenanceThread, persisted
        # at shutdown
        self.autotuner = None
        if self.config.get_bool("tsd.costmodel.autotune.enable"):
            from opentsdb_tpu.ops.calibrate import OnlineCalibrator
            self.autotuner = OnlineCalibrator(self)
        # health engine (obs/health.py): declared invariants evaluated
        # on the maintenance cadence into per-subsystem verdicts at
        # /api/diag/health — the chaos_soak post-heal gate.  Needs
        # start_time, so it initializes below after the clock is set.
        self.health = None
        from opentsdb_tpu.plugins import initialize_plugins
        initialize_plugins(self)
        self.start_time = time.time()
        if self.config.get_bool("tsd.health.enable"):
            from opentsdb_tpu.obs.health import HealthEngine
            self.health = HealthEngine(self)
            self.stats_hooks["health"] = self.health.stats_hook
        self._stats_lock = threading.Lock()
        # Serializes ingest against snapshots: writers hold it briefly per
        # record; snapshot() holds it for its stop-the-world walk so no
        # journaled write can fall between the state capture and WAL reset.
        self._ingest_lock = threading.RLock()
        # guarded-by: _stats_lock
        self.datapoints_added = 0
        self.illegal_arguments = 0  # guarded-by: _stats_lock
        self.unknown_metrics = 0  # guarded-by: _stats_lock
        # Restore LAST: WAL replay drives the full _apply_* paths, which
        # touch stats/meta/tree state initialized above.
        # _replaying is a property: the process-wide flag (startup WAL
        # replay) OR a per-thread flag (replication apply — concurrent
        # ingest on other threads must keep journaling)
        self._replay_tls = threading.local()
        self._replaying = False   # WAL replay bypasses the ro-mode gate
        # sharded ownership + WAL-shipping replication
        # (tsd/replication.py, docs/replication.md) — constructed
        # BEFORE the restore below so replayed "rr" records can rebuild
        # the per-origin catch-up positions
        self.replication = None
        if self.config.get_bool("tsd.network.cluster.shard.enable"):
            from opentsdb_tpu.tsd.replication import ReplicationManager
            self.replication = ReplicationManager(self)
            self.stats_hooks["replication"] = self.replication.stats_hook
        self.persistence = None
        storage_dir = self.config.get_string("tsd.storage.directory")
        if storage_dir:
            from opentsdb_tpu.storage.persist import DiskPersistence
            self.persistence = DiskPersistence(self, storage_dir)
            self.persistence.restore()

    @property
    def _replaying(self) -> bool:
        return self._replaying_flag or getattr(self._replay_tls, "on",
                                               False)

    @_replaying.setter
    def _replaying(self, value: bool) -> None:
        self._replaying_flag = value

    # ------------------------------------------------------------------ #
    # Write path (TSDB.addPoint :1051)                                   #
    # ------------------------------------------------------------------ #

    def _apply_precision_config(self) -> None:
        """Enforce tsd.tpu.precision.x64 (default true): ms-resolution
        timestamps are int64, and with jax_enable_x64 off jnp.int64
        silently degrades to int32 — every timestamp past 2^31 ms
        truncates.  The ops package enables x64 at import; with the key
        true this RE-ENABLES it per TSDB construction (flipping the
        process-global flag back on if an embedder turned it off), so
        queries never run in the silently-truncating state.  With the
        key false nothing is re-asserted and the downsample planners'
        require_x64 guard raises at query-plan time instead (the
        operator owns that choice and gets a warning here)."""
        import jax

        from opentsdb_tpu import ops  # noqa: F401  (enables x64 on import)
        if self.config.get_bool("tsd.tpu.precision.x64"):
            if not jax.config.jax_enable_x64:
                jax.config.update("jax_enable_x64", True)
        else:
            import logging
            logging.getLogger("tsdb").warning(
                "tsd.tpu.precision.x64=false: x64 is not re-asserted for "
                "this TSDB; if jax_enable_x64 is turned off the "
                "downsample planners refuse int64 window math "
                "(ops.downsample.require_x64) rather than truncate "
                "ms timestamps")

    def _apply_kernel_modes(self) -> None:
        """Apply tsd.query.kernel.* hot-path strategy config (operator
        counterpart of the TSDB_*_MODE env toggles; empty = leave the
        module default / env choice alone).

        PROCESS-GLOBAL: the strategies are trace-time module state (a
        per-instance form would thread through every jitted pipeline's
        static args), so the last constructed TSDB with a NON-EMPTY key
        wins for the whole process — matching the one-TSDB-per-process
        production shape.  Embedders running several TSDBs must config
        them identically or leave the keys empty.  No-op when the value
        already matches (the setters flush every dependent jit cache)."""
        from opentsdb_tpu.ops import downsample as _ds
        from opentsdb_tpu.ops import group_agg as _ga
        for key, setter, current in (
                ("tsd.query.kernel.scan_mode", _ds.set_scan_mode,
                 lambda: _ds._SCAN_MODE),
                ("tsd.query.kernel.search_mode", _ds.set_search_mode,
                 lambda: _ds._SEARCH_MODE),
                ("tsd.query.kernel.extreme_mode", _ds.set_extreme_mode,
                 lambda: _ds._EXTREME_MODE),
                ("tsd.query.kernel.group_reduce_mode",
                 _ga.set_group_reduce_mode,
                 lambda: _ga._GROUP_REDUCE_MODE)):
            value = self.config.get_string(key)
            if value and value != current():
                setter(value)   # invalid values raise at startup, loudly
        ratio = self.config.get_string(
            "tsd.query.kernel.stream_segment_ratio")
        if ratio:
            from opentsdb_tpu.ops import streaming as _st
            _st.set_segment_chunk_ratio(float(ratio))  # bad float: loud
        raw = self.config.get_string("tsd.query.kernel.platform_guard")
        if raw:   # empty keeps the module default (on) / test override
            token = raw.strip().lower()
            if token in ("true", "1", "yes"):
                guard = True
            elif token in ("false", "0", "no"):
                guard = False
            else:   # a typo must not silently disable the CPU guard
                raise ValueError(
                    "tsd.query.kernel.platform_guard must be "
                    "true/false (got %r)" % raw)
            if guard != _ds._PLATFORM_MODE_GUARD:
                _ds.set_platform_mode_guard(guard)

    def check_timestamp_and_tags(self, metric: str, timestamp: int | float,
                                 value, tags: dict[str, str]) -> None:
        """Validation rules of TSDB.checkTimestampAndTags (:1313)."""
        if not tags:
            raise ValueError(
                "Need at least one tag (metric=%s, ts=%s)" % (metric, timestamp))
        if len(tags) > MAX_NUM_TAGS:
            raise ValueError(
                "Too many tags: %d maximum allowed: %d" %
                (len(tags), MAX_NUM_TAGS))
        if int(timestamp) < 0:
            raise ValueError("Invalid timestamp: %s" % timestamp)

    def add_point(self, metric: str, timestamp: int | float, value,
                  tags: dict[str, str]) -> None:
        """Store one datapoint; value may be int, float, or numeric string.

        With sharded replication armed the point first routes to its
        shard's accepting member (forwarded in one hop when that is a
        peer); a locally-accepted point journals with its shard id and
        ships synchronously to the shard's replicas before returning —
        the ack-path durability contract (tsd/replication.py)."""
        repl = self.replication
        if repl is not None and not self._replaying:
            if repl.should_route() \
                    and repl.route_point(metric, timestamp, value, tags):
                return
            # accepting member (owner, failover member, or the routed
            # hop's receiver): apply + journal with the shard id, then
            # ship to the shard's replicas before acking
            shard = repl.shard_of(metric, tags)
            entry = None
            with self._ingest_lock:
                self._apply_point(metric, timestamp, value, tags)
                if self.persistence is not None:
                    rec = {"k": "p", "m": metric, "t": timestamp,
                           "v": value, "g": dict(tags), "sh": shard}
                    seq, crc = self.persistence.journal(rec)  # order-event: wal-append
                    entry = (seq, crc, shard, rec)
            if entry is not None:
                # order: wal-append before replica-ship
                repl.on_committed([entry])
            return
        with self._ingest_lock:
            self._apply_point(metric, timestamp, value, tags)
            if self.persistence is not None:
                self.persistence.journal({"k": "p", "m": metric,  # order-event: wal-append
                                          "t": timestamp, "v": value,
                                          "g": dict(tags)})

    def _validate_put_dp(self, dp: dict):
        """Per-point /api/put validation, storage-free (no UID creation):
        required fields, value parse + Java-long range, timestamp/tags.
        Returns (metric, tags, is_int, num); raises the same error the
        stored path would."""
        for field in ("metric", "timestamp", "value", "tags"):
            if field not in dp or dp[field] in (None, "", {}):
                raise ValueError("Missing required field: %s" % field)
        metric = dp["metric"]
        tags = dict(dp["tags"])
        is_int, num = parse_value(dp["value"])
        if is_int and not (-(1 << 63) <= num < (1 << 63)):
            # beyond Java long (the reference's parseLong rejects it per
            # point); without this check the group's int64 column build
            # would fail EVERY point of the series
            raise ValueError("Invalid value, out of long range: %r"
                             % dp["value"])
        self.check_timestamp_and_tags(metric, dp["timestamp"], num, tags)
        return metric, tags, is_int, num

    def add_points_bulk(self, dps: list[dict]
                        ) -> tuple[int, list[tuple[int, Exception]]]:
        """Vectorized bulk ingest for POST /api/put bodies.

        The reference writes each point through one addPoint call
        (PutDataPointRpc.processDataPoint :309 -> TSDB.addPoint :1051);
        per-point that costs a parse, a validation, a key resolution, a
        lock and a journal write.  Here points validate individually (so
        per-point error reporting survives) but group by series, and each
        series takes ONE lock + ONE columnar append_batch; the WAL gets
        one record per request.  Returns (success_count,
        [(index, exception), ...]) with indexes into `dps`.

        With sharded replication armed the body partitions by accepting
        member first (tsd/replication.py ingest_bulk): remote groups
        forward in one POST each, local groups land per shard so every
        WAL record carries one shard id and ships to that shard's
        replicas.
        """
        repl = self.replication
        if repl is not None and not self._replaying:
            return repl.ingest_bulk(dps)
        return self._add_points_bulk_local(dps)

    def _add_points_bulk_local(self, dps: list[dict], shard: int | None
                               = None) -> tuple[int, list]:
        """The locally-accepted bulk path.  ``shard`` (replication only)
        stamps the journaled record and ships it to the shard's
        replicas after commit."""
        import numpy as np

        if self.mode == "ro" and not self._replaying:
            # Validation errors first, RO for the rest — matching the
            # per-point path, where parsing reports before add_point hits
            # the RO gate (ADVICE r3): error classes and the RPC layer's
            # accounting (illegal_arguments vs hbase_errors, SEH spillway,
            # 400 + summary) must not depend on the ingest path taken.
            exc = RuntimeError("TSD is in read-only mode, writes rejected")
            ro_errors: list[tuple[int, Exception]] = []
            for i, dp in enumerate(dps):
                try:
                    self._validate_put_dp(dp)
                except Exception as e:
                    ro_errors.append((i, e))
                else:
                    ro_errors.append((i, exc))
            return 0, ro_errors
        errors: list[tuple[int, Exception]] = []
        # key -> (ts_ms, float, exact-int, is_int, dp index, raw dp,
        #         publish args) column lists
        groups: dict = {}
        key_cache: dict = {}
        success = 0
        for i, dp in enumerate(dps):
            try:
                metric, tags, is_int, num = self._validate_put_dp(dp)
                if self.write_filter is not None and \
                        not self.write_filter.allow(metric, dp["timestamp"],
                                                    num, tags):
                    success += 1   # silently dropped, like _apply_point
                    continue
                ts_ms = normalize_timestamp_ms(dp["timestamp"])
                if self.rollup_store is not None and self.tag_raw_data:
                    tags[self.agg_tag_key] = self.raw_agg_tag_value
                ck = (metric, tuple(sorted(tags.items())))
                key = key_cache.get(ck)
                if key is None:
                    key = self._series_key(metric, tags, create=True)
                    key_cache[ck] = key
                bucket = groups.get(key)
                if bucket is None:
                    bucket = groups[key] = ([], [], [], [], [], [], [])
                bucket[0].append(ts_ms)
                bucket[1].append(float(num))
                bucket[2].append(int(num) if is_int else 0)
                bucket[3].append(is_int)
                bucket[4].append(i)
                bucket[5].append(dp)
                if self.rt_publisher is not None:
                    bucket[6].append((metric, ts_ms, num, tags, key))
                success += 1
            except Exception as e:
                errors.append((i, e))
        stored: list[dict] = []    # journal only what actually landed
        publish: list = []
        entry = None
        with self._ingest_lock:
            for key, (tss, fvals, ivals, isints, idxs, raw,
                      pubs) in groups.items():
                try:
                    ts_arr = np.asarray(tss, np.int64)
                    self.store.add_batch(
                        key, ts_arr, np.asarray(fvals, np.float64),
                        np.asarray(isints, bool),
                        ival=np.asarray(ivals, np.int64))
                except Exception as e:
                    # storage failure: every point of this series batch
                    # reports it (SEH spillway parity with the per-point
                    # path's storeIntoDB error callbacks)
                    errors.extend((i, e) for i in idxs)
                    success -= len(idxs)
                    continue
                with self._stats_lock:
                    self.datapoints_added += len(tss)
                self._track_meta(key, int(ts_arr.max()), n=len(tss))
                stored.extend(raw)
                publish.extend(pubs)
            if self.persistence is not None and stored \
                    and not self._replaying:
                rec = {"k": "pb", "d": stored}
                if shard is not None:
                    rec["sh"] = shard
                seq, crc = self.persistence.journal(rec)  # order-event: wal-append
                if shard is not None:
                    entry = (seq, crc, shard, rec)
        if entry is not None and self.replication is not None:
            # order: wal-append before replica-ship
            self.replication.on_committed([entry])
        for metric, ts_ms, num, tags, key in publish:
            self.rt_publisher.publish_data_point(metric, ts_ms, num, tags,
                                                 key.tsuid())
        errors.sort(key=lambda t: t[0])
        return success, errors

    def add_points_bulk_native(self, body: bytes):
        """Native-parser fast path for a raw /api/put JSON body.

        The C++ parser (native/engine.cpp eng_put_parse) does the per-point
        work — JSON walk, validation with the Python path's exact error
        strings, value classification, timestamp normalization, series-key
        canonicalization — in one pass over the body bytes; Python cost
        drops to O(distinct series).  Returns
        (success, [(index, exception)], spans[n, 2]) or None when the fast
        path does not apply: native library absent, malformed JSON (the
        Python path owns the user-visible parse error), a construct the
        parser refuses to mirror, or a TSDB feature that needs per-point
        Python hooks (write filter, real-time publisher, raw-data rollup
        tagging).  With persistence on, the raw body journals as one
        "pj" WAL record; replay re-parses it through this same path.
        """
        if not self._native_ingest_eligible():
            return None
        body_text = None
        if self.persistence is not None and not self._replaying:
            try:
                # journaled verbatim as a "pj" record; replay re-parses
                # through this same path (deterministic per-point outcome)
                body_text = body.decode("utf-8")
            except UnicodeDecodeError:
                return None
        from opentsdb_tpu.storage.native_engine import parse_put_body
        parsed = parse_put_body(body)
        if parsed is None:
            return None
        success, errors = self._ingest_parsed_columns(
            parsed, {"k": "pj", "b": body_text}
            if body_text is not None else None)
        return success, errors, parsed.spans

    def _native_ingest_eligible(self) -> bool:
        """True when no TSDB feature needs per-point Python hooks.
        Sharded replication needs per-point shard routing, so its
        daemons take the Python bulk path (which partitions by owner)."""
        return (self.write_filter is None and self.rt_publisher is None
                and self.replication is None
                and not (self.rollup_store is not None
                         and self.tag_raw_data))

    def _ingest_parsed_columns(self, parsed, journal_record
                               ) -> tuple[int, list]:
        """Land a native-parsed column batch: per-group key resolution,
        columnar appends, stats/meta, WAL.  Shared by the JSON-body and
        telnet-block fast paths.  Returns (success, [(index, exc)])."""
        import numpy as np

        if self.mode == "ro" and not self._replaying:
            # Per-point path parity: points whose parse already failed
            # report their ValueError/TypeError (validation runs before
            # the RO gate there); only parseable points get the RO error
            # (ADVICE r3).
            exc = RuntimeError("TSD is in read-only mode, writes rejected")
            ro_errors: dict[int, Exception] = {
                i: ValueError(msg) if kind == "ValueError"
                else TypeError(msg)
                for i, kind, msg in parsed.errors}
            return 0, [(i, ro_errors.get(i, exc)) for i in range(parsed.n)]
        errors: list[tuple[int, Exception]] = [
            (i, ValueError(msg) if kind == "ValueError" else TypeError(msg))
            for i, kind, msg in parsed.errors]
        success = parsed.n - len(errors)

        # one key resolution per DISTINCT series; a resolution failure
        # (e.g. unknown metric with auto-create off) fails every point of
        # that group, exactly like the per-point path would
        keys: list = []
        for metric, tags in parsed.group_keys:
            try:
                keys.append(self._series_key(metric, tags, create=True))
            except Exception as e:
                keys.append(e)

        order = np.argsort(parsed.group, kind="stable")
        order = order[parsed.group[order] >= 0]
        bounds = np.searchsorted(parsed.group[order],
                                 np.arange(len(keys) + 1))
        with self._ingest_lock:
            for g in range(len(keys)):
                idx = order[bounds[g]:bounds[g + 1]]
                if not len(idx):
                    continue
                key = keys[g]
                if isinstance(key, Exception):
                    if isinstance(key, NoSuchUniqueName):
                        # stat parity: the per-point path increments
                        # unknown_metrics once per failing POINT; the
                        # one resolution above already counted 1
                        with self._stats_lock:
                            self.unknown_metrics += len(idx) - 1
                    errors.extend((int(i), key) for i in idx)
                    success -= len(idx)
                    continue
                ts_arr = parsed.ts[idx]
                try:
                    self.store.add_batch(key, ts_arr, parsed.fval[idx],
                                         parsed.isint[idx],
                                         ival=parsed.ival[idx])
                except Exception as e:
                    errors.extend((int(i), e) for i in idx)
                    success -= len(idx)
                    continue
                with self._stats_lock:
                    self.datapoints_added += len(idx)
                self._track_meta(key, int(ts_arr.max()), n=len(idx))
            if journal_record is not None and success > 0:
                # inside the ingest lock: a snapshot cannot slip between
                # the appends above and this journal line
                self.persistence.journal(journal_record)  # order-event: wal-append
        errors.sort(key=lambda t: t[0])
        return success, errors

    def add_telnet_batch_native(self, block: bytes):
        """Native fast path for a block of telnet `put` lines.

        Returns (telnet_batch, point_errors: dict[index, Exception]) or
        None when ineligible (same gates as add_points_bulk_native; the
        caller then walks lines through the per-line handler).  Lines the
        parser refuses (non-ASCII, exotic grammar) are marked FALLBACK in
        the returned batch and cost only themselves.  With persistence
        on, the raw block journals as one "pt" record.
        """
        if not self._native_ingest_eligible():
            return None
        from opentsdb_tpu.storage.native_engine import (parse_telnet_block,
                                                        LINE_FALLBACK)
        tb = parse_telnet_block(block)
        if tb is None:
            return None
        record = None
        if self.persistence is not None and not self._replaying:
            # journal only the natively-handled lines: FALLBACK lines
            # journal their own per-point "p" records when the per-line
            # handler lands them, so including them here would double-
            # ingest on a library-less replay
            data = block
            if (tb.status == LINE_FALLBACK).any():
                data = b"\n".join(
                    bytes(block[int(s):int(e)])
                    for st, (s, e) in zip(tb.status, tb.spans)
                    if st != LINE_FALLBACK)
            try:
                record = {"k": "pt", "b": data.decode("utf-8")}
            except UnicodeDecodeError:
                return None
        _, errors = self._ingest_parsed_columns(tb.points, record)
        return tb, dict(errors)

    def _apply_point(self, metric: str, timestamp: int | float, value,
                     tags: dict[str, str]) -> None:
        is_int, num = parse_value(value)
        self.check_timestamp_and_tags(metric, timestamp, num, tags)
        if self.mode == "ro" and not self._replaying:
            # WAL replay must restore data even when the daemon was
            # restarted read-only; the gate applies to new writes only.
            # Gate AFTER validation: every ingest path (per-point, bulk,
            # native columnar) must classify a malformed point the same
            # way regardless of mode (ADVICE r3).
            raise RuntimeError("TSD is in read-only mode, writes rejected")
        if self.write_filter is not None and not self.write_filter.allow(
                metric, timestamp, num, tags):
            return
        ts_ms = normalize_timestamp_ms(timestamp)
        if self.rollup_store is not None and self.tag_raw_data:
            # tsd.rollups.tag_raw: mark raw series with the agg tag so they
            # coexist with pre-aggregates (TSDB.addPointInternal :1471-1480).
            tags = dict(tags)
            tags[self.agg_tag_key] = self.raw_agg_tag_value
        key = self._series_key(metric, tags, create=True)
        self.store.add_point(key, ts_ms, num, is_int)
        with self._stats_lock:
            self.datapoints_added += 1
        self._track_meta(key, ts_ms)
        if self.rt_publisher is not None:
            self.rt_publisher.publish_data_point(metric, ts_ms, num, tags,
                                                 key.tsuid())

    def _series_key(self, metric: str, tags: dict[str, str],
                    create: bool) -> SeriesKey:
        if create:
            if self.config.auto_metric:
                metric_uid = self.metrics.get_or_create_id(metric)
            else:
                try:
                    metric_uid = self.metrics.get_id(metric)
                except NoSuchUniqueName:
                    with self._stats_lock:
                        self.unknown_metrics += 1
                    raise
            auto_tagk = self.config.get_bool("tsd.core.auto_create_tagks")
            auto_tagv = self.config.get_bool("tsd.core.auto_create_tagvs")
            uid_tags = {}
            for k, v in tags.items():
                ku = (self.tag_names.get_or_create_id(k) if auto_tagk
                      else self.tag_names.get_id(k))
                vu = (self.tag_values.get_or_create_id(v) if auto_tagv
                      else self.tag_values.get_id(v))
                uid_tags[ku] = vu
        else:
            metric_uid = self.metrics.get_id(metric)
            uid_tags = {self.tag_names.get_id(k): self.tag_values.get_id(v)
                        for k, v in tags.items()}
        return SeriesKey.make(metric_uid, uid_tags)

    # ------------------------------------------------------------------ #
    # Histogram write path (TSDB.addHistogramPoint :1171)                #
    # ------------------------------------------------------------------ #

    def add_histogram_point_raw(self, metric: str, timestamp: int | float,
                                codec_id: int, payload: str,
                                tags: dict[str, str]) -> None:
        """Base64 binary histogram ingest (telnet `histogram`,
        HistogramPojo.getBytes)."""
        if self.histogram_manager is None:
            raise ValueError("histograms are not configured "
                             "(tsd.core.histograms.config)")
        import base64
        codec = self.histogram_manager.get_codec(codec_id)
        hist = codec.decode(base64.b64decode(payload), includes_id=False)
        with self._ingest_lock:
            self._store_histogram(metric, timestamp, hist, tags)
            if self.persistence is not None:
                self.persistence.journal({"k": "h", "m": metric,
                                          "t": timestamp,
                                          "d": hist.to_json(),
                                          "g": dict(tags)})

    def add_histogram_point_json(self, metric: str, timestamp: int | float,
                                 dp: dict, tags: dict[str, str]) -> None:
        with self._ingest_lock:
            self._apply_histogram_json(metric, timestamp, dp, tags)
            if self.persistence is not None:
                journal_dp = {k: v for k, v in dp.items()
                              if k in ("id", "value", "buckets",
                                       "underflow", "overflow")}
                self.persistence.journal({"k": "h", "m": metric,
                                          "t": timestamp,
                                          "d": journal_dp,
                                          "g": dict(tags)})

    def _apply_histogram_json(self, metric: str, timestamp: int | float,
                              dp: dict, tags: dict[str, str]) -> None:
        """JSON histogram ingest (POST /api/histogram, HistogramPojo):
        either base64 `value` or explicit `buckets` {"lo,hi": count}."""
        if self.histogram_manager is None:
            raise ValueError("histograms are not configured "
                             "(tsd.core.histograms.config)")
        from opentsdb_tpu.histogram import SimpleHistogram
        codec_id = int(dp.get("id", 0))
        self.histogram_manager.get_codec(codec_id)  # validate the id
        if dp.get("value"):
            hist = SimpleHistogram.from_base64(str(dp["value"]),
                                               include_id=False)
            hist.id = codec_id
        elif "buckets" in dp:
            # Empty bucket maps are valid: the mass may sit entirely in
            # underflow/overflow.
            hist = SimpleHistogram.from_pojo(dp, codec_id)
        else:
            raise ValueError("Missing histogram value or buckets")
        self._store_histogram(metric, timestamp, hist, tags)

    def _store_histogram(self, metric: str, timestamp: int | float, hist,
                         tags: dict[str, str]) -> None:
        self.check_timestamp_and_tags(metric, timestamp, None, tags)
        if self.mode == "ro" and not self._replaying:
            # WAL replay must restore data even when the daemon was
            # restarted read-only; the gate applies to new writes only.
            # Gate after validation, like _apply_point (ADVICE r3).
            raise RuntimeError("TSD is in read-only mode, writes rejected")
        if self.write_filter is not None:
            # WriteableDataPointFilterPlugin gate (TSDB.java:1301-1306,
            # allowHistogramPoint; filters without a histogram hook use the
            # scalar gate).
            allow = getattr(self.write_filter, "allow_histogram",
                            self.write_filter.allow)
            if not allow(metric, timestamp, hist, tags):
                return
        ts_ms = normalize_timestamp_ms(timestamp)
        key = self._series_key(metric, tags, create=True)
        self.histogram_store.add_point(key, ts_ms, hist)
        with self._stats_lock:
            self.datapoints_added += 1
        self._track_meta(key, ts_ms)
        if self.rt_publisher is not None:
            publish = getattr(self.rt_publisher, "publish_histogram_point",
                              None)
            if publish is not None:
                publish(metric, ts_ms, hist, tags, key.tsuid())

    # ------------------------------------------------------------------ #
    # Rollup write path (TSDB.addAggregatePoint :1359-1457)              #
    # ------------------------------------------------------------------ #

    def add_aggregate_point(self, metric: str, timestamp: int | float, value,
                            tags: dict[str, str], is_groupby: bool,
                            interval: str | None, rollup_aggregator: str | None,
                            groupby_aggregator: str | None = None) -> None:
        with self._ingest_lock:
            self._apply_aggregate_point(metric, timestamp, value, tags,
                                        is_groupby, interval,
                                        rollup_aggregator,
                                        groupby_aggregator)
            if self.persistence is not None:
                self.persistence.journal({
                    "k": "r", "m": metric, "t": timestamp, "v": value,
                    "g": dict(tags), "gb": is_groupby, "i": interval,
                    "a": rollup_aggregator, "ga": groupby_aggregator})

    def _apply_aggregate_point(self, metric: str, timestamp: int | float,
                               value, tags: dict[str, str], is_groupby: bool,
                               interval: str | None,
                               rollup_aggregator: str | None,
                               groupby_aggregator: str | None = None) -> None:
        """Store one rolled-up and/or pre-aggregated datapoint.

        Reference behavior (TSDB.addAggregatePointInternal): with `interval`
        the value goes to that interval's rollup lane under
        `rollup_aggregator`; with `is_groupby` it goes to a pre-agg lane and
        the aggregate tag (tsd.rollups.agg_tag_key) is forced to the
        uppercased group-by aggregator.  NaN/Inf floats are rejected.
        """
        if self.rollup_store is None:
            raise RuntimeError("Rollups are not enabled "
                               "(tsd.rollups.enable=false)")
        is_int, num = parse_value(value)
        if interval:
            # Raises NoSuchRollupForInterval for unconfigured intervals.
            self.rollup_config.get_rollup_interval(interval)
            if not rollup_aggregator:
                raise ValueError("Missing rollup aggregator for interval %s"
                                 % interval)
            if (self.rollups_block_derived
                    and rollup_aggregator.upper() in ("AVG", "DEV")):
                # tsd.rollups.block_derived (TSDB.java:1562-1569)
                raise ValueError(
                    "Derived rollup aggregations are not allowed: %s"
                    % rollup_aggregator)
            self.rollup_config.get_id_for_aggregator(rollup_aggregator)
        elif not is_groupby:
            raise ValueError(
                "Either an interval or the groupby flag is required")
        tags = dict(tags)
        if is_groupby:
            if not groupby_aggregator:
                raise ValueError("Missing group-by aggregator")
            from opentsdb_tpu.ops.aggregators import AGGREGATORS
            if groupby_aggregator.lower() not in AGGREGATORS:
                raise ValueError("Invalid group by aggregator: %s"
                                 % groupby_aggregator)
            if (self.rollups_block_derived
                    and groupby_aggregator.upper() in ("AVG", "DEV")):
                # TSDB.java:1543-1550
                raise ValueError(
                    "Derived group by aggregations are not allowed: %s"
                    % groupby_aggregator)
            tags[self.agg_tag_key] = groupby_aggregator.upper()
        self.check_timestamp_and_tags(metric, timestamp, num, tags)
        if self.mode == "ro" and not self._replaying:
            # WAL replay must restore data even when the daemon was
            # restarted read-only; the gate applies to new writes only.
            # Gate after validation, like _apply_point (ADVICE r3).
            raise RuntimeError("TSD is in read-only mode, writes rejected")
        ts_ms = normalize_timestamp_ms(timestamp)
        key = self._series_key(metric, tags, create=True)
        lane_agg = (rollup_aggregator if interval else groupby_aggregator)
        self.rollup_store.add_point(
            key, interval or "", lane_agg.lower(), ts_ms, num, is_int,
            pre_agg=is_groupby)
        with self._stats_lock:
            self.datapoints_added += 1

    # ------------------------------------------------------------------ #
    # Read helpers                                                       #
    # ------------------------------------------------------------------ #

    def resolve_key_tags(self, key: SeriesKey) -> dict[str, str]:
        """UID tag pairs -> {tagk_name: tagv_name}."""
        return {self.tag_names.get_name(k): self.tag_values.get_name(v)
                for k, v in key.tags}

    def tsuid(self, key: SeriesKey) -> str:
        """Hex TSUID honoring the configured UID byte widths."""
        return key.tsuid(self.metrics.width, self.tag_names.width,
                         self.tag_values.width)

    def new_query_runner(self):
        from opentsdb_tpu.query.planner import QueryRunner
        return QueryRunner(self)

    @property
    def query_limits(self):
        """Scan-budget registry (QueryLimitOverride.java), built lazily."""
        if self._query_limits is None:
            from opentsdb_tpu.query.limits import QueryLimitOverride
            self._query_limits = QueryLimitOverride(self.config)
        return self._query_limits

    def query_mesh(self):
        """The device mesh serving /api/query, or None when single-device.

        Built lazily from every visible device — the TPU-native counterpart
        of the salt-bucket scanner fan-out (SaltScanner.java:269): instead of
        one concurrent HBase scanner per salt bucket, each chip owns a shard
        of the query batch's rows.  Disable with tsd.query.mesh.enable.
        """
        if not self.config.get_bool("tsd.query.mesh.enable"):
            return None
        if self._query_mesh is _UNSET:
            from opentsdb_tpu.parallel import make_mesh
            from opentsdb_tpu.parallel.distributed import (
                maybe_init_distributed, host_major_devices)
            maybe_init_distributed(self.config)
            devices = host_major_devices()
            self._query_mesh = (make_mesh(len(devices), devices=devices)
                                if len(devices) > 1 else None)
        return self._query_mesh

    # ------------------------------------------------------------------ #
    # UID admin (TSDB.assignUid :1901, renameUid :1968, suggest :1825)   #
    # ------------------------------------------------------------------ #

    def uid_table(self, kind: str) -> UniqueId:
        t = UniqueIdType.from_string(kind)
        return {UniqueIdType.METRIC: self.metrics,
                UniqueIdType.TAGK: self.tag_names,
                UniqueIdType.TAGV: self.tag_values}[t]

    def assign_uid(self, kind: str, name: str) -> int:
        table = self.uid_table(kind)
        if table.has_name(name):
            raise ValueError("Name already exists with UID: %s"
                             % table.uid_to_hex(table.get_id(name)))
        return table.get_or_create_id(name)

    def rename_uid(self, kind: str, old_name: str, new_name: str) -> None:
        self.uid_table(kind).rename(old_name, new_name)

    def delete_uid(self, kind: str, name: str) -> int:
        return self.uid_table(kind).delete(name)

    def suggest_metrics(self, prefix: str = "", max_results: int = 25):
        return self.metrics.suggest(prefix, max_results)

    def suggest_tagk(self, prefix: str = "", max_results: int = 25):
        return self.tag_names.suggest(prefix, max_results)

    def suggest_tagv(self, prefix: str = "", max_results: int = 25):
        return self.tag_values.suggest(prefix, max_results)

    # ------------------------------------------------------------------ #
    # Annotations                                                        #
    # ------------------------------------------------------------------ #

    def _track_meta(self, key, ts_ms: int, n: int = 1) -> None:
        """TSMeta maintenance on the write path (TSDB.java:1259-1285):
        counters only under enable_tsuid_tracking; realtime_ts creates and
        indexes the TSMeta once per new series (TSMeta.storeIfNecessary).
        `n` > 1 counts a whole bulk batch (ts_ms = the batch max)."""
        if not (self.enable_tsuid_tracking or self.enable_realtime_ts
                or self.tree_processing):
            return
        tsuid = self.tsuid(key)
        created = self.meta_store.record_datapoint(
            tsuid, ts_ms, count=self.enable_tsuid_tracking, n=n)
        if created and (self.tree_processing or (
                self.enable_realtime_ts
                and self.search_plugin is not None)):
            from opentsdb_tpu.meta.rpc import resolve_tsmeta
            meta = resolve_tsmeta(self, tsuid)
            if self.enable_realtime_ts and self.search_plugin is not None:
                self.search_plugin.index_tsmeta(meta)
            if self.tree_processing:
                # Realtime tree materialization (TSMeta.storeIfNecessary ->
                # TreeBuilder.processAllTrees when
                # tsd.core.tree.enable_processing).
                for tree in self.tree_store.all_trees():
                    if tree.enabled:
                        self.tree_store.process_tsmeta(
                            tree, meta,
                            metric=self.metrics.get_name(key.metric),
                            tags=self.resolve_key_tags(key))

    def _make_uid_meta_hook(self, kind: str, table):
        def hook(name: str, uid: int) -> None:
            meta = self.meta_store.ensure_uidmeta(
                kind, table.uid_to_hex(uid), name)
            if self.search_plugin is not None:
                self.search_plugin.index_uidmeta(meta)
        return hook

    def add_annotation(self, note: Annotation) -> None:
        with self._ingest_lock:
            self.store.add_annotation(note)
            if self.search_plugin is not None:
                self.search_plugin.index_annotation(note)
            if self.persistence is not None:
                self.persistence.journal({"k": "a", "n": {
                    "start_time": note.start_time,
                    "end_time": note.end_time,
                    "tsuid": note.tsuid, "description": note.description,
                    "notes": note.notes, "custom": note.custom}})

    # ------------------------------------------------------------------ #
    # Stats (TSDB.collectStats :785)                                     #
    # ------------------------------------------------------------------ #

    def collect_stats(self) -> dict[str, float]:
        now = time.time()
        out = {
            "tsd.uid.cache-hit metrics": self.metrics.cache_hits,
            "tsd.uid.cache-miss metrics": self.metrics.cache_misses,
            "tsd.uid.ids-used metrics": len(self.metrics),
            "tsd.uid.cache-hit tagk": self.tag_names.cache_hits,
            "tsd.uid.cache-miss tagk": self.tag_names.cache_misses,
            "tsd.uid.ids-used tagk": len(self.tag_names),
            "tsd.uid.cache-hit tagv": self.tag_values.cache_hits,
            "tsd.uid.cache-miss tagv": self.tag_values.cache_misses,
            "tsd.uid.ids-used tagv": len(self.tag_values),
            "tsd.datapoints.added": self.datapoints_added,
            "tsd.storage.series": self.store.num_series,
            "tsd.storage.datapoints": self.store.total_datapoints,
            "tsd.storage.bytes": self.store.total_bytes,
            "tsd.compaction.count": self.store.compaction_queue.compactions,
            # Operator-visible duplicate-data failures (fix_duplicates off):
            # surfaced here instead of only as the first reader's 400.
            "tsd.compaction.errors": self.store.compaction_queue.errors,
            "tsd.compaction.queue": len(self.store.compaction_queue),
            "tsd.uptime": now - self.start_time,
        }
        if self.maintenance is not None:
            out.update(self.maintenance.collect_stats())
        if self.device_cache is not None:
            out.update(self.device_cache.collect_stats())
        if self.agg_cache is not None:
            out.update(self.agg_cache.collect_stats())
        if self.rollup_lanes is not None:
            out.update(self.rollup_lanes.collect_stats())
        if self.dispatch_batcher is not None:
            out.update(self.dispatch_batcher.collect_stats())
        return out

    @staticmethod
    def version() -> str:
        return __version__

    @staticmethod
    def short_version() -> str:
        return SHORT_VERSION

    def flush(self) -> None:
        self.store.compaction_queue.flush()

    def snapshot(self) -> None:
        """Persist full state to tsd.storage.directory.

        Holds the ingest lock for the walk (stop-the-world checkpoint) so a
        concurrent write can never land after the state capture but before
        the WAL truncation."""
        if self.persistence is None:
            raise RuntimeError("tsd.storage.directory is not configured")
        with self._ingest_lock:
            self.persistence.snapshot()

    def start_maintenance(self):
        """Start the background maintenance thread (compaction flush + WAL
        fsync + snapshot cadence; CompactionQueue.java:95-107).

        Called by the daemon main; library embedders opt in explicitly so a
        bare TSDB() stays thread-free (the reference's tests mock the
        compaction thread out for the same reason).
        """
        if self.maintenance is None:
            from opentsdb_tpu.core.maintenance import MaintenanceThread
            self.maintenance = MaintenanceThread(self)
            self.maintenance.start()
        return self.maintenance

    def shutdown(self) -> None:
        if self.maintenance is not None:
            self.maintenance.stop(final_flush=False)
            self.maintenance = None
        if self.autotuner is not None:
            # detach FIRST: a maintenance pass that outlived the 5s
            # join timeout must find no autotuner to tick, or it could
            # re-force a kernel mode after the restore below (and a
            # second shutdown() must not re-run persist/teardown)
            autotuner, self.autotuner = self.autotuner, None
            # restore any exploration override and persist the fitted
            # constants so calibration survives the restart
            autotuner.shutdown()
        if self.replication is not None:
            # before the snapshot: no pull may apply (and journal) a
            # peer record while the WAL is being reset
            self.replication.stop_puller()
        self.flush()
        if self.persistence is not None:
            with self._ingest_lock:
                self.persistence.snapshot()
            self.persistence.close()                 # order-event: wal-close
        if self.spill_pool is not None:
            # after the query path is quiesced: drops every entry and
            # the private tempdir (in-flight tiled queries have their
            # own per-query release in ops/tiling.py)
            self.spill_pool.close()                  # order-event: spill-close
        if self.flightrec is not None:
            # LAST, so teardown events above still land in the ring
            # before the shutdown dump writes the black box; idempotent
            # (a server stop + an explicit shutdown both reach here)
            # order: wal-close before flightrec-shutdown
            # order: spill-close before flightrec-shutdown
            self.flightrec.shutdown()                # order-event: flightrec-shutdown


def parse_value(value) -> tuple[bool, int | float]:
    """Classify a put value as integer or float (Tags.parseLong / fixFloat).

    Strings follow the telnet `put` rules: "42" is an integer, "42.0" and
    "4e2" are floats.  Integers stay exact Python ints (Java-long parity up
    to 2^63); NaN/Infinity are rejected like the reference
    (TSDB.addPointInternal IllegalArgumentException).
    """
    import math
    if isinstance(value, bool):
        raise ValueError("Invalid value: %r" % value)
    if isinstance(value, int):
        return True, value
    if isinstance(value, float):
        if math.isnan(value) or math.isinf(value):
            raise ValueError("Invalid value: %r" % value)
        return False, value
    text = str(value).strip()
    if not text:
        raise ValueError("Empty value")
    try:
        return True, int(text)
    except ValueError:
        pass
    try:
        out = float(text)
    except ValueError:
        raise ValueError("Invalid value: %r" % value)
    if math.isnan(out) or math.isinf(out):
        raise ValueError("Invalid value: %r" % value)
    return False, out
