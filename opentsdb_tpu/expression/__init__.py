"""Expression engines.

Reference behavior: /root/reference/src/query/expression/ — the gexp
function DSL (/api/query/gexp, ExpressionFactory.java:26-60 registry) and
the 2.3 expression pipeline (/api/query/exp, ExpressionIterator.java +
QueryExecutor.java) with JEXL arithmetic replaced by a safe vectorized
evaluator (no arbitrary code execution).

These engines run host-side on the *aggregated* output series (small, one
point per output step) — the device pipeline has already reduced the raw
data, so numpy is the right tool here; shipping these few KB back to the
TPU would cost more in transfers than it saves.
"""

from opentsdb_tpu.expression.series import SeriesResult
from opentsdb_tpu.expression.arith import compile_expression
from opentsdb_tpu.expression.gexp import (
    parse_gexp, evaluate_tree, GEXP_FUNCTIONS)

__all__ = ["SeriesResult", "compile_expression", "parse_gexp",
           "evaluate_tree", "GEXP_FUNCTIONS"]
