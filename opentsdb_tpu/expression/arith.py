"""Safe arithmetic expression compiler for /api/query/exp.

Replaces the reference's Apache JEXL 2.1.1 engine
(/root/reference/src/query/expression/ExpressionIterator.java:77) and the
JavaCC syntax checker (/root/reference/src/parser.jj) with a small
recursive-descent parser producing a closure over numpy arrays — same
operator set (+ - * / % arithmetic, comparison and && || ! logic, parens),
none of JEXL's arbitrary-method-call surface.

Comparison/logic operators return 1.0/0.0 like JEXL-over-doubles did.
"""

from __future__ import annotations

import re

import numpy as np

_TOKEN = re.compile(r"""
    \s*(?:
      (?P<num>\d+\.\d+|\d+\.|\.\d+|\d+)
    | (?P<name>[A-Za-z_][A-Za-z0-9_]*)
    | (?P<op>&&|\|\||==|!=|>=|<=|>|<|[-+*/%()!,])
    )""", re.VERBOSE)


class ExpressionSyntaxError(ValueError):
    pass


def tokenize(text: str) -> list[tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if m is None:
            if text[pos:].strip() == "":
                break
            raise ExpressionSyntaxError(
                "Unexpected character %r in expression at offset %d"
                % (text[pos], pos))
        if m.group("num") is not None:
            out.append(("num", m.group("num")))
        elif m.group("name") is not None:
            out.append(("name", m.group("name")))
        else:
            out.append(("op", m.group("op")))
        pos = m.end()
    out.append(("end", ""))
    return out


class _Parser:
    """Precedence-climbing parser -> nested closures of (env) -> ndarray."""

    LEVELS = [
        ("||",),
        ("&&",),
        ("==", "!="),
        (">", "<", ">=", "<="),
        ("+", "-"),
        ("*", "/", "%"),
    ]

    def __init__(self, tokens: list[tuple[str, str]]):
        self.tokens = tokens
        self.pos = 0
        self.variables: set[str] = set()

    def peek(self) -> tuple[str, str]:
        return self.tokens[self.pos]

    def next(self) -> tuple[str, str]:
        tok = self.tokens[self.pos]
        self.pos += 1
        return tok

    def parse(self):
        fn = self._binary(0)
        kind, val = self.peek()
        if kind != "end":
            raise ExpressionSyntaxError("Trailing input at token %r" % val)
        return fn

    def _binary(self, level: int):
        if level == len(self.LEVELS):
            return self._unary()
        ops = self.LEVELS[level]
        left = self._binary(level + 1)
        while True:
            kind, val = self.peek()
            if kind != "op" or val not in ops:
                return left
            self.next()
            right = self._binary(level + 1)
            left = _make_binop(val, left, right)

    def _unary(self):
        kind, val = self.peek()
        if kind == "op" and val == "-":
            self.next()
            inner = self._unary()
            return lambda env: -inner(env)
        if kind == "op" and val == "!":
            self.next()
            inner = self._unary()
            return lambda env: (inner(env) == 0).astype(np.float64)
        return self._atom()

    def _atom(self):
        kind, val = self.next()
        if kind == "num":
            const = float(val)
            return lambda env: const
        if kind == "name":
            self.variables.add(val)
            name = val
            return lambda env: env[name]
        if kind == "op" and val == "(":
            inner = self._binary(0)
            kind, val = self.next()
            if val != ")":
                raise ExpressionSyntaxError("Expected ')', got %r" % val)
            return inner
        raise ExpressionSyntaxError("Unexpected token %r" % (val or kind))


def _make_binop(op: str, left, right):
    if op == "+":
        return lambda env: left(env) + right(env)
    if op == "-":
        return lambda env: left(env) - right(env)
    if op == "*":
        return lambda env: left(env) * right(env)
    if op == "/":
        def div(env):
            with np.errstate(divide="ignore", invalid="ignore"):
                return np.divide(left(env), right(env))
        return div
    if op == "%":
        def mod(env):
            with np.errstate(divide="ignore", invalid="ignore"):
                return np.mod(left(env), right(env))
        return mod
    if op == "==":
        return lambda env: (left(env) == right(env)).astype(np.float64)
    if op == "!=":
        return lambda env: (left(env) != right(env)).astype(np.float64)
    if op == ">":
        return lambda env: (left(env) > right(env)).astype(np.float64)
    if op == "<":
        return lambda env: (left(env) < right(env)).astype(np.float64)
    if op == ">=":
        return lambda env: (left(env) >= right(env)).astype(np.float64)
    if op == "<=":
        return lambda env: (left(env) <= right(env)).astype(np.float64)
    if op == "&&":
        return lambda env: (
            (left(env) != 0) & (right(env) != 0)).astype(np.float64)
    if op == "||":
        return lambda env: (
            (left(env) != 0) | (right(env) != 0)).astype(np.float64)
    raise ExpressionSyntaxError("Unknown operator: " + op)


class CompiledExpression:
    """expr text -> callable(env: {var: ndarray}) -> ndarray."""

    def __init__(self, text: str):
        parser = _Parser(tokenize(text))
        self._fn = parser.parse()
        self.text = text
        self.variables = frozenset(parser.variables)

    def __call__(self, env: dict) -> np.ndarray:
        missing = self.variables - set(env)
        if missing:
            raise KeyError("Expression '%s' references unknown variables: %s"
                           % (self.text, ", ".join(sorted(missing))))
        return np.asarray(self._fn(env), dtype=np.float64)


def compile_expression(text: str) -> CompiledExpression:
    if not text or not text.strip():
        raise ExpressionSyntaxError("Missing expression")
    return CompiledExpression(text)
