"""/api/query/exp: the 2.3 expression pipeline over the pojo query DSL.

Reference behavior: /root/reference/src/query/pojo/ (Query :35-50 {name,
time, filters, metrics, expressions, outputs}, Metric :34-49, Expression,
Join, Output) and /root/reference/src/tsd/QueryExecutor.java (:224 execute,
:482 serialize — output array of {id, alias?, dps: [[ts, v per series]],
dpsMeta, meta}) + ExpressionIterator.java (variable series joined across
metrics by tags: INTERSECTION default / UNION, arithmetic per timestamp).

The JEXL engine is replaced by arith.compile_expression; join + evaluation
are vectorized over [series, time] matrices.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from opentsdb_tpu.expression.arith import compile_expression
from opentsdb_tpu.expression.series import SeriesResult, union_grid, align
from opentsdb_tpu.models.tsquery import TSQuery, TSSubQuery
from opentsdb_tpu.ops.rate import RateOptions
from opentsdb_tpu.query.filters import build_filter


@dataclass
class PojoQuery:
    """Validated /api/query/exp body."""
    start: str
    end: str | None
    aggregator: str
    downsampler: str | None
    metrics: list[dict]
    expressions: list[dict]
    outputs: list[dict]
    filters: dict[str, list]         # id -> list[TagVFilter]
    filter_tags: dict[str, set]      # id -> explicit group-by tagks
    rate: bool = False
    rate_options: RateOptions = field(default_factory=RateOptions)

    @staticmethod
    def parse(body: dict) -> "PojoQuery":
        from opentsdb_tpu.tsd.http import BadRequestError
        if not isinstance(body, dict):
            raise BadRequestError("Unparseable data content")
        time_spec = body.get("time")
        if not time_spec:
            raise BadRequestError("Missing the time component")
        if not time_spec.get("start"):
            raise BadRequestError("missing or empty start")
        if not time_spec.get("aggregator"):
            raise BadRequestError("missing or empty aggregator")
        metrics = body.get("metrics") or []
        if not metrics:
            raise BadRequestError("Missing the metrics component")
        ids = set()
        for m in metrics:
            if not m.get("id"):
                raise BadRequestError("Missing metric id")
            if not m.get("metric"):
                raise BadRequestError("Missing metric name for id %s"
                                      % m["id"])
            if m["id"] in ids:
                raise BadRequestError("Duplicate metric id: %s" % m["id"])
            ids.add(m["id"])
        filters: dict[str, list] = {}
        filter_tags: dict[str, set] = {}
        for f in body.get("filters") or []:
            fid = f.get("id")
            if not fid:
                raise BadRequestError("Missing filter id")
            flist = []
            tagks = set()
            for t in f.get("tags") or []:
                flist.append(build_filter(
                    t["tagk"], t.get("type", "literal_or"),
                    t.get("filter", ""), group_by=bool(t.get("groupBy",
                                                             True))))
                tagks.add(t["tagk"])
            filters[fid] = flist
            filter_tags[fid] = tagks
        expressions = body.get("expressions") or []
        for e in expressions:
            if not e.get("id"):
                raise BadRequestError("Missing expression id")
            if not e.get("expr"):
                raise BadRequestError("Missing expression for id %s"
                                      % e["id"])
            if e["id"] in ids:
                raise BadRequestError(
                    "Duplicate expression/metric id: %s" % e["id"])
            ids.add(e["id"])
        ds = time_spec.get("downsampler")
        downsampler = None
        if ds:
            downsampler = "%s-%s" % (ds["interval"], ds["aggregator"])
            if ds.get("fillPolicy"):
                policy = ds["fillPolicy"]
                if isinstance(policy, dict):
                    policy = policy.get("policy", "none")
                downsampler += "-" + policy
        rate = bool(time_spec.get("rate", False))
        ro = time_spec.get("rateOptions") or {}
        return PojoQuery(
            start=str(time_spec["start"]),
            end=(str(time_spec["end"]) if time_spec.get("end") else None),
            aggregator=time_spec["aggregator"],
            downsampler=downsampler,
            metrics=metrics,
            expressions=expressions,
            outputs=body.get("outputs") or [],
            filters=filters,
            filter_tags=filter_tags,
            rate=rate,
            rate_options=RateOptions(
                counter=bool(ro.get("counter", False)),
                counter_max=int(ro.get("counterMax",
                                       RateOptions().counter_max)),
                reset_value=int(ro.get("resetValue", 0)),
                drop_resets=bool(ro.get("dropResets", False))))


class QueryExecutor:
    """Runs a PojoQuery: metrics -> variable matrices -> expressions.

    `http_query` (when serving over HTTP) lets the metric extraction go
    through the cluster front door — fan-out loop prevention reads the
    request's X-TSDB-Cluster header."""

    def __init__(self, tsdb, pojo: PojoQuery, http_query=None):
        self.tsdb = tsdb
        self.pojo = pojo
        self.http_query = http_query

    def _build_ts_query(self) -> TSQuery:
        q = TSQuery(start=self.pojo.start, end=self.pojo.end)
        for i, m in enumerate(self.pojo.metrics):
            sub = TSSubQuery(
                aggregator=m.get("aggregator") or self.pojo.aggregator,
                metric=m["metric"],
                downsample=m.get("downsample") or self.pojo.downsampler,
                rate=self.pojo.rate,
                rate_options=self.pojo.rate_options,
                index=i)
            fid = m.get("filter")
            if fid:
                if fid not in self.pojo.filters:
                    raise ValueError("No filter defined with id: %s" % fid)
                import copy
                sub.filters = copy.deepcopy(self.pojo.filters[fid])
            q.queries.append(sub)
        return q

    def execute(self) -> dict:
        from opentsdb_tpu.tsd.cluster import serve_query
        pojo = self.pojo
        ts_query = self._build_ts_query()
        ts_query.validate()

        # metric id -> list[SeriesResult] (one per group-by bucket)
        results: dict[str, list[SeriesResult]] = {
            m["id"]: [] for m in pojo.metrics}
        id_by_index = {i: m["id"] for i, m in enumerate(pojo.metrics)}
        fills: dict[str, float] = {}
        for m in pojo.metrics:
            fp = m.get("fillPolicy") or {}
            if isinstance(fp, str):
                fp = {"policy": fp}
            policy = fp.get("policy", "nan")
            if policy == "zero":
                fills[m["id"]] = 0.0
            elif policy == "scalar":
                fills[m["id"]] = float(fp.get("value", 0.0))
            else:
                fills[m["id"]] = np.nan
        exec_stats: dict = {}
        for qr in serve_query(self.tsdb, ts_query, self.http_query,
                              exec_stats=exec_stats):
            results[id_by_index[qr.index]].append(
                SeriesResult.from_query_result(qr))

        outputs = pojo.outputs
        if not outputs:
            source = pojo.expressions if pojo.expressions else pojo.metrics
            outputs = [{"id": e["id"]} for e in source]

        # Expression DAG: an expression's variables may name OTHER
        # expressions (reference: QueryExecutor.java:291 builds a
        # jgrapht DirectedAcyclicGraph over the expressions and wires
        # each ExpressionIterator's variable iterators from metric OR
        # expression results).  Evaluate in topological order, feeding
        # each result back into the variable namespace; a cycle (incl.
        # self-reference) is a 400.
        exprs = {e["id"]: e for e in pojo.expressions}
        self._eval: dict[str, dict] = {}
        for eid in self._topo_order(exprs):
            ev = self._eval_expression(exprs[eid], results, fills)
            self._eval[eid] = ev
            results[eid] = ev["series"]

        out_objs = []
        for output in outputs:
            oid = output.get("id")
            if oid in exprs:
                out_objs.append(self._serialize_expression(
                    exprs[oid], output))
            elif oid in results:
                out_objs.append(self._serialize_metric(
                    oid, output, results[oid]))
        reply = {"outputs": out_objs, "query": self._echo_query()}
        from opentsdb_tpu.tsd.cluster import partial_annotation
        partial = partial_annotation(exec_stats)
        if partial:
            # degraded cluster serving: the 200 must not be silently
            # partial
            reply.update(partial)
        return reply

    @staticmethod
    def _topo_order(exprs: dict[str, dict]) -> list[str]:
        """Kahn's algorithm over expression->expression references; 400 on
        a cycle (the reference's DirectedAcyclicGraph add throws there)."""
        from opentsdb_tpu.tsd.http import BadRequestError
        deps = {}
        for eid, e in exprs.items():
            deps[eid] = {v for v in compile_expression(e["expr"]).variables
                         if v in exprs}
            if eid in deps[eid]:
                raise BadRequestError(
                    "Self referencing expression found: %s" % eid)
        order = []
        ready = sorted(eid for eid, d in deps.items() if not d)
        pending = {eid: set(d) for eid, d in deps.items() if d}
        while ready:
            eid = ready.pop()
            order.append(eid)
            for other in sorted(pending):
                pending[other].discard(eid)
                if not pending[other]:
                    ready.append(other)
                    del pending[other]
        if pending:
            raise BadRequestError(
                "Circular expression reference involving: %s"
                % ", ".join(sorted(pending)))
        return order

    # -- joins (VariableIterator: INTERSECTION / UNION by tags) --

    @staticmethod
    def _join_key(series: SeriesResult, tagks: set | None) -> tuple:
        if tagks:
            return tuple(sorted((k, v) for k, v in series.tags.items()
                                if k in tagks))
        return tuple(sorted(series.tags.items()))

    def _join(self, var_ids: list[str],
              results: dict[str, list[SeriesResult]],
              join_spec: dict,
              query_tagks: set | None = None) -> list[dict]:
        """Match series across variables by tag identity; returns a list of
        {var_id: SeriesResult} sets.

        With useQueryTags (Join.java), only the tag keys named in the
        metrics' filters participate in the join key, so series carrying
        differing extra tags still pair up.
        """
        operator = (join_spec.get("operator") or "intersection").lower()
        use_keys = bool(join_spec.get("useQueryTags", False))
        tagks = query_tagks if use_keys else None
        keyed: dict[str, dict[tuple, SeriesResult]] = {}
        for vid in var_ids:
            keyed[vid] = {}
            for s in results.get(vid, []):
                keyed[vid][self._join_key(s, tagks)] = s
        all_keys: set = set()
        for vid in var_ids:
            all_keys.update(keyed[vid])
        joined = []
        for key in sorted(all_keys):
            sets = {vid: keyed[vid].get(key) for vid in var_ids}
            if operator == "intersection" and any(
                    v is None for v in sets.values()):
                continue
            joined.append(sets)
        return joined

    def _eval_expression(self, expr: dict,
                         results: dict[str, list[SeriesResult]],
                         fills: dict[str, float]) -> dict:
        """Evaluate one expression against the current variable namespace
        (metric results + previously evaluated expressions) and package
        each joined column as a SeriesResult so downstream expressions
        can consume it like any other variable."""
        compiled = compile_expression(expr["expr"])
        var_ids = [v for v in compiled.variables if v in results]
        join_spec = expr.get("join") or {}
        query_tagks: set = set()
        for m in self.pojo.metrics:
            if m["id"] in var_ids and m.get("filter"):
                query_tagks |= self.pojo.filter_tags.get(m["filter"], set())
        joined = self._join(var_ids, results, join_spec,
                            query_tagks or None)
        fill_policy = expr.get("fillPolicy") or {}
        if isinstance(fill_policy, str):
            fill_policy = {"policy": fill_policy}
        expr_fill = fill_policy.get("policy")

        # Union grid across every participating series.
        participating = [s for sets in joined for s in sets.values()
                         if s is not None]
        grid = union_grid(participating)
        columns = []
        metas = []
        for idx, sets in enumerate(joined):
            env = {}
            for vid in var_ids:
                s = sets.get(vid)
                fill = fills.get(vid, np.nan)
                if expr_fill == "zero":
                    fill = 0.0
                if s is None:
                    env[vid] = np.full(len(grid), fill)
                else:
                    env[vid] = align([s], grid, fill=fill)[0]
            columns.append(compiled(env))
            tags = {}
            for s in sets.values():
                if s is not None:
                    tags.update(s.tags)
            metas.append({
                "index": idx,
                "metrics": sorted({s.label for s in sets.values()
                                   if s is not None}),
                "commonTags": tags,
                "aggregatedTags": sorted({t for s in sets.values()
                                          if s is not None
                                          for t in s.agg_tags}),
            })
        series = [SeriesResult(label=expr["id"],
                               tags=dict(metas[i]["commonTags"]),
                               agg_tags=list(metas[i]["aggregatedTags"]),
                               ts=grid,
                               values=np.asarray(columns[i], np.float64))
                  for i in range(len(columns))]
        return {"grid": grid, "columns": columns, "metas": metas,
                "series": series}

    def _serialize_expression(self, expr: dict, output: dict) -> dict:
        ev = self._eval[expr["id"]]
        grid, columns = ev["grid"], ev["columns"]
        dps = []
        for j, t in enumerate(grid.tolist()):
            row = [t] + [self._num(col[j]) for col in columns]
            dps.append(row)
        return {
            "id": expr["id"],
            "alias": output.get("alias"),
            "dps": dps,
            "dpsMeta": {
                "firstTimestamp": int(grid[0]) if len(grid) else 0,
                "lastTimestamp": int(grid[-1]) if len(grid) else 0,
                "setCount": len(grid),
                "series": len(columns),
            },
            "meta": ev["metas"],
        }

    def _serialize_metric(self, oid: str, output: dict,
                          series: list[SeriesResult]) -> dict:
        grid = union_grid(series)
        mat = align(series, grid, fill=np.nan)
        dps = []
        for j, t in enumerate(grid.tolist()):
            dps.append([t] + [self._num(mat[i, j])
                              for i in range(len(series))])
        return {
            "id": oid,
            "alias": output.get("alias"),
            "dps": dps,
            "dpsMeta": {
                "firstTimestamp": int(grid[0]) if len(grid) else 0,
                "lastTimestamp": int(grid[-1]) if len(grid) else 0,
                "setCount": len(grid),
                "series": len(series),
            },
            "meta": [{
                "index": i,
                "metrics": [s.label],
                "commonTags": s.tags,
                "aggregatedTags": s.agg_tags,
            } for i, s in enumerate(series)],
        }

    @staticmethod
    def _num(v: float):
        v = float(v)
        if np.isnan(v):
            return None
        if np.isfinite(v) and v == int(v) and abs(v) < 2 ** 53:
            return int(v)
        return v

    def _echo_query(self) -> dict:
        return {
            "name": None,
            "time": {"start": self.pojo.start, "end": self.pojo.end,
                     "aggregator": self.pojo.aggregator,
                     "downsampler": self.pojo.downsampler},
            "metrics": self.pojo.metrics,
            "expressions": self.pojo.expressions,
            "outputs": self.pojo.outputs,
        }


def handle_exp_query(tsdb, query) -> None:
    """POST /api/query/exp (QueryRpc.handleExpressionQuery :330)."""
    from opentsdb_tpu.obs import latattr
    from opentsdb_tpu.tsd.rpcs import allowed_methods
    allowed_methods(query, "POST")
    pojo = PojoQuery.parse(query.json_body())
    latattr.mark("parse")
    executor = QueryExecutor(tsdb, pojo, http_query=query)
    payload = executor.execute()
    latattr.mark("serialize")
    query.send_reply(payload)
