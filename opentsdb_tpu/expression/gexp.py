"""Graphite-style expression functions + /api/query/gexp handler.

Reference behavior: /root/reference/src/query/expression/ —
ExpressionFactory.java (:31-60: alias, scale, absolute, movingAverage,
highestCurrent, highestMax, shift/timeShift, firstDiff, divideSeries/divide,
sumSeries/sum, diffSeries/difference, multiplySeries/multiply),
Expressions.java/ExpressionReader.java (paren parser collecting m-subquery
args), and QueryRpc.java:330 (gexp executes handleQuery with expression
post-processing).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from opentsdb_tpu.expression.series import SeriesResult, union_grid, align
from opentsdb_tpu.utils import datetime_util as DT


@dataclass
class ExpressionTree:
    """One parsed gexp call: function + args (subtrees, metric refs,
    literal params)."""
    func: str
    args: list = field(default_factory=list)   # ExpressionTree | MetricRef | str

    def metric_queries(self) -> list[str]:
        out = []
        for a in self.args:
            if isinstance(a, MetricRef):
                out.append(a.query)
            elif isinstance(a, ExpressionTree):
                out.extend(a.metric_queries())
        return out

    def to_string(self) -> str:
        parts = []
        for a in self.args:
            if isinstance(a, ExpressionTree):
                parts.append(a.to_string())
            elif isinstance(a, MetricRef):
                parts.append(a.query)
            else:
                parts.append(str(a))
        return "%s(%s)" % (self.func, ",".join(parts))


@dataclass
class MetricRef:
    query: str    # an m-subquery string like "sum:proc.stat.cpu{host=*}"


def parse_gexp(expression: str) -> ExpressionTree:
    """Parse a nested function-call expression (ExpressionReader)."""
    if not expression or "(" not in expression or ")" not in expression:
        raise ValueError("Invalid Expression: %s" % expression)
    text = expression.strip()
    tree, pos = _parse_call(text, 0)
    if text[pos:].strip():
        raise ValueError("Trailing input in expression: %s" % text[pos:])
    return tree


def _parse_call(text: str, pos: int) -> tuple[ExpressionTree, int]:
    start = pos
    while pos < len(text) and (text[pos].isalnum() or text[pos] == "_"):
        pos += 1
    name = text[start:pos].strip()
    if not name:
        raise ValueError("Missing function name at offset %d" % start)
    if name not in GEXP_FUNCTIONS:
        raise ValueError("Unknown function: %s" % name)
    while pos < len(text) and text[pos].isspace():
        pos += 1
    if pos >= len(text) or text[pos] != "(":
        raise ValueError("Expected '(' after %s" % name)
    pos += 1
    tree = ExpressionTree(func=name)
    while True:
        while pos < len(text) and text[pos].isspace():
            pos += 1
        if pos >= len(text):
            raise ValueError("Unbalanced parentheses in: %s" % text)
        if text[pos] == ")":
            return tree, pos + 1
        arg, pos = _parse_arg(text, pos)
        tree.args.append(arg)
        while pos < len(text) and text[pos].isspace():
            pos += 1
        if pos < len(text) and text[pos] == ",":
            pos += 1

def _parse_arg(text: str, pos: int):
    # A nested call starts with a known function name followed by '('.
    probe = pos
    while probe < len(text) and (text[probe].isalnum() or text[probe] == "_"):
        probe += 1
    word = text[pos:probe]
    rest = probe
    while rest < len(text) and text[rest].isspace():
        rest += 1
    if word in GEXP_FUNCTIONS and rest < len(text) and text[rest] == "(":
        return _parse_call(text, pos)
    # Otherwise scan to the matching ',' or ')' at depth 0 ('{' guards
    # filter braces, quotes guard string params).
    depth = 0
    out = []
    quote = None
    while pos < len(text):
        c = text[pos]
        if quote:
            if c == quote:
                quote = None
            else:
                out.append(c)
            pos += 1
            continue
        if c in "'\"":
            quote = c
            pos += 1
            continue
        if c in "({":
            depth += 1
        elif c in ")}":
            if depth == 0 and c == ")":
                break
            depth -= 1
        elif c == "," and depth == 0:
            break
        out.append(c)
        pos += 1
    token = "".join(out).strip()
    if not token:
        raise ValueError("Empty parameter at offset %d" % pos)
    if _is_literal(token):
        return token, pos
    return MetricRef(token), pos


def _is_literal(token: str) -> bool:
    if ":" in token:    # m-subquery "agg:metric"
        return False
    try:
        float(token)
        return True
    except ValueError:
        pass
    # duration strings ('10min') and alias text arrive as literals
    return True


# --------------------------------------------------------------------- #
# Function implementations: list[list[SeriesResult]] per metric arg      #
# --------------------------------------------------------------------- #


def _need_series(args, func):
    if not args or not isinstance(args[0], list):
        raise ValueError("%s needs at least one metric query" % func)


def f_scale(args) -> list[SeriesResult]:
    _need_series(args, "scale")
    if len(args) < 2:
        raise ValueError("Scale factor not specified")
    factor = float(args[1])
    return [s.copy_with(label="scale(%s,%s)" % (s.label, args[1]),
                        values=s.values * factor) for s in args[0]]


def f_absolute(args) -> list[SeriesResult]:
    _need_series(args, "absolute")
    return [s.copy_with(label="absolute(%s)" % s.label,
                        values=np.abs(s.values)) for s in args[0]]


def f_alias(args) -> list[SeriesResult]:
    _need_series(args, "alias")
    if len(args) < 2:
        raise ValueError("Missing the alias")
    template = str(args[1])
    out = []
    for s in args[0]:
        label = template
        for k, v in s.tags.items():
            label = label.replace("@" + k, v)
        out.append(s.copy_with(label=label))
    return out


def _java_expr_moving_average(ts, v, is_time: bool, window_ms: int,
                              window_n: int) -> np.ndarray:
    """The reference expression-layer evaluation loop, exactly
    (/root/reference/src/query/expression/MovingAverage.java:191
    MovingAverageAggregator): INCLUSIVE of the current point, 0 until the
    window condition is met; time windows additionally skip the series'
    first point (window_started) and require a point OLDER than the
    window to exist before emitting."""
    n_pts = len(v)
    idx = np.arange(n_pts)
    # Non-finite values poison exactly the windows containing them (the
    # Java loop sums fresh per point; a plain cumsum would emit NaN
    # forever after an inf via inf - inf).  Finite windows go through
    # cumsum differences; the (rare) windows overlapping a non-finite
    # point re-sum their slice directly for the exact Java result
    # (inf -> inf, mixed infs/NaN -> NaN).
    bad = ~np.isfinite(v)
    csum = np.concatenate([[0.0], np.cumsum(np.where(bad, 0.0, v))])
    bsum = np.concatenate([[0], np.cumsum(bad.astype(np.int64))])
    if is_time:
        lo = np.searchsorted(ts, ts - window_ms, side="right")
        met = (lo > 0) & (idx > 0)
    else:
        lo = np.maximum(idx - window_n + 1, 0)
        met = idx >= window_n - 1
    cnt = np.maximum(idx + 1 - lo, 1)
    mean = (csum[idx + 1] - csum[lo]) / cnt
    res = np.where(met, mean, 0.0)
    for i in np.flatnonzero(met & (bsum[idx + 1] - bsum[lo] > 0)):
        res[i] = np.sum(v[lo[i]:i + 1]) / cnt[i]
    return res


def f_moving_average(args) -> list[SeriesResult]:
    """movingAverage(m, N) points or movingAverage(m, '10min') time
    window, applied per result series like the reference (each series
    wrapped in its own AggregationIterator,
    /root/reference/src/query/expression/MovingAverage.java:105-118)."""
    _need_series(args, "movingAverage")
    if len(args) < 2:
        raise ValueError("Missing moving average window size")
    param = str(args[1]).strip("'\"")
    is_time = not param.isdigit()
    window_ms = 0
    window_n = 0
    if is_time:
        unit = "".join(ch for ch in param if not ch.isdigit())
        count = "".join(ch for ch in param if ch.isdigit())
        if not count or unit not in ("s", "sec", "m", "min", "h", "hr", "d",
                                     "day", "w", "week"):
            raise ValueError("Invalid moving window parameter: " + param)
        canonical = {"sec": "s", "min": "m", "hr": "h", "day": "d",
                     "week": "w"}.get(unit, unit)
        # parse_duration rejects zero/negative spans, matching the
        # reference's condition <= 0 check (MovingAverage.java:74-77)
        window_ms = DT.parse_duration(count + canonical)
    else:
        window_n = int(param)
        if window_n <= 0:
            raise ValueError("Moving average window must be an integer "
                             "greater than zero")
    out = []
    for s in args[0]:
        vals = _java_expr_moving_average(
            s.ts, s.values.astype(np.float64), is_time, window_ms, window_n)
        out.append(s.copy_with(label="movingAverage(%s,%s)"
                               % (s.label, param), values=vals))
    return out


def _top_n(args, key_fn, func) -> list[SeriesResult]:
    _need_series(args, func)
    if len(args) < 2:
        raise ValueError("Missing the top-n parameter")
    n = int(args[1])
    if n < 1:
        raise ValueError("Invalid parameter, n must be greater than zero: %d"
                         % n)
    scored = [(key_fn(s), i, s) for i, s in enumerate(args[0])
              if len(s.values)]
    scored.sort(key=lambda t: (-t[0], t[1]))
    return [s.copy_with(label="%s(%s,%d)" % (func, s.label, n))
            for _, _, s in scored[:n]]


def f_highest_current(args) -> list[SeriesResult]:
    return _top_n(args, lambda s: float(s.values[-1]), "highestCurrent")


def f_highest_max(args) -> list[SeriesResult]:
    return _top_n(args, lambda s: float(np.nanmax(s.values)), "highestMax")


def f_time_shift(args) -> list[SeriesResult]:
    """shift(m, '10min'): move each point's timestamp forward by the
    interval (TimeShift.java: 'increase timestamps by timeshift')."""
    _need_series(args, "timeShift")
    if len(args) < 2:
        raise ValueError("Need amount of timeshift to perform timeshift")
    param = str(args[1]).strip("'\"")
    unit = "".join(ch for ch in param if not ch.isdigit())
    count = "".join(ch for ch in param if ch.isdigit())
    canonical = {"sec": "s", "min": "m", "hr": "h", "day": "d",
                 "week": "w"}.get(unit, unit)
    try:
        shift_ms = DT.parse_duration(count + canonical)
    except Exception:
        raise ValueError("Invalid timeshift='" + param + "'")
    if shift_ms <= 0:
        raise ValueError("timeshift <= 0")
    return [s.copy_with(label="timeShift(%s,%s)" % (s.label, param),
                        ts=s.ts + shift_ms) for s in args[0]]


def f_first_diff(args) -> list[SeriesResult]:
    """firstDiff(m): v[i] - v[i-1], first point 0 (FirstDifference.java)."""
    _need_series(args, "firstDiff")
    out = []
    for s in args[0]:
        vals = np.zeros_like(s.values)
        if len(s.values) > 1:
            vals[1:] = s.values[1:] - s.values[:-1]
        out.append(s.copy_with(label="firstDiff(%s)" % s.label, values=vals))
    return out


def _merge_all(args) -> list[SeriesResult]:
    series = []
    for a in args:
        if isinstance(a, list):
            series.extend(a)
    return series


def f_sum_series(args) -> list[SeriesResult]:
    """sumSeries: all input series -> one series on the union grid; a
    missing point contributes 0 (TimeSyncedIterator's default
    FillPolicy.ZERO, TimeSyncedIterator.java:74)."""
    series = _merge_all(args)
    if not series:
        raise ValueError("sumSeries needs at least one metric query")
    grid = union_grid(series)
    mat = align(series, grid, fill=0.0)
    vals = np.sum(mat, axis=0)
    label = "sumSeries(%s)" % ",".join(s.label for s in series[:3])
    return [SeriesResult(label, _common_tags(series),
                         _agg_tags(series), grid, vals)]


def f_diff_series(args) -> list[SeriesResult]:
    """diffSeries(a, b, ...): first minus the rest (DiffSeries.java)."""
    series = _merge_all(args)
    if len(series) < 1:
        raise ValueError("diffSeries needs at least one metric query")
    grid = union_grid(series)
    mat = align(series, grid, fill=0.0)
    vals = mat[0] - np.sum(mat[1:], axis=0)
    label = "difference(%s)" % ",".join(s.label for s in series[:3])
    return [SeriesResult(label, _common_tags(series),
                         _agg_tags(series), grid, vals)]


def f_multiply_series(args) -> list[SeriesResult]:
    """multiplySeries: missing points fill 0, so the product at a
    partially-covered timestamp is 0 (UNION join + FillPolicy.ZERO)."""
    series = _merge_all(args)
    if not series:
        raise ValueError("multiplySeries needs at least one metric query")
    grid = union_grid(series)
    mat = align(series, grid, fill=0.0)
    vals = np.prod(mat, axis=0)
    label = "multiplySeries(%s)" % ",".join(s.label for s in series[:3])
    return [SeriesResult(label, _common_tags(series),
                         _agg_tags(series), grid, vals)]


def f_divide_series(args) -> list[SeriesResult]:
    """divideSeries(numerator, denominator) (DivideSeries.java: exactly two
    series, UNION join with TimeSyncedIterator's default FillPolicy.ZERO —
    a missing denominator point therefore divides by 0 and yields the
    Infinity the reference's JEXL double division produces)."""
    series = _merge_all(args)
    if len(series) != 2:
        raise ValueError("divideSeries expects exactly 2 series, got %d"
                         % len(series))
    grid = union_grid(series)
    mat = align(series, grid, fill=0.0)
    with np.errstate(divide="ignore", invalid="ignore"):
        vals = mat[0] / mat[1]
    label = "divideSeries(%s,%s)" % (series[0].label, series[1].label)
    return [SeriesResult(label, _common_tags(series),
                         _agg_tags(series), grid, vals)]


def _common_tags(series) -> dict[str, str]:
    from opentsdb_tpu.expression.series import compute_tags
    return compute_tags([s.tags for s in series])[0]


def _agg_tags(series) -> list[str]:
    from opentsdb_tpu.expression.series import compute_tags
    tags = set(compute_tags([s.tags for s in series])[1])
    for s in series:
        tags.update(s.agg_tags)
    return sorted(tags)


GEXP_FUNCTIONS = {
    "alias": f_alias,
    "scale": f_scale,
    "absolute": f_absolute,
    "movingAverage": f_moving_average,
    "highestCurrent": f_highest_current,
    "highestMax": f_highest_max,
    "shift": f_time_shift,
    "timeShift": f_time_shift,
    "firstDiff": f_first_diff,
    "divideSeries": f_divide_series,
    "divide": f_divide_series,
    "sumSeries": f_sum_series,
    "sum": f_sum_series,
    "diffSeries": f_diff_series,
    "difference": f_diff_series,
    "multiplySeries": f_multiply_series,
    "multiply": f_multiply_series,
}


def evaluate_tree(tree: ExpressionTree,
                  metric_results: dict[str, list[SeriesResult]]
                  ) -> list[SeriesResult]:
    """Bottom-up evaluation; metric args resolve from metric_results."""
    args = []
    for a in tree.args:
        if isinstance(a, ExpressionTree):
            args.append(evaluate_tree(a, metric_results))
        elif isinstance(a, MetricRef):
            args.append(metric_results[a.query])
        else:
            args.append(a)
    return GEXP_FUNCTIONS[tree.func](args)


# --------------------------------------------------------------------- #
# /api/query/gexp endpoint                                               #
# --------------------------------------------------------------------- #


def handle_gexp_query(tsdb, query) -> None:
    """GET /api/query/gexp?start=...&exp=scale(sum:m,10) (QueryRpc :330)."""
    from opentsdb_tpu.models.tsquery import TSQuery, parse_m_subquery
    from opentsdb_tpu.tsd.http import BadRequestError
    from opentsdb_tpu.tsd.rpcs import allowed_methods
    allowed_methods(query, "GET", "POST")
    exprs = query.get_query_string_params("exp")
    if not exprs and query.request.body:
        body = query.json_body()
        exprs = body.get("expressions") or []
        if isinstance(exprs, str):
            exprs = [exprs]
    if not exprs:
        raise BadRequestError.missing_parameter("exp")
    trees = [parse_gexp(e) for e in exprs]

    metric_queries: list[str] = []
    for t in trees:
        metric_queries.extend(t.metric_queries())
    if not metric_queries:
        raise BadRequestError("No metric queries found in the expressions")

    ts_query = TSQuery(
        start=query.required_query_string_param("start"),
        end=query.get_query_string_param("end"),
        timezone=query.get_query_string_param("tz"),
        ms_resolution=query.has_query_string_param("ms"))
    seen = {}
    for mq in metric_queries:
        if mq not in seen:
            sub = parse_m_subquery(mq)
            sub.index = len(seen)
            seen[mq] = sub.index
            ts_query.queries.append(sub)
    ts_query.validate()
    # the cluster front door: fans to peers when configured (the gexp
    # functions then see the whole cluster's series), local otherwise
    from opentsdb_tpu.tsd.cluster import serve_query

    metric_results: dict[str, list[SeriesResult]] = {m: [] for m in seen}
    by_index = {i: m for m, i in seen.items()}
    exec_stats: dict = {}
    for qr in serve_query(tsdb, ts_query, query, exec_stats=exec_stats):
        metric_results[by_index[qr.index]].append(
            SeriesResult.from_query_result(qr))

    out = []
    for tree in trees:
        for s in evaluate_tree(tree, metric_results):
            out.append(s.to_query_json(ts_query.ms_resolution))
    from opentsdb_tpu.tsd.cluster import partial_annotation
    partial = partial_annotation(exec_stats)
    if partial:
        # degraded cluster serving: the 200 must not be silently partial
        # (same trailer convention as /api/query)
        out.append(partial)
    query.send_reply(out)
