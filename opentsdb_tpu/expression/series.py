"""Series containers + time alignment for the expression engines.

Reference behavior: PostAggregatedDataPoints.java (function outputs wrap
aggregated series) and TimeSyncedIterator.java (zip N series onto common
timestamps, missing values filled per NumericFillPolicy).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class SeriesResult:
    """One aggregated output series flowing through expression functions."""
    label: str                      # metric name / expression label
    tags: dict[str, str]
    agg_tags: list[str]
    ts: np.ndarray                  # int64 ms, sorted
    values: np.ndarray              # float64

    @staticmethod
    def from_query_result(qr) -> "SeriesResult":
        if qr.dps:
            ts = np.array([t for t, _ in qr.dps], dtype=np.int64)
            vals = np.array([float(v) for _, v in qr.dps], dtype=np.float64)
        else:
            ts = np.empty(0, np.int64)
            vals = np.empty(0, np.float64)
        return SeriesResult(label=qr.metric, tags=dict(qr.tags),
                            agg_tags=list(qr.aggregate_tags),
                            ts=ts, values=vals)

    def to_query_json(self, ms_resolution: bool = False) -> dict:
        dps = {}
        for t, v in zip(self.ts.tolist(), self.values.tolist()):
            key = str(t if ms_resolution else t // 1000)
            if np.isfinite(v) and v == int(v) and abs(v) < 2 ** 53:
                dps[key] = int(v)
            else:
                # NaN/Infinity serialize as bare literals, matching the
                # reference's Jackson writeNumber behavior.
                dps[key] = v
        return {"metric": self.label, "tags": self.tags,
                "aggregateTags": self.agg_tags, "dps": dps}

    def copy_with(self, label: str | None = None,
                  ts: np.ndarray | None = None,
                  values: np.ndarray | None = None) -> "SeriesResult":
        return SeriesResult(
            label=label if label is not None else self.label,
            tags=dict(self.tags), agg_tags=list(self.agg_tags),
            ts=self.ts if ts is None else ts,
            values=self.values if values is None else values)


def compute_tags(tag_maps: list[dict]) -> tuple[dict, list]:
    """SpanGroup.computeTags (:348): keys holding one distinct value across
    all maps stay tags, conflicting keys become aggregate tags.  The single
    implementation shared by the planner, gexp, and the exp executor."""
    tag_set: dict[str, str] = {}
    discards: set[str] = set()
    for tags in tag_maps:
        for k, v in tags.items():
            if k in discards:
                continue
            if k not in tag_set:
                tag_set[k] = v
            elif tag_set[k] != v:
                discards.add(k)
                tag_set.pop(k)
    return tag_set, sorted(discards)


def union_grid(series: list[SeriesResult]) -> np.ndarray:
    """Union of all timestamps across series (AggregationIterator's
    union-of-timestamps stance, applied host-side)."""
    if not series:
        return np.empty(0, np.int64)
    return np.unique(np.concatenate([s.ts for s in series]))


def align(series: list[SeriesResult], grid: np.ndarray,
          fill: float = np.nan) -> np.ndarray:
    """[S, len(grid)] value matrix; timestamps a series lacks get `fill`."""
    out = np.full((len(series), len(grid)), fill, dtype=np.float64)
    for i, s in enumerate(series):
        if len(s.ts) == 0:
            continue
        idx = np.searchsorted(grid, s.ts)
        out[i, idx] = s.values
    return out
