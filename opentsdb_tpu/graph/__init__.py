"""Graph rendering (the gnuplot subprocess replacement).

Reference behavior: /root/reference/src/graph/Plot.java (:39 — writes
gnuplot scripts + per-series data files rendered by an external gnuplot
binary via mygnuplot.sh).  Rebuilt as a dependency-free SVG renderer: same
role (axes/ticks/series/legend from query results), no subprocess.
"""

from opentsdb_tpu.graph.plot import Plot

__all__ = ["Plot"]
