"""SVG time-series plot renderer.

Plays Plot.java's role (axis/format options, per-series data, :266
writeGnuplotScript) with an inline SVG instead of gnuplot output.  Series
colors follow gnuplot's classic default cycle.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from xml.sax.saxutils import escape

# gnuplot's classic line-color cycle
COLORS = ("#ff0000", "#00c000", "#0080ff", "#c000ff", "#00eeee",
          "#c04000", "#c8c800", "#4169e1", "#ffc020", "#008040")


@dataclass
class PlotSeries:
    label: str
    points: list[tuple[int, float]]   # (ts_ms, value)


@dataclass
class Plot:
    """Collects series + options, emits SVG (Plot.java:39)."""
    start_time: int                    # ms
    end_time: int                      # ms
    width: int = 1024
    height: int = 576
    title: str = ""
    ylabel: str = ""
    yrange: tuple[float, float] | None = None
    ylog: bool = False
    nokey: bool = False               # hide the legend
    series: list[PlotSeries] = field(default_factory=list)

    MARGIN_LEFT = 70
    MARGIN_RIGHT = 20
    MARGIN_TOP = 30
    MARGIN_BOTTOM = 60

    def add_series(self, label: str,
                   points: list[tuple[int, float]]) -> None:
        self.series.append(PlotSeries(label, points))

    # -- scales --

    def _y_domain(self) -> tuple[float, float]:
        # gnuplot range semantics: either end may be None (open, "[0:]")
        # and is then computed from the data (GraphHandler.java yrange)
        fix_lo = fix_hi = None
        if self.yrange is not None:
            fix_lo, fix_hi = self.yrange
            if fix_lo is not None and fix_hi is not None:
                return fix_lo, fix_hi
        lo, hi = math.inf, -math.inf
        for s in self.series:
            for _, v in s.points:
                if v == v and not math.isinf(v):     # skip NaN/Inf
                    lo = min(lo, v)
                    hi = max(hi, v)
        if lo is math.inf:
            lo, hi = 0.0, 1.0
        elif lo == hi:
            pad = abs(lo) * 0.1 or 1.0
            lo, hi = lo - pad, hi + pad
        else:
            pad = (hi - lo) * 0.05
            lo, hi = lo - pad, hi + pad
        if fix_lo is not None:
            lo = fix_lo
        if fix_hi is not None:
            hi = fix_hi
        if lo >= hi:            # fixed end collapsed the range
            hi = lo + (abs(lo) * 0.1 or 1.0)
        return lo, hi

    def _x_px(self, ts: int) -> float:
        span = max(self.end_time - self.start_time, 1)
        inner = self.width - self.MARGIN_LEFT - self.MARGIN_RIGHT
        return self.MARGIN_LEFT + (ts - self.start_time) / span * inner

    def _y_px(self, v: float, lo: float, hi: float) -> float:
        inner = self.height - self.MARGIN_TOP - self.MARGIN_BOTTOM
        if self.ylog and lo > 0:
            frac = (math.log10(v) - math.log10(lo)) / \
                (math.log10(hi) - math.log10(lo))
        else:
            frac = (v - lo) / (hi - lo)
        return self.height - self.MARGIN_BOTTOM - frac * inner

    @staticmethod
    def _nice_ticks(lo: float, hi: float, n: int = 6) -> list[float]:
        span = hi - lo
        if span <= 0:
            return [lo]
        raw = span / n
        mag = 10 ** math.floor(math.log10(raw))
        for mult in (1, 2, 2.5, 5, 10):
            if raw <= mult * mag:
                step = mult * mag
                break
        first = math.ceil(lo / step) * step
        ticks = []
        t = first
        while t <= hi + 1e-9 * span:
            ticks.append(round(t, 10))
            t += step
        return ticks

    def _time_ticks(self) -> list[tuple[int, str]]:
        span_s = (self.end_time - self.start_time) / 1000
        if span_s <= 0:
            return []
        if span_s <= 3 * 3600:
            step, fmt = 15 * 60, "%H:%M"
        elif span_s <= 26 * 3600:
            step, fmt = 2 * 3600, "%H:%M"
        elif span_s <= 8 * 86400:
            step, fmt = 86400, "%m/%d"
        else:
            step, fmt = 7 * 86400, "%m/%d"
        start_s = self.start_time // 1000
        first = (start_s // step + 1) * step
        out = []
        t = first
        while t * 1000 <= self.end_time:
            out.append((t * 1000, time.strftime(fmt, time.gmtime(t))))
            t += step
        return out

    # -- render --

    def render_svg(self) -> str:
        lo, hi = self._y_domain()
        w, h = self.width, self.height
        plot_left = self.MARGIN_LEFT
        plot_right = w - self.MARGIN_RIGHT
        plot_top = self.MARGIN_TOP
        plot_bottom = h - self.MARGIN_BOTTOM
        parts = [
            '<svg xmlns="http://www.w3.org/2000/svg" width="%d" '
            'height="%d" viewBox="0 0 %d %d" '
            'font-family="sans-serif" font-size="11">' % (w, h, w, h),
            '<rect width="%d" height="%d" fill="white"/>' % (w, h),
        ]
        if self.title:
            parts.append(
                '<text x="%d" y="18" text-anchor="middle" '
                'font-size="14">%s</text>' % (w // 2, escape(self.title)))
        # gridlines + y ticks
        for tick in self._nice_ticks(lo, hi):
            y = self._y_px(tick, lo, hi)
            if not plot_top <= y <= plot_bottom:
                continue
            parts.append(
                '<line x1="%d" y1="%.1f" x2="%d" y2="%.1f" '
                'stroke="#dddddd"/>' % (plot_left, y, plot_right, y))
            parts.append(
                '<text x="%d" y="%.1f" text-anchor="end" '
                'dominant-baseline="middle">%s</text>'
                % (plot_left - 6, y, _fmt_value(tick)))
        # x ticks
        for ts, label in self._time_ticks():
            x = self._x_px(ts)
            parts.append(
                '<line x1="%.1f" y1="%d" x2="%.1f" y2="%d" '
                'stroke="#dddddd"/>' % (x, plot_top, x, plot_bottom))
            parts.append(
                '<text x="%.1f" y="%d" text-anchor="middle">%s</text>'
                % (x, plot_bottom + 16, escape(label)))
        # frame
        parts.append(
            '<rect x="%d" y="%d" width="%d" height="%d" fill="none" '
            'stroke="black"/>' % (plot_left, plot_top,
                                  plot_right - plot_left,
                                  plot_bottom - plot_top))
        if self.ylabel:
            parts.append(
                '<text x="14" y="%d" transform="rotate(-90 14 %d)" '
                'text-anchor="middle">%s</text>'
                % ((plot_top + plot_bottom) // 2,
                   (plot_top + plot_bottom) // 2, escape(self.ylabel)))
        # series polylines
        for i, s in enumerate(self.series):
            color = COLORS[i % len(COLORS)]
            coords = []
            for ts, v in s.points:
                if v != v or math.isinf(v):
                    continue
                if self.ylog and v <= 0:
                    continue
                coords.append("%.1f,%.1f"
                              % (self._x_px(ts),
                                 max(plot_top, min(plot_bottom,
                                     self._y_px(v, lo, hi)))))
            if coords:
                parts.append(
                    '<polyline fill="none" stroke="%s" stroke-width="1.5" '
                    'points="%s"/>' % (color, " ".join(coords)))
        # legend
        if not self.nokey:
            for i, s in enumerate(self.series[:10]):
                color = COLORS[i % len(COLORS)]
                y = plot_bottom + 34 + (i % 2) * 14
                x = plot_left + (i // 2) * 240
                parts.append(
                    '<line x1="%d" y1="%d" x2="%d" y2="%d" stroke="%s" '
                    'stroke-width="2"/>' % (x, y - 4, x + 18, y - 4, color))
                parts.append(
                    '<text x="%d" y="%d">%s</text>'
                    % (x + 24, y, escape(s.label[:60])))
        parts.append("</svg>")
        return "".join(parts)


def _fmt_value(v: float) -> str:
    if abs(v) >= 1e9:
        return "%.1fG" % (v / 1e9)
    if abs(v) >= 1e6:
        return "%.1fM" % (v / 1e6)
    if abs(v) >= 1e4:
        return "%.1fk" % (v / 1e3)
    if v == int(v):
        return str(int(v))
    return "%g" % v
