"""Histogram / sketch subsystem.

Reference behavior: /root/reference/src/core/ histogram stack (17 files) —
SimpleHistogram.java (bucket codec + midpoint percentile rule),
HistogramCodecManager.java (codec registry from tsd.core.histograms.config),
HistogramSpan/SpanGroup/AggregationIterator/Downsampler (read path merging
bucket counts), HistogramPojo.java (JSON ingest shape), and the
DataPoints adaptors labeling percentile outputs `metric_pct_<p>` and bucket
outputs `metric_bucket_...`.
"""

from opentsdb_tpu.histogram.simple import SimpleHistogram
from opentsdb_tpu.histogram.codec import HistogramCodecManager
from opentsdb_tpu.histogram.store import HistogramStore

__all__ = ["SimpleHistogram", "HistogramCodecManager", "HistogramStore"]
