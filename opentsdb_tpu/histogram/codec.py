"""Histogram codec registry from tsd.core.histograms.config.

Reference behavior: /root/reference/src/core/HistogramCodecManager.java
(:36-71) — the config value is JSON (inline or a .json file path) mapping
decoder names to IDs, e.g. {"net.opentsdb.core.SimpleHistogramDecoder": 0}.
IDs must be unique in [0, 255].  Here decoder names resolve to codec classes
by simple name, and only SimpleHistogramDecoder ships.
"""

from __future__ import annotations

import json

from opentsdb_tpu.histogram.simple import SimpleHistogram


class SimpleHistogramDecoder:
    """Codec for SimpleHistogram payloads."""

    def __init__(self, codec_id: int):
        self.id = codec_id

    def decode(self, raw: bytes, includes_id: bool = False
               ) -> SimpleHistogram:
        out = SimpleHistogram.from_bytes(raw, include_id=includes_id)
        out.id = self.id
        return out

    def encode(self, histogram: SimpleHistogram,
               include_id: bool = True) -> bytes:
        return histogram.to_bytes(include_id=include_id)


_KNOWN_DECODERS = {
    "SimpleHistogramDecoder": SimpleHistogramDecoder,
}


class HistogramCodecManager:
    def __init__(self, config_text: str):
        if not config_text:
            raise ValueError(
                "Histogram support requires 'tsd.core.histograms.config'")
        if config_text.strip().endswith(".json"):
            with open(config_text.strip()) as fh:
                mapping = json.load(fh)
        else:
            mapping = json.loads(config_text)
        self.codecs: dict[int, object] = {}
        for name, codec_id in mapping.items():
            codec_id = int(codec_id)
            if not 0 <= codec_id <= 255:
                raise ValueError(
                    "ID for decoder '%s' must be between 0 and 255" % name)
            if codec_id in self.codecs:
                raise ValueError(
                    "Duplicate histogram decoder ID: %d" % codec_id)
            simple_name = name.rsplit(".", 1)[-1]
            cls = _KNOWN_DECODERS.get(simple_name)
            if cls is None:
                raise ValueError(
                    "Unable to find a decoder named '%s'" % name)
            self.codecs[codec_id] = cls(codec_id)

    def get_codec(self, codec_id: int):
        codec = self.codecs.get(codec_id)
        if codec is None:
            raise ValueError("No histogram codec with ID: %d" % codec_id)
        return codec

    @staticmethod
    def from_config(config) -> "HistogramCodecManager | None":
        raw = config.get_string("tsd.core.histograms.config")
        if not raw:
            return None
        return HistogramCodecManager(raw)
