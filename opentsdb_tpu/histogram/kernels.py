"""Device kernels for the histogram query path (VERDICT r3 #4).

Reference behavior: the histogram read stack
(/root/reference/src/core/HistogramSpan.java:585,
HistogramSpanGroup.java:529, HistogramAggregationIterator.java:319,
HistogramDownsampler.java:403) merges per-series histogram points with
per-datapoint iterator chains.  TPU-first form: ALL groups of a query
flatten into one (entry -> cell) segment-sum onto a [rows, B] bucket
grid — rows are every group's data-bearing windows stacked — and the
percentile rule (cumulative share -> first bucket -> midpoint,
SimpleHistogram.percentile) runs vectorized over the whole grid in the
same dispatch.  One device call per query, any group/series count.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnums=(2, 3))
def accumulate_rows(seg, cnt, num_rows: int, num_buckets: int):
    """Scatter nnz bucket entries onto the [rows, B] count grid.

    `seg[nnz]` is row * num_buckets + bucket, int64 counts accumulate
    exactly (x64 is enabled process-wide)."""
    grid = jax.ops.segment_sum(cnt, seg,
                               num_segments=num_rows * num_buckets)
    return grid.reshape(num_rows, num_buckets)


@jax.jit
def percentile_rows(counts, mid, percs):
    """[R, B] counts + bucket midpoints -> [P, R] percentile values.

    The SimpleHistogram.percentile rule: cumulative share along the
    bound-sorted bucket axis, first bucket reaching p, midpoint.  Rows
    with no mass answer 0.0; out-of-domain percentiles answer -1.0
    (HistogramPointRpc validation range).  Zero-count padding columns
    (vocabulary union / pow2 pad) never win the argmax: a padding column
    ties the PRECEDING real bucket's share and argmax takes the first.
    """
    cum = jnp.cumsum(counts, axis=1)
    total = cum[:, -1]
    has = total > 0
    share = jnp.where(has[:, None],
                      cum * 100.0 / jnp.maximum(total[:, None], 1), 0.0)

    def one(p):
        valid = (p >= 1.0) & (p <= 100.0)
        idx = jnp.argmax(share >= p, axis=1)
        vals = jnp.where(has, mid[idx], 0.0)
        return jnp.where(valid, vals, -1.0)

    return jax.vmap(one)(percs)
