"""SimpleHistogram: explicit-bucket histogram + binary codec.

Reference behavior: /root/reference/src/core/SimpleHistogram.java — sorted
(lower, upper) float buckets with int64 counts plus underflow/overflow;
binary layout `[id?][short nbuckets][float lo][float hi][varlong count]...
[varlong under][varlong over]` (histogram() :~57-80, Kryo positive-varint
longs); percentile(p) returns the MIDPOINT of the first bucket whose
cumulative share reaches p (:~118-148 — not interpolated; the interpolating
variant is commented out in the reference too).
"""

from __future__ import annotations

import base64
import struct


def write_varlong(value: int) -> bytes:
    """Kryo writeLong(v, optimizePositive=true): little-endian 7-bit groups,
    high bit = continuation."""
    if value < 0:
        raise ValueError("negative count: %d" % value)
    out = bytearray()
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def read_varlong(data: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


class SimpleHistogram:
    """Explicit-bucket histogram with the reference's aggregation rules."""

    def __init__(self, hist_id: int = 0):
        self.id = hist_id
        self.buckets: dict[tuple[float, float], int] = {}
        self.underflow = 0
        self.overflow = 0

    def add_bucket(self, lo: float, hi: float, count: int) -> None:
        if lo is None or hi is None:
            return
        self.buckets[(float(lo), float(hi))] = int(count or 0)

    def aggregate(self, other: "SimpleHistogram") -> None:
        """Merge counts; identical bounds accumulate (SimpleHistogram
        aggregation via HistogramAggregation.SUM)."""
        for bounds, count in other.buckets.items():
            self.buckets[bounds] = self.buckets.get(bounds, 0) + count
        self.underflow += other.underflow
        self.overflow += other.overflow

    def bucket_sum(self) -> int:
        return sum(self.buckets.values())

    def percentile(self, perc: float) -> float:
        """Midpoint of the first bucket reaching the cumulative share."""
        if perc < 1.0 or perc > 100.0:
            return -1.0
        total = self.bucket_sum()
        if total == 0:
            return 0.0
        running = 0
        for (lo, hi) in sorted(self.buckets):
            running += self.buckets[(lo, hi)]
            if running * 100.0 / total >= perc:
                return (lo + hi) / 2.0
        return 0.0

    def percentiles(self, percs: list[float]) -> list[float]:
        return [self.percentile(p) for p in percs]

    # -- binary codec --

    def to_bytes(self, include_id: bool = True) -> bytes:
        out = bytearray()
        if include_id:
            out.append(self.id & 0xFF)
        out += struct.pack(">h", len(self.buckets))
        for (lo, hi) in sorted(self.buckets):
            out += struct.pack(">f", lo)
            out += struct.pack(">f", hi)
            out += write_varlong(self.buckets[(lo, hi)])
        out += write_varlong(self.underflow)
        out += write_varlong(self.overflow)
        return bytes(out)

    @classmethod
    def from_bytes(cls, raw: bytes, include_id: bool = True
                   ) -> "SimpleHistogram":
        if len(raw) < 6:
            raise ValueError("Byte array shorter than 6 bytes")
        pos = 0
        hist_id = 0
        if include_id:
            hist_id = raw[0]
            pos = 1
        out = cls(hist_id)
        (n,) = struct.unpack_from(">h", raw, pos)
        pos += 2
        for _ in range(n):
            (lo,) = struct.unpack_from(">f", raw, pos)
            (hi,) = struct.unpack_from(">f", raw, pos + 4)
            pos += 8
            count, pos = read_varlong(raw, pos)
            out.buckets[(lo, hi)] = count
        out.underflow, pos = read_varlong(raw, pos)
        out.overflow, pos = read_varlong(raw, pos)
        return out

    def to_base64(self, include_id: bool = True) -> str:
        return base64.b64encode(self.to_bytes(include_id)).decode()

    @classmethod
    def from_base64(cls, encoded: str, include_id: bool = True
                    ) -> "SimpleHistogram":
        return cls.from_bytes(base64.b64decode(encoded), include_id)

    # -- JSON (HistogramPojo: buckets keyed "lo,hi") --

    @classmethod
    def from_pojo(cls, dp: dict, hist_id: int = 0) -> "SimpleHistogram":
        out = cls(int(dp.get("id", hist_id)))
        for key, count in (dp.get("buckets") or {}).items():
            lo, hi = key.split(",")
            out.add_bucket(float(lo), float(hi), int(count))
        out.underflow = int(dp.get("underflow", 0))
        out.overflow = int(dp.get("overflow", 0))
        return out

    def to_json(self) -> dict:
        return {
            "id": self.id,
            "buckets": {"%g,%g" % b: c
                        for b, c in sorted(self.buckets.items())},
            "underflow": self.underflow,
            "overflow": self.overflow,
        }

    def __eq__(self, other) -> bool:
        return (isinstance(other, SimpleHistogram)
                and self.buckets == other.buckets
                and self.underflow == other.underflow
                and self.overflow == other.overflow)

    def __repr__(self) -> str:
        return "SimpleHistogram(id=%d, %d buckets, sum=%d)" % (
            self.id, len(self.buckets), self.bucket_sum())
