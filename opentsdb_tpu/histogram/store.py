"""Columnar histogram storage + the vectorized percentile read path.

Reference behavior: the histogram Span/RowSeq/SpanGroup/Downsampler stack
(/root/reference/src/core/HistogramSpan.java, HistogramSpanGroup.java:67,
HistogramDownsampler.java, HistogramAggregationIterator.java) — assemble
per-series histogram sequences, merge across series at shared timestamps,
and answer percentile queries.

TPU-first transform: a group's histograms become a dense [T, B] bucket-count
matrix over the union of bucket bounds; downsampling is a segment-sum over
window ids, the percentile rule (cumulative share -> bucket midpoint,
SimpleHistogram.percentile) is one vectorized cumsum + argmax per window —
replacing the per-datapoint iterator merges.
"""

from __future__ import annotations

import threading

import numpy as np

from opentsdb_tpu.histogram.simple import SimpleHistogram
from opentsdb_tpu.storage.memstore import SeriesKey


class HistogramSeries:
    """One series' histogram points: parallel (ts, histogram) lists.

    `columns()` maintains a columnar CSR image (ts[N] + per-point bucket
    id/count runs over a per-series bucket vocabulary), built once per
    write burst — the Python per-point/per-bucket walk that round 3's
    query path paid on EVERY query (VERDICT r3 weak #6) amortizes to
    ingest rate, and batch assembly becomes pure array ops.
    """

    def __init__(self, key: SeriesKey):
        self.key = key
        # guarded-by: _lock
        self._ts: list[int] = []
        self._hists: list[SimpleHistogram] = []  # guarded-by: _lock
        self._sorted = True  # guarded-by: _lock
        self._lock = threading.Lock()
        # (ts[N], indptr[N+1], bids[nnz], cnts[nnz])
        self._cols = None  # guarded-by: _lock
        self._vocab: list[tuple[float, float]] = []   # local id -> bounds

    def append(self, ts_ms: int, hist: SimpleHistogram) -> None:
        with self._lock:
            if self._ts and ts_ms < self._ts[-1]:
                self._sorted = False
                self._cols = None    # the re-sort shuffles everything
            self._ts.append(ts_ms)
            self._hists.append(hist)
            # in-order appends keep the columnar image: columns()
            # detects the length gap and extends incrementally

    def _normalize_locked(self) -> None:
        if not self._sorted:
            order = np.argsort(np.asarray(self._ts, dtype=np.int64),
                               kind="stable")
            self._ts = [self._ts[i] for i in order]
            self._hists = [self._hists[i] for i in order]
            self._sorted = True
            self._cols = None

    def window(self, start_ms: int, end_ms: int
               ) -> list[tuple[int, SimpleHistogram]]:
        with self._lock:
            self._normalize_locked()
            lo = int(np.searchsorted(np.asarray(self._ts), start_ms, "left"))
            hi = int(np.searchsorted(np.asarray(self._ts), end_ms, "right"))
            return list(zip(self._ts[lo:hi], self._hists[lo:hi]))

    def count_in_range(self, start_ms: int, end_ms: int) -> int:
        """Points in [start_ms, end_ms] without materializing anything
        (budget charging BEFORE assembly work, review r4)."""
        with self._lock:
            self._normalize_locked()
            ts = np.asarray(self._ts, np.int64)
            return int(np.searchsorted(ts, end_ms, "right")
                       - np.searchsorted(ts, start_ms, "left"))

    def columns(self):
        """(ts[N], indptr[N+1], bids[nnz], cnts[nnz], vocab) — stable
        arrays (rebuilt, never mutated) safe to use outside the lock.

        In-order appends EXTEND the previous image (the Python
        per-bucket walk covers only the new points; array concats are
        vectorized), so a steady write+query mix pays O(new), not
        O(total), per query.  Out-of-order appends re-sort and rebuild.
        """
        with self._lock:
            self._normalize_locked()
            start = 0
            old = self._cols
            if old is not None and len(old[1]) - 1 == len(self._hists):
                return old + (list(self._vocab),)
            if old is not None:
                start = len(old[1]) - 1
            vocab_idx = {b: i for i, b in enumerate(self._vocab)}
            indptr = np.zeros(len(self._hists) - start + 1, np.int64)
            base = int(old[1][-1]) if old is not None else 0
            indptr[0] = base
            bids: list[int] = []
            cnts: list[int] = []
            for i, h in enumerate(self._hists[start:]):
                for b, c in h.buckets.items():
                    gi = vocab_idx.get(b)
                    if gi is None:
                        gi = vocab_idx[b] = len(self._vocab)
                        self._vocab.append(b)
                    bids.append(gi)
                    cnts.append(c)
                indptr[i + 1] = base + len(bids)
            new_ts = np.asarray(self._ts[start:], np.int64)
            new_bids = np.asarray(bids, np.int64)
            new_cnts = np.asarray(cnts, np.int64)
            if old is None:
                self._cols = (new_ts, indptr, new_bids, new_cnts)
            else:
                self._cols = (np.concatenate([old[0], new_ts]),
                              np.concatenate([old[1], indptr[1:]]),
                              np.concatenate([old[2], new_bids]),
                              np.concatenate([old[3], new_cnts]))
            return self._cols + (list(self._vocab),)

    def __len__(self) -> int:
        return len(self._ts)


class HistogramStore:
    """All histogram series, keyed like the scalar MemStore."""

    def __init__(self):
        # guarded-by: _lock
        self._series: dict[SeriesKey, HistogramSeries] = {}
        self._by_metric: dict[int, set[SeriesKey]] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self.datapoints_added = 0  # guarded-by: _lock

    def add_point(self, key: SeriesKey, ts_ms: int,
                  hist: SimpleHistogram) -> None:
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = HistogramSeries(key)
                self._series[key] = series
                self._by_metric.setdefault(key.metric, set()).add(key)
            self.datapoints_added += 1
        series.append(ts_ms, hist)

    def series_for_metric(self, metric: int) -> list[HistogramSeries]:
        with self._lock:
            return [self._series[k]
                    for k in self._by_metric.get(metric, ())]

    def all_series(self) -> list[HistogramSeries]:
        with self._lock:
            return list(self._series.values())

    @property
    def num_series(self) -> int:
        with self._lock:
            return len(self._series)


# --------------------------------------------------------------------- #
# Columnar all-groups batch assembly (device query path)                 #
# --------------------------------------------------------------------- #


def _pad_pow2(n: int, floor: int = 8) -> int:
    p = floor
    while p < n:
        p *= 2
    return p


def assemble_columnar(groups_members, start_ms: int, end_ms: int,
                      interval_ms: int):
    """Flatten every group's histogram points into one device batch.

    `groups_members`: ordered [(group_key, [HistogramSeries, ...]), ...].
    Returns None when no group has data in range, else a dict with
      seg[nnz], cnt[nnz]   flat (row * n_buckets + bucket) scatter entries
      n_rows, n_buckets    padded static dims for the jitted kernels
      bounds[B, 2], mid[n_buckets]   bound-sorted global bucket vocabulary
      groups: [(group_key, row_lo, row_hi, ts[T_g], used[Ug], points)]
    Rows are each group's data-bearing windows (unique timestamps, or
    epoch-aligned edges when downsampling) stacked in group order —
    uniform [rows, B] shape from ragged per-group grids, so ONE dispatch
    serves any group count.  All index math is vectorized numpy; the
    per-bucket Python walk lives in HistogramSeries.columns(), amortized
    to ingest.
    """
    # pass 1: slices + global bound-sorted bucket vocabulary
    vocab: dict[tuple[float, float], int] = {}
    sliced = []     # (group_key, [(series_cols, lo, hi)])
    for group_key, members in groups_members:
        cuts = []
        for s in members:
            ts, indptr, bids, cnts, svocab = s.columns()
            lo = int(np.searchsorted(ts, start_ms, "left"))
            hi = int(np.searchsorted(ts, end_ms, "right"))
            if hi > lo:
                cuts.append(((ts, indptr, bids, cnts, svocab), lo, hi))
                for b in svocab:
                    vocab.setdefault(b, 0)
        if cuts:
            sliced.append((group_key, cuts))
    if not sliced:
        return None
    bounds_sorted = sorted(vocab)
    for i, b in enumerate(bounds_sorted):
        vocab[b] = i
    n_b = len(bounds_sorted)
    b_pad = _pad_pow2(max(n_b, 1))

    # pass 2: per-group rows + flat scatter entries
    seg_parts, cnt_parts, groups = [], [], []
    row_base = 0
    for group_key, cuts in sliced:
        keys_parts = []
        for (ts, indptr, bids, cnts, svocab), lo, hi in cuts:
            w = ts[lo:hi]
            keys_parts.append(w - w % interval_ms if interval_ms > 0 else w)
        edges = np.unique(np.concatenate(keys_parts))
        used_parts = []
        points = 0
        for part_keys, ((ts, indptr, bids, cnts, svocab), lo, hi) \
                in zip(keys_parts, cuts):
            points += hi - lo
            rows = np.searchsorted(edges, part_keys)
            e_lo, e_hi = int(indptr[lo]), int(indptr[hi])
            entry_pt = np.repeat(np.arange(hi - lo),
                                 np.diff(indptr[lo:hi + 1]))
            gmap = np.asarray([vocab[b] for b in svocab], np.int64)
            entry_bid = gmap[bids[e_lo:e_hi]]
            seg_parts.append((row_base + rows[entry_pt]) * b_pad
                             + entry_bid)
            cnt_parts.append(cnts[e_lo:e_hi])
            used_parts.append(entry_bid)
        groups.append((group_key, row_base, row_base + len(edges), edges,
                       np.unique(np.concatenate(used_parts)), points))
        row_base += len(edges)

    bounds = np.asarray(bounds_sorted, np.float64).reshape(-1, 2)
    mid = np.zeros(b_pad, np.float64)
    mid[:n_b] = (bounds[:, 0] + bounds[:, 1]) / 2.0
    n_rows = _pad_pow2(max(row_base, 1))
    seg = np.concatenate(seg_parts)
    if n_rows * b_pad < 2 ** 31:
        # scatter ids ride int32 (int64 is an emulated u32 pair on TPU);
        # counts stay int64 — they are exact Java longs
        seg = seg.astype(np.int32)
    return {
        "seg": seg,
        "cnt": np.concatenate(cnt_parts),
        "n_rows": n_rows,
        "n_buckets": b_pad,
        "n_real_buckets": n_b,
        "bounds": bounds,
        "mid": mid,
        "groups": groups,
    }


# --------------------------------------------------------------------- #
# Vectorized merge + percentile kernels                                  #
# --------------------------------------------------------------------- #


def merge_group(points: list[tuple[int, SimpleHistogram]]
                ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(ts[T], counts[T, B], bounds[B, 2]) over the union of bucket bounds.

    Points sharing a timestamp (across series of one group) accumulate —
    the HistogramAggregationIterator SUM merge.
    """
    bounds_set = sorted({b for _, h in points for b in h.buckets})
    bounds_idx = {b: i for i, b in enumerate(bounds_set)}
    ts_sorted = sorted({t for t, _ in points})
    ts_idx = {t: i for i, t in enumerate(ts_sorted)}
    counts = np.zeros((len(ts_sorted), len(bounds_set)), dtype=np.int64)
    for t, h in points:
        row = ts_idx[t]
        for b, c in h.buckets.items():
            counts[row, bounds_idx[b]] += c
    bounds = np.asarray(bounds_set, dtype=np.float64).reshape(-1, 2) \
        if bounds_set else np.zeros((0, 2))
    return (np.asarray(ts_sorted, dtype=np.int64), counts, bounds)


def downsample_counts(ts: np.ndarray, counts: np.ndarray,
                      interval_ms: int) -> tuple[np.ndarray, np.ndarray]:
    """Sum bucket counts per epoch-aligned window (HistogramDownsampler)."""
    if len(ts) == 0:
        return ts, counts
    win = ts - ts % interval_ms
    edges, inverse = np.unique(win, return_inverse=True)
    out = np.zeros((len(edges), counts.shape[1]), dtype=np.int64)
    np.add.at(out, inverse, counts)
    return edges, out


def percentiles_of(counts: np.ndarray, bounds: np.ndarray,
                   percs: list[float]) -> np.ndarray:
    """[T, B] counts -> [P, T] percentile values (midpoint rule).

    Vectorized SimpleHistogram.percentile: cumulative share along the
    sorted-bucket axis, first bucket reaching p, midpoint of its bounds.
    """
    t, b = counts.shape
    out = np.zeros((len(percs), t), dtype=np.float64)
    if b == 0 or t == 0:
        return out
    cum = np.cumsum(counts, axis=1)
    total = cum[:, -1]
    mid = (bounds[:, 0] + bounds[:, 1]) / 2.0
    with np.errstate(divide="ignore", invalid="ignore"):
        share = cum * 100.0 / total[:, None]
    for i, p in enumerate(percs):
        if p < 1.0 or p > 100.0:
            out[i, :] = -1.0
            continue
        hit = share >= p
        idx = np.argmax(hit, axis=1)
        vals = mid[idx]
        vals = np.where(total > 0, vals, 0.0)
        out[i, :] = vals
    return out
