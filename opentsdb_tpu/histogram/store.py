"""Columnar histogram storage + the vectorized percentile read path.

Reference behavior: the histogram Span/RowSeq/SpanGroup/Downsampler stack
(/root/reference/src/core/HistogramSpan.java, HistogramSpanGroup.java:67,
HistogramDownsampler.java, HistogramAggregationIterator.java) — assemble
per-series histogram sequences, merge across series at shared timestamps,
and answer percentile queries.

TPU-first transform: a group's histograms become a dense [T, B] bucket-count
matrix over the union of bucket bounds; downsampling is a segment-sum over
window ids, the percentile rule (cumulative share -> bucket midpoint,
SimpleHistogram.percentile) is one vectorized cumsum + argmax per window —
replacing the per-datapoint iterator merges.
"""

from __future__ import annotations

import threading

import numpy as np

from opentsdb_tpu.histogram.simple import SimpleHistogram
from opentsdb_tpu.storage.memstore import SeriesKey


class HistogramSeries:
    """One series' histogram points: parallel (ts, histogram) lists."""

    def __init__(self, key: SeriesKey):
        self.key = key
        self._ts: list[int] = []
        self._hists: list[SimpleHistogram] = []
        self._sorted = True
        self._lock = threading.Lock()

    def append(self, ts_ms: int, hist: SimpleHistogram) -> None:
        with self._lock:
            if self._ts and ts_ms < self._ts[-1]:
                self._sorted = False
            self._ts.append(ts_ms)
            self._hists.append(hist)

    def window(self, start_ms: int, end_ms: int
               ) -> list[tuple[int, SimpleHistogram]]:
        with self._lock:
            if not self._sorted:
                order = np.argsort(np.asarray(self._ts, dtype=np.int64),
                                   kind="stable")
                self._ts = [self._ts[i] for i in order]
                self._hists = [self._hists[i] for i in order]
                self._sorted = True
            lo = int(np.searchsorted(np.asarray(self._ts), start_ms, "left"))
            hi = int(np.searchsorted(np.asarray(self._ts), end_ms, "right"))
            return list(zip(self._ts[lo:hi], self._hists[lo:hi]))

    def __len__(self) -> int:
        return len(self._ts)


class HistogramStore:
    """All histogram series, keyed like the scalar MemStore."""

    def __init__(self):
        self._series: dict[SeriesKey, HistogramSeries] = {}
        self._by_metric: dict[int, set[SeriesKey]] = {}
        self._lock = threading.Lock()
        self.datapoints_added = 0

    def add_point(self, key: SeriesKey, ts_ms: int,
                  hist: SimpleHistogram) -> None:
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = HistogramSeries(key)
                self._series[key] = series
                self._by_metric.setdefault(key.metric, set()).add(key)
            self.datapoints_added += 1
        series.append(ts_ms, hist)

    def series_for_metric(self, metric: int) -> list[HistogramSeries]:
        with self._lock:
            return [self._series[k]
                    for k in self._by_metric.get(metric, ())]

    def all_series(self) -> list[HistogramSeries]:
        with self._lock:
            return list(self._series.values())

    @property
    def num_series(self) -> int:
        with self._lock:
            return len(self._series)


# --------------------------------------------------------------------- #
# Vectorized merge + percentile kernels                                  #
# --------------------------------------------------------------------- #


def merge_group(points: list[tuple[int, SimpleHistogram]]
                ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(ts[T], counts[T, B], bounds[B, 2]) over the union of bucket bounds.

    Points sharing a timestamp (across series of one group) accumulate —
    the HistogramAggregationIterator SUM merge.
    """
    bounds_set = sorted({b for _, h in points for b in h.buckets})
    bounds_idx = {b: i for i, b in enumerate(bounds_set)}
    ts_sorted = sorted({t for t, _ in points})
    ts_idx = {t: i for i, t in enumerate(ts_sorted)}
    counts = np.zeros((len(ts_sorted), len(bounds_set)), dtype=np.int64)
    for t, h in points:
        row = ts_idx[t]
        for b, c in h.buckets.items():
            counts[row, bounds_idx[b]] += c
    bounds = np.asarray(bounds_set, dtype=np.float64).reshape(-1, 2) \
        if bounds_set else np.zeros((0, 2))
    return (np.asarray(ts_sorted, dtype=np.int64), counts, bounds)


def downsample_counts(ts: np.ndarray, counts: np.ndarray,
                      interval_ms: int) -> tuple[np.ndarray, np.ndarray]:
    """Sum bucket counts per epoch-aligned window (HistogramDownsampler)."""
    if len(ts) == 0:
        return ts, counts
    win = ts - ts % interval_ms
    edges, inverse = np.unique(win, return_inverse=True)
    out = np.zeros((len(edges), counts.shape[1]), dtype=np.int64)
    np.add.at(out, inverse, counts)
    return edges, out


def percentiles_of(counts: np.ndarray, bounds: np.ndarray,
                   percs: list[float]) -> np.ndarray:
    """[T, B] counts -> [P, T] percentile values (midpoint rule).

    Vectorized SimpleHistogram.percentile: cumulative share along the
    sorted-bucket axis, first bucket reaching p, midpoint of its bounds.
    """
    t, b = counts.shape
    out = np.zeros((len(percs), t), dtype=np.float64)
    if b == 0 or t == 0:
        return out
    cum = np.cumsum(counts, axis=1)
    total = cum[:, -1]
    mid = (bounds[:, 0] + bounds[:, 1]) / 2.0
    with np.errstate(divide="ignore", invalid="ignore"):
        share = cum * 100.0 / total[:, None]
    for i, p in enumerate(percs):
        if p < 1.0 or p > 100.0:
            out[i, :] = -1.0
            continue
        hit = share >= p
        idx = np.argmax(hit, axis=1)
        vals = mid[idx]
        vals = np.where(total > 0, vals, 0.0)
        out[i, :] = vals
    return out
