"""Metadata subsystem: UIDMeta, TSMeta, meta store + HTTP handlers.

Reference behavior: /root/reference/src/meta/ — UIDMeta.java (:81-112
fields), TSMeta.java (:91-142 fields + CAS counters under
tsd.core.meta.enable_tsuid_tracking), TSUIDQuery.java (last-point/meta
lookups), MetaDataCache.java (SPI).
"""

from opentsdb_tpu.meta.objects import UIDMeta, TSMeta, MetaStore

__all__ = ["UIDMeta", "TSMeta", "MetaStore"]
