"""UIDMeta / TSMeta objects and the in-memory meta table.

Reference behavior: /root/reference/src/meta/UIDMeta.java (fields :81-112,
user-editable set via `changed` map — display_name, description, notes,
custom; `name`/`uid`/`type`/`created` are system-controlled) and
TSMeta.java (fields :91-142; counters last_received/total_dps maintained on
write when tsd.core.meta.enable_tsuid_tracking).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


# Fields a PUT/POST may modify (UIDMeta.syncMeta / TSMeta.syncMeta).
UIDMETA_EDITABLE = ("display_name", "description", "notes", "custom")
TSMETA_EDITABLE = ("display_name", "description", "notes", "custom",
                   "units", "data_type", "retention", "max", "min")


@dataclass
class UIDMeta:
    uid: str = ""
    type: str = ""          # METRIC / TAGK / TAGV
    name: str = ""
    display_name: str = ""
    description: str = ""
    notes: str = ""
    created: int = 0
    custom: dict | None = None

    def to_json(self) -> dict:
        return {
            "uid": self.uid,
            "type": self.type.upper(),
            "name": self.name,
            "displayName": self.display_name,
            "description": self.description,
            "notes": self.notes,
            "created": self.created,
            "custom": self.custom,
        }

    def update_from(self, body: dict) -> None:
        for json_key, attr in (("displayName", "display_name"),
                               ("description", "description"),
                               ("notes", "notes"), ("custom", "custom")):
            if json_key in body:
                setattr(self, attr, body[json_key])


@dataclass
class TSMeta:
    tsuid: str = ""
    display_name: str = ""
    description: str = ""
    notes: str = ""
    created: int = 0
    custom: dict | None = None
    units: str = ""
    data_type: str = ""
    retention: int = 0
    max: float = float("nan")
    min: float = float("nan")
    last_received: int = 0
    total_dps: int = 0
    # resolved views (metric + tag UIDMeta objects)
    metric: UIDMeta | None = None
    tags: list[UIDMeta] = field(default_factory=list)

    def to_json(self) -> dict:
        out = {
            "tsuid": self.tsuid,
            "displayName": self.display_name,
            "description": self.description,
            "notes": self.notes,
            "created": self.created,
            "custom": self.custom,
            "units": self.units,
            "dataType": self.data_type,
            "retention": self.retention,
            "max": self.max,
            "min": self.min,
            "lastReceived": self.last_received,
            "totalDatapoints": self.total_dps,
        }
        if self.metric is not None:
            out["metric"] = self.metric.to_json()
        out["tags"] = [t.to_json() for t in self.tags]
        return out

    def update_from(self, body: dict) -> None:
        mapping = (("displayName", "display_name"),
                   ("description", "description"), ("notes", "notes"),
                   ("custom", "custom"), ("units", "units"),
                   ("dataType", "data_type"), ("retention", "retention"),
                   ("max", "max"), ("min", "min"))
        for json_key, attr in mapping:
            if json_key in body:
                setattr(self, attr, body[json_key])


class MetaStore:
    """In-memory tsdb-meta table: UIDMeta by (type, uid), TSMeta by tsuid."""

    def __init__(self):
        # guarded-by: _lock
        self._uidmeta: dict[tuple[str, str], UIDMeta] = {}
        self._tsmeta: dict[str, TSMeta] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    # -- UIDMeta --

    def get_uidmeta(self, kind: str, uid: str) -> UIDMeta | None:
        with self._lock:
            return self._uidmeta.get((kind.lower(), uid.upper()))

    def ensure_uidmeta(self, kind: str, uid: str, name: str) -> UIDMeta:
        with self._lock:
            key = (kind.lower(), uid.upper())
            meta = self._uidmeta.get(key)
            if meta is None:
                meta = UIDMeta(uid=uid.upper(), type=kind.lower(),
                               name=name, created=int(time.time()))
                self._uidmeta[key] = meta
            return meta

    def delete_uidmeta(self, kind: str, uid: str) -> bool:
        with self._lock:
            return self._uidmeta.pop((kind.lower(), uid.upper()),
                                     None) is not None

    def all_uidmeta(self) -> list[UIDMeta]:
        with self._lock:
            return list(self._uidmeta.values())

    # -- TSMeta --

    def get_tsmeta(self, tsuid: str) -> TSMeta | None:
        with self._lock:
            return self._tsmeta.get(tsuid.upper())

    def ensure_tsmeta(self, tsuid: str) -> TSMeta:
        with self._lock:
            meta = self._tsmeta.get(tsuid.upper())
            if meta is None:
                meta = TSMeta(tsuid=tsuid.upper(),
                              created=int(time.time()))
                self._tsmeta[tsuid.upper()] = meta
            return meta

    def record_datapoint(self, tsuid: str, ts_ms: int,
                         count: bool = True, n: int = 1) -> bool:
        """Ensure the TSMeta row and (optionally) bump the counters.

        Returns True when this call created the TSMeta — the
        TSMeta.storeIfNecessary signal realtime indexing keys off.  Counters
        last_received/total_dps only move under
        tsd.core.meta.enable_tsuid_tracking (TSMeta.incrementAndGetCounter).
        `n` lets the bulk ingest path count a whole batch in one call
        (ts_ms should then be the batch's max timestamp).
        """
        key = tsuid.upper()
        with self._lock:
            meta = self._tsmeta.get(key)
            created = meta is None
            if created:
                meta = TSMeta(tsuid=key, created=int(time.time()))
                self._tsmeta[key] = meta
            if count:
                meta.last_received = max(meta.last_received, ts_ms // 1000)
                meta.total_dps += n
        return created

    def delete_tsmeta(self, tsuid: str) -> bool:
        with self._lock:
            return self._tsmeta.pop(tsuid.upper(), None) is not None

    def all_tsmeta(self) -> list[TSMeta]:
        with self._lock:
            return list(self._tsmeta.values())
