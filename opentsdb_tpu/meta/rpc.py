"""/api/uid/uidmeta and /api/uid/tsmeta handlers.

Reference behavior: /root/reference/src/tsd/UniqueIdRpc.java —
handleUIDMeta (:~200: GET by uid+type, POST/PUT sync editable fields,
DELETE) and handleTSMeta (:~300: GET by tsuid or metric query `m`,
POST/PUT, DELETE; `method_override` query param honored).
"""

from __future__ import annotations

from opentsdb_tpu.meta.objects import TSMeta, UIDMeta
from opentsdb_tpu.tsd.http import BadRequestError, HttpQuery
from opentsdb_tpu.uid import NoSuchUniqueId, NoSuchUniqueName, UniqueIdType


def _resolve_uidmeta(tsdb, kind: str, uid: str) -> UIDMeta:
    """Existing meta, or a default one synthesized from the UID table
    (UIDMeta.getUIDMeta returns defaults when no storage row exists)."""
    table = tsdb.uid_table(kind)
    name = table.get_name(table.hex_to_uid(uid))  # raises NoSuchUniqueId
    meta = tsdb.meta_store.get_uidmeta(kind, uid)
    if meta is None:
        meta = UIDMeta(uid=uid.upper(), type=kind.lower(), name=name)
    return meta


def handle_uidmeta(tsdb, query: HttpQuery) -> None:
    method = query.effective_method()
    if method == "GET":
        uid = query.required_query_string_param("uid")
        kind = query.required_query_string_param("type")
        UniqueIdType.from_string(kind)
        try:
            meta = _resolve_uidmeta(tsdb, kind, uid)
        except NoSuchUniqueId:
            raise BadRequestError(
                "Could not find the requested UID", status=404,
                details="No such UID %s of type %s" % (uid, kind))
        query.send_reply(meta.to_json())
        return
    if method in ("POST", "PUT"):
        body = query.json_body() if query.request.body else {
            "uid": query.get_query_string_param("uid"),
            "type": query.get_query_string_param("type"),
            "displayName": query.get_query_string_param("display_name"),
            "description": query.get_query_string_param("description"),
            "notes": query.get_query_string_param("notes"),
        }
        uid = body.get("uid")
        kind = body.get("type")
        if not uid or not kind:
            raise BadRequestError("Missing UID or type")
        table = tsdb.uid_table(kind)
        try:
            name = table.get_name(table.hex_to_uid(uid))
        except NoSuchUniqueId:
            raise BadRequestError(
                "Could not find the requested UID", status=404)
        meta = tsdb.meta_store.ensure_uidmeta(kind, uid, name)
        if method == "PUT":
            # full overwrite of the editable fields
            meta.display_name = meta.description = meta.notes = ""
            meta.custom = None
        meta.update_from({k: v for k, v in body.items() if v is not None})
        if tsdb.search_plugin is not None:
            tsdb.search_plugin.index_uidmeta(meta)
        query.send_reply(meta.to_json())
        return
    if method == "DELETE":
        uid = query.required_query_string_param("uid")
        kind = query.required_query_string_param("type")
        tsdb.meta_store.delete_uidmeta(kind, uid)
        if tsdb.search_plugin is not None:
            tsdb.search_plugin.delete_uidmeta(kind, uid)
        query.send_status_only(204)
        return
    raise BadRequestError("Method not allowed", status=405)


def resolve_tsmeta(tsdb, tsuid: str) -> TSMeta:
    """TSMeta with metric/tag UIDMeta views resolved (TSMeta.getTSMeta).

    Returns a transient copy — the stored TSMeta is shared across requests
    and must not be mutated outside the MetaStore lock.
    """
    import dataclasses
    stored = tsdb.meta_store.get_tsmeta(tsuid)
    if stored is None:
        meta = TSMeta(tsuid=tsuid.upper())
    else:
        meta = dataclasses.replace(stored, metric=None, tags=[])
    mw = tsdb.metrics.width * 2
    kw = tsdb.tag_names.width * 2
    vw = tsdb.tag_values.width * 2
    metric_uid = tsuid[:mw]
    meta.metric = _resolve_uidmeta(tsdb, "metric", metric_uid)
    meta.tags = []
    pos = mw
    while pos < len(tsuid):
        meta.tags.append(_resolve_uidmeta(tsdb, "tagk",
                                          tsuid[pos:pos + kw]))
        pos += kw
        meta.tags.append(_resolve_uidmeta(tsdb, "tagv",
                                          tsuid[pos:pos + vw]))
        pos += vw
    return meta


def handle_tsmeta(tsdb, query: HttpQuery) -> None:
    method = query.effective_method()
    if method == "GET":
        tsuids = []
        if query.has_query_string_param("tsuid"):
            tsuids = [query.required_query_string_param("tsuid")]
        elif query.has_query_string_param("m"):
            # metric query form: every matching series' TSMeta
            from opentsdb_tpu.query.filters import parse_metric_with_filters
            filters: list = []
            metric = parse_metric_with_filters(
                query.required_query_string_param("m"), filters)
            try:
                metric_uid = tsdb.metrics.get_id(metric)
            except NoSuchUniqueName:
                raise BadRequestError("Could not find the requested "
                                      "metric", status=404)
            for series in tsdb.store.series_for_metric(metric_uid):
                tags = tsdb.resolve_key_tags(series.key)
                if all(f.match(tags) for f in filters):
                    tsuids.append(tsdb.tsuid(series.key))
        else:
            raise BadRequestError.missing_parameter("tsuid or m")
        out = []
        for t in tsuids:
            try:
                out.append(resolve_tsmeta(tsdb, t).to_json())
            except NoSuchUniqueId:
                raise BadRequestError(
                    "Could not find one or more UIDs in the TSUID",
                    status=404, details="tsuid: " + t)
        if query.has_query_string_param("tsuid"):
            query.send_reply(out[0] if out else {})
        else:
            query.send_reply(out)
        return
    if method in ("POST", "PUT"):
        body = query.json_body() if query.request.body else {
            "tsuid": query.get_query_string_param("tsuid"),
            "displayName": query.get_query_string_param("display_name"),
            "description": query.get_query_string_param("description"),
            "notes": query.get_query_string_param("notes"),
        }
        tsuid = body.get("tsuid")
        if not tsuid:
            raise BadRequestError("Missing TSUID")
        # Validate every UID in the TSUID BEFORE creating the store row,
        # or a typo'd TSUID would leave a garbage TSMeta that suppresses
        # later realtime indexing of the real series.
        try:
            resolve_tsmeta(tsdb, tsuid)
        except NoSuchUniqueId:
            raise BadRequestError(
                "Could not find one or more UIDs in the TSUID",
                status=404, details="tsuid: " + str(tsuid))
        meta = tsdb.meta_store.ensure_tsmeta(tsuid)
        if method == "PUT":
            meta.display_name = meta.description = meta.notes = ""
            meta.custom = None
            meta.units = meta.data_type = ""
            meta.retention = 0
        meta.update_from({k: v for k, v in body.items() if v is not None})
        resolved = resolve_tsmeta(tsdb, tsuid)
        if tsdb.search_plugin is not None:
            tsdb.search_plugin.index_tsmeta(resolved)
        query.send_reply(resolved.to_json())
        return
    if method == "DELETE":
        tsuid = query.required_query_string_param("tsuid")
        tsdb.meta_store.delete_tsmeta(tsuid)
        if tsdb.search_plugin is not None:
            tsdb.search_plugin.delete_tsmeta(tsuid)
        query.send_status_only(204)
        return
    raise BadRequestError("Method not allowed", status=405)
