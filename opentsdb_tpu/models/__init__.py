from opentsdb_tpu.models.tsquery import (
    TSQuery, TSSubQuery, DownsamplingSpecification, parse_m_subquery,
    parse_tsuid_subquery, parse_rate_options, parse_percentiles)

__all__ = [
    "TSQuery", "TSSubQuery", "DownsamplingSpecification", "parse_m_subquery",
    "parse_tsuid_subquery", "parse_rate_options", "parse_percentiles",
]
