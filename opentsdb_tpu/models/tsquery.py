"""Query object model: TSQuery / TSSubQuery / downsampling spec + URI grammar.

Reference behavior: /root/reference/src/core/TSQuery.java (:47-112 fields,
validateAndSetQuery), TSSubQuery.java (:50-104), and the URI parsers in
src/tsd/QueryRpc.java (parseQuery :521, parseMTypeSubQuery :638 — grammar
``agg:[interval-agg[-fill][c]:][rate[{counter[,max[,reset]]}]:][percentiles[..]:]
[explicit_tags:]metric{groupby}{filters}`` — parseRateOptions :762,
parsePercentiles :902) and DownsamplingSpecification.java (spec string
"interval-function[-fill_policy]", trailing 'c' = calendar alignment).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from opentsdb_tpu.ops.rate import RateOptions
from opentsdb_tpu.utils import datetime_util as DT
from opentsdb_tpu.query.filters import TagVFilter, parse_metric_with_filters

_FILL_POLICIES = ("none", "zero", "nan", "null", "scalar")


@dataclass
class DownsamplingSpecification:
    """Parsed downsample spec (DownsamplingSpecification.java:116-191)."""
    interval_ms: int
    function: str
    fill_policy: str = "none"
    fill_value: float = 0.0
    string_interval: str | None = None
    use_calendar: bool = False
    run_all: bool = False
    timezone: str = "UTC"

    @staticmethod
    def parse(spec: str) -> "DownsamplingSpecification":
        if not spec:
            raise ValueError("Downsampling specifier cannot be empty")
        parts = spec.split("-")
        if len(parts) < 2:
            raise ValueError(
                "Invalid downsampling specifier '%s': must provide at least "
                "interval and function" % spec)
        if len(parts) > 3:
            raise ValueError(
                "Invalid downsampling specifier '%s': must consist of interval, "
                "function, and optional fill policy" % spec)

        run_all = False
        use_calendar = False
        interval_ms = 0
        raw_interval = parts[0]
        if "all" in raw_interval:
            run_all = True
            string_interval = raw_interval
        elif raw_interval.endswith("c"):
            string_interval = raw_interval[:-1]
            interval_ms = DT.parse_duration(string_interval)
            use_calendar = True
        else:
            string_interval = raw_interval
            interval_ms = DT.parse_duration(raw_interval)

        function = parts[1]
        from opentsdb_tpu.ops.aggregators import is_valid_agg
        if not is_valid_agg(function):
            raise ValueError("No such downsampling function: " + function)
        if function == "none":
            raise ValueError("cannot use the NONE aggregator for downsampling")

        fill_policy = "none"
        fill_value = 0.0
        if len(parts) == 3:
            fp = parts[2]
            if fp not in _FILL_POLICIES:
                raise ValueError("No such fill policy: '%s': must be one of: %s"
                                 % (fp, " ".join(_FILL_POLICIES)))
            fill_policy = fp
        return DownsamplingSpecification(
            interval_ms=interval_ms, function=function, fill_policy=fill_policy,
            fill_value=fill_value, string_interval=string_interval,
            use_calendar=use_calendar, run_all=run_all)

    @property
    def calendar_unit(self) -> str:
        return DT.get_duration_units(self.string_interval)

    @property
    def calendar_interval(self) -> int:
        return DT.get_duration_interval(self.string_interval)


@dataclass
class TSSubQuery:
    """One sub query: aggregator + metric/tsuids + transforms (TSSubQuery.java)."""
    aggregator: str = "sum"
    metric: str | None = None
    tsuids: list[str] | None = None
    downsample: str | None = None
    rate: bool = False
    rate_options: RateOptions = field(default_factory=RateOptions)
    filters: list[TagVFilter] = field(default_factory=list)
    explicit_tags: bool = False
    pre_aggregate: bool = False
    rollup_usage: str | None = None
    percentiles: list[float] | None = None
    show_histogram_buckets: bool = False
    index: int = 0
    # filled by validate()
    downsample_spec: DownsamplingSpecification | None = None

    def validate(self) -> None:
        if not self.aggregator:
            raise ValueError("Missing the aggregation function")
        from opentsdb_tpu.ops.aggregators import is_valid_agg
        if not is_valid_agg(self.aggregator):
            raise ValueError("No such aggregator: " + self.aggregator)
        if not self.metric and not self.tsuids:
            raise ValueError(
                "Missing the metric or tsuids, provide at least one")
        if self.downsample:
            self.downsample_spec = DownsamplingSpecification.parse(
                self.downsample)

    @property
    def fill_policy(self) -> str:
        if self.downsample_spec is None:
            return "none"
        return self.downsample_spec.fill_policy

    def group_by_tags(self) -> list[str]:
        return sorted({f.tagk for f in self.filters if f.group_by})

    def to_json(self) -> dict:
        out = {
            "aggregator": self.aggregator,
            "metric": self.metric,
            "tsuids": self.tsuids,
            "downsample": self.downsample,
            "rate": self.rate,
            "filters": [f.to_json() for f in self.filters],
            "explicitTags": self.explicit_tags,
            "index": self.index,
            "rateOptions": ({
                "counter": self.rate_options.counter,
                "counterMax": self.rate_options.counter_max,
                "resetValue": self.rate_options.reset_value,
                "dropResets": self.rate_options.drop_resets,
            } if self.rate else None),
            "tags": {f.tagk: f.spec_string() for f in self.filters
                     if f.group_by},
        }
        return out

    def dedup_key(self):
        return (self.aggregator, self.metric,
                tuple(self.tsuids or ()), self.downsample, self.rate,
                self.rate_options, tuple((f.tagk, f.type, f.filter,
                                          f.group_by) for f in self.filters),
                self.explicit_tags)


@dataclass
class TSQuery:
    """Top-level /api/query body (TSQuery.java)."""
    start: str | int | None = None
    end: str | int | None = None
    timezone: str | None = None
    queries: list[TSSubQuery] = field(default_factory=list)
    padding: bool = False
    no_annotations: bool = False
    global_annotations: bool = False
    show_tsuids: bool = False
    ms_resolution: bool = False
    show_query: bool = False
    show_stats: bool = False
    show_summary: bool = False
    delete: bool = False
    use_calendar: bool = False
    # resolved by validate()
    start_time: int = 0
    end_time: int = 0

    def validate(self, now_ms: int | None = None) -> None:
        """validateAndSetQuery (TSQuery.java:112): resolve times, sub queries."""
        if self.start is None or self.start == "":
            raise ValueError("Missing start time")
        self.start_time = DT.parse_datetime_string(str(self.start),
                                                   self.timezone, now_ms)
        if self.end is None or self.end == "":
            self.end_time = (now_ms if now_ms is not None
                             else DT.current_time_millis())
        else:
            self.end_time = DT.parse_datetime_string(str(self.end),
                                                     self.timezone, now_ms)
        if self.end_time <= self.start_time:
            raise ValueError(
                "End time [%d] must be greater than the start time [%d]"
                % (self.end_time, self.start_time))
        if not self.queries:
            raise ValueError("Missing sub queries")
        seen = set()
        deduped = []
        for i, sub in enumerate(self.queries):
            sub.validate()
            key = sub.dedup_key()
            if key in seen:
                continue
            seen.add(key)
            deduped.append(sub)
        self.queries = deduped
        for i, sub in enumerate(self.queries):
            sub.index = i
            if sub.downsample_spec is not None:
                if self.timezone:
                    sub.downsample_spec.timezone = self.timezone
                if self.use_calendar:
                    sub.downsample_spec.use_calendar = True


def parse_rate_options(spec: str) -> RateOptions:
    """Parse "rate{counter[,max[,reset]]}" (QueryRpc.parseRateOptions :762)."""
    if len(spec) == 4:  # bare "rate"
        return RateOptions()
    if len(spec) < 6 or "{" not in spec or not spec.endswith("}"):
        raise ValueError("Invalid rate options specification: " + spec)
    inner = spec[5:-1]
    parts = inner.split(",")
    if len(parts) < 1 or len(parts) > 3:
        raise ValueError(
            "Incorrect number of values in rate options specification, must "
            "be counter[,counter max value,reset value], received: %d parts"
            % len(parts))
    kind = parts[0].strip().lower()
    if kind not in ("counter", "dropcounter", ""):
        raise ValueError("Invalid rate counter type: " + parts[0])
    counter = kind in ("counter", "dropcounter")
    drop = kind == "dropcounter"
    counter_max = RateOptions().counter_max
    reset = 0
    if len(parts) >= 2 and parts[1].strip():
        counter_max = int(parts[1])
    if len(parts) >= 3 and parts[2].strip():
        reset = int(parts[2])
    return RateOptions(counter, counter_max, reset, drop)


def parse_percentiles(spec: str) -> list[float]:
    """Parse "percentiles[99,99.9]" (QueryRpc.parsePercentiles :902)."""
    bracket = spec.index("[")
    if not spec.endswith("]"):
        raise ValueError("Invalid percentiles specification: " + spec)
    inner = spec[bracket + 1:-1]
    out = []
    for part in inner.split(","):
        part = part.strip()
        if not part:
            continue
        value = float(part)
        if not 0 < value <= 100:
            raise ValueError("Invalid percentile value: " + part)
        out.append(value)
    if not out:
        raise ValueError("No percentiles specified: " + spec)
    return out


def parse_m_subquery(query_string: str) -> TSSubQuery:
    """Parse one m= parameter (QueryRpc.parseMTypeSubQuery :638)."""
    if not query_string:
        raise ValueError("The query string was empty")
    parts = query_string.split(":")
    n = len(parts)
    if n < 2 or n > 5:
        raise ValueError(
            "Invalid parameter m=%s (%s :-separated parts)"
            % (query_string, "not enough" if n < 2 else "too many"))
    sub = TSSubQuery()
    sub.aggregator = parts[0]
    filters: list[TagVFilter] = []
    sub.metric = parse_metric_with_filters(parts[-1], filters)
    sub.filters = filters
    for x in range(1, n - 1):
        part = parts[x]
        low = part.lower()
        if low.startswith("rate"):
            sub.rate = True
            if "{" in part:
                sub.rate_options = parse_rate_options(part)
        elif part and part[0].isdigit():
            sub.downsample = part
        elif low == "pre-agg":
            sub.pre_aggregate = True
        elif low.startswith("rollup_"):
            sub.rollup_usage = part.upper()
        elif low.startswith("percentiles"):
            sub.percentiles = parse_percentiles(part)
        elif low.startswith("show-histogram-buckets"):
            sub.show_histogram_buckets = True
        elif low.startswith("explicit_tags"):
            sub.explicit_tags = True
    return sub


def parse_tsuid_subquery(query_string: str) -> TSSubQuery:
    """Parse one tsuid= parameter (QueryRpc.parseTsuidTypeSubQuery :700)."""
    if not query_string:
        raise ValueError("The tsuid query string was empty")
    parts = query_string.split(":")
    n = len(parts)
    if n < 2 or n > 5:
        raise ValueError("Invalid parameter tsuid=%s" % query_string)
    sub = TSSubQuery()
    sub.aggregator = parts[0]
    sub.tsuids = [t for t in parts[-1].split(",") if t]
    for x in range(1, n - 1):
        part = parts[x]
        low = part.lower()
        if low.startswith("rate"):
            sub.rate = True
            if "{" in part:
                sub.rate_options = parse_rate_options(part)
        elif part and part[0].isdigit():
            sub.downsample = part
        elif low.startswith("percentiles"):
            sub.percentiles = parse_percentiles(part)
        elif low.startswith("show-histogram-buckets"):
            sub.show_histogram_buckets = True
    return sub
