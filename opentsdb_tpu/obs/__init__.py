"""tsdbobs: end-to-end query tracing, metrics registry, JAX profiling.

Three layers, one package (docs/observability.md):

  * obs/trace.py     span-tree tracer threaded through rpc_manager ->
                     QueryRpc -> planner -> cluster fan-out; spans carry
                     wall + device time and ride /api/stats/query plus
                     the inline showStats summary.
  * obs/registry.py  thread-safe counters / gauges / log-bucketed
                     latency histograms (obs/histogram.py) with a
                     Prometheus text-exposition endpoint
                     (/api/stats/prometheus).
  * obs/jaxprof.py   per-kernel compile accounting (the SHARED
                     compile-log capture tsdbsan's JaxSanitizer also
                     subscribes to), device-cache gauges, and costmodel
                     predicted-vs-actual feedback per query segment.

obs/selfreport.py closes the dogfooding loop: the daemon ingests its own
tsd.* metrics into its own memstore every tsd.stats.interval seconds, so
the TSD is queryable about itself through its own pipeline.
"""

from opentsdb_tpu.obs.histogram import LogHistogram
from opentsdb_tpu.obs.registry import REGISTRY, MetricsRegistry

__all__ = ["LogHistogram", "REGISTRY", "MetricsRegistry"]
