"""tsdbobs: end-to-end query tracing, metrics registry, JAX profiling.

Three layers, one package (docs/observability.md):

  * obs/trace.py     span-tree tracer threaded through rpc_manager ->
                     QueryRpc -> planner -> cluster fan-out; spans carry
                     wall + device time and ride /api/stats/query plus
                     the inline showStats summary.
  * obs/registry.py  thread-safe counters / gauges / log-bucketed
                     latency histograms (obs/histogram.py) with a
                     Prometheus text-exposition endpoint
                     (/api/stats/prometheus).
  * obs/jaxprof.py   per-kernel compile accounting (the SHARED
                     compile-log capture tsdbsan's JaxSanitizer also
                     subscribes to), device-cache gauges, and costmodel
                     predicted-vs-actual feedback per query segment.

obs/selfreport.py closes the dogfooding loop: the daemon ingests its own
tsd.* metrics into its own memstore every tsd.stats.interval seconds, so
the TSD is queryable about itself through its own pipeline.

METRICS_SCHEMA (below) is the declared universe of metric names this
codebase emits through the registry families or StatsCollector.record —
tools/lint/metrics_schema.py holds every emission site to it (an
undeclared name is a lint failure), and docs/metrics.md is generated
from it via `python tools/lint/run.py --update-doc` (byte-pinned by
test, same contract as docs/configuration.md).
"""

from __future__ import annotations

from typing import NamedTuple

from opentsdb_tpu.obs.histogram import LogHistogram
from opentsdb_tpu.obs.registry import REGISTRY, MetricsRegistry

__all__ = ["LogHistogram", "REGISTRY", "MetricsRegistry",
           "METRICS_SCHEMA", "MetricSpec", "generate_metrics_doc"]


class MetricSpec(NamedTuple):
    kind: str            # counter | gauge | histogram
    labels: tuple        # label keys minted at the emission sites
    doc: str


def _m(kind: str, labels: tuple, doc: str) -> MetricSpec:
    return MetricSpec(kind, labels, doc)


# The declared metric-name universe.  Names are the FULL dotted form
# (StatsCollector.record's "tsd." prefix included); a `*` segment
# matches one %-formatted hole at an emission site that builds its name
# from a template ("%s.errors" % kind declares as "tsd.*.errors").
# Every StatsCollector record is exposed as a gauge on
# /api/stats/prometheus, so record-emitted names declare kind "gauge";
# every record additionally carries the collector's ambient tags
# (`host`, plus any context tags) on top of the labels listed here.
METRICS_SCHEMA: dict[str, MetricSpec] = {
    # -- HTTP / RPC serving (tsd/rpc_manager.py, tsd/rpcs.py) ---------- #
    "tsd.http.requests": _m(
        "counter", ("route", "status"),
        "HTTP requests served, by registered route and status code."),
    "tsd.http.latency_ms": _m(
        "histogram", ("route",),
        "End-to-end HTTP request latency in milliseconds."),
    "tsd.http.errors": _m(
        "gauge", ("family",),
        "HTTP error responses by family (4xx client / 5xx server)."),
    "tsd.query.count": _m(
        "counter", ("status",),
        "/api/query requests served, by response status."),
    "tsd.query.latency_ms": _m(
        "histogram", ("tenant",),
        "End-to-end /api/query latency in milliseconds, by clamped "
        "tenant (X-TSDB-Tenant against the tsd.diag.tenants table)."),
    "tsd.query.tenant.demand": _m(
        "counter", ("tenant",),
        "Queries arriving at the admission gate, by clamped tenant — "
        "the per-tenant demand telemetry the fair-share scheduler "
        "(tsd.query.tenant.fair_share) drains against."),
    "tsd.query.tenant.admitted": _m(
        "counter", ("tenant",),
        "Queries admitted through the gate, by clamped tenant — the "
        "drained half of the demand split (tsd/admission.py weighted "
        "DRR; auditable at /api/diag)."),
    "tsd.query.tenant.refused": _m(
        "counter", ("tenant",),
        "Queries refused (shed) by the gate, by clamped tenant — the "
        "refused half of the demand split."),
    # -- fused multi-query dispatch (query/batcher.py) ------------------ #
    "tsd.query.batch.queries": _m(
        "counter", ("outcome",),
        "Batch-routed queries, by outcome: 'stacked' (member of a "
        "multi-query dispatch) or 'solo' (no sibling arrived within "
        "the coalesce window; ordinary single dispatch)."),
    "tsd.query.batch.dispatches": _m(
        "counter", (),
        "Stacked multi-query device dispatches (one launch serving "
        ">= 2 member queries)."),
    "tsd.query.batch.q": _m(
        "histogram", (),
        "Member queries per stacked dispatch."),
    "tsd.query.batch.wait_ms": _m(
        "histogram", (),
        "Coalesce wait before the stacked/solo dispatch, in "
        "milliseconds (bounded by tsd.query.batch.hold_ms)."),
    "tsd.query.batch.stacked_dispatches": _m(
        "gauge", (),
        "Stats-walk mirror of the stacked-dispatch total "
        "(TSDB.collect_stats)."),
    "tsd.query.batch.stacked_members": _m(
        "gauge", (),
        "Stats-walk mirror of member queries served by stacked "
        "dispatches."),
    "tsd.query.batch.solo_dispatches": _m(
        "gauge", (),
        "Stats-walk mirror of batch-routed queries that dispatched "
        "solo."),
    "tsd.query.explain.requests": _m(
        "counter", ("outcome",),
        "/api/query/explain requests served, by outcome (ok/error).  "
        "Explain acquires no admission permit and dispatches no "
        "device work (query/explain.py)."),
    "tsd.query.explain.latency_ms": _m(
        "histogram", (),
        "Explain planning latency in milliseconds — the no-dispatch "
        "decision walk, including the admission preview."),
    # -- admission control (tsd/admission.py) -------------------------- #
    "tsd.query.admission.queue_depth": _m(
        "gauge", ("priority",),
        "Admission wait-queue depth, by priority class."),
    "tsd.query.admission.wait_ms": _m(
        "histogram", ("priority",),
        "Admission queue wait in milliseconds, by priority class."),
    "tsd.query.admission.inflight": _m(
        "gauge", (),
        "Queries currently holding an admission permit (bounded by "
        "tsd.query.admission.permits)."),
    "tsd.query.admission.shed": _m(
        "counter", ("reason",),
        "Queries refused by the admission gate (503 + Retry-After), "
        "by reason: queue_full, max_wait, predicted_cost."),
    "tsd.query.admission.degraded": _m(
        "counter", ("reason",),
        "Queries served degraded by the admission ladder "
        "(coarsened/truncated, 200 + partialResults)."),
    "tsd.query.admission.cancelled": _m(
        "counter", ("reason",),
        "Queries cancelled cooperatively, by reason: "
        "client_disconnect, drain_timeout, queued."),
    "tsd.query.limits.reload_errors": _m(
        "counter", (),
        "Query-limit overrides loads that failed (the daemon kept "
        "the last good config; logged once per distinct error)."),
    "tsd.rpc.received": _m(
        "gauge", ("type",),
        "RPCs received, by transport/command type."),
    "tsd.*.errors": _m(
        "gauge", ("type",),
        "Per-RPC-kind error tallies (put.errors, rollup.errors, ...) "
        "by error type."),
    "tsd.connectionmgr.connections": _m(
        "gauge", ("type",),
        "Connection manager totals: established/open/rejected."),
    "tsd.connectionmgr.exceptions": _m(
        "gauge", (),
        "Exceptions caught by the connection manager."),
    # -- auth (auth/core.py) ------------------------------------------- #
    "tsd.authentication.telnet.allowed": _m(
        "gauge", (), "Telnet connections allowed by the auth plugin."),
    "tsd.authentication.http.allowed": _m(
        "gauge", (), "HTTP connections allowed by the auth plugin."),
    "tsd.authorization.queries.allowed": _m(
        "gauge", (), "Queries allowed by the authorization plugin."),
    # -- cluster fan-out (tsd/cluster.py) ------------------------------ #
    "tsd.cluster.fetch.retries": _m(
        "gauge", (), "Peer-fetch retry attempts."),
    "tsd.cluster.fetch.failures": _m(
        "gauge", (), "Peer fetches that exhausted their retries."),
    "tsd.cluster.queries": _m(
        "gauge", ("result",),
        "Clustered queries by outcome (partial / failed)."),
    "tsd.cluster.breaker.state": _m(
        "gauge", ("peer",),
        "Per-peer circuit-breaker state (0 closed, 1 half-open, "
        "2 open)."),
    "tsd.cluster.breaker.opens": _m(
        "gauge", ("peer",), "Circuit-breaker open transitions."),
    "tsd.cluster.breaker.fast_fails": _m(
        "gauge", ("peer",),
        "Requests fast-failed by an open breaker."),
    # -- sharded replication (tsd/replication.py, docs/replication.md): #
    #    registry families ---------------------------------------------#
    "tsd.replication.ship.records": _m(
        "counter", ("peer",),
        "WAL records synchronously shipped to a replica on the ingest "
        "ack path, by replica peer."),
    "tsd.replication.ship.errors": _m(
        "counter", ("peer",),
        "Synchronous ship attempts that failed (the pull cadence "
        "fills the gap), by replica peer."),
    "tsd.replication.tail.requests": _m(
        "counter", (),
        "/api/replication/tail pages served to catching-up peers."),
    "tsd.replication.tail.records": _m(
        "counter", (),
        "WAL records served through /api/replication/tail."),
    "tsd.replication.catch_up.records": _m(
        "counter", ("peer",),
        "Peer WAL records applied from pulled tails (the catch-up "
        "path), by origin peer."),
    "tsd.replication.forwarded": _m(
        "counter", ("peer",),
        "Ingest writes forwarded to the shard's accepting member, by "
        "destination peer."),
    "tsd.replication.divergence": _m(
        "counter", ("peer",),
        "Anti-entropy CRC-chain divergences detected (position reset "
        "to the last agreed record + re-pull), by peer."),
    "tsd.replication.inflight_rejected": _m(
        "counter", (),
        "Replication ship/tail requests refused by the "
        "tsd.replication.max_inflight_mb byte gate (503; the sender "
        "falls back to the pull cadence)."),
    # -- sharded replication stats walk (ReplicationManager.stats_hook #
    #    -> /api/stats + the self-report loop) ------------------------- #
    "tsd.replication.epoch": _m(
        "gauge", (),
        "Ownership epoch: bumps on every shard-cover change (failover, "
        "rejoin); the flight recorder retains the transition."),
    "tsd.replication.last_seq": _m(
        "gauge", (), "This node's newest assigned WAL sequence number."),
    "tsd.replication.under_replicated": _m(
        "gauge", (),
        "Shards with fewer healthy members than the replication "
        "factor (the eighth health invariant's input)."),
    "tsd.replication.lag": _m(
        "gauge", (),
        "Worst replica's unacknowledged backlog in this node's WAL "
        "stream, records."),
    "tsd.replication.peer_position": _m(
        "gauge", ("peer",),
        "Per-replica acknowledged position in this node's WAL stream "
        "(ship acks + tail since marks)."),
    # -- JAX / costmodel (obs/jaxprof.py, ops/calibrate.py,             #
    #    query/planner.py) -------------------------------------------- #
    "tsd.jax.compiles": _m(
        "counter", ("kernel",), "XLA compilations per jitted kernel."),
    "tsd.costmodel.segments": _m(
        "counter", ("kind",),
        "Query segments with predicted-vs-actual accounting."),
    "tsd.costmodel.predicted_ms": _m(
        "counter", ("kind",),
        "Costmodel-predicted device milliseconds, summed."),
    "tsd.costmodel.actual_ms": _m(
        "counter", ("kind",),
        "Measured device milliseconds, summed."),
    "tsd.costmodel.infeasible": _m(
        "counter", ("axis",),
        "Strategy decisions outside the feasible candidate set "
        "(must stay 0 — chaos_soak --autotune gates on it)."),
    "tsd.costmodel.calibration.fits": _m(
        "counter", ("platform",), "Online costmodel fits installed."),
    "tsd.costmodel.calibration.samples": _m(
        "gauge", ("platform",),
        "Ring entries consumed by the last fit."),
    "tsd.costmodel.calibration.residual": _m(
        "gauge", ("platform",),
        "Relative residual of the last fit."),
    "tsd.costmodel.calibration.constant": _m(
        "gauge", ("platform", "term"),
        "Live-fitted per-unit cost, seconds."),
    "tsd.costmodel.calibration.explorations": _m(
        "counter", ("axis",),
        "Epsilon-exploration intervals dispatched."),
    "tsd.costmodel.calibration.*": _m(
        "gauge", ("term",),
        "The installed live calibration constants, per platform "
        "(tsd.costmodel.calibration.cpu / .tpu), term-tagged."),
    # -- autotune loop counters (ops/calibrate.py collect_stats,        #
    #    re-emitted through the stats-hook forwarder) ------------------ #
    "tsd.costmodel.autotune.fits": _m(
        "gauge", (), "Autotune fits installed since startup."),
    "tsd.costmodel.autotune.fit_errors": _m(
        "gauge", (), "Autotune passes that raised (caught + counted)."),
    "tsd.costmodel.autotune.samples_used": _m(
        "gauge", (), "Ring entries consumed by the last fit."),
    "tsd.costmodel.autotune.explorations": _m(
        "gauge", (), "Epsilon-exploration intervals started."),
    "tsd.costmodel.autotune.residual": _m(
        "gauge", (), "Relative residual of the last fit."),
    "tsd.costmodel.autotune.exploring": _m(
        "gauge", (), "1 while a losing mode is being explored."),
    # -- query caches: shared tier-labeled families (tier values:      #
    #    device_series = storage/device_cache.py HBM columns,          #
    #    agg_host / agg_device = storage/agg_cache.py partial-         #
    #    aggregate blocks, agg = tier-less agg-cache events) ---------- #
    "tsd.query.cache.hits": _m(
        "counter", ("tier",),
        "Query-cache hits, by tier."),
    "tsd.query.cache.misses": _m(
        "counter", ("tier",),
        "Query-cache misses, by tier."),
    "tsd.query.cache.evictions": _m(
        "counter", ("tier",),
        "Query-cache evictions, by tier."),
    "tsd.query.cache.invalidations": _m(
        "counter", ("tier",),
        "Query-cache invalidation marks (ingest dirty ranges, "
        "dropcaches), by tier."),
    "tsd.query.cache.bytes": _m(
        "gauge", ("tier",),
        "Query-cache resident bytes, by tier."),
    "tsd.query.cache.entries": _m(
        "gauge", ("tier",),
        "Query-cache resident entries, by tier."),
    # -- out-of-core tiled execution (ops/tiling.py,                    #
    #    storage/spill.py) --------------------------------------------- #
    "tsd.query.spill.bytes": _m(
        "gauge", ("tier",),
        "Spill-pool resident bytes, by tier (host ring / disk "
        "overflow) — bounded by tsd.query.spill.host_mb/disk_mb."),
    "tsd.query.spill.entries": _m(
        "gauge", ("tier",),
        "Spill-pool resident entries, by tier."),
    "tsd.query.spill.tiles": _m(
        "counter", (),
        "Series tiles executed by the out-of-core tiled path."),
    "tsd.query.spill.spills": _m(
        "counter", ("tier",),
        "Partial grids written to the spill pool, by landing tier."),
    "tsd.query.spill.reads": _m(
        "counter", (),
        "Spill entries read back from the disk tier."),
    "tsd.query.spill.evictions": _m(
        "counter", (),
        "Spill-pool host-ring entries demoted to the disk tier."),
    "tsd.query.spill.invalidations": _m(
        "counter", (),
        "Spill entries released back to the pool (per-query cleanup "
        "and shutdown)."),
    "tsd.query.spill.refusals": _m(
        "counter", ("reason",),
        "Over-budget plans the tiled path could not serve (still "
        "413), by reason: disabled, not_streamable, no_fit, "
        "pool_budget."),
    "tsd.query.spill.write_errors": _m(
        "counter", (),
        "Spill-pool disk writes that failed (disk full / injected "
        "spill.write fault)."),
    # -- partial-aggregate cache stats walk (storage/agg_cache.py       #
    #    collect_stats -> /api/stats + prometheus gauges) -------------- #
    "tsd.query.agg_cache.hits": _m(
        "gauge", (), "Aggregate-block cache hits (blocks served)."),
    "tsd.query.agg_cache.misses": _m(
        "gauge", (), "Aggregate-block cache misses (blocks computed)."),
    "tsd.query.agg_cache.evictions": _m(
        "gauge", (), "Aggregate-block cache evictions (both tiers)."),
    "tsd.query.agg_cache.invalidations": _m(
        "gauge", (), "Aggregate-block dirty marks recorded."),
    "tsd.query.agg_cache.rewrites": _m(
        "gauge", (), "Plans served via the partial-aggregate rewrite."),
    "tsd.query.agg_cache.populated": _m(
        "gauge", (), "Aggregate blocks materialized into the cache."),
    "tsd.query.agg_cache.entries": _m(
        "gauge", (), "Aggregate blocks resident (host tier)."),
    "tsd.query.agg_cache.bytes": _m(
        "gauge", (), "Aggregate-block host-tier resident bytes."),
    "tsd.query.agg_cache.device_bytes": _m(
        "gauge", (), "Aggregate-block device-tier resident bytes."),
    # -- rollup lanes (storage/rollup.py): registry families ----------- #
    "tsd.rollup.lane.hits": _m(
        "counter", ("lane",),
        "Plans answered from a rollup lane, by lane interval."),
    "tsd.rollup.lane.misses": _m(
        "counter", ("reason",),
        "Lane-eligible plans that fell back to the exact paths, by "
        "reason (cold, striping)."),
    "tsd.rollup.lane.builds": _m(
        "counter", ("lane",),
        "Lane blocks materialized from the memstore by the "
        "maintenance thread, by lane interval."),
    "tsd.rollup.lane.build_errors": _m(
        "counter", (),
        "Lane block builds that raised (caught + counted; retried "
        "next pass)."),
    "tsd.rollup.lane.evictions": _m(
        "counter", (),
        "Lane blocks evicted by the tsd.rollup.mb LRU."),
    "tsd.rollup.lane.invalidations": _m(
        "counter", (),
        "Rollup-lane invalidation marks (ingest dirty ranges, "
        "dropcaches)."),
    "tsd.rollup.lane.bytes": _m(
        "gauge", (),
        "Rollup-lane store resident bytes (tsd.rollup.mb budget)."),
    "tsd.rollup.lane.blocks": _m(
        "gauge", (), "Rollup-lane blocks resident."),
    # -- rollup-lane stats walk (storage/rollup.py collect_stats ->     #
    #    /api/stats + prometheus gauges) ------------------------------- #
    "tsd.query.rollup.hits": _m(
        "gauge", (), "Plans served from rollup lanes."),
    "tsd.query.rollup.misses": _m(
        "gauge", (), "Lane-eligible plans that fell back."),
    "tsd.query.rollup.builds": _m(
        "gauge", (), "Lane blocks materialized."),
    "tsd.query.rollup.build_errors": _m(
        "gauge", (), "Lane block builds that raised."),
    "tsd.query.rollup.blocks": _m(
        "gauge", (), "Lane blocks resident."),
    "tsd.query.rollup.bytes": _m(
        "gauge", (), "Lane store resident bytes."),
    "tsd.query.rollup.evictions": _m(
        "gauge", (), "Lane blocks evicted (byte-budget LRU)."),
    "tsd.query.rollup.invalidations": _m(
        "gauge", (), "Lane invalidation marks recorded."),
    "tsd.query.rollup.served_windows": _m(
        "gauge", (), "Downsample windows answered from lane cells."),
    "tsd.query.rollup.demand_entries": _m(
        "gauge", (),
        "Tracked (metric, lane) demand candidates (the Storyboard "
        "selection corpus)."),
    # -- flight recorder + health engine (obs/flightrec.py,             #
    #    obs/health.py, served at /api/diag*) -------------------------- #
    # -- WAL integrity (storage/persist.py) ----------------------------- #
    "tsd.storage.wal.corrupt_records": _m(
        "counter", (),
        "WAL records whose CRC32/frame failed verification at "
        "replay/tail time (interior corruption; replay stops at the "
        "last valid record and truncates the hole)."),
    "tsd.diag.events": _m(
        "counter", ("kind",),
        "Flight-recorder events recorded, by event kind (admission, "
        "plan, tiling, breaker, deadline, compile, autotune, health, "
        "...)."),
    "tsd.diag.slow_captures": _m(
        "counter", (),
        "Slow/anomalous queries whose span tree + flight-recorder "
        "slice were retained at /api/diag/slow."),
    "tsd.diag.dropped": _m(
        "counter", ("kind",),
        "Flight-recorder events dropped on ring overflow, by the "
        "evicted event's kind — evidence lost before any reader saw "
        "it (the health engine's diag subsystem judges the rate)."),
    "tsd.health.status": _m(
        "gauge", ("subsystem",),
        "Health-engine verdict per subsystem: 0 ok, 1 degraded, "
        "2 failing (chaos_soak's post-heal gate)."),
    # -- latency attribution (obs/latattr.py, served at                  #
    #    /api/diag/latency) -------------------------------------------- #
    "tsd.latattr.requests": _m(
        "counter", (),
        "Requests folded into the always-on latency-attribution "
        "profiles (every HTTP request, tracing on or off)."),
    "tsd.latattr.phase_ms": _m(
        "counter", ("phase",),
        "Cumulative milliseconds attributed to each fixed request "
        "phase (parse, admission_wait, plan, batch_rendezvous, "
        "dispatch, device_wait, serialize, flush) across all "
        "requests."),
    "tsd.latattr.profiles": _m(
        "gauge", (),
        "Distinct (route, plan fingerprint, tenant) latency-"
        "attribution profiles live (bounded by "
        "tsd.latattr.max_profiles)."),
    "tsd.latattr.profile_overflow": _m(
        "counter", (),
        "Requests folded into the overflow profile because the "
        "profile table was already at tsd.latattr.max_profiles "
        "distinct keys."),
    # -- diagnostics stats walk (flight recorder + health stats hooks   #
    #    -> /api/stats + the self-report loop) ------------------------- #
    "tsd.diag.ring.events": _m(
        "gauge", (), "Flight-recorder events recorded since startup "
        "(the ring's latest sequence number)."),
    "tsd.diag.slow.captured": _m(
        "gauge", (), "Slow-query captures retained since startup."),
    "tsd.diag.ring.dropped": _m(
        "gauge", (), "Flight-recorder events dropped on ring overflow "
        "since startup (all kinds), re-walked for /api/stats and the "
        "self-report loop."),
    "tsd.latattr.observed": _m(
        "gauge", (), "Latency-attribution requests folded since "
        "startup, re-walked for /api/stats and the self-report loop."),
    "tsd.latattr.live_profiles": _m(
        "gauge", (), "Distinct latency-attribution profiles live, "
        "re-walked for /api/stats and the self-report loop."),
    "tsd.latattr.ms": _m(
        "gauge", ("phase",),
        "Cumulative per-phase attributed milliseconds, re-walked for "
        "/api/stats and the self-report loop."),
    "tsd.diag.tenant.demand": _m(
        "gauge", ("tenant",),
        "Per-tenant demand counters re-walked for /api/stats and the "
        "self-report loop."),
    "tsd.diag.tenant.admitted": _m(
        "gauge", ("tenant",),
        "Per-tenant admitted counters (the drained half of the "
        "demand split) re-walked for /api/stats and the self-report "
        "loop."),
    "tsd.diag.tenant.refused": _m(
        "gauge", ("tenant",),
        "Per-tenant refused counters (the shed half of the demand "
        "split) re-walked for /api/stats and the self-report loop."),
    "tsd.health.passes": _m(
        "gauge", (), "Health-engine evaluation passes completed."),
    # -- device cache (storage/device_cache.py collect_stats, mirrored  #
    #    by obs/jaxprof.py update_device_gauges) ----------------------- #
    "tsd.query.device_cache.hits": _m(
        "gauge", (), "Device-cache batch gathers served from HBM."),
    "tsd.query.device_cache.misses": _m(
        "gauge", (), "Device-cache misses (cold/stale/over-budget)."),
    "tsd.query.device_cache.builds": _m(
        "gauge", (), "Device-cache entry builds."),
    "tsd.query.device_cache.evictions": _m(
        "gauge", (), "Device-cache LRU evictions."),
    "tsd.query.device_cache.entries": _m(
        "gauge", (), "Device-cache resident entries."),
    "tsd.query.device_cache.bytes": _m(
        "gauge", (), "Device-cache resident bytes."),
}


def generate_metrics_doc() -> str:
    """Render docs/metrics.md from METRICS_SCHEMA (one table per
    top-level prefix).  tests/test_lint_clean.py pins the committed
    file to this output."""
    groups: dict[str, list[tuple[str, MetricSpec]]] = {}
    for name, spec in sorted(METRICS_SCHEMA.items()):
        segs = name.split(".")
        if "*" in segs[:2]:
            # templated names (tsd.*.errors) get their own section
            # instead of a literal '## `tsd.*.*`' heading
            prefix = "templated"
        else:
            prefix = ".".join(segs[:2])
        groups.setdefault(prefix, []).append((name, spec))
    lines = [
        "# Metrics reference",
        "",
        "<!-- GENERATED FILE — do not edit by hand.",
        "     Regenerate with: python tools/lint/run.py --update-doc",
        "     Source of truth: opentsdb_tpu/obs/__init__.py "
        "METRICS_SCHEMA. -->",
        "",
        "Every metric name emitted through the obs/registry.py families "
        "or `StatsCollector.record` is declared here; "
        "tools/lint/metrics_schema.py fails the build on an undeclared "
        "name or a kind collision.  A `*` segment stands for a value "
        "interpolated at the emission site (RPC kind, platform).  "
        "Record-emitted metrics are exposed as gauges on "
        "`/api/stats/prometheus` and additionally carry the collector's "
        "ambient tags (`host`, plus any context tags) on top of the "
        "labels listed.",
        "",
    ]
    for prefix in sorted(groups):
        lines.append("## `%s.*`" % prefix)
        lines.append("")
        lines.append("| metric | kind | labels | description |")
        lines.append("|---|---|---|---|")
        for name, spec in groups[prefix]:
            lines.append("| `%s` | %s | %s | %s |" % (
                name, spec.kind,
                ", ".join("`%s`" % k for k in spec.labels) or "—",
                spec.doc))
        lines.append("")
    return "\n".join(lines)
