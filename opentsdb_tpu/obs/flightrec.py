"""Flight recorder: an always-on, bounded ring of diagnostic events.

The r03-r05 chip-bench blackout stayed undiagnosable for three sessions
because nothing RETAINED what the daemon was doing when it mattered —
every decision the query-path subsystems make (admission verdicts,
cache/rollup consults, tile spills, autotune flips, breaker
transitions, deadline expiries, steady-state recompiles) was visible
only to a query that opted into showStats or an operator scraping at
the right instant.  This module is the retained-evidence layer:

  * **The ring** — a bounded deque of structured events, each stamped
    with a monotonic sequence number, a wall-clock timestamp, and the
    AMBIENT trace id (obs/trace.py) when one is active, so a recorded
    decision correlates with the span tree that made it.  Appends are
    lock-cheap (one short critical section, no I/O, no allocation
    beyond the event dict); overflow drops the OLDEST events by
    design.  Served at ``/api/diag`` (``?since=<seq>`` for incremental
    scrapes) and dumped to disk at shutdown/SIGTERM when
    ``tsd.diag.dump_path`` is set — a wedged bench session leaves a
    black box.
  * **Slow-query capture** — queries breaching a latency threshold
    (absolute ``tsd.diag.slow_ms``, or the rolling
    ``tsd.diag.slow_quantile`` of this recorder's own latency
    histogram) automatically retain their full span tree — which
    carries the costmodel decisions the planner annotated — plus the
    flight-recorder slice sharing their trace id, in a bounded store
    served at ``/api/diag/slow``.  No showStats required.
  * **Tenant clamping** — the ``X-TSDB-Tenant`` header value is
    clamped to a registered (``tsd.diag.tenants``) or hashed
    (``tsd.diag.tenant_buckets``) table before it mints a metric
    label, so a client cannot mint unbounded label cardinality.  The
    per-tenant demand counters this enables are the telemetry
    prerequisite for the fair-share scheduler (ROADMAP item 1).

One recorder per TSDB (``tsdb.flightrec``; ``tsd.diag.enable=false``
disables it and the /api/diag surface).  Event producers are the
EXISTING decision points — the wiring is wide but shallow; see
docs/observability.md for the event-kind catalog.

The recorder subscribes to the shared ``CompileLogCapture``
(obs/jaxprof.py) so steady-state recompiles land in the ring with the
trace id of the query that triggered them — the same single capture
tsdbsan and the compile counters use.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import zlib
from collections import deque

from opentsdb_tpu.obs import latattr
from opentsdb_tpu.obs import trace as obs_trace
from opentsdb_tpu.obs.histogram import LogHistogram
from opentsdb_tpu.obs.registry import REGISTRY

LOG = logging.getLogger("tsd.flightrec")

# Rolling-quantile slow capture needs this many observations before the
# quantile is trusted; below it only the absolute threshold applies.
SLOW_MIN_SAMPLES = 64


def clamp_tenant(config, raw: str | None) -> str:
    """Clamp a client-supplied tenant header to a bounded label table.

    A registered tenant (``tsd.diag.tenants``, comma-separated) keeps
    its name; anything else hashes into one of
    ``tsd.diag.tenant_buckets`` stable buckets (0 buckets collapses
    every unregistered tenant to "other").  An absent/empty header is
    "default".  This is the ONLY path from the header to a metric
    label — labels must never come from raw client strings.
    """
    raw = (raw or "").strip()
    if not raw:
        return "default"
    registered = config.get_string("tsd.diag.tenants")
    if registered:
        for name in registered.split(","):
            if raw == name.strip():
                return raw
    buckets = config.get_int("tsd.diag.tenant_buckets")
    if buckets <= 0:
        return "other"
    return "tenant-%02x" % (zlib.crc32(raw.encode("utf-8")) % buckets)


class FlightRecorder:
    """Bounded ring of structured diagnostic events + the slow store.

    ``record()`` is the one producer entry point; it must stay cheap
    enough for the query hot path (the tsdbobs 1.15x overhead pin
    measures it on by default).
    """

    def __init__(self, config):
        self.ring_size = max(config.get_int("tsd.diag.ring_size"), 16)
        self.dump_path = config.get_string("tsd.diag.dump_path")
        self.slow_ms = config.get_int("tsd.diag.slow_ms")
        self.slow_quantile = config.get_float("tsd.diag.slow_quantile")
        slow_keep = max(config.get_int("tsd.diag.slow_keep"), 1)
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._events: deque = deque(maxlen=self.ring_size)
        self._seq = 0  # guarded-by: _lock
        self._slow: deque = deque(maxlen=slow_keep)  # guarded-by: _lock
        self.slow_captured = 0  # guarded-by: _lock
        self._subscribed = False  # guarded-by: _lock
        self._dumped = False  # guarded-by: _lock
        # the recorder's OWN latency summary: the rolling-quantile slow
        # threshold must not depend on how the registry's histogram is
        # labeled (tenants split that one into many cells)
        self._latency = LogHistogram()
        # per-kind counter cells cached so the hot path skips the
        # registry's family/labels dict locks after first use
        self._event_family = REGISTRY.counter(
            "tsd.diag.events", "Flight-recorder events recorded, "
            "by event kind")
        self._cells: dict[str, object] = {}  # guarded-by: _lock
        # ring-overflow accounting: events evicted oldest-first, by the
        # EVICTED event's kind — a silent ring wrap hides exactly the
        # fault window the recorder exists for, so the drops themselves
        # are evidence (/api/diag "dropped", tsd.diag.dropped, and the
        # health engine's sustained-drop-rate invariant)
        self._dropped: dict[str, int] = {}  # guarded-by: _lock
        self._dropped_total = 0  # guarded-by: _lock
        self._drop_family = REGISTRY.counter(
            "tsd.diag.dropped", "Flight-recorder events dropped on "
            "ring overflow, by the evicted event's kind")
        self._drop_cells: dict[str, object] = {}  # guarded-by: _lock

    # -- lifecycle ------------------------------------------------------ #

    def start(self) -> None:
        """Arm the steady-state recompile feed: subscribe to the SHARED
        compile-log capture (one handler, one event stream — the same
        one the compile counters and tsdbsan use)."""
        from opentsdb_tpu.obs import jaxprof
        with self._lock:
            if self._subscribed:
                return
            self._subscribed = True
        # global-install: unsubscribe paired-with: shutdown
        jaxprof.compile_capture.subscribe(self._on_compile)

    def shutdown(self) -> None:
        """Mirror start(): drop the compile subscription, then write
        the shutdown dump (once) so a post-mortem has the ring even
        when nobody scraped /api/diag in time.  Reached from
        TSDB.shutdown on every exit path incl. SIGTERM."""
        from opentsdb_tpu.obs import jaxprof
        with self._lock:
            was_subscribed, self._subscribed = self._subscribed, False
        if was_subscribed:
            jaxprof.compile_capture.unsubscribe(self._on_compile)
        self.record("shutdown")
        with self._lock:
            if self._dumped:
                return
            self._dumped = True
        if self.dump_path:
            try:
                self.dump(self.dump_path)
            except OSError:
                LOG.exception("flight-recorder shutdown dump to %s "
                              "failed", self.dump_path)

    def _on_compile(self, kernel: str) -> None:
        # synchronous in the compiling thread: the ambient trace id (if
        # any) names the query whose dispatch forced the compile
        self.record("compile", kernel=kernel)

    # -- the ring -------------------------------------------------------- #

    def record(self, kind: str, trace_id: str | None = None,
               **fields) -> int:
        """Append one event; returns its sequence number.  The ambient
        trace id is stamped automatically when none is passed."""
        if trace_id is None:
            tr = obs_trace.active()
            if tr is not None:
                trace_id = tr.trace_id
        event = {"kind": kind, "tMs": int(time.time() * 1e3)}
        if trace_id:
            event["traceId"] = trace_id
        phase = latattr.phase_in_flight()
        if phase is not None:
            # the request phase in flight when this event was recorded
            # (obs/latattr.py) — "which phase was the daemon in when
            # the breaker opened" without needing a trace
            event["phase"] = phase
        if fields:
            event.update(fields)
        drop_cell = None
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            if len(self._events) == self.ring_size:
                evicted = self._events[0]["kind"]
                self._dropped[evicted] = self._dropped.get(evicted, 0) + 1
                self._dropped_total += 1
                drop_cell = self._drop_cells.get(evicted)
                if drop_cell is None:
                    drop_cell = self._drop_cells[evicted] = \
                        self._drop_family.labels(kind=evicted)
            self._events.append(event)
            cell = self._cells.get(kind)
            if cell is None:
                cell = self._cells[kind] = \
                    self._event_family.labels(kind=kind)
        cell.inc()
        if drop_cell is not None:
            drop_cell.inc()
        return event["seq"]

    def latest_seq(self) -> int:
        with self._lock:
            return self._seq

    def dropped(self) -> tuple[dict[str, int], int]:
        """(per-kind dropped-oldest tallies, total) since start."""
        with self._lock:
            return dict(self._dropped), self._dropped_total

    def events(self, since: int = 0) -> list[dict]:
        """Ring snapshot, oldest first; ``since`` returns only events
        with a LARGER sequence number (the /api/diag?since= contract:
        poll with the last seq you saw)."""
        with self._lock:
            snap = list(self._events)
        if since > 0:
            snap = [e for e in snap if e["seq"] > since]
        return snap

    def events_for_trace(self, trace_id: str) -> list[dict]:
        with self._lock:
            snap = list(self._events)
        return [e for e in snap if e.get("traceId") == trace_id]

    # -- slow-query capture ---------------------------------------------- #

    def maybe_capture_slow(self, trace, elapsed_ms: float, status: int,
                           query_json: dict | None,
                           tenant: str = "default") -> bool:
        """Called per served query: observe the latency, and when it
        breaches the absolute or rolling-quantile threshold retain the
        full evidence bundle (span tree + the ring slice sharing the
        trace id) in the bounded slow store."""
        threshold = float("inf")
        if self.slow_ms > 0:
            threshold = float(self.slow_ms)
        if 0.0 < self.slow_quantile <= 1.0 \
                and self._latency.count >= SLOW_MIN_SAMPLES:
            threshold = min(threshold,
                            self._latency.quantile(self.slow_quantile))
        self._latency.observe(max(elapsed_ms, 0.0))
        if elapsed_ms < threshold:
            return False
        trace_id = trace.trace_id if trace is not None else None
        entry = {
            "capturedMs": int(time.time() * 1e3),
            "elapsedMs": round(elapsed_ms, 3),
            "thresholdMs": round(threshold, 3),
            "status": int(status),
            "tenant": tenant,
        }
        if trace_id:
            entry["traceId"] = trace_id
            entry["events"] = self.events_for_trace(trace_id)
        if query_json is not None:
            entry["query"] = query_json
        if trace is not None:
            # the tree carries the costmodel/agg_cache/rollup/tiling
            # decision tags the planner annotated — no showStats needed
            entry["trace"] = trace.to_json()
        with self._lock:
            self._slow.append(entry)
            self.slow_captured += 1
        REGISTRY.counter(
            "tsd.diag.slow_captures",
            "Slow/anomalous queries retained by the flight "
            "recorder").inc()
        self.record("slow_query", trace_id=trace_id,
                    elapsedMs=round(elapsed_ms, 3), status=int(status),
                    tenant=tenant)
        return True

    def slow_queries(self, trace_id: str | None = None) -> list[dict]:
        """The retained slow captures, newest first; with a trace id,
        only the captures for that trace (the one-request lookup an
        explain fingerprint's exemplar resolves through)."""
        with self._lock:
            snap = list(self._slow)[::-1]
        if trace_id:
            snap = [e for e in snap if e.get("traceId") == trace_id]
        return snap

    # -- shutdown dump ---------------------------------------------------- #

    def dump(self, path: str) -> None:
        """Write the black box: ring + slow store, one JSON document."""
        with self._lock:
            payload = {
                "dumpedMs": int(time.time() * 1e3),
                "seq": self._seq,
                "ringSize": self.ring_size,
                "dropped": dict(self._dropped),
                "droppedTotal": self._dropped_total,
                "events": list(self._events),
                "slowQueries": list(self._slow),
            }
        with open(path, "w") as fh:
            json.dump(payload, fh)
        LOG.info("flight recorder dumped %d events to %s",
                 len(payload["events"]), path)

    # -- stats ------------------------------------------------------------ #

    def stats_hook(self, collector) -> None:
        """The /api/stats + self-report view: ring volume, slow
        captures, and the per-tenant demand counters (read back from
        the registry family the admission gate increments) — so the
        TSD can query its own demand/health history through its own
        pipeline (obs/selfreport.py)."""
        with self._lock:
            seq = self._seq
            captured = self.slow_captured
            dropped_total = self._dropped_total
        collector.record("diag.ring.events", seq)
        collector.record("diag.ring.dropped", dropped_total)
        collector.record("diag.slow.captured", captured)
        def cells(fam):
            for labels, cell in fam.children():
                yield (dict(labels).get("tenant", "default"),
                       cell.get())

        for tenant, value in cells(REGISTRY.counter(
                "tsd.query.tenant.demand",
                "Queries arriving at admission, by clamped tenant")):
            collector.record("diag.tenant.demand", value,
                             "tenant=%s" % tenant)
        for tenant, value in cells(REGISTRY.counter(
                "tsd.query.tenant.admitted",
                "Queries admitted through the gate, by clamped "
                "tenant")):
            collector.record("diag.tenant.admitted", value,
                             "tenant=%s" % tenant)
        for tenant, value in cells(REGISTRY.counter(
                "tsd.query.tenant.refused",
                "Queries refused by the gate, by clamped tenant")):
            collector.record("diag.tenant.refused", value,
                             "tenant=%s" % tenant)
