"""Health engine: declared invariants -> per-subsystem verdicts.

The flight recorder (obs/flightrec.py) retains WHAT happened; this
module judges whether it is FINE.  On the maintenance cadence
(``tsd.health.interval``) the engine evaluates a fixed set of declared
invariants — each a burn-rate/ratio check over the window since the
last pass, never a point-in-time glance — and folds each into an
``ok | degraded | failing`` verdict per subsystem:

  * **admission** — shed burn: queries refused per second over the
    window vs ``tsd.health.shed_rate``.  A daemon shedding steadily
    after a burst lifted has NOT healed.
  * **compile** — steady-state recompiles: XLA compilations per window
    (via the shared compile counters) past ``tsd.health.recompile_limit``
    once the daemon is older than ``tsd.health.recompile_warmup``
    seconds.  Steady-state serving must be compile-clean (the tsdbsan
    contract, now judged continuously).
  * **agg_cache** — hit-rate collapse: consults in the window with a
    hit fraction under ``tsd.health.cache_hit_floor`` (volume-gated:
    a handful of cold misses is not a collapse).
  * **costmodel** — predicted-vs-actual drift: the window's summed
    predicted vs measured device ms off by more than
    ``tsd.health.costmodel_drift`` x in either direction (volume-gated).
  * **spill** — pool saturation: resident bytes vs the combined
    host+disk budget past ``tsd.health.spill_saturation``.
  * **cluster** — breaker flap: open transitions in the window past
    ``tsd.health.breaker_flap``, and any breaker currently open is at
    least degraded.
  * **tenant** — cross-tenant starvation: among tenants with
    meaningful window demand, the max/min admitted-share ratio past
    ``tsd.health.tenant_share_ratio`` (failing when a demanding
    tenant was admitted NOTHING while others were served).  Judges
    the fair-share drain (tsd/admission.py weighted DRR) — a healthy
    storm sheds the storming tenant's excess, it never zeroes anyone
    out.
  * **replication** — under-replicated shards / lag burn: any shard
    with fewer healthy members than the replication factor is at
    least degraded (one more failure loses data), and growth of the
    worst replica's unacknowledged WAL backlog past
    ``tsd.health.replication_lag`` records per window is degraded
    (failing at 4x) — a replica that stops draining has NOT healed
    just because ships stop erroring.
  * **latency** — phase-share burn: the serialize phase's share of
    the window's total attributed request time (obs/latattr.py
    always-on phase stamps) past ``tsd.health.phase_share``
    (volume-gated).  Serialize time is pure host-side overhead — a
    daemon spending a growing fraction of every request JSON-encoding
    replies is burning its latency budget outside the device, the
    precise regression tsdbsan's serialize pin guards at test time,
    now judged continuously in production.
  * **diag** — evidence loss: flight-recorder ring overflow (events
    evicted before any reader saw them) past
    ``tsd.health.diag_drop_rate`` drops/second over the window.  A
    steadily-overflowing ring means the next incident's history is
    already gone.

Verdicts are exported as ``tsd.health.status`` gauges (0 ok /
1 degraded / 2 failing), served at ``/api/diag/health``, recorded into
the flight recorder on every level CHANGE, walked into /api/stats and
the self-report loop via the stats-hook registry, and consumed by
``tools/chaos_soak.py`` as the post-heal gate: after a fault window
clears, every subsystem must read ``ok``.

A subsystem that is disabled, cold, or below the volume gate reports
``ok`` — the engine judges violated invariants, it does not punish
idleness.
"""

from __future__ import annotations

import threading
import time

from opentsdb_tpu.obs.registry import REGISTRY

LEVELS = ("ok", "degraded", "failing")
_LEVEL_NUM = {lvl: i for i, lvl in enumerate(LEVELS)}

# Volume gates: below these per-window totals a ratio check abstains.
_CACHE_MIN_CONSULTS = 16
_CACHE_FAIL_CONSULTS = 64
_COSTMODEL_MIN_ACTUAL_MS = 50.0
_TENANT_MIN_DEMAND = 16.0
_LATENCY_MIN_REQUESTS = 32.0
_LATENCY_MIN_TOTAL_MS = 50.0


def _worst(a: str, b: str) -> str:
    return a if _LEVEL_NUM[a] >= _LEVEL_NUM[b] else b


def _counter_total(name: str) -> float:
    """Sum of a registry counter family across label cells (0.0 when
    the family never registered)."""
    # forwarder: callers pass names already declared in METRICS_SCHEMA
    # (tsd.costmodel.predicted_ms/actual_ms); nothing is minted here
    fam = REGISTRY.counter(name)  # tsdblint: disable=metrics-dynamic-name
    return sum(cell.get() for _labels, cell in fam.children())


class HealthEngine:
    """Evaluates the declared invariants against one TSDB instance."""

    SUBSYSTEMS = ("admission", "compile", "agg_cache", "costmodel",
                  "spill", "cluster", "tenant", "replication",
                  "latency", "diag")

    def __init__(self, tsdb):
        cfg = tsdb.config
        self.tsdb = tsdb
        self.interval = cfg.get_int("tsd.health.interval")
        self.shed_rate = cfg.get_float("tsd.health.shed_rate")
        self.recompile_warmup = cfg.get_int("tsd.health.recompile_warmup")
        self.recompile_limit = cfg.get_int("tsd.health.recompile_limit")
        self.cache_hit_floor = cfg.get_float("tsd.health.cache_hit_floor")
        self.costmodel_drift = cfg.get_float("tsd.health.costmodel_drift")
        self.spill_saturation = cfg.get_float(
            "tsd.health.spill_saturation")
        self.breaker_flap = cfg.get_int("tsd.health.breaker_flap")
        self.tenant_share_ratio = cfg.get_float(
            "tsd.health.tenant_share_ratio")
        self.replication_lag = cfg.get_int("tsd.health.replication_lag")
        self.phase_share = cfg.get_float("tsd.health.phase_share")
        self.diag_drop_rate = cfg.get_float("tsd.health.diag_drop_rate")
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._verdicts: dict[str, dict] = {}
        self.passes = 0  # guarded-by: _lock
        self._evaluated_ms = 0  # guarded-by: _lock
        # previous pass's cumulative counters (deltas = the window)
        # guarded-by: _lock
        self._last: dict[str, float] = {}
        self._last_eval_t: float | None = None  # guarded-by: _lock
        # maintenance-thread cadence state: only that thread's tick
        # touches it (same discipline as OnlineCalibrator._next_fit)
        self._next_eval: float | None = None

    # -- cadence --------------------------------------------------------- #

    def tick(self, now: float | None = None) -> bool:
        """One maintenance heartbeat; evaluates when the interval
        elapsed.  Returns True when a pass ran."""
        if now is None:
            now = time.monotonic()
        if self.interval <= 0:
            return False
        if self._next_eval is None:
            self._next_eval = now + max(self.interval, 1)
            return False
        if now < self._next_eval:
            return False
        self._next_eval = now + max(self.interval, 1)
        self.evaluate()
        return True

    # -- evaluation ------------------------------------------------------ #

    def evaluate(self) -> dict[str, dict]:
        """One pass over every invariant.  Window = time since the
        previous pass (since construction on the first)."""
        tsdb = self.tsdb
        now = time.monotonic()
        with self._lock:
            last = dict(self._last)
            last_t = self._last_eval_t
        window_s = max(now - last_t, 1e-3) if last_t is not None \
            else max(time.time() - tsdb.start_time, 1e-3)
        window_s = min(window_s, 3600.0)
        cur: dict[str, float] = {}
        verdicts: dict[str, dict] = {}

        def delta(key: str, value: float) -> float:
            cur[key] = float(value)
            return max(float(value) - last.get(key, 0.0), 0.0)

        # admission: shed burn rate over the window
        gate = getattr(tsdb, "_admission_gate", None)
        shed = delta("shed", gate.shed if gate is not None else 0.0)
        rate = shed / window_s
        level = "ok"
        if rate > self.shed_rate > 0:
            level = "failing" if rate > 4 * self.shed_rate else "degraded"
        verdicts["admission"] = {
            "level": level,
            "detail": "%.2f sheds/s over %.0fs window (limit %.2f/s)"
                      % (rate, window_s, self.shed_rate)}

        # compile: steady-state recompiles per window after warmup.
        # Source is whichever shared-capture subscriber is armed: the
        # flight recorder's compile events (server-armed regardless of
        # tracing) or jaxprof's per-kernel counters (tracing on) — max
        # of two cumulative counts of the same event stream stays
        # monotone when either is dark.
        from opentsdb_tpu.obs import jaxprof
        diag_compiles = REGISTRY.counter(
            "tsd.diag.events", "Flight-recorder events recorded, "
            "by event kind").labels(kind="compile").get()
        compiles = delta("compiles",
                         max(sum(jaxprof.compile_counts().values()),
                             diag_compiles))
        uptime = time.time() - tsdb.start_time
        level = "ok"
        if uptime >= self.recompile_warmup > 0:
            excess = compiles - self.recompile_limit
            if excess > 0:
                level = "failing" if excess > 4 else "degraded"
        verdicts["compile"] = {
            "level": level,
            "detail": "%d compiles in window (limit %d; warmup %s)"
                      % (compiles, self.recompile_limit,
                         "done" if uptime >= self.recompile_warmup
                         else "%.0fs left"
                         % (self.recompile_warmup - uptime))}

        # agg_cache: hit-rate collapse (volume-gated)
        cache = getattr(tsdb, "agg_cache", None)
        level, detail = "ok", "cache disabled"
        if cache is not None:
            hits = delta("cache_hits", cache.hits)
            misses = delta("cache_misses", cache.misses)
            consults = hits + misses
            detail = "%.0f/%.0f hits/consults in window" \
                % (hits, consults)
            if consults >= _CACHE_MIN_CONSULTS \
                    and hits / consults < self.cache_hit_floor:
                level = ("failing" if hits == 0
                         and consults >= _CACHE_FAIL_CONSULTS
                         else "degraded")
        verdicts["agg_cache"] = {"level": level, "detail": detail}

        # costmodel: predicted-vs-actual drift.  Volume-gated AND
        # calibration-gated: an uncalibrated daemon (no autotune loop,
        # or none of its fits installed yet) predicts from another
        # platform's constants — orders-of-magnitude "drift" there is
        # the expected state autotune exists to fix, not ill health.
        predicted = delta("cm_predicted",
                          _counter_total("tsd.costmodel.predicted_ms"))
        actual = delta("cm_actual",
                       _counter_total("tsd.costmodel.actual_ms"))
        calibrator = getattr(tsdb, "autotuner", None)
        fitted = calibrator is not None and calibrator.fits > 0
        level, detail = "ok", (
            "insufficient device time in window" if fitted
            else "uncalibrated (no live fit installed)")
        if fitted and actual >= _COSTMODEL_MIN_ACTUAL_MS \
                and predicted > 0:
            ratio = max(predicted / actual, actual / predicted)
            detail = "predicted %.0fms vs actual %.0fms (x%.1f drift, " \
                "limit x%.1f)" % (predicted, actual, ratio,
                                  self.costmodel_drift)
            if ratio > self.costmodel_drift > 0:
                level = "failing" if ratio > 4 * self.costmodel_drift \
                    else "degraded"
        verdicts["costmodel"] = {"level": level, "detail": detail}

        # spill: pool saturation
        pool = getattr(tsdb, "spill_pool", None)
        level, detail = "ok", "spill pool disabled"
        if pool is not None:
            budget = pool.host_budget + pool.disk_budget
            resident = pool.host_bytes + pool.disk_bytes
            util = resident / budget if budget > 0 else 0.0
            detail = "%.0f%% of %.0fMB pool resident" \
                % (util * 100, budget / 2**20)
            if util >= 1.0:
                level = "failing"
            elif util > self.spill_saturation > 0:
                level = "degraded"
        verdicts["spill"] = {"level": level, "detail": detail}

        # cluster: breaker flap + currently-open breakers
        state = getattr(tsdb, "_cluster_state", None)
        level, detail = "ok", "no clustered serving yet"
        if state is not None:
            breakers = state.breakers()
            opens = delta("breaker_opens",
                          sum(b.opens for b in breakers.values()))
            open_now = [p for p, b in breakers.items()
                        if b.state != b.CLOSED]
            detail = "%d open transitions in window; open now: %s" \
                % (opens, ",".join(sorted(open_now)) or "none")
            if opens > self.breaker_flap > 0:
                level = "failing" if opens > 2 * self.breaker_flap \
                    else "degraded"
            if open_now:
                level = _worst(level, "degraded")
        verdicts["cluster"] = {"level": level, "detail": detail}

        # tenant: cross-tenant starvation — among tenants with
        # meaningful window demand, admitted-share (admitted/demand
        # deltas) must stay within tsd.health.tenant_share_ratio of
        # each other; a demanding tenant admitted NOTHING while
        # another was served is failing.  Every cell's delta is taken
        # every pass (even below the volume gate) so the window
        # baselines stay aligned.
        def _tenant_cells(name: str, doc: str) -> dict[str, float]:
            fam = REGISTRY.counter(name, doc)  # tsdblint: disable=metrics-dynamic-name
            return {dict(labels).get("tenant", "default"): cell.get()
                    for labels, cell in fam.children()}

        demand_cells = _tenant_cells(
            "tsd.query.tenant.demand",
            "Queries arriving at admission, by clamped tenant")
        admit_cells = _tenant_cells(
            "tsd.query.tenant.admitted",
            "Queries admitted through the gate, by clamped tenant")
        d_deltas: dict[str, float] = {}
        a_deltas: dict[str, float] = {}
        for t in set(demand_cells) | set(admit_cells):
            d_deltas[t] = delta("tenant_demand:%s" % t,
                                demand_cells.get(t, 0.0))
            a_deltas[t] = delta("tenant_admitted:%s" % t,
                                admit_cells.get(t, 0.0))
        shares = {t: a_deltas.get(t, 0.0) / d
                  for t, d in d_deltas.items()
                  if d >= _TENANT_MIN_DEMAND}
        level, detail = "ok", (
            "%d tenant(s) above the demand gate in window"
            % len(shares))
        if len(shares) >= 2:
            hi_t = max(shares, key=shares.get)
            lo_t = min(shares, key=shares.get)
            hi, lo = shares[hi_t], shares[lo_t]
            detail = ("admitted-share %s=%.2f vs %s=%.2f in window "
                      "(ratio limit x%.1f)"
                      % (hi_t, hi, lo_t, lo, self.tenant_share_ratio))
            if lo <= 0.0 and hi > 0.0:
                level = "failing"
            elif self.tenant_share_ratio > 0 \
                    and hi / max(lo, 1e-9) > self.tenant_share_ratio:
                level = "degraded"
        verdicts["tenant"] = {"level": level, "detail": detail}

        # replication: under-replicated shards + lag burn.  The lag
        # judged is the GROWTH of the worst replica's backlog over the
        # window — a standing-but-draining backlog after a burst is
        # healing, a growing one is not.
        repl = getattr(tsdb, "replication", None)
        level, detail = "ok", "replication disabled"
        if repl is not None:
            snap = repl.health_snapshot()
            lag_growth = delta("repl_lag_hwm", snap["lag"])
            detail = ("%d under-replicated shard(s); backlog %d "
                      "records (+%d in window, limit +%d)"
                      % (snap["under_replicated"], snap["lag"],
                         lag_growth, self.replication_lag))
            if snap["under_replicated"] > 0:
                level = "degraded"
            if self.replication_lag > 0 \
                    and lag_growth > self.replication_lag:
                level = _worst(
                    level,
                    "failing" if lag_growth > 4 * self.replication_lag
                    else "degraded")
        verdicts["replication"] = {"level": level, "detail": detail}

        # latency: phase-share burn — serialize's share of the
        # window's total attributed ms (obs/latattr.py).  Every phase
        # counter's delta is taken every pass so window baselines stay
        # aligned even while the volume gate abstains.
        latattr_engine = getattr(tsdb, "latattr", None)
        level, detail = "ok", "latency attribution disabled"
        if latattr_engine is not None:
            totals = latattr_engine.phase_totals()
            requests = delta("latattr_requests", totals["requests"])
            phase_win = {p: delta("latattr_ms:%s" % p, ms)
                         for p, ms in totals.items() if p != "requests"}
            total_ms = sum(phase_win.values())
            serialize_ms = phase_win.get("serialize", 0.0)
            detail = "%.0f request(s), %.0fms attributed in window" \
                % (requests, total_ms)
            if requests >= _LATENCY_MIN_REQUESTS \
                    and total_ms >= _LATENCY_MIN_TOTAL_MS:
                share = serialize_ms / total_ms
                detail = ("serialize %.0f%% of %.0fms attributed over "
                          "%.0f requests (budget %.0f%%)"
                          % (share * 100, total_ms, requests,
                             self.phase_share * 100))
                if share > self.phase_share > 0:
                    level = "failing" if share > 2 * self.phase_share \
                        else "degraded"
        verdicts["latency"] = {"level": level, "detail": detail}

        # diag: evidence loss — ring-overflow drop rate over the window
        recorder = getattr(tsdb, "flightrec", None)
        level, detail = "ok", "flight recorder disabled"
        if recorder is not None:
            _by_kind, dropped_total = recorder.dropped()
            drops = delta("diag_dropped", dropped_total)
            drop_rate = drops / window_s
            detail = "%.2f ring drops/s over %.0fs window (limit %.2f/s)" \
                % (drop_rate, window_s, self.diag_drop_rate)
            if drop_rate > self.diag_drop_rate > 0:
                level = "failing" if drop_rate > 4 * self.diag_drop_rate \
                    else "degraded"
        verdicts["diag"] = {"level": level, "detail": detail}

        self._publish(verdicts, cur, now)
        return verdicts

    def _publish(self, verdicts: dict[str, dict], cur: dict[str, float],
                 now: float) -> None:
        gauge = REGISTRY.gauge(
            "tsd.health.status",
            "Health-engine verdict per subsystem (0 ok, 1 degraded, "
            "2 failing)")
        with self._lock:
            previous = {k: v["level"] for k, v in self._verdicts.items()}
            self._verdicts = verdicts
            self._last = cur
            self._last_eval_t = now
            self.passes += 1
            self._evaluated_ms = int(time.time() * 1e3)
        changed = []
        for name, verdict in verdicts.items():
            gauge.labels(subsystem=name).set(
                _LEVEL_NUM[verdict["level"]])
            before = previous.get(name, "ok")
            if verdict["level"] != before:
                changed.append((name, before, verdict))
        recorder = getattr(self.tsdb, "flightrec", None)
        if recorder is not None:
            for name, before, verdict in changed:
                recorder.record("health", subsystem=name,
                                before=before, level=verdict["level"],
                                detail=verdict["detail"])

    # -- reporting ------------------------------------------------------- #

    def report(self) -> dict:
        """The /api/diag/health payload.  Evaluates inline when no
        maintenance pass has run yet, so a freshly-started (or
        maintenance-less library) daemon still answers with real
        verdicts instead of an empty shell."""
        with self._lock:
            passes = self.passes
        if passes == 0:
            self.evaluate()
        with self._lock:
            verdicts = {k: dict(v) for k, v in self._verdicts.items()}
            passes = self.passes
            evaluated = self._evaluated_ms
        overall = "ok"
        for v in verdicts.values():
            overall = _worst(overall, v["level"])
        return {"overall": overall, "subsystems": verdicts,
                "passes": passes, "evaluatedMs": evaluated}

    # -- stats ----------------------------------------------------------- #

    def stats_hook(self, collector) -> None:
        """The /api/stats + self-report view of the verdicts — the TSD
        can query its own health history (obs/selfreport.py ingests
        these through the same walk, ro-skip preserved)."""
        with self._lock:
            verdicts = {k: v["level"] for k, v in self._verdicts.items()}
            passes = self.passes
        collector.record("health.passes", passes)
        for name, level in verdicts.items():
            collector.record("health.status", _LEVEL_NUM[level],
                             "subsystem=%s" % name)
