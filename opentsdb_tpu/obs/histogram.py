"""Log-bucketed latency histogram: mergeable, quantile-estimating.

The summary structure behind the metrics registry's histograms, in the
spirit of the mergeable low-overhead summaries of Storyboard
(arXiv:2002.03063): geometric bucket bounds ``lo * growth**i`` make
rank queries answerable with a RELATIVE error bounded by one bucket's
growth factor, and two histograms with the same bucket layout merge by
adding counts — per-thread / per-host summaries fold losslessly.

Differences from stats/histogram.py (the reference-parity
linear-then-doubling `LatencyHistogram` kept for its Java fidelity):
pure geometric spacing (constant relative error across the whole
range), float observations, sum tracking (Prometheus `_sum`), merge,
and interpolated quantiles.
"""

from __future__ import annotations

import math
import threading

# Default layout: 1 microsecond .. ~84 seconds in ms units at 2**(1/4)
# growth — worst-case quantile error is a factor of ~1.19, and aligned
# coarsening (merge 4 adjacent buckets) yields clean power-of-two
# Prometheus bounds.
DEFAULT_LO = 1e-3
DEFAULT_GROWTH = 2 ** 0.25
DEFAULT_BUCKETS = 96


class LogHistogram:
    """Thread-safe log-bucketed histogram.

    Bucket 0 holds values <= ``lo``; bucket i (1..buckets-1) holds
    (lo*growth**(i-1), lo*growth**i]; the final slot is the +Inf
    overflow.  ``merge`` requires an identical layout.
    """

    __slots__ = ("lo", "growth", "buckets", "_log_growth", "_lock",
                 "counts", "count", "total", "exemplars")

    def __init__(self, lo: float = DEFAULT_LO,
                 growth: float = DEFAULT_GROWTH,
                 buckets: int = DEFAULT_BUCKETS):
        if lo <= 0 or growth <= 1.0 or buckets < 1:
            raise ValueError("invalid histogram layout: lo=%r growth=%r "
                             "buckets=%r" % (lo, growth, buckets))
        self.lo = float(lo)
        self.growth = float(growth)
        self.buckets = int(buckets)
        self._log_growth = math.log(self.growth)
        self._lock = threading.Lock()
        # guarded-by: _lock
        self.counts = [0] * (self.buckets + 1)
        self.count = 0  # guarded-by: _lock
        self.total = 0.0  # guarded-by: _lock
        # last exemplar per FINE bucket: {index: (label, value)} —
        # bounded by the bucket count; populated only when observers
        # pass one (obs/flightrec.py trace ids)  # guarded-by: _lock
        self.exemplars: dict[int, tuple[str, float]] = {}

    def _index(self, value: float) -> int:
        if value <= self.lo:
            return 0
        idx = int(math.ceil(math.log(value / self.lo) / self._log_growth
                            - 1e-9))
        return min(max(idx, 1), self.buckets)

    def bound(self, index: int) -> float:
        """Upper bound of bucket `index` (inf for the overflow slot)."""
        if index >= self.buckets:
            return math.inf
        return self.lo * self.growth ** index

    def observe(self, value: float, exemplar: str | None = None) -> None:
        if value != value or value < 0:        # NaN / negative
            raise ValueError("invalid observation: %r" % value)
        idx = self._index(value)
        with self._lock:
            self.counts[idx] += 1
            self.count += 1
            self.total += value
            if exemplar is not None:
                self.exemplars[idx] = (exemplar, value)

    def merge(self, other: "LogHistogram") -> None:
        if (other.lo, other.growth, other.buckets) != \
                (self.lo, self.growth, self.buckets):
            raise ValueError(
                "cannot merge histograms with different layouts: "
                "(%g, %g, %d) vs (%g, %g, %d)"
                % (self.lo, self.growth, self.buckets,
                   other.lo, other.growth, other.buckets))
        o_counts, o_count, o_total = other.snapshot()
        with self._lock:
            for i, c in enumerate(o_counts):
                self.counts[i] += c
            self.count += o_count
            self.total += o_total

    def snapshot(self) -> tuple[list[int], int, float]:
        with self._lock:
            return list(self.counts), self.count, self.total

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (0 < q <= 1): geometric interpolation
        inside the holding bucket, so the estimate is within one
        `growth` factor of any sample at that rank.  NaN when empty;
        the overflow bucket answers its lower bound (the largest
        trustworthy value)."""
        if not 0.0 < q <= 1.0:
            raise ValueError("invalid quantile: %r" % q)
        counts, count, _total = self.snapshot()
        if count == 0:
            return math.nan
        rank = max(int(math.ceil(q * count)), 1)
        seen = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if seen + c >= rank:
                if i == 0:
                    return self.lo
                if i >= self.buckets:
                    return self.lo * self.growth ** (self.buckets - 1)
                lower = self.lo * self.growth ** (i - 1)
                frac = (rank - seen) / c
                return lower * self.growth ** frac
            seen += c
        return self.lo * self.growth ** (self.buckets - 1)

    def cumulative(self, max_buckets: int = 24
                   ) -> list[tuple[float, int]]:
        """[(upper_bound, cumulative_count)] coarsened to at most
        `max_buckets` entries by merging ALIGNED runs of adjacent
        buckets (plus the +Inf slot) — the Prometheus `_bucket`
        series.  Coarsening preserves mergeability: two exposed
        histograms with the same layout coarsen identically."""
        counts, _count, _total = self.snapshot()
        step = max(-(-self.buckets // max(max_buckets - 1, 1)), 1)
        out: list[tuple[float, int]] = []
        cum = 0
        for lo_i in range(0, self.buckets, step):
            hi_i = min(lo_i + step, self.buckets)
            cum += sum(counts[lo_i:hi_i])
            out.append((self.bound(hi_i - 1), cum))
        cum += counts[self.buckets]
        out.append((math.inf, cum))
        return out

    def exemplar_entries(self, max_buckets: int = 24
                         ) -> list[tuple[float, str, float]]:
        """[(coarse_upper_bound, exemplar_label, observed_value)] using
        the SAME aligned coarsening as `cumulative`, so each exemplar
        attaches to a bucket bound the scrape actually exposes.  Within
        a coarse bucket the highest fine bucket's exemplar wins (the
        tail-most observation is the diagnostic one)."""
        with self._lock:
            snap = dict(self.exemplars)
        if not snap:
            return []
        step = max(-(-self.buckets // max(max_buckets - 1, 1)), 1)
        out: list[tuple[float, str, float]] = []
        for lo_i in range(0, self.buckets, step):
            hi_i = min(lo_i + step, self.buckets)
            best = None
            for i in range(lo_i, hi_i):
                if i in snap:
                    best = snap[i]
            if best is not None:
                out.append((self.bound(hi_i - 1), best[0], best[1]))
        if self.buckets in snap:
            label, value = snap[self.buckets]
            out.append((math.inf, label, value))
        return out
