"""JAX profiling hooks: compile accounting, device gauges, costmodel
predicted-vs-actual feedback.

Compile capture — THE shared source.  `jax_log_compiles` emits one
"Compiling <kernel> ..." log record per XLA compilation, synchronously
in the compiling thread.  `CompileLogCapture` owns the single logging
handler (and the flag save/restore) and fans each kernel name out to
subscribers; both this module's per-kernel counters AND tsdbsan's
JaxSanitizer (tools/sanitize/jax_san.py) subscribe to the same capture,
so the profiler and the sanitizer can never disagree about what
compiled — one regex, one handler, one event stream.

Costmodel feedback — the loop is CLOSED (PR 6).  ops/costmodel.py
predicts per-stage dispatch costs from calibrated per-unit constants;
`record_segment()` keeps a ring of (shape, chosen modes, feature
vector, predicted, actual) per query segment plus running totals in
the metrics registry.  ops/calibrate.py consumes the ring: it solves
the per-unit constants by non-negative least squares over the feature
vectors and installs them as the costmodel's live override layer, so
a daemon's strategy argmin converges to what its own traffic measures.
`segment_decisions()` recomputes the per-axis strategy decisions
through the same choosers the kernels consult (the trace annotates
them per segment), and `stage_breakdown()` apportions a fused
dispatch's measured device time across downsample/rate/groupby/
aggregate children (tagged estimated).
"""

from __future__ import annotations

import logging
import re
import threading
from collections import deque

from opentsdb_tpu.obs.registry import REGISTRY

COMPILING_RE = re.compile(r"Compiling (\S+) with global")
PXLA_LOGGER = "jax._src.interpreters.pxla"


class _CaptureHandler(logging.Handler):
    def __init__(self, capture: "CompileLogCapture") -> None:
        super().__init__(level=logging.DEBUG)
        self._capture = capture

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:       # noqa: BLE001 — a malformed record must
            # never break the compiling thread; counted, not hidden
            self._capture.count_parse_error()
            return
        m = COMPILING_RE.match(msg)
        if m:
            self._capture._emit(m.group(1))


class CompileLogCapture:
    """Refcounted owner of the pxla compile-log handler.

    `subscribe(cb)` installs the handler (and turns jax_log_compiles on)
    on the first subscriber; `unsubscribe(cb)` restores both when the
    last one leaves.  Callbacks run synchronously in the compiling
    thread — the stack still shows who asked for the compile, which is
    what tsdbsan's attribution depends on.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._subscribers: list = []
        self._handler: _CaptureHandler | None = None  # guarded-by: _lock
        self._prev_flag = None  # guarded-by: _lock
        # unparsable log records (diagnostic)  # guarded-by: _lock
        self.parse_errors = 0

    def count_parse_error(self) -> None:
        with self._lock:
            self.parse_errors += 1

    def subscribe(self, callback) -> None:
        import jax
        with self._lock:
            if self._handler is None:
                # all fallible work BEFORE the first state write: a
                # raise after `_prev_flag` was set but before
                # `_handler` would make the next subscribe() re-save
                # the already-overridden flag, so unsubscribe() could
                # never restore the user's original setting
                handler = _CaptureHandler(self)
                prev = jax.config.jax_log_compiles
                jax.config.update("jax_log_compiles", True)
                self._prev_flag = prev
                self._handler = handler
                logging.getLogger(PXLA_LOGGER).addHandler(handler)
            # registering the callback is the commit point: a failed
            # install must not leave a subscriber the caller never got
            # a working subscription for (it would pin the flag
            # override past the last real unsubscribe)
            self._subscribers.append(callback)

    def unsubscribe(self, callback) -> None:
        import jax
        with self._lock:
            try:
                self._subscribers.remove(callback)
            except ValueError:
                pass
            if not self._subscribers and self._handler is not None:
                logging.getLogger(PXLA_LOGGER).removeHandler(self._handler)
                self._handler = None
                if self._prev_flag is not None:
                    jax.config.update("jax_log_compiles", self._prev_flag)
                self._prev_flag = None

    def _emit(self, kernel: str) -> None:
        with self._lock:
            subs = list(self._subscribers)
        for cb in subs:
            cb(kernel)


compile_capture = CompileLogCapture()


# --------------------------------------------------------------------- #
# Per-kernel compile counters (the profiler's subscriber)               #
# --------------------------------------------------------------------- #

class _CompileCounter:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._refs = 0
        self.counts: dict[str, int] = {}  # guarded-by: _lock

    def start(self) -> None:
        with self._lock:
            self._refs += 1
            if self._refs > 1:
                return
        # global-install: unsubscribe paired-with: stop
        compile_capture.subscribe(self._on_compile)

    def stop(self) -> None:
        with self._lock:
            if self._refs == 0:
                return
            self._refs -= 1
            if self._refs:
                return
        compile_capture.unsubscribe(self._on_compile)

    def _on_compile(self, kernel: str) -> None:
        with self._lock:
            self.counts[kernel] = self.counts.get(kernel, 0) + 1
        REGISTRY.counter(
            "tsd.jax.compiles",
            "XLA compilations per jitted kernel").labels(
                kernel=kernel).inc()

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return dict(self.counts)


_COUNTER = _CompileCounter()


def start_compile_counting() -> None:
    """Arm per-kernel compile counting (refcounted; the daemon arms it
    when tsd.trace.enable is on)."""
    _COUNTER.start()


def stop_compile_counting() -> None:
    _COUNTER.stop()


def compile_counts() -> dict[str, int]:
    return _COUNTER.snapshot()


# --------------------------------------------------------------------- #
# Device-cache gauges                                                   #
# --------------------------------------------------------------------- #

def update_device_gauges(tsdb) -> None:
    """Mirror the device cache's hit/miss/build/eviction tallies into
    registry gauges.

    For EMBEDDERS exporting REGISTRY.prometheus_text() directly without
    a TSD stats walk.  The daemon's /api/stats/prometheus does NOT call
    this: its extra_records already carry the same values host-tagged,
    and registering them here would shadow that richer labeling."""
    cache = getattr(tsdb, "device_cache", None)
    if cache is None:
        return
    for name, value in cache.collect_stats().items():
        # forwarder: the names are the device cache's collect_stats()
        # keys (tsd.query.device_cache.*), declared in METRICS_SCHEMA
        # and walked, not minted  # tsdblint: disable=metrics-dynamic-name
        REGISTRY.gauge(name, "Device series cache (HBM) state").set(value)


# --------------------------------------------------------------------- #
# Costmodel predicted-vs-actual                                         #
# --------------------------------------------------------------------- #

SEGMENT_RING = 256

_seg_lock = threading.Lock()
# guarded-by: _seg_lock
_segments: deque = deque(maxlen=SEGMENT_RING)


def segment_decisions(platform: str, s: int, n: int, w: int, g: int,
                      ds_function: str | None,
                      aggregator: str | None = None) -> dict[str, dict]:
    """The kernel strategy decisions one grouped dispatch of shape
    [s series, n points] -> [w windows, g groups] makes, per kernel
    axis — recomputed through the SAME `_effective_*` choosers the
    kernels consult at trace time, so the report cannot drift from the
    dispatched modes.  Keys: 'search', 'scan' OR 'extreme' (by the
    DOWNSAMPLE function — it picks the windowed-reduce kernel),
    'group'; values are decision reports (chosen mode, per-candidate
    predicted ms, source — see downsample.search_decision).

    The group axis's extremes flag comes from the CROSS-SERIES
    `aggregator` — that is what moment_group_reduce keys its kernel
    (and the matmul candidacy) on; a `max:10s-avg:` query downsamples
    with the scan path but group-reduces as an extreme.  When the
    aggregator is unknown (offline recomputation from a bare shape)
    the downsample function is the fallback."""
    from opentsdb_tpu.ops import downsample as ds
    from opentsdb_tpu.ops import group_agg as ga
    s = max(int(s), 1)
    n = max(int(n), 1)
    w = max(int(w), 1)
    g = max(int(g), 1)
    e = w + 1
    extremes = ds_function in ("min", "max", "mimmin", "mimmax")
    group_extremes = (aggregator in ("min", "max", "mimmin", "mimmax")
                      if aggregator is not None else extremes)
    out = {"search": ds.search_decision(s, n, e, platform)}
    if extremes:
        out["extreme"] = ds.extreme_decision(n, w, platform)
    else:
        out["scan"] = ds.scan_decision(s, n, e, platform)
    out["group"] = ga.group_decision(s, w, g, platform,
                                     extremes=group_extremes)
    return out


def segment_features(platform: str, s: int, n: int, w: int, g: int,
                     has_rate: bool,
                     decisions: dict[str, dict]) -> dict[str, float]:
    """The per-unit-cost feature vector of one dispatch under its CHOSEN
    modes: unit counts per costmodel term, summed across the pipeline
    stages.  `dot(features, costmodel.costs(platform))` is the
    dispatch's predicted seconds; the fitter regresses measured device
    seconds onto exactly these vectors (ops/calibrate.py)."""
    from opentsdb_tpu.ops import costmodel as cm
    s = max(int(s), 1)
    n = max(int(n), 1)
    w = max(int(w), 1)
    g = max(int(g), 1)
    e = w + 1
    features: dict[str, float] = {}

    def add(fv: dict[str, float]) -> None:
        for term, units in fv.items():
            features[term] = features.get(term, 0.0) + units

    add(cm.features_search(decisions["search"]["mode"], s, n, e))
    if "extreme" in decisions:
        add(cm.features_extreme(decisions["extreme"]["mode"], s, n, e))
    else:
        add(cm.features_scan(decisions["scan"]["mode"], s, n, e))
    add(cm.features_group(decisions["group"]["mode"], s, w, g))
    # rate + final aggregate: elementwise passes over the [*, W] grids
    add({"elem_f64": float(g * w + (s * w if has_rate else 0))})
    return features


def stage_breakdown(platform: str, s: int, n: int, w: int, g: int,
                    ds_function: str | None, has_rate: bool,
                    decisions: dict[str, dict] | None = None
                    ) -> dict[str, float]:
    """Predicted seconds per logical pipeline stage for one grouped
    dispatch, using the calibrated costmodel under the modes the
    kernels actually chose (`decisions`; recomputed here when absent).
    Approximate by design — this is the PREDICTED side of the
    predicted-vs-actual ledger, not a timer."""
    from opentsdb_tpu.ops import costmodel as cm
    s = max(int(s), 1)
    n = max(int(n), 1)
    w = max(int(w), 1)
    g = max(int(g), 1)
    e = w + 1
    if decisions is None:
        decisions = segment_decisions(platform, s, n, w, g, ds_function)
    elem = cm.costs(platform)["elem_f64"]
    out: dict[str, float] = {}
    search = cm.predict_search(decisions["search"]["mode"], s, n, e,
                               platform)
    if "extreme" in decisions:
        reduce_cost = cm.predict_extreme(decisions["extreme"]["mode"],
                                         s, n, e, platform)
    else:
        reduce_cost = cm.predict_scan(decisions["scan"]["mode"],
                                      s, n, e, platform)
    out["downsample"] = search + reduce_cost
    if has_rate:
        out["rate"] = s * w * elem
    out["groupby"] = cm.predict_group(decisions["group"]["mode"],
                                      s, w, g, platform)
    out["aggregate"] = g * w * elem
    return out


def record_segment(kind: str, s: int, n: int, w: int, g: int,
                   predicted_s: float, actual_ms: float,
                   platform: str | None = None,
                   modes: dict[str, str] | None = None,
                   features: dict[str, float] | None = None,
                   aggregator: str | None = None) -> None:
    """One executed query segment's predicted-vs-actual device cost.
    Lands in the in-process ring (`segments()`) and the registry
    running totals; the ring is the calibration corpus.  Entries
    carrying `platform` + `features` (the planner always sends both)
    are FITTABLE: ops/calibrate.py regresses actualMs onto the feature
    vector to re-solve the per-unit constants from live traffic."""
    entry = {
        "kind": kind, "series": int(s), "points": int(n),
        "windows": int(w), "groups": int(g),
        "predictedMs": round(predicted_s * 1e3, 4),
        "actualMs": round(actual_ms, 4),
    }
    if platform is not None:
        entry["platform"] = platform
    if aggregator is not None:
        # the group axis's extremes flag keys on this — the explorer
        # needs it to recompute the entry's candidate sets faithfully
        entry["aggregator"] = aggregator
    if modes is not None:
        entry["modes"] = dict(modes)
    if features is not None:
        entry["features"] = {t: float(u) for t, u in features.items()}
    with _seg_lock:
        _segments.append(entry)
    REGISTRY.counter(
        "tsd.costmodel.segments",
        "Query segments with predicted-vs-actual accounting").labels(
            kind=kind).inc()
    REGISTRY.counter(
        "tsd.costmodel.predicted_ms",
        "Costmodel-predicted device milliseconds, summed").labels(
            kind=kind).inc(predicted_s * 1e3)
    REGISTRY.counter(
        "tsd.costmodel.actual_ms",
        "Measured device milliseconds, summed").labels(
            kind=kind).inc(actual_ms)


def segments() -> list[dict]:
    """The predicted-vs-actual ring, oldest first."""
    with _seg_lock:
        return list(_segments)


def clear_segments() -> None:
    with _seg_lock:
        _segments.clear()
