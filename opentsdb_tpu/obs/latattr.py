"""Always-on latency attribution: phase stamps + keyed profiles.

Tracing (obs/trace.py) answers "what is THIS query doing" for requests
that opted in; the flight recorder retains discrete events.  Neither
retains *aggregate* phase evidence — after the fact, nothing says where
the serving tier's milliseconds go at p50 vs p99.  This module does,
for EVERY request, tracing on or off:

* ``PhaseStamps`` — a per-request recorder the RPC layer attaches to
  every HTTP request.  Producers along the serving path call
  ``latattr.mark("plan")`` at phase boundaries; each mark attributes
  the monotonic time since the previous mark to that phase.  A mark is
  two perf_counter reads and a dict add — no locks, no registry, no
  allocation beyond the first mark of a phase — so the always-on cost
  stays under the tests/test_latattr.py overhead pin.

* ``LatencyAttribution`` — the aggregation engine.  Finished stamps
  fold into bounded streaming per-phase ``LogHistogram``s keyed by
  (route arm, plan fingerprint, clamped tenant), with exemplar trace
  ids linking tail buckets to retained slow-query captures
  (/api/diag/slow).  Served at ``GET /api/diag/latency`` with
  ``?since=`` incremental polling and ``?fingerprint=``/``?tenant=``
  filters (tsd/admin_rpcs.py).

The phase set is FIXED — every request reports the full ordered tuple
exactly once, with unexercised phases zero-filled — so two captures
diff phase-by-phase without key reconciliation (tools/latency_report.py
builds the "where did the milliseconds move" table from exactly this
property).

Attribution model: time between two marks belongs to the LATER mark's
phase, and repeated marks accumulate (a multi-segment query folds every
segment's dispatch into one "dispatch" figure).  The trailing "flush"
mark in RpcManager.handle_http absorbs the unstamped handler tail
(response buffering, envelope metrics) — for routes that stamp nothing
(diag, stats), the whole handler lands there.  For batched dispatches
the rendezvous wait includes the leader's shared dispatch, so
"batch_rendezvous" carries the batching cost and the member's own
"dispatch" delta is ~0.
"""

from __future__ import annotations

import threading
import time

from opentsdb_tpu.obs.histogram import LogHistogram
from opentsdb_tpu.obs.registry import REGISTRY

# The fixed request phases, in serving order.  parse: request decode +
# query validation.  admission_wait: the admission gate (queueing).
# plan: series resolution + plan decision.  batch_rendezvous: the
# cross-request dispatch batcher (zero when unbatched).  dispatch:
# device dispatch + host compute.  device_wait: device->host result
# extraction.  serialize: response formatting.  flush: the handler
# tail after serialization (reply buffering, envelope metrics).
PHASES = ("parse", "admission_wait", "plan", "batch_rendezvous",
          "dispatch", "device_wait", "serialize", "flush")

# Profile-table overflow sentinel: once tsd.latattr.max_profiles
# distinct (route, fingerprint, tenant) keys exist, further keys fold
# here — the table is bounded no matter what fingerprints the query
# mix mints.
OVERFLOW_KEY = ("overflow", "-", "-")


class PhaseStamps:
    """Per-request phase recorder.  Owned and touched by the request's
    handler thread only (the batcher's rendezvous and the admission
    wait both block that same thread), so no lock."""

    __slots__ = ("t0", "_prev", "deltas", "phase", "route",
                 "fingerprint", "tenant", "trace_id")

    def __init__(self, trace_id: str | None = None):
        now = time.perf_counter()
        self.t0 = now
        self._prev = now
        self.deltas: dict[str, float] = {}      # phase -> seconds
        self.phase = "recv"                     # last completed mark
        self.route = "other"
        self.fingerprint: str | None = None     # set by the planner
        self.tenant: str | None = None          # set by admission
        self.trace_id = trace_id

    def mark(self, phase: str) -> None:
        """Attribute time since the previous mark to ``phase``."""
        now = time.perf_counter()
        self.deltas[phase] = (self.deltas.get(phase, 0.0)
                              + (now - self._prev))
        self._prev = now
        self.phase = phase

    def phase_ms(self) -> dict[str, float]:
        """The full ordered phase set in milliseconds, zero-filled."""
        return {p: self.deltas.get(p, 0.0) * 1e3 for p in PHASES}

    def total_ms(self) -> float:
        return (self._prev - self.t0) * 1e3


# --------------------------------------------------------------------- #
# Ambient stamps: one per handler thread (mirrors obs/trace.py)         #
# --------------------------------------------------------------------- #

_tls = threading.local()


def activate(stamps: PhaseStamps) -> None:
    _tls.stamps = stamps


def deactivate() -> None:
    _tls.stamps = None


def active() -> PhaseStamps | None:
    return getattr(_tls, "stamps", None)


def mark(phase: str) -> None:
    """Phase boundary in the ambient request; free when none active."""
    st = getattr(_tls, "stamps", None)
    if st is not None:
        st.mark(phase)


def set_fingerprint(fingerprint: str) -> None:
    st = getattr(_tls, "stamps", None)
    if st is not None and st.fingerprint is None:
        # first plan decision wins: a multi-segment query keys its
        # profile by the segment that planned first
        st.fingerprint = fingerprint


def set_tenant(tenant: str) -> None:
    st = getattr(_tls, "stamps", None)
    if st is not None:
        st.tenant = tenant


def phase_in_flight() -> str | None:
    """The last completed phase of the ambient request, for the flight
    recorder's events ("recv" before any mark; None outside one)."""
    st = getattr(_tls, "stamps", None)
    return st.phase if st is not None else None


class _Profile:
    """One (route, fingerprint, tenant) key's streaming summary."""

    __slots__ = ("key", "count", "last_seq", "hists")

    def __init__(self, key: tuple[str, str, str]):
        self.key = key
        self.count = 0
        self.last_seq = 0
        self.hists = {p: LogHistogram() for p in PHASES}

    def to_json(self) -> dict:
        route, fingerprint, tenant = self.key
        phases: dict[str, dict] = {}
        exemplars: dict[str, list] = {}
        for p in PHASES:
            h = self.hists[p]
            _counts, count, total = h.snapshot()
            phases[p] = {"count": count, "totalMs": total,
                         "p50Ms": _finite(h.quantile(0.5)),
                         "p99Ms": _finite(h.quantile(0.99))}
            tail = [{"traceId": label, "ms": value}
                    for _bound, label, value in h.exemplar_entries()]
            if tail:
                # the tail-most exemplars are the diagnostic ones
                exemplars[p] = tail[-3:]
        out = {"route": route, "fingerprint": fingerprint,
               "tenant": tenant, "count": self.count,
               "lastSeq": self.last_seq, "phases": phases}
        if exemplars:
            out["exemplars"] = exemplars
        return out


def _finite(value: float) -> float:
    return value if value == value else 0.0      # NaN (empty) -> 0


class LatencyAttribution:
    """Folds finished PhaseStamps into bounded keyed profiles plus a
    global per-phase summary, and serves both as one JSON report."""

    def __init__(self, config):
        self.max_profiles = max(
            config.get_int("tsd.latattr.max_profiles"), 1)
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._profiles: dict[tuple[str, str, str], _Profile] = {}
        self._seq = 0          # guarded-by: _lock
        self._requests = 0     # guarded-by: _lock
        self._overflow = 0     # guarded-by: _lock
        # cumulative per-phase milliseconds — the health engine's
        # phase-share window deltas read this  # guarded-by: _lock
        self._phase_total_ms = {p: 0.0 for p in PHASES}
        # global per-phase histograms (LogHistogram locks itself)
        self._overall = {p: LogHistogram() for p in PHASES}
        self._requests_cell = REGISTRY.counter(
            "tsd.latattr.requests",
            "Requests folded into the latency-attribution profiles")
        self._overflow_cell = REGISTRY.counter(
            "tsd.latattr.profile_overflow",
            "Requests folded into the overflow profile because "
            "tsd.latattr.max_profiles distinct keys already exist")
        self._profiles_gauge = REGISTRY.gauge(
            "tsd.latattr.profiles",
            "Distinct (route, fingerprint, tenant) profiles live")
        phase_fam = REGISTRY.counter(
            "tsd.latattr.phase_ms",
            "Cumulative milliseconds attributed to each request phase")
        self._phase_cells = {p: phase_fam.labels(phase=p)
                             for p in PHASES}

    def observe(self, stamps: PhaseStamps) -> None:
        """Fold one finished request.  Called by RpcManager.handle_http
        after the trailing flush mark, on the handler thread."""
        deltas = stamps.phase_ms()
        key = (stamps.route, stamps.fingerprint or "-",
               stamps.tenant or "default")
        overflowed = False
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._requests += 1
            profile = self._profiles.get(key)
            if profile is None:
                if len(self._profiles) >= self.max_profiles \
                        and key != OVERFLOW_KEY:
                    overflowed = True
                    self._overflow += 1
                    key = OVERFLOW_KEY
                    profile = self._profiles.get(key)
                if profile is None:
                    profile = _Profile(key)
                    self._profiles[key] = profile
            profile.count += 1
            profile.last_seq = seq
            for p in PHASES:
                self._phase_total_ms[p] += deltas[p]
            live = len(self._profiles)
        exemplar = stamps.trace_id
        for p in PHASES:
            profile.hists[p].observe(deltas[p], exemplar=exemplar)
            self._overall[p].observe(deltas[p])
            self._phase_cells[p].inc(deltas[p])
        self._requests_cell.inc()
        if overflowed:
            self._overflow_cell.inc()
        self._profiles_gauge.set(live)

    def phase_totals(self) -> dict:
        """Cumulative per-phase ms + request count, for the health
        engine's windowed phase-share invariant."""
        with self._lock:
            out = dict(self._phase_total_ms)
            out["requests"] = float(self._requests)
            return out

    def report(self, since: int = 0, fingerprint: str | None = None,
               tenant: str | None = None) -> dict:
        """The /api/diag/latency payload.  ``since`` keeps only
        profiles touched after that sequence number (poll with the
        last ``seq`` you saw); the filters match profile keys exactly.
        Histograms are cumulative since daemon start — differential
        views belong to tools/latency_report.py."""
        with self._lock:
            seq = self._seq
            requests = self._requests
            overflow = self._overflow
            profiles = list(self._profiles.values())
        selected = []
        for profile in profiles:
            _route, key_fp, key_tenant = profile.key
            if profile.last_seq <= since:
                continue
            if fingerprint is not None and key_fp != fingerprint:
                continue
            if tenant is not None and key_tenant != tenant:
                continue
            selected.append(profile)
        selected.sort(key=lambda pr: (-pr.count, pr.key))
        overall: dict[str, dict] = {}
        for p in PHASES:
            h = self._overall[p]
            _counts, count, total = h.snapshot()
            overall[p] = {"count": count, "totalMs": total,
                          "p50Ms": _finite(h.quantile(0.5)),
                          "p99Ms": _finite(h.quantile(0.99))}
        return {"seq": seq, "requests": requests,
                "phases": list(PHASES),
                "profileOverflow": overflow,
                "overall": overall,
                "profiles": [pr.to_json() for pr in selected]}

    def stats_hook(self, collector) -> None:
        """tsdb.stats_hooks entry: fold summary gauges into the
        standard stats walk (self-report + /api/stats)."""
        with self._lock:
            requests = self._requests
            live = len(self._profiles)
            totals = dict(self._phase_total_ms)
        collector.record("latattr.observed", requests)
        collector.record("latattr.live_profiles", live)
        for p in PHASES:
            collector.record("latattr.ms", totals[p], "phase=%s" % p)
