"""Thread-safe metrics registry + Prometheus text exposition.

The push-style StatsCollector (stats/collector.py) walks subsystems on
demand; this registry is the PULL-style complement for code that wants
to instrument itself at the event site — counters, gauges, and
log-bucketed latency histograms (obs/histogram.py), labeled, with one
global `REGISTRY` the way the Prometheus client libraries work.

`/api/stats/prometheus` (tsd/admin_rpcs.py) renders the registry in the
text exposition format (version 0.0.4) and folds in the StatsCollector
records from the same walk `/api/stats` serves — so device-cache,
breaker, compaction, and every other existing counter is scrapeable
without re-instrumenting its source.
"""

from __future__ import annotations

import math
import re
import threading

from opentsdb_tpu.obs.histogram import LogHistogram

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_BAD = re.compile(r"[^a-zA-Z0-9_]")

KINDS = ("counter", "gauge", "histogram")


def sanitize_name(name: str) -> str:
    """Metric name -> Prometheus name (dots and dashes to underscores)."""
    out = _NAME_BAD.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def sanitize_label(name: str) -> str:
    out = _LABEL_BAD.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def escape_label_value(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt(value: float) -> str:
    if value != value:
        return "NaN"
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    if isinstance(value, float) and value.is_integer() \
            and abs(value) < 2 ** 53:
        return str(int(value))
    return repr(value) if isinstance(value, float) else str(value)


def _label_str(labels: tuple[tuple[str, str], ...],
               extra: str = "") -> str:
    parts = ['%s="%s"' % (sanitize_label(k), escape_label_value(v))
             for k, v in labels]
    if extra:
        parts.append(extra)
    return "{%s}" % ",".join(parts) if parts else ""


class _Value:
    """One labeled counter/gauge cell."""

    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0  # guarded-by: _lock

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def get(self) -> float:
        with self._lock:
            return self.value


class Family:
    """One metric family: name + kind + help + labeled children."""

    def __init__(self, name: str, kind: str, help_text: str = "",
                 **hist_kw):
        if kind not in KINDS:
            raise ValueError("unknown metric kind: %r" % kind)
        self.name = name
        self.kind = kind
        self.help = help_text
        self._hist_kw = hist_kw
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._children: dict[tuple[tuple[str, str], ...], object] = {}

    def labels(self, **labels):
        """The child cell for a label set (created on first use)."""
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = (LogHistogram(**self._hist_kw)
                         if self.kind == "histogram" else _Value())
                self._children[key] = child
            return child

    # bare-cell conveniences (the no-label common case)
    def inc(self, n: float = 1.0) -> None:
        self.labels().inc(n)

    def set(self, v: float) -> None:
        self.labels().set(v)

    def observe(self, v: float, exemplar: str | None = None) -> None:
        self.labels().observe(v, exemplar=exemplar)

    def children(self) -> list[tuple[tuple[tuple[str, str], ...], object]]:
        with self._lock:
            return sorted(self._children.items())


class MetricsRegistry:
    """Name -> Family, with kind conflicts rejected loudly."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, Family] = {}  # guarded-by: _lock

    def _family(self, name: str, kind: str, help_text: str,
                **hist_kw) -> Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = Family(name, kind, help_text, **hist_kw)
                self._families[name] = fam
            elif fam.kind != kind:
                raise ValueError(
                    "metric %s already registered as a %s (asked for %s)"
                    % (name, fam.kind, kind))
            return fam

    def counter(self, name: str, help_text: str = "") -> Family:
        return self._family(name, "counter", help_text)

    def gauge(self, name: str, help_text: str = "") -> Family:
        return self._family(name, "gauge", help_text)

    def histogram(self, name: str, help_text: str = "",
                  **hist_kw) -> Family:
        return self._family(name, "histogram", help_text, **hist_kw)

    def families(self) -> list[Family]:
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    def reset(self) -> None:
        """Drop every family (test isolation)."""
        with self._lock:
            self._families.clear()

    # -- exposition ---------------------------------------------------- #

    def prometheus_text(self, extra_records: list[dict] | None = None,
                        hist_buckets: int = 24,
                        exemplars: bool = False) -> str:
        """The full scrape body: every registry family, then every
        StatsCollector record (as gauges) whose name does not collide
        with a registry family.  ``exemplars`` additionally emits
        OpenMetrics-style exemplar COMMENT lines per histogram bucket
        (`# exemplar: <bucket> {trace_id="..."} <value>`) — comments,
        so the text stays exposition-format-0.0.4 parseable."""
        lines: list[str] = []
        emitted: set[str] = set()
        for fam in self.families():
            pname = sanitize_name(fam.name)
            if pname in emitted:
                continue
            emitted.add(pname)
            sample = pname + ("_total" if fam.kind == "counter" else "")
            if fam.help:
                lines.append("# HELP %s %s"
                             % (sample, fam.help.replace("\n", " ")))
            lines.append("# TYPE %s %s" % (sample, fam.kind))
            for labels, child in fam.children():
                if fam.kind == "histogram":
                    self._render_histogram(lines, pname, labels, child,
                                           hist_buckets,
                                           exemplars=exemplars)
                else:
                    lines.append("%s%s %s" % (sample, _label_str(labels),
                                              _fmt(child.get())))
        for name, samples in _group_records(extra_records or []):
            pname = sanitize_name(name)
            if pname in emitted:
                continue
            emitted.add(pname)
            lines.append("# TYPE %s gauge" % pname)
            for labels, value in samples:
                lines.append("%s%s %s" % (pname, _label_str(labels),
                                          _fmt(value)))
        return "\n".join(lines) + "\n"

    @staticmethod
    def _render_histogram(lines: list[str], pname: str,
                          labels: tuple[tuple[str, str], ...],
                          hist: LogHistogram, max_buckets: int,
                          exemplars: bool = False) -> None:
        _counts, count, total = hist.snapshot()
        for bound, cum in hist.cumulative(max_buckets):
            # 6 significant digits: bounds are exact powers of the
            # growth factor, whose float repr carries ulp noise
            le = "+Inf" if bound == math.inf else "%.6g" % bound
            lines.append("%s_bucket%s %d"
                         % (pname, _label_str(labels, 'le="%s"' % le),
                            cum))
        if exemplars:
            # OpenMetrics-style exemplars as 0.0.4-safe COMMENT lines:
            # a strict text-format parser skips anything starting '#',
            # while an operator (or the scrape-side regex in our own
            # tests) can join a tail bucket to its flight-recorder
            # trace id
            for bound, label, value in hist.exemplar_entries(max_buckets):
                le = "+Inf" if bound == math.inf else "%.6g" % bound
                lines.append(
                    '# exemplar: %s_bucket%s {trace_id="%s"} %s'
                    % (pname, _label_str(labels, 'le="%s"' % le),
                       escape_label_value(label), _fmt(value)))
        lines.append("%s_sum%s %s" % (pname, _label_str(labels),
                                      _fmt(total)))
        lines.append("%s_count%s %d" % (pname, _label_str(labels), count))


def _group_records(records: list[dict]
                   ) -> list[tuple[str, list[tuple[tuple, float]]]]:
    """StatsCollector records -> [(metric, [(labels, value)])] with
    duplicate (metric, labels) keeping the LAST value recorded."""
    grouped: dict[str, dict[tuple, float]] = {}
    for r in records:
        labels = tuple(sorted((k, str(v))
                              for k, v in (r.get("tags") or {}).items()))
        grouped.setdefault(r["metric"], {})[labels] = float(r["value"])
    return [(name, sorted(samples.items()))
            for name, samples in sorted(grouped.items())]


REGISTRY = MetricsRegistry()
