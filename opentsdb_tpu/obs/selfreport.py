"""Self-report loop: the TSD ingests its own tsd.* metrics.

The dogfooding design the reference's StatsCollector was built for —
one collector walk (the SAME walk /api/stats serves: TSDB counters,
cluster breakers, rollup lanes, plus every registered stats hook) is
written back into the local memstore through the normal ingest path, so
a dashboard can query the daemon about itself with ordinary /api/query
downsample/rate semantics.  tsd.stats.interval (seconds) gates the
cadence from the maintenance thread; 0 (the default) disables it.

Because the walk IS the stats-hook registry, the health engine's
verdicts (tsd.health.status per subsystem, obs/health.py) and the
flight recorder's per-tenant demand counters (tsd.diag.tenant.demand,
obs/flightrec.py) land here too: the TSD can query its own health and
demand HISTORY — "when did admission start degrading" is an ordinary
downsample query over tsd.health.status.  Read-only daemons still skip
the write (the ro gate below), exactly as before.

Metric UIDs auto-create for the tsd.* namespace even when
tsd.core.auto_create_metrics is off: the operator's ingest policy
governs CLIENT data, and a stats loop that silently dropped every
record under the default policy would be a dead feature.
"""

from __future__ import annotations

import logging
import re

from opentsdb_tpu.stats import StatsCollector

LOG = logging.getLogger("tsd.selfreport")

# the UID charset (uid.validate_uid_name / Tags.validateString):
# anything else in a stats tag (the ':' in a peer host:port, most
# commonly) maps to '_' so the record still lands
_UID_ILLEGAL = re.compile(r"[^-_./a-zA-Z0-9À-ヿ]")


def _uid_safe(name: str) -> str:
    return _UID_ILLEGAL.sub("_", name) or "_"


def collect_all(tsdb) -> StatsCollector:
    """The full stats walk: every record /api/stats (and the telnet
    `stats` command) would serve.  Shared by StatsRpc and the
    self-report loop so the two surfaces can never diverge."""
    collector = StatsCollector("tsd", use_host_tag=True)
    collector.record_map(tsdb.collect_stats())
    from opentsdb_tpu.tsd.cluster import collect_stats as cluster_stats
    cluster_stats(tsdb, collector)
    if tsdb.rollup_store is not None:
        collector.record_map(tsdb.rollup_store.collect_stats())
    for hook in getattr(tsdb, "stats_hooks", {}).values():
        hook(collector)
    return collector


def self_report(tsdb) -> int:
    """One pass: collect and ingest.  Returns datapoints written (0 in
    read-only mode — a ro daemon must not write, even about itself)."""
    if tsdb.mode == "ro":
        return 0
    collector = collect_all(tsdb)
    written = 0
    for record in collector.records:
        metric = _uid_safe(record["metric"])
        tags = {_uid_safe(k): _uid_safe(str(v))
                for k, v in record["tags"].items()}
        # pre-create EVERY UID (metric, tagk, tagv) so the
        # auto_create_* gates — client-data policy — never reject the
        # daemon's own stats; cached dict hits after the first pass
        tsdb.metrics.get_or_create_id(metric)
        for k, v in tags.items():
            tsdb.tag_names.get_or_create_id(k)
            tsdb.tag_values.get_or_create_id(v)
        tsdb.add_point(metric, record["timestamp"], record["value"],
                       tags)
        written += 1
    return written
