"""Span-tree tracer for query serving.

One `Trace` per request (started by RpcManager.handle_http when
`tsd.trace.enable` is on), a stack of nested `Span`s manipulated by the
request's handler thread, and explicit `child()` spans for work that
hops threads (the cluster fan-out pool).  The planner and RPC layers
annotate stages through the AMBIENT trace (`stage()` below), which
no-ops at near-zero cost when no trace is active — library callers of
QueryRunner.run() and the sanitizer's steady-state loops see no
behavior change.

Span times:

  * ``wallMs``   start-to-finish wall time of the stage.
  * ``deviceMs`` time spent waiting on device results inside the stage
    (`device_wait()`: a block_until_ready at the stage boundary,
    enabled by ``tsd.trace.device_time``).  JAX dispatch is
    asynchronous, so this is queue+execute time for work the stage
    enqueued — the honest observable without per-kernel device
    profiling.  Stage children of a fused dispatch carry device time
    APPORTIONED from the measured total by the costmodel's per-stage
    predictions and say so (``estimated`` tag) — XLA fuses
    downsample/rate/groupby/aggregate into one kernel, so per-stage
    device truth does not exist at runtime.

This module is a registered tsdbsan SANCTIONED_SITES entry: the
device_wait sync is the trace path's one deliberate device->host
rendezvous, and it must never count as a hidden hot-path sync.

Trace ids propagate across the cluster fan-out via the
``X-TSDB-Trace-Id`` header (tsd/cluster.py attaches it; handle_http
adopts an incoming one), so one clustered query is one trace id across
every TSD that served a piece of it.
"""

from __future__ import annotations

import os
import struct
import threading
import time
from contextlib import contextmanager

TRACE_HEADER = "x-tsdb-trace-id"


def _new_trace_id() -> str:
    return struct.unpack("<Q", os.urandom(8))[0].__format__("016x")


class Span:
    """One named stage; a node in the trace tree."""

    __slots__ = ("name", "tags", "children", "start", "wall_ms",
                 "device_ms")

    def __init__(self, name: str, **tags):
        self.name = name
        self.tags = tags
        self.children: list[Span] = []
        self.start = time.perf_counter()
        self.wall_ms: float | None = None
        self.device_ms = 0.0

    def finish(self) -> None:
        if self.wall_ms is None:
            self.wall_ms = (time.perf_counter() - self.start) * 1e3

    def child(self, name: str, **tags) -> "Span":
        """A new child span.  Create it on the thread that OWNS this
        span (children list is not locked); the child itself may then
        be finished/annotated by another thread."""
        sp = Span(name, **tags)
        self.children.append(sp)
        return sp

    def to_json(self) -> dict:
        wall = self.wall_ms
        if wall is None:        # still running: elapsed so far
            wall = (time.perf_counter() - self.start) * 1e3
        out: dict = {
            "name": self.name,
            "wallMs": round(wall, 3),
            "deviceMs": round(self.device_ms, 3),
        }
        if self.tags:
            # a stats scrape can render while another thread (the
            # handler, or a straggling peer-fetch pool thread) is still
            # inserting tags; item writes are atomic under the GIL but
            # dict ITERATION mid-insert raises — retry the copy instead
            # of surfacing a 500 from the stats endpoint
            for _ in range(4):
                try:
                    out["tags"] = dict(self.tags)
                    break
                except RuntimeError:
                    continue
        if self.children:
            out["spans"] = [c.to_json() for c in self.children]
        return out


class Trace:
    """One request's span tree + the id that names it across hosts."""

    def __init__(self, name: str, trace_id: str | None = None,
                 device_time: bool = True):
        self.trace_id = trace_id or _new_trace_id()
        self.device_time = device_time
        self.root = Span(name)
        # the span stack of the OWNING thread; cross-thread work uses
        # explicit Span.child() handles instead
        self._stack: list[Span] = [self.root]

    def current(self) -> Span:
        return self._stack[-1]

    @contextmanager
    def span(self, name: str, **tags):
        sp = self.current().child(name, **tags)
        self._stack.append(sp)
        try:
            yield sp
        finally:
            self._stack.pop()
            sp.finish()

    def finish(self) -> None:
        """Close the trace: every still-open span in the tree finishes
        NOW.  The trace outlives its request in the /api/stats/query
        ring, so a span left open by an error path (a 413 raised
        mid-dispatch between begin() and end(), an aborted fan-out)
        must stop accruing elapsed-so-far here — not render a
        forever-climbing wallMs at every later scrape."""
        self._finish_open(self.root)
        del self._stack[1:]

    @staticmethod
    def _finish_open(span: Span) -> None:
        for child in span.children:
            Trace._finish_open(child)
        span.finish()

    def to_json(self) -> dict:
        out = self.root.to_json()
        out["traceId"] = self.trace_id
        return out


# --------------------------------------------------------------------- #
# Ambient trace: one per handler thread                                 #
# --------------------------------------------------------------------- #

_tls = threading.local()


def activate(trace: Trace) -> None:
    _tls.trace = trace


def deactivate() -> None:
    _tls.trace = None


def active() -> Trace | None:
    return getattr(_tls, "trace", None)


@contextmanager
def stage(name: str, **tags):
    """`with stage("scan", kind="raw") as sp:` — a child span of the
    ambient trace's current span, or None (and no cost) untraced."""
    tr = active()
    if tr is None:
        yield None
        return
    with tr.span(name, **tags) as sp:
        yield sp


def annotate(span: Span | None, **tags) -> None:
    if span is not None:
        span.tags.update(tags)


def begin(name: str, **tags) -> Span | None:
    """Non-context-manager stage start for long straight-line sections
    (the planner's dispatch chain).  Pair with `end()`.  An exception
    between the two leaves the span unfinished, which is safe: the
    trace is per-request and to_json renders unfinished spans with
    elapsed-so-far."""
    tr = active()
    if tr is None:
        return None
    sp = tr.current().child(name, **tags)
    tr._stack.append(sp)
    return sp


def end(span: Span | None) -> None:
    tr = active()
    if span is None or tr is None:
        return
    if tr._stack and tr._stack[-1] is span:
        tr._stack.pop()
    span.finish()


def device_wait(span: Span | None, outputs) -> float:
    """Block until `outputs` (a jax array or pytree) are ready,
    attributing the wait to `span` as device time.  Returns the wait in
    ms.  No-ops (0.0) when untraced or device timing is off — the
    dispatch then stays fully asynchronous, exactly as before."""
    tr = active()
    if span is None or tr is None or not tr.device_time:
        return 0.0
    import jax
    t0 = time.perf_counter()
    jax.block_until_ready(outputs)
    dt = (time.perf_counter() - t0) * 1e3
    span.device_ms += dt
    return dt
