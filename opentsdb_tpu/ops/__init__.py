"""JAX/XLA kernels for the query-time numeric pipeline.

This package replaces the reference's per-datapoint iterator stack
(src/core/Aggregators.java, Downsampler.java, RateSpan.java,
AggregationIterator.java) with batched, jit-compiled array kernels:

  aggregators.py  registry + masked cross-series reductions
  downsample.py   windowed segment-reductions over [series, time] batches
  rate.py         first-difference / counter-rate kernels
  union_agg.py    LERP-at-union-timestamps cross-series merge
  percentile.py   sort-based percentile selection (LEGACY/R-3/R-7)
  pipeline.py     fused end-to-end query kernels (jit entry points)

float64/int64 precision is enabled process-wide to match the reference's
Java double/long arithmetic; kernels themselves are dtype-polymorphic so the
TPU fast path can run float32 batches.
"""

import jax

jax.config.update("jax_enable_x64", True)

# Keep the host CPU platform registered next to a restricted accelerator
# platform (JAX_PLATFORMS=tpu/axon): the small-query fast lane places
# sub-threshold dispatches on the host, dodging the accelerator dispatch
# floor.  Must happen before the first backend initialization.
from opentsdb_tpu.ops.hostlane import ensure_cpu_platform  # noqa: E402

ensure_cpu_platform()

from opentsdb_tpu.ops import aggregators  # noqa: E402
from opentsdb_tpu.ops.aggregators import AGGREGATORS, get_agg, agg_names  # noqa: E402

__all__ = ["aggregators", "AGGREGATORS", "get_agg", "agg_names"]
