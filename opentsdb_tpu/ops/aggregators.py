"""Aggregator registry and masked cross-series reduction kernels.

Reference behavior: /root/reference/src/core/Aggregators.java — the named
aggregation functions with their interpolation policies (:38 Interpolation
enum, registry :175-203), and Aggregator.java's runLong/runDouble contracts:
double reductions skip NaN inputs; long reductions use Java integer division
for avg (Aggregators.java:378) and truncate stddev (:522).

The reference reduces with virtual-call iterators, one value at a time; here
each aggregator is a vectorized masked reduction over the series axis of a
[series, time] batch, so a whole group-by bucket reduces in one XLA op.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax.numpy as jnp
from jax import lax

from opentsdb_tpu.ops.percentile import (
    masked_percentile,
    EST_LEGACY,
    EST_R3,
    EST_R7,
)

# Interpolation policies (Aggregators.java:38-44).
LERP = "lerp"
ZIM = "zim"     # zero if missing
MAX_IF_MISSING = "max"
MIN_IF_MISSING = "min"
PREV = "prev"

_F64_MAX = jnp.finfo(jnp.float64).max
_I64_MAX = jnp.iinfo(jnp.int64).max
_I64_MIN = jnp.iinfo(jnp.int64).min


def _where(mask, v, fill):
    return jnp.where(mask, v, jnp.asarray(fill, dtype=v.dtype))


def _valid(values, mask):
    """Participating AND non-NaN, the double-path skip rule."""
    if jnp.issubdtype(values.dtype, jnp.floating):
        return mask & ~jnp.isnan(values)
    return mask


def _nan_if_empty(result, count, dtype):
    if jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
        return jnp.where(count > 0, result, jnp.asarray(jnp.nan, dtype))
    return result


# --- reduction kernels over axis 0 of (values[S, T], mask[S, T]) ---

def _sum(values, mask):
    ok = _valid(values, mask)
    n = ok.sum(axis=0)
    return _nan_if_empty(_where(ok, values, 0).sum(axis=0), n, values.dtype)


def _squaresum(values, mask):
    ok = _valid(values, mask)
    n = ok.sum(axis=0)
    sq = _where(ok, values, 0)
    return _nan_if_empty((sq * sq).sum(axis=0), n, values.dtype)


def _min(values, mask):
    ok = _valid(values, mask)
    n = ok.sum(axis=0)
    if jnp.issubdtype(values.dtype, jnp.floating):
        out = _where(ok, values, jnp.inf).min(axis=0)
    else:
        out = _where(ok, values, _I64_MAX).min(axis=0)
    return _nan_if_empty(out, n, values.dtype)


def _max(values, mask):
    ok = _valid(values, mask)
    n = ok.sum(axis=0)
    if jnp.issubdtype(values.dtype, jnp.floating):
        out = _where(ok, values, -jnp.inf).max(axis=0)
    else:
        out = _where(ok, values, _I64_MIN).max(axis=0)
    return _nan_if_empty(out, n, values.dtype)


def _avg(values, mask):
    ok = _valid(values, mask)
    n = ok.sum(axis=0)
    total = _where(ok, values, 0).sum(axis=0)
    if jnp.issubdtype(values.dtype, jnp.floating):
        return jnp.where(n > 0, total / jnp.maximum(n, 1), jnp.nan)
    # Java long division truncates toward zero (Aggregators.java:378).
    return lax.div(total, jnp.maximum(n, 1).astype(total.dtype))


def _count(values, mask):
    # runDouble counts non-NaN values; runLong counts everything (:620-646).
    return _valid(values, mask).sum(axis=0).astype(
        values.dtype if jnp.issubdtype(values.dtype, jnp.floating)
        else jnp.int64)


def _dev(values, mask):
    """Welford stddev (Aggregators.java:498): sqrt(M2/(n-1)), 0 when n<2."""
    ok = _valid(values, mask)
    n = ok.sum(axis=0)
    vf = values.astype(jnp.float64)
    total = _where(ok, vf, 0).sum(axis=0)
    mean = total / jnp.maximum(n, 1)
    centered = _where(ok, vf - mean, 0)
    m2 = (centered * centered).sum(axis=0)
    var = m2 / jnp.maximum(n - 1, 1)
    out = jnp.where(n >= 2, jnp.sqrt(var), 0.0)
    if jnp.issubdtype(values.dtype, jnp.floating):
        return jnp.where(n > 0, out, jnp.nan)
    return out.astype(values.dtype)  # (long) cast truncation (:522)


def _mult(values, mask):
    ok = _valid(values, mask)
    n = ok.sum(axis=0)
    return _nan_if_empty(_where(ok, values, 1).prod(axis=0), n, values.dtype)


def _first_ordered(values, mask):
    """First participating value in series order (Aggregators.First :810)."""
    ok = _valid(values, mask)
    idx = jnp.argmax(ok, axis=0)
    out = jnp.take_along_axis(values, idx[None, :], axis=0)[0]
    return _nan_if_empty(out, ok.sum(axis=0), values.dtype)


def _last_ordered(values, mask):
    ok = _valid(values, mask)
    s = ok.shape[0]
    rev_idx = jnp.argmax(ok[::-1], axis=0)
    idx = s - 1 - rev_idx
    out = jnp.take_along_axis(values, idx[None, :], axis=0)[0]
    return _nan_if_empty(out, ok.sum(axis=0), values.dtype)


def _diff(values, mask):
    """last - first in iteration order; 0 with a single value (:576-617)."""
    ok = _valid(values, mask)
    n = ok.sum(axis=0)
    first = _first_ordered(values, mask)
    last = _last_ordered(values, mask)
    zero = jnp.asarray(0, values.dtype)
    out = jnp.where(n >= 2, last - first, zero)
    return _nan_if_empty(out, n, values.dtype)


def _median(values, mask):
    """Upper median: sorted[n // 2] (Aggregators.Median :397-431)."""
    ok = _valid(values, mask)
    n = ok.sum(axis=0)
    big = jnp.inf if jnp.issubdtype(values.dtype, jnp.floating) else _I64_MAX
    sorted_vals = jnp.sort(_where(ok, values, big), axis=0)
    idx = jnp.clip(n // 2, 0, values.shape[0] - 1)
    out = jnp.take_along_axis(sorted_vals, idx[None, :], axis=0)[0]
    return _nan_if_empty(out, n, values.dtype)


def _none_agg(values, mask):
    # Pipeline guarantees a single series reaches "none" (QueryRpc enforces it).
    return _first_ordered(values, mask)


# shape: sums[S,W] any, live[S,W] bool -> [S,W] any
def java_moving_average(sums, live, n_window: int, int_mode: bool = False):
    """The MovingAverage evaluation loop, vectorized over the last axis.

    Reference semantics (Aggregators.MovingAverage:709-760): each
    evaluated timestamp pushes its cross-series sum, then the result is
    the average of the PRECEDING `n_window` sums — exclusive of the
    current one, 0 until that window has filled, Java long division in
    the integer lane.  `sums[..., T]` are per-evaluation totals and
    `live[..., T]` marks which slots are real evaluations (grid windows
    with data / unique union timestamps); dead slots neither produce nor
    consume window state, exactly like timestamps the iterator never
    visits.
    """
    shape = sums.shape
    t = shape[-1]
    s2 = sums.reshape(-1, t)
    l2 = live.reshape(-1, t)
    r = s2.shape[0]
    kk = jnp.cumsum(l2.astype(jnp.int64), axis=1)     # live count through t
    zero = jnp.asarray(0, s2.dtype)
    contrib = jnp.where(l2, s2, zero)
    d = jnp.cumsum(contrib, axis=1)
    # dm[row, m] = sum of the first m live contributions.  Every column t
    # with the same kk value carries the same d value (d only moves at
    # live columns), so an unconditional scatter is exact.
    rows = jnp.arange(r)[:, None]
    dm = jnp.zeros((r, t + 1), d.dtype).at[rows, kk].set(d)
    prev = kk - 1                     # live evaluations before column t
    hi = jnp.take_along_axis(dm, jnp.clip(prev, 0, t), axis=1)
    lo = jnp.take_along_axis(dm, jnp.clip(prev - n_window, 0, t), axis=1)
    wsum = hi - lo
    if int_mode and not jnp.issubdtype(s2.dtype, jnp.floating):
        out = lax.div(wsum, jnp.asarray(n_window, wsum.dtype))
    else:
        out = wsum.astype(jnp.float64) / n_window
    filled = l2 & (prev >= n_window)  # conditionMet: window fully behind us
    out = jnp.where(filled, out, jnp.asarray(0, out.dtype))
    return out.reshape(shape[:-1] + (t,))


def moving_average_columns(contrib, participate, live, n_window: int,
                           int_mode: bool = False):
    """Cross-series sum per column, then the Java window loop.

    `live[T]` is the caller's evaluation mask (duplicate union slots
    participate in interpolation but are NOT separate evaluations, so the
    per-column participation cannot stand in for it)."""
    ok = participate & ~jnp.isnan(contrib.astype(jnp.float64))
    zero = jnp.asarray(0, contrib.dtype)
    sums = jnp.where(ok, contrib, zero).sum(axis=0)
    out = java_moving_average(sums, live, n_window, int_mode)
    if jnp.issubdtype(out.dtype, jnp.floating):
        return jnp.where(live, out, jnp.nan)
    return out


DEFAULT_MA_WINDOW = 5


def ma_window(name: str) -> int | None:
    """`movingAverage` family parse: bare name (DEFAULT_MA_WINDOW points)
    or `movingAverage<N>` for a trailing window of N points.  Returns the
    window size, or None when `name` is not a moving average.

    The reference only instantiates MovingAverage through the expression
    layer (ExpressionFactory "movingAverage"; absent from the static
    registry, Aggregators.java:175-203) — registering it here makes the
    same windowed form addressable from `m=` and downsample positions,
    with time-unit windows remaining gexp-only (the reduce signature has
    no timestamps).
    """
    if not name.startswith("movingAverage"):
        return None
    suffix = name[len("movingAverage"):]
    if suffix == "":
        return DEFAULT_MA_WINDOW
    if suffix.isdigit() and int(suffix) > 0:
        return int(suffix)
    return None


def _moving_average_reduce(values, mask, n_window: int):
    # Direct registry form: every column with a participant counts as an
    # evaluation.  The union/grid paths call moving_average_columns with
    # their own live mask instead (duplicate-slot correctness).
    live = _valid(values, mask).any(axis=0)
    int_mode = not jnp.issubdtype(values.dtype, jnp.floating)
    return moving_average_columns(values, mask, live, n_window, int_mode)


def _percentile_agg(values, mask, q, estimation):
    ok = _valid(values, mask)
    out = masked_percentile(values.astype(jnp.float64), ok, q, estimation,
                            axis=0)
    if jnp.issubdtype(values.dtype, jnp.floating):
        return out
    return out.astype(values.dtype)  # (long) cast (Aggregators.java:685)


@dataclass(frozen=True)
class Aggregator:
    """A named aggregation function + its missing-value interpolation policy."""
    name: str
    interpolation: str
    reduce: callable  # (values[S, T], mask[S, T]) -> [T]

    def __repr__(self) -> str:
        return "Aggregator(%s)" % self.name


def _make_registry() -> dict[str, Aggregator]:
    """The static registry (Aggregators.java:175-203) — name-for-name parity.

    MovingAverage (Aggregators.java:709) is deliberately NOT here: the
    reference's static map omits it too (it is stateful and only
    instantiated by the gexp expression layer, ExpressionFactory
    "movingAverage"); ours lives in expression/gexp.py f_moving_average.
    """
    reg = {
        "sum": Aggregator("sum", LERP, _sum),
        "pfsum": Aggregator("pfsum", PREV, _sum),
        "min": Aggregator("min", LERP, _min),
        "max": Aggregator("max", LERP, _max),
        "avg": Aggregator("avg", LERP, _avg),
        "median": Aggregator("median", LERP, _median),
        "none": Aggregator("none", ZIM, _none_agg),
        "mult": Aggregator("mult", LERP, _mult),
        "dev": Aggregator("dev", LERP, _dev),
        "diff": Aggregator("diff", LERP, _diff),
        "count": Aggregator("count", ZIM, _count),
        "zimsum": Aggregator("zimsum", ZIM, _sum),
        "mimmin": Aggregator("mimmin", MAX_IF_MISSING, _min),
        "mimmax": Aggregator("mimmax", MIN_IF_MISSING, _max),
        "first": Aggregator("first", ZIM, _first_ordered),
        "last": Aggregator("last", ZIM, _last_ordered),
        "squareSum": Aggregator("squareSum", ZIM, _squaresum),
        # LERP like the expression layer's instantiation
        # (ExpressionFactory.java movingAverage)
        "movingAverage": Aggregator(
            "movingAverage", LERP,
            partial(_moving_average_reduce, n_window=DEFAULT_MA_WINDOW)),
    }
    percentiles = [99.9, 99.0, 95.0, 90.0, 75.0, 50.0]
    names = ["999", "99", "95", "90", "75", "50"]
    for q, n in zip(percentiles, names):
        reg["p" + n] = Aggregator(
            "p" + n, LERP, partial(_percentile_agg, q=q, estimation=EST_LEGACY))
        reg["ep%sr3" % n] = Aggregator(
            "ep%sr3" % n, LERP, partial(_percentile_agg, q=q, estimation=EST_R3))
        reg["ep%sr7" % n] = Aggregator(
            "ep%sr7" % n, LERP, partial(_percentile_agg, q=q, estimation=EST_R7))
    return reg


AGGREGATORS: dict[str, Aggregator] = _make_registry()

# Dynamically-constructed movingAverage<N> aggregators, cached apart from
# the static registry so /api/aggregators keeps a stable listing.  The
# cache is bounded: query strings are untrusted, and each distinct N also
# seeds fresh jit traces downstream — beyond the cap new windows still
# work, they just construct per call (review r4).
# cache: dynamic-aggs invalidated-by: none
_DYNAMIC: dict[str, Aggregator] = {}
_DYNAMIC_MAX = 128


def get_agg(name: str) -> Aggregator:
    agg = AGGREGATORS.get(name) or _DYNAMIC.get(name)
    if agg is None:
        n = ma_window(name)
        if n is not None:
            agg = Aggregator(name, LERP,
                             partial(_moving_average_reduce, n_window=n))
            if len(_DYNAMIC) < _DYNAMIC_MAX:
                _DYNAMIC[name] = agg
        else:
            raise KeyError("No such aggregator: " + name)
    return agg


def is_valid_agg(name: str) -> bool:
    """Registry membership including the movingAverage<N> family."""
    return name in AGGREGATORS or ma_window(name) is not None


def agg_names() -> list[str]:
    return sorted(AGGREGATORS.keys())
