"""Online costmodel calibration: close the predicted-vs-actual loop.

ops/costmodel.py ranks kernel strategy modes with a LINEAR model —
every prediction is dot(feature vector, per-unit constants) — and
obs/jaxprof.py records each executed query segment's feature vector
(under the modes the kernels actually chose) beside its measured
device time.  This module solves the inverse problem: regress the
measured seconds onto the feature vectors by non-negative least
squares, and install the solution as the costmodel's live override
layer.  A daemon serving traffic thereby converges its `choose_*`
argmins to whatever its own hardware measures — reproducing the
offline chip-A/B winners (BENCH_WINNERS.json) without a bench session,
and beating them on shapes the A/B never visited.  The hash- vs
sort-style group-by crossover this tunes is the one the focused
empirical study measures (PAPERS.md, arXiv:2411.13245); the shared-
aggregation adaptivity mirrors Enthuse (arXiv:2405.18168).

Numerical shape of the fit.  Unit counts span ~10 orders of magnitude
(one gather round vs 3e10 compare cells), so the design matrix is
column-scaled by the CURRENT constants: the solver sees multipliers,
x_j ~ "how wrong is constant j", conditioned near 1.  An intercept
column absorbs the fixed per-dispatch overhead (real on both CPU and
chip) so it cannot corrupt the per-unit terms.  Three guards keep a
noisy batch from destabilizing serving:

  * minimum-sample window — no fit below `min_samples` ring entries,
    and a term must appear in `MIN_TERM_ROWS` entries to move;
  * bounded step — each fit moves a constant by at most a factor of
    `max_step` (multipliers clipped into [1/max_step, max_step]), so
    convergence is geometric and a wild batch is bounded;
  * ridge prior centered on the current constants — terms whose
    priced contribution sits below ~`ridge_frac` of the actuals' RMS
    are unidentifiable from this window (any multiplier fits equally;
    bare NNLS would collapse them toward the clip, fit after fit);
    the prior pins them at their current value while terms with real
    signal override it freely;
  * hysteresis — costmodel.set_hysteresis arms the sticky argmin: a
    challenger mode must beat a shape bucket's incumbent by the band
    before the choice (and the jit caches behind it) flips.

Epsilon exploration.  The ring only holds actuals for modes that WON
the argmin; constants for losing modes would never re-fit.  With
`tsd.costmodel.autotune.epsilon` > 0 the calibrator occasionally
forces one losing-but-feasible mode globally for one interval (via the
set_*_mode setters, which clear the jit caches — per-query exploration
would be silently ignored by the compiled-program cache), observes its
actuals, then restores 'auto'.  Off by default: exploration dispatches
deliberately-slower kernels.

Everything is wired behind `tsd.costmodel.autotune.*` (utils/config.py
CONFIG_SCHEMA, docs/costmodel.md); the maintenance thread drives
`OnlineCalibrator.tick()` and TSDB.shutdown persists the fitted
constants to BENCH_CALIBRATION.json so calibration survives restarts.
"""

from __future__ import annotations

import json
import logging
import math
import os
import random
import threading
import time

import numpy as np

LOG = logging.getLogger("tsd.costmodel.autotune")

# a term must appear (with nonzero units) in at least this many ring
# entries before a fit may move it
MIN_TERM_ROWS = 3

# deterministic exploration stream: reproducible soak runs
_EXPLORE_SEED = 0xC057


def nnls(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Non-negative least squares: argmin ||a @ x - b|| s.t. x >= 0.

    scipy's Lawson-Hanson when available; otherwise a small active-set
    implementation of the same algorithm (the problems here are tiny —
    a handful of columns — so the pure-numpy path is plenty)."""
    try:
        from scipy.optimize import nnls as _scipy_nnls
        return _scipy_nnls(a, b)[0]
    except ImportError:  # pragma: no cover - scipy is in the base image
        return _nnls_numpy(a, b)


def _nnls_numpy(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Lawson-Hanson active-set NNLS (Solving Least Squares Problems,
    ch. 23) in plain numpy."""
    m, n = a.shape
    x = np.zeros(n)
    passive = np.zeros(n, dtype=bool)
    w = a.T @ (b - a @ x)
    tol = 10 * np.finfo(float).eps * np.linalg.norm(a, 1) * (max(m, n) + 1)
    it, max_it = 0, 3 * n
    while (~passive).any() and (w[~passive] > tol).any() and it < max_it:
        it += 1
        j = int(np.argmax(np.where(~passive, w, -np.inf)))
        passive[j] = True
        while True:
            z = np.zeros(n)
            cols = np.where(passive)[0]
            z[cols] = np.linalg.lstsq(a[:, cols], b, rcond=None)[0]
            if (z[cols] > tol).all():
                x = z
                break
            # step back to the boundary, drop newly-zero columns
            neg = cols[z[cols] <= tol]
            steps = [x[k] / (x[k] - z[k]) for k in neg if x[k] > z[k]]
            if not steps:
                # degenerate (collinear) columns: the just-added column
                # solved to exactly 0 with x already 0 — no boundary to
                # step back to; drop the offenders and re-solve
                passive[neg] = False
                if not passive.any():
                    return np.zeros(n)
                continue
            alpha = min(steps)
            x = x + alpha * (z - x)
            passive &= x > tol
            if not passive.any():
                return np.zeros(n)
        w = a.T @ (b - a @ x)
    return np.clip(x, 0.0, None)


def fittable_entries(entries: list[dict], platform: str) -> list[dict]:
    """Ring entries the fitter can use for one platform: a feature
    vector AND a positive measured actual (device timing on)."""
    return [e for e in entries
            if e.get("platform") == platform and e.get("features")
            and float(e.get("actualMs", 0.0)) > 0.0]


def fit_constants(entries: list[dict], platform: str,
                  current: dict[str, float] | None = None,
                  min_samples: int = 64,
                  max_step: float = 4.0,
                  ridge_frac: float = 0.01) -> tuple[dict | None, dict]:
    """One NNLS fit of the per-unit constants from ring entries.

    Returns (constants, info): `constants` maps every fitted term to
    its new value (bounded to a factor of `max_step` around `current`),
    or None when the window holds fewer than `min_samples` fittable
    entries.  Terms without MIN_TERM_ROWS covering entries are left
    untouched (absent from the result).  `ridge_frac` sets the prior
    strength (as a fraction of the actuals' RMS) pulling each
    multiplier toward 1 — the identifiability floor; 0 disables it
    (pure NNLS).  The returned constants are finite and positive BY
    CONSTRUCTION: NNLS gives x >= 0 and the step clip keeps every
    multiplier in [1/max_step, max_step].
    """
    from opentsdb_tpu.ops import costmodel
    if current is None:
        current = dict(costmodel.costs(platform))
    rows = fittable_entries(entries, platform)
    info: dict = {"platform": platform, "samples": len(rows)}
    if len(rows) < max(int(min_samples), 1):
        info["skipped"] = "min_samples"
        return None, info
    coverage: dict[str, int] = {}
    for e in rows:
        for term, units in e["features"].items():
            if units > 0.0 and term in current:
                coverage[term] = coverage.get(term, 0) + 1
    terms = sorted(t for t, c in coverage.items() if c >= MIN_TERM_ROWS)
    info["terms"] = terms
    if not terms:
        info["skipped"] = "no_covered_terms"
        return None, info
    # columns scaled by the current constants -> x is a multiplier;
    # final intercept column absorbs the fixed per-dispatch overhead
    a = np.array([[e["features"].get(t, 0.0) * current[t] for t in terms]
                  + [1.0] for e in rows], dtype=float)
    b = np.array([float(e["actualMs"]) / 1e3 for e in rows], dtype=float)
    if ridge_frac > 0.0:
        # prior rows: lam * (x_j - 1) per term (and lam * x_intercept
        # toward 0).  Terms whose priced signal clears lam override
        # the prior; sub-lam terms hold their current value
        lam = float(ridge_frac) * float(np.sqrt(np.mean(b * b)))
        if lam > 0.0:
            k = len(terms)
            a = np.vstack([a, lam * np.eye(k + 1)])
            b = np.concatenate([b, lam * np.ones(k), [0.0]])
    x = nnls(a, b)
    info["overhead_s"] = float(x[-1])
    # residual over the DATA rows only (not the prior rows)
    nd = len(rows)
    resid = a[:nd] @ x - b[:nd]
    denom = float(np.sum(b[:nd] * b[:nd])) or 1.0
    info["residual"] = float(np.sqrt(np.sum(resid * resid) / denom))
    # max_step <= 0 means unbounded (the offline CLI's single-shot fit);
    # the online loop always passes a finite bound
    step = math.inf if float(max_step) <= 0.0 \
        else max(float(max_step), 1.0 + 1e-9)
    fitted: dict[str, float] = {}
    for t, mult in zip(terms, x[:-1]):
        mult = min(max(float(mult), 1.0 / step), step)
        if not math.isfinite(mult) or mult <= 0.0:
            # unbounded step + an NNLS zero: the term lost all its
            # cost in this window — keep the current constant instead
            # of installing 0
            info.setdefault("rejected", []).append(t)
            continue
        value = current[t] * mult
        if not math.isfinite(value) or value <= 0.0:
            # unreachable given the clip; belt-and-suspenders so a
            # poisoned value can never reach install_live_calibration
            info.setdefault("rejected", []).append(t)
            continue
        fitted[t] = value
    return fitted, info


def merge_calibration_file(path: str,
                           per_platform: dict[str, dict]) -> None:
    """Merge fitted constants into a calibration file (atomic replace;
    existing platforms/terms not in `per_platform` are preserved).
    Shared by the online loop's shutdown persistence and the offline
    CLI (tools/fit_costmodel.py)."""
    existing: dict = {}
    try:
        with open(path) as fh:
            loaded = json.load(fh)
        if isinstance(loaded, dict):
            existing = loaded
    except (OSError, ValueError):
        pass    # absent/corrupt file: start fresh
    for plat, constants in per_platform.items():
        table = existing.setdefault(plat, {})
        if isinstance(table, dict):
            table.update(constants)
        else:
            existing[plat] = dict(constants)
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as fh:
        json.dump(existing, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


# --------------------------------------------------------------------- #
# The online loop                                                       #
# --------------------------------------------------------------------- #

def _axis_setters() -> dict:
    from opentsdb_tpu.ops import downsample as ds
    from opentsdb_tpu.ops import group_agg as ga
    return {
        "search": ds.set_search_mode,
        "scan": ds.set_scan_mode,
        "extreme": ds.set_extreme_mode,
        "group": ga.set_group_reduce_mode,
    }


class OnlineCalibrator:
    """The self-tuning loop: fit from the live segment ring on the
    maintenance cadence, install bounded-step live constants, optionally
    explore losing modes, persist at shutdown.

    Driven by MaintenanceThread._maybe_autotune; constructed by TSDB
    when ``tsd.costmodel.autotune.enable`` is true.  All mutable state
    is guarded by ``_lock`` (the maintenance thread ticks; stats walks
    read from request threads)."""

    def __init__(self, tsdb):
        cfg = tsdb.config
        self.tsdb = tsdb
        self.interval = cfg.get_int("tsd.costmodel.autotune.interval")
        self.min_samples = cfg.get_int(
            "tsd.costmodel.autotune.min_samples")
        self.max_step = cfg.get_float("tsd.costmodel.autotune.max_step")
        self.epsilon = cfg.get_float("tsd.costmodel.autotune.epsilon")
        self.persist_on_shutdown = cfg.get_bool(
            "tsd.costmodel.autotune.persist")
        path = cfg.get_string("tsd.costmodel.autotune.calibration_file")
        from opentsdb_tpu.ops import costmodel
        # remember what construction installs process-globally so
        # shutdown() can restore it: a LATER TSDB in the same process
        # with autotune off must not inherit this instance's band,
        # live constants, or calibration-file redirect
        self._prior_calibration_file = costmodel.calibration_file()
        self._prior_hysteresis = costmodel.hysteresis()
        if path:
            # global-install: set_calibration_file paired-with: shutdown
            costmodel.set_calibration_file(path)
        try:
            self.calibration_path = path or costmodel.calibration_file()
            # PROCESS-GLOBAL, like _apply_kernel_modes: the sticky-argmin
            # band lives with the module-level choosers
            # global-install: set_hysteresis paired-with: shutdown
            costmodel.set_hysteresis(cfg.get_float(
                "tsd.costmodel.autotune.hysteresis"))
            self._lock = threading.Lock()
            self._rng = random.Random(_EXPLORE_SEED)
            # guarded-by: _lock
            self.fits = 0
            self.fit_errors = 0  # guarded-by: _lock
            self.samples_used = 0  # guarded-by: _lock
            self.explorations = 0  # guarded-by: _lock
            self.last_residual = 0.0  # guarded-by: _lock
            # active exploration: {"axis": ..., "mode": ...} while a
            # losing mode is forced  # guarded-by: _lock
            self.exploring: dict | None = None

            # NOT under _lock: only the maintenance thread's tick
            # touches it.  Armed by the first heartbeat (one full
            # interval after startup) rather than here: tick() accepts
            # an injected clock, and a monotonic-anchored init would
            # never fire under one.
            self._next_fit: float | None = None
            tsdb.stats_hooks["costmodel_autotune"] = self._stats_hook
        except BaseException:
            # a failed construction leaves no instance whose shutdown()
            # could restore the process-global redirect — undo it here
            costmodel.set_calibration_file(self._prior_calibration_file)
            costmodel.set_hysteresis(self._prior_hysteresis)
            raise

    # -- cadence ------------------------------------------------------- #

    def tick(self, now: float | None = None) -> bool:
        """One maintenance heartbeat: no-op until the interval elapses,
        then end any active exploration, fit, maybe start a new
        exploration.  Returns True when a pass ran."""
        if now is None:
            now = time.monotonic()
        if self.interval <= 0:
            return False
        if self._next_fit is None:
            self._next_fit = now + max(self.interval, 1)
            return False
        if now < self._next_fit:
            return False
        self._next_fit = now + max(self.interval, 1)
        self._end_exploration()
        try:
            self.fit_once()
        except Exception:
            with self._lock:
                self.fit_errors += 1
            LOG.exception("costmodel autotune fit failed")
        self._maybe_explore()
        return True

    # -- fitting ------------------------------------------------------- #

    def fit_once(self) -> int:
        """Fit every platform with fittable ring entries; install the
        results as live calibration.  Returns platforms installed."""
        from opentsdb_tpu.obs import jaxprof
        from opentsdb_tpu.obs.registry import REGISTRY
        from opentsdb_tpu.ops import costmodel
        entries = jaxprof.segments()
        platforms = sorted({e.get("platform") for e in entries
                            if e.get("platform")})
        installed = 0
        for plat in platforms:
            fitted, info = fit_constants(
                entries, plat, min_samples=self.min_samples,
                max_step=self.max_step)
            if not fitted:
                continue
            # global-install: clear_live_calibration paired-with: shutdown
            costmodel.install_live_calibration(plat, fitted)
            installed += 1
            with self._lock:
                self.fits += 1
                self.samples_used = info["samples"]
                self.last_residual = info["residual"]
            REGISTRY.counter(
                "tsd.costmodel.calibration.fits",
                "Online costmodel fits installed").labels(
                    platform=plat).inc()
            REGISTRY.gauge(
                "tsd.costmodel.calibration.samples",
                "Ring entries consumed by the last fit").labels(
                    platform=plat).set(info["samples"])
            REGISTRY.gauge(
                "tsd.costmodel.calibration.residual",
                "Relative residual of the last fit").labels(
                    platform=plat).set(info["residual"])
            for term, value in fitted.items():
                REGISTRY.gauge(
                    "tsd.costmodel.calibration.constant",
                    "Live-fitted per-unit cost, seconds").labels(
                        platform=plat, term=term).set(value)
            LOG.info("costmodel fit installed for %s: %d samples, "
                     "residual %.3f, %d terms", plat, info["samples"],
                     info["residual"], len(fitted))
            recorder = getattr(self.tsdb, "flightrec", None)
            if recorder is not None:
                recorder.record("autotune", action="fit", platform=plat,
                                samples=int(info["samples"]),
                                residual=round(float(info["residual"]),
                                               4))
        return installed

    # -- exploration --------------------------------------------------- #

    def _maybe_explore(self) -> None:
        """With probability epsilon, force one losing-but-feasible mode
        for one interval so the ring collects actuals for it.  Only
        explores decisions the argmin owns (source == 'auto'): an
        operator-forced mode is never overridden."""
        if self.epsilon <= 0.0 or self._rng.random() >= self.epsilon:
            return
        from opentsdb_tpu.obs import jaxprof
        candidates = [e for e in jaxprof.segments()
                      if e.get("modes") and e.get("platform")]
        if not candidates:
            return
        entry = self._rng.choice(candidates)
        extremes = "extreme" in entry["modes"]
        decisions = jaxprof.segment_decisions(
            entry["platform"], entry["series"], entry["points"],
            entry["windows"], entry["groups"],
            "min" if extremes else "avg",
            aggregator=entry.get("aggregator"))
        axes = [a for a, rep in decisions.items()
                if rep["source"] == "auto"
                and len(rep["candidates"]) > 1]
        from opentsdb_tpu.ops import downsample as ds
        if entry["platform"] == "cpu" and ds._PLATFORM_MODE_GUARD:
            # the CPU platform guard demotes the dense search forms at
            # dispatch: forcing one would flush every jit cache twice
            # and record zero new data — spend this epsilon draw on an
            # axis that can actually be explored here
            axes = [a for a in axes if a != "search"]
        if not axes:
            return
        axis = self._rng.choice(axes)
        report = decisions[axis]
        losers = [m for m in report["candidates"]
                  if m != report["mode"]]
        if not losers:
            return
        mode = self._rng.choice(losers)
        _axis_setters()[axis](mode)     # clears the dependent jit caches
        with self._lock:
            self.exploring = {"axis": axis, "mode": mode}
            self.explorations += 1
        from opentsdb_tpu.obs.registry import REGISTRY
        REGISTRY.counter(
            "tsd.costmodel.calibration.explorations",
            "Epsilon-exploration intervals dispatched").labels(
                axis=axis).inc()
        LOG.info("costmodel exploration: forcing %s mode %r for one "
                 "interval", axis, mode)
        recorder = getattr(self.tsdb, "flightrec", None)
        if recorder is not None:
            # a mode flip clears the dependent jit caches — exactly the
            # event a "why did serving recompile at 14:32" post-mortem
            # needs retained
            recorder.record("autotune", action="explore", axis=axis,
                            mode=mode)

    def _end_exploration(self) -> None:
        with self._lock:
            active = self.exploring
            self.exploring = None
        if active is None:
            return
        _axis_setters()[active["axis"]]("auto")
        recorder = getattr(self.tsdb, "flightrec", None)
        if recorder is not None:
            recorder.record("autotune", action="restore",
                            axis=active["axis"], mode=active["mode"])

    # -- persistence --------------------------------------------------- #

    def persist(self) -> bool:
        """Merge the live-fitted constants into the calibration file
        (atomic replace) so the next process starts from them.  Returns
        True when something was written."""
        from opentsdb_tpu.ops import costmodel
        live = {p: costmodel.live_calibration(p) for p in ("tpu", "cpu")}
        live = {p: v for p, v in live.items() if v}
        if not live:
            return False
        merge_calibration_file(self.calibration_path, live)
        LOG.info("persisted live costmodel calibration to %s "
                 "(platforms: %s)", self.calibration_path,
                 ", ".join(sorted(live)))
        return True

    def shutdown(self) -> None:
        """Mirror construction: restore any forced exploration mode,
        persist the fitted constants (config-gated), then un-install
        the process-global state this instance set up — the live
        layer (safe to drop once persisted: the file layer serves it
        from `calibration_path`), the hysteresis band, and the
        calibration-file redirect.  Called from TSDB.shutdown."""
        self._end_exploration()
        if self.persist_on_shutdown:
            try:
                self.persist()
            except OSError:
                LOG.exception("could not persist costmodel calibration")
        from opentsdb_tpu.ops import costmodel
        costmodel.clear_live_calibration()
        costmodel.set_hysteresis(self._prior_hysteresis)
        if costmodel.calibration_file() != self._prior_calibration_file:
            costmodel.set_calibration_file(self._prior_calibration_file)

    # -- stats --------------------------------------------------------- #

    def collect_stats(self) -> dict[str, float]:
        with self._lock:
            return {
                "costmodel.autotune.fits": float(self.fits),
                "costmodel.autotune.fit_errors": float(self.fit_errors),
                "costmodel.autotune.samples_used":
                    float(self.samples_used),
                "costmodel.autotune.explorations":
                    float(self.explorations),
                "costmodel.autotune.residual": float(self.last_residual),
                "costmodel.autotune.exploring":
                    1.0 if self.exploring else 0.0,
            }

    def _stats_hook(self, collector) -> None:
        """/api/stats + self-report view: loop counters plus the live
        constants themselves (term-tagged), so an operator — and the
        chaos gate — can read the installed calibration off any stats
        surface."""
        from opentsdb_tpu.ops import costmodel
        for name, value in self.collect_stats().items():
            # forwarder: the names are this class's collect_stats()
            # keys (tsd.costmodel.autotune.*), declared in
            # METRICS_SCHEMA  # tsdblint: disable=metrics-dynamic-name
            collector.record(name, value)
        for plat in ("tpu", "cpu"):
            for term, value in costmodel.live_calibration(plat).items():
                collector.record("costmodel.calibration.%s" % plat,
                                 value, "term=%s" % term)
