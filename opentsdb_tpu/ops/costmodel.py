"""Shape-driven kernel-mode selection (VERDICT r4 #4).

Replaces crowned-env-var-plus-reactive-guard mode policy with a small
analytical cost model: for each kernel axis (edge search, prefix scan,
extreme reduce, group reduce) predict the per-dispatch cost of every
feasible mode from the dispatch shape and the execution platform, and
take the argmin.  Feasibility (memory caps, divisibility, platform
hazards) stays with the kernels in downsample.py/group_agg.py — this
module only ranks the modes those guards admit, so a wrong prediction
can cost a few x, never an OOM or a compile failure.

The per-unit constants are CALIBRATED, not guessed: each anchor cites
the chip measurement it comes from (BENCH_CONFIGS_r04.json bench_prefix
/ stage_bench rows at the headline shape — 1024 series x 65536 points,
514 window edges, f64 contract).  A measurement session can re-calibrate
without code edits by writing BENCH_CALIBRATION.json at the repo root
({"tpu": {...}, "cpu": {...}} partial overrides); BENCH_WINNERS.json
stays as recorded evidence, no longer policy.

The decisions this model reproduces from the r4 chip data:
  * search: hier (20ms) < compare_all (116ms) < binary scan (154ms) on
    the chip at the headline shape; binary everywhere on CPU (the dense
    compare materializes there — measured 18-70x slower).
  * prefix scan: subblock windowed-sum (88ms) < flat (130ms) on the
    chip (the full-length emulated-f64 cumsum is the cost, 100ms vs
    3ms for 1/32-length) — and subblock wins on CPU too, 5.5x: the XLA
    CPU cumsum is a SERIAL scalar loop (measured on the config-1 shape,
    [1, 2^20]: 8.8ms cumsum vs 0.97ms elementwise; full avg path 2.1ms
    subblock / 11.6 flat / 9.4 subblock2 — subblock2's within-block
    inclusive-prefix pass is flat-class on CPU, so it gets its own
    per-element constant).
  * extremes: reset-scan (0.5245s/dispatch) < subblock (0.8282 — its
    per-edge boundary-lane reduces outweigh the shorter scan at the
    headline W) << segment scatter (7.161) on the chip; the scatter is
    cheap on CPU.
  * group reduce: the serializing segment scatter (219ms) loses on the
    chip to the one-hot MXU matmul (~100ms at G=100) and the sorted
    reset-scan (~90ms, G-independent); matmul's cost grows linearly in
    G so large-G queries flip to sorted.  CPU keeps segment.

Reference being outperformed: the per-datapoint iterator stack
(/root/reference/src/core/AggregationIterator.java:514,
Downsampler.java:292) has exactly one "mode"; this module exists
because the TPU-first kernel space has several and the fastest one is
shape-dependent.
"""

from __future__ import annotations

import json
import math
import os

# --------------------------------------------------------------------- #
# Calibrated per-unit costs, seconds.  Anchors (r04b chip session,
# BENCH_CONFIGS_r04.json, headline shape S=1024 N=65536 E=514 G=100
# W=512):
#   gather_round  0.154s / (S*E*log2(N)=8.42e6)      binary search stage
#   cmp_cell      0.116s / (S*N*E=3.45e10)           compare_all stage
#   hier_cell     0.020s / (S*(N/32)*E=1.08e9)       hier stage
#   scan_f64      0.100s / (S*N=6.71e7)              f64 cumsum stage
#   elem_f64      0.018s / (S*N=6.71e7)              raw f64 elementwise
#   win_gather    (0.130-0.100)s / (S*E=5.26e5)      flat windowed-sum
#                                                    minus its cumsum
#   seg_scatter   0.219s / (S*W=5.24e5)              group segment stage
#   mxu_cell      0.100s / (G*S*W=5.24e9)            group matmul stage
#   sorted_grid   0.090s / (S*W=5.24e5)              group sorted stage
#   ext_scan      0.52s/dispatch vs ext_segment 7.09s — modeled per
#                 grid element over S*N
# CPU anchors are this dev box (differential suite timings): searchsorted
# ~2e-8/unit, native cumsum ~1.5e-9/elem, scatters ~5e-9/elem; the
# dense-compare materialization hazard is handled by feasibility (the
# platform guard), not by the model.
# --------------------------------------------------------------------- #

DEFAULT_COSTS: dict[str, dict[str, float]] = {
    "tpu": {
        "gather_round": 1.83e-8,
        "cmp_cell": 3.36e-12,
        "hier_cell": 1.87e-11,
        "scan_f64": 1.49e-9,
        "elem_f64": 2.7e-10,
        # within-block prefix pass: priced slightly ABOVE elem_f64 so
        # the chip-race-crowned subblock stays the auto pick on TPU
        # until a calibration actually measures subblock2 faster (its
        # CPU prefix pass is 8x elem-cost — the chip may disappoint too)
        "sub2_elem": 3.5e-10,
        "win_gather": 5.7e-8,
        "seg_scatter": 4.2e-7,
        "mxu_cell": 1.9e-9,
        "sorted_grid": 1.7e-7,
        # blocked level-masked fold (mode "sorted2"): ESTIMATE (~0.4x
        # sorted — half the full-width levels, no pair-op selects/bool
        # channel) until a chip race records it; deliberately not an
        # auto candidate until then (group_agg._effective_group_reduce_mode)
        "sorted2_grid": 7.0e-8,
        "ext_scan_elem": 6.0e-9,
        "ext_seg_elem": 1.06e-7,
        "ext_boundary_cell": 4.0e-8,
    },
    "cpu": {
        "gather_round": 2.0e-8,
        "cmp_cell": 1.0e-9,      # materializes; feasibility-capped anyway
        "hier_cell": 1.0e-9,
        # XLA's CPU cumsum lowers to a SERIAL scalar loop: measured
        # 8.8ms over 2^20 f64 (8.4e-9/elem) while an elementwise pass
        # streams the same data in 0.97ms — the subblock form's
        # 1/32-length scan is therefore a ~6x win on the host as well
        "scan_f64": 8.4e-9,
        "elem_f64": 1.0e-9,
        # subblock2's within-block inclusive prefixes are flat-class on
        # CPU (measured 9.4ms vs subblock's 2.1 on the config-1 shape)
        "sub2_elem": 8.0e-9,
        "win_gather": 2.0e-8,
        "seg_scatter": 5.0e-9,   # CPU scatters are cheap
        "mxu_cell": 1.0e-9,      # no MXU: dense [G,S]x[S,W] is real FLOPs
        "sorted_grid": 1.0e-8,
        "sorted2_grid": 1.0e-8,  # estimate; not an auto candidate yet

        "ext_scan_elem": 4.0e-9,
        "ext_seg_elem": 2.0e-9,
        "ext_boundary_cell": 2.0e-8,
    },
}

_CALIBRATION_FILE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "BENCH_CALIBRATION.json")

_COSTS: dict[str, dict[str, float]] | None = None


def costs(platform: str) -> dict[str, float]:
    """Per-unit costs for a platform, with BENCH_CALIBRATION.json
    overrides applied once per process.  Unknown platforms (the axon
    tunnel reports 'axon') use the TPU table — this framework's device
    path IS the TPU path."""
    global _COSTS
    if _COSTS is None:
        table = {p: dict(c) for p, c in DEFAULT_COSTS.items()}
        try:
            with open(_CALIBRATION_FILE) as fh:
                for plat, over in json.load(fh).items():
                    if plat in table and isinstance(over, dict):
                        for k, v in over.items():
                            if k in table[plat]:
                                table[plat][k] = float(v)
        except (OSError, ValueError):
            pass
        _COSTS = table
    return _COSTS["cpu" if platform == "cpu" else "tpu"]


def reload_calibration() -> None:
    """Drop the cached cost table (tests / post-session recalibration).
    Callers that already traced with the old table must clear jit caches
    themselves (downsample.set_* helpers do)."""
    global _COSTS
    _COSTS = None


def _log2(n: int) -> int:
    return max(int(math.ceil(math.log2(max(n, 2)))), 1)


# -- edge search: idx[S, E] from [S, N] sorted timestamps -------------- #

def predict_search(mode: str, s: int, n: int, e: int,
                   platform: str) -> float:
    c = costs(platform)
    if mode == "scan":
        return s * e * _log2(n) * c["gather_round"]
    if mode == "compare_all":
        return s * n * e * c["cmp_cell"]
    if mode == "hier":
        k = 32
        return s * ((n // k) + k) * e * c["hier_cell"]
    raise ValueError("unknown search mode: " + mode)


def choose_search(s: int, n: int, e: int, platform: str,
                  candidates: list[str]) -> str:
    return min(candidates,
               key=lambda m: predict_search(m, s, n, e, platform))


# -- prefix scan: windowed sums over [S, N] ---------------------------- #

def predict_scan(mode: str, s: int, n: int, e: int,
                 platform: str) -> float:
    c = costs(platform)
    if mode == "flat":
        return s * n * c["scan_f64"] + s * e * c["win_gather"]
    if mode == "blocked":
        # two-level scan: same element count, measured slightly slower
        # than flat on both platforms (r3 chip: 0.600 vs 0.568)
        return 1.06 * (s * n * c["scan_f64"] + s * e * c["win_gather"])
    if mode == "subblock":
        k = 32
        return (s * n * c["elem_f64"]                 # sub-block reduce
                + s * (n // k) * c["scan_f64"]        # 1/32-length cumsum
                + s * e * k * c["elem_f64"]           # boundary remainder
                + s * e * c["win_gather"])
    if mode == "subblock2":
        k = 32
        # within-block inclusive prefixes (block sums fall out of the
        # last lane) + ONE element gather per edge — no [S, E, K]
        # remainder intermediate, but the prefix pass has its own
        # platform-dependent cost (serial-ish on CPU)
        return (s * n * c["sub2_elem"]
                + s * (n // k) * c["scan_f64"]
                + s * e * c["win_gather"])
    raise ValueError("unknown scan mode: " + mode)


def choose_scan(s: int, n: int, e: int, platform: str,
                candidates: list[str]) -> str:
    return min(candidates,
               key=lambda m: predict_scan(m, s, n, e, platform))


# -- extreme (min/max) over [S, N] ------------------------------------- #

def predict_extreme(mode: str, s: int, n: int, e: int,
                    platform: str) -> float:
    c = costs(platform)
    if mode == "scan":
        return s * n * c["ext_scan_elem"]
    if mode == "segment":
        return s * n * c["ext_seg_elem"]
    if mode == "subblock":
        k = 32
        # sub-block reduces + a 1/32-length reset-scan + per-edge
        # boundary-lane masked reduces (the term that loses it the
        # headline shape: measured 0.83 vs scan's 0.52 s/dispatch)
        return (s * n * c["elem_f64"]
                + s * (n // k) * c["ext_scan_elem"]
                + s * e * k * c["ext_boundary_cell"])
    raise ValueError("unknown extreme mode: " + mode)


def choose_extreme(s: int, n: int, e: int, platform: str,
                   candidates: list[str]) -> str:
    return min(candidates,
               key=lambda m: predict_extreme(m, s, n, e, platform))


# -- group reduce: [S, W] + gid[S] -> [G, W] --------------------------- #

def predict_group(mode: str, s: int, w: int, g: int,
                  platform: str) -> float:
    c = costs(platform)
    if mode == "segment":
        return s * w * c["seg_scatter"]
    if mode == "matmul":
        return g * s * w * c["mxu_cell"]
    if mode == "sorted":
        return s * w * c["sorted_grid"]
    if mode == "sorted2":
        return s * w * c["sorted2_grid"]
    raise ValueError("unknown group mode: " + mode)


def choose_group(s: int, w: int, g: int, platform: str,
                 candidates: list[str]) -> str:
    return min(candidates,
               key=lambda m: predict_group(m, s, w, g, platform))
