"""Shape-driven kernel-mode selection (VERDICT r4 #4).

Replaces crowned-env-var-plus-reactive-guard mode policy with a small
analytical cost model: for each kernel axis (edge search, prefix scan,
extreme reduce, group reduce) predict the per-dispatch cost of every
feasible mode from the dispatch shape and the execution platform, and
take the argmin.  Feasibility (memory caps, divisibility, platform
hazards) stays with the kernels in downsample.py/group_agg.py — this
module only ranks the modes those guards admit, so a wrong prediction
can cost a few x, never an OOM or a compile failure.

The per-unit constants are CALIBRATED, not guessed: each anchor cites
the chip measurement it comes from (BENCH_CONFIGS_r04.json bench_prefix
/ stage_bench rows at the headline shape — 1024 series x 65536 points,
514 window edges, f64 contract).  A measurement session can re-calibrate
without code edits by writing BENCH_CALIBRATION.json at the repo root
({"tpu": {...}, "cpu": {...}} partial overrides); BENCH_WINNERS.json
stays as recorded evidence, no longer policy.

The decisions this model reproduces from the r4 chip data:
  * search: hier (20ms) < compare_all (116ms) < binary scan (154ms) on
    the chip at the headline shape; binary everywhere on CPU (the dense
    compare materializes there — measured 18-70x slower).
  * prefix scan: subblock windowed-sum (88ms) < flat (130ms) on the
    chip (the full-length emulated-f64 cumsum is the cost, 100ms vs
    3ms for 1/32-length) — and subblock wins on CPU too, 5.5x: the XLA
    CPU cumsum is a SERIAL scalar loop (measured on the config-1 shape,
    [1, 2^20]: 8.8ms cumsum vs 0.97ms elementwise; full avg path 2.1ms
    subblock / 11.6 flat / 9.4 subblock2 — subblock2's within-block
    inclusive-prefix pass is flat-class on CPU, so it gets its own
    per-element constant).
  * extremes: reset-scan (0.5245s/dispatch) < subblock (0.8282 — its
    per-edge boundary-lane reduces outweigh the shorter scan at the
    headline W) << segment scatter (7.161) on the chip; the scatter is
    cheap on CPU.
  * group reduce: the serializing segment scatter (219ms) loses on the
    chip to the one-hot MXU matmul (~100ms at G=100) and the sorted
    reset-scan (~90ms, G-independent); matmul's cost grows linearly in
    G so large-G queries flip to sorted.  CPU keeps segment.

Online calibration (PR 6, docs/costmodel.md).  Every `predict_*` is a
LINEAR form: a dot product of a per-mode feature vector (unit counts —
gather rounds, scanned elements, scattered cells; `features_*` below)
with the per-unit cost table.  That linearity is what makes the model
fittable from live traffic: obs/jaxprof.py records each executed query
segment's feature vector next to its measured device time, and
ops/calibrate.py solves for the per-unit constants by non-negative
least squares, installing the result here as a LIVE override layer on
top of the file calibration (`install_live_calibration`).  The three
layers compose default -> BENCH_CALIBRATION.json -> live fit, and
`calibration_source()` names the winning layer so every traced query
can say where its mode decision came from.

A hysteresis band (`set_hysteresis`) makes the argmin sticky per shape
bucket: once a mode has won a bucket, a challenger must beat it by the
band's margin to flip the choice — one noisy calibration batch cannot
thrash modes (and the jit caches behind them) every query.

Reference being outperformed: the per-datapoint iterator stack
(/root/reference/src/core/AggregationIterator.java:514,
Downsampler.java:292) has exactly one "mode"; this module exists
because the TPU-first kernel space has several and the fastest one is
shape-dependent.
"""

from __future__ import annotations

import json
import math
import os
import threading

# --------------------------------------------------------------------- #
# Calibrated per-unit costs, seconds.  Anchors (r04b chip session,
# BENCH_CONFIGS_r04.json, headline shape S=1024 N=65536 E=514 G=100
# W=512):
#   gather_round  0.154s / (S*E*log2(N)=8.42e6)      binary search stage
#   cmp_cell      0.116s / (S*N*E=3.45e10)           compare_all stage
#   hier_cell     0.020s / (S*(N/32)*E=1.08e9)       hier stage
#   scan_f64      0.100s / (S*N=6.71e7)              f64 cumsum stage
#   elem_f64      0.018s / (S*N=6.71e7)              raw f64 elementwise
#   win_gather    (0.130-0.100)s / (S*E=5.26e5)      flat windowed-sum
#                                                    minus its cumsum
#   seg_scatter   0.219s / (S*W=5.24e5)              group segment stage
#   mxu_cell      0.100s / (G*S*W=5.24e9)            group matmul stage
#   sorted_grid   0.090s / (S*W=5.24e5)              group sorted stage
#   ext_scan      0.52s/dispatch vs ext_segment 7.09s — modeled per
#                 grid element over S*N
# CPU anchors are this dev box (differential suite timings): searchsorted
# ~2e-8/unit, native cumsum ~1.5e-9/elem, scatters ~5e-9/elem; the
# dense-compare materialization hazard is handled by feasibility (the
# platform guard), not by the model.
# --------------------------------------------------------------------- #

DEFAULT_COSTS: dict[str, dict[str, float]] = {
    "tpu": {
        "gather_round": 1.83e-8,
        "cmp_cell": 3.36e-12,
        "hier_cell": 1.87e-11,
        "scan_f64": 1.49e-9,
        "elem_f64": 2.7e-10,
        # within-block prefix pass: priced slightly ABOVE elem_f64 so
        # the chip-race-crowned subblock stays the auto pick on TPU
        # until a calibration actually measures subblock2 faster (its
        # CPU prefix pass is 8x elem-cost — the chip may disappoint too)
        "sub2_elem": 3.5e-10,
        "win_gather": 5.7e-8,
        "seg_scatter": 4.2e-7,
        "mxu_cell": 1.9e-9,
        "sorted_grid": 1.7e-7,
        # blocked level-masked fold (mode "sorted2"): ESTIMATE (~0.4x
        # sorted — half the full-width levels, no pair-op selects/bool
        # channel) until a chip race records it; deliberately not an
        # auto candidate until then (group_agg._effective_group_reduce_mode)
        "sorted2_grid": 7.0e-8,
        "ext_scan_elem": 6.0e-9,
        "ext_seg_elem": 1.06e-7,
        "ext_boundary_cell": 4.0e-8,
        # out-of-core tiling (ops/tiling.py): partial-grid spill-pool
        # write/read seconds per MB (host memcpy + the disk-overflow
        # share at the default pool split — the fitter separates the
        # real mix from live traffic) and the per-dispatch overhead of
        # a tiled plan's extra launches (chunk folds, finishes,
        # stripe tails).  ESTIMATES until a chip session records the
        # tunnel-transfer reality; the tiled decision only ever
        # arbitrates tiled-vs-refuse, so a bad constant costs admission
        # accuracy, never a wrong answer.
        "spill_write_mb": 6.0e-4,
        "spill_read_mb": 4.0e-4,
        "tile_dispatch": 1.5e-3,
        # rollup lanes (storage/rollup.py): host-side lane-cell
        # assembly+re-reduce seconds per MB of cells touched, and the
        # per-(series, cell) cost of a maintenance block build (the
        # Storyboard selection prices build amortization with it).
        # ESTIMATES until the fitter sees lane traffic; a bad constant
        # skews which lanes materialize, never an answer.
        "lane_assemble_mb": 2.5e-4,
        "lane_build_cell": 2.0e-9,
        # fused multi-query dispatch (query/batcher.py): the per-
        # dispatch floor a stacked [Q, S, W] launch amortizes away
        # (tunnel round trip + XLA launch — the quantity the batcher
        # exists to stop paying Q times), and the per-cell host cost
        # of stacking a member's [S, N] batch in + unpacking its
        # [G, W] slice out.  ESTIMATES until the fitter sees batch
        # traffic; batched runs are EXCLUDED from the calibration ring
        # (like rewrites/tiled runs), so a bad constant skews the
        # coalesce-vs-dispatch-now line, never an answer.
        "stacked_dispatch": 1.5e-3,
        "stacked_cell": 1.0e-9,
    },
    "cpu": {
        "gather_round": 2.0e-8,
        "cmp_cell": 1.0e-9,      # materializes; feasibility-capped anyway
        "hier_cell": 1.0e-9,
        # XLA's CPU cumsum lowers to a SERIAL scalar loop: measured
        # 8.8ms over 2^20 f64 (8.4e-9/elem) while an elementwise pass
        # streams the same data in 0.97ms — the subblock form's
        # 1/32-length scan is therefore a ~6x win on the host as well
        "scan_f64": 8.4e-9,
        "elem_f64": 1.0e-9,
        # subblock2's within-block inclusive prefixes are flat-class on
        # CPU (measured 9.4ms vs subblock's 2.1 on the config-1 shape)
        "sub2_elem": 8.0e-9,
        "win_gather": 2.0e-8,
        "seg_scatter": 5.0e-9,   # CPU scatters are cheap
        "mxu_cell": 1.0e-9,      # no MXU: dense [G,S]x[S,W] is real FLOPs
        "sorted_grid": 1.0e-8,
        "sorted2_grid": 1.0e-8,  # estimate; not an auto candidate yet

        "ext_scan_elem": 4.0e-9,
        "ext_seg_elem": 2.0e-9,
        "ext_boundary_cell": 2.0e-8,
        # spill pool on the host platform: same memcpy, no tunnel
        "spill_write_mb": 4.0e-4,
        "spill_read_mb": 3.0e-4,
        "tile_dispatch": 3.0e-4,
        # rollup lanes: same host memcpy either platform
        "lane_assemble_mb": 2.5e-4,
        "lane_build_cell": 2.0e-9,
        # stacked dispatch: the CPU jit-launch floor is smaller than
        # the tunnel's but still dwarfs a small query's compute
        # (~0.3 ms/dispatch measured on this dev box); stacking cells
        # is host memcpy either platform
        "stacked_dispatch": 3.0e-4,
        "stacked_cell": 1.0e-9,
    },
}

# The per-unit cost TERMS — identical key set on every platform (the
# fitter's design matrix columns; asserted at import so a new term
# cannot be added to one table and silently stay un-fittable on the
# other).
COST_TERMS: tuple[str, ...] = tuple(sorted(DEFAULT_COSTS["tpu"]))
assert tuple(sorted(DEFAULT_COSTS["cpu"])) == COST_TERMS

_CALIBRATION_FILE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "BENCH_CALIBRATION.json")

_lock = threading.Lock()
# the cached three-layer cost table; every jitted kernel bakes it in at
# trace time  # guarded-by: _lock  # cache: cost-table invalidated-by: reload_calibration
_COSTS: dict[str, dict[str, float]] | None = None
# live-fit override layer (ops/calibrate.py installs; applied on top of
# the file layer)  # guarded-by: _lock
_LIVE: dict[str, dict[str, float]] = {}
# platforms whose table took BENCH_CALIBRATION.json overrides — rebuilt
# with the table  # cache: cost-table invalidated-by: reload_calibration
_FILE_PLATFORMS: set[str] = set()    # guarded-by: _lock


def _table_key(platform: str) -> str:
    # Unknown platforms (the axon tunnel reports 'axon') use the TPU
    # table — this framework's device path IS the TPU path.
    return "cpu" if platform == "cpu" else "tpu"


def _apply_file_overrides(table: dict[str, dict[str, float]]) -> set[str]:
    """Overlay BENCH_CALIBRATION.json onto a defaults table in place;
    returns the platforms that took at least one override.  ONE parser
    for the file layer — the serving table build and the what-if
    repricer (`layer_table`) must never read the file differently."""
    touched: set[str] = set()
    try:
        with open(_CALIBRATION_FILE) as fh:
            for plat, over in json.load(fh).items():
                if plat in table and isinstance(over, dict):
                    for k, v in over.items():
                        if k in table[plat]:
                            table[plat][k] = float(v)
                            touched.add(plat)
    except (OSError, ValueError):
        pass
    return touched


def _build_table_locked() -> dict[str, dict[str, float]]:
    table = {p: dict(c) for p, c in DEFAULT_COSTS.items()}
    _FILE_PLATFORMS.clear()
    _FILE_PLATFORMS.update(_apply_file_overrides(table))
    for plat, over in _LIVE.items():
        if plat in table:
            table[plat].update(over)
    return table


def costs(platform: str) -> dict[str, float]:
    """Per-unit costs for a platform: defaults, then
    BENCH_CALIBRATION.json overrides, then the live-fit layer — cached
    until `reload_calibration()`.  Callers must treat the result as
    read-only."""
    global _COSTS
    with _lock:
        if _COSTS is None:
            _COSTS = _build_table_locked()
        return _COSTS[_table_key(platform)]


def calibration_source(platform: str) -> str:
    """Which layer last touched this platform's cost table: 'live'
    (online fitter), 'file' (BENCH_CALIBRATION.json), or 'default'.
    Traced queries stamp this on every strategy decision."""
    global _COSTS
    with _lock:
        if _COSTS is None:
            _COSTS = _build_table_locked()
        key = _table_key(platform)
        if _LIVE.get(key):
            return "live"
        if key in _FILE_PLATFORMS:
            return "file"
        return "default"


def layer_table(platform: str, layer: str) -> dict[str, float]:
    """A COPY of the per-unit cost table as a specific layer would
    price it — the what-if repricer's view (query/explain.py):
    'default' = the shipped constants, 'file' = defaults +
    BENCH_CALIBRATION.json, 'auto' (or anything else) = the live
    three-layer table ``costs()`` serves.  Never consulted by the
    serving argmin, and never cached — explain is cold-path."""
    key = _table_key(platform)
    if layer == "default":
        return dict(DEFAULT_COSTS[key])
    if layer == "file":
        table = {p: dict(c) for p, c in DEFAULT_COSTS.items()}
        _apply_file_overrides(table)
        return table[key]
    return dict(costs(platform))


def install_live_calibration(platform: str,
                             constants: dict[str, float]) -> None:
    """Install online-fitted per-unit constants for `platform` (merged
    over any previous live values) and drop every cache that baked the
    old table in.  Values must be finite and positive and every term
    must exist — the fitter's guards should make a violation impossible,
    so one here raises instead of installing a poisoned table."""
    key = _table_key(platform)
    clean: dict[str, float] = {}
    for term, value in constants.items():
        v = float(value)
        if term not in DEFAULT_COSTS[key]:
            raise ValueError("unknown cost term: %r" % term)
        if not math.isfinite(v) or v <= 0.0:
            raise ValueError("non-positive/NaN cost for %s: %r"
                             % (term, value))
        clean[term] = v
    with _lock:
        _LIVE.setdefault(key, {}).update(clean)
    reload_calibration()


def clear_live_calibration() -> None:
    """Drop the live-fit layer (back to file/default constants)."""
    with _lock:
        _LIVE.clear()
    reload_calibration()


def live_calibration(platform: str) -> dict[str, float]:
    """The currently-installed live overrides for a platform (empty when
    the fitter has not run)."""
    with _lock:
        return dict(_LIVE.get(_table_key(platform), {}))


def set_calibration_file(path: str) -> None:
    """Point the file layer somewhere else (daemon config/tests) and
    reload."""
    global _CALIBRATION_FILE
    _CALIBRATION_FILE = path
    reload_calibration()


def calibration_file() -> str:
    return _CALIBRATION_FILE


def reload_calibration() -> None:
    """THE calibration-invalidation entry point: drops the cached cost
    table, the sticky-choice memory, AND every dependent compiled
    program (the downsample/group_agg pipelines bake mode choices in at
    trace time — a reload that left them cached would keep serving
    stale-mode kernels; that footgun used to be the caller's problem).
    The hysteresis incumbent memory deliberately SURVIVES a reload:
    it is what keeps one noisy calibration install from flipping modes
    — every later choice re-prices the incumbent under the new table
    and flips only past the band."""
    global _COSTS
    with _lock:
        _COSTS = None
    from opentsdb_tpu.ops.downsample import _clear_dependent_caches
    _clear_dependent_caches()


# --------------------------------------------------------------------- #
# Sticky argmin: the hysteresis band                                    #
# --------------------------------------------------------------------- #

_HYSTERESIS = 0.0
_MEMO_MAX = 1024
# last winning mode per (kind, platform, candidates, shape bucket).
# Deliberately SURVIVES reload_calibration (see its docstring);
# set_hysteresis is the one entry point that drops it.
# cache: choice-memo invalidated-by: set_hysteresis
_choice_memo: dict[tuple, str] = {}    # guarded-by: _lock


def set_hysteresis(band: float) -> None:
    """Sticky-argmin band: a challenger mode must predict at least
    ``band`` (fraction, e.g. 0.15) cheaper than a shape bucket's
    incumbent before the choice flips.  0 (the default) keeps the pure
    argmin — exactly the pre-autotune behavior.  Changing the band
    clears the incumbent memory AND the dependent jit caches (the band
    changes which mode _choose returns, and compiled programs bake
    that in — same rule as every other mode-policy toggle)."""
    global _HYSTERESIS
    if band < 0.0 or not math.isfinite(band):
        raise ValueError("hysteresis band must be finite and >= 0")
    with _lock:
        if _HYSTERESIS == band:
            return      # idempotent: no policy change, nothing to drop
        _HYSTERESIS = band
        _choice_memo.clear()
    from opentsdb_tpu.ops.downsample import _clear_dependent_caches
    _clear_dependent_caches()


def hysteresis() -> float:
    return _HYSTERESIS


def _choose(kind: str, mode_costs: dict[str, float], platform: str,
            bucket: tuple) -> str:
    """Argmin over mode_costs with the hysteresis band applied."""
    best = min(mode_costs, key=mode_costs.get)
    band = _HYSTERESIS
    if band <= 0.0:
        return best
    key = (kind, _table_key(platform), tuple(sorted(mode_costs)), bucket)
    with _lock:
        prev = _choice_memo.get(key)
        if (prev is not None and prev in mode_costs
                and mode_costs[best] >= mode_costs[prev] / (1.0 + band)):
            best = prev
        if len(_choice_memo) >= _MEMO_MAX and key not in _choice_memo:
            _choice_memo.clear()    # tiny table; wholesale reset is fine
        _choice_memo[key] = best
    return best


def _bucket(*dims: int) -> tuple:
    """Power-of-two shape bucket: hysteresis memory is per dispatch
    SIZE CLASS, not per exact shape (the jit caches bucket the same
    way via pad_pow2)."""
    return tuple(max(int(d), 1).bit_length() for d in dims)


def _log2(n: int) -> int:
    return max(int(math.ceil(math.log2(max(n, 2)))), 1)


def _dot(features: dict[str, float], platform: str) -> float:
    c = costs(platform)
    return sum(units * c[term] for term, units in features.items())


# --------------------------------------------------------------------- #
# Feature vectors: unit counts per (kernel axis, mode).                 #
#                                                                       #
# predict_* == dot(features_*, costs) BY CONSTRUCTION — the fitter      #
# (ops/calibrate.py) regresses measured device time onto these same     #
# vectors, so a fitted constant means exactly what the predictor        #
# consumes.  Keep every form LINEAR in the constants.                   #
# --------------------------------------------------------------------- #

_SUB_K = 32     # sub-block lane width, mirrored from ops.downsample


def features_search(mode: str, s: int, n: int, e: int
                    ) -> dict[str, float]:
    """Unit counts for one edge search: idx[S, E] from [S, N] sorted
    timestamps."""
    if mode == "scan":
        return {"gather_round": float(s * e * _log2(n))}
    if mode == "compare_all":
        return {"cmp_cell": float(s * n * e)}
    if mode == "hier":
        k = _SUB_K
        return {"hier_cell": float(s * ((n // k) + k) * e)}
    raise ValueError("unknown search mode: " + mode)


def features_scan(mode: str, s: int, n: int, e: int) -> dict[str, float]:
    """Unit counts for one windowed-sum pass over [S, N]."""
    if mode == "flat":
        return {"scan_f64": float(s * n), "win_gather": float(s * e)}
    if mode == "blocked":
        # two-level scan: same element count, measured slightly slower
        # than flat on both platforms (r3 chip: 0.600 vs 0.568)
        return {"scan_f64": 1.06 * s * n, "win_gather": 1.06 * s * e}
    if mode == "subblock":
        k = _SUB_K
        return {"elem_f64": float(s * n + s * e * k),  # reduce + remainder
                "scan_f64": float(s * (n // k)),       # 1/32-length cumsum
                "win_gather": float(s * e)}
    if mode == "subblock2":
        k = _SUB_K
        # within-block inclusive prefixes (block sums fall out of the
        # last lane) + ONE element gather per edge — no [S, E, K]
        # remainder intermediate, but the prefix pass has its own
        # platform-dependent cost (serial-ish on CPU)
        return {"sub2_elem": float(s * n),
                "scan_f64": float(s * (n // k)),
                "win_gather": float(s * e)}
    raise ValueError("unknown scan mode: " + mode)


def features_extreme(mode: str, s: int, n: int, e: int
                     ) -> dict[str, float]:
    """Unit counts for one min/max pass over [S, N]."""
    if mode == "scan":
        return {"ext_scan_elem": float(s * n)}
    if mode == "segment":
        return {"ext_seg_elem": float(s * n)}
    if mode == "subblock":
        k = _SUB_K
        # sub-block reduces + a 1/32-length reset-scan + per-edge
        # boundary-lane masked reduces (the term that loses it the
        # headline shape: measured 0.83 vs scan's 0.52 s/dispatch)
        return {"elem_f64": float(s * n),
                "ext_scan_elem": float(s * (n // k)),
                "ext_boundary_cell": float(s * e * k)}
    raise ValueError("unknown extreme mode: " + mode)


def features_group(mode: str, s: int, w: int, g: int
                   ) -> dict[str, float]:
    """Unit counts for one group reduce: [S, W] + gid[S] -> [G, W]."""
    if mode == "segment":
        return {"seg_scatter": float(s * w)}
    if mode == "matmul":
        return {"mxu_cell": float(g * s * w)}
    if mode == "sorted":
        return {"sorted_grid": float(s * w)}
    if mode == "sorted2":
        return {"sorted2_grid": float(s * w)}
    raise ValueError("unknown group mode: " + mode)


def cost_features(kind: str, mode: str, s: int, n: int, e: int,
                  g: int = 1) -> dict[str, float]:
    """One entry point over the four axes ('search' | 'scan' |
    'extreme' | 'group').  For 'group', `n` is the grid width W."""
    if kind == "search":
        return features_search(mode, s, n, e)
    if kind == "scan":
        return features_scan(mode, s, n, e)
    if kind == "extreme":
        return features_extreme(mode, s, n, e)
    if kind == "group":
        return features_group(mode, s, n, g)
    raise ValueError("unknown kernel axis: " + kind)


# -- edge search: idx[S, E] from [S, N] sorted timestamps -------------- #

def predict_search(mode: str, s: int, n: int, e: int,
                   platform: str) -> float:
    return _dot(features_search(mode, s, n, e), platform)


def choose_search(s: int, n: int, e: int, platform: str,
                  candidates: list[str]) -> str:
    return _choose("search",
                   {m: predict_search(m, s, n, e, platform)
                    for m in candidates},
                   platform, _bucket(s, n, e))


# -- prefix scan: windowed sums over [S, N] ---------------------------- #

def predict_scan(mode: str, s: int, n: int, e: int,
                 platform: str) -> float:
    return _dot(features_scan(mode, s, n, e), platform)


def choose_scan(s: int, n: int, e: int, platform: str,
                candidates: list[str]) -> str:
    return _choose("scan",
                   {m: predict_scan(m, s, n, e, platform)
                    for m in candidates},
                   platform, _bucket(s, n, e))


# -- extreme (min/max) over [S, N] ------------------------------------- #

def predict_extreme(mode: str, s: int, n: int, e: int,
                    platform: str) -> float:
    return _dot(features_extreme(mode, s, n, e), platform)


def choose_extreme(s: int, n: int, e: int, platform: str,
                   candidates: list[str]) -> str:
    return _choose("extreme",
                   {m: predict_extreme(m, s, n, e, platform)
                    for m in candidates},
                   platform, _bucket(s, n, e))


# -- group reduce: [S, W] + gid[S] -> [G, W] --------------------------- #

def predict_group(mode: str, s: int, w: int, g: int,
                  platform: str) -> float:
    return _dot(features_group(mode, s, w, g), platform)


def choose_group(s: int, w: int, g: int, platform: str,
                 candidates: list[str]) -> str:
    return _choose("group",
                   {m: predict_group(m, s, w, g, platform)
                    for m in candidates},
                   platform, _bucket(s, w, g))


# -- out-of-core tiled execution (ops/tiling.py) ----------------------- #

def features_tiled(s: int, w: int, g: int, n_tiles: int, n_stripes: int,
                   spill_bytes: int, dispatches: int) -> dict[str, float]:
    """Unit counts for the tiled OVERHEAD of one [s, w] -> [g, w] plan:
    the spill-pool round trip of the full partial grid plus the extra
    launches a tiled plan issues (per-tile chunk folds + finishes, per-
    stripe tail dispatches).  The streamed compute itself is priced by
    the same stage features a resident plan uses (obs.jaxprof) — this
    vector is strictly the delta, so the fitter can regress the spill
    constants from (tiled actual - resident prediction) residuals
    without the compute terms aliasing them.  Linear in the constants
    by construction: `predict_tiled == dot(features_tiled, costs)`.
    """
    mb = spill_bytes / 2.0**20
    return {"spill_write_mb": mb,
            "spill_read_mb": mb,
            "tile_dispatch": float(max(dispatches,
                                       n_tiles + n_stripes))}


def predict_tiled(s: int, w: int, g: int, n_tiles: int, n_stripes: int,
                  spill_bytes: int, dispatches: int,
                  platform: str) -> float:
    """Predicted seconds of tiled-execution OVERHEAD (spill + extra
    dispatches) on top of the plan's ordinary compute prediction."""
    return _dot(features_tiled(s, w, g, n_tiles, n_stripes, spill_bytes,
                               dispatches), platform)


# -- rollup lanes (storage/rollup.py) ---------------------------------- #

# bytes per lane cell (sum f64 + count i32 + min f64 + max f64),
# mirrored from storage.rollup.LANE_CELL_BYTES without the import
# (storage stays numpy-only; a drift is a wrong estimate, not a wrong
# answer)
_LANE_CELL_BYTES = 28


def features_lane(s: int, w: int, k: int) -> dict[str, float]:
    """Unit counts for serving one [s series, w windows] grid from a
    rollup lane: the host assembly + k-cell re-reduce touches
    s * w * k cells.  The downsample/scan of the raw points — the term
    a lane hit ELIMINATES — is deliberately absent; the caller adds
    the tail stages (rate/group/aggregate) from the same
    stage_breakdown either side pays.  Linear in the constants:
    ``predict_lane == dot(features_lane, costs)``."""
    mb = s * w * max(k, 1) * _LANE_CELL_BYTES / 2.0 ** 20
    return {"lane_assemble_mb": mb}


def predict_lane(s: int, w: int, k: int, platform: str) -> float:
    """Predicted seconds of the lane-serve assembly for [s, w] at k
    cells per window."""
    return _dot(features_lane(s, w, k), platform)


def features_lane_build(s: int, cells: int) -> dict[str, float]:
    """Unit counts for one maintenance block build over s series x
    `cells` lane cells (the Storyboard selection's amortization
    side)."""
    return {"lane_build_cell": float(s * max(cells, 1))}


def predict_lane_build(s: int, cells: int, platform: str) -> float:
    return _dot(features_lane_build(s, cells), platform)


# -- fused multi-query dispatch (query/batcher.py) ---------------------- #

def features_stacked(q: int, s: int, n: int, w: int, g: int
                     ) -> dict[str, float]:
    """Unit counts for the batching OVERHEAD of one stacked [Q, S, W]
    dispatch: the single launch floor plus the host-side stack/unpack
    traffic (each member's [S, N] input cells copied into the stacked
    batch and its [G, W] output slice copied back out).  The members'
    compute itself is priced by the same stage features a solo plan
    uses (obs.jaxprof) — this vector is strictly the delta, so the
    fitter could regress the stacking constants from residuals without
    the compute terms aliasing them.  Linear in the constants by
    construction: ``predict_stacked == dot(features_stacked, costs)``.
    """
    return {"stacked_dispatch": 1.0,
            "stacked_cell": float(q * (s * n + g * w))}


def predict_stacked(q: int, s: int, n: int, w: int, g: int,
                    platform: str) -> float:
    """Predicted seconds of stacked-execution overhead (one launch
    floor + q members' stack/unpack traffic)."""
    return _dot(features_stacked(q, s, n, w, g), platform)


def coalesce_worthwhile(compute_s: float, s: int, n: int, w: int,
                        g: int, platform: str, factor: float) -> bool:
    """The coalesce-vs-dispatch-now verdict for ONE plan, from the
    fitted constants (the Factor-Windows cost-based-rewrite framing:
    price the rewrite, don't hardcode a batch size).  A plan is
    DISPATCH-BOUND — worth stacking — when its predicted monolithic
    compute plus its per-member stack/unpack overhead stays within
    ``factor`` x the per-dispatch floor the stacking amortizes; a
    compute-bound plan gains nothing from sharing a launch and
    dispatches now.  Deterministic in (shape, cost table, factor), so
    the explain engine reaches the same verdict the executor does."""
    c = costs(platform)
    member_s = float(s * n + g * w) * c["stacked_cell"]
    return compute_s + member_s <= factor * c["stacked_dispatch"]
