"""Windowed downsampling as segment reductions over [series, time] batches.

Reference behavior: /root/reference/src/core/Downsampler.java (ValuesInInterval
:292 — per-interval reduce with runDouble semantics, interval start as the
output timestamp :437-449, epoch-aligned ts - ts % interval :452),
DownsamplingSpecification.java (spec grammar "1h-avg[-fill][c]"), and
FillingDownsampler.java (emit empty intervals under non-NONE fill policies).
Downsampled values are always doubles (Downsampler.java:257).

TPU-first design: instead of an iterator per span, every series row maps its
timestamps to window ids; one flattened `segment_sum`-family reduction
computes all (series x window) cells at once.

Compile-stability: only the window *count* and interval are static — the
window origin (query start), calendar edges, and live window count are traced
operands, so a dashboard re-issuing the same query over a sliding time range
hits the jit cache.  Calendar windows arrive as a precomputed edge array
(host computes timezone math, device does searchsorted) — SURVEY.md §7 hard
part (d).
"""

from __future__ import annotations

import os as _os
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from opentsdb_tpu.ops.percentile import EST_LEGACY, EST_R3, EST_R7

# Fill policies (FillPolicy.java:22-27).
FILL_NONE = "none"
FILL_ZERO = "zero"
FILL_NAN = "nan"
FILL_NULL = "null"     # NaN internally; serializer emits nulls
FILL_SCALAR = "scalar"

_I64_MAX = np.iinfo(np.int64).max


def require_x64() -> None:
    """Refuse to plan int64 window math when x64 is disabled.

    The window kernels build jnp.int64 timestamp grids; with
    jax_enable_x64 off JAX silently lowers them to int32 and every ms
    timestamp past 2^31 (≈ Jan 1970 + 25 days) truncates — queries
    return wrong windows with no error.  The ops package __init__
    enables x64 process-wide and TSDB construction re-asserts it
    (tsd.tpu.precision.x64); this guard is the backstop for embedders
    that flip the flag afterwards.  Called from the host-side window
    planners (one attribute read per query plan, nothing on the device
    path)."""
    if not jax.config.jax_enable_x64:
        raise RuntimeError(
            "jax_enable_x64 is disabled: int64 ms-timestamp window math "
            "would silently truncate to int32.  Re-enable x64 (or set "
            "tsd.tpu.precision.x64=true, the default, and construct the "
            "TSDB after any config that disables it).")


def pad_pow2(n: int, floor: int = 8) -> int:
    out = floor
    while out < n:
        out *= 2
    return out


@dataclass(frozen=True)
class WindowSpec:
    """Static window shape: kind + padded count (+ interval for fixed grids).

    The traced counterpart is a dict of device scalars/arrays built by the
    host-side planners below; together they describe the same windows the
    reference's ValuesInInterval walked.
    """
    kind: str           # "fixed" | "edges" | "all"
    count: int          # padded number of windows, static
    interval_ms: int = 0  # fixed grids only


@dataclass(frozen=True)
class FixedWindows:
    """Host plan: epoch-aligned fixed-interval windows over [start, end]."""
    interval_ms: int
    first_window_ms: int
    count: int  # real (unpadded) count

    @staticmethod
    def for_range(start_ms: int, end_ms: int, interval_ms: int) -> "FixedWindows":
        first = start_ms - (start_ms % interval_ms)
        last = end_ms - (end_ms % interval_ms)
        count = int((last - first) // interval_ms) + 1
        return FixedWindows(interval_ms, first, count)

    def split(self, pad: bool = True) -> tuple[WindowSpec, dict]:
        require_x64()
        padded = pad_pow2(self.count) if pad else self.count
        return (WindowSpec("fixed", padded, self.interval_ms),
                {"first": jnp.asarray(self.first_window_ms, jnp.int64),
                 "nwin": jnp.asarray(self.count, jnp.int32)})


@dataclass(frozen=True)
class EdgeWindows:
    """Host plan: calendar windows from precomputed edges[W+1]."""
    edges: tuple  # ints; window w spans [edges[w], edges[w+1])

    @property
    def count(self) -> int:
        return len(self.edges) - 1

    def split(self, pad: bool = True) -> tuple[WindowSpec, dict]:
        require_x64()
        w = self.count
        padded = pad_pow2(w) if pad else w
        edges = np.full(padded + 1, _I64_MAX, dtype=np.int64)
        edges[:w + 1] = self.edges
        return (WindowSpec("edges", padded),
                {"edges": jnp.asarray(edges),
                 "nwin": jnp.asarray(w, jnp.int32)})


@dataclass(frozen=True)
class AllWindow:
    """Host plan: the "0all" run-all window spanning [query_start, query_end)."""
    query_start_ms: int
    query_end_ms: int

    @property
    def count(self) -> int:
        return 1

    def split(self, pad: bool = True) -> tuple[WindowSpec, dict]:
        require_x64()
        return (WindowSpec("all", 1),
                {"qstart": jnp.asarray(self.query_start_ms, jnp.int64),
                 "qend": jnp.asarray(self.query_end_ms, jnp.int64),
                 "nwin": jnp.asarray(1, jnp.int32)})


# shape: ts[S,N] any, wargs.ts_base[] i64 -> [S,N] i64
def _absolute_ts(ts, wargs: dict):
    """Reconstruct absolute int64 timestamps from a pre-compacted batch.

    Device-cache hits can arrive as int32 offsets from wargs["ts_base"]
    (the per-point compaction pass moved into the cache's gather
    dispatch); paths that need absolute time (the segment fallback, edge
    grids) lift back to int64 here.  int64 batches pass through.
    """
    if ts.dtype == jnp.int32 and "ts_base" in wargs:
        return ts.astype(jnp.int64) + wargs["ts_base"]
    return ts


# shape: ts[S,N] any, wargs.first[] i64, wargs.edges[*] i64
# shape: wargs.qstart[] i64, wargs.qend[] i64 -> [S,N] i64
def window_ids(ts, spec: WindowSpec, wargs: dict):
    """Window index per point; negative / >= count means outside any window."""
    ts = _absolute_ts(ts, wargs)
    if spec.kind == "fixed":
        return ((ts - wargs["first"]) // spec.interval_ms).astype(jnp.int64)
    if spec.kind == "edges":
        edges = wargs["edges"]
        return jnp.searchsorted(edges, ts, side="right").astype(jnp.int64) - 1
    if spec.kind == "all":
        inside = (ts >= wargs["qstart"]) & (ts < wargs["qend"])
        return jnp.where(inside, 0, -1).astype(jnp.int64)
    raise ValueError("Unknown window kind: " + spec.kind)


# shape: wargs.first[] i64, wargs.edges[*] i64, wargs.qstart[] i64 -> [W] i64
def window_timestamps(spec: WindowSpec, wargs: dict):
    """Representative (start-of-interval) timestamp per window [count]."""
    if spec.kind == "fixed":
        return wargs["first"] + jnp.arange(spec.count, dtype=jnp.int64) \
            * spec.interval_ms
    if spec.kind == "edges":
        return wargs["edges"][:spec.count]
    if spec.kind == "all":
        return wargs["qstart"][None]
    raise ValueError("Unknown window kind: " + spec.kind)


# Downsample functions served by the sorted prefix-sum fast path (additive
# moments only; rank/order functions keep segment reductions).
PREFIX_AGGS = frozenset(
    {"sum", "zimsum", "pfsum", "count", "avg", "squareSum", "dev"})

# min/max ride a scatter-free segmented reset-scan (sorted rows make each
# window a contiguous run; an associative_scan that resets at run starts
# replaces the serializing segment scatter).  "segment" keeps the scatter
# form — faster on CPU where scatters are cheap.  "subblock" removes the
# full-length scan too (the r4 subblock-sum idea applied to extremes):
# 32-point sub-block reduces, a reset-scan over the [S, N/32] sub-block
# extremes for each window's interior, and 32-wide masked reduces over
# the two boundary sub-blocks.  The chip A/B decides the default.
EXTREME_AGGS = frozenset({"min", "mimmin", "max", "mimmax"})
_EXTREME_MODES = ("auto", "scan", "segment", "subblock")
_EXTREME_MODE = (_os.environ.get("TSDB_EXTREME_MODE")
                 if _os.environ.get("TSDB_EXTREME_MODE")
                 in _EXTREME_MODES else "auto")


def set_extreme_mode(mode: str) -> None:
    """'auto' | 'scan' | 'segment' | 'subblock' — min/max downsample
    strategy ('auto' = shape/platform cost model, ops.costmodel); clears
    caches."""
    global _EXTREME_MODE
    if mode not in _EXTREME_MODES:
        raise ValueError("extreme mode must be one of %r"
                         % (_EXTREME_MODES,))
    _EXTREME_MODE = mode
    _clear_dependent_caches()


# shape: wargs.first[] i64, wargs.edges[*] i64 -> [W1] i64
def window_edges(ts_dtype, spec: WindowSpec, wargs: dict):
    """Edge timestamps e[W+1]; window w spans [e[w], e[w+1])."""
    if spec.kind == "fixed":
        return wargs["first"] + jnp.arange(
            spec.count + 1, dtype=jnp.int64) * spec.interval_ms
    if spec.kind == "edges":
        return wargs["edges"]
    if spec.kind == "all":
        return jnp.stack([wargs["qstart"], wargs["qend"]])
    raise ValueError("Unknown window kind: " + spec.kind)


# Prefix-scan strategy for the hot path.  "flat" = one cumsum over the full
# time axis; "blocked" = two-level scan (intra-block cumsum + tiny block-
# offset scan) — shorter scan segments, same memory.  "subblock" = no
# full-length scan at all: exact f64 sums of 32-point sub-blocks (a tree
# reduce — one cheap pass), a cumsum over the [S, N/32] sub-block sums
# (1/32 the scan work), and per-edge remainders as 32-wide masked dots.
# Rationale (r4 chip attribution, tools/stage_bench.py): a full-length
# f64 cumsum costs 95ms/67M pts on the chip while an f64 elementwise
# pass costs 14ms — the emulated-f64 SCAN is the bottleneck, not the
# data traffic, so the subblock form does 1/32 of it.
# Measured on the real chip (BENCH_CONFIGS_r03.json bench_prefix stage):
# flat 0.568s vs blocked 0.600s per 67M-pt dispatch at int32 — XLA's
# native cumsum lowering beats the hand-blocked form on TPU.
#
# Env overrides (TSDB_SCAN_MODE / TSDB_SEARCH_MODE / TSDB_EXTREME_MODE,
# read once at import): lets the one-command measurement session feed
# bench_prefix's A/B winners into the later stages without editing
# source mid-run.  Invalid values are ignored (defaults win).
_SCAN_MODES = ("auto", "flat", "blocked", "subblock", "subblock2")
_SCAN_MODE = (_os.environ.get("TSDB_SCAN_MODE")
              if _os.environ.get("TSDB_SCAN_MODE") in _SCAN_MODES
              else "auto")
_SCAN_BLOCK = 512
_SUB_K = 32      # subblock scan / hier search granule (power of two)

_I32_BIG = np.int64(2**31 - 2)
# Pad sentinel for int32 batches — the exact value the device cache's
# ts_base gather writes (storage.device_cache.I32_PAD_TS mirrors this;
# a parity test pins the pair).  Clean-batch detection compares against
# it and pad sorting relies on it exceeding every re-based edge.
_I32_PAD = np.int32(2**31 - 2)


_COMPACT_ENABLED = True

# Edge-position search strategy.  "scan" = jnp.searchsorted's binary
# search: log2(N) rounds of gathers — TPU gathers serialize, so for the
# [S, W+1]-edges-into-[S, N] search this is a chain of ~17 gather passes.
# "compare_all" = one broadcasted compare + sum-reduce (idx[s, w] =
# #points < edge): O(N*W) VPU compares that XLA fuses into a streaming
# reduction over W-tiles — no gathers at all.  "hier" = two-level
# compare_all: count sub-block FIRST timestamps below each edge (rows are
# time-sorted, so every earlier sub-block is entirely below the edge),
# then resolve the one boundary sub-block with a 32-wide compare — the
# compare work drops from O(N*W) to O(N*W/32 + 32*W).  r3/r4 chip data:
# scan 182ms, compare_all ~116ms for the 65536x513 headline search.
_SEARCH_MODES = ("auto", "scan", "compare_all", "hier")
_SEARCH_MODE = (_os.environ.get("TSDB_SEARCH_MODE")
                if _os.environ.get("TSDB_SEARCH_MODE")
                in _SEARCH_MODES else "auto")


def set_search_mode(mode: str) -> None:
    """'auto' | 'scan' | 'compare_all' | 'hier' — edge-search strategy
    ('auto' = shape/platform cost model, ops.costmodel); clears
    caches."""
    global _SEARCH_MODE
    if mode not in _SEARCH_MODES:
        raise ValueError("search mode must be one of %r" % (_SEARCH_MODES,))
    _SEARCH_MODE = mode
    _clear_dependent_caches()

# Value-accumulation precision for the prefix hot path.  "double" (default)
# is the numeric contract — the reference accumulates in Java double
# (Downsampler.java:257) and the golden tests pin 1e-9 agreement.  "single"
# runs the cumsum in float32 (native TPU ALUs; f64 is emulated) at
# ~n_points_per_window * 6e-8 relative error — a documented fast mode for
# dashboards, never the default.
_VALUE_PRECISION = "double"


# bumped on every mode-policy change (all of them funnel through
# _clear_dependent_caches): the planner snapshots it before a dispatch
# and drops the calibration-ring entry if it moved mid-query — the
# recomputed decision report could otherwise pair one mode's measured
# time with another mode's feature vector
_MODE_POLICY_EPOCH = 0


def mode_policy_epoch() -> int:
    return _MODE_POLICY_EPOCH


def _clear_dependent_caches() -> None:
    """Drop every compiled program that baked in the hot-path toggles.

    The toggles are read at TRACE time; a cached program keeps its config
    forever, so flipping a toggle without clearing these would silently
    mix configs between already-seen and new query shapes.
    """
    global _MODE_POLICY_EPOCH
    # the epoch must move BEFORE any compiled program is dropped: a
    # planner that snapshots the epoch mid-splice sees it already
    # bumped and discards its calibration entry, instead of pairing a
    # stale program's timing with the new policy (checked contract)
    # order: epoch-bump before jit-cache-splice
    _MODE_POLICY_EPOCH += 1                          # order-event: epoch-bump
    from opentsdb_tpu.ops import pipeline, streaming
    for fn in (pipeline._jitted, pipeline._jitted_rollup_avg,
               pipeline._jitted_group, pipeline._jitted_grid_tail,
               pipeline._jitted_downsample_grid,
               pipeline._jitted_group_rollup_avg,
               pipeline._jitted_union_batch,
               pipeline._jitted_stacked_group,
               streaming._jitted_update,
               streaming._jitted_update_sliced, streaming._jitted_finish):
        fn.clear_cache()                             # order-event: jit-cache-splice
    try:
        from opentsdb_tpu.parallel import sharded
        sharded.sharded_query_pipeline.cache_clear()  # order-event: jit-cache-splice
        sharded._stream_update_fn.cache_clear()
        sharded._stream_update_sliced_fn.cache_clear()
        sharded._stream_finish_fn.cache_clear()
    except ImportError:  # parallel extras absent in minimal installs
        pass


def set_scan_mode(mode: str) -> None:
    """'auto' | 'flat' | 'blocked' | 'subblock' | 'subblock2' —
    benchmarking/ops hook ('auto' = shape/platform cost model); clears
    affected jit caches."""
    global _SCAN_MODE
    if mode not in _SCAN_MODES:
        raise ValueError("scan mode must be one of %r" % (_SCAN_MODES,))
    _SCAN_MODE = mode
    _clear_dependent_caches()


def set_ts_compaction(enabled: bool) -> None:
    """Toggle int32 timestamp compaction — benchmarking hook; clears
    affected jit caches."""
    global _COMPACT_ENABLED
    _COMPACT_ENABLED = bool(enabled)
    _clear_dependent_caches()


def set_value_precision(mode: str) -> None:
    """'double' | 'single' — prefix-path accumulation dtype; clears
    affected jit caches.  See _VALUE_PRECISION above for the contract."""
    global _VALUE_PRECISION
    if mode not in ("double", "single"):
        raise ValueError("precision must be 'double' or 'single'")
    _VALUE_PRECISION = mode
    _clear_dependent_caches()


def _edge_prefix_builder(s: int, n: int, idx):
    """Returns windowed(data): per-window sums via prefix evaluation at the
    searched edge positions idx[S, W+1] (exclusive prefixes differenced).

    flat: materialize cumsum[S, N+1], gather at idx.
    blocked: intra-block cumsum (scan length _SCAN_BLOCK) + cumsum over the
    [S, B] block totals; prefix(p) = block_offset[p // K] + intra[p-1 within
    its block].  Same HBM traffic, much shorter scan dependency chains.
    """
    # only an EXPLICIT "blocked" takes the two-level form ("auto" never
    # picks it: it lost the r3 chip race, 0.600 vs 0.568)
    if _SCAN_MODE != "blocked" or n % _SCAN_BLOCK or n <= _SCAN_BLOCK:
        def windowed(data):
            csum = jnp.concatenate(
                [jnp.zeros((s, 1), data.dtype),
                 jnp.cumsum(data, axis=1)], axis=1)
            at = jnp.take_along_axis(csum, idx, axis=1)
            return at[:, 1:] - at[:, :-1]
        return windowed

    k = _SCAN_BLOCK
    b = n // k
    blk = idx // k               # block containing each edge position
    off = idx - blk * k          # position within the block
    # Exclusive intra-block prefix at `off` = inclusive intra cumsum at
    # off-1; off==0 contributes nothing.  Flatten (block, slot) so one
    # gather serves both lookups.
    gather_pos = jnp.clip(blk * k + off - 1, 0, n - 1)
    zero_intra = off == 0
    safe_blk = jnp.clip(blk, 0, b)   # idx can be n -> blk == b (offset row)

    def windowed(data):
        blocks = data.reshape(s, b, k)
        intra = jnp.cumsum(blocks, axis=2)
        bsum = intra[:, :, -1]
        boff = jnp.concatenate(
            [jnp.zeros((s, 1), data.dtype), jnp.cumsum(bsum, axis=1)],
            axis=1)                                      # [S, B+1]
        base = jnp.take_along_axis(boff, safe_blk, axis=1)
        part = jnp.take_along_axis(intra.reshape(s, n), gather_pos, axis=1)
        part = jnp.where(zero_intra, jnp.zeros_like(part), part)
        at = base + part
        return at[:, 1:] - at[:, :-1]
    return windowed


def _edge_subblock_builder(s: int, n: int, idx):
    """windowed(data) with NO full-length scan (scan mode "subblock").

    prefix(p) decomposes at the 32-point sub-block containing p: the sum
    of every earlier sub-block (an exact f64 tree reduce + a cumsum over
    [S, N/32] sub-block sums — 1/32 of the flat form's scan work) plus a
    32-wide masked dot over the boundary sub-block, gathered as ONE
    contiguous [1, K] slice per edge (vector loads, not 32 scalar
    gathers).  Chip rationale: the emulated-f64 full-length cumsum costs
    ~7x an elementwise f64 pass (tools/stage_bench.py r4) — this form
    keeps the same f64 accumulation contract with 1/32 of the scan.
    """
    k = _SUB_K
    nb = n // k
    blk = idx // k                     # [S, W+1] boundary sub-block
    off = idx - blk * k                # position within it
    safe_blk = jnp.clip(blk, 0, nb - 1)
    lanes = jnp.arange(k, dtype=off.dtype)

    def windowed(data):
        d3 = data.reshape(s, nb, k)
        ssum = d3.sum(axis=2)                                   # [S, nb]
        scum = jnp.concatenate(
            [jnp.zeros((s, 1), data.dtype), jnp.cumsum(ssum, axis=1)],
            axis=1)                                             # [S, nb+1]
        base = jnp.take_along_axis(scum, blk, axis=1)
        bvals = jnp.take_along_axis(
            d3, safe_blk[:, :, None], axis=1)                   # [S, W+1, K]
        # blk == nb (edge past every point) has off == 0, so the masked
        # dot over the clipped gather contributes nothing there.
        rem = jnp.where(lanes[None, None, :] < off[:, :, None],
                        bvals, 0).sum(axis=2)
        at = base + rem
        return at[:, 1:] - at[:, :-1]
    return windowed


def _edge_subblock2_builder(s: int, n: int, idx):
    """subblock variant: within-block inclusive prefixes + ONE scalar
    gather per edge (scan mode "subblock2").

    Same decomposition as _edge_subblock_builder, but the boundary
    remainder is read from a precomputed within-block prefix
    (cumsum along the K axis — a depth-log2(K) scan over the full data,
    cheap and parallel) with a single element gather per edge, instead
    of gathering a [*, K] lane per edge and masked-dotting it.  Trades
    one extra full-size vector pass for 1/K of the per-edge gather
    volume and no [S, W+1, K] intermediate — so it has no
    _subblock_edges_fit constraint.  The chip race decides which wins.
    """
    k = _SUB_K
    nb = n // k
    blk = idx // k                     # [S, W+1] boundary sub-block
    off = idx - blk * k                # position within it
    safe_blk = jnp.clip(blk, 0, nb - 1)

    def windowed(data):
        d3 = data.reshape(s, nb, k)
        prefix3 = jnp.cumsum(d3, axis=2)            # within-block incl.
        ssum = prefix3[:, :, -1]                    # block sums for free
        scum = jnp.concatenate(
            [jnp.zeros((s, 1), data.dtype), jnp.cumsum(ssum, axis=1)],
            axis=1)                                             # [S, nb+1]
        base = jnp.take_along_axis(scum, blk, axis=1)
        prefix = prefix3.reshape(s, n)
        # off == 0 (edge at a block boundary, incl. blk == nb past every
        # point) contributes no remainder; otherwise prefix[blk*K+off-1]
        pos = jnp.clip(safe_blk * k + off - 1, 0, n - 1)
        rem = jnp.where(off > 0,
                        jnp.take_along_axis(prefix, pos, axis=1), 0)
        at = base + rem
        return at[:, 1:] - at[:, :-1]
    return windowed


def precompact_base(spec: WindowSpec, first_window_ms) -> int | None:
    """The int32 pre-compaction base for a batch source, or None.

    When a fixed grid provably spans < 2^31 ms, batch builders (the
    device cache's gather) may deliver timestamps as int32 offsets from
    this base — the per-point compaction pass then disappears from the
    query dispatch entirely (r4 chip attribution: 74ms of the headline
    dispatch was the ts - first sub+clip+cast over [S, N] int64).
    """
    if (_COMPACT_ENABLED and spec.kind == "fixed"
            and first_window_ms is not None
            and (spec.count + 1) * spec.interval_ms < 2**31 - 2):
        return int(first_window_ms)
    return None


# shape: ts[S,N] any, wargs.first[] i64, wargs.ts_base[] i64
def _compact_ts(ts, spec: WindowSpec, wargs: dict):
    """(ts', edges') for the prefix path: int32 ms offsets when
    the whole fixed-window grid provably spans < 2^31 ms.

    TPUs have no native 64-bit integer ALU — every compare in the
    binary search and every window-id division runs emulated on int64.
    Fixed grids know their span statically (count * interval); offsets
    from the traced window origin fit int32, and clipping keeps the
    int64-max padding timestamps sorted (they land beyond the last edge,
    exactly like before).  Calendar/all grids keep int64.

    Pre-compacted batches (int32 offsets from wargs["ts_base"], built by
    the device cache's gather dispatch) skip the per-point pass: only
    the [W+1] edge vector is re-based here.
    """
    if ts.dtype == jnp.int32 and "ts_base" in wargs:
        edges64 = window_edges(jnp.int64, spec, wargs)
        edges32 = jnp.clip(edges64 - wargs["ts_base"],
                           -_I32_BIG, _I32_BIG).astype(jnp.int32)
        return ts, edges32
    edges64 = window_edges(ts.dtype, spec, wargs)
    if not _COMPACT_ENABLED or spec.kind != "fixed" or \
            (spec.count + 1) * spec.interval_ms >= 2**31 - 2:
        return ts, edges64
    first = wargs["first"]
    ts32 = jnp.clip(ts - first, -_I32_BIG, _I32_BIG).astype(jnp.int32)
    edges32 = jnp.clip(edges64 - first, -_I32_BIG, _I32_BIG).astype(jnp.int32)
    return ts32, edges32


# shape: ts[S,N] any, val[S,N] f64, mask[S,N] bool -> ([S,W] f64, [S,W] any)
def _prefix_downsample(ts, val, mask, agg_name: str, spec: WindowSpec,
                       wargs: dict):
    """Scatter-free windowed moments for sorted rows.

    TPU scatters (`segment_sum`) serialize; for the additive-moment family
    the batch layout contract (rows time-sorted, pads at int64 max) lets
    window reductions run as exclusive prefix sums differenced at
    binary-searched window edges — dense vector work the VPU streams
    through.  Non-participating slots (masked or NaN) contribute zero to
    every cumulative sum, so correctness needs only ts-sortedness.

    Hot-path dtypes: timestamps compact to int32 offsets when the grid
    span allows (no 64-bit emulation in the search), counts accumulate in
    int32 (N < 2^31 per row); VALUES stay float64 — the reference's Java
    double accumulation is the numeric contract (Downsampler.java:257).

    Returns (out[S, W], count[S, W]).
    """
    w = spec.count
    vf, ok, cts, _idx, windowed, count = _window_scan_setup(ts, val, mask,
                                                            spec, wargs)
    fdtype = vf.dtype
    acc_dtype = jnp.float32 if _VALUE_PRECISION == "single" else fdtype
    v0 = jnp.where(ok, vf, 0).astype(acc_dtype)
    if agg_name == "count":
        return count.astype(fdtype), count
    total = windowed(v0)
    safe = jnp.maximum(count, 1)
    if agg_name in ("sum", "zimsum", "pfsum"):
        return total.astype(fdtype), count
    if agg_name == "avg":
        return (total / safe).astype(fdtype), count
    if agg_name == "squareSum":
        return windowed(v0 * v0).astype(fdtype), count
    if agg_name == "dev":
        # Two-pass centered moment (matches the segment path's numerics):
        # per-point window mean via the same edge-search, then one more
        # prefix pass over the centered squares.
        mean = total / safe
        win = jnp.clip(_window_ids_fast(ts, cts, spec, wargs), 0, w - 1)
        mean_pp = jnp.take_along_axis(mean, win, axis=1)
        centered = jnp.where(ok, vf - mean_pp, 0).astype(acc_dtype)
        m2 = windowed(centered * centered)
        return jnp.where(count >= 2,
                         jnp.sqrt(m2 / jnp.maximum(count - 1, 1))
                         .astype(fdtype), 0.0), count
    raise KeyError("No prefix-sum path for: " + agg_name)


# shape: ts[S,N] any, cts[S,N] any, wargs.first[] i64, wargs.ts_base[] i64 -> [S,N] any
def _window_ids_fast(ts, cts, spec: WindowSpec, wargs: dict):
    """Per-point window ids, preferring the compacted int32 timestamps.

    On fixed grids the id is a division; doing it on the int32 offsets
    (cts, already relative to the window origin when compacted — dtype
    is the compaction marker) avoids a full [S, N] pass of emulated
    int64 arithmetic.  Non-fixed grids keep the generic search.
    """
    if spec.kind == "fixed" and cts.dtype == jnp.int32:
        if ts.dtype == jnp.int32 and "ts_base" in wargs:
            # pre-compacted batch: cts is relative to ts_base, not to the
            # window origin — re-base with one int32 scalar subtract.
            # The i64 difference is clipped before narrowing: today's
            # callers derive ts_base FROM first (delta 0), but a caller
            # handing a stale base from another query's grid would
            # otherwise wrap silently and scatter points into random
            # windows; saturated deltas land everything out-of-range
            # instead, which the valid-window mask then drops.
            shift = jnp.clip(wargs["first"] - wargs["ts_base"],
                             -_I32_BIG, _I32_BIG).astype(jnp.int32)
            return (cts - shift) // jnp.int32(spec.interval_ms)
        return cts // jnp.int32(spec.interval_ms)
    return window_ids(ts, spec, wargs)


# Dense-vs-binary search crossover.  Per edge, compare_all costs N
# compares, hier N/32 compares, the binary search log2(N) serialized
# gathers; every form is linear in the edge count, so the decision is a
# RATIO of per-edge costs, independent of W.  The r4 chip attribution
# measured ~20ns/gather (scan: 182ms / 8.9M gathers) vs ~3.4ps/compare
# (compare_all: 116ms / 34e9) — a ~5900x gap; 4096 is the conservative
# round-down, placing the compare_all crossover just past the headline's
# N=65536 (where compare_all measured faster) and well before a
# streaming chunk's N=1M (config 2's W~10M grid: a dense search there
# burned the whole 2400s chip budget in r4).
_SEARCH_DEMOTE_RATIO = 4096

# Sub-block remainder forms (hier search, subblock scan/extreme) gather
# one [*, K] lane per edge/window — an [S, W, K] intermediate.  For the
# intended shapes W*K << N (headline: 513 edges x 32 = 2.4% of N); when
# a grid is wider than the data (streaming config 2: W ~ N*10), that
# intermediate EXCEEDS the batch itself and can OOM (a 0.01-scale CPU
# smoke hit a 283GB allocation).  Cap it at this multiple of the data.
_SUBBLOCK_EDGE_FACTOR = 4


def _subblock_edges_fit(n: int, w_edges: int) -> bool:
    return w_edges * _SUB_K <= _SUBBLOCK_EDGE_FACTOR * n


# compare_all's [N, W+1] per-row compare can MATERIALIZE when the
# backend does not fuse the reduce (measured: CPU at N=65536 x 16385
# edges attempted a multi-TB buffer).  Cap the per-row compare matrix;
# the headline shape (65536 x 514 = 34M cells) stays comfortably under.
_COMPARE_ALL_CELL_CAP = 1 << 27

# hier's sub-block-firsts compare is a [N/K, W+1] per-row matrix — 32x
# smaller than compare_all's, but it still materializes where the
# backend does not fuse the compare into its count.  Measured at the
# config-1 shape (N=1M, W=3501: 109M cells/row): 18x slower than the
# binary search on the host lane, and a scoped-vmem compile failure on
# the chip (r04b session, config 1 device lane).  The headline shape
# (2048 x 286 = 0.6M cells/row) sits two orders of magnitude under this
# cap; shapes above it take the binary search.
_HIER_CELL_CAP = 1 << 23


# The dense search forms are ACCELERATOR winners: on the chip their
# compare+count fuses into vmem (r04b: hier 0.416s vs scan 0.590s on the
# headline dispatch), but on CPU the backend materializes the compare
# matrix — measured 70x slower than the binary search at [64, 65536] x
# 514 edges, and 18x end-to-end on the config-1 host lane.  With this
# guard on (production default), any trace executing on CPU — the
# planner's small-query host lane, or a CPU-only process — takes the
# binary search regardless of the configured/env mode.  Tests disable it
# suite-wide (conftest) so CPU CI still exercises the dense kernels'
# correctness at small shapes.
_PLATFORM_MODE_GUARD = True


def set_platform_mode_guard(on: bool) -> None:
    """Enable/disable CPU demotion of dense search modes; clears caches."""
    global _PLATFORM_MODE_GUARD
    _PLATFORM_MODE_GUARD = bool(on)
    _clear_dependent_caches()


def _search_feasible(mode: str, n: int, w_edges: int) -> bool:
    """Hard feasibility for the dense search forms: memory caps on the
    compare intermediates and the per-edge compare-vs-gather cost ratio.
    Shapes outside these bounds demote to the binary scan no matter what
    crowned/auto policy says — a wrong choice here is an OOM or a
    scoped-vmem compile failure, not a slowdown."""
    logn = max(int(np.ceil(np.log2(max(n, 2)))), 1)
    if mode == "compare_all":
        return (n <= _SEARCH_DEMOTE_RATIO * logn
                and n * w_edges <= _COMPARE_ALL_CELL_CAP)
    if mode == "hier":
        return (n % _SUB_K == 0 and n > _SUB_K
                and n // _SUB_K <= _SEARCH_DEMOTE_RATIO * logn
                and (n // _SUB_K) * w_edges <= _HIER_CELL_CAP
                and _subblock_edges_fit(n, w_edges))
    return True


def _search_candidates(n: int, w_edges: int) -> list[str]:
    return [m for m in ("scan", "compare_all", "hier")
            if _search_feasible(m, n, w_edges)]


def _effective_search_mode(s: int, n: int, w_edges: int,
                           platform: str | None = None) -> str:
    """The search mode for this shape: 'auto' (default) ranks the
    feasible modes with the calibrated cost model (ops.costmodel);
    an explicit mode (env/setter — measurement sessions) is honored but
    still demoted to "scan" when infeasible for the shape or when the
    trace executes on CPU (see _PLATFORM_MODE_GUARD — the dense forms'
    compare matrices materialize there).  `platform` defaults to the
    ambient execution platform; the planner's decision report passes
    its per-segment platform explicitly."""
    mode = _SEARCH_MODE
    from opentsdb_tpu.ops.hostlane import execution_platform
    if platform is None:
        platform = execution_platform()
    if mode == "auto":
        if platform == "cpu":
            return "scan"      # dense compares materialize on CPU
        from opentsdb_tpu.ops import costmodel
        return costmodel.choose_search(s, n, w_edges, platform,
                                       _search_candidates(n, w_edges))
    if _PLATFORM_MODE_GUARD and mode != "scan" and platform == "cpu":
        return "scan"
    if not _search_feasible(mode, n, w_edges):
        return "scan"
    return mode


def _scan_candidates(n: int, w_edges: int) -> list[str]:
    sub_ok = n % _SUB_K == 0 and n > _SUB_K
    cands = ["flat"]
    if sub_ok and _subblock_edges_fit(n, w_edges):
        cands.append("subblock")
    if sub_ok:
        cands.append("subblock2")
    return cands


def _effective_scan_mode(s: int, n: int, w_edges: int,
                         platform: str | None = None) -> str:
    """The prefix-scan strategy for this shape: 'auto' ranks the
    feasible modes with the cost model (the sub-block forms need
    K-divisible rows; "subblock" additionally needs the [S, W, K]
    boundary intermediate to fit).  Explicit modes keep their existing
    call-site eligibility fallbacks."""
    mode = _SCAN_MODE
    if mode != "auto":
        return mode
    cands = _scan_candidates(n, w_edges)
    if len(cands) == 1:
        return "flat"
    from opentsdb_tpu.ops.hostlane import execution_platform
    from opentsdb_tpu.ops import costmodel
    return costmodel.choose_scan(
        s, n, w_edges, platform or execution_platform(), cands)


def _extreme_candidates(n: int, w_padded: int) -> list[str]:
    sub_ok = (n % _SUB_K == 0 and n > _SUB_K
              and _subblock_edges_fit(n, w_padded + 1))
    return ["scan", "segment"] + (["subblock"] if sub_ok else [])


def _effective_extreme_mode(n: int, w_padded: int,
                            platform: str | None = None) -> str:
    """The min/max strategy for this shape: 'auto' ranks scan vs segment
    vs (when eligible) subblock with the cost model; an explicit
    "subblock" falls back to "scan" on ineligible shapes — same rule on
    the materialized and streaming paths (they must never drift)."""
    mode = _EXTREME_MODE
    sub_ok = (n % _SUB_K == 0 and n > _SUB_K
              and _subblock_edges_fit(n, w_padded + 1))
    if mode == "auto":
        from opentsdb_tpu.ops.hostlane import execution_platform
        from opentsdb_tpu.ops import costmodel
        return costmodel.choose_extreme(
            1, n, w_padded + 1, platform or execution_platform(),
            _extreme_candidates(n, w_padded))
    if mode == "subblock" and not sub_ok:
        return "scan"
    return mode


def search_decision(s: int, n: int, w_edges: int, platform: str) -> dict:
    """The edge-search strategy decision for one dispatch shape, as the
    trace annotates it: chosen mode, per-candidate predicted ms, and
    where the choice came from.  Recomputes exactly what the kernel's
    trace-time `_effective_search_mode` picks for this platform."""
    from opentsdb_tpu.ops import costmodel
    return _decision_report(
        "search", _effective_search_mode(s, n, w_edges, platform),
        _SEARCH_MODE, _search_candidates(n, w_edges), platform,
        lambda m: costmodel.predict_search(m, s, n, w_edges, platform))


def scan_dispatch_mode(smode: str, n: int, w_edges: int) -> str:
    """The prefix form that ACTUALLY dispatches for an effective scan
    mode: explicit sub-block/blocked picks fall back to flat on
    ineligible shapes at the kernel call sites (_window_scan_setup /
    _edge_prefix_builder) — the decision report and the calibration
    ring must record the dispatched form, not the configured wish."""
    sub_ok = n % _SUB_K == 0 and n > _SUB_K
    if smode == "subblock" and sub_ok and _subblock_edges_fit(n, w_edges):
        return "subblock"
    if smode == "subblock2" and sub_ok:
        return "subblock2"
    if smode == "blocked" and n % _SCAN_BLOCK == 0 and n > _SCAN_BLOCK:
        return "blocked"
    return "flat"


def scan_decision(s: int, n: int, w_edges: int, platform: str) -> dict:
    """The prefix-scan strategy decision for one dispatch shape (see
    `search_decision`)."""
    from opentsdb_tpu.ops import costmodel
    dispatched = scan_dispatch_mode(
        _effective_scan_mode(s, n, w_edges, platform), n, w_edges)
    # every form dispatchable at this shape (blocked is explicit-only —
    # it never wins auto — but it IS a legal dispatch, so the report
    # prices it rather than flagging a forced 'blocked' as infeasible)
    cands = _scan_candidates(n, w_edges)
    if n % _SCAN_BLOCK == 0 and n > _SCAN_BLOCK:
        cands = cands + ["blocked"]
    return _decision_report(
        "scan", dispatched, _SCAN_MODE, cands, platform,
        lambda m: costmodel.predict_scan(m, s, n, w_edges, platform))


def extreme_decision(n: int, w_padded: int, platform: str) -> dict:
    """The min/max strategy decision for one dispatch shape (see
    `search_decision`)."""
    from opentsdb_tpu.ops import costmodel
    return _decision_report(
        "extreme", _effective_extreme_mode(n, w_padded, platform),
        _EXTREME_MODE, _extreme_candidates(n, w_padded), platform,
        lambda m: costmodel.predict_extreme(m, 1, n, w_padded + 1,
                                            platform))


def _decision_report(axis: str, chosen: str, configured: str,
                     candidates: list[str], platform: str,
                     predict) -> dict:
    """Shared decision-report shape (group_agg uses it too): `source`
    says whether the mode came from the costmodel argmin ('auto') or an
    explicit env/config override ('forced'); `calibration` names the
    cost-table layer the argmin consulted (default/file/live);
    `feasible` is False only if a mode outside the feasible candidate
    set would dispatch — the kernels' guards make that unreachable, and
    the planner counts any violation (tsd.costmodel.infeasible)."""
    from opentsdb_tpu.ops import costmodel
    return {
        "axis": axis,
        "mode": chosen,
        "source": "auto" if configured == "auto" else "forced",
        "calibration": costmodel.calibration_source(platform),
        "candidates": {m: round(predict(m) * 1e3, 4)
                       for m in candidates},
        "feasible": chosen in candidates,
    }


def _edge_search(cts, cedges):
    """idx[S, W+1] = per-row count of points strictly below each edge.

    "hier" exploits row sortedness at sub-block granularity: if a
    sub-block's FIRST timestamp is below the edge, every point of every
    EARLIER sub-block is too (each is <= that first) — so one compare+
    count over the [S, N/32] sub-block firsts locates the boundary
    sub-block, and a 32-wide compare over that one (contiguous) sub-block
    finishes the count.  O(N*W/32) compares vs compare_all's O(N*W) and
    scan's log2(N) serialized gather rounds.
    """
    s, n = cts.shape
    mode = _effective_search_mode(s, n, cedges.shape[0])
    if mode == "hier" and n % _SUB_K == 0 and n > _SUB_K:
        k = _SUB_K
        nb = n // k
        c3 = cts.reshape(s, nb, k)
        firsts = c3[:, :, 0]                                     # [S, nb]
        nfull = jnp.sum(firsts[:, :, None] < cedges[None, None, :],
                        axis=1)                                  # [S, W+1]
        blk = jnp.maximum(nfull - 1, 0)     # boundary sub-block (nfull>0)
        bvals = jnp.take_along_axis(c3, blk[:, :, None], axis=1)
        rem = jnp.sum(bvals < cedges[None, :, None], axis=2)
        idx = blk * k + rem
        # int32 like searchsorted's result (n < 2^31): int64 here would
        # push the subblock builder's edge arithmetic onto emulated ALUs
        return jnp.where(nfull == 0, 0, idx).astype(jnp.int32)
    method = ("compare_all" if mode == "compare_all" else "scan")
    return jax.vmap(lambda row: jnp.searchsorted(
        row, cedges, side="left", method=method))(cts)


def _window_scan_setup(ts, val, mask, spec: WindowSpec, wargs: dict):
    """Shared preamble of the sorted-row window kernels: float view, valid
    mask, edge positions, the edge-prefix evaluator, and per-window counts.
    One definition — the prefix and extreme paths must never drift on the
    edge search or the int32 compaction."""
    s, n = ts.shape
    fdtype = val.dtype if jnp.issubdtype(val.dtype, jnp.floating) \
        else jnp.float64
    vf = val.astype(fdtype)
    ok = mask & ~jnp.isnan(vf)
    cts, cedges = _compact_ts(ts, spec, wargs)
    idx = _edge_search(cts, cedges)
    smode = scan_dispatch_mode(_effective_scan_mode(s, n,
                                                    cedges.shape[0]),
                               n, cedges.shape[0])
    if smode == "subblock":
        windowed = _edge_subblock_builder(s, n, idx)
    elif smode == "subblock2":
        # no edges-fit constraint: the remainder reads a same-size
        # prefix array, never an [S, W, K] intermediate
        windowed = _edge_subblock2_builder(s, n, idx)
    else:
        windowed = _edge_prefix_builder(s, n, idx)
    # Per-window counts: for a CLEAN batch — every unmasked slot is a pad
    # (ts at the pad sentinel, beyond the last edge) and no masked value
    # is NaN — the edge positions already count exactly the participating
    # points, so count = diff(idx) and the dedicated int32 cumsum pass (a
    # full [S, N] scan + gather, as expensive as the value scan it sits
    # next to) is skipped.  Batches from build_batch / the device cache
    # are clean by construction; NaN data or exotic masks take the scan.
    # Pre-compacted int32 batches pad at the clip ceiling, not int64 max.
    pad_sentinel = _I32_PAD if ts.dtype == jnp.int32 else _I64_MAX
    clean = ~jnp.any(ok ^ (ts != pad_sentinel))
    count = jax.lax.cond(
        clean,
        lambda: (idx[:, 1:] - idx[:, :-1]).astype(jnp.int64),
        lambda: windowed(ok.astype(jnp.int32)).astype(jnp.int64))
    return vf, ok, cts, idx, windowed, count


def _extreme_downsample(ts, val, mask, spec: WindowSpec, wargs: dict,
                        want_min: bool, want_max: bool):
    """Scatter-free windowed min/max for sorted rows.

    Windows are contiguous runs in a time-sorted row, so the per-window
    extreme is a segmented scan: an inclusive associative scan of
    (value..., new-run flag) where a set flag resets the accumulation —
    the classic segmented-reduce combinator — evaluated by gathering the
    scan at each window's last position (idx[w+1]-1).  No scatter: TPU
    scatters serialize, which is why the additive family left them first
    (VERDICT r1 weak #1); this extends the scatter-free family to the
    extremes.  min and max share ONE scan when both are wanted.

    Returns (lo[S, W] | None, hi[S, W] | None, count[S, W]).
    """
    from jax import lax

    s, n = ts.shape
    vf, ok, cts, idx, _windowed, count = _window_scan_setup(ts, val, mask,
                                                            spec, wargs)
    # run boundaries: window id changes between consecutive points
    win = _window_ids_fast(ts, cts, spec, wargs)
    flags = jnp.concatenate(
        [jnp.ones((s, 1), bool), win[:, 1:] != win[:, :-1]], axis=1)

    carry = ()
    if want_min:
        carry += (jnp.where(ok, vf, jnp.inf),)
    if want_max:
        carry += (jnp.where(ok, vf, -jnp.inf),)
    carry += (flags,)

    def combine(a, b):
        bf = b[-1]
        out = []
        i = 0
        if want_min:
            out.append(jnp.where(bf, b[i], jnp.minimum(a[i], b[i])))
            i += 1
        if want_max:
            out.append(jnp.where(bf, b[i], jnp.maximum(a[i], b[i])))
            i += 1
        return tuple(out) + (a[-1] | bf,)

    scanned = lax.associative_scan(combine, carry, axis=1)
    # window w's run ends at idx[w+1]-1 (the last point < its upper edge)
    last_pos = jnp.clip(idx[:, 1:] - 1, 0, n - 1)

    def at_ends(x, sentinel):
        out = jnp.take_along_axis(x, last_pos, axis=1)
        return jnp.where(count > 0, out, sentinel)

    i = 0
    lo = hi = None
    if want_min:
        lo = at_ends(scanned[i], jnp.inf)
        i += 1
    if want_max:
        hi = at_ends(scanned[i], -jnp.inf)
    return lo, hi, count


def _use_subblock_extreme(n: int, w_padded: int) -> bool:
    """ONE predicate for taking the subblock extreme form, shared by the
    materialized and streaming paths (they must never drift); ineligible
    shapes fall back to the scan form on BOTH paths.  Eligibility (the
    edge-fit guard bounding the [S, W, K] boundary-lane intermediates)
    and auto-selection both live in _effective_extreme_mode."""
    return _effective_extreme_mode(n, w_padded) == "subblock"


def _extreme_subblock(ts, val, mask, spec: WindowSpec, wargs: dict,
                      want_min: bool, want_max: bool):
    """Windowed min/max with no full-length scan (extreme mode "subblock").

    Decomposes each window at 32-point sub-block granularity: sub-blocks
    whose span [B*32, (B+1)*32) lies inside [idx[w], idx[w+1]) are
    entirely window w's, so the interior extreme is a segmented
    reset-scan over the [S, N/32] sub-block extremes (1/32 the scan
    work); the at-most-two boundary sub-blocks are resolved with 32-wide
    masked reduces over contiguous [1, 32] gathers.  Same decomposition
    as _edge_subblock_builder, reduced with min/max instead of sum.
    min and max share ONE scan when both are wanted (the carry holds
    both lanes), like the full-length scan form.

    Returns (lo[S, W] | None, hi[S, W] | None, count[S, W]).
    """
    from jax import lax

    s, n = ts.shape
    vf, ok, cts, idx, _windowed, count = _window_scan_setup(ts, val, mask,
                                                            spec, wargs)
    k = _SUB_K
    nb = n // k
    lo_e = idx[:, :-1]                     # [S, W] window start positions
    hi_e = idx[:, 1:]                      # window end positions
    b0 = jnp.clip(lo_e // k, 0, nb - 1)    # boundary sub-blocks
    b1 = jnp.clip(hi_e // k, 0, nb - 1)
    r0 = (lo_e + k - 1) // k               # first interior sub-block
    r1 = hi_e // k                         # one past last interior
    lanes = jnp.arange(k, dtype=idx.dtype)

    v3 = vf.reshape(s, nb, k)
    o3 = ok.reshape(s, nb, k)
    g0v = jnp.take_along_axis(v3, b0[:, :, None], axis=1)    # [S, W, K]
    g0o = jnp.take_along_axis(o3, b0[:, :, None], axis=1)
    g1v = jnp.take_along_axis(v3, b1[:, :, None], axis=1)
    g1o = jnp.take_along_axis(o3, b1[:, :, None], axis=1)
    pos0 = b0[:, :, None] * k + lanes[None, None, :]
    pos1 = b1[:, :, None] * k + lanes[None, None, :]
    in0 = (pos0 >= lo_e[:, :, None]) & (pos0 < hi_e[:, :, None]) & g0o
    in1 = (pos1 >= lo_e[:, :, None]) & (pos1 < hi_e[:, :, None]) & g1o

    # Interior reset flags: sub-block b starts some window's interior,
    # i.e. b appears in the (per-row sorted) r0 sequence — a searchsorted
    # membership test, O(nb log W), not an [S, W, nb] broadcast compare
    # (which would exceed the full-length scan this mode replaces).
    blocks = jnp.arange(nb, dtype=r0.dtype)
    w_pad = r0.shape[1]
    p = jax.vmap(lambda row: jnp.searchsorted(row, blocks,
                                              side="left"))(r0)
    at = jnp.take_along_axis(r0, jnp.clip(p, 0, w_pad - 1), axis=1)
    flags = (at == blocks[None, :]) & (p < w_pad)
    interior_pos = jnp.clip(r1 - 1, 0, nb - 1)
    has_interior = r1 > r0

    # one scan carries every wanted lane + the shared reset flag
    carry = ()
    if want_min:
        carry += (jnp.where(o3, v3, jnp.inf).min(axis=2),)
    if want_max:
        carry += (jnp.where(o3, v3, -jnp.inf).max(axis=2),)
    carry += (flags,)

    def combine(a, b):
        bf = b[-1]
        out = []
        i = 0
        if want_min:
            out.append(jnp.where(bf, b[i], jnp.minimum(a[i], b[i])))
            i += 1
        if want_max:
            out.append(jnp.where(bf, b[i], jnp.maximum(a[i], b[i])))
        return tuple(out) + (a[-1] | bf,)

    scanned = lax.associative_scan(combine, carry, axis=1)

    def finish(lane, is_min: bool):
        ident = jnp.inf if is_min else -jnp.inf
        op = jnp.minimum if is_min else jnp.maximum
        red = jnp.min if is_min else jnp.max
        interior = jnp.take_along_axis(lane, interior_pos, axis=1)
        interior = jnp.where(has_interior, interior, ident)
        rem0 = red(jnp.where(in0, g0v, ident), axis=2)
        rem1 = red(jnp.where(in1, g1v, ident), axis=2)
        out = op(op(interior, rem0), rem1)
        return jnp.where(count > 0, out, ident)

    i = 0
    lo = hi = None
    if want_min:
        lo = finish(scanned[i], True)
        i += 1
    if want_max:
        hi = finish(scanned[i], False)
    return lo, hi, count


# shape: ts[S,N] any, val[S,N] any, mask[S,N] bool, wargs.first[] i64
# shape: wargs.nwin[] i32 -> ([W] i64, [S,W] f64, [S,W] bool)
def downsample(ts, val, mask, agg_name: str, spec: WindowSpec, wargs: dict,
               fill_policy: str = FILL_NONE, fill_value: float = 0.0):
    """Downsample a [S, N] batch into (window_ts[W], values[S, W], mask[S, W]).

    `agg_name` follows the runDouble contract (NaN inputs skipped); output is
    always float (Downsampler.java:257).  With FILL_NONE empty windows are
    masked out; other policies emit every live window with the fill applied.

    Additive-moment functions take the sorted prefix-sum fast path (no
    scatter — the hot loop the reference walked per interval,
    Downsampler.java:292); the rest reduce via segment ops.
    """
    from opentsdb_tpu.ops.aggregators import java_moving_average, ma_window
    nw = ma_window(agg_name)
    if nw is not None:
        # Downsample-position movingAverage<N>: the reference Downsampler
        # would feed each window's values into the aggregator, whose
        # run{Long,Double} sums them and averages the PRECEDING N window
        # sums (Aggregators.MovingAverage:709) — so: window sums, then
        # the same Java loop across this series' data-bearing windows.
        wts, sums, sum_mask = downsample(ts, val, mask, "sum", spec, wargs,
                                         FILL_NONE, 0.0)
        out = java_moving_average(sums, sum_mask, nw)
        w = spec.count
        live = jnp.arange(w, dtype=jnp.int32)[None, :] < wargs["nwin"]
        fdtype = val.dtype if jnp.issubdtype(val.dtype, jnp.floating) \
            else jnp.float64
        out, out_mask = apply_fill(out.astype(fdtype), sum_mask, live,
                                   fill_policy, fill_value, fdtype)
        return wts, out, out_mask

    emode = (_effective_extreme_mode(ts.shape[1], spec.count)
             if agg_name in EXTREME_AGGS else None)
    if agg_name in PREFIX_AGGS or emode in ("scan", "subblock"):
        w = spec.count
        nwin = wargs["nwin"]
        if agg_name in PREFIX_AGGS:
            out, count_grid = _prefix_downsample(ts, val, mask, agg_name,
                                                 spec, wargs)
        else:
            # ineligible shapes under "subblock" fall back to the scan
            # form (NOT the segment scatter) — same rule as streaming
            is_min = agg_name in ("min", "mimmin")
            extreme = _extreme_subblock if emode == "subblock" \
                else _extreme_downsample
            lo, hi, count_grid = extreme(
                ts, val, mask, spec, wargs, is_min, not is_min)
            out = lo if is_min else hi
        live = jnp.arange(w, dtype=jnp.int32)[None, :] < nwin
        out_mask = (count_grid > 0) & live
        wts = window_timestamps(spec, wargs)
        fdtype = val.dtype if jnp.issubdtype(val.dtype, jnp.floating) \
            else jnp.float64
        out, out_mask = apply_fill(out, out_mask, live, fill_policy,
                                   fill_value, fdtype)
        return wts, out, out_mask

    s, n = ts.shape
    w = spec.count
    num = s * w + 1
    fdtype = val.dtype if jnp.issubdtype(val.dtype, jnp.floating) else jnp.float64
    vf = val.astype(fdtype)
    nwin = wargs["nwin"]

    win = window_ids(ts, spec, wargs)
    valid = mask & (win >= 0) & (win < nwin.astype(win.dtype))
    rows = jnp.arange(s, dtype=jnp.int64)[:, None]
    seg = jnp.where(valid, rows * w + jnp.clip(win, 0, w - 1), s * w)
    seg = seg.reshape(-1)
    ok = valid.reshape(-1) & ~jnp.isnan(vf.reshape(-1))
    seg = jnp.where(ok, seg, s * w)
    flat_v = jnp.where(ok, vf.reshape(-1), 0)

    def segsum(data):
        return jax.ops.segment_sum(data, seg, num_segments=num)[:-1]

    counts = segsum(ok.astype(jnp.int32))
    count_grid = counts.reshape(s, w)
    live = jnp.arange(w, dtype=jnp.int32)[None, :] < nwin
    out_mask = (count_grid > 0) & live

    if agg_name in ("sum", "zimsum", "pfsum"):
        out = segsum(flat_v).reshape(s, w)
    elif agg_name == "count":
        out = count_grid.astype(fdtype)
    elif agg_name == "squareSum":
        out = segsum(flat_v * flat_v).reshape(s, w)
    elif agg_name in ("min", "mimmin"):
        out = jax.ops.segment_min(
            jnp.where(ok, vf.reshape(-1), jnp.inf), seg, num_segments=num
        )[:-1].reshape(s, w)
    elif agg_name in ("max", "mimmax"):
        out = jax.ops.segment_max(
            jnp.where(ok, vf.reshape(-1), -jnp.inf), seg, num_segments=num
        )[:-1].reshape(s, w)
    elif agg_name == "avg":
        total = segsum(flat_v).reshape(s, w)
        out = total / jnp.maximum(count_grid, 1)
    elif agg_name == "dev":
        # Two-pass: mean per window, then centered second moment — avoids the
        # catastrophic cancellation of sumsq - n*mean^2 at large magnitudes
        # (matches the reference's Welford numerics, Aggregators.java:498).
        total = segsum(flat_v).reshape(s, w)
        cnt = jnp.maximum(count_grid, 1)
        mean = total / cnt
        mean_per_point = mean.reshape(-1)[jnp.clip(seg, 0, s * w - 1)]
        centered = jnp.where(ok, vf.reshape(-1) - mean_per_point, 0.0)
        m2 = segsum(centered * centered).reshape(s, w)
        out = jnp.where(count_grid >= 2,
                        jnp.sqrt(m2 / jnp.maximum(count_grid - 1, 1)), 0.0)
    elif agg_name == "mult":
        out = jax.ops.segment_prod(
            jnp.where(ok, vf.reshape(-1), 1.0), seg, num_segments=num
        )[:-1].reshape(s, w)
    elif agg_name in ("first", "last", "diff"):
        pos = jnp.arange(s * n, dtype=jnp.int64)
        first_idx = jax.ops.segment_min(jnp.where(ok, pos, _I64_MAX), seg,
                                        num_segments=num)[:-1]
        last_idx = jax.ops.segment_max(jnp.where(ok, pos, -1), seg,
                                       num_segments=num)[:-1]
        flat_vals = vf.reshape(-1)
        first_v = flat_vals[jnp.clip(first_idx, 0, s * n - 1)].reshape(s, w)
        last_v = flat_vals[jnp.clip(last_idx, 0, s * n - 1)].reshape(s, w)
        if agg_name == "first":
            out = first_v
        elif agg_name == "last":
            out = last_v
        else:
            out = jnp.where(count_grid >= 2, last_v - first_v, 0.0)
    elif agg_name == "median" or agg_name.startswith(("p", "ep")):
        # Row-wise (window, value) sort: windows partition each row's
        # points, so S independent row sorts replace the global [S*N]
        # lexsort (invalid slots keyed past every window); per-cell runs
        # follow from the count grid.
        from jax import lax
        from opentsdb_tpu.ops.percentile import row_run_percentile
        ok2 = ok.reshape(s, n)
        wkey = jnp.where(ok2, jnp.clip(win, 0, w - 1).astype(jnp.int32),
                         w)
        svals = jnp.where(ok2, vf, jnp.inf)
        _, sorted_rows = lax.sort((wkey, svals), dimension=1, num_keys=2)
        starts = jnp.concatenate(
            [jnp.zeros((s, 1), count_grid.dtype),
             jnp.cumsum(count_grid, axis=1)], axis=1)[:, :-1]
        if agg_name == "median":
            idx = jnp.clip(starts + count_grid // 2, 0, n - 1)
            out = jnp.where(
                count_grid > 0,
                jnp.take_along_axis(sorted_rows, idx, axis=1), jnp.nan)
        else:
            q, est = parse_percentile_name(agg_name)
            out = row_run_percentile(sorted_rows, starts, count_grid, q,
                                     est)
    else:
        raise KeyError("No such downsampling function: " + agg_name)

    wts = window_timestamps(spec, wargs)
    out, out_mask = apply_fill(out, out_mask, live, fill_policy, fill_value,
                               fdtype)
    return wts, out, out_mask


def apply_fill(out, out_mask, live, fill_policy: str, fill_value: float,
               fdtype=None):
    """Fill empty live windows per FillPolicy (FillingDownsampler semantics).

    `out_mask` marks windows holding data; `live` marks windows inside the
    query range.  Returns (values, mask) — under FILL_NONE empty windows stay
    masked out; other policies substitute a fill value and expose every live
    window.  Shared by the raw downsample above and the rollup-avg pipeline.
    """
    if fdtype is None:
        fdtype = out.dtype
    if fill_policy == FILL_NONE:
        return jnp.where(out_mask, out, jnp.nan), out_mask
    if fill_policy == FILL_ZERO:
        fill = jnp.asarray(0.0, fdtype)
    elif fill_policy in (FILL_NAN, FILL_NULL):
        fill = jnp.asarray(jnp.nan, fdtype)
    elif fill_policy == FILL_SCALAR:
        fill = jnp.asarray(fill_value, fdtype)
    else:
        raise ValueError("Unrecognized fill policy: " + fill_policy)
    out = jnp.where(out_mask, out, fill)
    return out, jnp.broadcast_to(live, out_mask.shape)


def parse_percentile_name(name: str) -> tuple[float, str]:
    """"p99" -> (99.0, legacy); "ep999r3" -> (99.9, r_3); "ep50r7" -> (50.0, r_7)."""
    est = EST_LEGACY
    digits = name
    if name.startswith("ep"):
        if name.endswith("r3"):
            est = EST_R3
        elif name.endswith("r7"):
            est = EST_R7
        else:
            raise KeyError("No such aggregator: " + name)
        digits = name[2:-2]
    elif name.startswith("p"):
        digits = name[1:]
    if digits == "999":
        return 99.9, est
    q = float(digits)
    if not 0 < q <= 100:
        raise KeyError("Invalid percentile: " + name)
    return q, est
