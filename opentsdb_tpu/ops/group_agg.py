"""Grouped cross-series aggregation on a shared downsample grid.

Reference behavior: TsdbQuery.GroupByAndAggregateCB
(/root/reference/src/core/TsdbQuery.java:981-1114) hands each group-by
bucket its own SpanGroup whose AggregationIterator merges member series one
datapoint at a time.  Round 1 mirrored that shape too literally: the planner
looped over buckets in Python, dispatching one jitted pipeline per group —
10k dispatches for a 10k-group query.

TPU-first form: ALL groups travel in one [S, W] batch with a group id per
row.  Per-series interpolation (the AggregationIterator missing-point
policies, :682/:735) is row-local and group-independent, so it runs over the
whole batch at once; the cross-series reduction becomes one segment
reduction over (group, window) cells — a single device dispatch regardless
of group count.

Cross-chip: moment-decomposable aggregators combine per-chip partial
moments with `psum`/`pmin`/`pmax` over ICI; order/rank-based aggregators
(percentiles, median, first/last/diff, mult, none) use gather-to-owner —
the [S, W] grid (already downsampled, so far smaller than the raw points)
is all-gathered and reduced identically on every chip.  The collectives are
injected by parallel/sharded.py; this module stays collective-free so the
same finish code serves both paths.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from opentsdb_tpu.ops.aggregators import Aggregator
from opentsdb_tpu.ops.downsample import parse_percentile_name
from opentsdb_tpu.ops.rate import _prev_valid_index
from opentsdb_tpu.ops.union_agg import interpolate, _next_valid

_I64_MAX = jnp.iinfo(jnp.int64).max


def _seg_dtype(num: int):
    """Segment/scatter id dtype: int32 whenever the id range fits.
    int64 on TPU is an emulated u32 pair — scatter/gather index handling
    is native at 32 bits, and every feasible (group, window) or (row,
    window) id space here is far below 2^31."""
    return jnp.int32 if num < 2 ** 31 else jnp.int64

# Aggregators whose cross-series reduction decomposes into psum/pmin/pmax
# combinable per-chip moments (count/sum/sumsq/min/max + two-pass dev).
MOMENT_AGGS = frozenset({
    "sum", "zimsum", "pfsum", "count", "avg", "min", "mimmin", "max",
    "mimmax", "dev", "squareSum"})


def is_moment_agg(name: str) -> bool:
    """movingAverage<N> included: its cross-series step is a plain sum
    (psum-combinable); the temporal window pass runs on the already
    combined [G, W] grid."""
    from opentsdb_tpu.ops.aggregators import ma_window
    return name in MOMENT_AGGS or ma_window(name) is not None


def _identity(x):
    return x


# Cross-series moment reduction strategy: "segment" scatters per-cell
# partial moments with jax.ops.segment_sum (serializing on TPU), "matmul"
# computes the same sums as onehot[G, S] @ grid[S, W] contractions — dense
# MXU work, no scatter.  "sorted" permutes rows into group order on
# device (argsort of gid — S elements, trivial) so every group is a
# contiguous row run; group sums and extremes are short segmented
# reset-scans along the tiny [S, W] grid's row axis — no scatter, no
# one-hot, cost independent
# of the group count (r4 chip attribution: the segment tail cost 219ms
# and the matmul tail ~100ms on a 0.5M-cell grid that one pass covers
# in ~1ms).  All are float64 (Java-double contract); the sum order
# differs so results can drift in the last ulp.  The chip A/B
# (bench_prefix) picks the default via TSDB_GROUP_REDUCE_MODE.
import os as _os

_GROUP_REDUCE_MODES = ("auto", "segment", "matmul", "sorted", "sorted2")
_GROUP_REDUCE_MODE = (_os.environ.get("TSDB_GROUP_REDUCE_MODE")
                      if _os.environ.get("TSDB_GROUP_REDUCE_MODE")
                      in _GROUP_REDUCE_MODES else "auto")

# Shape gate for the matmul form: the dense one-hot is [S, G] f64, so a
# wide group-by (10k groups) would build GBs and burn O(S*G*W) FLOPs —
# those shapes keep the scatter regardless of the A/B winner.
_MATMUL_MAX_GROUPS = 512
_MATMUL_MAX_ONEHOT_BYTES = 1 << 25        # 32 MB


def set_group_reduce_mode(mode: str) -> None:
    """Benchmarking/ops hook ('auto' = shape/platform cost model); clears
    the jitted pipelines that baked the old strategy in (read at trace
    time)."""
    global _GROUP_REDUCE_MODE
    if mode not in _GROUP_REDUCE_MODES:
        raise ValueError("group reduce mode must be one of %r"
                         % (_GROUP_REDUCE_MODES,))
    _GROUP_REDUCE_MODE = mode
    # one list of toggle-dependent compiled programs, owned by downsample
    # (review r4: a hand-copied list here would drift)
    from opentsdb_tpu.ops.downsample import _clear_dependent_caches
    _clear_dependent_caches()


def _matmul_feasible(s: int, g: int) -> bool:
    return g <= _MATMUL_MAX_GROUPS and s * g * 8 <= _MATMUL_MAX_ONEHOT_BYTES


def _group_candidates(s: int, g: int, extremes: bool) -> list[str]:
    # "sorted2" is deliberately NOT an auto candidate yet: its cost
    # constant is an estimate until a chip race records it (r5 policy:
    # no unraced mode can be auto-picked by a BASELINE config).
    cands = ["segment", "sorted"]
    # extremes have no matmul form (min/max don't distribute over the
    # one-hot dot) — auto must rank only the forms that exist for them
    if not extremes and _matmul_feasible(s, g):
        cands.append("matmul")
    return cands


def _effective_group_reduce_mode(s: int, w: int, g: int,
                                 extremes: bool = False,
                                 platform: str | None = None) -> str:
    """The group-combine strategy for this shape: 'auto' (default) ranks
    segment/sorted/(feasible) matmul with the calibrated cost model
    (ops.costmodel — chip anchors: segment scatter 219ms, matmul ~100ms
    at G=100, sorted ~90ms G-independent on the headline grid; CPU
    scatters are cheap so segment wins there).  Explicit modes keep the
    matmul feasibility gate at the call sites.  `platform` defaults to
    the ambient execution platform; the planner's decision report
    passes its per-segment platform explicitly."""
    mode = _GROUP_REDUCE_MODE
    if mode != "auto":
        return mode
    from opentsdb_tpu.ops.hostlane import execution_platform
    from opentsdb_tpu.ops import costmodel
    return costmodel.choose_group(s, w, g, platform
                                  or execution_platform(),
                                  _group_candidates(s, g, extremes))


def group_decision(s: int, w: int, g: int, platform: str,
                   extremes: bool = False) -> dict:
    """The group-reduce strategy decision for one dispatch shape, as
    the trace annotates it (same report shape as
    downsample.search_decision).  An explicit matmul on an infeasible
    shape dispatches segment at the call sites — the report records the
    dispatched form."""
    from opentsdb_tpu.ops import costmodel
    from opentsdb_tpu.ops.downsample import _decision_report
    mode = _effective_group_reduce_mode(s, w, g, extremes, platform)
    if mode == "matmul" and (extremes or not _matmul_feasible(s, g)):
        mode = "segment"    # the call-site feasibility fallback
    cands = _group_candidates(s, g, extremes)
    if _GROUP_REDUCE_MODE == "sorted2":
        cands = cands + ["sorted2"]     # explicit-only mode: price it
    return _decision_report(
        "group", mode, _GROUP_REDUCE_MODE, cands, platform,
        lambda m: costmodel.predict_group(m, s, w, g, platform))


class _SortedGroups:
    """Rows permuted into group order: the machinery behind mode "sorted".

    Group g's members occupy rows [bounds[g], bounds[g+1]) of the
    permuted grid; rows with gid outside [0, G) sort past bounds[G] and
    drop out.  Group sums AND extremes are segmented reset-scans over
    the permuted row order, gathered at each group's last row.
    Everything is [S, W]-sized vector work — no scatter.
    """

    def __init__(self, gid, num_groups: int, s: int,
                 presorted: bool = False):
        self.g = num_groups
        self.s = s
        if presorted:
            # Caller-guaranteed non-decreasing gid (the planner always
            # emits groups as concatenated runs, planner.py:403): skip
            # the argsort AND the [S, W] permute gather in every fold.
            self.perm = None
            self.sorted_gid = gid
        else:
            self.perm = jnp.argsort(gid, stable=True)
            self.sorted_gid = jnp.take(gid, self.perm)
        self.bounds = jnp.searchsorted(
            self.sorted_gid, jnp.arange(num_groups + 1,
                                        dtype=self.sorted_gid.dtype))
        # reset flags: row starts a new group run (for the reset-scan)
        self.flags = jnp.concatenate(
            [jnp.ones((1,), bool),
             self.sorted_gid[1:] != self.sorted_gid[:-1]])

    def sum(self, x2d):
        """[S, W] -> [G, W] per-group column sums via a segmented
        reset-scan (NOT a cumsum differenced at bounds: that computes a
        small group's sum as the difference of two large running totals,
        and the cancellation error scales with the GLOBAL total — a
        1e15-magnitude group next to a 1.0-magnitude group would break
        the 1e-9 parity contract.  The reset-scan restarts each group's
        accumulation at zero, so error scales with the group's own sum,
        same as segment_sum)."""
        from jax import lax
        xs = x2d if self.perm is None \
            else jnp.take(x2d, self.perm, axis=0)
        flags = jnp.broadcast_to(self.flags[:, None], xs.shape)

        def combine(a, b):
            av, af = a
            bv, bf = b
            return jnp.where(bf, bv, av + bv), af | bf

        scanned, _ = lax.associative_scan(combine, (xs, flags), axis=0)
        ends = jnp.clip(self.bounds[1:] - 1, 0, self.s - 1)
        out = jnp.take(scanned, ends, axis=0)            # [G, W]
        # empty groups gather a neighboring run's total: zero them
        empty = (self.bounds[1:] == self.bounds[:-1])[:, None]
        return jnp.where(empty, jnp.zeros_like(out), out)

    def extreme(self, x2d, want_max: bool):
        """[S, W] -> [G, W] per-group min or max via a reset-scan.

        Callers pre-fill non-participating cells with the identity
        (+inf for min / -inf for max); empty groups return the identity.
        """
        from jax import lax
        xs = x2d if self.perm is None \
            else jnp.take(x2d, self.perm, axis=0)
        flags = jnp.broadcast_to(self.flags[:, None], xs.shape)

        def combine(a, b):
            av, af = a
            bv, bf = b
            ext = jnp.maximum(av, bv) if want_max else jnp.minimum(av, bv)
            return jnp.where(bf, bv, ext), af | bf

        scanned, _ = lax.associative_scan(combine, (xs, flags), axis=0)
        # group g's run ends at row bounds[g+1]-1; empty groups gather a
        # clipped row and are masked by the caller's count grid
        ends = jnp.clip(self.bounds[1:] - 1, 0, self.s - 1)
        return jnp.take(scanned, ends, axis=0)

    # -- mode "sorted2": blocked level-masked folds (same answers) ---- #

    def sum2(self, x2d):
        """[S, W] -> [G, W] per-group column sums via the blocked
        level-masked reset-scan (_blocked_group_fold) — dtype-preserving,
        so int32 counts ride native TPU adds instead of emulated f64."""
        xs = x2d if self.perm is None \
            else jnp.take(x2d, self.perm, axis=0)
        return _blocked_group_fold(xs, self.flags, self.bounds, self.s,
                                   jnp.add, 0)

    def extreme2(self, x2d, want_max: bool):
        """[S, W] -> [G, W] per-group min/max via the blocked fold;
        same identity-fill contract as extreme()."""
        xs = x2d if self.perm is None \
            else jnp.take(x2d, self.perm, axis=0)
        if want_max:
            return _blocked_group_fold(xs, self.flags, self.bounds,
                                       self.s, jnp.maximum, -jnp.inf)
        return _blocked_group_fold(xs, self.flags, self.bounds, self.s,
                                   jnp.minimum, jnp.inf)


_SORTED2_K = 8          # rows per block in the blocked reset-scan


def _blocked_group_fold(xs, flags, bounds, s_orig: int, op, identity):
    """Per-group fold over group-sorted rows: a blocked, level-masked
    segmented (reset) scan — the machinery behind group mode "sorted2".

    Same answer as _SortedGroups' associative_scan reset-fold, ~3x less
    device work on the value channel:

      * the reset flags depend only on the [S] row axis, never on W, so
        every level's carry mask is precomputed on [S] bools and the
        heavy [S, W] channel pays ONE select+op per level instead of the
        pair operator's add + two selects + a broadcast [S, W] bool OR;
      * blocking at K rows halves the level count on the full-size
        channel: log2(K) full-width levels + log2(S/K) levels on the
        [S/K, W] block summaries (vs log2(S) full-width levels).

    Like the reset-scan (and unlike a cumsum differenced at group
    bounds), no addition ever combines values from two different groups
    — error scales with each group's own magnitude, so the
    1e15-next-to-1.0 skew contract holds (see _SortedGroups.sum).

    xs: [S, W] group-sorted rows (any dtype with `op`/`identity`, f64
    values or int32 counts); flags: [S] bool, True where a row starts a
    new group run; bounds: [G+1] group row bounds; s_orig: valid row
    count (xs rows past it are ignored).  Returns [G, W] per-group fold,
    `identity` for empty groups.
    """
    k = _SORTED2_K
    s, w = xs.shape
    sp = -(-max(s, 1) // k) * k
    if sp != s:
        pad_rows = jnp.full((sp - s, w), identity, xs.dtype)
        xs = jnp.concatenate([xs, pad_rows], axis=0)
        flags = jnp.concatenate(
            [flags, jnp.ones((sp - s,), bool)], axis=0)
    nb = sp // k
    pos_in_block = jnp.arange(sp, dtype=jnp.int32) % k

    def shift_rows(a, d, fill):
        return jnp.concatenate(
            [jnp.full((d,) + a.shape[1:], fill, a.dtype), a[:-d]], axis=0)

    # Within-block Hillis-Steele with per-level [S] carry masks: after
    # log2(K) levels, row i holds the fold of its run restricted to its
    # own block (runs reset at group starts).
    fl = flags
    v = xs
    d = 1
    while d < k:
        in_block = pos_in_block >= d
        carry = in_block & ~fl
        v = jnp.where(carry[:, None], op(v, shift_rows(v, d, identity)), v)
        fl = fl | (in_block & shift_rows(fl, d, False))
        d *= 2

    # Block summaries: Y[b] = fold of block b's trailing run; Fb[b] =
    # block contains a run start (so carries stop at it).
    y = v[k - 1::k]                                         # [nb, W]
    fb = flags.reshape(nb, k).any(axis=1)                   # [nb]
    zb = y
    fbl = fb
    bpos = jnp.arange(nb, dtype=jnp.int32)
    d = 1
    while d < nb:
        carry_b = (bpos >= d) & ~fbl
        zb = jnp.where(carry_b[:, None],
                       op(zb, shift_rows(zb, d, identity)), zb)
        fbl = fbl | ((bpos >= d) & shift_rows(fbl, d, False))
        d *= 2

    # Group g ends at row e: fold = intra[e], combined with the previous
    # blocks' summary iff e's run reaches back past its block start
    # (no flag in rows [block_start(e) .. e] — an OR-scan on [S] bools).
    fcum = jnp.cumsum(flags.reshape(nb, k).astype(jnp.int32),
                      axis=1).reshape(sp) > 0               # [S'] incl. OR
    ends = jnp.clip(bounds[1:] - 1, 0, s_orig - 1)          # [G]
    be = (ends // k).astype(jnp.int32)
    intra_e = jnp.take(v, ends, axis=0)                     # [G, W]
    z_prev = jnp.take(zb, jnp.clip(be - 1, 0, nb - 1), axis=0)
    carry_e = ((~jnp.take(fcum, ends)) & (be > 0))[:, None]
    out = jnp.where(carry_e, op(intra_e, z_prev), intra_e)
    empty = (bounds[1:] == bounds[:-1])[:, None]
    return jnp.where(empty, jnp.asarray(identity, xs.dtype), out)


def grid_contributions(grid_ts, val, mask, agg: Aggregator):
    """Per-series contribution + participation at every grid slot.

    The batched form of AggregationIterator's missing-point substitution
    (nextDoubleValue :735): a series missing window w contributes the
    interpolated value per the aggregator's policy, participating only
    between its first and last present window.  Row-local — valid across
    any row sharding.  Returns (contrib[S, W], participate[S, W]).

    Hole-free grids (every series has every window — the common
    downsampled dense shape, and the headline benchmark's) take a
    lax.cond fast lane that skips the prev/next scans, the four gathers,
    and the interpolation entirely: with mask all-true, contrib == val
    and participate == mask exactly.  Data with holes runs the full
    branch; the cond costs one jnp.all reduce.
    """
    from jax import lax

    def _full(operand):
        grid_ts_, val_, mask_ = operand
        w = val_.shape[1]
        prev_i = _prev_valid_index(mask_)
        next_i = _next_valid(mask_)
        has_prev = prev_i >= 0
        has_next = next_i < w
        safe_prev = jnp.clip(prev_i, 0, w - 1)
        safe_next = jnp.clip(next_i, 0, w - 1)

        x = grid_ts_[None, :]
        x0 = jnp.take(grid_ts_, safe_prev)
        x1 = jnp.take(grid_ts_, safe_next)
        y0 = jnp.take_along_axis(val_, safe_prev, axis=1)
        y1 = jnp.take_along_axis(val_, safe_next, axis=1)

        participate = has_prev & has_next | mask_
        interp = interpolate(agg.interpolation, False, x, x0, y0, x1, y1,
                             val_)
        contrib = jnp.where(mask_, val_, interp)
        return contrib, participate

    # both cond branches must agree on dtype, and the full branch's
    # depends on the agg's interpolation policy (LERP promotes f32 val
    # to f64 through the int64 timestamp division; ZIM keeps val's
    # dtype) — derive it from the full branch itself, abstractly
    out_dtype = jax.eval_shape(_full, (grid_ts, val, mask))[0].dtype

    def _dense(operand):
        _, val_, mask_ = operand
        return val_.astype(out_dtype), mask_

    return lax.cond(jnp.all(mask), _dense, _full, (grid_ts, val, mask))


def _flat_segments(contrib, participate, gid, num_groups: int):
    """Flatten [S, W] to (seg, ok, v) over (group, window) cells."""
    s, w = contrib.shape
    dt = _seg_dtype(num_groups * w + w)
    cols = jnp.arange(w, dtype=dt)[None, :]
    seg = (gid.astype(dt)[:, None] * w + cols).reshape(-1)
    vf = contrib.astype(jnp.float64)
    ok = (participate & ~jnp.isnan(vf)).reshape(-1)
    v = jnp.where(ok, vf.reshape(-1), 0.0)
    return seg, ok, v


# shape: contrib[S,W] any, participate[S,W] bool, gid[S] any
def moment_group_reduce(agg_name: str, contrib, participate, gid,
                        num_groups: int, combine_sum=_identity,
                        combine_min=_identity, combine_max=_identity,
                        rows_sorted: bool = False):
    """[S, W] -> ([G, W] out, [G, W] count) for moment-decomposable aggs.

    `combine_*` inject the cross-chip collectives (psum/pmin/pmax over the
    mesh) between the local partial moments and the finish arithmetic; the
    defaults make this the complete single-device reduction.  The dev
    aggregator's second (centered) pass re-uses `combine_sum`, costing one
    extra ICI round-trip — the two-pass scheme the reference's Welford loop
    approximates (Aggregators.java:498).
    """
    s, w = contrib.shape
    g = num_groups
    num = g * w
    extremes = agg_name in ("min", "mimmin", "max", "mimmax")
    mode = _effective_group_reduce_mode(s, w, g, extremes=extremes)

    if extremes:
        want_max = agg_name in ("max", "mimmax")
        if mode in ("sorted", "sorted2"):
            # contiguous-run reset-scan over group-sorted rows: no
            # scatter.  sorted2 = the blocked fold, with native-int32
            # counts (exact: counts <= S).
            sg = _SortedGroups(gid, g, s, rows_sorted)
            fold = sg.sum2 if mode == "sorted2" else sg.sum
            cdt = jnp.int32 if mode == "sorted2" else jnp.float64
            vf0 = contrib.astype(jnp.float64)
            ok0 = participate & ~jnp.isnan(vf0)
            local_cnt = fold(ok0.astype(cdt))                   # [G, W]
            cnt_grid = combine_sum(local_cnt.reshape(-1)) \
                .reshape(g, w).astype(jnp.int64)
            ident = -jnp.inf if want_max else jnp.inf
            filled = jnp.where(ok0, vf0, ident)
            ext = (sg.extreme2(filled, want_max) if mode == "sorted2"
                   else sg.extreme(filled, want_max))
            # a group empty on THIS shard must contribute the identity to
            # pmin/pmax, not the boundary gather's neighboring-run value
            ext = jnp.where(local_cnt > 0.5, ext, ident).reshape(-1)
            ext = (combine_max(ext) if want_max
                   else combine_min(ext)).reshape(g, w)
            out = jnp.where(cnt_grid > 0, ext, jnp.nan)
            return out, cnt_grid
        # segment/matmul modes: extremes have no matmul form — scatter ops
        seg, ok, v = _flat_segments(contrib, participate, gid, g)
        cnt = combine_sum(jax.ops.segment_sum(ok.astype(jnp.int32), seg,
                                              num_segments=num))
        cnt_grid = cnt.reshape(g, w).astype(jnp.int64)
        if agg_name in ("min", "mimmin"):
            ext = combine_min(jax.ops.segment_min(
                jnp.where(ok, v, jnp.inf), seg, num_segments=num))
        else:
            ext = combine_max(jax.ops.segment_max(
                jnp.where(ok, v, -jnp.inf), seg, num_segments=num))
        out = jnp.where(cnt_grid > 0, ext.reshape(g, w), jnp.nan)
        return out, cnt_grid

    # One finish, two group-sum primitives.  The matmul form is gated to
    # shapes where the dense one-hot is cheap (small G relative to S —
    # the headline group-by shape); a 10k-group query would build a
    # multi-GB [S, G] one-hot, so big-G shapes keep the scatter
    # regardless of the A/B winner (review r4).
    vf = contrib.astype(jnp.float64)
    ok2 = participate & ~jnp.isnan(vf)
    v2 = jnp.where(ok2, vf, 0.0)
    use_matmul = mode == "matmul" and _matmul_feasible(s, g)
    if mode in ("sorted", "sorted2"):
        sg = _SortedGroups(gid, g, s, rows_sorted)
        fold = sg.sum2 if mode == "sorted2" else sg.sum

        def gsum(x2d):   # [S, W] -> [G, W], cross-chip combined
            return combine_sum(fold(x2d).reshape(-1)).reshape(g, w)
    elif use_matmul:
        # out[g, w] = Σ_s onehot[s, g] * grid[s, w] — dense MXU work, no
        # serializing scatter.  Counts are 0/1 sums (exact in f64 far
        # beyond any real S); value sums reassociate vs segment_sum, so
        # parity is to the last ulp, not bitwise.
        o_t = (gid[:, None]
               == jnp.arange(g, dtype=gid.dtype)[None, :]) \
            .astype(jnp.float64).T                             # [G, S]

        def gsum(x2d):   # [S, W] -> [G, W], cross-chip combined
            return combine_sum((o_t @ x2d).reshape(-1)).reshape(g, w)
    else:
        dt = _seg_dtype(num + w)     # pre-clamp ids reach num + w - 1
        cols = jnp.arange(w, dtype=dt)[None, :]
        seg = (jnp.clip(gid.astype(dt), 0, g)[:, None] * w
               + cols).reshape(-1)
        seg = jnp.where(seg < num, seg, jnp.asarray(num, dt))

        def gsum(x2d):
            return combine_sum(jax.ops.segment_sum(
                x2d.reshape(-1), seg, num_segments=num + 1)[:-1]) \
                .reshape(g, w)

    # sorted2 counts ride int32 (native TPU adds, exact — counts <= S;
    # psum combines int32 fine); other modes keep their f64/scatter form
    cnt_dtype = jnp.int32 if mode == "sorted2" else jnp.float64
    cnt_grid = gsum(ok2.astype(cnt_dtype)).astype(jnp.int64)
    safe = jnp.maximum(cnt_grid, 1)

    if agg_name in ("sum", "zimsum", "pfsum"):
        out = gsum(v2)
    elif agg_name == "count":
        out = cnt_grid.astype(jnp.float64)
    elif agg_name == "avg":
        out = gsum(v2) / safe
    elif agg_name == "squareSum":
        out = gsum(v2 * v2)
    elif agg_name == "dev":
        # Two-pass centered moment with the GLOBAL mean (one extra
        # combine round-trip) — the scheme the reference's Welford loop
        # approximates (Aggregators.java:498).
        mean = gsum(v2) / safe                                  # [G, W]
        mean_pp = jnp.take(mean, jnp.clip(gid, 0, g - 1), axis=0)
        centered = jnp.where(ok2, vf - mean_pp, 0.0)
        m2 = gsum(centered * centered)
        out = jnp.where(cnt_grid >= 2,
                        jnp.sqrt(m2 / jnp.maximum(cnt_grid - 1, 1)), 0.0)
    else:
        from opentsdb_tpu.ops.aggregators import java_moving_average, \
            ma_window
        nw = ma_window(agg_name)
        if nw is None:
            raise KeyError("Aggregator %r is not moment-decomposable"
                           % agg_name)
        # Cross-series sum combines across chips; the Java window pass
        # then runs on the replicated [G, W] grid (live = windows with
        # data, matching the evaluation order the iterator would visit).
        out = java_moving_average(gsum(v2), cnt_grid > 0, nw)

    if agg_name != "count":
        out = jnp.where(cnt_grid > 0, out, jnp.nan)
    return out, cnt_grid


# shape: contrib[S,W] any, participate[S,W] bool, gid[S] any
def ordered_group_reduce(agg_name: str, contrib, participate, gid,
                         num_groups: int):
    """[S, W] -> ([G, W] out, [G, W] count) for rank/order-based aggs.

    Needs every member row present (no partial-moment form); the sharded
    path all-gathers the grid before calling.  first/last/diff follow row
    order — the order series entered the group, matching the reference's
    iteration order over spans (Aggregators.java:576-617, :810).
    """
    s, w = contrib.shape
    g = num_groups
    num = g * w
    if not (agg_name == "median" or agg_name.startswith(("p", "ep"))):
        seg, ok, v = _flat_segments(contrib, participate, gid, g)
        cnt = jax.ops.segment_sum(ok.astype(jnp.int32), seg,
                                  num_segments=num).reshape(g, w) \
            .astype(jnp.int64)

    if agg_name == "mult":
        out = jax.ops.segment_prod(jnp.where(ok, v, 1.0), seg,
                                   num_segments=num).reshape(g, w)
    elif agg_name in ("first", "last", "diff", "none"):
        rows = jnp.broadcast_to(
            jnp.arange(s, dtype=jnp.int32)[:, None], (s, w)).reshape(-1)
        first_row = jax.ops.segment_min(
            jnp.where(ok, rows, jnp.asarray(s, jnp.int32)), seg,
            num_segments=num).reshape(g, w)
        last_row = jax.ops.segment_max(
            jnp.where(ok, rows, jnp.asarray(-1, jnp.int32)), seg,
            num_segments=num).reshape(g, w)
        vf = contrib.astype(jnp.float64)
        first_v = jnp.take_along_axis(vf, jnp.clip(first_row, 0, s - 1),
                                      axis=0)
        last_v = jnp.take_along_axis(vf, jnp.clip(last_row, 0, s - 1), axis=0)
        if agg_name in ("first", "none"):
            out = first_v
        elif agg_name == "last":
            out = last_v
        else:
            out = jnp.where(cnt >= 2, last_v - first_v, 0.0)
    elif agg_name == "median" or agg_name.startswith(("p", "ep")):
        # ONE column sort with (gid, value) lexicographic keys instead of
        # a global [S*W] lexsort: each window's column sorts its S values
        # independently (W tiny bitonic sorts — the natural vectorized
        # form), invalid rows keyed past every group.  The SAME sort
        # yields starts AND counts (per-column searchsorted of the
        # sorted keys at the group boundaries) — no scatter, no second
        # valid-mask definition, nothing but this one sort.
        from jax import lax
        from opentsdb_tpu.ops.percentile import column_run_percentile
        vf2 = contrib.astype(jnp.float64)
        ok2 = (participate & ~jnp.isnan(vf2))
        in_range = (gid >= 0) & (gid < g)
        gkey = jnp.broadcast_to(
            jnp.where(in_range, gid, g).astype(jnp.int32)[:, None], (s, w))
        gkey = jnp.where(ok2, gkey, g)
        vals = jnp.where(ok2, vf2, jnp.inf)
        sorted_keys, sorted_cols = lax.sort((gkey, vals), dimension=0,
                                            num_keys=2)
        bounds = jax.vmap(
            lambda col: jnp.searchsorted(
                col, jnp.arange(g + 1, dtype=sorted_keys.dtype)),
            in_axes=1, out_axes=1)(sorted_keys)              # [G+1, W]
        starts = bounds[:-1]
        cnt = (bounds[1:] - bounds[:-1]).astype(jnp.int64)
        if agg_name == "median":
            # Upper median sorted[n // 2] (Aggregators.Median :397-431).
            idx = jnp.clip(starts + (cnt // 2).astype(starts.dtype),
                           0, s - 1)
            out = jnp.where(
                cnt > 0,
                jnp.take_along_axis(sorted_cols, idx, axis=0), jnp.nan)
        else:
            q, est = parse_percentile_name(agg_name)
            out = column_run_percentile(sorted_cols, starts, cnt, q, est)
    else:
        raise KeyError("No such aggregator: " + agg_name)

    out = jnp.where(cnt > 0, out, jnp.nan)
    return out, cnt


# shape: grid_ts[W] i64, val[S,W] any, mask[S,W] bool, gid[S] any
def grid_group_aggregate(grid_ts, val, mask, gid, num_groups: int,
                         agg: Aggregator, rows_sorted: bool = False):
    """All-groups-at-once grid aggregation (single-device form).

    [S, W] batch + gid[S] -> (grid_ts[W], out[G, W], out_mask[G, W]).
    out_mask marks (group, window) cells where at least one member holds an
    actual (non-interpolated) value — the union-timestamp rule restricted to
    the shared grid.

    rows_sorted=True is a CALLER GUARANTEE that gid is non-decreasing
    (the planner always builds it that way, planner.py:403) — the sorted
    modes then skip the argsort and the [S, W] permute gathers.  A false
    claim silently misassigns rows to groups.
    """
    vf = val.astype(jnp.float64)
    contrib, participate = grid_contributions(grid_ts, vf, mask, agg)
    if is_moment_agg(agg.name):
        out, _ = moment_group_reduce(agg.name, contrib, participate, gid,
                                     num_groups, rows_sorted=rows_sorted)
    else:
        out, _ = ordered_group_reduce(agg.name, contrib, participate, gid,
                                      num_groups)
    s, w = val.shape
    # same extremes flag as moment_group_reduce's own decision: the mask
    # pass must ride the mode the reduce actually took, or an auto pick
    # of matmul (excluded for extremes) would put the segment scatter
    # back into a dispatch the sorted mode was chosen to keep
    # scatter-free (review r5)
    extreme_agg = agg.name in ("min", "mimmin", "max", "mimmax")
    mask_mode = _effective_group_reduce_mode(
        s, w, num_groups,
        extremes=is_moment_agg(agg.name) and extreme_agg)
    if mask_mode in ("sorted", "sorted2"):
        # same fold machinery as the reduce (XLA CSEs the repeated
        # argsort/bounds); sorted2 presence rides native int32 adds.
        # Both fold exact integer counts, so > 0 is the same test.
        sg = _SortedGroups(gid, num_groups, s, rows_sorted)
        present = (sg.sum2(mask.astype(jnp.int32))
                   if mask_mode == "sorted2"
                   else sg.sum(mask.astype(jnp.float64)))
        out_mask = present > 0
    else:
        dt = _seg_dtype(num_groups * w + w)
        cols = jnp.arange(w, dtype=dt)[None, :]
        seg = (gid.astype(dt)[:, None] * w + cols).reshape(-1)
        present = jax.ops.segment_sum(
            mask.reshape(-1).astype(jnp.int32), seg,
            num_segments=num_groups * w)
        out_mask = present.reshape(num_groups, w) > 0
    return grid_ts, out, out_mask
