"""Small-query fast lane: run the SAME jitted kernels on the host CPU.

VERDICT r3 weak #2: a 1M-point query lost 11x to the reference's iterator
loop because every accelerator dispatch pays a fixed floor (tunnel RTT +
launch + host->HBM transfer) that dwarfs the compute at small scale —
production TSDs serve mostly small queries.  The reference never had this
cliff because it always computes on the serving host
(/root/reference/src/core/AggregationIterator.java:514 runs in the Netty
worker).

The fix keeps ONE implementation: below a configured point count the
planner executes the identical pipeline functions under
`jax.default_device(<cpu>)`, so XLA compiles the same program for the
host (vectorized, still beating the Java iterator) and the tunnel is
never touched.  No numpy re-implementation — the lane cannot diverge
semantically from the device path, and every existing kernel test covers
both lanes by construction.

The axon/TPU environment restricts JAX to the accelerator platform via
JAX_PLATFORMS; `ensure_cpu_platform` (called once at package import,
before any backend initializes) widens the restriction to keep the host
platform registered alongside.  If the backend already initialized
without a CPU platform the lane degrades to None and the planner keeps
the accelerator path — routing is best-effort, correctness never depends
on it.

The kernel strategies (scan/search/extreme/group-reduce modes) are
process-global trace-time choices, but they are resolved PER EXECUTION
PLATFORM: the r04b chip session measured the dense edge-search forms —
chip winners — running 18x SLOWER than the binary search on the host
lane at the config-1 shape (they materialize their compare matrix where
the backend does not fuse it into the count), so the shape guards in
ops.downsample consult `execution_platform()` and demote dense forms on
CPU.  This is safe with one shared jit cache because
`jax.default_device` participates in the cache key (probed: two devices
-> two traces, re-entry hits the cache), so each lane's trace reads the
lane context that was active when IT was traced.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import os

LOG = logging.getLogger("ops.hostlane")

_UNSET = object()
_CPU_DEVICE = _UNSET


def ensure_cpu_platform() -> None:
    """Keep the CPU platform registered when JAX_PLATFORMS restricts to an
    accelerator.  Must run before the first backend initialization; a
    no-op when platforms are unrestricted (cpu is always registered then)
    or already include cpu."""
    plats = os.environ.get("JAX_PLATFORMS", "").strip()
    if not plats or "cpu" in plats.split(","):
        return
    try:
        import jax
        jax.config.update("jax_platforms", plats + ",cpu")
    except Exception:   # backend already up, or unknown platform string
        LOG.debug("could not widen jax_platforms=%r with cpu", plats,
                  exc_info=True)


def cpu_device():
    """The host CPU jax device, or None when unavailable (cached)."""
    global _CPU_DEVICE
    if _CPU_DEVICE is _UNSET:
        try:
            import jax
            _CPU_DEVICE = jax.devices("cpu")[0]
        except Exception:
            _CPU_DEVICE = None
            LOG.info("no CPU platform registered; small-query host lane "
                     "disabled (accelerator path serves all sizes)")
    return _CPU_DEVICE


# True while a host_lane() context is active on this thread/task: the
# planner routed this dispatch to the host CPU, so trace-time kernel-mode
# guards must pick host-friendly strategies (see module docstring).
_LANE_ACTIVE = contextvars.ContextVar("tsdb_host_lane_active",
                                      default=False)


@contextlib.contextmanager
def _lane_marked(inner):
    tok = _LANE_ACTIVE.set(True)
    try:
        with inner:
            yield
    finally:
        _LANE_ACTIVE.reset(tok)


def host_lane(enabled: bool):
    """Context manager: place this dispatch on the host CPU when enabled
    and a CPU device exists; otherwise a no-op.

    On a CPU-backend process the dispatch already executes on the host,
    so the context would only add per-dispatch overhead — measured 8ms
    per config-1 query (21.2ms with the redundant `jax.default_device`
    wrap vs 12.8 without, identical compiled program) — and
    execution_platform() already reports 'cpu' without the lane marker
    there."""
    dev = cpu_device() if enabled else None
    if dev is None:
        return contextlib.nullcontext()
    import jax
    if jax.default_backend() == "cpu":
        return contextlib.nullcontext()
    return _lane_marked(jax.default_device(dev))


def execution_platform() -> str:
    """Best-effort platform this thread's dispatches execute on — for
    trace-time kernel-mode guards.  'cpu' inside an active host_lane()
    (regardless of the process's accelerator), else the default backend's
    platform ('tpu', 'cpu', ...)."""
    if _LANE_ACTIVE.get():
        return "cpu"
    try:
        import jax
        return jax.default_backend()
    except Exception:
        # best-effort probe before the backend initializes; "cpu" is
        # the conservative answer for the trace-time mode guards
        return "cpu"  # tsdblint: disable=except-swallow
