"""Small-query fast lane: run the SAME jitted kernels on the host CPU.

VERDICT r3 weak #2: a 1M-point query lost 11x to the reference's iterator
loop because every accelerator dispatch pays a fixed floor (tunnel RTT +
launch + host->HBM transfer) that dwarfs the compute at small scale —
production TSDs serve mostly small queries.  The reference never had this
cliff because it always computes on the serving host
(/root/reference/src/core/AggregationIterator.java:514 runs in the Netty
worker).

The fix keeps ONE implementation: below a configured point count the
planner executes the identical pipeline functions under
`jax.default_device(<cpu>)`, so XLA compiles the same program for the
host (vectorized, still beating the Java iterator) and the tunnel is
never touched.  No numpy re-implementation — the lane cannot diverge
semantically from the device path, and every existing kernel test covers
both lanes by construction.

The axon/TPU environment restricts JAX to the accelerator platform via
JAX_PLATFORMS; `ensure_cpu_platform` (called once at package import,
before any backend initializes) widens the restriction to keep the host
platform registered alongside.  If the backend already initialized
without a CPU platform the lane degrades to None and the planner keeps
the accelerator path — routing is best-effort, correctness never depends
on it.

Known trade-off: the hot-path kernel strategies (scan/search/extreme/
group-reduce modes) are process-global trace-time choices, so the lane
compiles whatever modes the chip A/B crowned — tuned for the TPU, not
the host.  At host-lane sizes (<= ~2M points) the measured spread
between modes is small (every mode answers identically; only speed
differs), and per-lane modes would mean per-lane jit cache flushes —
deliberately not worth it.
"""

from __future__ import annotations

import contextlib
import logging
import os

LOG = logging.getLogger("ops.hostlane")

_UNSET = object()
_CPU_DEVICE = _UNSET


def ensure_cpu_platform() -> None:
    """Keep the CPU platform registered when JAX_PLATFORMS restricts to an
    accelerator.  Must run before the first backend initialization; a
    no-op when platforms are unrestricted (cpu is always registered then)
    or already include cpu."""
    plats = os.environ.get("JAX_PLATFORMS", "").strip()
    if not plats or "cpu" in plats.split(","):
        return
    try:
        import jax
        jax.config.update("jax_platforms", plats + ",cpu")
    except Exception:   # backend already up, or unknown platform string
        LOG.debug("could not widen jax_platforms=%r with cpu", plats,
                  exc_info=True)


def cpu_device():
    """The host CPU jax device, or None when unavailable (cached)."""
    global _CPU_DEVICE
    if _CPU_DEVICE is _UNSET:
        try:
            import jax
            _CPU_DEVICE = jax.devices("cpu")[0]
        except Exception:
            _CPU_DEVICE = None
            LOG.info("no CPU platform registered; small-query host lane "
                     "disabled (accelerator path serves all sizes)")
    return _CPU_DEVICE


def host_lane(enabled: bool):
    """Context manager: place this dispatch on the host CPU when enabled
    and a CPU device exists; otherwise a no-op."""
    dev = cpu_device() if enabled else None
    if dev is None:
        return contextlib.nullcontext()
    import jax
    return jax.default_device(dev)
