"""Sort-based percentile kernels (LEGACY / R-3 / R-7 estimation).

Reference behavior: Aggregators.PercentileAgg
(/root/reference/src/core/Aggregators.java:657-708) delegates to Apache
commons-math3 `Percentile`.  Its default ("LEGACY") estimation uses
pos = p*(n+1)/100 with linear interpolation between order statistics; the
`ep*r3`/`ep*r7` variants use Hyndman-Fan types R-3 and R-7.

The iterator-based reference gathers values into a resizable array per output
timestamp; here whole [series, time] batches are sorted on the reduction axis
once and order statistics gathered vectorially — the non-associative kernel
flagged by SURVEY.md §7 hard part (b).  Cross-chip, the planner gathers each
group to its owner shard before selection.
"""

from __future__ import annotations

import jax.numpy as jnp

EST_LEGACY = "legacy"
EST_R3 = "r_3"
EST_R7 = "r_7"


def masked_percentile(values, mask, q: float, estimation: str = EST_LEGACY,
                      axis: int = 0):
    """Percentile q (0..100] of masked values along `axis` (axis 0 supported).

    Masked-out slots are sorted to +inf so valid values occupy the first n
    positions of each column; empty columns yield NaN.  The degenerate
    whole-column case of column_run_percentile (starts = 0), sharing the
    same estimator core (commons-math3 LEGACY pos = p*(n+1)/100, and
    Hyndman-Fan R-3 / R-7).
    """
    if axis != 0:
        raise ValueError("masked_percentile reduces axis 0")
    n = mask.sum(axis=0)
    sorted_vals = jnp.sort(jnp.where(mask, values, jnp.inf), axis=0)
    starts = jnp.zeros((1,) + n.shape, dtype=jnp.int64)
    return column_run_percentile(sorted_vals, starts, n[None, :], q,
                                 estimation)[0]


def _estimate(at, n, q: float, estimation: str):
    """Shared estimator core: `at(k)` returns the k-th (1-based) order
    statistic of each cell's run, clipped to the run; `n` is the count
    per cell.  One definition serves the flat-run and column-run forms —
    the three estimators must never drift between them."""
    nf = n.astype(jnp.float64)
    if estimation == EST_LEGACY:
        pos = q * (nf + 1.0) / 100.0
        fpos = jnp.floor(pos)
        d = pos - fpos
        k = fpos.astype(jnp.int64)
        mid = at(k) + d * (at(k + 1) - at(k))
        out = jnp.where(pos < 1.0, at(jnp.ones_like(k)),
                        jnp.where(pos >= nf, at(n), mid))
    elif estimation == EST_R3:
        h = nf * q / 100.0
        k = jnp.clip(jnp.ceil(h - 0.5).astype(jnp.int64), 1,
                     jnp.maximum(n, 1))
        out = at(k)
    elif estimation == EST_R7:
        h = (nf - 1.0) * q / 100.0 + 1.0
        fh = jnp.floor(h)
        k = fh.astype(jnp.int64)
        out = at(k) + (h - fh) * (at(k + 1) - at(k))
    else:
        raise ValueError("Unknown estimation type: " + estimation)
    return jnp.where(n > 0, out, jnp.nan)


def row_run_percentile(sorted_rows, starts, counts, q: float,
                       estimation: str = EST_LEGACY):
    """Percentile per (series, window) cell of row-sorted runs.

    `sorted_rows[S, N]` holds each row sorted so window w's members
    occupy columns [starts[s, w], starts[s, w] + counts[s, w]); starts /
    counts are [S, W].  Serves the downsample-position percentile path —
    S independent row sorts instead of one global [S*N] lexsort.
    """
    n = counts
    top = sorted_rows.shape[1] - 1

    def at(one_based_idx):
        idx = starts + jnp.clip(one_based_idx - 1, 0,
                                jnp.maximum(n - 1, 0))
        return jnp.take_along_axis(sorted_rows,
                                   jnp.clip(idx, 0, top), axis=1)

    return _estimate(at, n, q, estimation)


def column_run_percentile(sorted_cols, starts, counts, q: float,
                          estimation: str = EST_LEGACY):
    """Percentile per (group, window) cell of column-sorted runs.

    `sorted_cols[S, W]` holds each column sorted so group g's members
    occupy rows [starts[g, w], starts[g, w] + counts[g, w]); starts /
    counts are [G, W].  The transposed twin of row_run_percentile — one
    column sort replaces a global [S*W] lexsort in the grouped
    cross-series percentile reduction.
    """
    n = counts
    top = sorted_cols.shape[0] - 1

    def at(one_based_idx):
        idx = starts + jnp.clip(one_based_idx - 1, 0,
                                jnp.maximum(n - 1, 0))
        return jnp.take_along_axis(sorted_cols,
                                   jnp.clip(idx, 0, top), axis=0)

    return _estimate(at, n, q, estimation)
