"""Fused query pipeline: downsample -> rate -> cross-series aggregation.

Composes the kernels in the reference's iterator-chain order
(AggregationIterator.create :253-380 wires Span -> Downsampler -> RateSpan ->
merge) as one jit-compiled function per static pipeline spec.  XLA fuses the
stages.  Compile churn is bounded: batch shapes and window counts pad to
powers of two, and time-range-dependent values (window origin, calendar
edges) are traced operands, so repeated dashboard queries hit the jit cache.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from opentsdb_tpu.ops.aggregators import get_agg, Aggregator, PREV
from opentsdb_tpu.ops.downsample import (
    downsample, apply_fill, WindowSpec, FixedWindows, EdgeWindows, AllWindow,
    window_timestamps, pad_pow2, FILL_NONE)
from opentsdb_tpu.ops.rate import rate, RateOptions
from opentsdb_tpu.ops.union_agg import union_aggregate, grid_aggregate

PAD_TS = np.iinfo(np.int64).max


@dataclass(frozen=True)
class DownsampleStep:
    """Static downsample config; traced window args travel separately."""
    function: str
    window_spec: WindowSpec
    fill_policy: str = FILL_NONE
    fill_value: float = 0.0


@dataclass(frozen=True)
class PipelineSpec:
    """Static (hashable) description of one group's numeric pipeline."""
    aggregator: str
    downsample: DownsampleStep | None = None
    rate: RateOptions | None = None
    int_mode: bool = False  # Java long arithmetic end-to-end
    # union-path tile budget override (<= 0: module default); the batched
    # union runner sets default/B so B vmapped groups share one envelope
    tile_cells: int = 0
    # caller guarantee: the batch's gid is non-decreasing (the planner
    # always emits groups as concatenated runs, planner.py:403) — the
    # sorted group-reduce modes then skip argsort + permute gathers
    rows_sorted: bool = False


def _pipeline(spec: PipelineSpec, ts, val, mask, wargs):
    agg = get_agg(spec.aggregator)
    if spec.rate is not None:
        # Rates never LERP across series: a missing rate contributes the
        # previous rate value (AggregationIterator.java:744-752).
        agg = Aggregator(agg.name, PREV, agg.reduce)
    if spec.downsample is not None:
        step = spec.downsample
        wts, v, m = downsample(ts, val, mask, step.function, step.window_spec,
                               wargs, step.fill_policy, step.fill_value)
        grid = jnp.asarray(wts)
        if spec.rate is not None:
            grid_b = jnp.broadcast_to(grid[None, :], v.shape)
            _, v, m = rate(grid_b, v, m, spec.rate, all_int=False)
        return grid_aggregate(grid, v, m, agg, int_mode=False)
    if spec.rate is not None:
        work_ts, work_val, work_mask = rate(ts, val, mask, spec.rate,
                                            all_int=spec.int_mode)
        return union_aggregate(work_ts, work_val, work_mask, agg,
                               int_mode=False, tile_cells=spec.tile_cells)
    return union_aggregate(ts, val, mask, agg, int_mode=spec.int_mode,
                           tile_cells=spec.tile_cells)


_jitted = jax.jit(_pipeline, static_argnums=0)


def _union_batch_pipeline(spec: PipelineSpec, ts, val, mask):
    """B same-shaped union (no-downsample) groups in ONE dispatch.

    vmaps the union pipeline over a leading group axis [B, S, N]; the
    caller divides the union tile budget by B via spec.tile_cells so the
    total materialization envelope stays what a single group's would be.
    The per-group union grids are independent — outputs come back
    batched ([B, S*N] timestamps/values/mask), one row per group.
    """
    return jax.vmap(lambda t, v, m: _pipeline(spec, t, v, m, {}))(
        ts, val, mask)


_jitted_union_batch = jax.jit(_union_batch_pipeline, static_argnums=0)


# shape: ts[B,S,N] any, val[B,S,N] any, mask[B,S,N] bool
def run_union_batch_pipeline(spec: PipelineSpec, ts, val, mask):
    """Batched union pipeline -> per-group (u[B, U], out[B, U], mask[B, U])."""
    return _jitted_union_batch(spec, ts, val, mask)


# shape: ts[S,N] any, val[S,N] any, mask[S,N] bool
def run_pipeline(spec: PipelineSpec, ts, val, mask, wargs: dict | None = None):
    """Execute the pipeline; returns (out_ts, out_val, out_mask) on device."""
    return _jitted(spec, ts, val, mask, wargs or {})


def _rollup_avg_pipeline(spec: PipelineSpec, ts_s, val_s, mask_s,
                         ts_c, val_c, mask_c, wargs):
    """Rollup-average read: sum lane / count lane, then the normal tail.

    Reference behavior: Downsampler.java:155-210 — when reading an `avg`
    rollup the downsampler consumes paired sum and count cells and divides.
    Here both lanes downsample with segment-sum, the per-window quotient
    becomes the per-series value, then rate/fill/cross-series aggregation
    proceed exactly like the raw pipeline.
    """
    step = spec.downsample
    wts, sums, msum = downsample(ts_s, val_s, mask_s, "sum", step.window_spec,
                                 wargs, FILL_NONE)
    _, cnts, mcnt = downsample(ts_c, val_c, mask_c, "sum", step.window_spec,
                               wargs, FILL_NONE)
    ok = msum & mcnt & (cnts > 0)
    v = jnp.where(ok, sums / jnp.where(ok, cnts, 1.0), jnp.nan)
    # Fill policy over empty live windows (FillingDownsampler semantics).
    nwin = wargs["nwin"]
    live = jnp.arange(v.shape[-1]) < nwin
    v, m = apply_fill(v, ok, live[None, :], step.fill_policy,
                      step.fill_value)
    grid = jnp.asarray(wts)
    agg = get_agg(spec.aggregator)
    if spec.rate is not None:
        agg = Aggregator(agg.name, PREV, agg.reduce)
        grid_b = jnp.broadcast_to(grid[None, :], v.shape)
        _, v, m = rate(grid_b, v, m, spec.rate, all_int=False)
    return grid_aggregate(grid, v, m, agg, int_mode=False)


_jitted_rollup_avg = jax.jit(_rollup_avg_pipeline, static_argnums=0)


def run_rollup_avg_pipeline(spec: PipelineSpec, ts_s, val_s, mask_s,
                            ts_c, val_c, mask_c, wargs: dict | None = None):
    """Execute the rollup-avg pipeline (sum lane + count lane batches)."""
    return _jitted_rollup_avg(spec, ts_s, val_s, mask_s, ts_c, val_c, mask_c,
                              wargs or {})


def _group_pipeline(spec: PipelineSpec, num_groups: int, ts, val, mask, gid,
                    wargs):
    """All-groups-at-once pipeline: one dispatch for any group count.

    Replaces the per-group Python loop of round 1 (one jit call per group-by
    bucket — 10k dispatches for a 10k-group query) with a single
    gid-segmented device call: downsample and rate are row-local, the
    cross-series reduce segments over (group, window) cells.
    """
    step = spec.downsample
    wts, v, m = downsample(ts, val, mask, step.function, step.window_spec,
                           wargs, step.fill_policy, step.fill_value)
    return _grid_tail(spec, num_groups, wts, v, m, gid)


def _grid_tail(spec: PipelineSpec, num_groups: int, wts, v, m, gid):
    """Shared pipeline tail: (rate ->) grouped cross-series aggregation on
    an already-downsampled [S, W] grid.  Also the finish stage of the
    streaming executor (ops.streaming hands it the accumulated grid)."""
    from opentsdb_tpu.ops.group_agg import grid_group_aggregate
    agg = get_agg(spec.aggregator)
    if spec.rate is not None:
        agg = Aggregator(agg.name, PREV, agg.reduce)
    grid = jnp.asarray(wts)
    if spec.rate is not None:
        grid_b = jnp.broadcast_to(grid[None, :], v.shape)
        _, v, m = rate(grid_b, v, m, spec.rate, all_int=False)
    return grid_group_aggregate(grid, v, m, gid, num_groups, agg,
                                rows_sorted=spec.rows_sorted)


def _downsample_grid(step: DownsampleStep, ts, val, mask, wargs):
    """Downsample only — the block evaluator of the partial-aggregate
    cache (storage/agg_cache.py): per-(series, window) grids computed
    block-by-block, with rate/group/aggregate running later on the
    assembled grid via _grid_tail (they cross block boundaries)."""
    return downsample(ts, val, mask, step.function, step.window_spec,
                      wargs, step.fill_policy, step.fill_value)


def _lane_partials(spec: WindowSpec, ts, val, mask, wargs):
    """Mergeable per-(series, window) partials — the rollup-lane block
    builder (storage/rollup.py): one dispatch computes the sum, count,
    min and max of every cell, the four moments every lane-derivable
    downsample re-reduces from exactly.  Mirrors the segment path of
    ops.downsample.downsample cell-for-cell (same window ids, same
    NaN-skip rule, float64 accumulation), so a lane-derived window is
    bit-identical to the raw kernel's on integer data.  Empty cells
    hold (0, 0, +inf, -inf) — the mergeable identities — and mask
    derives as count > 0 at serve time."""
    s, n = ts.shape
    w = spec.count
    num = s * w + 1
    vf = val.astype(jnp.float64)
    nwin = wargs["nwin"]
    from opentsdb_tpu.ops.downsample import window_ids
    win = window_ids(ts, spec, wargs)
    valid = mask & (win >= 0) & (win < nwin.astype(win.dtype))
    rows = jnp.arange(s, dtype=jnp.int64)[:, None]
    seg = jnp.where(valid, rows * w + jnp.clip(win, 0, w - 1), s * w)
    seg = seg.reshape(-1)
    flat = vf.reshape(-1)
    ok = valid.reshape(-1) & ~jnp.isnan(flat)
    seg = jnp.where(ok, seg, s * w)
    counts = jax.ops.segment_sum(ok.astype(jnp.int32), seg,
                                 num_segments=num)[:-1].reshape(s, w)
    sums = jax.ops.segment_sum(jnp.where(ok, flat, 0.0), seg,
                               num_segments=num)[:-1].reshape(s, w)
    mins = jax.ops.segment_min(jnp.where(ok, flat, jnp.inf), seg,
                               num_segments=num)[:-1].reshape(s, w)
    maxs = jax.ops.segment_max(jnp.where(ok, flat, -jnp.inf), seg,
                               num_segments=num)[:-1].reshape(s, w)
    return sums, counts, mins, maxs


def _stacked_group_pipeline(spec: PipelineSpec, num_groups: int, ts, val,
                            mask, gid, wargs):
    """Q compatible grouped queries in ONE stacked [Q, S, N] dispatch.

    The fused multi-query batcher (query/batcher.py) buckets concurrent
    small plans by (static spec, padded shapes, mode-policy epoch) and
    vmaps the SAME _group_pipeline over a leading member axis — each
    member keeps its own gid row map and its own traced window args
    (stacked along axis 0), and inside the vmap the kernels trace on
    the per-member [S, N] shapes, so the mode choosers pick exactly
    what a solo dispatch of the same member would.  Per-member results
    come back batched ([Q, W], [Q, G, W], [Q, G, W]) for host-side
    unpack; on integer data a member's slice is bitwise what its solo
    dispatch would produce (integer-exact f64 accumulation is
    reassociation-proof — the same contract the rollup lanes pin).
    """
    return jax.vmap(
        lambda t, v, m, g, w: _group_pipeline(spec, num_groups, t, v,
                                              m, g, w))(
        ts, val, mask, gid, wargs)


_jitted_group = jax.jit(_group_pipeline, static_argnums=(0, 1))
_jitted_stacked_group = jax.jit(_stacked_group_pipeline,
                                static_argnums=(0, 1))
_jitted_grid_tail = jax.jit(_grid_tail, static_argnums=(0, 1))
_jitted_downsample_grid = jax.jit(_downsample_grid, static_argnums=0)
_jitted_lane_partials = jax.jit(_lane_partials, static_argnums=0)


def run_grid_tail(spec: PipelineSpec, wts, v, m, gid, num_groups: int):
    """Finish a streamed query: grid [S, W] -> (wts, out[G, W], mask[G, W])."""
    return _jitted_grid_tail(spec, num_groups, wts, v, m, gid)


# shape: ts[Q,S,N] any, val[Q,S,N] any, mask[Q,S,N] bool, gid[Q,S] any
def run_stacked_group_pipeline(spec: PipelineSpec, ts, val, mask, gid,
                               num_groups: int, wargs: dict):
    """Q stacked grouped pipelines -> (wts[Q, W], out[Q, G, W],
    mask[Q, G, W]) — the batcher's one-launch form of
    run_group_pipeline; `wargs` values carry a leading member axis."""
    if spec.downsample is None:
        raise ValueError("grouped pipeline requires a downsample step")
    return _jitted_stacked_group(spec, num_groups, ts, val, mask, gid,
                                 wargs)


# shape: ts[S,N] any, val[S,N] f64, mask[S,N] bool
def run_downsample_grid(step: DownsampleStep, ts, val, mask, wargs: dict):
    """One downsample-only dispatch -> (wts[W], v[S, W], mask[S, W])."""
    return _jitted_downsample_grid(step, ts, val, mask, wargs)


# shape: ts[S,N] any, val[S,N] f64, mask[S,N] bool
def run_lane_partials(spec: WindowSpec, ts, val, mask, wargs: dict):
    """One lane-partials dispatch -> (sum[S, W] f64, count[S, W] i32,
    min[S, W] f64, max[S, W] f64) — the rollup-lane block builder."""
    return _jitted_lane_partials(spec, ts, val, mask, wargs)


# shape: ts[S,N] any, val[S,N] any, mask[S,N] bool, gid[S] any
def run_group_pipeline(spec: PipelineSpec, ts, val, mask, gid,
                       num_groups: int, wargs: dict | None = None):
    """Execute the grouped pipeline -> (wts[W], out[G, W], out_mask[G, W]).

    Requires a downsample step (the shared grid is what makes the segmented
    cross-series reduce possible); union-timestamp queries keep the
    per-group path.
    """
    if spec.downsample is None:
        raise ValueError("grouped pipeline requires a downsample step")
    return _jitted_group(spec, num_groups, ts, val, mask, gid, wargs or {})


def _group_rollup_avg(spec: PipelineSpec, num_groups: int, ts_s, val_s,
                      mask_s, ts_c, val_c, mask_c, gid, wargs):
    """Grouped rollup-avg read: sum/count lane division, then the grid tail."""
    from opentsdb_tpu.ops.group_agg import grid_group_aggregate
    step = spec.downsample
    wts, sums, msum = downsample(ts_s, val_s, mask_s, "sum", step.window_spec,
                                 wargs, FILL_NONE)
    _, cnts, mcnt = downsample(ts_c, val_c, mask_c, "sum", step.window_spec,
                               wargs, FILL_NONE)
    ok = msum & mcnt & (cnts > 0)
    v = jnp.where(ok, sums / jnp.where(ok, cnts, 1.0), jnp.nan)
    nwin = wargs["nwin"]
    live = jnp.arange(v.shape[-1]) < nwin
    v, m = apply_fill(v, ok, live[None, :], step.fill_policy,
                      step.fill_value)
    grid = jnp.asarray(wts)
    agg = get_agg(spec.aggregator)
    if spec.rate is not None:
        agg = Aggregator(agg.name, PREV, agg.reduce)
        grid_b = jnp.broadcast_to(grid[None, :], v.shape)
        _, v, m = rate(grid_b, v, m, spec.rate, all_int=False)
    return grid_group_aggregate(grid, v, m, gid, num_groups, agg,
                                rows_sorted=spec.rows_sorted)


_jitted_group_rollup_avg = jax.jit(_group_rollup_avg, static_argnums=(0, 1))


def run_group_rollup_avg_pipeline(spec: PipelineSpec, ts_s, val_s, mask_s,
                                  ts_c, val_c, mask_c, gid, num_groups: int,
                                  wargs: dict | None = None):
    """Grouped rollup-avg pipeline -> (wts[W], out[G, W], out_mask[G, W])."""
    return _jitted_group_rollup_avg(spec, num_groups, ts_s, val_s, mask_s,
                                    ts_c, val_c, mask_c, gid, wargs or {})


# shape: -> ([S,N] i64, [S,N] f64, [S,N] bool, [] bool)
def build_batch_direct(series_list: list, start_ms: int, end_ms: int,
                       fix_duplicates: bool, pad_to_pow2: bool = True):
    """Single-copy batch build: size/type from window_stats, then each
    series copies its window STRAIGHT into its padded row under its own
    lock (Series.window_into) — no intermediate per-series arrays.
    build_batch + window() copies every point twice (25MB of transient
    copies on a 1M-point query, ~30%% of the host-lane query time);
    this is the same output contract (ts[S, N], val[S, N], mask[S, N],
    all_int) in one pass."""
    stats = [s.window_stats(start_ms, end_ms, fix_duplicates)
             for s in series_list]
    s = len(series_list)
    n_max = max((c for c, _ in stats), default=0)
    n = pad_pow2(max(n_max, 1)) if pad_to_pow2 else max(n_max, 1)
    all_int = s > 0 and all(isint for c, isint in stats if c)
    while True:
        ts = np.empty((s, n), dtype=np.int64)
        mask = np.empty((s, n), dtype=bool)
        val = np.empty((s, n), dtype=np.int64 if all_int else np.float64)
        retype = False
        for i, series in enumerate(series_list):
            k, ok_int = series.window_into(start_ms, end_ms,
                                           fix_duplicates, ts[i], val[i],
                                           mask[i], all_int)
            if not ok_int:
                # a float point landed in range between the sizing pass
                # and this row's fill (no snapshot isolation): the int64
                # batch can no longer represent the data — rebuild as
                # float.  At most one retype per build (float accepts
                # everything).
                retype = True
                break
            ts[i, k:] = PAD_TS
            val[i, k:] = 0
            mask[i, k:] = False
        if not retype:
            return ts, val, mask, all_int
        all_int = False


# shape: -> ([S,N] i64, [S,N] f64, [S,N] bool, [] bool)
def build_batch(windows: list, pad_to_pow2: bool = True):
    """Pack per-series (ts, fval, ival, is_int) windows into padded arrays.

    Returns (ts[S, N], val[S, N], mask[S, N], all_int).  When every series is
    integer-typed, `val` is an exact int64 array (Java-long-exact above 2^53);
    otherwise float64.  Padding timestamps are int64 max so rows stay sorted;
    shapes pad to powers of two to bound jit recompiles (SURVEY.md §7 (c)).
    """
    s = len(windows)
    n_max = max((len(w[0]) for w in windows), default=0)
    n = pad_pow2(max(n_max, 1)) if pad_to_pow2 else max(n_max, 1)
    all_int = s > 0
    for w in windows:
        isint = w[3]
        if len(w[0]) and not bool(np.all(isint)):
            all_int = False
            break
    # np.empty + per-row tail fill, not np.full/zeros: a dense batch
    # (the common case — one big series is the whole row) would pay a
    # full-array memset immediately overwritten by the copy
    ts = np.empty((s, n), dtype=np.int64)
    mask = np.empty((s, n), dtype=bool)
    val = np.empty((s, n), dtype=np.int64 if all_int else np.float64)
    for i, (t, fv, iv, isint) in enumerate(windows):
        k = len(t)
        ts[i, :k] = t
        ts[i, k:] = PAD_TS
        val[i, :k] = iv if all_int else fv
        val[i, k:] = 0
        mask[i, :k] = True
        mask[i, k:] = False
    return ts, val, mask, all_int
