"""Rate-of-change kernels with counter rollover handling.

Reference behavior: /root/reference/src/core/RateSpan.java (populateNextRate
:121 — per-second dv/dt between adjacent points, long arithmetic when both
values are integers, counter rollover diff = counter_max - prev + next,
reset_value spike suppression -> 0, drop_resets skips negative diffs) and
RateOptions.java (:27).  Rates are emitted at the timestamp of the latter
point; the first point of a span yields no output, matching how
AggregationIterator consumes the synthetic time-zero rate as interpolation
state only (AggregationIterator.java:448-459).

Vectorized form: for each row of a [S, N] sorted batch, the "previous valid
point" is found with a prefix-max scan over masked positions, so gaps from
FILL_NONE downsampling are skipped exactly like the iterator would.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
from jax import lax

LONG_MAX = 2**63 - 1


@dataclass(frozen=True)
class RateOptions:
    """Counter options (RateOptions.java:27-62).

    Parsing of the "rate{counter[,max[,reset]]}" URI form lives in
    models.tsquery.parse_rate_options.
    """
    counter: bool = False
    counter_max: int = LONG_MAX
    reset_value: int = 0
    drop_resets: bool = False


def _prev_valid_index(mask):
    """prev[k] = largest j < k with mask[j], else -1; per row, via cummax.

    Indices ride int32: any axis length fits, int32 scans are native TPU
    ALU work (int64 lowers to emulated u32-pair reduce-windows — ~7x
    slower, and the u32-pair lowering trips an XLA scoped-vmem compile
    bug at some [1, N] shapes: "Ran out of memory in memory space vmem
    ... reduce-window u32[1,2,128]", seen on configs 1/4).
    """
    s, n = mask.shape
    pos = jnp.where(mask, jnp.arange(n, dtype=jnp.int32)[None, :], -1)
    running = lax.associative_scan(jnp.maximum, pos, axis=1)
    prev = jnp.concatenate(
        [jnp.full((s, 1), -1, dtype=jnp.int32), running[:, :-1]], axis=1)
    return prev


# shape: ts[S,N] any, val[S,N] any, mask[S,N] bool
def rate(ts, val, mask, options: RateOptions, all_int: bool = False):
    """Compute rates over a [S, N] sorted batch.

    Returns (ts, rate_values[S, N] float, mask[S, N]): slot k holds the rate
    between point k and its previous valid point, masked off for first points
    (and dropped resets).  Timestamps are unchanged (rate sits at the latter
    point's timestamp).
    """
    s, n = ts.shape
    prev = _prev_valid_index(mask)
    has_prev = prev >= 0
    safe_prev = jnp.clip(prev, 0, n - 1)
    prev_ts = jnp.take_along_axis(ts, safe_prev, axis=1)
    prev_val = jnp.take_along_axis(val, safe_prev, axis=1)

    dt_sec = (ts - prev_ts).astype(jnp.float64) / 1000.0
    dt_sec = jnp.where(dt_sec == 0, jnp.inf, dt_sec)

    if all_int:
        # Long-typed difference first, then divide — avoids double rounding
        # of large longs (RateSpan.java:140-147).
        diff = (val.astype(jnp.int64) - prev_val.astype(jnp.int64)).astype(
            jnp.float64)
        rolled = (jnp.asarray(options.counter_max, jnp.int64)
                  - prev_val.astype(jnp.int64)
                  + val.astype(jnp.int64)).astype(jnp.float64)
    else:
        diff = val.astype(jnp.float64) - prev_val.astype(jnp.float64)
        rolled = (jnp.asarray(options.counter_max, jnp.float64)
                  - prev_val.astype(jnp.float64) + val.astype(jnp.float64))

    out_mask = mask & has_prev
    if options.counter:
        negative = diff < 0
        if options.drop_resets:
            out = diff / dt_sec
            out_mask = out_mask & ~negative
        else:
            roll_rate = rolled / dt_sec
            suppressed = (options.reset_value > 0) & (
                roll_rate > options.reset_value)
            out = jnp.where(negative,
                            jnp.where(suppressed, 0.0, roll_rate),
                            diff / dt_sec)
    else:
        out = diff / dt_sec

    out = jnp.where(out_mask, out, jnp.nan)
    return ts, out, out_mask
