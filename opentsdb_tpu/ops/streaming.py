"""Chunked/streaming execution: beyond-memory queries on bounded HBM.

Reference behavior: the scan layer streams storage rows through overlapping
scanner callbacks (/root/reference/src/core/SaltScanner.java:463-740 —
ScannerCB fetches the next batch while span assembly digests the last) and
never holds more than the assembled spans; queries too big to assemble are
refused by byte budgets.  Round 1 materialized the whole [S, N] batch in
host memory (VERDICT missing #4) — a 1B-point query cannot fit.

TPU-first form: the time axis is chunked; each chunk is a bounded [S, n]
batch whose per-(series, window) moments are computed with the scatter-free
prefix-sum kernel and MERGED into device-resident accumulator state.  All
downsample functions with associative merges stream:

  * count/sum/sumsq -> additive; min/max -> pointwise min/max
  * dev -> Chan parallel-variance merge of (n, total, M2) — numerically the
    two-pass scheme, exact under chunking
  * first/last -> chunks arrive in time order, so first sticks and last
    overwrites; diff = last - first; mult -> running product

Only rank-based window functions (median/p* as *downsample* functions)
cannot stream — those queries fall back to the materialized path and the
scan budget guards them.

JAX's async dispatch gives the ScannerCB overlap for free: `update()`
returns as soon as the device program is enqueued, so the host fetches and
packs chunk k+1 while the device reduces chunk k (double buffering without
explicit machinery).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from opentsdb_tpu.ops.downsample import (
    WindowSpec, apply_fill, window_ids, window_timestamps,
    _compact_ts, _edge_prefix_builder, FILL_NONE)

# Downsample functions whose window moments merge associatively.
STREAMABLE_DS = frozenset({
    "sum", "zimsum", "pfsum", "count", "avg", "squareSum", "dev",
    "min", "mimmin", "max", "mimmax", "first", "last", "diff", "mult"})

_I64_MAX = np.iinfo(np.int64).max


def _zero_state(s: int, w: int) -> dict:
    return {
        "n": jnp.zeros((s, w), jnp.int64),
        "total": jnp.zeros((s, w), jnp.float64),
        "m2": jnp.zeros((s, w), jnp.float64),
        "lo": jnp.full((s, w), jnp.inf, jnp.float64),
        "hi": jnp.full((s, w), -jnp.inf, jnp.float64),
        "first": jnp.zeros((s, w), jnp.float64),
        "last": jnp.zeros((s, w), jnp.float64),
        "prod": jnp.ones((s, w), jnp.float64),
    }


def _chunk_moments(ts, val, mask, spec: WindowSpec, wargs: dict):
    """One chunk's per-(series, window) moments via the prefix-sum kernel."""
    s, n = ts.shape
    vf = val.astype(jnp.float64)
    ok = mask & ~jnp.isnan(vf)
    v0 = jnp.where(ok, vf, 0.0)

    cts, cedges = _compact_ts(ts, spec, wargs)
    idx = jax.vmap(
        lambda row: jnp.searchsorted(row, cedges, side="left"))(cts)
    windowed = _edge_prefix_builder(s, n, idx)

    cnt = windowed(ok.astype(jnp.int32)).astype(jnp.int64)
    tot = windowed(v0)
    safe = jnp.maximum(cnt, 1)
    mean = tot / safe
    w = spec.count
    raw_win = window_ids(ts, spec, wargs)
    win = jnp.clip(raw_win, 0, w - 1)
    mean_pp = jnp.take_along_axis(mean, win, axis=1)
    centered = jnp.where(ok, vf - mean_pp, 0.0)
    m2 = windowed(centered * centered)

    # min/max/first/last/prod need per-point window membership; the segment
    # forms are fine here (one scatter per chunk, amortized over its points).
    num = s * w + 1
    valid = ok & (raw_win >= 0) & (raw_win < jnp.asarray(w, raw_win.dtype))
    rows = jnp.arange(s, dtype=jnp.int64)[:, None]
    seg = jnp.where(valid, rows * w + win, s * w).reshape(-1)
    flat = jnp.where(valid, vf, 0.0).reshape(-1)
    okf = valid.reshape(-1)
    lo = jax.ops.segment_min(jnp.where(okf, flat, jnp.inf), seg,
                             num_segments=num)[:-1].reshape(s, w)
    hi = jax.ops.segment_max(jnp.where(okf, flat, -jnp.inf), seg,
                             num_segments=num)[:-1].reshape(s, w)
    pos = jnp.arange(s * n, dtype=jnp.int64)
    first_i = jax.ops.segment_min(jnp.where(okf, pos, _I64_MAX), seg,
                                  num_segments=num)[:-1]
    last_i = jax.ops.segment_max(jnp.where(okf, pos, -1), seg,
                                 num_segments=num)[:-1]
    flat_v = vf.reshape(-1)
    first_v = flat_v[jnp.clip(first_i, 0, s * n - 1)].reshape(s, w)
    last_v = flat_v[jnp.clip(last_i, 0, s * n - 1)].reshape(s, w)
    prod = jax.ops.segment_prod(jnp.where(okf, flat, 1.0), seg,
                                num_segments=num)[:-1].reshape(s, w)
    return dict(n=cnt, total=tot, m2=m2, lo=lo, hi=hi, first=first_v,
                last=last_v, prod=prod)


def _merge(state: dict, chunk: dict) -> dict:
    """Associative merge of two moment sets (Chan et al. for m2)."""
    n1, n2 = state["n"], chunk["n"]
    t1, t2 = state["total"], chunk["total"]
    n = n1 + n2
    safe_n = jnp.maximum(n, 1).astype(jnp.float64)
    nf1 = n1.astype(jnp.float64)
    nf2 = n2.astype(jnp.float64)
    # delta = mean2 - mean1 with empty sides contributing zero.
    mean1 = t1 / jnp.maximum(nf1, 1.0)
    mean2 = t2 / jnp.maximum(nf2, 1.0)
    delta = jnp.where((n1 > 0) & (n2 > 0), mean2 - mean1, 0.0)
    m2 = state["m2"] + chunk["m2"] + delta * delta * nf1 * nf2 / safe_n
    had = n1 > 0
    got = n2 > 0
    return {
        "n": n,
        "total": t1 + t2,
        "m2": m2,
        "lo": jnp.minimum(state["lo"], chunk["lo"]),
        "hi": jnp.maximum(state["hi"], chunk["hi"]),
        # Chunks arrive in time order: first sticks, last overwrites.
        "first": jnp.where(had, state["first"], chunk["first"]),
        "last": jnp.where(got, chunk["last"], state["last"]),
        "prod": state["prod"] * chunk["prod"],
    }


def _update(spec: WindowSpec, state: dict, ts, val, mask, wargs: dict):
    return _merge(state, _chunk_moments(ts, val, mask, spec, wargs))


_jitted_update = jax.jit(_update, static_argnums=0)


def _finish(spec: WindowSpec, ds_function: str, fill_policy: str,
            state: dict, wargs: dict, fill_value):
    """Final per-series downsampled grid from accumulated moments."""
    n = state["n"]
    safe = jnp.maximum(n, 1)
    if ds_function in ("sum", "zimsum", "pfsum"):
        out = state["total"]
    elif ds_function == "count":
        out = n.astype(jnp.float64)
    elif ds_function == "avg":
        out = state["total"] / safe
    elif ds_function == "squareSum":
        # sumsq = M2 + total^2/n (exact algebraic identity).
        out = state["m2"] + state["total"] * state["total"] / safe
    elif ds_function == "dev":
        out = jnp.where(n >= 2, jnp.sqrt(state["m2"]
                                         / jnp.maximum(n - 1, 1)), 0.0)
    elif ds_function in ("min", "mimmin"):
        out = state["lo"]
    elif ds_function in ("max", "mimmax"):
        out = state["hi"]
    elif ds_function == "first":
        out = state["first"]
    elif ds_function == "last":
        out = state["last"]
    elif ds_function == "diff":
        out = jnp.where(n >= 2, state["last"] - state["first"], 0.0)
    elif ds_function == "mult":
        out = state["prod"]
    else:
        raise KeyError("Downsample function does not stream: " + ds_function)
    w = spec.count
    live = jnp.arange(w, dtype=jnp.int32)[None, :] < wargs["nwin"]
    out_mask = (n > 0) & live
    out, out_mask = apply_fill(out, out_mask, live, fill_policy, fill_value,
                               jnp.float64)
    wts = window_timestamps(spec, wargs)
    return wts, out, out_mask


_jitted_finish = jax.jit(_finish, static_argnums=(0, 1, 2))


@dataclass
class StreamAccumulator:
    """Device-resident per-(series, window) moment state fed chunk by chunk.

    Usage::

        acc = StreamAccumulator.create(num_series, window_spec, wargs)
        for chunk in chunks:            # increasing time order
            acc.update(ts, val, mask)   # [S, n_chunk] padded batches
        wts, values, mask = acc.finish("avg")
    """
    spec: WindowSpec
    wargs: dict
    state: dict

    @staticmethod
    def create(num_series: int, spec: WindowSpec,
               wargs: dict) -> "StreamAccumulator":
        return StreamAccumulator(spec, wargs, _zero_state(num_series,
                                                          spec.count))

    def update(self, ts, val, mask) -> None:
        """Fold one [S, n] chunk in (async — returns at enqueue)."""
        self.state = _jitted_update(self.spec, self.state, ts, val, mask,
                                    self.wargs)

    def finish(self, ds_function: str, fill_policy: str = FILL_NONE,
               fill_value: float = 0.0):
        """(window_ts[W], values[S, W], mask[S, W]) — the downsample output."""
        return _jitted_finish(self.spec, ds_function, fill_policy,
                              self.state, self.wargs, fill_value)
