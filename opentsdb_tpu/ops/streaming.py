"""Chunked/streaming execution: beyond-memory queries on bounded HBM.

Reference behavior: the scan layer streams storage rows through overlapping
scanner callbacks (/root/reference/src/core/SaltScanner.java:463-740 —
ScannerCB fetches the next batch while span assembly digests the last) and
never holds more than the assembled spans; queries too big to assemble are
refused by byte budgets.  Round 1 materialized the whole [S, N] batch in
host memory (VERDICT missing #4) — a 1B-point query cannot fit.

TPU-first form: the time axis is chunked; each chunk is a bounded [S, n]
batch whose per-(series, window) moments are computed with the scatter-free
prefix-sum kernel and MERGED into device-resident accumulator state.  All
downsample functions with associative merges stream:

  * count/sum/sumsq -> additive; min/max -> pointwise min/max
  * dev -> Chan parallel-variance merge of (n, total, M2) — numerically the
    two-pass scheme, exact under chunking
  * first/last -> chunks arrive in time order, so first sticks and last
    overwrites; diff = last - first; mult -> running product

Rank-based window functions (median/p* as *downsample* functions) stream
through a mergeable fixed-size quantile summary (is_sketch_ds below): each
chunk's exact per-(series, window) K-point equi-rank grid folds into the
accumulated grid by weighted merge + re-interpolation.  Error is in RANK,
not value: one compaction to a K-grid moves a quantile's rank by at most
1/(2K), so a cell that receives data from C chunks drifts at most
~C/(2K) of its population in the worst case (K=64).  Two things keep C
small in practice: chunks partition TIME while windows partition time
too, so a window-sized cell only overlaps the few chunks that span it
(an empty-side merge is an exact no-op); and on stationary data the
per-merge errors are signed and largely cancel (random-walk, not
linear — see test_many_merges_drift_bounded).  The hazard case is a
window much wider than a chunk (e.g. "0all" over a huge range), where C
equals the chunk count; for those prefer the exact path via
tsd.query.streaming.sketch_percentiles=false + budgets.  The exact sort
path still serves materialized (sub-threshold) queries; the reference
would have refused big rank queries on budget instead
(Aggregators.java:657-708 sorts fully in memory).

JAX's async dispatch gives the ScannerCB overlap for free: `update()`
returns as soon as the device program is enqueued, so the host fetches and
packs chunk k+1 while the device reduces chunk k (double buffering without
explicit machinery).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from opentsdb_tpu.ops.downsample import (
    WindowSpec, apply_fill, window_ids, window_timestamps,
    _absolute_ts, _extreme_downsample,
    _window_scan_setup, _window_ids_fast, FILL_NONE)

# Summary points per (series, window) quantile sketch.
SKETCH_K = 64


# Extra state lanes each downsample function's finish needs ("n" is always
# present — it carries the output mask).  Restricting the accumulator to
# the needed lanes removes ALL segment scatters from the streamed hot loop
# for the additive family (lo/hi/first/last/prod are the scatter-heavy
# lanes) and shrinks state memory accordingly.
LANES_FOR = {
    "sum": {"total"}, "zimsum": {"total"}, "pfsum": {"total"},
    "count": set(), "avg": {"total"},
    "squareSum": {"total", "m2"}, "dev": {"total", "m2"},
    "min": {"lo"}, "mimmin": {"lo"}, "max": {"hi"}, "mimmax": {"hi"},
    "first": {"first"}, "last": {"last"}, "diff": {"first", "last"},
    "mult": {"prod"},
}
# Downsample functions whose window moments merge associatively (exact) —
# derived from LANES_FOR so the two can never drift.
STREAMABLE_DS = frozenset(LANES_FOR)
_ALL_LANES = frozenset(
    {"total", "m2", "lo", "hi", "first", "last", "prod"})


def lanes_for(ds_functions) -> frozenset:
    """Union of state lanes needed to finish the given ds functions.

    Rank-based (sketch) functions contribute NO moment lanes — their
    state is the sketch lane, enabled by the accumulators' `sketch` flag;
    unknown functions fall back to every lane (conservative).
    """
    out: set = set()
    for fn in ds_functions:
        if is_sketch_ds(fn):
            continue
        out |= LANES_FOR.get(fn, _ALL_LANES)
    if "m2" in out:
        out.add("total")   # the centered pass needs the mean
    return frozenset(out)


def is_sketch_ds(name: str) -> bool:
    """Rank-based downsample functions served by the mergeable quantile
    summary when streaming (median / p* / ep*r3 / ep*r7)."""
    if name == "median":
        return True
    if name.startswith(("p", "ep")) and name not in ("pfsum",):
        from opentsdb_tpu.ops.downsample import parse_percentile_name
        try:
            parse_percentile_name(name)
            return True
        except (KeyError, ValueError):   # non-percentile p*-named fn
            return False
    return False


def _zero_state(s: int, w: int, sketch: bool = False,
                lanes: frozenset | None = None,
                with_oob: bool = False) -> dict:
    """Zero accumulator state holding only the requested lanes
    (None = every lane, the conservative default).  `with_oob` adds the
    0-d audit counter sliced updates maintain — only slice-enabled
    accumulators carry it (the sharded accumulator's shard_map specs are
    rank-2 per leaf)."""
    if lanes is None:
        lanes = _ALL_LANES
    if "m2" in lanes and "total" not in lanes:
        raise ValueError("the m2 lane requires the total lane (use "
                         "lanes_for())")
    builders = {
        "total": lambda: jnp.zeros((s, w), jnp.float64),
        "m2": lambda: jnp.zeros((s, w), jnp.float64),
        "lo": lambda: jnp.full((s, w), jnp.inf, jnp.float64),
        "hi": lambda: jnp.full((s, w), -jnp.inf, jnp.float64),
        "first": lambda: jnp.zeros((s, w), jnp.float64),
        "last": lambda: jnp.zeros((s, w), jnp.float64),
        "prod": lambda: jnp.ones((s, w), jnp.float64),
    }
    state = {"n": jnp.zeros((s, w), jnp.int64)}
    if with_oob:
        # audit counter for window-sliced updates: valid points that
        # fell OUTSIDE the caller-declared window slice (a w0/slice
        # contract violation — see StreamAccumulator.update)
        state["oob"] = jnp.zeros((), jnp.int64)
    for name in lanes:
        state[name] = builders[name]()
    if sketch:
        # q[s, w, j] = value at fractional rank (j+0.5)/K of the cell's
        # population seen so far (midpoint convention); counts live in "n".
        # float32: the sketch's rank error (~chunks/2K) dwarfs f32 value
        # precision by orders of magnitude, and f64 is emulated on TPU.
        state["q"] = jnp.zeros((s, w, SKETCH_K), jnp.float32)
    return state


def _segment_chunk_moments(ts, val, mask, spec: WindowSpec, wargs: dict,
                           lanes: frozenset):
    """Chunk moments for wider-than-data grids: N-bounded sorted scatters.

    When a chunk's window grid has (far) more windows than the chunk has
    points (BASELINE config 2: a 64k-point chunk against a ~1M-window
    10s grid), every edge-search form costs O(W) or worse PER CHUNK —
    the r4 chip session burned its whole config-2 budget there.  Here
    the cost is bounded by the POINT count instead: per-point window ids
    (a division on fixed grids), then one segment reduction per lane
    with `indices_are_sorted=True` — the flattened (row, window) ids are
    genuinely sorted because rows are time-sorted, and invalid slots
    keep their clipped (monotone) id while contributing the lane's
    identity element, never a shuffled sentinel.

    Serves the n/total/m2/lo/hi lanes (the streamable moment family);
    callers keep the edge-search form for first/last/prod/sketch.
    """
    s, n = ts.shape
    w = spec.count
    num = s * w
    vf = val.astype(jnp.float64)
    ok = mask & ~jnp.isnan(vf)
    win = window_ids(ts, spec, wargs)
    nwin = wargs["nwin"]
    valid = ok & (win >= 0) & (win < nwin.astype(win.dtype))
    # int32 ids once clipped in-range: int64 scatter indices are
    # emulated u32 pairs on TPU (the id space s*w is far below 2^31)
    from opentsdb_tpu.ops.group_agg import _seg_dtype
    dt = _seg_dtype(s * w + w)
    winc = jnp.clip(win, 0, w - 1).astype(dt)
    rows = jnp.arange(s, dtype=dt)[:, None]
    seg = (rows * w + winc).reshape(-1)

    def reduce(data, ident, kind="sum"):
        flat = jnp.where(valid, data, ident).reshape(-1)
        fn = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
              "max": jax.ops.segment_max}[kind]
        return fn(flat, seg, num_segments=num,
                  indices_are_sorted=True).reshape(s, w)

    cnt = reduce(jnp.ones_like(vf, dtype=jnp.int32), 0).astype(jnp.int64)
    out = {"n": cnt}
    if "total" in lanes:
        tot = reduce(vf, 0.0)
        out["total"] = tot
        if "m2" in lanes:
            mean = tot / jnp.maximum(cnt, 1)
            mean_pp = jnp.take_along_axis(mean, winc, axis=1)
            centered = jnp.where(valid, vf - mean_pp, 0.0)
            out["m2"] = reduce(centered * centered, 0.0)
    if "lo" in lanes:
        out["lo"] = reduce(vf, jnp.inf, "min")
    if "hi" in lanes:
        out["hi"] = reduce(vf, -jnp.inf, "max")
    return out


# Segment-vs-dense routing threshold for streamed chunks: the segment
# form engages when W > ratio * N.  1.0 is the analytic crossover (per-
# edge search work vs per-point scatter work); the chip session's
# stream_chunk_segment / stream_chunk_dense rows (tools/stage_bench.py)
# measure the real one — TPU scatters serialize, so the measured ratio
# may sit well above 1.  Env override pending a chip-crowned default.
import os as _os

_SEGMENT_CHUNK_RATIO = float(_os.environ.get(
    "TSDB_STREAM_SEGMENT_RATIO", "1.0"))


def set_segment_chunk_ratio(ratio: float) -> None:
    """W/N threshold above which streamed chunks take the segment form;
    clears dependent jit caches (read at trace time)."""
    global _SEGMENT_CHUNK_RATIO
    _SEGMENT_CHUNK_RATIO = float(ratio)
    from opentsdb_tpu.ops.downsample import _clear_dependent_caches
    _clear_dependent_caches()


def _use_segment_chunk(n: int, w: int, lanes: frozenset,
                       with_sketch: bool) -> bool:
    """Route chunks with more windows than points to the segment form:
    past W ~ ratio*N the edge search's per-edge work exceeds the segment
    form's per-point work (config 4 sits at exactly W = 4N; config 2 at
    W = 16N).  first/last/prod and the sketch keep the edge-search form
    (their reductions are position- or sort-based)."""
    return (w > _SEGMENT_CHUNK_RATIO * n and not with_sketch
            and not (lanes & {"first", "last", "prod"}))


# shape: ts[S,N] any, val[S,N] f64, mask[S,N] bool, wargs.first[] i64
# shape: wargs.nwin[] i32
def _chunk_moments(ts, val, mask, spec: WindowSpec, wargs: dict,
                   lanes: frozenset = _ALL_LANES,
                   with_sketch: bool = False):
    """One chunk's per-(series, window) moments, restricted to `lanes`.

    The additive lanes (n/total/m2) ride the scatter-free prefix-sum
    kernel; lo/hi/first/last/prod need per-point window membership and
    cost one segment scatter each — skipped entirely when not requested,
    which is the common case (sum/avg/count queries stream scatter-free).
    Wider-than-data grids (W >> chunk points) take the N-bounded segment
    form instead — see _segment_chunk_moments.
    """
    s, n = ts.shape
    w = spec.count
    if _use_segment_chunk(n, w, lanes, with_sketch):
        return _segment_chunk_moments(ts, val, mask, spec, wargs, lanes)
    # ONE setup shared with the materialized path: same edge search
    # (incl. the search-mode toggle), same int32 compaction, and the
    # clean-batch count shortcut — streamed chunks are clean by
    # construction, so their count lane costs no scan at all.
    vf, ok, cts, idx, windowed, cnt = _window_scan_setup(ts, val, mask,
                                                         spec, wargs)
    out = {"n": cnt}

    need_win = ("m2" in lanes or with_sketch
                or lanes & {"first", "last", "prod"})
    raw_win = _window_ids_fast(ts, cts, spec, wargs) if need_win else None

    if "total" in lanes:
        v0 = jnp.where(ok, vf, 0.0)
        tot = windowed(v0)
        out["total"] = tot
        if "m2" in lanes:
            mean = tot / jnp.maximum(cnt, 1)
            win = jnp.clip(raw_win, 0, w - 1)
            mean_pp = jnp.take_along_axis(mean, win, axis=1)
            centered = jnp.where(ok, vf - mean_pp, 0.0)
            out["m2"] = windowed(centered * centered)

    # lo/hi ride the scatter-free segmented reset-scan — ONE fused scan
    # for both (XLA CSEs the edge-search it shares with the prefix lanes
    # inside this one jit); extreme mode "subblock" swaps in the
    # sub-block decomposition, same as the materialized path
    if lanes & {"lo", "hi"}:
        from opentsdb_tpu.ops import downsample as _ds
        extreme = _ds._extreme_subblock \
            if _ds._use_subblock_extreme(n, w) else _extreme_downsample
        lo, hi, _ = extreme(ts, val, mask, spec, wargs,
                            "lo" in lanes, "hi" in lanes)
        if lo is not None:
            out["lo"] = lo
        if hi is not None:
            out["hi"] = hi

    seg_lanes = lanes & {"first", "last", "prod"}
    if seg_lanes or with_sketch:
        from opentsdb_tpu.ops.group_agg import _seg_dtype
        num = s * w + 1
        dt = _seg_dtype(s * w + w)
        win = jnp.clip(raw_win, 0, w - 1).astype(dt)
        valid = ok & (raw_win >= 0) & (raw_win
                                       < jnp.asarray(w, raw_win.dtype))
        rows = jnp.arange(s, dtype=dt)[:, None]
        seg = jnp.where(valid, rows * w + win,
                        jnp.asarray(s * w, dt)).reshape(-1)
        flat = jnp.where(valid, vf, 0.0).reshape(-1)
        okf = valid.reshape(-1)
        if seg_lanes & {"first", "last"}:
            dtp = _seg_dtype(s * n + 1)      # positions span s*n, not s*w
            pos = jnp.arange(s * n, dtype=dtp)
            flat_v = vf.reshape(-1)
            if "first" in seg_lanes:
                first_i = jax.ops.segment_min(
                    jnp.where(okf, pos, jnp.iinfo(dtp).max), seg,
                    num_segments=num)[:-1]
                out["first"] = flat_v[
                    jnp.clip(first_i, 0, s * n - 1)].reshape(s, w)
            if "last" in seg_lanes:
                last_i = jax.ops.segment_max(
                    jnp.where(okf, pos, -1), seg,
                    num_segments=num)[:-1]
                out["last"] = flat_v[
                    jnp.clip(last_i, 0, s * n - 1)].reshape(s, w)
        if "prod" in seg_lanes:
            out["prod"] = jax.ops.segment_prod(
                jnp.where(okf, flat, 1.0), seg,
                num_segments=num)[:-1].reshape(s, w)
        if with_sketch:
            # Exact per-cell equi-rank grid for this chunk: ONE row sort
            # with (window, value) keys (windows partition each row's
            # points — S independent sorts, not a global [S*N] lexsort),
            # then interpolate K midpoint ranks per cell.
            from jax import lax
            wkey = jnp.where(valid, win.astype(jnp.int32), w)
            svals = jnp.where(valid, vf, jnp.inf)
            _, sorted_rows = lax.sort((wkey, svals), dimension=1,
                                      num_keys=2)
            row_starts = jnp.concatenate(
                [jnp.zeros((s, 1), jnp.int64),
                 jnp.cumsum(cnt, axis=1)], axis=1)[:, :-1]   # [S, W]
            out["q"] = _rank_grid(sorted_rows, row_starts, cnt) \
                .astype(jnp.float32)
    return out


def _rank_grid(sorted_rows, starts, cnt, k: int = SKETCH_K):
    """Exact K-point equi-rank grid per cell from row-sorted runs.

    sorted_rows[S, N] ascending within each (series, window) run (cell
    (s, w) occupies columns [starts[s, w], starts[s, w] + cnt[s, w]);
    non-members +inf past every run).  Returns q[S, W, k]: value at
    fractional rank (j+0.5)/k of each cell via linear interpolation
    between adjacent order statistics; empty cells yield zeros (their
    count is zero, so merges ignore them).
    """
    s, w = cnt.shape
    cf = cnt.astype(jnp.float64)[:, :, None]
    # fractional 0-based rank of target j: (j+0.5)/k * cnt - 0.5
    fr = (jnp.arange(k, dtype=jnp.float64)[None, None, :] + 0.5) / k \
        * cf - 0.5
    fr = jnp.clip(fr, 0.0, jnp.maximum(cf - 1.0, 0.0))
    lo = jnp.floor(fr)
    frac = fr - lo
    top = sorted_rows.shape[1] - 1
    base = starts[:, :, None].astype(jnp.int64)
    i_lo = jnp.clip(base + lo.astype(jnp.int64), 0, top)
    i_hi = jnp.clip(base + lo.astype(jnp.int64) + 1, 0, top)
    # never read past the cell's own run
    last = base + jnp.maximum(cnt[:, :, None].astype(jnp.int64) - 1, 0)
    i_hi = jnp.minimum(i_hi, last)
    v_lo = jnp.take_along_axis(sorted_rows, i_lo.reshape(s, w * k),
                               axis=1).reshape(s, w, k)
    v_hi = jnp.take_along_axis(sorted_rows, i_hi.reshape(s, w * k),
                               axis=1).reshape(s, w, k)
    q = v_lo + frac * (v_hi - v_lo)
    return jnp.where(cnt[:, :, None] > 0, q, 0.0)


def _interp_rows(t, xp, fp):
    """Row-wise linear interpolation, inf-safe.

    Unlike jnp.interp, equal-value brackets return the endpoint instead of
    computing a 0 * (fp_hi - fp_lo) slope — inf - inf would poison grids
    carrying legitimate infinite data values.  t[C, K], xp/fp[C, X].
    """
    x = xp.shape[1]
    idx = jax.vmap(lambda tr, xr: jnp.searchsorted(xr, tr, side="left"))(
        t, xp)
    lo = jnp.clip(idx - 1, 0, x - 1)
    hi = jnp.clip(idx, 0, x - 1)
    x_lo = jnp.take_along_axis(xp, lo, axis=1)
    x_hi = jnp.take_along_axis(xp, hi, axis=1)
    f_lo = jnp.take_along_axis(fp, lo, axis=1)
    f_hi = jnp.take_along_axis(fp, hi, axis=1)
    dx = x_hi - x_lo
    frac = jnp.where(dx > 0, (t - x_lo) / jnp.where(dx > 0, dx, 1.0), 0.0)
    same = (f_lo == f_hi) | (dx <= 0)
    return jnp.where(same, f_lo, f_lo + frac * (f_hi - f_lo))


def _merge_sketch(q1, n1, q2, n2, k: int = SKETCH_K):
    """Weighted merge of two per-cell equi-rank summaries -> one K-grid.

    Each summary point carries weight n/K at its midpoint rank; the merged
    grid re-reads the mixture's cumulative weight at the K new midpoint
    targets.  One compaction moves any quantile's rank by <= 1/(2K) of the
    cell population — the documented per-merge error bound.
    q1/q2: [C, K]; n1/n2: [C].  Returns [C, K].
    """
    nf1 = n1.astype(jnp.float64)[:, None]
    nf2 = n2.astype(jnp.float64)[:, None]
    v = jnp.concatenate([q1, q2], axis=1)                    # [C, 2K]
    wt = jnp.concatenate([jnp.broadcast_to(nf1 / k, q1.shape),
                          jnp.broadcast_to(nf2 / k, q2.shape)], axis=1)
    # Zero-weight points (an empty side) must not perturb interpolation:
    # sort them last via an inf key, then REPLACE them with the row's max
    # carried value — their cum ranks are flat at the total, so any target
    # interpolating into that region reads the max instead of poisoning
    # the grid (a 0-clamp would break sortedness and decay every
    # subsequent merge).  A sentinel FLAG (not isfinite) distinguishes
    # them from legitimate +inf data values, which must survive so the
    # streamed and exact paths agree on inf-bearing series.
    sentinel = wt <= 0
    key = jnp.where(sentinel, jnp.inf, v)
    order = jnp.argsort(key, axis=1)
    v = jnp.take_along_axis(v, order, axis=1)
    wt = jnp.take_along_axis(wt, order, axis=1)
    sentinel = jnp.take_along_axis(sentinel, order, axis=1)
    vmax = jnp.max(jnp.where(sentinel, -jnp.inf, v), axis=1, keepdims=True)
    v = jnp.where(sentinel, vmax, v)
    cum = jnp.cumsum(wt, axis=1) - 0.5 * wt                  # midpoint ranks
    total = nf1 + nf2
    targets = (jnp.arange(k, dtype=jnp.float64)[None, :] + 0.5) / k * total
    merged = _interp_rows(targets, cum, v)
    both_zero = (n1 + n2) <= 0
    return jnp.where(both_zero[:, None], 0.0, merged).astype(q1.dtype)


def sketch_quantile(q, n, pct):
    """Estimate the pct-quantile (0-100) from summaries q[..., K], n[...].

    Linear interpolation on the midpoint-rank grid (R-7-flavored); the
    ep*r3/r7 estimator distinction is below the sketch's rank error and is
    deliberately collapsed here (documented approximation).
    """
    k = q.shape[-1]
    nf = jnp.maximum(n.astype(jnp.float64), 1.0)
    lead = q.shape[:-1]
    qs = q.reshape(-1, k)
    nfs = nf.reshape(-1, 1)
    mid = (jnp.arange(k, dtype=jnp.float64)[None, :] + 0.5) / k * nfs
    target = jnp.asarray(pct, jnp.float64) / 100.0 * nfs[:, 0]
    out = _interp_rows(target[:, None], mid, qs)[:, 0]
    return out.reshape(lead)


def _merge(state: dict, chunk: dict) -> dict:
    """Associative merge of two moment sets, per present lane (Chan et al.
    for m2)."""
    n1, n2 = state["n"], chunk["n"]
    n = n1 + n2
    had = n1 > 0
    got = n2 > 0
    merged = {"n": n}
    if "total" in state:
        t1, t2 = state["total"], chunk["total"]
        merged["total"] = t1 + t2
        if "m2" in state:
            safe_n = jnp.maximum(n, 1).astype(jnp.float64)
            nf1 = n1.astype(jnp.float64)
            nf2 = n2.astype(jnp.float64)
            # delta = mean2 - mean1 with empty sides contributing zero.
            mean1 = t1 / jnp.maximum(nf1, 1.0)
            mean2 = t2 / jnp.maximum(nf2, 1.0)
            delta = jnp.where(had & got, mean2 - mean1, 0.0)
            merged["m2"] = (state["m2"] + chunk["m2"]
                            + delta * delta * nf1 * nf2 / safe_n)
    if "lo" in state:
        merged["lo"] = jnp.minimum(state["lo"], chunk["lo"])
    if "hi" in state:
        merged["hi"] = jnp.maximum(state["hi"], chunk["hi"])
    # Chunks arrive in time order: first sticks, last overwrites.
    if "first" in state:
        merged["first"] = jnp.where(had, state["first"], chunk["first"])
    if "last" in state:
        merged["last"] = jnp.where(got, chunk["last"], state["last"])
    if "prod" in state:
        merged["prod"] = state["prod"] * chunk["prod"]
    if "q" in state:
        s, w, k = state["q"].shape
        merged["q"] = _merge_sketch(
            state["q"].reshape(-1, k), n1.reshape(-1),
            chunk["q"].reshape(-1, k), n2.reshape(-1)).reshape(s, w, k)
    if "oob" in state:
        merged["oob"] = state["oob"] + chunk.get("oob", 0)
    return merged


def _update(spec: WindowSpec, state: dict, ts, val, mask, wargs: dict):
    lanes = frozenset(state) & _ALL_LANES
    return _merge(state, _chunk_moments(ts, val, mask, spec, wargs,
                                        lanes=lanes,
                                        with_sketch="q" in state))


# shape: ts[S,N] any, val[S,N] f64, mask[S,N] bool, wargs.first[] i64
# shape: wargs.nwin[] i32
def _update_sliced(spec: WindowSpec, wc: int, state: dict, ts, val, mask,
                   wargs: dict, w0):
    """Fold a chunk whose windows live in [w0, w0 + wc) of the grid.

    The full-grid update computes and merges [S, W] moment grids PER
    CHUNK — for wider-than-data streams (BASELINE config 2: an 8.4M-pt
    chunk against a 721k-window grid) that is O(S*W) state traffic and a
    92M-segment scatter per chunk, which is where the measured
    4.7s/chunk went (chip, r04b).  A time-ordered chunk only ever
    touches a contiguous window range, so: compute the chunk's moments
    on a LOCAL wc-window grid (same kernels, wc static), merge them into
    the state's [w0, w0+wc) slice, and write the slice back —
    O(S*wc + points) per chunk, W-independent.

    w0 is caller-declared (the planner/bench know each chunk's time
    range on the host); valid points OUTSIDE the declared slice are
    counted into state["oob"] instead of being silently dropped, so a
    wrong w0 is detectable (StreamAccumulator.oob_count()).  Fixed
    grids only.
    """
    from jax import lax

    if spec.kind != "fixed":
        raise ValueError("sliced streaming updates require a fixed grid")
    w_total = spec.count
    lanes = frozenset(state) & _ALL_LANES
    w0 = jnp.clip(jnp.asarray(w0, jnp.int64), 0, max(w_total - wc, 0))

    spec_l = WindowSpec("fixed", wc, spec.interval_ms)
    wargs_l = dict(wargs)
    wargs_l["first"] = wargs["first"] + w0 * spec.interval_ms
    wargs_l["nwin"] = jnp.clip(
        wargs["nwin"] - w0.astype(jnp.int32), 0, wc).astype(jnp.int32)
    chunk = _chunk_moments(ts, val, mask, spec_l, wargs_l, lanes=lanes,
                           with_sketch="q" in state)

    # slice-merge: every lane is a per-cell associative merge, so merging
    # the slice equals merging the full grid (cells outside the slice
    # receive only identity contributions from this chunk)
    cur = {}
    for k in state:
        if k == "oob":
            continue
        if k == "q":
            s, _, kq = state["q"].shape
            cur["q"] = lax.dynamic_slice(state["q"], (0, w0, 0),
                                         (s, wc, kq))
        else:
            s = state[k].shape[0]
            cur[k] = lax.dynamic_slice(state[k], (0, w0), (s, wc))
    merged = _merge(cur, chunk)
    new_state = dict(state)
    for k, v in merged.items():
        starts = (0, w0, 0) if k == "q" else (0, w0)
        new_state[k] = lax.dynamic_update_slice(state[k], v, starts)

    # audit: valid in-grid points the declared slice missed.  No
    # per-point division: in-grid membership is a timestamp range
    # compare, and the points the slice DID fold are exactly the live
    # cells of the local count lane the kernels already computed.
    ok = mask & ~jnp.isnan(val.astype(jnp.float64))
    tsa = _absolute_ts(ts, wargs)
    lo = wargs["first"]
    hi = lo + wargs["nwin"].astype(jnp.int64) * spec.interval_ms
    in_grid_total = jnp.sum(ok & (tsa >= lo) & (tsa < hi))
    live_l = jnp.arange(wc, dtype=jnp.int32)[None, :] < wargs_l["nwin"]
    folded = jnp.sum(jnp.where(live_l, chunk["n"], 0))
    new_state["oob"] = state["oob"] + (in_grid_total - folded)
    return new_state


# State buffers are DONATED: the accumulator grid can reach GBs (config 2:
# [128, 2^20] x 4 lanes ~ 3.5 GB), and without donation every queued async
# update holds old state + chunk moments + new state — the r3 chip run
# crashed the TPU worker exactly there.  Donation lets XLA alias the
# state in/out buffers so the peak stays ~one state + one chunk.  The
# caller never touches the pre-update state again (StreamAccumulator
# replaces self.state at enqueue).
_jitted_update = jax.jit(_update, static_argnums=0, donate_argnums=1)
_jitted_update_sliced = jax.jit(_update_sliced, static_argnums=(0, 1),
                                donate_argnums=2)


def _finish(spec: WindowSpec, ds_function: str, fill_policy: str,
            state: dict, wargs: dict, fill_value):
    """Final per-series downsampled grid from accumulated moments."""
    missing = LANES_FOR.get(ds_function, frozenset()) - frozenset(state)
    if missing:
        raise KeyError(
            "accumulator lacks lane(s) %s for %s — create it with "
            "lanes=lanes_for([...]) covering every finish function"
            % (sorted(missing), ds_function))
    n = state["n"]
    safe = jnp.maximum(n, 1)
    if ds_function in ("sum", "zimsum", "pfsum"):
        out = state["total"]
    elif ds_function == "count":
        out = n.astype(jnp.float64)
    elif ds_function == "avg":
        out = state["total"] / safe
    elif ds_function == "squareSum":
        # sumsq = M2 + total^2/n (exact algebraic identity).
        out = state["m2"] + state["total"] * state["total"] / safe
    elif ds_function == "dev":
        out = jnp.where(n >= 2, jnp.sqrt(state["m2"]
                                         / jnp.maximum(n - 1, 1)), 0.0)
    elif ds_function in ("min", "mimmin"):
        out = state["lo"]
    elif ds_function in ("max", "mimmax"):
        out = state["hi"]
    elif ds_function == "first":
        out = state["first"]
    elif ds_function == "last":
        out = state["last"]
    elif ds_function == "diff":
        out = jnp.where(n >= 2, state["last"] - state["first"], 0.0)
    elif ds_function == "mult":
        out = state["prod"]
    elif "q" in state and is_sketch_ds(ds_function):
        # Approximate (rank error ~chunks/(2K), see module docstring);
        # median uses the 50th pct of the summary rather than the exact
        # upper-median convention — the gap is below the sketch error.
        if ds_function == "median":
            pct = 50.0
        else:
            from opentsdb_tpu.ops.downsample import parse_percentile_name
            pct, _est = parse_percentile_name(ds_function)
        out = sketch_quantile(state["q"], n, pct)
    else:
        raise KeyError("Downsample function does not stream: " + ds_function)
    w = spec.count
    live = jnp.arange(w, dtype=jnp.int32)[None, :] < wargs["nwin"]
    out_mask = (n > 0) & live
    out, out_mask = apply_fill(out, out_mask, live, fill_policy, fill_value,
                               jnp.float64)
    wts = window_timestamps(spec, wargs)
    return wts, out, out_mask


_jitted_finish = jax.jit(_finish, static_argnums=(0, 1, 2))


def quantize_window_slice(window_slice, spec: WindowSpec):
    """Static sliced-update width from a requested chunk window span.

    Quantized up for jit-cache stability across similar streams, but
    gently: full pow2 padding would double the slice (and every
    per-chunk fold) at just-past-a-power shapes.  None when slicing
    cannot help (non-fixed grid, or the slice would cover the grid)."""
    if window_slice is None or spec.kind != "fixed":
        return None
    ws = max(int(window_slice), 1)
    bucket = 1 << max(6, ws.bit_length() - 3)
    wc = min(-(-ws // bucket) * bucket, spec.count)
    return None if wc >= spec.count else wc


@dataclass
class StreamAccumulator:
    """Device-resident per-(series, window) moment state fed chunk by chunk.

    Usage::

        acc = StreamAccumulator.create(num_series, window_spec, wargs)
        for chunk in chunks:            # increasing time order
            acc.update(ts, val, mask)   # [S, n_chunk] padded batches
        wts, values, mask = acc.finish("avg")
    """
    spec: WindowSpec
    wargs: dict
    state: dict
    window_slice: int | None = None

    @staticmethod
    def create(num_series: int, spec: WindowSpec, wargs: dict,
               sketch: bool = False,
               lanes: frozenset | None = None,
               window_slice: int | None = None) -> "StreamAccumulator":
        """`sketch=True` adds the [S, W, K] quantile-summary lane so
        rank-based downsample functions can finish (approximate).
        `lanes` (from lanes_for()) restricts state to what the finish
        functions need — sum/avg/count stream scatter-free.
        `window_slice` (fixed grids only) enables O(S*wc)-per-chunk
        sliced updates for wider-than-data streams: the static count of
        windows any single chunk can span; callers then pass each
        chunk's first window index to update(w0=...)."""
        wc = quantize_window_slice(window_slice, spec)
        return StreamAccumulator(spec, wargs,
                                 _zero_state(num_series, spec.count,
                                             sketch, lanes,
                                             with_oob=wc is not None),
                                 wc)

    def update(self, ts, val, mask, w0: int | None = None) -> None:
        """Fold one [S, n] chunk in (async — returns at enqueue).

        `w0`: index of the first grid window this chunk's points can
        touch (host-known for time-ordered chunking).  With a
        window_slice-enabled accumulator this routes to the sliced
        update — the chunk must fit in [w0, w0 + window_slice); points
        outside are counted in oob_count() rather than folded."""
        if w0 is not None and self.window_slice is not None:
            self.state = _jitted_update_sliced(
                self.spec, self.window_slice, self.state, ts, val, mask,
                self.wargs, w0)
        else:
            self.state = _jitted_update(self.spec, self.state, ts, val,
                                        mask, self.wargs)

    def oob_count(self) -> int:
        """Valid points sliced updates missed (w0 contract violations);
        0 in correct use.  Host sync."""
        if "oob" not in self.state:
            return 0
        return int(np.asarray(self.state["oob"]))

    def finish(self, ds_function: str, fill_policy: str = FILL_NONE,
               fill_value: float = 0.0):
        """(window_ts[W], values[S, W], mask[S, W]) — the downsample output."""
        return _jitted_finish(self.spec, ds_function, fill_policy,
                              self.state, self.wargs, fill_value)
