"""Out-of-core tiled execution: series-tiled streaming past the HBM wall.

ROADMAP item 4.  The streaming executor (ops/streaming.py) already
bounds the POINT axis — chunks fold into a device-resident [S, W]
moment grid — but the grid itself is the remaining wall: a months-long
range at a fine interval times a high-cardinality group-by exceeds
``tsd.query.streaming.state_mb`` and the planner used to refuse it with
a 413 at three duplicated sites.  This module executes those plans
instead, in the spilled-window-aggregation stance (arXiv:2007.10385):

  1. **Series tiling.**  The series axis splits into costmodel-sized
     tiles; each tile's [S_tile, W] accumulator fits the device budget
     by construction.  Every tile streams its time-chunks through the
     existing ``StreamAccumulator`` — same kernels, same merges, same
     double-buffering (the host packs chunk k+1 while the device
     reduces chunk k; JAX async dispatch).  When the device series
     cache holds the metric's columns pinned, a tile whose padded
     batch fits serves in one on-device gather instead of chunking.

  2. **Row-local finish, then spill.**  Rate and per-series grid
     contributions (the interpolation + participation step of
     AggregationIterator's missing-point substitution) are ROW-LOCAL
     (`ops.group_agg.grid_contributions` docstring) — each tile holds
     complete rows, so both run per tile on the full-width grid with
     no cross-tile carries.  The finished per-tile (contrib,
     participate, actual-mask) grids spill to the bounded pool
     (storage/spill.py), pre-split into window stripes so the
     assembly pass reads ~its own bytes per stripe.

  3. **Window-striped tail replay.**  The remaining stage — the
     per-(group, window) cross-series reduce — is WINDOW-LOCAL, so the
     shared ``run_grid_tail`` (rate already applied; spec replayed with
     ``rate=None``) runs over [S_total, stripe] column bands: the full
     [S_total, W] grid never materializes anywhere, host or device.
     Replaying contributions through ``grid_contributions`` is exact:
     participation regions are contiguous per row, so the recomputation
     is the identity on every participating cell, and group-by
     reduction over a stripe equals the same reduction over the full
     grid restricted to those columns (associative per cell).  The
     out-mask comes from the spilled ACTUAL mask (a cell is present
     only where a member holds a real value, not an interpolated one —
     the same rule the resident tail applies).

The tiled-vs-refuse decision and its price come from the fitted
costmodel: ``costmodel.features_tiled`` / ``predict_tiled`` stay a dot
product against ``COST_TERMS`` (spill write/read MB, per-tile dispatch
overhead) per the linearity contract, `tsd/admission.py` prices the
tiled plan with the same vector instead of shedding it, and every
tiled pipeline span carries a ``tiling`` annotation (tile count, spill
bytes, decision source).  Tiled executions are deliberately EXCLUDED
from the calibration ring, like partial-aggregate rewrites: the
monolithic stage breakdown does not describe a tiled execution
(pinned by tests/test_tiling.py).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace

import jax
import jax.numpy as jnp
import numpy as np

from opentsdb_tpu.ops.downsample import pad_pow2
from opentsdb_tpu.ops.pipeline import PAD_TS, run_grid_tail
from opentsdb_tpu.ops.streaming import StreamAccumulator

# Per-cell byte weights for plan sizing.  Spill entries hold contrib
# (f64) + participate (bool) + actual mask (bool) per (series, window)
# cell; the tile's device working set holds the accumulator state plus
# the finished/contribution grids; an assembled stripe holds the three
# spill lanes for every series plus the [G, stripe] output.
SPILL_CELL_BYTES = 10
TILE_WORK_CELL_BYTES = 26
STRIPE_CELL_BYTES = 24


@dataclass(frozen=True)
class TilePlan:
    """A sized tiled execution: how the series/window axes split."""
    tile_rows: int       # series per tile (last tile may be smaller)
    n_tiles: int
    stripe_w: int        # windows per assembly stripe
    n_stripes: int
    spill_bytes: int     # total partial-grid bytes through the pool
    dispatches: int      # extra launches a tiled plan issues
    predicted_s: float   # tiled OVERHEAD prediction (costmodel)
    source: str          # calibration layer that priced it


def size_tiles(s: int, w: int, budget_bytes: int, acc_cell_bytes: int,
               g_pad: int, max_tiles: int,
               chunks_per_tile: int = 1) -> TilePlan | None:
    """Pure sizing: split [s, w] so every device-resident piece fits
    ``budget_bytes``.  None when no split can (a single row's [1, w]
    state, or a single-window stripe over all series, still busts the
    budget — the genuine refusal case)."""
    if s < 1 or w < 1 or budget_bytes <= 0:
        return None
    per_row = w * max(acc_cell_bytes, TILE_WORK_CELL_BYTES)
    tile_rows = budget_bytes // per_row
    if tile_rows < 1:
        return None
    tile_rows = min(int(tile_rows), s)
    n_tiles = -(-s // tile_rows)
    if max_tiles > 0 and n_tiles > max_tiles:
        return None
    stripe_w = budget_bytes // ((s + g_pad) * STRIPE_CELL_BYTES)
    if stripe_w < 1:
        return None
    if stripe_w >= w:
        stripe_w = w
    else:
        # pow2 stripe widths: one compiled tail shape per plan family
        stripe_w = 1 << max(int(stripe_w).bit_length() - 1, 0)
    n_stripes = -(-w // stripe_w)
    spill_bytes = s * w * SPILL_CELL_BYTES
    # launches beyond what a resident plan issues: per-tile chunk folds
    # + finish/contrib, per-stripe tail + presence
    dispatches = n_tiles * (chunks_per_tile + 2) + 2 * n_stripes
    return TilePlan(tile_rows, n_tiles, stripe_w, n_stripes, spill_bytes,
                    dispatches, 0.0, "default")


def count_refusal(reason: str) -> None:
    """One over-budget plan the tiled path could not serve (still a
    413), counted by reason for the operator dashboard."""
    from opentsdb_tpu.obs.registry import REGISTRY
    REGISTRY.counter(
        "tsd.query.spill.refusals",
        "Over-budget plans the tiled path could not serve (still "
        "413), by reason").labels(reason=reason).inc()


# effects: observe-gated(observe)
def plan_tiled(tsdb, *, s: int, w: int, g_pad: int, acc_cell_bytes: int,
               total_points: int, platform: str,
               state_mb: int | None = None,
               observe: bool = True) -> TilePlan | None:
    """Size and price a tiled execution for an over-budget [s, w] plan.

    Returns None (with the refusal reason counted under
    ``tsd.query.spill.refusals``) when the pool is disabled, the spill
    bytes exceed the pool's combined budgets, or no tile split fits the
    device budget.  ``observe=False`` (the explain engine's dry-run)
    suppresses the refusal counters; ``state_mb`` overrides the
    configured device budget for what-if sizing."""
    from opentsdb_tpu.ops import costmodel as cm

    refuse = count_refusal if observe else (lambda reason: None)
    pool = getattr(tsdb, "spill_pool", None)
    if pool is None:
        refuse("disabled")
        return None
    if state_mb is None:
        state_mb = tsdb.config.get_int("tsd.query.streaming.state_mb")
    budget_bytes = state_mb * 2**20
    chunk_points = max(tsdb.config.get_int(
        "tsd.query.streaming.chunk_points"), 1)
    max_tiles = tsdb.config.get_int("tsd.query.spill.max_tiles")
    chunks_per_tile = max(int(math.ceil(total_points
                                        / max(chunk_points, 1))), 1)
    plan = size_tiles(s, w, budget_bytes, acc_cell_bytes, g_pad,
                      max_tiles, chunks_per_tile)
    if plan is None:
        refuse("no_fit")
        return None
    # one stripe-entry of slack: demotion is per-entry, so up to one
    # entry of disk headroom can go unusable at the boundary — a plan
    # admitted here must never die mid-query with a capacity error
    entry_bytes = plan.tile_rows * plan.stripe_w * SPILL_CELL_BYTES
    if plan.spill_bytes + entry_bytes \
            > pool.host_budget + pool.disk_budget:
        refuse("pool_budget")
        return None
    predicted = cm.predict_tiled(s, w, g_pad, plan.n_tiles,
                                 plan.n_stripes, plan.spill_bytes,
                                 plan.dispatches, platform)
    return replace(plan, predicted_s=predicted,
                   source=cm.calibration_source(platform))


# --------------------------------------------------------------------- #
# Per-tile finish kernels                                                #
# --------------------------------------------------------------------- #

def _tile_contrib(spec, wts, v, m):
    """Row-local tail prefix on one tile's finished [S_tile, W] grid:
    rate (when the spec has one), then the per-series contribution +
    participation grids the cross-series reduce consumes.  Exactly the
    computation ``pipeline._grid_tail`` performs before its group
    reduce, so a striped replay of the remainder reproduces the
    resident tail."""
    from opentsdb_tpu.ops.aggregators import PREV, Aggregator, get_agg
    from opentsdb_tpu.ops.group_agg import grid_contributions
    from opentsdb_tpu.ops.rate import rate

    agg = get_agg(spec.aggregator)
    grid = jnp.asarray(wts)
    if spec.rate is not None:
        agg = Aggregator(agg.name, PREV, agg.reduce)
        grid_b = jnp.broadcast_to(grid[None, :], v.shape)
        _, v, m = rate(grid_b, v, m, spec.rate, all_int=False)
    contrib, participate = grid_contributions(
        grid, v.astype(jnp.float64), m, agg)
    return contrib, participate, m


def _group_presence(num_groups: int, mask, gid):
    """[S, W] actual-value mask + gid[S] -> [G, W] any-member-present —
    the resident tail's out-mask rule, window-local."""
    from opentsdb_tpu.ops.group_agg import _seg_dtype
    s, w = mask.shape
    dt = _seg_dtype(num_groups * w + w)
    cols = jnp.arange(w, dtype=dt)[None, :]
    seg = (gid.astype(dt)[:, None] * w + cols).reshape(-1)
    present = jax.ops.segment_sum(
        mask.reshape(-1).astype(jnp.int32), seg,
        num_segments=num_groups * w)
    return present.reshape(num_groups, w) > 0


_jitted_tile_contrib = jax.jit(_tile_contrib, static_argnums=0)
_jitted_presence = jax.jit(_group_presence, static_argnums=0)


# Cross-series aggregators whose group reduce folds tile-by-tile into
# [G, W] partial moments (sum/count for the additive family, min/max
# for the extremes) — the same partial-moment decomposition
# moment_group_reduce's combine_* hooks use across mesh shards.
# Everything else (dev's two-pass, rank/order aggs) needs all rows at
# once and keeps the spill-pool stripe replay.
LANE_FOLDABLE = frozenset({"sum", "zimsum", "count", "avg",
                           "min", "mimmin", "max", "mimmax"})


def _lane_fold(spec, num_groups: int, extreme: bool, wts, v, m, gid):
    """One tile's [G, W] partial group moments from its finished grid.

    Runs the SAME row-local contribution step as the stripe replay
    (_tile_contrib: rate + interpolation/participation), then reduces
    this tile's rows straight to per-(group, window) partials — sum +
    count (additive) or min/max + count (extremes) plus the
    actual-value presence the out-mask derives from.  Partials merge
    across tiles by +/min/max/| and one host-side finish reproduces
    moment_group_reduce's arithmetic on identical operands, so the
    fold is exact (bitwise on integer data) while the full [S, W]
    grid never exists on the device."""
    from opentsdb_tpu.ops.group_agg import _seg_dtype
    contrib, participate, actual = _tile_contrib(spec, wts, v, m)
    s, w = contrib.shape
    num = num_groups * w
    dt = _seg_dtype(num + w)
    cols = jnp.arange(w, dtype=dt)[None, :]
    seg = (gid.astype(dt)[:, None] * w + cols).reshape(-1)
    vf = contrib.astype(jnp.float64)
    flat = vf.reshape(-1)
    ok2 = (participate & ~jnp.isnan(vf)).reshape(-1)
    cnt = jax.ops.segment_sum(ok2.astype(jnp.int32), seg,
                              num_segments=num).reshape(num_groups, w)
    present = jax.ops.segment_sum(
        actual.reshape(-1).astype(jnp.int32), seg,
        num_segments=num).reshape(num_groups, w)
    if extreme:
        lo = jax.ops.segment_min(jnp.where(ok2, flat, jnp.inf), seg,
                                 num_segments=num
                                 ).reshape(num_groups, w)
        hi = jax.ops.segment_max(jnp.where(ok2, flat, -jnp.inf), seg,
                                 num_segments=num
                                 ).reshape(num_groups, w)
        return lo, hi, cnt, present
    tot = jax.ops.segment_sum(jnp.where(ok2, flat, 0.0), seg,
                              num_segments=num).reshape(num_groups, w)
    return tot, cnt, present


_jitted_lane_fold = jax.jit(_lane_fold, static_argnums=(0, 1, 2))


def run_lane_fold(spec, num_groups: int, extreme: bool, wts, v, m,
                  gid_tile):
    """One tile's partial group moments (see _lane_fold)."""
    return _jitted_lane_fold(spec, num_groups, extreme, wts, v, m,
                             gid_tile)


# --------------------------------------------------------------------- #
# Executor                                                               #
# --------------------------------------------------------------------- #

def _stream_tile(tsdb, seg, tile_series, window_spec, wargs, lanes,
                 sketch: bool, fix: bool, store,
                 ds_function: str, fill_policy: str,
                 fill_value: float) -> tuple:
    """One tile's finished (wts, values, mask) downsample grid.

    Device-cache fast path first: a metric pinned in HBM whose padded
    [S_tile, N] batch fits the cache's batch budget serves in one
    on-device gather.  Otherwise the chunked streaming loop — per-series
    timestamp cursors, one [S_tile, n_chunk] compile, async overlap,
    the same sliced-update sizing the resident streamed path uses."""
    from opentsdb_tpu.ops.pipeline import run_downsample_grid

    s = len(tile_series)
    if tsdb.device_cache is not None and store is not None:
        batch = tsdb.device_cache.batch_for(
            store, tile_series[0].key.metric, tile_series,
            seg.start_ms, seg.end_ms, fix, build=False)
        if batch is not None:
            from opentsdb_tpu.ops.pipeline import DownsampleStep
            ts, val, mask = batch
            step = DownsampleStep(ds_function, window_spec, fill_policy,
                                  fill_value)
            return run_downsample_grid(step, ts, val, mask, wargs), 1

    chunk_points = max(tsdb.config.get_int(
        "tsd.query.streaming.chunk_points"), 1)
    n_chunk = pad_pow2(max(1024, chunk_points // max(s, 1)))
    use_slice = window_spec.kind == "fixed"
    first_ms = int(np.asarray(wargs["first"])) if use_slice else 0
    interval = window_spec.interval_ms
    max_len = max((sr.window_count(seg.start_ms, seg.end_ms, fix)
                   for sr in tile_series), default=0)
    n_chunks_total = -(-max_len // n_chunk) if max_len else 0
    cursors: list = [None] * s
    acc = None
    for chunk_i in range(n_chunks_total):
        ts = np.full((s, n_chunk), PAD_TS, np.int64)
        val = np.zeros((s, n_chunk), np.float64)
        mask = np.zeros((s, n_chunk), bool)
        tmin = tmax = None
        for i, series in enumerate(tile_series):
            t, fv = series.window_chunk(seg.start_ms, seg.end_ms,
                                        cursors[i], n_chunk, fix)
            m = len(t)
            if m:
                ts[i, :m] = t
                val[i, :m] = fv
                mask[i, :m] = True
                cursors[i] = int(t[-1])
                tmin = int(t[0]) if tmin is None else min(tmin, int(t[0]))
                tmax = int(t[-1]) if tmax is None else max(tmax,
                                                           int(t[-1]))
        if tmin is None:
            continue
        if acc is None:
            wslice = None
            if use_slice:
                wslice = 2 * ((tmax - tmin) // interval + 2)
            acc = StreamAccumulator.create(s, window_spec, wargs,
                                           sketch=sketch, lanes=lanes,
                                           window_slice=wslice)
        w0 = None
        if acc.window_slice is not None \
                and (tmax - tmin) // interval + 2 <= acc.window_slice:
            w0 = (tmin - first_ms) // interval
        acc.update(jnp.asarray(ts), jnp.asarray(val), jnp.asarray(mask),
                   w0=w0)
        if (chunk_i + 1) % 16 == 0:
            # backpressure: drain the async queue (see _stream_grouped)
            np.asarray(acc.state["n"][:1, :1])
    if acc is None:
        acc = StreamAccumulator.create(s, window_spec, wargs,
                                       sketch=sketch, lanes=lanes)
    if acc.oob_count():
        raise RuntimeError(
            "internal: %d points fell outside their declared tiled "
            "streaming window slice" % acc.oob_count())
    return (acc.finish(ds_function, fill_policy, fill_value),
            max(n_chunks_total, 1))


def run_tiled(tsdb, spec, seg, series_list, gid, g_pad: int, window_spec,
              wargs, ds_function: str, lanes, sketch: bool, fix: bool,
              plan: TilePlan, budget, store=None, tile_grid_fn=None):
    """Execute an over-budget grouped downsample plan tiled.

    Returns ((out_ts, out_val[g_pad, W], out_mask[g_pad, W]) as numpy,
    stats dict for the span annotation).  Every spilled entry is
    released on every exit path; a pool failure surfaces as the 413/503
    query contract, never a leak.

    ``tile_grid_fn(row_lo, row_hi) -> (wts[W], v[S_tile, W],
    m[S_tile, W])`` substitutes the tile's finished downsample grid for
    the streamed build — the rollup-lane executor (storage/rollup.py)
    serves over-budget plans through the SAME spill + window-striped
    tail replay with grids derived from lane partials instead of raw
    points."""
    from opentsdb_tpu.obs.registry import REGISTRY
    from opentsdb_tpu.query.limits import QueryException
    from opentsdb_tpu.storage.spill import SpillError, SpillWriteError

    pool = tsdb.spill_pool
    step = spec.downsample
    s = len(series_list)
    w = window_spec.count
    spec_tail = replace(spec, rate=None)
    gid_dev = jnp.asarray(np.asarray(gid, np.int64))
    stripes = [(i * plan.stripe_w, min((i + 1) * plan.stripe_w, w))
               for i in range(plan.n_stripes)]
    keys: list = []           # every pooled key, released in finally
    # entry keys per (tile, stripe)
    grid_keys: list[list] = []
    tile_bounds = [(lo, min(lo + plan.tile_rows, s))
                   for lo in range(0, s, plan.tile_rows)]
    wts_full = None
    spilled_bytes = 0
    chunks_total = 0
    try:
        for t_i, (lo, hi) in enumerate(tile_bounds):
            budget.check_deadline()
            if tile_grid_fn is not None:
                wts, v, m = tile_grid_fn(lo, hi)
                n_chunks = 1
            else:
                (wts, v, m), n_chunks = _stream_tile(
                    tsdb, seg, series_list[lo:hi], window_spec, wargs,
                    lanes, sketch, fix, store, ds_function,
                    step.fill_policy, step.fill_value)
            chunks_total += n_chunks
            contrib, participate, actual = _jitted_tile_contrib(
                spec, wts, v, m)
            if wts_full is None:
                wts_full = np.asarray(wts)
            contrib = np.asarray(contrib)
            participate = np.asarray(participate)
            actual = np.asarray(actual)
            REGISTRY.counter(
                "tsd.query.spill.tiles",
                "Series tiles executed by the out-of-core path").inc()
            row = []
            for (w0, w1) in stripes:
                entry = (contrib[:, w0:w1], participate[:, w0:w1],
                         actual[:, w0:w1])
                try:
                    key = pool.put(entry)
                except SpillWriteError as e:
                    raise QueryException(
                        "Sorry, the spill pool backing this tiled "
                        "query failed to write (%s); please retry."
                        % e, status=503)
                except SpillError as e:
                    raise QueryException(
                        "Sorry, this query's partial aggregates "
                        "(%d series x %d windows, ~%dMB) exceed the "
                        "spill pool budget (tsd.query.spill.*): %s"
                        % (s, w, plan.spill_bytes // 2**20, e))
                keys.append(key)
                row.append(key)
                spilled_bytes += sum(a.nbytes for a in entry)
            grid_keys.append(row)
        # ---- window-striped tail replay ---------------------------- #
        out_val = np.zeros((g_pad, w), np.float64)
        out_mask = np.zeros((g_pad, w), bool)
        ws = plan.stripe_w
        for s_i, (w0, w1) in enumerate(stripes):
            budget.check_deadline()
            n = w1 - w0
            V = np.zeros((s, ws), np.float64)
            P = np.zeros((s, ws), bool)
            A = np.zeros((s, ws), bool)
            for t_i, (lo, hi) in enumerate(tile_bounds):
                key = grid_keys[t_i][s_i]
                cv, cp, ca = pool.get(key)
                V[lo:hi, :n] = cv
                P[lo:hi, :n] = cp
                A[lo:hi, :n] = ca
                pool.free(key)
            # stripe timestamps: pad short edge stripes by repeating
            # the last value (only read for non-participating cells)
            wts_s = np.empty(ws, wts_full.dtype)
            wts_s[:n] = wts_full[w0:w1]
            if n < ws:
                wts_s[n:] = wts_full[w1 - 1]
            _, ov, _om = run_grid_tail(spec_tail, jnp.asarray(wts_s),
                                       jnp.asarray(V), jnp.asarray(P),
                                       gid_dev, g_pad)
            pres = _jitted_presence(g_pad, jnp.asarray(A), gid_dev)
            out_val[:, w0:w1] = np.asarray(ov)[:, :n]
            out_mask[:, w0:w1] = np.asarray(pres)[:, :n]
        stats = {"tiles": plan.n_tiles, "stripes": plan.n_stripes,
                 "spillBytes": int(spilled_bytes),
                 "chunks": int(chunks_total),
                 "predictedMs": round(plan.predicted_s * 1e3, 3),
                 "source": plan.source}
        recorder = getattr(tsdb, "flightrec", None)
        if recorder is not None:
            # retained spill evidence: tile/stripe split + bytes
            # through the pool (host-ring demotions surface in the
            # tsd.query.spill.* gauges; the event ties the traffic to
            # the query's trace id)
            recorder.record("tiling", series=s, windows=w, **stats)
        return (wts_full, out_val, out_mask), stats
    finally:
        pool.release(keys)
