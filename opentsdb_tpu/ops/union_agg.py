"""Cross-series aggregation at the union of timestamps, with interpolation.

Reference behavior: /root/reference/src/core/AggregationIterator.java — the
k-way merge that emits one aggregated value at every timestamp any series in
the group has a point (next() :514), where series missing a point at that
timestamp contribute an interpolated value per the aggregator's policy
(nextLongValue :682 / nextDoubleValue :735): LERP (linear, with Java *long*
division when every live value is an integer), ZIM (0), MAX/MIN (type max/min
sentinels), PREV (previous value).  A series only participates between its
first and last point in range (slots zeroed before/after — :411-465, :521-526).

The O(total_points x spans) virtual-call loop becomes: sort+dedup all
timestamps once, then one vmapped searchsorted + gather per series and a
single masked reduction over the series axis — MXU/VPU-friendly, O(S·U·logN)
with everything batched.

Batch layout contract: each row's valid points are its first `count` slots
(mask[s, :count]=True, rest False), timestamps strictly increasing, padding
timestamps set to _PAD (int64 max).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from opentsdb_tpu.ops.aggregators import (
    Aggregator, LERP, ZIM, MAX_IF_MISSING, MIN_IF_MISSING, PREV)
from opentsdb_tpu.ops.rate import _prev_valid_index

_PAD = jnp.iinfo(jnp.int64).max
_F64_MAX = jnp.finfo(jnp.float64).max
_I64_MAX = jnp.iinfo(jnp.int64).max
_I64_MIN = jnp.iinfo(jnp.int64).min


def interpolate(policy: str, int_mode: bool, x, x0, y0, x1, y1, exemplar):
    """Missing-point substitute per interpolation policy at timestamps x.

    The vectorized form of AggregationIterator.nextLongValue (:682) /
    nextDoubleValue (:735): LERP between the bracketing points (Java
    truncating long division in int mode), ZIM -> 0, MAX/MIN -> type
    sentinels, PREV -> previous value.  `exemplar` fixes the output
    shape/dtype for the constant policies.
    """
    if policy == LERP:
        if int_mode:
            dx = jnp.maximum(x1 - x0, 1)
            return y0 + lax.div((x - x0) * (y1 - y0), dx)
        dx = (x1 - x0).astype(jnp.float64)
        dx = jnp.where(dx == 0, 1.0, dx)
        return y0 + (x - x0).astype(jnp.float64) * (y1 - y0) / dx
    if policy == ZIM:
        return jnp.zeros_like(exemplar)
    if policy == MAX_IF_MISSING:
        return jnp.full_like(exemplar, _I64_MAX if int_mode else _F64_MAX)
    if policy == MIN_IF_MISSING:
        return jnp.full_like(exemplar, _I64_MIN if int_mode else -_F64_MAX)
    if policy == PREV:
        return y0
    raise ValueError("Invalid interpolation: " + policy)


def union_timestamps(ts, mask):
    """Sorted unique timestamps over all valid points.

    Returns (u[S*N], u_mask[S*N]): sorted ascending with duplicates and pads
    masked off; valid entries occupy a prefix (pads sort to the end, dup slots
    are interleaved but masked).
    """
    flat = jnp.where(mask, ts, _PAD).reshape(-1)
    u = jnp.sort(flat)
    first = jnp.concatenate([jnp.array([True]), u[1:] != u[:-1]])
    u_mask = first & (u != _PAD)
    return u, u_mask


def _series_contribution(ts_row, val_row, mask_row, u, policy: str,
                         int_mode: bool):
    """Contribution of one series at each union timestamp u[U].

    Returns (contrib[U], participate[U]).
    """
    n = ts_row.shape[0]
    count = mask_row.sum()
    nonempty = count > 0
    padded_ts = jnp.where(mask_row, ts_row, _PAD)
    first_ts = padded_ts[0]
    last_ts = jnp.where(nonempty, ts_row[jnp.maximum(count - 1, 0)], _I64_MIN)

    idx = jnp.searchsorted(padded_ts, u, side="left")
    idx_c = jnp.clip(idx, 0, n - 1)
    exact = (idx < count) & (jnp.take(ts_row, idx_c) == u)
    v_exact = jnp.take(val_row, idx_c)

    prev_i = jnp.clip(idx - 1, 0, n - 1)
    x0 = jnp.take(ts_row, prev_i)
    y0 = jnp.take(val_row, prev_i)
    x1 = jnp.take(ts_row, idx_c)
    y1 = jnp.take(val_row, idx_c)

    in_range = nonempty & (u >= first_ts) & (u <= last_ts)

    interp = interpolate(policy, int_mode, u, x0, y0, x1, y1, v_exact)
    contrib = jnp.where(exact, v_exact, interp)
    return contrib, in_range


def compact_rows(ts, val, mask):
    """Re-sort each row so valid points form a sorted prefix.

    Upstream stages (rate) can mask interior slots; a stable per-row sort on
    pad-masked timestamps restores the layout contract.
    """
    key = jnp.where(mask, ts, _PAD)
    order = jnp.argsort(key, axis=1, stable=True)
    return (jnp.take_along_axis(ts, order, axis=1),
            jnp.take_along_axis(val, order, axis=1),
            jnp.take_along_axis(mask, order, axis=1))


# Ceiling on materialized (series x union-slot) cells per tile.  The union
# axis is U = S*N, so the untiled contribution matrix is quadratic in the
# batch (S=1k, N=65k -> 6.7e10 cells); tiles bound it to a fixed envelope
# (default 2^24 cells = 128 MiB f64) regardless of query size.
_UNION_TILE_CELLS = 1 << 24


def set_union_tile_cells(cells: int) -> None:
    """Benchmarking/ops hook; clears the jitted pipelines that baked the
    old tiling in (the constant is read at trace time)."""
    global _UNION_TILE_CELLS
    if cells < 1:
        raise ValueError("tile cells must be positive")
    _UNION_TILE_CELLS = int(cells)
    from opentsdb_tpu.ops import pipeline
    pipeline._jitted.clear_cache()
    pipeline._jitted_union_batch.clear_cache()


# shape: ts[S,N] any, val[S,N] any, mask[S,N] bool
def union_aggregate(ts, val, mask, agg: Aggregator, int_mode: bool = False,
                    tile_cells: int = 0):
    """Aggregate a [S, N] batch at the union of all timestamps.

    Returns (u[S*N] timestamps, out[S*N] values, u_mask[S*N]).  `int_mode`
    selects Java long arithmetic end-to-end (only valid when every input
    series is integer-typed and no rate/downsample stage ran).

    The per-slot reduce over the series axis is independent across union
    slots, so the union axis is processed in tiles of at most
    tile_cells // S slots via `lax.map` (`tile_cells` <= 0 means the
    module default; callers running B instances under vmap pass
    default/B so the ENVELOPE, not the per-instance tile, stays fixed) —
    peak memory is one tile's [S, tile] contributions, never the
    quadratic [S, S*N] matrix (VERDICT r2 weak #5).  Tiling is a
    static-shape decision: small batches keep the single-pass form with
    no loop overhead.
    """
    ts, val, mask = compact_rows(ts, val, mask)
    u, u_mask = union_timestamps(ts, mask)
    work_val = val if not int_mode else val.astype(jnp.int64)
    s = ts.shape[0]
    total = u.shape[0]

    def contribs(u_chunk):
        return jax.vmap(
            lambda t, v, m: _series_contribution(
                t, v, m, u_chunk, agg.interpolation, int_mode)
        )(ts, work_val, mask)

    if tile_cells <= 0:
        tile_cells = _UNION_TILE_CELLS
    tile = max(tile_cells // max(s, 1), 1)

    from opentsdb_tpu.ops.aggregators import (java_moving_average,
                                              ma_window)
    nw = ma_window(agg.name)
    if nw is not None:
        # The temporal window state crosses union slots, but the
        # cross-series SUM per slot is column-independent — so the sums
        # tile under the same memory envelope as every other aggregator,
        # and the (cheap, [U]-shaped) Java window pass runs once on the
        # concatenated sums.  Duplicate slots participate in
        # interpolation but are NOT evaluations: live is u_mask, not
        # per-column participation (review r4).
        def tile_sums(u_chunk):
            contrib, participate = contribs(u_chunk)
            ok = participate & ~jnp.isnan(contrib.astype(jnp.float64))
            zero = jnp.asarray(0, contrib.dtype)
            return jnp.where(ok, contrib, zero).sum(axis=0)

        if total <= tile:
            sums = tile_sums(u)
        else:
            n_tiles = -(-total // tile)
            pad = n_tiles * tile - total
            u_padded = jnp.concatenate(
                [u, jnp.full((pad,), _PAD, u.dtype)]) if pad else u
            sums = lax.map(tile_sums,
                           u_padded.reshape(n_tiles, tile)).reshape(-1)
            sums = sums[:total]
        out = java_moving_average(sums, u_mask, nw, int_mode)
        if jnp.issubdtype(out.dtype, jnp.floating):
            out = jnp.where(u_mask, out, jnp.nan)
        return u, out, u_mask

    if total <= tile:
        contrib, participate = contribs(u)
        return u, agg.reduce(contrib, participate), u_mask

    n_tiles = -(-total // tile)
    pad = n_tiles * tile - total
    # Pad slots carry _PAD timestamps: every series reports them out of
    # participation range, and u_mask is False there regardless.
    u_padded = jnp.concatenate(
        [u, jnp.full((pad,), _PAD, u.dtype)]) if pad else u

    def one_tile(u_chunk):
        contrib, participate = contribs(u_chunk)
        return agg.reduce(contrib, participate)

    out = lax.map(one_tile, u_padded.reshape(n_tiles, tile)).reshape(-1)
    return u, out[:total], u_mask


def _next_valid(mask):
    # int32 indices: native TPU scan (int64 = emulated u32 pairs, and
    # the u32-pair reduce-window lowering trips an XLA scoped-vmem
    # compile bug at some shapes — see rate._prev_valid_index).
    n = mask.shape[1]
    big = jnp.asarray(n, jnp.int32)
    pos = jnp.where(mask, jnp.arange(n, dtype=jnp.int32)[None, :], big)
    running = lax.associative_scan(jnp.minimum, pos, axis=1, reverse=True)
    return jnp.concatenate(
        [running[:, 1:], jnp.full((mask.shape[0], 1), big, jnp.int32)], axis=1)


# shape: grid_ts[W] i64, val[S,W] any, mask[S,W] bool
def grid_aggregate(grid_ts, val, mask, agg: Aggregator, int_mode: bool = False):
    """Fast path: all series share one timestamp grid (post-downsample).

    The union of timestamps is the grid itself; per-series gaps (FILL_NONE
    windows) are interpolated with prefix/suffix scans instead of searchsorted
    — O(S*W) with no sort.  Returns (grid_ts[W], out[W], out_mask[W]).
    """
    s, w = val.shape
    any_mask = mask.any(axis=0)
    work_val = val if not int_mode else val.astype(jnp.int64)

    prev_i = _prev_valid_index(mask)
    next_i = _next_valid(mask)
    has_prev = prev_i >= 0
    has_next = next_i < w
    safe_prev = jnp.clip(prev_i, 0, w - 1)
    safe_next = jnp.clip(next_i, 0, w - 1)

    x = grid_ts[None, :]
    x0 = jnp.take(grid_ts, safe_prev)
    x1 = jnp.take(grid_ts, safe_next)
    y0 = jnp.take_along_axis(work_val, safe_prev, axis=1)
    y1 = jnp.take_along_axis(work_val, safe_next, axis=1)

    in_range = has_prev & has_next | mask

    interp = interpolate(agg.interpolation, int_mode, x, x0, y0, x1, y1,
                         work_val)
    contrib = jnp.where(mask, work_val, interp)
    from opentsdb_tpu.ops.aggregators import (ma_window,
                                              moving_average_columns)
    nw = ma_window(agg.name)
    if nw is not None:
        # grid slots with no data anywhere are never evaluated
        out = moving_average_columns(contrib, in_range, any_mask, nw,
                                     int_mode)
    else:
        out = agg.reduce(contrib, in_range)
    return grid_ts, out, any_mask
