"""Distributed execution: device meshes + shard_map query kernels.

The reference scales scans by salting row keys across HBase regions and
running one scanner per bucket concurrently (SaltScanner.java:269,
RowKey.prefixKeyWithSalt :141); its distributed backend is asynchbase RPC +
ZooKeeper (SURVEY.md §2.7).  The TPU-native equivalent: a
`jax.sharding.Mesh` with a *series* axis (the salt-bucket analog — each chip
owns a shard of series) and a *time* axis (sequence-parallel analog — long
series split across chips), with XLA collectives (`psum`/`pmax`/`pmin`)
combining partial window moments over ICI.
"""

from opentsdb_tpu.parallel.mesh import (
    make_mesh, mesh_shape_for, AXIS_SERIES, AXIS_TIME)
from opentsdb_tpu.parallel.sharded import (
    sharded_group_downsample, sharded_rollup, shard_series,
    sharded_query_pipeline, shard_rows, SHARDED_AGGS,
    ShardedStreamAccumulator)

__all__ = [
    "make_mesh", "mesh_shape_for", "AXIS_SERIES", "AXIS_TIME",
    "sharded_group_downsample", "sharded_rollup", "shard_series",
    "sharded_query_pipeline", "shard_rows", "SHARDED_AGGS",
    "ShardedStreamAccumulator",
]
