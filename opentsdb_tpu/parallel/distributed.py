"""Multi-host (DCN) initialization for the query mesh.

The reference's distributed substrate is the asynchbase RPC fabric to
HBase RegionServers plus ZooKeeper discovery (/root/reference/src/core/
TSDB.java:235-253) — storage-side scale-out.  The TPU-native equivalent
scales the COMPUTE mesh across hosts: `jax.distributed.initialize` joins
every TSD process into one JAX runtime whose `jax.devices()` spans all
hosts, and the existing shard_map kernels run unchanged — XLA routes
collectives over ICI within a slice and DCN between hosts.

Layout stance (scaling-book recipe): the series axis is the outer,
host-spanning axis — row shards never exchange raw points, so the only
DCN traffic is the reduced [G, W] / [S, W] grids (psum or the
gather-to-owner all_gather), both orders of magnitude smaller than the
scanned data.  The time axis stays within a host so the denser moment
combines ride ICI.

Config (all tsd.network.distributed.*):
  coordinator     "host:port" of process 0 — presence enables multi-host
  num_processes   total TSD processes in the cluster
  process_id      this process's index (defaults to $JAX_PROCESS_ID)
"""

from __future__ import annotations

import logging
import os

LOG = logging.getLogger(__name__)

_initialized = False


def maybe_init_distributed(config) -> bool:
    """Join the multi-host JAX runtime when configured; idempotent.

    Returns True when running multi-host (after a successful initialize),
    False for the ordinary single-host deployment.
    """
    global _initialized
    coordinator = config.get_string("tsd.network.distributed.coordinator")
    if not coordinator:
        return False
    if _initialized:
        return True
    num = config.get_int("tsd.network.distributed.num_processes")
    pid_raw = config.get_string("tsd.network.distributed.process_id") \
        or os.environ.get("JAX_PROCESS_ID", "")
    if num <= 0 or pid_raw == "":
        raise ValueError(
            "tsd.network.distributed.coordinator is set but num_processes/"
            "process_id are not — every TSD in the cluster needs all three")
    import jax
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num,
                               process_id=int(pid_raw))
    _initialized = True
    LOG.info("joined multi-host JAX runtime: %d processes, %d devices",
             num, len(jax.devices()))
    return True


def host_major_devices():
    """All visible devices ordered host-major (process_index, then id).

    Feeding this order into make_mesh puts each host's chips contiguous
    on the series axis, so the time-axis collectives stay intra-host
    (ICI) and only the small reduced-grid combines cross DCN.
    """
    import jax
    return sorted(jax.devices(),
                  key=lambda d: (d.process_index, d.id))
