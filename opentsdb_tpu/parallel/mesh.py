"""Device mesh construction for the sharded query/rollup kernels.

Axes:
  * ``series`` — data-parallel over time series (the salt-bucket analog,
    SaltScanner.java:269: one concurrent scanner per hash bucket becomes one
    chip per series shard).
  * ``time``   — sequence-parallel over the time axis for long series
    (the 3600s row-chunking analog, Const.java:95).

Collectives ride ICI within a slice: additive window moments combine with
`psum` over both axes; min/max with `pmin`/`pmax`.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_SERIES = "series"
AXIS_TIME = "time"


def mesh_shape_for(n_devices: int) -> tuple[int, int]:
    """Pick a (series, time) grid for n devices, series-major.

    Series parallelism is the cheaper axis (no halo/overlap concerns), so it
    gets the larger factor: 8 -> (4, 2), 4 -> (2, 2), 2 -> (2, 1), 1 -> (1, 1).
    """
    if n_devices < 1:
        raise ValueError("need at least one device")
    time = 1
    series = n_devices
    while series % 2 == 0 and series > 2 * time:
        series //= 2
        time *= 2
    return series, time


def make_mesh(n_devices: int | None = None,
              shape: tuple[int, int] | None = None,
              devices=None) -> Mesh:
    """Build a 2-D (series, time) mesh over the first n devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    if shape is None:
        shape = mesh_shape_for(n)
    if shape[0] * shape[1] != n:
        raise ValueError("mesh shape %r does not cover %d devices"
                         % (shape, n))
    grid = np.asarray(devices).reshape(shape)
    return Mesh(grid, (AXIS_SERIES, AXIS_TIME))
