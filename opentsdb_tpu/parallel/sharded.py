"""shard_map query kernels: cross-chip downsample + group-by aggregation.

Reference behavior being re-expressed (not translated): the group-by
aggregation fan-out of TsdbQuery.GroupByAndAggregateCB
(/root/reference/src/core/TsdbQuery.java:981-1114) over the salt-bucket
scatter/gather of SaltScanner (/root/reference/src/core/SaltScanner.java:269).
Each HBase salt bucket scanned concurrently becomes a series shard owned by
one chip; the TreeMap merge of per-bucket results becomes XLA collectives:
window moments (count/sum/sumsq/min/max) are computed per chip with segment
reductions, then combined over ICI with `psum`/`pmax`/`pmin` inside
`shard_map`.  The time axis is additionally sharded (sequence parallelism)
— window moments are associative over time, so time shards combine with the
same collectives, no halo exchange needed.

The serving path (`sharded_query_pipeline`) runs the full /api/query
numeric pipeline — per-series downsample + rate + interpolation, then the
grouped cross-series reduce — with rows of the [S, N] batch spread over
every chip of the mesh.  Moment-decomposable aggregators combine partial
(count/sum/sumsq/min/max) moments over ICI; order/rank aggregators
(percentiles/median/first/last/mult) gather the already-downsampled [S, W]
grid to every chip and reduce replicated — gather-to-owner with W ≪ N, so
the transfer is the reduced grid, never the raw points.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from opentsdb_tpu.ops.downsample import (
    WindowSpec, window_ids, window_timestamps)
from opentsdb_tpu.parallel.mesh import AXIS_SERIES, AXIS_TIME

_BOTH = (AXIS_SERIES, AXIS_TIME)

# Cross-chip aggregators expressible as psum/pmax/pmin-combinable moments.
# Scopes sharded_group_downsample (the offline rollup pass, which only ever
# needs moment lanes); the SERVING path (sharded_query_pipeline below)
# covers every registry aggregator — percentiles/median/first/last/mult run
# via gather-to-owner on the reduced grid.
SHARDED_AGGS = frozenset({
    "sum", "zimsum", "count", "avg", "min", "mimmin", "max", "mimmax",
    "dev", "squareSum"})


def _group_moments(ts, val, mask, gid, num_groups: int, spec: WindowSpec,
                   wargs: dict):
    """Per-chip (count, sum, min, max) over (group, window) cells + helpers.

    Returns (seg, ok_flat, flat_v, count, total) with count/total already
    psum-combined across the mesh; min/max are computed lazily by callers.
    """
    s, n = ts.shape
    w = spec.count
    num = num_groups * w + 1
    nwin = wargs["nwin"]

    win = window_ids(ts, spec, wargs)
    valid = mask & (win >= 0) & (win < nwin.astype(win.dtype))
    vf = val.astype(jnp.float64)
    ok = valid & ~jnp.isnan(vf)
    # int32 segment ids + counts: int64 is an emulated u32 pair on TPU
    from opentsdb_tpu.ops.group_agg import _seg_dtype
    dt = _seg_dtype(num)
    seg = jnp.where(ok, gid[:, None].astype(dt) * w
                    + jnp.clip(win, 0, w - 1).astype(dt),
                    jnp.asarray(num_groups * w, dt))
    seg = seg.reshape(-1)
    ok_flat = ok.reshape(-1)
    flat_v = jnp.where(ok_flat, vf.reshape(-1), 0.0)

    count = jax.ops.segment_sum(ok_flat.astype(jnp.int32), seg,
                                num_segments=num)[:-1].astype(jnp.int64)
    total = jax.ops.segment_sum(flat_v, seg, num_segments=num)[:-1]
    count = lax.psum(count, _BOTH)
    total = lax.psum(total, _BOTH)
    return seg, ok_flat, flat_v, count, total, num


def _finish(agg_name, seg, ok_flat, flat_v, count, total, num,
            num_groups, w):
    """Combine cross-chip moments into the final [G, W] aggregate."""
    g = num_groups
    cnt = count.reshape(g, w)
    tot = total.reshape(g, w)
    safe = jnp.maximum(cnt, 1)

    if agg_name in ("sum", "zimsum"):
        out = tot
    elif agg_name == "count":
        out = cnt.astype(jnp.float64)
    elif agg_name == "avg":
        out = tot / safe
    elif agg_name == "squareSum":
        sq = jax.ops.segment_sum(flat_v * flat_v, seg, num_segments=num)[:-1]
        out = lax.psum(sq, _BOTH).reshape(g, w)
    elif agg_name in ("min", "mimmin"):
        lo = jax.ops.segment_min(jnp.where(ok_flat, flat_v, jnp.inf), seg,
                                 num_segments=num)[:-1]
        out = lax.pmin(lo, _BOTH).reshape(g, w)
    elif agg_name in ("max", "mimmax"):
        hi = jax.ops.segment_max(jnp.where(ok_flat, flat_v, -jnp.inf), seg,
                                 num_segments=num)[:-1]
        out = lax.pmax(hi, _BOTH).reshape(g, w)
    elif agg_name == "dev":
        # Second pass with the *global* mean (ICI round-trip already paid by
        # the psum of count/total): numerically the two-pass scheme the
        # reference's Welford loop approximates (Aggregators.java:498).
        mean = (tot / safe).reshape(-1)
        mean_pp = mean[jnp.clip(seg, 0, g * w - 1)]
        centered = jnp.where(ok_flat, flat_v - mean_pp, 0.0)
        m2 = jax.ops.segment_sum(centered * centered, seg,
                                 num_segments=num)[:-1]
        m2 = lax.psum(m2, _BOTH).reshape(g, w)
        out = jnp.where(cnt >= 2, jnp.sqrt(m2 / jnp.maximum(cnt - 1, 1)), 0.0)
    else:
        raise KeyError("Aggregator %r has no cross-chip decomposition; "
                       "use the single-device path" % agg_name)
    return out, cnt


@lru_cache(maxsize=128)
def sharded_group_downsample(mesh: Mesh, agg_name: str, spec: WindowSpec,
                             num_groups: int):
    """Build the jitted sharded step: [S,N] batch -> [G,W] group aggregates.

    fn(ts, val, mask, gid, wargs) with ts/val/mask sharded (series, time),
    gid sharded (series,); returns replicated
    (window_ts[W], out[G, W], out_mask[G, W]).

    lru_cached (tsdblint jax-jit-per-call): every call used to build a
    fresh shard_map + jax.jit wrapper, recompiling per invocation.
    """
    if agg_name not in SHARDED_AGGS:
        raise KeyError("Aggregator %r has no cross-chip decomposition"
                       % agg_name)
    w = spec.count

    def step(ts, val, mask, gid, wargs):
        seg, ok_flat, flat_v, count, total, num = _group_moments(
            ts, val, mask, gid, num_groups, spec, wargs)
        out, cnt = _finish(agg_name, seg, ok_flat, flat_v, count, total,
                           num, num_groups, w)
        live = jnp.arange(w, dtype=jnp.int32)[None, :] \
            < wargs["nwin"].astype(jnp.int32)
        out_mask = (cnt > 0) & live
        out = jnp.where(out_mask, out, jnp.nan)
        wts = window_timestamps(spec, wargs)
        return wts, out, out_mask

    mapped = shard_map(
        step, mesh=mesh,
        in_specs=(P(AXIS_SERIES, AXIS_TIME), P(AXIS_SERIES, AXIS_TIME),
                  P(AXIS_SERIES, AXIS_TIME), P(AXIS_SERIES), P()),
        out_specs=(P(), P(), P()),
        check_vma=False)
    return jax.jit(mapped)


@lru_cache(maxsize=32)
def sharded_rollup(mesh: Mesh, spec: WindowSpec):
    """Build the sharded offline rollup pass (BASELINE config 5).

    lru_cached (tsdblint jax-jit-per-call): the rollup job calls this
    per run, and an uncached builder meant a full recompile per pass.

    fn(ts, val, mask, wargs) -> per-series (window_ts[W], sum[S,W],
    count[S,W], min[S,W], max[S,W]) with the series axis still sharded on
    the way out (out_specs keep P(series)) — each chip materializes the
    rollup rows for the series it owns, the write-path analog of
    TSDB.addAggregatePoint (/root/reference/src/core/TSDB.java:1359-1457)
    batched over every interval at once.  Time shards combine with psum /
    pmin / pmax over the time axis only.
    """
    w = spec.count

    def step(ts, val, mask, wargs):
        s, n = ts.shape
        num = s * w + 1
        nwin = wargs["nwin"]
        win = window_ids(ts, spec, wargs)
        valid = mask & (win >= 0) & (win < nwin.astype(win.dtype))
        vf = val.astype(jnp.float64)
        ok = valid & ~jnp.isnan(vf)
        from opentsdb_tpu.ops.group_agg import _seg_dtype
        dt = _seg_dtype(num)
        rows = jnp.arange(s, dtype=dt)[:, None]
        seg = jnp.where(ok, rows * w + jnp.clip(win, 0, w - 1).astype(dt),
                        jnp.asarray(s * w, dt)).reshape(-1)
        okf = ok.reshape(-1)
        flat = jnp.where(okf, vf.reshape(-1), 0.0)

        cnt = jax.ops.segment_sum(okf.astype(jnp.int32), seg,
                                  num_segments=num)[:-1].astype(jnp.int64)
        tot = jax.ops.segment_sum(flat, seg, num_segments=num)[:-1]
        lo = jax.ops.segment_min(jnp.where(okf, flat, jnp.inf), seg,
                                 num_segments=num)[:-1]
        hi = jax.ops.segment_max(jnp.where(okf, flat, -jnp.inf), seg,
                                 num_segments=num)[:-1]
        cnt = lax.psum(cnt, AXIS_TIME).reshape(s, w)
        tot = lax.psum(tot, AXIS_TIME).reshape(s, w)
        lo = lax.pmin(lo, AXIS_TIME).reshape(s, w)
        hi = lax.pmax(hi, AXIS_TIME).reshape(s, w)
        wts = window_timestamps(spec, wargs)
        return wts, tot, cnt, lo, hi

    mapped = shard_map(
        step, mesh=mesh,
        in_specs=(P(AXIS_SERIES, AXIS_TIME), P(AXIS_SERIES, AXIS_TIME),
                  P(AXIS_SERIES, AXIS_TIME), P()),
        out_specs=(P(), P(AXIS_SERIES), P(AXIS_SERIES), P(AXIS_SERIES),
                   P(AXIS_SERIES)),
        check_vma=False)
    return jax.jit(mapped)


def _local_grid_tail(spec, num_groups: int, wts, v, m, gid):
    """Collective-aware pipeline tail for code running INSIDE shard_map:
    (rate ->) grouped cross-series aggregation on a row-sharded [S, W] grid.

    The mesh analog of ops.pipeline._grid_tail: moment-decomposable
    aggregators combine per-chip partial moments with psum/pmin/pmax;
    order/rank aggregators all-gather the reduced grid (gather-to-owner,
    W ≪ N) and reduce replicated.  Shared by the materialized serving path
    (sharded_query_pipeline) and the streamed finish (sharded stream
    accumulator) so both answer identically.
    """
    from opentsdb_tpu.ops.aggregators import Aggregator, get_agg, PREV
    from opentsdb_tpu.ops.group_agg import (
        _seg_dtype, grid_contributions, is_moment_agg,
        moment_group_reduce, ordered_group_reduce)
    from opentsdb_tpu.ops.rate import rate

    g = num_groups
    agg = get_agg(spec.aggregator)
    if spec.rate is not None:
        agg = Aggregator(agg.name, PREV, agg.reduce)
    grid = jnp.asarray(wts)
    if spec.rate is not None:
        grid_b = jnp.broadcast_to(grid[None, :], v.shape)
        _, v, m = rate(grid_b, v, m, spec.rate, all_int=False)
    vf = v.astype(jnp.float64)
    contrib, participate = grid_contributions(grid, vf, m, agg)
    if is_moment_agg(agg.name):
        out, _ = moment_group_reduce(
            agg.name, contrib, participate, gid, g,
            combine_sum=lambda x: lax.psum(x, _BOTH),
            combine_min=lambda x: lax.pmin(x, _BOTH),
            combine_max=lambda x: lax.pmax(x, _BOTH),
            # contiguous row sharding + end-padding preserve the
            # planner's non-decreasing gid on every shard
            rows_sorted=spec.rows_sorted)
    else:
        # Gather-to-owner on the reduced grid: every chip receives all
        # rows (global row order preserved — first/last follow series
        # order) and reduces replicated.
        c_all = lax.all_gather(contrib, _BOTH, axis=0, tiled=True)
        p_all = lax.all_gather(participate, _BOTH, axis=0, tiled=True)
        g_all = lax.all_gather(gid, _BOTH, axis=0, tiled=True)
        out, _ = ordered_group_reduce(agg.name, c_all, p_all, g_all, g)
    w = v.shape[1]
    dt = _seg_dtype(g * w + w)
    cols = jnp.arange(w, dtype=dt)[None, :]
    seg = (gid.astype(dt)[:, None] * w + cols).reshape(-1)
    present = jax.ops.segment_sum(m.reshape(-1).astype(jnp.int32), seg,
                                  num_segments=g * w)
    out_mask = lax.psum(present, _BOTH).reshape(g, w) > 0
    return wts, out, out_mask


@lru_cache(maxsize=128)
def sharded_query_pipeline(mesh: Mesh, spec, num_groups: int):
    """Build the jitted mesh-serving step for one /api/query pipeline.

    fn(ts, val, mask, gid, wargs) with rows sharded over every chip
    (dim 0 split across both mesh axes, time dim intact so downsample/rate
    stay row-local); returns replicated (wts[W], out[G, W], out_mask[G, W])
    identical to ops.pipeline.run_group_pipeline's single-device answer.

    `spec` is a PipelineSpec (hashable) — the builder is lru_cached so a
    dashboard re-issuing the same query shape reuses the compiled program.
    """
    from opentsdb_tpu.ops.downsample import downsample

    step = spec.downsample

    def local(ts, val, mask, gid, wargs):
        wts, v, m = downsample(ts, val, mask, step.function, step.window_spec,
                               wargs, step.fill_policy, step.fill_value)
        return _local_grid_tail(spec, num_groups, wts, v, m, gid)

    mapped = shard_map(
        local, mesh=mesh,
        in_specs=(P(_BOTH, None), P(_BOTH, None), P(_BOTH, None), P(_BOTH),
                  P()),
        out_specs=(P(), P(), P()),
        check_vma=False)
    return jax.jit(mapped)


def n_devices(mesh: Mesh) -> int:
    """Total chips in the query mesh (single definition — padding widths
    derived from it must agree between the streamed and materialized
    paths)."""
    return mesh.shape[AXIS_SERIES] * mesh.shape[AXIS_TIME]


# shape: ts[S,N] any, val[S,N] f64, mask[S,N] bool
def _pad_rows(s_pad: int, ts: np.ndarray, val: np.ndarray, mask: np.ndarray,
              gid: np.ndarray | None = None, pad_gid_value: int = 0):
    """Pad the series axis to `s_pad` with inert rows.

    The pad values are load-bearing: pad-sentinel timestamps keep rows
    sorted (I64_MAX, or the int32 clip ceiling for pre-compacted ts_base
    batches — the device-cache gather's pad value), mask False keeps
    points out of every window, and `pad_gid_value` must
    be an OUT-OF-RANGE group id (pass num_groups) — mask False alone is not
    enough, because fill policies other than "none" expose every live
    window after downsample, so a phantom row with a real gid would
    participate in count/avg.  JAX segment ops drop out-of-range ids.
    """
    s, n = ts.shape
    if s_pad == s:
        return ts, val, mask, gid
    from opentsdb_tpu.storage.device_cache import I32_PAD_TS
    sentinel = I32_PAD_TS if ts.dtype == np.int32 \
        else np.iinfo(np.int64).max
    pad_ts = np.full((s_pad, n), sentinel, ts.dtype)
    pad_val = np.zeros((s_pad, n), val.dtype)
    pad_mask = np.zeros((s_pad, n), bool)
    pad_ts[:s] = ts
    pad_val[:s] = val
    pad_mask[:s] = mask
    out_gid = None
    if gid is not None:
        out_gid = np.full(s_pad, pad_gid_value, gid.dtype)
        out_gid[:s] = gid
    return pad_ts, pad_val, pad_mask, out_gid


def padded_rows(mesh: Mesh, s: int) -> int:
    """Sharded row count: series padded up to a multiple of the mesh's
    device count (one source of truth for accumulator state and the
    planner's chunk-packing width)."""
    n_dev = n_devices(mesh)
    return -(-s // n_dev) * n_dev


def _leaf_spec(key: str):
    """shard_map spec per accumulator-state leaf: grids shard rows over
    the mesh; the 0-d oob audit counter stays replicated."""
    return P() if key == "oob" else P(_BOTH, None)


@lru_cache(maxsize=64)
def _stream_update_fn(mesh: Mesh, window_spec, state_keys=None):
    """Jitted shard_map'd accumulator fold: row-local, zero collectives.

    Each chip folds its own [S_local, n] chunk rows into its own
    [S_local, W] moment state — the SaltScanner concurrent-bucket scan
    (/root/reference/src/core/SaltScanner.java:269) with buckets = chips
    and the TreeMap merge deferred to finish().
    """
    from opentsdb_tpu.ops import streaming

    def upd(state, ts, val, mask, wargs):
        return streaming._update(window_spec, state, ts, val, mask, wargs)

    # state_keys is passed when the accumulator carries the 0-d "oob"
    # audit leaf (slice-enabled accumulators whose overflow chunks fall
    # back to this full fold): per-leaf specs keep the scalar replicated
    # while the grids shard
    state_specs = P(_BOTH, None) if state_keys is None else {
        k: _leaf_spec(k) for k in state_keys}
    mapped = shard_map(
        upd, mesh=mesh,
        in_specs=(state_specs, P(_BOTH, None), P(_BOTH, None),
                  P(_BOTH, None), P()),
        out_specs=state_specs,
        check_vma=False)
    # Donate the state (arg 0) for the same reason as streaming's
    # _jitted_update: the sharded grid can reach GBs per chip and the
    # caller replaces its reference at enqueue.
    return jax.jit(mapped, donate_argnums=0)


@lru_cache(maxsize=64)
def _stream_update_sliced_fn(mesh: Mesh, window_spec, wc: int,
                             state_keys: frozenset):
    """Sharded window-sliced fold (see streaming._update_sliced): each
    chip merges its row shard's chunk moments into the [w0, w0+wc) slice
    of its own [S_local, W] state — per-chunk cost O(S_local*wc), not
    O(S_local*W).  w0 is replicated; the 0-d oob audit counter psums
    over the mesh so it stays replicated."""
    from opentsdb_tpu.ops import streaming

    def upd(state, ts, val, mask, wargs, w0):
        prev_oob = state["oob"]
        new = streaming._update_sliced(window_spec, wc, state, ts, val,
                                       mask, wargs, w0)
        new["oob"] = prev_oob + lax.psum(new["oob"] - prev_oob, _BOTH)
        return new

    state_specs = {k: _leaf_spec(k) for k in state_keys}
    mapped = shard_map(
        upd, mesh=mesh,
        in_specs=(state_specs, P(_BOTH, None), P(_BOTH, None),
                  P(_BOTH, None), P(), P()),
        out_specs=state_specs,
        check_vma=False)
    return jax.jit(mapped, donate_argnums=0)


@lru_cache(maxsize=64)
def _stream_finish_fn(mesh: Mesh, window_spec, pipeline_spec,
                      num_groups: int):
    """Jitted shard_map'd stream finish: per-chip moment state -> replicated
    (wts[W], out[G, W], out_mask[G, W]) via the collective grid tail."""
    from opentsdb_tpu.ops import streaming

    step = pipeline_spec.downsample

    def fin(state, gid, wargs):
        wts, v, m = streaming._finish(
            window_spec, step.function, step.fill_policy, state, wargs,
            step.fill_value)
        return _local_grid_tail(pipeline_spec, num_groups, wts, v, m, gid)

    mapped = shard_map(
        fin, mesh=mesh,
        in_specs=(P(_BOTH, None), P(_BOTH), P()),
        out_specs=(P(), P(), P()),
        check_vma=False)
    return jax.jit(mapped)


class ShardedStreamAccumulator:
    """Mesh-sharded streaming state: beyond-memory queries on ALL chips.

    Composes the two scale axes the reference's scan layer composes —
    concurrent salt-bucket scanners (SaltScanner.java:269) × incremental
    per-batch callbacks (:463-740).  Series rows are sharded over every
    chip of the mesh; each host chunk is device_put row-sharded and folded
    into per-chip [S_local, W] moments (associative, collective-free); the
    finish runs the sharded grid tail (psum/pmin/pmax for moment
    aggregators, gather-to-owner for order/rank) so the answer matches the
    single-device StreamAccumulator + run_grid_tail bit-for-bit up to
    psum reassociation.

    HBM per chip is O(S/n_chips * W + chunk), independent of total points.
    """

    def __init__(self, mesh: Mesh, num_series: int, window_spec, wargs,
                 sketch: bool = False, lanes: frozenset | None = None,
                 window_slice: int | None = None):
        from opentsdb_tpu.ops import streaming

        self.mesh = mesh
        self.window_spec = window_spec
        self.wargs = wargs
        self.num_series = num_series
        self.s_pad = padded_rows(mesh, num_series)
        self._row_sh = NamedSharding(mesh, P(_BOTH, None))
        self._rep_sh = NamedSharding(mesh, P())
        self._gid_sh = NamedSharding(mesh, P(_BOTH))
        self.window_slice = streaming.quantize_window_slice(window_slice,
                                                            window_spec)
        state = streaming._zero_state(self.s_pad, window_spec.count,
                                      sketch, lanes,
                                      with_oob=self.window_slice
                                      is not None)
        self.state = {k: jax.device_put(
            v, self._rep_sh if _leaf_spec(k) == P() else self._row_sh)
            for k, v in state.items()}
        keys = (frozenset(state) if self.window_slice is not None
                else None)
        self._update = _stream_update_fn(mesh, window_spec, keys)
        self._update_sliced = None
        if self.window_slice is not None:
            self._update_sliced = _stream_update_sliced_fn(
                mesh, window_spec, self.window_slice, keys)

    def update(self, ts: np.ndarray, val: np.ndarray,
               mask: np.ndarray, w0: int | None = None) -> None:
        """Fold one [num_series, n] host chunk (async — returns at enqueue).

        Rows are padded to the sharded row count (callers may pack chunks
        at `s_pad` rows directly to skip the copy); padding rows carry
        mask False so their moment state stays zero (n=0), which the
        finish's participate logic excludes (pad gid is out-of-range too).

        `w0` (with a window_slice-enabled accumulator) routes to the
        sliced fold — each chip merges an O(S_local * wc) state slice
        instead of its whole [S_local, W] grid; see
        StreamAccumulator.update for the contract.
        """
        ts, val, mask, _ = _pad_rows(self.s_pad, ts, val, mask)
        d_ts, d_val, d_mask = (jax.device_put(x, self._row_sh)
                               for x in (ts, val, mask))
        if w0 is not None and self._update_sliced is not None:
            self.state = self._update_sliced(self.state, d_ts, d_val,
                                             d_mask, self.wargs,
                                             jnp.asarray(w0, jnp.int64))
            return
        self.state = self._update(self.state, d_ts, d_val, d_mask,
                                  self.wargs)

    def oob_count(self) -> int:
        """Valid points sliced folds missed (w0 contract violations);
        0 in correct use.  Host sync."""
        if "oob" not in self.state:
            return 0
        return int(np.asarray(self.state["oob"]))

    def finish_tail(self, pipeline_spec, gid: np.ndarray, num_groups: int):
        """Replicated (wts[W], out[G, W], out_mask[G, W]) for the query."""
        fn = _stream_finish_fn(self.mesh, self.window_spec, pipeline_spec,
                               num_groups)
        pad_gid = np.full(self.s_pad, num_groups, np.int64)
        pad_gid[:self.num_series] = gid
        d_gid = jax.device_put(pad_gid, self._gid_sh)
        # the finish fn's state spec is rank-2 per leaf; the 0-d oob
        # audit counter is not part of the grid finish
        state = {k: v for k, v in self.state.items() if k != "oob"}
        return fn(state, d_gid, self.wargs)


# shape: ts[S,N] any, val[S,N] f64, mask[S,N] bool, gid[S] any
def shard_rows(mesh: Mesh, ts: np.ndarray, val: np.ndarray, mask: np.ndarray,
               gid: np.ndarray, pad_gid_value: int):
    """Pad the series axis to device-count multiple and device_put row-sharded.

    The serving-path layout: dim 0 split over both mesh axes (each chip owns
    a block of whole rows), time dim intact.  Padding rows get mask False
    AND `pad_gid_value` — REQUIRED, pass num_groups: an out-of-range group
    id, whose segments JAX scatter drops.  mask False alone is NOT enough —
    fill policies other than "none" expose every live window after
    downsample, so a phantom row with an in-range gid would participate in
    count/avg (the r3 phantom-row bug).
    """
    s, n = ts.shape
    s_pad = padded_rows(mesh, s)
    ts, val, mask, gid = _pad_rows(s_pad, ts, val, mask, gid, pad_gid_value)
    return _put_row_sharded(mesh, ts, val, mask, gid)


def _put_row_sharded(mesh: Mesh, ts, val, mask, gid):
    """The shared layout tail: dim 0 over both mesh axes, time intact."""
    row_sh = NamedSharding(mesh, P(_BOTH, None))
    gid_sh = NamedSharding(mesh, P(_BOTH))
    return (jax.device_put(ts, row_sh), jax.device_put(val, row_sh),
            jax.device_put(mask, row_sh), jax.device_put(gid, gid_sh))


# shape: ts[S,N] any, val[S,N] f64, mask[S,N] bool, gid[S] any
def shard_rows_device(mesh: Mesh, ts, val, mask, gid: np.ndarray,
                      pad_gid_value: int):
    """shard_rows for an already-device-resident batch (device-cache hit).

    Row padding happens ON DEVICE (tiny concats, same load-bearing pad
    rule as _pad_rows) and the device_put re-lays the single-device
    arrays out across the mesh — an ICI scatter on real hardware instead
    of a fresh host upload.  gid is host-side (the planner builds it per
    query) and pads exactly like shard_rows.
    """
    s, n = ts.shape
    s_pad = padded_rows(mesh, s)
    if s_pad != s:
        # pure pad ROWS from _pad_rows (empty data in, pads out), then
        # concatenated on device: one definition of the phantom-row rule
        # serves both layouts (incl. the int32 ts_base pad sentinel)
        pad_ts, pad_val, pad_mask, pad_gid = _pad_rows(
            s_pad - s, np.empty((0, n), np.dtype(str(ts.dtype))),
            np.empty((0, n), np.dtype(str(val.dtype))),
            np.empty((0, n), bool),
            np.empty(0, gid.dtype), pad_gid_value)
        ts = jnp.concatenate([ts, jnp.asarray(pad_ts)])
        val = jnp.concatenate([val, jnp.asarray(pad_val)])
        mask = jnp.concatenate([mask, jnp.asarray(pad_mask)])
        gid = np.concatenate([gid, pad_gid])
    return _put_row_sharded(mesh, ts, val, mask, gid)


def shard_series(mesh: Mesh, ts: np.ndarray, val: np.ndarray,
                 mask: np.ndarray, gid: np.ndarray):
    """Pad a host batch to mesh-divisible shape and device_put with shardings.

    Pads S up to a multiple of the series-axis size and N to the time-axis
    size (padding rows have mask False / group 0), then places each array
    with its NamedSharding so the jitted shard_map consumes it zero-copy.
    """
    n_s = mesh.shape[AXIS_SERIES]
    n_t = mesh.shape[AXIS_TIME]
    s, n = ts.shape
    s_pad = -(-s // n_s) * n_s
    n_pad = -(-n // n_t) * n_t
    if (s_pad, n_pad) != (s, n):
        pad_ts = np.full((s_pad, n_pad), np.iinfo(np.int64).max, np.int64)
        pad_val = np.zeros((s_pad, n_pad), val.dtype)
        pad_mask = np.zeros((s_pad, n_pad), bool)
        pad_gid = np.zeros(s_pad, gid.dtype)
        pad_ts[:s, :n] = ts
        pad_val[:s, :n] = val
        pad_mask[:s, :n] = mask
        pad_gid[:s] = gid
        ts, val, mask, gid = pad_ts, pad_val, pad_mask, pad_gid
    data_sh = NamedSharding(mesh, P(AXIS_SERIES, AXIS_TIME))
    gid_sh = NamedSharding(mesh, P(AXIS_SERIES))
    return (jax.device_put(ts, data_sh), jax.device_put(val, data_sh),
            jax.device_put(mask, data_sh), jax.device_put(gid, gid_sh))
