"""Plugin SPIs + the loader.

Reference behavior: plugin interfaces scattered across the reference
(SURVEY.md §2 layer 10): RTPublisher.java (realtime datapoint fanout),
StorageExceptionHandler.java (failed-write spillway), RpcPlugin.java /
HttpRpcPlugin.java (extra protocol endpoints),
WriteableDataPointFilterPlugin.java (write gate), UniqueIdFilterPlugin.java
(UID assignment gate), StartupPlugin.java, MetaDataCache.java — loaded via
PluginLoader.java + ServiceLoader.  Python loading resolves dotted
`module:Class` (or `module.Class`) paths from config.
"""

from opentsdb_tpu.plugins.spi import (
    RTPublisher, StorageExceptionHandler, RpcPlugin, HttpRpcPlugin,
    WriteableDataPointFilterPlugin, UniqueIdFilterPlugin, StartupPlugin,
    MetaDataCache)
from opentsdb_tpu.plugins.loader import load_plugin, initialize_plugins

__all__ = ["RTPublisher", "StorageExceptionHandler", "RpcPlugin",
           "HttpRpcPlugin", "WriteableDataPointFilterPlugin",
           "UniqueIdFilterPlugin", "StartupPlugin", "MetaDataCache",
           "load_plugin", "initialize_plugins"]
