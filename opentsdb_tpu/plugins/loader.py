"""Plugin loading + TSDB.initializePlugins equivalent.

Reference behavior: PluginLoader.java (jar scanning + ServiceLoader lookup;
here: dotted-path import) and TSDB.initializePlugins (:422 — loads auth,
startup, RTPublisher, SEH, search, write filters, UID filters from their
tsd.* config keys, failing fast on misconfiguration).
"""

from __future__ import annotations

import importlib
import logging
import sys

LOG = logging.getLogger("plugins")


def load_plugin(path: str, expected_type: type | None = None):
    """Instantiate `package.module:Class` (or `package.module.Class`)."""
    if not path:
        raise ValueError("Empty plugin path")
    if ":" in path:
        module_name, class_name = path.split(":", 1)
    else:
        module_name, _, class_name = path.rpartition(".")
        if not module_name:
            raise ValueError("Invalid plugin path: %s" % path)
    try:
        module = importlib.import_module(module_name)
    except ImportError as e:
        raise ValueError("Unable to locate plugin module: %s (%s)"
                         % (module_name, e))
    cls = getattr(module, class_name, None)
    if cls is None:
        raise ValueError("Unable to locate plugin class: %s" % path)
    instance = cls()
    if expected_type is not None and not isinstance(instance,
                                                    expected_type):
        raise ValueError(
            "Plugin %s is not an instance of %s"
            % (path, expected_type.__name__))
    return instance


def add_plugin_path(plugin_path: str) -> None:
    """tsd.core.plugin_path: a directory added to the import path."""
    if plugin_path and plugin_path not in sys.path:
        sys.path.insert(0, plugin_path)


def initialize_plugins(tsdb) -> None:
    """Wire every configured plugin into the TSDB (TSDB.java:422-540)."""
    from opentsdb_tpu.auth import (Authentication,
                                   AllowAllAuthenticatingAuthorizer)
    from opentsdb_tpu.plugins.spi import (
        RTPublisher, StorageExceptionHandler, StartupPlugin,
        UniqueIdFilterPlugin, WriteableDataPointFilterPlugin)
    config = tsdb.config
    plugin_path = config.get_string("tsd.core.plugin_path")
    if plugin_path:
        add_plugin_path(plugin_path)

    if config.get_bool("tsd.core.authentication.enable"):
        path = config.get_string("tsd.core.authentication.plugin")
        if path:
            tsdb.authentication = load_plugin(path, Authentication)
        else:
            tsdb.authentication = AllowAllAuthenticatingAuthorizer()
        tsdb.authentication.initialize(tsdb)
        LOG.info("Initialized authentication plugin: %s",
                 type(tsdb.authentication).__name__)

    if config.get_bool("tsd.rtpublisher.enable"):
        path = config.get_string("tsd.rtpublisher.plugin")
        if not path:
            raise ValueError(
                "tsd.rtpublisher.enable is set but tsd.rtpublisher.plugin "
                "is empty")
        tsdb.rt_publisher = load_plugin(path, RTPublisher)
        tsdb.rt_publisher.initialize(tsdb)

    if config.get_bool("tsd.core.storage_exception_handler.enable"):
        path = config.get_string("tsd.core.storage_exception_handler.plugin")
        if not path:
            raise ValueError(
                "tsd.core.storage_exception_handler.enable is set but the "
                "plugin is empty")
        tsdb.storage_exception_handler = load_plugin(
            path, StorageExceptionHandler)
        tsdb.storage_exception_handler.initialize(tsdb)

    if config.get_bool("tsd.timeseriesfilter.enable"):
        path = config.get_string("tsd.timeseriesfilter.plugin")
        if not path:
            raise ValueError("tsd.timeseriesfilter.enable is set but "
                             "tsd.timeseriesfilter.plugin is empty")
        tsdb.write_filter = load_plugin(path,
                                        WriteableDataPointFilterPlugin)
        tsdb.write_filter.initialize(tsdb)

    if config.get_bool("tsd.uidfilter.enable"):
        path = config.get_string("tsd.uidfilter.plugin")
        if not path:
            raise ValueError("tsd.uidfilter.enable is set but "
                             "tsd.uidfilter.plugin is empty")
        uid_filter = load_plugin(path, UniqueIdFilterPlugin)
        uid_filter.initialize(tsdb)
        for table in (tsdb.metrics, tsdb.tag_names, tsdb.tag_values):
            table.set_filter(uid_filter)

    if config.get_bool("tsd.search.enable"):
        path = config.get_string("tsd.search.plugin")
        from opentsdb_tpu.search import MemorySearchPlugin, SearchPlugin
        if path:
            tsdb.search_plugin = load_plugin(path, SearchPlugin)
        else:
            # Bundled default so /api/search works out of the box.
            tsdb.search_plugin = MemorySearchPlugin()
        tsdb.search_plugin.initialize(tsdb)

    if config.get_bool("tsd.startup.enable"):
        path = config.get_string("tsd.startup.plugin")
        if path:
            tsdb.startup_plugin = load_plugin(path, StartupPlugin)
            tsdb.startup_plugin.initialize(tsdb)
