"""Plugin SPI base classes (one per reference plugin interface)."""

from __future__ import annotations


class Plugin:
    """Shared lifecycle (every reference SPI declares these four)."""

    def initialize(self, tsdb) -> None:
        pass

    def shutdown(self) -> None:
        pass

    def version(self) -> str:
        return "3.0.0"

    def collect_stats(self, collector) -> None:
        pass


class RTPublisher(Plugin):
    """Realtime datapoint fanout (RTPublisher.java: publishDataPoint
    :121-136, sinkDataPoint :97, publishAnnotation)."""

    def publish_data_point(self, metric: str, timestamp: int, value,
                           tags: dict, tsuid: str) -> None:
        raise NotImplementedError

    def publish_histogram_point(self, metric: str, timestamp: int, hist,
                                tags: dict, tsuid: str) -> None:
        pass

    def publish_annotation(self, annotation) -> None:
        pass


class StorageExceptionHandler(Plugin):
    """Failed-write spillway (StorageExceptionHandler.java: handleError)."""

    def handle_error(self, dp: dict, exception: Exception) -> None:
        raise NotImplementedError


class RpcPlugin(Plugin):
    """Arbitrary protocol plugin (RpcPlugin.java)."""


class HttpRpcPlugin(Plugin):
    """Extra HTTP endpoints under /plugin/<route> (HttpRpcPlugin.java)."""

    def route(self) -> str:
        raise NotImplementedError

    def execute_http(self, tsdb, query) -> None:
        raise NotImplementedError


class WriteableDataPointFilterPlugin(Plugin):
    """Write gate (WriteableDataPointFilterPlugin.java: allowDataPoint /
    allowHistogramPoint)."""

    def allow(self, metric: str, timestamp, value, tags: dict) -> bool:
        raise NotImplementedError

    def allow_histogram(self, metric: str, timestamp, hist,
                        tags: dict) -> bool:
        return self.allow(metric, timestamp, hist, tags)


class UniqueIdFilterPlugin(Plugin):
    """UID assignment gate (UniqueIdFilterPlugin.java: allowUIDAssignment,
    fillterUIDAssignments)."""

    def allow_uid_assignment(self, name: str, kind) -> bool:
        raise NotImplementedError


class StartupPlugin(Plugin):
    """Pre-TSDB startup hook (tools/StartupPlugin.java)."""

    def set_ready(self, tsdb) -> None:
        pass


class MetaDataCache(Plugin):
    """Meta cache SPI (meta/MetaDataCache.java)."""

    def get_tsmeta(self, tsuid: str):
        raise NotImplementedError

    def put_tsmeta(self, meta) -> None:
        raise NotImplementedError
