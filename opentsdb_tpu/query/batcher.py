"""Fused multi-query dispatch: coalesce concurrent small queries into
one stacked device kernel (docs/batching.md, ROADMAP item 1).

Every hot-path subsystem so far accelerates one query at a time; at
dashboard-fleet QPS the per-dispatch floor — not FLOPs — caps
throughput, because each admitted plan still pays its own jitted
launch.  This module is the Enthuse-style shared-aggregation answer
(arXiv:2405.18168): concurrent plans that the routing verdict priced
as DISPATCH-BOUND (query/plandecision.py path ``batched``) rendezvous
here, bucket by compatibility, and execute as ONE stacked ``[Q, S, N]``
kernel (ops/pipeline.py run_stacked_group_pipeline) with host-side
unpack — Q queries, one launch floor.

Compatibility = one jit program: plans share a bucket only when they
would trace the SAME kernel — identical static ``PipelineSpec``,
identical padded batch shapes and dtypes, identical window-arg
structure, the same host-lane verdict, and the same **mode-policy
epoch** (an autotune flip mid-coalesce must not splice two kernel
generations into one launch; members on either side of the flip land
in different buckets).  Within a bucket each member keeps its own
mask plane, its own gid row map, and its own traced window args
(stacked along the member axis), so on integer data a member's
unpacked slice is bitwise what its solo dispatch would produce
(integer-exact f64 accumulation is reassociation-proof — the same
contract the rollup lanes pin).

Coalesce-vs-dispatch-now is COSTMODEL-priced, not a static batch size
(the Factor-Windows cost-based-rewrite framing, arXiv:2008.12379):
the routing verdict already gated on ``coalesce_worthwhile`` (new
linear COST_TERMS ``stacked_dispatch`` + ``stacked_cell``), and the
rendezvous itself holds a bucket open only while there is concurrent
demand to coalesce — the first member of a bucket becomes the LEADER,
waits up to ``tsd.query.batch.hold_ms`` for joiners (zero wait when
the admission gate shows no other query in flight: an uncontended
query never pays coalesce latency), seals the bucket at
``tsd.query.batch.max_q`` members / ``tsd.query.batch.max_mb`` of
stacked operands, dispatches once, and distributes the host-unpacked
slices.  Batched executions are EXCLUDED from the calibration ring
like rewrites/tiled runs (a stacked launch's measured time describes
no single member's feature vector).

Deadlines stay per-member: a member whose deadline expires or cancels
while waiting leaves the bucket WITHOUT poisoning its siblings — the
leader drops expired members (its own included: winning the submit
race does not outrank the deadline) before stacking, and a member that
expires after sealing simply abandons its slice.  Each member keeps
its own trace span; the planner annotates it with the batch verdict
(q, waited ms, stacked vs solo).

One instance per TSDB (``tsdb.dispatch_batcher``); every stacked
dispatch lands a ``batch`` event in the flight recorder and the
``tsd.query.batch.*`` metric families.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from opentsdb_tpu.obs import latattr
from opentsdb_tpu.obs.registry import REGISTRY
from opentsdb_tpu.ops.pipeline import (run_group_pipeline,
                                       run_stacked_group_pipeline)

# Waiting members re-check their own deadline on this cadence even
# without a bucket notification (cancellation flips a token without
# notifying the batcher's condition) — same discipline as the
# admission gate's queue wait.
_WAIT_TICK_S = 0.05


class _Member:
    """One submitted plan: operands in, an unpacked slice (or error)
    out.  State transitions are guarded by the batcher lock; `done`
    flips exactly once, under it."""

    __slots__ = ("ts", "val", "mask", "gid", "wargs", "deadline",
                 "done", "result", "error", "abandoned")

    def __init__(self, ts, val, mask, gid, wargs, deadline):
        self.ts = ts
        self.val = val
        self.mask = mask
        self.gid = gid
        self.wargs = wargs
        self.deadline = deadline
        self.done = False        # guarded-by: DispatchBatcher._lock
        self.result = None       # guarded-by: DispatchBatcher._lock
        self.error = None        # guarded-by: DispatchBatcher._lock
        self.abandoned = False   # guarded-by: DispatchBatcher._lock

    def nbytes(self) -> int:
        return (self.ts.nbytes + self.val.nbytes + self.mask.nbytes
                + self.gid.nbytes)


class _Bucket:
    """One open coalesce window: members compatible enough to share a
    single stacked jit program."""

    __slots__ = ("key", "members", "sealed", "nbytes")

    def __init__(self, key):
        self.key = key
        self.members: list[_Member] = []  # guarded-by: DispatchBatcher._lock
        self.sealed = False               # guarded-by: DispatchBatcher._lock
        self.nbytes = 0                   # guarded-by: DispatchBatcher._lock


def _wargs_signature(wargs: dict) -> tuple:
    """Structural identity of the traced window args: keys, shapes,
    dtypes — two members stack only when their wargs trees match."""
    out = []
    for k in sorted(wargs):
        v = np.asarray(wargs[k])
        out.append((k, v.shape, v.dtype.str))
    return tuple(out)


def bucket_key(spec, g_pad: int, ts, val, gid, wargs: dict,
               host_small: bool, policy_epoch: int) -> tuple:
    """The compatibility key: everything the stacked jit program bakes
    in at trace time.  PipelineSpec is frozen/hashable (it IS the
    static argument); shapes/dtypes cover the operand layout; the
    mode-policy epoch keeps an autotune flip from splicing kernel
    generations into one launch."""
    return (spec, g_pad, ts.shape, val.dtype.str, gid.dtype.str,
            _wargs_signature(wargs), bool(host_small),
            int(policy_epoch))


class DispatchBatcher:
    """The rendezvous: submit() blocks until this plan's slice (or its
    bucket's error) is ready, and internally elects one submitting
    thread per bucket as the dispatch leader."""

    def __init__(self, config, tsdb=None):
        self.enabled = config.get_bool("tsd.query.batch.enable")
        self.hold_ms = max(config.get_int("tsd.query.batch.hold_ms"), 0)
        self.max_q = max(config.get_int("tsd.query.batch.max_q"), 1)
        self.max_bytes = max(
            config.get_int("tsd.query.batch.max_mb"), 1) * 2 ** 20
        self._tsdb = tsdb
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # open buckets by compatibility key
        self._buckets: dict[tuple, _Bucket] = {}  # guarded-by: _lock
        self.stacked_dispatches = 0  # guarded-by: _lock
        self.stacked_members = 0     # guarded-by: _lock
        self.solo_dispatches = 0     # guarded-by: _lock

    # -- demand hint ---------------------------------------------------- #

    def _concurrent_demand(self) -> int:
        """Queries currently holding admission permits — the leader's
        evidence that a sibling may arrive within the hold window.  An
        uncontended query (demand <= 1: only itself) never waits."""
        gate = getattr(self._tsdb, "_admission_gate", None)
        if gate is None:
            return 0
        with gate._lock:
            return gate.in_flight + gate._depth_locked()

    # -- the rendezvous -------------------------------------------------- #

    def submit(self, spec, ts, val, mask, gid, g_pad: int, wargs: dict,
               host_small: bool, policy_epoch: int, deadline=None):
        """Execute one batch-routed plan; returns ((out_ts, out_val,
        out_mask), info) where the outputs are the member's own
        host-unpacked slice (np arrays when stacked, device arrays on
        the solo fallback) and ``info`` carries the batch verdict for
        span annotation.  Raises the member's own deadline error if it
        expires while coalescing — siblings are unaffected."""
        member = _Member(ts, val, mask, np.asarray(gid), wargs, deadline)
        t0 = time.monotonic()
        key = bucket_key(spec, g_pad, ts, val, member.gid, wargs,
                         host_small, policy_epoch)
        with self._lock:
            bucket = self._buckets.get(key)
            leader = bucket is None
            if leader:
                bucket = _Bucket(key)
                self._buckets[key] = bucket
            bucket.members.append(member)
            bucket.nbytes += member.nbytes()
            full = (len(bucket.members) >= self.max_q
                    or bucket.nbytes >= self.max_bytes)
            if full and not bucket.sealed:
                bucket.sealed = True
                del self._buckets[bucket.key]
                self._cv.notify_all()
        if leader:
            self._lead(spec, g_pad, bucket, host_small, full, t0)
            if member.abandoned:
                # the leader's OWN deadline died while the window held:
                # it already dispatched for its live followers above,
                # but its answer would arrive past the deadline — same
                # exit as a dropped follower (413/503, siblings keep
                # their results)
                member.deadline.check()
                from opentsdb_tpu.query.limits import QueryException
                raise QueryException(
                    "Sorry, your query's deadline expired while "
                    "batched.")
        else:
            self._follow(bucket, member, t0)
        with self._lock:
            if member.error is not None:
                raise member.error
            result = member.result
        waited_ms = (time.monotonic() - t0) * 1e3
        # attribution boundary: the coalesce wait (which for followers
        # includes the leader's shared dispatch) is batch time; the
        # planner's own "dispatch" mark right after submit() returns
        # then reads ~0 for stacked members
        latattr.mark("batch_rendezvous")
        q = result[3]
        outcome = "stacked" if q > 1 else "solo"
        REGISTRY.counter(
            "tsd.query.batch.queries",
            "Batch-routed queries, by outcome").labels(
                outcome=outcome).inc()
        REGISTRY.histogram(
            "tsd.query.batch.wait_ms",
            "Coalesce wait before the stacked/solo dispatch "
            "(ms)").observe(waited_ms)
        return result[:3], {"q": q, "stacked": q > 1,
                            "waitMs": round(waited_ms, 3)}

    def _follow(self, bucket: _Bucket, member: _Member,
                t0: float) -> None:
        """Wait for the leader's dispatch; leave alone on own expiry."""
        with self._lock:
            while not member.done:
                deadline = member.deadline
                if deadline is not None and (deadline.is_cancelled()
                                             or deadline.expired()):
                    if not bucket.sealed:
                        # still coalescing: step out of the bucket so
                        # the leader never stacks a dead member
                        bucket.members.remove(member)
                        bucket.nbytes -= member.nbytes()
                    member.abandoned = True
                    member.done = True
                    break
                self._cv.wait(_WAIT_TICK_S)
        if member.abandoned and member.error is None \
                and member.result is None:
            # raises the deadline's own 413/503 — the member leaves
            # WITHOUT an answer, its siblings keep theirs
            member.deadline.check()
            from opentsdb_tpu.query.limits import QueryException
            raise QueryException(
                "Sorry, your query's deadline expired while batched.")

    def _lead(self, spec, g_pad: int, bucket: _Bucket,
              host_small: bool, already_full: bool, t0: float) -> None:
        """Hold the coalesce window, seal, stack, dispatch ONCE,
        distribute host-unpacked slices."""
        if not already_full:
            hold_s = self.hold_ms / 1e3 if self.hold_ms > 0 \
                and self._concurrent_demand() > 1 else 0.0
            deadline_t = t0 + hold_s
            with self._lock:
                while not bucket.sealed:
                    remaining = deadline_t - time.monotonic()
                    if remaining <= 0:
                        bucket.sealed = True
                        self._buckets.pop(bucket.key, None)
                        break
                    self._cv.wait(min(remaining, _WAIT_TICK_S))
        with self._lock:
            members = [m for m in bucket.members if not m.abandoned]
            # drop members whose deadline died while the window held —
            # the leader's own member included (it submitted first, but
            # first-in-line does not outrank the deadline; submit()
            # raises its 413/503 after this dispatch serves the rest)
            live: list[_Member] = []
            for m in members:
                d = m.deadline
                if d is not None and (d.is_cancelled() or d.expired()):
                    m.abandoned = True
                    m.done = True
                    continue
                live.append(m)
            self._cv.notify_all()
        try:
            outs = self._dispatch(spec, g_pad, live, host_small)
        except BaseException as e:
            with self._lock:
                for m in live:
                    m.error = e
                    m.done = True
                self._cv.notify_all()
            if isinstance(e, Exception):
                return      # the leader re-raises via submit()'s check
            raise
        with self._lock:
            for m, out in zip(live, outs):
                m.result = out
                m.done = True
            self._cv.notify_all()

    def _dispatch(self, spec, g_pad: int, live: list[_Member],
                  host_small: bool) -> list:
        """One launch for the sealed bucket.  Q == 1 short-circuits to
        the ordinary solo program (zero extra compile variants, and
        trivially bitwise-identical to an unbatched run); Q > 1 stacks
        along the member axis and unpacks HOST-SIDE — one np.asarray
        per output, microsecond row slices per member."""
        from opentsdb_tpu.ops.hostlane import host_lane
        q = len(live)
        if q == 0:
            return []
        if q == 1:
            m = live[0]
            with host_lane(host_small):
                out = run_group_pipeline(spec, m.ts, m.val, m.mask,
                                         m.gid, g_pad, m.wargs)
            with self._lock:
                self.solo_dispatches += 1
            return [(out[0], out[1], out[2], 1)]
        # The member axis pads to a power of FOUR (replicating the
        # first member; its extra slices are dropped after unpack), so
        # the stacked program compiles once per (bucket key, quantum)
        # instead of once per exact arrival count — without this, a
        # fleet whose bucket sizes jitter 2..16 recompiles on nearly
        # every dispatch and the batcher LOSES throughput (measured;
        # pow2 still left 4 live variants churning mid-burst).  The
        # padding waste is bounded (< 4x member cells) and members are
        # dispatch-bound by routing, so cells are cheap by definition.
        q_pad = 1
        while q_pad < q:
            q_pad *= 4
        q_pad = min(max(q_pad, 1), max(self.max_q, 1))
        padded = live + [live[0]] * (q_pad - q)
        ts = np.stack([m.ts for m in padded])
        val = np.stack([m.val for m in padded])
        mask = np.stack([m.mask for m in padded])
        gid = np.stack([m.gid for m in padded])
        wargs = {k: np.stack([np.asarray(m.wargs[k]) for m in padded])
                 for k in live[0].wargs}
        with host_lane(host_small):
            wts, out_val, out_mask = run_stacked_group_pipeline(
                spec, ts, val, mask, gid, g_pad, wargs)
        # host-side unpack: one transfer per output, then row views
        wts = np.asarray(wts)
        out_val = np.asarray(out_val)
        out_mask = np.asarray(out_mask)
        with self._lock:
            self.stacked_dispatches += 1
            self.stacked_members += q
        REGISTRY.counter(
            "tsd.query.batch.dispatches",
            "Stacked multi-query device dispatches").inc()
        REGISTRY.histogram(
            "tsd.query.batch.q",
            "Member queries per stacked dispatch").observe(float(q))
        recorder = getattr(self._tsdb, "flightrec", None)
        if recorder is not None:
            recorder.record("batch", q=q,
                            series=int(ts.shape[1]),
                            points=int(ts.shape[2]),
                            groups=int(g_pad),
                            hostSmall=bool(host_small))
        return [(wts[i], out_val[i], out_mask[i], q)
                for i in range(q)]

    # -- stats ----------------------------------------------------------- #

    def collect_stats(self) -> dict:
        with self._lock:
            return {
                "tsd.query.batch.stacked_dispatches": float(
                    self.stacked_dispatches),
                "tsd.query.batch.stacked_members": float(
                    self.stacked_members),
                "tsd.query.batch.solo_dispatches": float(
                    self.solo_dispatches),
            }
