"""The query EXPLAIN engine: /api/query/explain's no-dispatch what-if
planner (docs/query_explain.md).

Accepts the full ``/api/query`` request shape plus what-if overrides
and returns the complete routing decision tree — the admission
estimate vs the deadline with a shed/degrade-ladder preview, the
rollup-lane consult verdict with coverage, the agg-cache block
coverage, the grid-budget/tiling decision with predicted spill
traffic, and the per-axis costmodel pricing for every feasible
candidate — WITHOUT any device dispatch and without acquiring an
admission permit (explain is deadline-bounded but permit-exempt: an
overloaded daemon must still be explainable).

Drift-proofing is structural, not aspirational: the routing verdict
comes from the SAME ``plan_decision()`` the executor dispatches on
(query/plandecision.py), fed by read-only consult arms —
``RollupLanes.plan(observe=False)``, ``AggCache.plan(observe=False)``,
``DeviceSeriesCache.peek`` — so the explained path + fingerprint
equals what the flight-recorder ``plan`` event will record when the
same query executes (pinned per routing path by
tests/test_explain.py, and corpus-pinned by tools/plan_corpus.py ->
PLAN_CORPUS.json).

## What-if grammar

``what_if=key=value`` query-string params (repeatable) or a ``whatIf``
JSON object on POST:

  * ``assume_rollup=cold|warm``       lane store empty / fully covered
  * ``assume_agg_cache=cold|warm``    block cache empty / fully covered
  * ``assume_device_cache=cold|warm`` HBM column cache cold / pinned
  * ``state_mb=<int>``        hypothetical tsd.query.streaming.state_mb
  * ``rollup_mb=<int>``       hypothetical tsd.rollup.mb (0 = lanes off)
  * ``platform=cpu|tpu``      price for an alternate execution platform
  * ``calibration=default|file|auto`` reprice candidates from a layer
  * ``deadline_ms=<int>``     admission preview against this budget
  * ``force_search|force_scan|force_extreme|force_group=<mode>``
                              forced kernel modes in the report

Cache/budget/platform what-ifs feed the routing decision itself;
forced modes and the calibration layer produce a repriced
``costmodelWhatIf`` report beside the actual decision (per-candidate
pricing is already part of every decision report, so a forced mode is
a reporting question, not a global mode flip).
"""

from __future__ import annotations

import copy
import math
from dataclasses import dataclass, field

from opentsdb_tpu.ops.downsample import (AllWindow, FixedWindows,
                                         WindowSpec, pad_pow2,
                                         precompact_base)
from opentsdb_tpu.query import plandecision as pdn
from opentsdb_tpu.query.limits import QueryException, active_deadline

_ASSUME = ("live", "cold", "warm")
_CAL_LAYERS = ("auto", "default", "file")
_FORCE_AXES = ("search", "scan", "extreme", "group")


class WhatIfError(ValueError):
    """A what-if override the grammar refuses (400 at the endpoint)."""


@dataclass
class WhatIf:
    """Parsed what-if overrides; defaults = explain the live state."""
    assume_rollup: str = "live"
    assume_agg_cache: str = "live"
    assume_device_cache: str = "live"
    state_mb: int | None = None
    rollup_mb: int | None = None
    platform: str | None = None
    calibration: str = "auto"
    deadline_ms: int | None = None
    force: dict = field(default_factory=dict)   # axis -> mode

    @property
    def active(self) -> bool:
        return (self.assume_rollup != "live"
                or self.assume_agg_cache != "live"
                or self.assume_device_cache != "live"
                or self.state_mb is not None
                or self.rollup_mb is not None
                or self.platform is not None
                or self.calibration != "auto"
                or self.deadline_ms is not None
                or bool(self.force))

    def to_json(self) -> dict:
        out: dict = {}
        for key, live in (("assume_rollup", "live"),
                          ("assume_agg_cache", "live"),
                          ("assume_device_cache", "live"),
                          ("calibration", "auto")):
            value = getattr(self, key)
            if value != live:
                out[key] = value
        for key in ("state_mb", "rollup_mb", "platform", "deadline_ms"):
            value = getattr(self, key)
            if value is not None:
                out[key] = value
        for axis, mode in self.force.items():
            out["force_%s" % axis] = mode
        return out


# effects: pure
def parse_what_if(raw: dict) -> WhatIf:
    """The what-if grammar above; raises :class:`WhatIfError` on an
    unknown key or a value outside the grammar."""
    wi = WhatIf()
    for key, value in (raw or {}).items():
        value = str(value).strip().lower()
        if key in ("assume_rollup", "assume_agg_cache",
                   "assume_device_cache"):
            if value not in _ASSUME:
                raise WhatIfError(
                    "%s must be one of %s" % (key, "|".join(_ASSUME)))
            setattr(wi, key, value)
        elif key in ("state_mb", "rollup_mb", "deadline_ms"):
            try:
                parsed = int(value)
            except ValueError:
                raise WhatIfError("%s must be an integer" % key)
            if parsed < 0:
                raise WhatIfError("%s must be >= 0" % key)
            setattr(wi, key, parsed)
        elif key == "platform":
            if value not in ("cpu", "tpu"):
                raise WhatIfError("platform must be cpu|tpu")
            wi.platform = value
        elif key == "calibration":
            if value not in _CAL_LAYERS:
                raise WhatIfError("calibration must be one of %s"
                                  % "|".join(_CAL_LAYERS))
            wi.calibration = value
        elif key.startswith("force_") and key[6:] in _FORCE_AXES:
            wi.force[key[6:]] = value
        else:
            raise WhatIfError("unknown what-if key: %r" % key)
    return wi


# --------------------------------------------------------------------- #
# Read-only consult arms                                                #
# --------------------------------------------------------------------- #

@dataclass
class _WhatIfLanePlan:
    """A hypothetical lane hit (assume_rollup=warm): just enough
    surface for plan_decision's striping sizer and the fingerprint."""
    lane: str
    lane_ms: int
    k: int
    striped: bool = False
    tile_plan: object = None
    decision: dict = field(default_factory=dict)


class _ExplainConsults:
    """plan_decision()'s READ-ONLY consult provider: dry-run subsystem
    calls (``observe=False``), a pure device-cache peek, no accounting
    callbacks — explaining a query must not perturb what the executor
    then decides (see the observe contracts on each subsystem)."""

    def __init__(self, tsdb, ctx, what_if: WhatIf, seg, sub, windows,
                 store, series_list, fix):
        self.tsdb = tsdb
        self.ctx = ctx
        self.what_if = what_if
        self.seg = seg
        self.sub = sub
        self.windows = windows
        self.store = store
        self.series_list = series_list
        self.fix = fix

    def _metric(self) -> int:
        return self.series_list[0].key.metric

    # -- rollup ---------------------------------------------------------

    # effects: reads-only
    def rollup_plan(self):
        wi = self.what_if
        assume = wi.assume_rollup
        if wi.rollup_mb == 0:
            assume = "cold"
        if assume == "cold":
            return None, {"decision": "fallback",
                          "reason": "what_if_cold", "lane": "",
                          "coverage": 0.0}
        lanes = self.tsdb.rollup_lanes
        if assume == "warm":
            # a hypothetical full lane hit — honest only where the
            # PURE eligibility holds (derivable fn + a dividing lane)
            note = {"decision": "fallback", "reason": "", "lane": "",
                    "coverage": 0.0, "whatIf": "warm"}
            if not lanes.derivable(self.ctx.ds_fn):
                note["reason"] = "not_derivable"
                return None, note
            picked = lanes.lane_for(self.windows.interval_ms,
                                    self.windows.first_window_ms)
            if picked is None:
                note["reason"] = "no_lane_divides"
                return None, note
            label, lane_ms = picked
            k = self.windows.interval_ms // lane_ms
            note.update(decision="lane", reason="what_if_warm",
                        lane=label, coverage=1.0)
            return _WhatIfLanePlan(lane=label, lane_ms=lane_ms, k=k,
                                   decision=note), note
        ctx = self.ctx
        return lanes.plan(
            self._metric(), self.series_list, self.windows,
            self.seg.start_ms, self.seg.end_ms, ctx.ds_fn,
            ctx.platform, ctx.s, ctx.n_max, ctx.g_pad, ctx.has_rate,
            total_points=ctx.total_points, observe=False)

    # effects: pure
    def note_lane_served(self, plan) -> None:
        pass

    # effects: pure
    def note_lane_fallback(self) -> None:
        pass

    # -- tiled ----------------------------------------------------------

    # effects: pure
    def tiled_refusal(self, reason: str) -> None:
        pass

    # effects: reads-only
    def tiled_plan(self, acc_cell: int):
        from opentsdb_tpu.ops import tiling
        ctx = self.ctx
        return tiling.plan_tiled(
            self.tsdb, s=ctx.s, w=ctx.wp, g_pad=ctx.g_pad,
            acc_cell_bytes=acc_cell, total_points=ctx.total_points,
            platform=ctx.platform, state_mb=ctx.state_mb,
            observe=False)

    # -- agg cache -------------------------------------------------------

    # effects: reads-only
    def agg_plan(self, platform: str):
        assume = self.what_if.assume_agg_cache
        w = self.windows.count
        if assume == "cold":
            return None, {"decision": "recompute",
                          "reason": "what_if_cold", "coverage": 0.0,
                          "cachedWindows": 0, "computedWindows": w}
        if assume == "warm":
            note = {"decision": "rewrite", "reason": "what_if_warm",
                    "coverage": 1.0, "cachedWindows": w,
                    "computedWindows": 0}
            return object(), note
        ctx = self.ctx
        ds = self.sub.downsample_spec
        return self.tsdb.agg_cache.plan(
            self.store, self._metric(), self.series_list, self.windows,
            self.seg.start_ms, self.seg.end_ms, ctx.ds_fn,
            ds.fill_policy, ds.fill_value, platform, ctx.s, ctx.n_max,
            ctx.g_pad, ctx.has_rate, total_points=ctx.total_points,
            observe=False)

    # -- device cache ----------------------------------------------------

    # effects: reads-only
    def device_batch(self, build: bool, ts_base: int | None):
        assume = self.what_if.assume_device_cache
        if assume == "cold":
            return None
        if assume == "warm":
            return True
        warm = self.tsdb.device_cache.peek(
            self.store, self._metric(), self.series_list,
            self.seg.start_ms, self.seg.end_ms, self.fix, build=build,
            ts_base=ts_base)
        return True if warm else None


# --------------------------------------------------------------------- #
# What-if repricing                                                     #
# --------------------------------------------------------------------- #

def _reprice_decisions(decisions: dict, what_if: WhatIf, s: int,
                       n_pad: int, wp: int, g_dec: int,
                       platform: str) -> dict | None:
    """Forced-mode / alternate-calibration view of the per-axis
    decision reports: same candidate sets, repriced from the requested
    layer's table via the same ``cost_features`` vectors the fitter
    regresses on.  None when no costmodel what-if is active."""
    from opentsdb_tpu.ops import costmodel as cm
    if not what_if.force and what_if.calibration == "auto":
        return None
    table = cm.layer_table(platform, what_if.calibration)
    e = wp + 1
    out: dict = {}
    for axis, report in decisions.items():
        rep = dict(report)
        rep["calibration"] = what_if.calibration
        # dims mirror what each *_decision report priced with
        # (extreme_decision prices per-row: s=1)
        dims = {"search": (s, n_pad, e),
                "scan": (s, n_pad, e),
                "extreme": (1, n_pad, e),
                "group": (s, wp, e, g_dec)}[axis]
        priced = {}
        for mode in report["candidates"]:
            if axis == "group":
                fv = cm.cost_features("group", mode, dims[0], dims[1],
                                      dims[2], dims[3])
            else:
                fv = cm.cost_features(axis, mode, *dims)
            priced[mode] = round(sum(
                units * table[term] for term, units in fv.items())
                * 1e3, 4)
        rep["candidates"] = priced
        forced = what_if.force.get(axis)
        if forced is not None:
            rep["mode"] = forced
            rep["source"] = "what_if"
            rep["feasible"] = forced in priced
        elif priced:
            # the argmin under the repriced table (no hysteresis — a
            # what-if report must not touch the sticky-choice memory)
            rep["mode"] = min(priced, key=priced.get)
            rep["source"] = "what_if"
        out[axis] = rep
    return out


# --------------------------------------------------------------------- #
# Admission preview                                                     #
# --------------------------------------------------------------------- #

def _admission_preview(tsdb, ts_query, what_if: WhatIf) -> dict:
    """The admission verdict this query would get RIGHT NOW — the same
    ``estimate_plan_cost_ms`` + queue-wait estimate ``admit()``
    consults, with the degrade ladder run on a deep copy so the
    preview cannot mutate the request being explained.  No permit is
    acquired and no shed/degrade counters fire."""
    from opentsdb_tpu.tsd import admission
    gate = admission.gate_for(tsdb)
    predicted_ms = admission.estimate_plan_cost_ms(tsdb, ts_query)
    queue_ms = gate.queue_wait_estimate_ms()
    if what_if.deadline_ms is not None:
        remaining_ms = float(what_if.deadline_ms)
    else:
        deadline = active_deadline()
        if deadline is not None and deadline.bounded:
            remaining_ms = deadline.remaining_ms()
        else:
            remaining_ms = float(tsdb.config.get_int(
                "tsd.query.timeout"))
    bounded = remaining_ms > 0 and math.isfinite(remaining_ms)
    out = {
        "enabled": gate.enabled,
        "predictedMs": round(predicted_ms, 3),
        "queueWaitEstimateMs": round(queue_ms, 3),
        "remainingMs": round(remaining_ms, 3) if bounded else None,
        "verdict": "admit",
    }
    if gate.enabled and bounded \
            and predicted_ms + queue_ms > remaining_ms:
        note = None
        if tsdb.config.get_string(
                "tsd.query.degrade").strip().lower() == "allow":
            preview = copy.deepcopy(ts_query)
            note = admission.try_degrade(tsdb, preview, remaining_ms,
                                         queue_ms)
        if note is None:
            out["verdict"] = "shed"
            out["retryAfterS"] = gate.retry_after_s()
        else:
            out["verdict"] = "degrade"
            out["degraded"] = note
    return out


# --------------------------------------------------------------------- #
# The engine                                                            #
# --------------------------------------------------------------------- #

def explain_query(tsdb, ts_query, what_if: WhatIf) -> dict:
    """The complete decision tree for one parsed, validated TSQuery —
    zero device dispatches, zero admission permits, deadline-bounded
    (the per-sub QueryBudget charges the same scan the executor
    would, so an over-limit explain reports the 413 it predicts
    instead of doing unbounded planning work)."""
    runner = tsdb.new_query_runner()
    include_candidates = tsdb.config.get_bool(
        "tsd.explain.include_candidates")
    out = {
        "whatIf": what_if.to_json(),
        "admission": _admission_preview(tsdb, ts_query, what_if),
        "subQueries": [],
    }
    cluster = _explain_cluster(tsdb)
    if cluster is not None:
        out["cluster"] = cluster
    for sub in ts_query.queries:
        out["subQueries"].append(
            _explain_sub(tsdb, runner, ts_query, sub, what_if,
                         include_candidates))
    return out


def _explain_cluster(tsdb) -> dict | None:
    """The shard-scoped fan-out arm: WHICH peers a clustered query
    would fetch from, and which shards each would serve.  Same pure
    ``plan_cover`` the executor dispatches on (tsd/replication.py —
    the plan_decision convention applied to fan-out routing), consumed
    read-only: no epoch bump, no flight-recorder event, no breaker
    churn."""
    from opentsdb_tpu.tsd.cluster import cluster_peers
    peers = cluster_peers(tsdb.config)
    if not peers:
        return None
    repl = getattr(tsdb, "replication", None)
    if repl is None:
        return {"mode": "fanout", "peers": sorted(peers)}
    from opentsdb_tpu.tsd.replication import plan_cover
    cover, uncovered = plan_cover(repl.preferences, repl._healthy)
    return {
        "mode": "sharded",
        "epoch": repl.current_epoch(),
        "rf": repl.rf,
        "shardCount": repl.shard_count,
        "fanout": [
            {"node": node, "shards": len(shards),
             "role": "self" if node == repl.self_id else "peer"}
            for node, shards in sorted(cover.items())],
        "uncoveredShards": sorted(uncovered),
    }


def _explain_sub(tsdb, runner, query, sub, what_if: WhatIf,
                 include_candidates: bool) -> dict:
    report: dict = {"index": sub.index, "metric": sub.metric or None,
                    "aggregator": sub.aggregator, "segments": []}
    if sub.percentiles or sub.show_histogram_buckets:
        report["note"] = ("histogram plans are one bucket-scatter "
                          "dispatch and are not routed through "
                          "plan_decision")
        return report
    try:
        budget = runner._new_budget(sub)
        segments = runner._plan_segments(query, sub)
    except QueryException as e:
        report["refused"] = _refusal_json(e)
        return report
    for seg in segments:
        try:
            report["segments"].append(
                _explain_segment(tsdb, runner, query, sub, seg,
                                 what_if, budget, include_candidates))
        except QueryException as e:
            # the budget/deadline refusal the executor would raise —
            # reported, not served (the explain response itself is 200)
            report["segments"].append({
                "kind": seg.kind, "startMs": seg.start_ms,
                "endMs": seg.end_ms, "path": "refused",
                "refused": _refusal_json(e)})
            break
    return report


def _refusal_json(e: QueryException) -> dict:
    out = {"status": getattr(e, "status", 413), "message": str(e)}
    details = getattr(e, "details", None)
    if details:
        out["details"] = details
    return out


def _explain_segment(tsdb, runner, query, sub, seg, what_if: WhatIf,
                     budget, include_candidates: bool) -> dict:
    # series resolution + grouping + counts: the executor's scan,
    # read-only (QueryRunner methods shared, not re-implemented)
    if seg.kind == "raw":
        store = tsdb.store
        if sub.pre_aggregate and tsdb.rollup_store is not None:
            pre = tsdb.rollup_store.peek_lane("", sub.aggregator, True)
            store = pre if pre is not None else store
    else:
        store = seg.lane
    series_tags = runner._resolve_series(sub, store)
    groups = runner._group(series_tags, sub)
    windows = runner._windows_for(sub, query)
    base = {"kind": seg.kind, "startMs": seg.start_ms,
            "endMs": seg.end_ms, "series": len(series_tags),
            "groups": len(groups)}
    if windows is None:
        # union-timestamp aggregation: per-group fused dispatches, no
        # downsample grid — not routed through plan_decision
        base.update(path="union",
                    note="union plans dispatch per shape bucket and "
                         "are not routed through plan_decision")
        return base
    fix = tsdb.config.fix_duplicates
    kept = []
    for group_key in sorted(groups, key=lambda k: tuple(map(str, k))):
        members = groups[group_key]
        counts = [s.window_count(seg.start_ms, seg.end_ms, fix)
                  for s, _ in members]
        points = sum(counts)
        if points:
            budget.charge(points)
            kept.append((group_key, members, counts))
    if not kept:
        base.update(path="empty", note="no datapoints in range")
        return base
    budget.check_deadline()
    ds = sub.downsample_spec
    ds_fn = seg.ds_function or ds.function
    series_list = [s for _, members, _ in kept for s, _t in members]
    n_rows = len(series_list)
    total_points = sum(sum(c) for _, _, c in kept)
    n_max = max(max(c) for _, _, c in kept)
    g_pad = pad_pow2(len(kept))
    sketchable, hazard = runner._sketch_eligible(seg, ds_fn, windows,
                                                 kept, n_rows, fix)
    from opentsdb_tpu.ops.streaming import STREAMABLE_DS
    stream_ok = (seg.kind != "rollup_avg"
                 and (ds_fn in STREAMABLE_DS or sketchable))
    wp = 1 if isinstance(windows, AllWindow) else pad_pow2(windows.count)
    mesh = tsdb.query_mesh()
    use_mesh = (mesh is not None and n_rows >= tsdb.config.get_int(
        "tsd.query.mesh.min_series"))
    n_chips = 1
    if use_mesh:
        from opentsdb_tpu.parallel.sharded import n_devices
        n_chips = n_devices(mesh)
    ts_base = None
    if isinstance(windows, FixedWindows):
        ts_base = precompact_base(
            WindowSpec("fixed", wp, windows.interval_ms),
            windows.first_window_ms)
    from opentsdb_tpu.ops.hostlane import cpu_device, execution_platform
    platform = what_if.platform or execution_platform()
    state_mb = (what_if.state_mb if what_if.state_mb is not None
                else tsdb.config.get_int("tsd.query.streaming.state_mb"))
    ctx = pdn.RouteContext(
        seg_kind=seg.kind, ds_fn=ds_fn, aggregator=sub.aggregator,
        has_rate=bool(sub.rate), s=n_rows, n_max=int(n_max), wp=wp,
        groups=len(kept), g_pad=g_pad, total_points=int(total_points),
        sketchable=sketchable, stream_ok=stream_ok, use_mesh=use_mesh,
        n_chips=n_chips, windows_fixed=isinstance(windows, FixedWindows),
        store_is_raw=store is tsdb.store, has_store=store is not None,
        platform=platform, cpu_lane_ok=cpu_device() is not None,
        state_mb=state_mb,
        point_threshold=tsdb.config.get_int(
            "tsd.query.streaming.point_threshold"),
        host_lane_max=tsdb.config.get_int(
            "tsd.query.host_lane.max_points"),
        ts_base=ts_base,
        batch_ok=(getattr(tsdb, "dispatch_batcher", None) is not None
                  and tsdb.dispatch_batcher.enabled),
        batch_factor=tsdb.config.get_float(
            "tsd.query.batch.amortize_factor"))
    pd = pdn.plan_decision(
        tsdb, ctx, _ExplainConsults(tsdb, ctx, what_if, seg, sub,
                                    windows, store, series_list, fix))
    base.update(
        path=pd.path,
        fingerprint=pd.fingerprint,
        provenance=pd.fp_fields,
        shape={"series": ctx.s, "pointsMax": ctx.n_max,
               "nPad": pd.n_pad, "windows": ctx.wp,
               "groups": ctx.groups, "gPad": ctx.g_pad,
               "totalPoints": ctx.total_points,
               "platform": pd.dec_platform},
        budget={"kind": pd.gbd.kind, "gridMb": pd.gbd.grid_mb,
                "limitMb": pd.gbd.state_mb, "over": pd.gbd.over,
                "wouldStream": pd.would_stream},
        deviceCache={"warm": bool(pd.cached)},
        sketch={"sketchable": sketchable, "hazardFallback": hazard})
    if pd.lane_note is not None:
        base["rollup"] = pd.lane_note
    if pd.agg_note is not None:
        base["aggCache"] = pd.agg_note
    if pd.tiled_plan is not None:
        from opentsdb_tpu.ops import costmodel as cm
        tp = pd.tiled_plan
        base["tiling"] = {
            "tiles": tp.n_tiles, "tileRows": tp.tile_rows,
            "stripes": tp.n_stripes, "stripeWindows": tp.stripe_w,
            "spillBytes": tp.spill_bytes, "dispatches": tp.dispatches,
            "predictedOverheadMs": round(tp.predicted_s * 1e3, 3),
            "calibration": tp.source or cm.calibration_source(
                pd.dec_platform)}
    if pd.refusal is not None:
        base["refused"] = _refusal_json(pd.refusal.exception())
    # per-axis costmodel pricing for the report: plan_decision computes
    # the decisions only on monolithic paths (the hot-path rule);
    # explain is cold-path and always reports them
    from opentsdb_tpu.obs import jaxprof
    decisions = pd.decisions
    if decisions is None:
        decisions = jaxprof.segment_decisions(
            pd.dec_platform, ctx.s, pd.n_pad, ctx.wp, pd.g_dec,
            ctx.ds_fn, aggregator=ctx.aggregator)
    whatif_decisions = _reprice_decisions(
        decisions, what_if, ctx.s, pd.n_pad, ctx.wp, pd.g_dec,
        pd.dec_platform)
    if not include_candidates:
        decisions = {axis: {k: v for k, v in rep.items()
                            if k != "candidates"}
                     for axis, rep in decisions.items()}
        if whatif_decisions is not None:
            whatif_decisions = {
                axis: {k: v for k, v in rep.items()
                       if k != "candidates"}
                for axis, rep in whatif_decisions.items()}
    base["costmodel"] = decisions
    if whatif_decisions is not None:
        base["costmodelWhatIf"] = whatif_decisions
    return base
