"""Tag-value filters with the reference's dynamic registry and URI grammar.

Reference behavior: /root/reference/src/query/filter/TagVFilter.java (:70 —
abstract filter + registry :75-104, getFilter :199, mapToFilters/tagsToFilters
:306-360, stripParentheses :226) and the concrete filters:
TagVLiteralOrFilter (pipe-separated exact values, i-variant case-insensitive),
TagVNotLiteralOrFilter, TagVRegexFilter (java regex, full match),
TagVWildcardFilter ('*' glob, i-variant), TagVNotKeyFilter (series must lack
the tag key).  Filters marked group_by split results per tag value.

These run host-side against resolved tag value strings — the role the
reference's post-scan filter pass played (SaltScanner.java:700-740);
literal filters are additionally compiled to UID sets by the planner so
the hot path can prune series without string resolution.
"""

from __future__ import annotations

import re
from typing import Callable


class TagVFilter:
    """Base tag-value filter."""

    TYPE = "base"
    POST_SCAN = True

    def __init__(self, tagk: str, filter_str: str):
        if not tagk:
            raise ValueError("Tagk cannot be null or empty")
        if filter_str is None or filter_str == "":
            raise ValueError("Filter cannot be null or empty")
        self.tagk = tagk
        self.filter = filter_str
        self.group_by = False

    @property
    def type(self) -> str:
        return self.TYPE

    def match(self, tags: dict[str, str]) -> bool:
        """Whether a series' resolved {tagk: tagv} map passes this filter."""
        raise NotImplementedError

    def literal_values(self) -> set[str] | None:
        """The exact tag values this filter accepts, when enumerable."""
        return None

    def spec_string(self) -> str:
        return "%s(%s)" % (self.type, self.filter)

    def to_json(self) -> dict:
        return {
            "tagk": self.tagk,
            "filter": self.filter,
            "type": self.type,
            "group_by": self.group_by,
        }

    def __repr__(self) -> str:
        return "%s(%s=%s,group_by=%s)" % (
            type(self).__name__, self.tagk, self.filter, self.group_by)


class TagVLiteralOrFilter(TagVFilter):
    """literal_or: case-sensitive pipe-separated exact values."""

    TYPE = "literal_or"
    CASE_INSENSITIVE = False

    def __init__(self, tagk: str, filter_str: str):
        super().__init__(tagk, filter_str)
        values = [v for v in filter_str.split("|") if v]
        if not values:
            raise ValueError("No values in literal filter: " + filter_str)
        if self.CASE_INSENSITIVE:
            self._values = {v.lower() for v in values}
        else:
            self._values = set(values)

    def match(self, tags: dict[str, str]) -> bool:
        value = tags.get(self.tagk)
        if value is None:
            return False
        return (value.lower() if self.CASE_INSENSITIVE else value) in self._values

    def literal_values(self) -> set[str] | None:
        return None if self.CASE_INSENSITIVE else set(self._values)


class TagVILiteralOrFilter(TagVLiteralOrFilter):
    TYPE = "iliteral_or"
    CASE_INSENSITIVE = True


class TagVNotLiteralOrFilter(TagVLiteralOrFilter):
    """not_literal_or: excludes listed values; series WITHOUT the tag key
    pass (TagVNotLiteralOrFilter.java:80-83)."""

    TYPE = "not_literal_or"

    def match(self, tags: dict[str, str]) -> bool:
        value = tags.get(self.tagk)
        if value is None:
            return True
        return (value.lower() if self.CASE_INSENSITIVE
                else value) not in self._values

    def literal_values(self) -> set[str] | None:
        return None


class TagVNotILiteralOrFilter(TagVNotLiteralOrFilter):
    TYPE = "not_iliteral_or"
    CASE_INSENSITIVE = True


class TagVRegexFilter(TagVFilter):
    """regexp: full-match java-style regex (TagVRegexFilter)."""

    TYPE = "regexp"

    def __init__(self, tagk: str, filter_str: str):
        super().__init__(tagk, filter_str)
        try:
            self._pattern = re.compile(filter_str)
        except re.error as e:
            raise ValueError("Invalid regular expression: %s (%s)"
                             % (filter_str, e))

    def match(self, tags: dict[str, str]) -> bool:
        value = tags.get(self.tagk)
        if value is None:
            return False
        return self._pattern.fullmatch(value) is not None


class TagVWildcardFilter(TagVFilter):
    """wildcard: '*' glob; matches_all when the filter is just '*'."""

    TYPE = "wildcard"
    CASE_INSENSITIVE = False

    def __init__(self, tagk: str, filter_str: str):
        super().__init__(tagk, filter_str)
        if "*" not in filter_str:
            raise ValueError(
                "Filter must contain an asterisk: " + filter_str)
        actual = filter_str.lower() if self.CASE_INSENSITIVE else filter_str
        self.matches_all = set(actual) == {"*"}
        components = [c for c in actual.split("*")]
        pattern = ".*".join(re.escape(c) for c in components)
        self._pattern = re.compile("^" + pattern + "$")

    def match(self, tags: dict[str, str]) -> bool:
        value = tags.get(self.tagk)
        if value is None:
            return False
        if self.matches_all:
            return True
        if self.CASE_INSENSITIVE:
            value = value.lower()
        return self._pattern.match(value) is not None


class TagVIWildcardFilter(TagVWildcardFilter):
    TYPE = "iwildcard"
    CASE_INSENSITIVE = True


class TagVNotKeyFilter(TagVFilter):
    """not_key: matches series that do NOT carry the tag key at all."""

    TYPE = "not_key"

    def __init__(self, tagk: str, filter_str: str):
        # The reference requires an empty filter value (TagVNotKeyFilter).
        if filter_str and filter_str != " ":
            raise ValueError(
                "The filter value must be null or empty for not_key")
        if not tagk:
            raise ValueError("Tagk cannot be null or empty")
        self.tagk = tagk
        self.filter = ""
        self.group_by = False

    def match(self, tags: dict[str, str]) -> bool:
        return self.tagk not in tags


FILTER_TYPES: dict[str, type[TagVFilter]] = {
    cls.TYPE: cls for cls in (
        TagVLiteralOrFilter, TagVILiteralOrFilter, TagVNotLiteralOrFilter,
        TagVNotILiteralOrFilter, TagVRegexFilter, TagVWildcardFilter,
        TagVIWildcardFilter, TagVNotKeyFilter)
}


def build_filter(tagk: str, type_name: str, filter_str: str,
                 group_by: bool = False) -> TagVFilter:
    cls = FILTER_TYPES.get(type_name)
    if cls is None:
        raise ValueError("Could not find a filter of type: " + type_name)
    out = cls(tagk, filter_str)
    out.group_by = group_by
    return out


def strip_parentheses(filter_str: str) -> str:
    """"regexp(foo.*)" -> "foo.*" (TagVFilter.stripParentheses :226)."""
    if not filter_str:
        raise ValueError("Filter string cannot be null or empty")
    if not filter_str.endswith(")"):
        raise ValueError("Filter must end with a ')': " + filter_str)
    start = filter_str.find("(")
    if start < 0:
        raise ValueError("Filter must include a '(': " + filter_str)
    return filter_str[start + 1:-1]


def get_filter(tagk: str, filter_str: str) -> TagVFilter | None:
    """URI value -> filter; None means plain literal/group-by marker
    (TagVFilter.getFilter :199)."""
    if not tagk:
        raise ValueError("Tagk cannot be null or empty")
    if not filter_str:
        raise ValueError("Filter cannot be null or empty")
    if filter_str == "*":
        return None  # group-by-all marker
    paren = filter_str.find("(")
    if paren > -1:
        prefix = filter_str[:paren].lower()
        return build_filter(tagk, prefix, strip_parentheses(filter_str))
    if "*" in filter_str:
        return TagVWildcardFilter(tagk, filter_str)
    return None  # plain literal


def tags_to_filters(tag_map: dict[str, str],
                    filters: list[TagVFilter]) -> None:
    """First-brace group ({tag=value}): create group_by filters
    (TagVFilter.tagsToFilters :306)."""
    _map_to_filters(tag_map, filters, group_by=True)


def map_to_filters(tag_map: dict[str, str], filters: list[TagVFilter],
                   group_by: bool = False) -> None:
    """Second-brace group: non-grouping filters (TagVFilter.mapToFilters :318)."""
    _map_to_filters(tag_map, filters, group_by=group_by)


def _map_to_filters(tag_map: dict[str, str], filters: list[TagVFilter],
                    group_by: bool) -> None:
    for tagk, value in tag_map.items():
        parsed = get_filter(tagk, value)
        if parsed is None:
            if value == "*":
                parsed = TagVWildcardFilter(tagk, "*")
            else:
                parsed = TagVLiteralOrFilter(tagk, value)
        parsed.group_by = group_by
        filters.append(parsed)


def _parse_tag(tag_map: dict[str, str], tag: str) -> None:
    """"k=v" -> map entry (Tags.parse)."""
    if "=" not in tag:
        raise ValueError("invalid tag: " + tag)
    key, _, value = tag.partition("=")
    if not key or not value:
        raise ValueError("invalid tag: " + tag)
    if key in tag_map and tag_map[key] != value:
        raise ValueError("duplicate tag: %s, tags=%s" % (tag, tag_map))
    tag_map[key] = value


def parse_metric_with_filters(metric: str,
                              filters: list[TagVFilter]) -> str:
    """"metric{groupby}{filters}" -> metric name, filters filled
    (Tags.parseWithMetricAndFilters :220)."""
    if not metric:
        raise ValueError("Metric cannot be null or empty")
    if filters is None:
        raise ValueError("Filters cannot be null")
    curly = metric.find("{")
    if curly < 0:
        return metric
    if not metric.endswith("}"):
        raise ValueError("Missing '}' at the end of: " + metric)
    if curly == len(metric) - 2:  # "foo{}"
        return metric[:-2]
    close = metric.find("}")
    # Optional second brace group: non-grouping filters.
    if close != len(metric) - 1:
        filter_bracket = metric.rfind("{")
        for part in metric[filter_bracket + 1:-1].split(","):
            if not part:
                break
            tag_map: dict[str, str] = {}
            _parse_tag(tag_map, part)
            map_to_filters(tag_map, filters, group_by=False)
    # First brace group: group-by filters.
    for tag in metric[curly + 1:close].split(","):
        if not tag and close != len(metric) - 1:
            break
        tag_map = {}
        _parse_tag(tag_map, tag)
        tags_to_filters(tag_map, filters)
    return metric[:curly]
