"""Per-metric scan budgets and query timeout enforcement.

Reference behavior: /root/reference/src/query/QueryLimitOverride.java —
regex-keyed byte/datapoint budget overrides hot-reloaded from a JSON file
(:44-52, loadFromFile), first match wins, defaults when nothing matches
(getByteLimit :137, getDataPointLimit :157) — and the enforcement sites in
SaltScanner.java: the running query fails with HTTP 413 when it exceeds the
datapoint budget (:580), the byte budget (:596), or `tsd.query.timeout`
(:559).

The TPU rebuild enforces at the planner: budgets are charged as series
windows are selected (before any device batch materializes — the whole
point is refusing work that would OOM the host building the batch), and the
deadline is checked between group/segment dispatches.
"""

from __future__ import annotations

import json
import os
import re
import time
from dataclasses import dataclass


class QueryException(Exception):
    """Query failed mid-flight; carries the HTTP status (QueryException.java)."""

    def __init__(self, message: str, status: int = 413):
        super().__init__(message)
        self.status = status


# Charged per datapoint when estimating "bytes fetched from storage":
# 8B timestamp + 8B value in the columnar chunks (the reference counted
# HBase cell bytes; ours is the columnar at-rest cost).
BYTES_PER_POINT = 16


@dataclass
class LimitOverrideItem:
    """One override entry (QueryLimitOverrideItem :249-295)."""
    regex: str
    byte_limit: int = 0
    data_points_limit: int = 0

    def __post_init__(self):
        self._pattern = re.compile(self.regex)

    def matches(self, metric: str) -> bool:
        return bool(self._pattern.search(metric))


class QueryLimitOverride:
    """Budget registry with file hot-reload (QueryLimitOverride.java:92-118).

    The overrides file is a JSON array of
    ``{"regex": ..., "byteLimit": N, "dataPointsLimit": N}`` objects
    (Jackson's serialization of QueryLimitOverrideItem); camelCase and
    snake_case keys are both accepted.  Reloaded at most every
    ``tsd.query.limits.overrides.interval`` seconds, and only when the file
    mtime changed.
    """

    def __init__(self, config):
        self.default_byte_limit = config.get_int(
            "tsd.query.limits.bytes.default")
        self.default_data_points_limit = config.get_int(
            "tsd.query.limits.data_points.default")
        if self.default_byte_limit < 0:
            raise ValueError("The default byte limit cannot be negative")
        if self.default_data_points_limit < 0:
            raise ValueError(
                "The default data points limit cannot be negative")
        self.file_location = config.get_string(
            "tsd.query.limits.overrides.config")
        self.reload_interval = config.get_int(
            "tsd.query.limits.overrides.interval")
        self.overrides: list[LimitOverrideItem] = []
        self._mtime = 0.0
        self._next_check = 0.0
        if self.file_location:
            self._load_from_file()

    def _load_from_file(self) -> None:
        try:
            mtime = os.path.getmtime(self.file_location)
        except OSError:
            return
        if mtime == self._mtime:
            return
        with open(self.file_location) as fh:
            raw = json.load(fh)
        items = []
        for entry in raw:
            items.append(LimitOverrideItem(
                regex=entry["regex"],
                byte_limit=int(entry.get("byteLimit",
                                         entry.get("byte_limit", 0))),
                data_points_limit=int(entry.get(
                    "dataPointsLimit", entry.get("data_points_limit", 0)))))
        self.overrides = items
        self._mtime = mtime

    def maybe_reload(self) -> None:
        """Hot-reload check, rate-limited to the configured interval."""
        if not self.file_location or self.reload_interval <= 0:
            return
        now = time.time()
        if now < self._next_check:
            return
        self._next_check = now + self.reload_interval
        try:
            self._load_from_file()
        except (OSError, ValueError, KeyError, re.error):
            pass  # keep serving the last good config (loadFromFile catch)

    def get_byte_limit(self, metric: str) -> int:
        if metric:
            for item in self.overrides:
                if item.matches(metric):
                    return item.byte_limit
        return self.default_byte_limit

    def get_data_points_limit(self, metric: str) -> int:
        if metric:
            for item in self.overrides:
                if item.matches(metric):
                    return item.data_points_limit
        return self.default_data_points_limit


class QueryBudget:
    """Running charge for one sub query (the SaltScanner counters).

    Raises QueryException with the reference's 413 error shape when the
    datapoint budget (:580), byte budget (:596), or wall-clock deadline
    (:559) is exceeded.
    """

    def __init__(self, limits: QueryLimitOverride | None, metric: str,
                 timeout_ms: int):
        self.max_data_points = (
            limits.get_data_points_limit(metric) if limits else 0)
        self.max_bytes = limits.get_byte_limit(metric) if limits else 0
        self.timeout_ms = timeout_ms
        self.start = time.monotonic()
        self.data_points = 0

    def charge(self, num_points: int) -> None:
        self.data_points += num_points
        if 0 < self.max_data_points <= self.data_points:
            raise QueryException(
                "Sorry, you have attempted to fetch more than our limit of "
                "%d data points. Please try filtering using more tags or "
                "decrease your time range." % self.max_data_points)
        if self.max_bytes > 0 and \
                self.data_points * BYTES_PER_POINT > self.max_bytes:
            raise QueryException(
                "Sorry, you have attempted to fetch more than our maximum "
                "amount of %dMB from storage. Please try filtering using "
                "more tags or decrease your time range."
                % (self.max_bytes / 1024 / 1024))

    def check_deadline(self) -> None:
        if self.timeout_ms <= 0:
            return
        elapsed_ms = (time.monotonic() - self.start) * 1000.0
        if elapsed_ms > self.timeout_ms:
            raise QueryException(
                "Sorry, your query timed out. Time limit: %d ms, elapsed: "
                "%d ms. Please try filtering using more tags or decrease "
                "your time range." % (self.timeout_ms, elapsed_ms))
