"""Per-metric scan budgets and query timeout enforcement.

Reference behavior: /root/reference/src/query/QueryLimitOverride.java —
regex-keyed byte/datapoint budget overrides hot-reloaded from a JSON file
(:44-52, loadFromFile), first match wins, defaults when nothing matches
(getByteLimit :137, getDataPointLimit :157) — and the enforcement sites in
SaltScanner.java: the running query fails with HTTP 413 when it exceeds the
datapoint budget (:580), the byte budget (:596), or `tsd.query.timeout`
(:559).

The TPU rebuild enforces at the planner: budgets are charged as series
windows are selected (before any device batch materializes — the whole
point is refusing work that would OOM the host building the batch), and the
deadline is checked between group/segment dispatches.
"""

from __future__ import annotations

import json
import logging
import math
import os
import re
import threading
import time
from dataclasses import dataclass

LOG = logging.getLogger(__name__)


class QueryException(Exception):
    """Query failed mid-flight; carries the HTTP status (QueryException.java)
    and an optional structured ``details`` payload for the error
    envelope (the grid-budget 413s report computed MB / limit /
    suggested config machine-readably)."""

    def __init__(self, message: str, status: int = 413,
                 details: dict | None = None):
        super().__init__(message)
        self.status = status
        self.details = details


class QueryCancelledException(QueryException):
    """The request-scoped deadline was cancelled mid-flight: the client
    disconnected, the server is draining, or the deadline expired and an
    outside party (the responder loop) flipped the token.  503: the
    server gave up on purpose, the query itself was not malformed."""

    def __init__(self, message: str):
        super().__init__(message, status=503)


class QueryDeadlineExpired(QueryException):
    """The request outlived its wall budget (`Deadline.check`).  Same
    413 shape/message as the reference timeout — a distinct type so the
    error envelope (tsd/rpc_manager.py) can record a `deadline` event
    in the flight recorder without string-matching the message."""


class Deadline:
    """One request-scoped wall budget + cooperative cancellation token.

    Minted ONCE per request (rpc_manager.handle_http) from
    ``tsd.query.timeout`` and/or the client's ``X-TSDB-Deadline-Ms``
    header (whichever is smaller), then threaded through the whole
    lifecycle: every planner ``QueryBudget`` derives its clock from this
    object instead of a fresh ``time.monotonic()``, the cluster fan-out
    clamps its retry budget to ``remaining_ms()`` and forwards the
    remainder to peers, and the admission gate refuses queries whose
    predicted cost cannot fit in what's left.

    Cancellation is COOPERATIVE: ``cancel()`` flips the token (client
    disconnect is detected by the server responder loop; drain timeout
    by ``TSDServer.stop``), and every existing ``check_deadline()``
    site — plus the admission-queue wait — observes it via ``check()``.
    """

    def __init__(self, timeout_ms: float = 0.0,
                 clock=time.monotonic):
        self.start = clock()
        self.timeout_ms = float(timeout_ms)      # <= 0: unbounded
        self._clock = clock
        self._lock = threading.Lock()
        self._cancelled = False  # guarded-by: _lock
        self._cancel_reason = ""  # guarded-by: _lock
        # the wakeable half of the token: request-path sleeps park on
        # this instead of time.sleep so cancel() interrupts them
        self._cancel_event = threading.Event()

    @property
    def bounded(self) -> bool:
        return self.timeout_ms > 0

    def elapsed_ms(self) -> float:
        return (self._clock() - self.start) * 1e3

    def remaining_ms(self) -> float:
        """Milliseconds left; +inf when unbounded, <= 0 once expired."""
        if not self.bounded:
            return math.inf
        return self.timeout_ms - self.elapsed_ms()

    def expired(self) -> bool:
        return self.bounded and self.remaining_ms() <= 0.0

    def cancel(self, reason: str) -> bool:
        """Flip the cancellation token (idempotent; first reason wins).
        Returns True when this call did the flip."""
        with self._lock:
            if self._cancelled:
                return False
            self._cancelled = True
            self._cancel_reason = reason
        self._cancel_event.set()
        return True

    def is_cancelled(self) -> bool:
        return self._cancelled

    def wait_cancelled(self, timeout_s: float) -> bool:
        """An interruptible sleep: block up to ``timeout_s`` seconds
        (clamped to the remaining wall budget when bounded) OR until
        ``cancel()`` flips the token, whichever comes first.  Returns
        ``is_cancelled()`` so pollers can tell the wake reasons apart.

        This is the primitive request-path code must use instead of
        ``time.sleep``: a bare sleep serves out its full delay for a
        client that already disconnected, while this one releases
        within the tick that the responder loop cancels the request
        (tsdblint's deadline_discipline pins the distinction)."""
        if timeout_s > 0 and not self._cancelled:
            if self.bounded:
                timeout_s = min(timeout_s,
                                max(self.remaining_ms() / 1e3, 0.0))
            self._cancel_event.wait(timeout_s)
        return self._cancelled

    @property
    def cancel_reason(self) -> str:
        return self._cancel_reason

    def check(self) -> None:
        """Raise if this request should stop doing work NOW: cancelled
        (503) or past its wall budget (the reference's 413 shape)."""
        if self._cancelled:
            raise QueryCancelledException(
                "Query cancelled: %s" % (self._cancel_reason or "unknown"))
        if self.expired():
            raise QueryDeadlineExpired(
                "Sorry, your query timed out. Time limit: %d ms, elapsed: "
                "%d ms. Please try filtering using more tags or decrease "
                "your time range." % (self.timeout_ms, self.elapsed_ms()))


# --------------------------------------------------------------------- #
# Ambient request deadline: one per responder thread                    #
# --------------------------------------------------------------------- #

_tls = threading.local()


def activate_deadline(deadline: Deadline) -> None:
    _tls.deadline = deadline


def deactivate_deadline() -> None:
    _tls.deadline = None


def active_deadline() -> Deadline | None:
    """The current request's deadline, or None outside a request (the
    library-caller path: QueryRunner.run with no server above it)."""
    return getattr(_tls, "deadline", None)


# --------------------------------------------------------------------- #
# Shared device-state grid budget (tsd.query.streaming.state_mb)        #
# --------------------------------------------------------------------- #

# The three planner enforcement sites (streaming accumulator,
# materialized downsample grid, histogram bucket grid) each estimate
# their grid bytes differently BY DESIGN, but the limit read, the
# over/under decision, and the structured 413 all live here — the
# copy-pasted refusal prose can never drift again, and the tiled
# executor consults the same decision to know a plan "would have
# refused" (ops/tiling.py).

_GRID_MESSAGES = {
    "streaming": (
        "Sorry, this query's streaming state (%d series x %d windows%s) "
        "needs ~%dMB of accelerator memory per chip, over the %dMB "
        "limit (tsd.query.streaming.state_mb). Please use a coarser "
        "downsample interval or decrease your time range."),
    "grid": (
        "Sorry, this query's downsample grid (%d series x %d windows%s) "
        "needs ~%dMB of accelerator memory per chip, over the %dMB "
        "limit (tsd.query.streaming.state_mb). Please use a coarser "
        "downsample interval or decrease your time range."),
    "histogram": (
        "Sorry, this histogram query's bucket grid (%d windows x "
        "%d buckets%s) needs ~%dMB of accelerator memory, over the "
        "%dMB limit (tsd.query.streaming.state_mb). Please use a "
        "coarser downsample interval or decrease your time range."),
}


@dataclass(frozen=True)
class GridBudgetDecision:
    """One grid-vs-budget verdict: the bytes a plan's device-resident
    grid needs against the configured allowance."""
    kind: str           # "streaming" | "grid" | "histogram"
    grid_bytes: int
    state_mb: int       # configured limit; <= 0 disables the guard
    dim_a: int          # series (rows for histogram)
    dim_b: int          # windows (buckets for histogram)
    sketch: bool = False

    @property
    def over(self) -> bool:
        return self.state_mb > 0 and self.grid_bytes > self.state_mb * 2**20

    @property
    def grid_mb(self) -> int:
        return self.grid_bytes // 2**20

    def exception(self) -> QueryException:
        """The structured 413: the reference's budget prose plus a
        machine-readable details payload (computed MB, limit, suggested
        config) for operators and clients."""
        from opentsdb_tpu.ops.streaming import SKETCH_K
        note = " x %d-point sketches" % SKETCH_K if self.sketch else ""
        return QueryException(
            _GRID_MESSAGES[self.kind]
            % (self.dim_a, self.dim_b, note, self.grid_mb, self.state_mb),
            details={
                "gridMb": self.grid_mb,
                "limitMb": self.state_mb,
                "limitKey": "tsd.query.streaming.state_mb",
                "kind": self.kind,
                "suggestion": "use a coarser downsample interval, "
                              "decrease the time range, or raise "
                              "tsd.query.streaming.state_mb / enable "
                              "tsd.query.spill.enable for tiled "
                              "execution",
            })


def grid_budget(kind: str, state_mb: int, grid_bytes: int, dim_a: int,
                dim_b: int, sketch: bool = False) -> GridBudgetDecision:
    """THE shared guard: every state_mb enforcement site builds its
    decision here.  Callers compute ``grid_bytes`` (their estimates
    differ by design); raising ``decision.exception()`` yields the one
    canonical 413."""
    if kind not in _GRID_MESSAGES:
        raise ValueError("unknown grid budget kind: %r" % kind)
    return GridBudgetDecision(kind, int(grid_bytes), int(state_mb),
                              int(dim_a), int(dim_b), sketch)


# Everything a hostile/corrupt overrides file can raise through
# json.load + LimitOverrideItem construction: I/O, non-JSON bytes
# (ValueError covers JSONDecodeError and non-UTF-8 decode), a missing
# "regex" key, non-mapping entries (TypeError), a bad regex.
_LOAD_ERRORS = (OSError, ValueError, KeyError, TypeError, re.error)

# Charged per datapoint when estimating "bytes fetched from storage":
# 8B timestamp + 8B value in the columnar chunks (the reference counted
# HBase cell bytes; ours is the columnar at-rest cost).
BYTES_PER_POINT = 16


@dataclass
class LimitOverrideItem:
    """One override entry (QueryLimitOverrideItem :249-295)."""
    regex: str
    byte_limit: int = 0
    data_points_limit: int = 0

    def __post_init__(self):
        self._pattern = re.compile(self.regex)

    def matches(self, metric: str) -> bool:
        return bool(self._pattern.search(metric))


class QueryLimitOverride:
    """Budget registry with file hot-reload (QueryLimitOverride.java:92-118).

    The overrides file is a JSON array of
    ``{"regex": ..., "byteLimit": N, "dataPointsLimit": N}`` objects
    (Jackson's serialization of QueryLimitOverrideItem); camelCase and
    snake_case keys are both accepted.  Reloaded at most every
    ``tsd.query.limits.overrides.interval`` seconds, and only when the file
    mtime changed.
    """

    def __init__(self, config):
        self.default_byte_limit = config.get_int(
            "tsd.query.limits.bytes.default")
        self.default_data_points_limit = config.get_int(
            "tsd.query.limits.data_points.default")
        if self.default_byte_limit < 0:
            raise ValueError("The default byte limit cannot be negative")
        if self.default_data_points_limit < 0:
            raise ValueError(
                "The default data points limit cannot be negative")
        self.file_location = config.get_string(
            "tsd.query.limits.overrides.config")
        self.reload_interval = config.get_int(
            "tsd.query.limits.overrides.interval")
        self.overrides: list[LimitOverrideItem] = []
        self._mtime = 0.0
        self._next_check = 0.0
        self.reload_errors = 0
        self._logged_errors: set[str] = set()
        if self.file_location:
            # A corrupt/unreadable overrides file must not crash TSDB
            # construction (the hot-reload path already keeps last-good;
            # construction starts from defaults): log, count, serve.
            try:
                self._load_from_file()
            except _LOAD_ERRORS as e:
                self._count_reload_error(e, during="construction")

    def _load_from_file(self) -> None:
        try:
            mtime = os.path.getmtime(self.file_location)
        except OSError:
            return
        if mtime == self._mtime:
            return
        with open(self.file_location) as fh:
            raw = json.load(fh)
        items = []
        for entry in raw:
            items.append(LimitOverrideItem(
                regex=entry["regex"],
                byte_limit=int(entry.get("byteLimit",
                                         entry.get("byte_limit", 0))),
                data_points_limit=int(entry.get(
                    "dataPointsLimit", entry.get("data_points_limit", 0)))))
        self.overrides = items
        self._mtime = mtime

    def _count_reload_error(self, exc: Exception,
                            during: str = "reload") -> None:
        """An overrides file the loader refused: keep serving the
        current (last-good or default) limits, but leave an operator
        trail — a counter on every failure, a log line once per
        DISTINCT error so a bad push is loud without a log flood."""
        self.reload_errors += 1
        from opentsdb_tpu.obs.registry import REGISTRY
        REGISTRY.counter(
            "tsd.query.limits.reload_errors",
            "Query-limit overrides loads that failed (kept last "
            "good)").inc()
        key = "%s: %s" % (type(exc).__name__, exc)
        if key not in self._logged_errors:
            self._logged_errors.add(key)
            LOG.error(
                "query limit overrides %s failed on %s (%s); keeping %s",
                during, self.file_location, key,
                "last good config" if self.overrides else "defaults")

    def maybe_reload(self) -> None:
        """Hot-reload check, rate-limited to the configured interval."""
        if not self.file_location or self.reload_interval <= 0:
            return
        now = time.time()
        if now < self._next_check:
            return
        self._next_check = now + self.reload_interval
        try:
            self._load_from_file()
        except _LOAD_ERRORS as e:
            # keep serving the last good config (loadFromFile catch) —
            # but counted and logged, not silent
            self._count_reload_error(e)

    def get_byte_limit(self, metric: str) -> int:
        if metric:
            for item in self.overrides:
                if item.matches(metric):
                    return item.byte_limit
        return self.default_byte_limit

    def get_data_points_limit(self, metric: str) -> int:
        if metric:
            for item in self.overrides:
                if item.matches(metric):
                    return item.data_points_limit
        return self.default_data_points_limit


class QueryBudget:
    """Running charge for one sub query (the SaltScanner counters).

    Raises QueryException with the reference's 413 error shape when the
    datapoint budget (:580), byte budget (:596), or wall-clock deadline
    (:559) is exceeded.
    """

    def __init__(self, limits: QueryLimitOverride | None, metric: str,
                 timeout_ms: int, deadline: Deadline | None = None):
        self.max_data_points = (
            limits.get_data_points_limit(metric) if limits else 0)
        self.max_bytes = limits.get_byte_limit(metric) if limits else 0
        self.timeout_ms = timeout_ms
        # Derived from the REQUEST deadline when one is active: every
        # sub query of a request shares the clock that started when the
        # request arrived, instead of each sub query restarting
        # tsd.query.timeout from planner time.
        self.deadline = deadline
        self.start = deadline.start if deadline is not None \
            else time.monotonic()
        self.data_points = 0

    def charge(self, num_points: int) -> None:
        self.data_points += num_points
        if 0 < self.max_data_points <= self.data_points:
            raise QueryException(
                "Sorry, you have attempted to fetch more than our limit of "
                "%d data points. Please try filtering using more tags or "
                "decrease your time range." % self.max_data_points)
        if self.max_bytes > 0 and \
                self.data_points * BYTES_PER_POINT > self.max_bytes:
            raise QueryException(
                "Sorry, you have attempted to fetch more than our maximum "
                "amount of %dMB from storage. Please try filtering using "
                "more tags or decrease your time range."
                % (self.max_bytes / 1024 / 1024))

    def check_deadline(self) -> None:
        if self.deadline is not None:
            # request-scoped expiry + the cooperative cancellation token
            # (client disconnect, server drain) — checked at every
            # existing deadline site for free
            self.deadline.check()
        if self.timeout_ms <= 0:
            return
        elapsed_ms = (time.monotonic() - self.start) * 1000.0
        if elapsed_ms > self.timeout_ms:
            # same type as Deadline.check's expiry so the error
            # envelope records a `deadline` flight-recorder event for
            # BOTH timeout arms (a budget running without an ambient
            # Deadline must not be invisible in the black box)
            raise QueryDeadlineExpired(
                "Sorry, your query timed out. Time limit: %d ms, elapsed: "
                "%d ms. Please try filtering using more tags or decrease "
                "your time range." % (self.timeout_ms, elapsed_ms))
