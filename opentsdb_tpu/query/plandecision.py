"""plan_decision(): the planner's routing verdict as ONE pure function.

Before this module the five-way fast-path arbitration (rollup lane vs
agg-cache rewrite vs tiled spill vs streamed vs resident, with the
mesh/host-lane/device-cache sub-choices) lived inline in
``QueryRunner._run_segment_grouped`` — executable, but not askable.
The EXPLAIN engine (query/explain.py, /api/query/explain) must answer
"which path would this query take, and why" WITHOUT dispatching, and
the only way report and execution provably cannot drift is the PR 6
convention applied to routing itself: one decision function, two
callers.

  * The EXECUTOR builds an ``ExecConsults``-style provider whose
    consult hooks do real work (``RollupLanes.plan`` with demand
    recording, ``AggCache.plan`` with repeat bookkeeping,
    ``DeviceSeriesCache.batch_for`` with the device gather) and
    dispatches on the returned :class:`PlanDecision`.
  * EXPLAIN builds a read-only provider (``observe=False`` consult
    arms, ``DeviceSeriesCache.peek``) and serializes the same
    :class:`PlanDecision` — same eligibility gates, same ordering,
    same ``grid_budget`` guard, same ``_effective_*`` choosers behind
    ``segment_decisions``.

Every decision carries a stable **plan fingerprint** — a hash over the
discrete routing facts (path, shapes, chosen kernel modes, lane/cache
verdicts, calibration layer; never raw milliseconds) — which the
executor stamps into the flight-recorder ``plan`` event and the
pipeline span, so explain-vs-actual parity is mechanically checkable
and ``PLAN_CORPUS.json`` can byte-pin the routing of a canonical query
matrix (tools/plan_corpus.py).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field

from opentsdb_tpu.query.limits import GridBudgetDecision, grid_budget

# Paths whose dispatch runs the monolithic downsample/group kernels —
# the only paths whose per-axis kernel-mode decisions describe what
# actually executes (lane/tiled/agg-rewrite paths run their own
# programs); their fingerprints include the chosen modes.  "batched"
# is monolithic too: the stacked [Q, S, W] kernel vmaps the SAME
# grouped pipeline, and inside the vmap the mode choosers see the
# per-member [S, N] shapes a solo dispatch would.
MONOLITHIC_PATHS = frozenset(
    {"streamed", "resident", "host_lane", "mesh", "rollup_avg",
     "batched"})


@dataclass(frozen=True)
class RouteContext:
    """Everything the routing verdict depends on, snapshotted once.

    The executor fills this from live config + the scan it just
    budgeted; explain fills the same fields from a read-only walk (and
    may override the config-derived ones — ``state_mb``, ``platform`` —
    for what-if analysis)."""
    seg_kind: str            # "raw" | "rollup" | "rollup_avg"
    ds_fn: str | None
    aggregator: str
    has_rate: bool
    s: int                   # series rows in the dispatch (len(gid))
    n_max: int               # max per-series point count, unpadded
    wp: int                  # padded window count (window_spec.count)
    groups: int              # group-by buckets kept (len(kept))
    g_pad: int               # padded group axis of the dispatch
    total_points: int
    sketchable: bool
    stream_ok: bool
    use_mesh: bool
    n_chips: int
    windows_fixed: bool      # isinstance(windows, FixedWindows)
    store_is_raw: bool       # store is tsdb.store
    has_store: bool
    platform: str            # execution_platform() (or a what-if)
    cpu_lane_ok: bool        # cpu_device() is not None
    state_mb: int
    point_threshold: int
    host_lane_max: int
    ts_base: int | None
    # fused multi-query dispatch (query/batcher.py): tsd.query.batch.*
    # enablement + the coalesce-pricing factor; the executor fills
    # these from live config, explain from the same keys, so the
    # `batched` arm cannot drift between them
    batch_ok: bool = False
    batch_factor: float = 0.0


@dataclass
class PlanDecision:
    """One grouped segment's complete routing verdict."""
    path: str
    would_stream: bool
    use_mesh: bool
    host_small: bool
    lane_small: bool
    gbd: GridBudgetDecision          # the governing budget decision
    grid_gbd: GridBudgetDecision     # the materialized-grid decision
    lane_plan: object = None
    lane_note: dict | None = None
    tiled_plan: object = None
    agg_plan: object = None
    agg_note: dict | None = None
    cached: object = None            # device batch (executor) / bool
    refusal: GridBudgetDecision | None = None
    decisions: dict | None = None    # per-axis kernel-mode decisions
    n_pad: int = 0
    g_dec: int = 0
    dec_platform: str = ""
    fp_fields: dict = field(default_factory=dict)
    fingerprint: str = ""


def acc_cell_bytes(ds_fn: str | None, sketchable: bool) -> int:
    """Streaming accumulator bytes per (series, window) cell — the ONE
    formula behind the streaming budget estimate, the tiled plan
    sizing, and admission's out-of-core pricing."""
    from opentsdb_tpu.ops.streaming import SKETCH_K, lanes_for
    return 8 + 8 * len(lanes_for([ds_fn])) \
        + (4 * SKETCH_K if sketchable else 0)


# effects: pure
def grid_budget_for(state_mb: int, s: int, wp: int, seg_kind: str,
                    n_chips: int) -> GridBudgetDecision:
    """The materialized-grid budget decision (the planner's
    ``grid_budget_decision`` closure, extracted): ~3 grid lanes live
    through a dispatch; per chip when the mesh shards the rows, except
    rollup_avg which never shards and carries a second count-lane
    grid."""
    lanes = 2 if seg_kind == "rollup_avg" else 1
    chips = 1 if seg_kind == "rollup_avg" else max(n_chips, 1)
    grid_bytes = s * wp * 24 * lanes // chips
    return grid_budget("grid", state_mb, grid_bytes, s, wp)


# effects: pure
def streaming_budget_for(state_mb: int, s: int, wp: int,
                         ds_fn: str | None, sketchable: bool,
                         n_chips: int) -> GridBudgetDecision:
    """The streaming-accumulator budget decision (the planner's
    ``streaming_budget_decision`` closure, extracted)."""
    per_cell = acc_cell_bytes(ds_fn, sketchable)
    est = s * wp * per_cell // max(n_chips, 1)
    return grid_budget("streaming", state_mb, est, s, wp,
                       sketch=sketchable)


def size_lane_stripes(tsdb, plan, s: int, wp: int, g_pad: int,
                      state_mb: int, aggregator: str):
    """Attach an over-budget serve sizing to a rollup lane plan (moved
    from the planner so explain sizes striping identically).

    Moment-decomposable cross-series aggregators fold tile by tile
    into [G, W] partial moments (no pool needed — only the tile split
    is sized here); everything else reuses the PR 10 spill-pool stripe
    replay and additionally requires the pool to hold the partials.
    None -> the caller falls back to the tiled-exact/413 path."""
    from opentsdb_tpu.ops import tiling
    tp = tiling.size_tiles(
        s, wp, state_mb * 2 ** 20, 9, g_pad,
        tsdb.config.get_int("tsd.query.spill.max_tiles"),
        chunks_per_tile=1)
    if tp is None:
        return None
    fold_ok = (aggregator in tiling.LANE_FOLDABLE
               and 5 * g_pad * wp * 8 <= state_mb * 2 ** 20)
    if not fold_ok:
        pool = getattr(tsdb, "spill_pool", None)
        if pool is None:
            return None
        entry_bytes = tp.tile_rows * tp.stripe_w \
            * tiling.SPILL_CELL_BYTES
        if tp.spill_bytes + entry_bytes \
                > pool.host_budget + pool.disk_budget:
            return None
    plan.striped = True
    plan.tile_plan = tp
    plan.decision["striped"] = True
    return plan


# effects: pure
def _fingerprint(fields: dict) -> str:
    """Stable hash over the discrete routing facts — canonical JSON,
    first 16 hex chars of sha256.  Deliberately excludes every raw
    millisecond so a calibration-constant edit alone cannot churn a
    fingerprint unless it actually flips a decision."""
    blob = json.dumps(fields, sort_keys=True, separators=(",", ":"))
    return "pf-" + hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


def _finish(pd: PlanDecision, ctx: RouteContext) -> PlanDecision:
    """Fingerprint assembly shared by the refused and served arms."""
    from opentsdb_tpu.ops import costmodel as cm
    fields = {
        "path": pd.path,
        "seg": ctx.seg_kind,
        "ds": ctx.ds_fn,
        "agg": ctx.aggregator,
        "rate": ctx.has_rate,
        "platform": pd.dec_platform,
        "s": ctx.s, "n": pd.n_pad, "w": ctx.wp,
        "g": pd.g_dec, "gPad": ctx.g_pad,
        "stream": pd.would_stream,
        "mesh": pd.use_mesh,
        "hostSmall": pd.host_small,
        "deviceCache": bool(pd.cached),
        "calibration": cm.calibration_source(pd.dec_platform),
    }
    if pd.decisions is not None:
        fields["modes"] = {axis: d["mode"]
                           for axis, d in pd.decisions.items()}
    if pd.lane_plan is not None:
        fields["lane"] = {"lane": pd.lane_plan.lane,
                          "k": pd.lane_plan.k,
                          "striped": bool(pd.lane_plan.striped)}
    if pd.path == "agg_rewrite" and pd.agg_note is not None:
        fields["aggCache"] = {
            "reason": pd.agg_note.get("reason"),
            "cached": pd.agg_note.get("cachedWindows"),
            "computed": pd.agg_note.get("computedWindows")}
    if pd.tiled_plan is not None:
        fields["tiled"] = {"tiles": pd.tiled_plan.n_tiles,
                           "rows": pd.tiled_plan.tile_rows,
                           "stripes": pd.tiled_plan.n_stripes,
                           "stripeW": pd.tiled_plan.stripe_w}
    if pd.refusal is not None:
        fields["refused"] = {"kind": pd.refusal.kind,
                             "limitMb": pd.refusal.state_mb}
    pd.fp_fields = fields
    pd.fingerprint = _fingerprint(fields)
    return pd


def plan_decision(tsdb, ctx: RouteContext, consults) -> PlanDecision:
    """THE routing verdict for one grouped segment.

    ``consults`` provides the four stateful consult hooks —
    ``rollup_plan()``, ``tiled_plan(acc_cell)``, ``agg_plan(platform)``,
    ``device_batch(build, ts_base)`` — plus the accounting callbacks
    (``note_lane_served``/``note_lane_fallback``/``tiled_refusal``).
    The executor's arms do real work; explain's arms are read-only.
    Eligibility gates, consult ordering, budget guards, and the path
    derivation all live HERE, once.
    """
    from opentsdb_tpu.obs import jaxprof
    from opentsdb_tpu.ops.downsample import pad_pow2

    would_stream = (ctx.stream_ok
                    and ctx.total_points > ctx.point_threshold)
    grid_gbd = grid_budget_for(ctx.state_mb, ctx.s, ctx.wp,
                               ctx.seg_kind, ctx.n_chips)
    gbd = (streaming_budget_for(ctx.state_mb, ctx.s, ctx.wp, ctx.ds_fn,
                                ctx.sketchable, ctx.n_chips)
           if would_stream else grid_gbd)

    # Rollup-lane consult (storage/rollup.py): THE shared fast-path
    # hook — one eligibility gate, one verdict, consumed by both the
    # over-budget (tiled) decision and the resident cache chain.
    lane_plan = None
    lane_note = None
    lanes = getattr(tsdb, "rollup_lanes", None)
    if (lanes is not None and ctx.seg_kind == "raw"
            and ctx.store_is_raw and not ctx.use_mesh
            and ctx.s > 0 and ctx.windows_fixed):
        lane_plan, lane_note = consults.rollup_plan()
        if lane_plan is not None:
            # residency: the assembled [S, Wp] grid against the SAME
            # shared device-state allowance every other path honors
            lane_gbd = grid_budget("grid", ctx.state_mb,
                                   ctx.s * ctx.wp * 24, ctx.s, ctx.wp)
            if lane_gbd.over:
                lane_plan = size_lane_stripes(
                    tsdb, lane_plan, ctx.s, ctx.wp, ctx.g_pad,
                    ctx.state_mb, ctx.aggregator)
                if lane_plan is None:
                    lane_note = dict(lane_note, decision="fallback",
                                     reason="striping_unavailable")
                    consults.note_lane_fallback()
            if lane_plan is not None:
                consults.note_lane_served(lane_plan)

    # Over-budget plan: a tiled execution, or the structured 413.
    tiled_plan = None
    if gbd.over and lane_plan is None:
        if not ctx.stream_ok:
            consults.tiled_refusal("not_streamable")
        else:
            tiled_plan = consults.tiled_plan(
                acc_cell_bytes(ctx.ds_fn, ctx.sketchable))
        if tiled_plan is None:
            pd = PlanDecision(
                path="refused", would_stream=would_stream,
                use_mesh=ctx.use_mesh, host_small=False,
                lane_small=False, gbd=gbd, grid_gbd=grid_gbd,
                lane_note=lane_note, refusal=gbd,
                n_pad=pad_pow2(max(ctx.n_max, 1)),
                g_dec=pad_pow2(max(ctx.groups, 1)),
                dec_platform=ctx.platform)
            return _finish(pd, ctx)

    lane_small = (tiled_plan is None and lane_plan is None
                  and not ctx.use_mesh and not would_stream
                  and 0 < ctx.total_points <= ctx.host_lane_max
                  and ctx.cpu_lane_ok)

    # Partial-aggregate rewrite (storage/agg_cache.py), tried BEFORE
    # the device series cache: a warm rewrite skips the column gather
    # too.  ONE host-lane decision for this dispatch: the agg cache
    # keys blocks on the execution platform and the dispatch chain
    # picks its lane from the same value.
    agg_plan = None
    agg_note = None
    if (tiled_plan is None and lane_plan is None
            and getattr(tsdb, "agg_cache", None) is not None
            and not would_stream and not ctx.use_mesh
            and ctx.seg_kind == "raw" and ctx.store_is_raw
            and ctx.windows_fixed):
        agg_platform = "cpu" if lane_small else ctx.platform
        agg_plan, agg_note = consults.agg_plan(agg_platform)

    n_pad = pad_pow2(max(ctx.n_max, 1))
    g_dec = pad_pow2(max(ctx.groups, 1))

    # Fused multi-query dispatch (query/batcher.py), decided BEFORE
    # the device-cache consult: a dispatch-bound plan (predicted
    # compute within batch_factor x the fitted stacked-dispatch floor)
    # routes through the batcher, which coalesces concurrent
    # compatible plans into one stacked [Q, S, W] launch — the
    # per-dispatch floor, not FLOPs, is what caps dashboard-fleet QPS,
    # so amortizing ONE launch across Q members beats Q per-member
    # device-cache gathers.  Compute-bound plans price as dispatch-now
    # and keep the resident/device-cache chain below.  Deterministic
    # in (shape, cost table, factor): explain reaches the same verdict.
    batched = False
    batch_decisions = None
    price_platform = None
    if (tiled_plan is None and lane_plan is None and agg_plan is None
            and ctx.batch_ok and not would_stream and not ctx.use_mesh
            and ctx.seg_kind == "raw" and ctx.has_store
            and ctx.ds_fn is not None):
        from opentsdb_tpu.ops import costmodel as cm
        price_platform = "cpu" if lane_small else ctx.platform
        # ONE decision recomputation: these per-axis reports price the
        # coalesce line here and become pd.decisions below when the
        # batched arm wins (the batched path's dec_platform equals
        # price_platform by construction: cached stays None)
        batch_decisions = jaxprof.segment_decisions(
            price_platform, ctx.s, n_pad, ctx.wp, g_dec, ctx.ds_fn,
            aggregator=ctx.aggregator)
        compute_s = sum(jaxprof.stage_breakdown(
            price_platform, ctx.s, n_pad, ctx.wp, g_dec, ctx.ds_fn,
            ctx.has_rate, decisions=batch_decisions).values())
        batched = cm.coalesce_worthwhile(
            compute_s, ctx.s, n_pad, ctx.wp, g_dec, price_platform,
            ctx.batch_factor)

    # Device-cache fast path (BlockCache analog): cold entries build
    # inline only when the alternative is a full host materialization
    # anyway; a warm hit that would divert a streaming query onto an
    # over-budget materialized grid DECLINES the diversion.  Batched
    # plans skip the consult entirely: the stacked launch needs host
    # arrays to stack, and one shared upload amortizes better than
    # per-member pinned-column gathers.
    cached = None
    if (tiled_plan is None and lane_plan is None and agg_plan is None
            and not batched
            and getattr(tsdb, "device_cache", None) is not None
            and ctx.has_store
            and ctx.seg_kind in ("raw", "rollup")):
        cached = consults.device_batch(build=not would_stream,
                                       ts_base=ctx.ts_base)
        if cached is not None and would_stream and grid_gbd.over:
            cached = None
    host_small = cached is None and lane_small

    if lane_plan is not None:
        path = "rollup_lane"
    elif tiled_plan is not None:
        path = "tiled"
    elif agg_plan is not None:
        path = "agg_rewrite"
    elif batched:
        path = "batched"
    elif cached is None and would_stream:
        path = "streamed"
    elif ctx.seg_kind == "rollup_avg":
        path = "rollup_avg"
    elif ctx.use_mesh:
        path = "mesh"
    elif host_small:
        path = "host_lane"
    else:
        path = "resident"

    dec_platform = "cpu" if host_small else ctx.platform
    decisions = None
    if path in MONOLITHIC_PATHS:
        if batch_decisions is not None \
                and dec_platform == price_platform:
            # the coalesce-pricing recomputation already produced this
            # platform's reports — reuse them on the batched arm AND
            # on the batch-declined fallthrough (dec_platform equals
            # price_platform whenever the device-cache consult missed)
            decisions = batch_decisions
        else:
            # per-axis kernel-mode decisions through the SAME
            # _effective_* choosers the kernels consult at trace time
            # (PR 6); computed only where the monolithic kernels
            # actually dispatch — lane/agg/tiled paths run their own
            # programs, and pricing 4 axes of candidates would tax the
            # warm fast paths the caches exist to shrink
            decisions = jaxprof.segment_decisions(
                dec_platform, ctx.s, n_pad, ctx.wp, g_dec, ctx.ds_fn,
                aggregator=ctx.aggregator)
    pd = PlanDecision(
        path=path, would_stream=would_stream, use_mesh=ctx.use_mesh,
        host_small=host_small, lane_small=lane_small, gbd=gbd,
        grid_gbd=grid_gbd, lane_plan=lane_plan, lane_note=lane_note,
        tiled_plan=tiled_plan, agg_plan=agg_plan, agg_note=agg_note,
        cached=cached, decisions=decisions, n_pad=n_pad, g_dec=g_dec,
        dec_platform=dec_platform)
    return _finish(pd, ctx)
